"""Native shim tests: frame parse → batch → classify → verdict return, the
steering-hash C++/Python agreement, and the 3-way goldengen parity
(C++ generator vs Python oracle vs TPU kernels)."""

import os
import random
import subprocess

import numpy as np
import pytest

SHIM_DIR = os.path.join(os.path.dirname(__file__), "..", "cilium_tpu", "shim")


@pytest.fixture(scope="module", autouse=True)
def built_shim():
    subprocess.run(["make", "-C", SHIM_DIR, "-s"], check=True)


from cilium_tpu.utils import constants as C  # noqa: E402
from cilium_tpu.utils.ip import parse_addr  # noqa: E402


class TestShimParse:
    def _shim(self):
        from cilium_tpu.shim.bindings import FlowShim
        s = FlowShim(batch_size=8, timeout_us=1000)
        s.register_endpoint("192.168.1.10", 1)
        return s

    def test_tcp_v4_frame(self):
        from cilium_tpu.shim.bindings import build_frame
        s = self._shim()
        assert s.feed_frame(build_frame("192.168.1.10", "10.1.2.3", 40000, 443))
        b = s.poll_batch(force=True)
        assert b is not None
        assert b["valid"][0] and b["direction"][0] == C.DIR_EGRESS
        assert b["sport"][0] == 40000 and b["dport"][0] == 443
        assert b["proto"][0] == C.PROTO_TCP
        assert b["tcp_flags"][0] == C.TCP_SYN
        # address words match the python layout
        a16, _ = parse_addr("10.1.2.3")
        assert (b["dst"][0] == np.frombuffer(a16, dtype=">u4")).all()
        s.close()

    def test_ingress_direction_and_unknown_ep(self):
        from cilium_tpu.shim.bindings import build_frame
        s = self._shim()
        s.feed_frame(build_frame("7.7.7.7", "192.168.1.10", 555, 80))
        s.feed_frame(build_frame("7.7.7.7", "8.8.8.8", 555, 80))  # unknown
        b = s.poll_batch(force=True)
        assert b["valid"][0] and b["direction"][0] == C.DIR_INGRESS
        assert not b["valid"][1]  # fail closed
        s.close()

    def test_v6_and_vlan_and_icmp(self):
        from cilium_tpu.shim.bindings import build_frame
        s = self._shim()
        s.register_endpoint("2001:db8::10", 2)
        assert s.feed_frame(build_frame("2001:db8::10", "2001:db8::1", 1, 443))
        assert s.feed_frame(build_frame("192.168.1.10", "10.0.0.1", 0, 8,
                                        proto=C.PROTO_ICMP))
        assert s.feed_frame(build_frame("192.168.1.10", "10.0.0.2", 1, 53,
                                        proto=C.PROTO_UDP, vlan=42))
        b = s.poll_batch(force=True)
        assert b["is_v6"][0] and b["ep_slot"] is not None
        assert b["dport"][1] == 8          # ICMP type in dport
        assert b["proto"][2] == C.PROTO_UDP
        s.close()

    def test_http_tokenizer(self):
        from cilium_tpu.shim.bindings import build_http_frame
        s = self._shim()
        s.feed_frame(build_http_frame("7.7.7.7", "192.168.1.10", 555, 80,
                                      "GET", "/api/users?id=7"))
        b = s.poll_batch(force=True)
        assert b["http_method"][0] == C.HTTP_METHOD_IDS["GET"]
        path = bytes(b["http_path"][0]).rstrip(b"\x00")
        assert path == b"/api/users?id=7"
        s.close()

    def test_garbage_frames_counted(self):
        s = self._shim()
        assert not s.feed_frame(b"\x00" * 10)
        assert not s.feed_frame(b"\xff" * 60)  # bad ethertype
        stats = s.stats()
        assert stats["parse_errors"] == 2 and stats["frames_parsed"] == 0
        s.close()

    def test_batching_threshold_and_timeout(self):
        from cilium_tpu.shim.bindings import build_frame, FlowShim
        s = FlowShim(batch_size=4, timeout_us=1000)
        s.register_endpoint("192.168.1.10", 1)
        for i in range(3):
            s.feed_frame(build_frame("192.168.1.10", "10.0.0.1", 1000 + i, 443),
                         now_us=100)
        assert s.poll_batch(now_us=500) is None        # not full, not timed out
        assert s.poll_batch(now_us=1200) is not None   # deadline hit
        for i in range(5):
            s.feed_frame(build_frame("192.168.1.10", "10.0.0.1", 2000 + i, 443),
                         now_us=2000)
        b = s.poll_batch(now_us=2001)                  # full batch immediately
        assert b is not None and int(b["valid"].sum()) == 4
        s.close()

    def test_steering_hash_matches_python(self):
        from cilium_tpu.shim.bindings import FlowShim, build_frame
        from cilium_tpu.parallel.mesh import flow_shard_of
        s = FlowShim(batch_size=16, timeout_us=0)
        s.register_endpoint("192.168.1.10", 1)
        rng = random.Random(5)
        for i in range(16):
            s.feed_frame(build_frame("192.168.1.10",
                                     f"10.{rng.randrange(256)}.{rng.randrange(256)}.{rng.randrange(1,255)}",
                                     rng.randrange(1024, 65535),
                                     rng.randrange(1, 65535)))
        b = s.poll_batch(force=True)
        want = flow_shard_of(b, 8)
        got = [s.flow_shard(i, 8) for i in range(16)]
        np.testing.assert_array_equal(np.asarray(got), want[:16])
        s.close()

    def test_steering_with_lb_matches_python(self):
        """Service traffic: the shim's steering applies the same DNAT the
        kernel does, so it matches flow_shard_of(..., lb=lb) — forward and
        reply of a service flow agree on the shard."""
        from cilium_tpu.shim.bindings import FlowShim, build_frame
        from cilium_tpu.parallel.mesh import flow_shard_of
        from cilium_tpu.compile.lb import LBConfig, build_lb
        from cilium_tpu.model.services import Backend, Frontend, Service
        lb = build_lb([Service(
            name="api", namespace="prod",
            frontends=(Frontend("10.96.0.10", 443, C.PROTO_TCP),),
            lb_backends=(Backend("10.50.0.1", 8443),
                         Backend("10.50.0.2", 8443)),
        )], LBConfig(maglev_m=31))
        s = FlowShim(batch_size=32, timeout_us=0)
        s.register_endpoint("192.168.1.10", 1)
        s.set_lb(lb)
        rng = random.Random(6)
        sports = [rng.randrange(1024, 65535) for _ in range(8)]
        for sp in sports:       # VIP traffic
            s.feed_frame(build_frame("192.168.1.10", "10.96.0.10", sp, 443))
        for sp in sports[:4]:   # non-service traffic
            s.feed_frame(build_frame("192.168.1.10", "10.77.0.1", sp, 443))
        b = s.poll_batch(force=True)
        n = 12
        want = flow_shard_of(b, 8, lb=lb)
        got = [s.flow_shard(i, 8) for i in range(n)]
        np.testing.assert_array_equal(np.asarray(got), want[:n])
        # the reply direction (backend → client) must land on the same shard
        from cilium_tpu.compile.lb import lb_translate_np
        new_dst, new_dport, _rn, _nb, _fe = lb_translate_np(lb, b)
        s2 = FlowShim(batch_size=32, timeout_us=0)
        s2.register_endpoint("192.168.1.10", 1)
        s2.set_lb(lb)
        from cilium_tpu.utils.ip import words_to_addr, addr_to_str
        for i in range(8):
            s2.feed_frame(build_frame(
                addr_to_str(words_to_addr(new_dst[i])), "192.168.1.10",
                int(new_dport[i]), int(b["sport"][i]),
                tcp_flags=C.TCP_SYN | C.TCP_ACK))
        b2 = s2.poll_batch(force=True)
        got2 = [s2.flow_shard(i, 8) for i in range(8)]
        np.testing.assert_array_equal(np.asarray(got2), np.asarray(got[:8]))
        s2.close()
        s.close()

    def test_afxdp_bind_succeeds_or_fails_gracefully(self):
        # In a privileged VM (this CI image) the socket+UMEM+bind sequence
        # succeeds on loopback; unprivileged containers get a clean -errno.
        # Either way it must not crash and must clean up on close.
        s = self._shim()
        rc = s.afxdp_bind("lo", 0)
        assert isinstance(rc, int) and (rc == 0 or rc < 0)
        s.close()


class TestShimToKernel:
    def test_frames_to_verdicts_end_to_end(self):
        """The full ingress path: craft frames → shim → engine → verdicts →
        shim_apply_verdicts."""
        from cilium_tpu.shim.bindings import FlowShim, build_frame
        from cilium_tpu.runtime import DaemonConfig, Engine
        eng = Engine(DaemonConfig(ct_capacity=4096, auto_regen=False))
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        eng.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toCIDR": ["10.0.0.0/8"],
                        "toPorts": [{"ports": [{"port": "443",
                                                "protocol": "TCP"}]}]}]}])
        shim = FlowShim(batch_size=8, timeout_us=0)
        shim.register_endpoint("192.168.1.10", 1)
        shim.feed_frame(build_frame("192.168.1.10", "10.1.2.3", 40000, 443))
        shim.feed_frame(build_frame("192.168.1.10", "10.1.2.3", 40001, 80))
        shim.feed_frame(build_frame("192.168.1.10", "9.9.9.9", 40002, 443))
        batch = shim.poll_batch(force=True)
        # map shim ep ids → snapshot slots
        slot_of = eng.active.snapshot.ep_slot_of
        for i in range(len(batch["_ep_raw"])):
            if batch["valid"][i]:
                batch["ep_slot"][i] = slot_of[int(batch["_ep_raw"][i])]
        clean = {k: v for k, v in batch.items() if not k.startswith("_")}
        out = eng.classify(clean, now=100)
        assert out["allow"].tolist()[:3] == [True, False, False]
        shim.apply_verdicts(out["allow"][: int(batch["valid"].sum())])
        st = shim.stats()
        assert st["verdict_passes"] == 1 and st["verdict_drops"] == 2
        shim.close()


class TestGoldengen:
    def test_three_way_parity(self, tmp_path):
        """C++ goldengen vs Python oracle vs device kernel on a random
        scenario."""
        import jax.numpy as jnp
        from cilium_tpu.compile.ct_layout import CTConfig, make_ct_arrays
        from cilium_tpu.compile.snapshot import build_snapshot
        from cilium_tpu.kernels.classify import classify_step
        from cilium_tpu.kernels.records import batch_from_records
        from cilium_tpu.shim.bindings import run_goldengen, write_scenario
        from tests.test_parity import build_world, random_packet
        from oracle import Oracle

        rng = random.Random(21)
        ctx, repo, eps = build_world()
        snap = build_snapshot(repo, ctx, eps, CTConfig(capacity=4096))
        web = snap.policies[0]       # ep 1 (slot 0)

        # flatten ep-1's MapState into goldengen entries + l7 sets
        l7_sets, l7_index = [], {}
        entries = []
        for d, dirpol in ((C.DIR_EGRESS, web.egress), (C.DIR_INGRESS, web.ingress)):
            for key, entry in dirpol.mapstate.items():
                l7 = 0
                if entry.l7_rules is not None:
                    fs = frozenset(entry.l7_rules)
                    if fs not in l7_index:
                        l7_sets.append(sorted(
                            (C.HTTP_METHOD_IDS.get(h.method, 255)
                             if h.method else 255, h.path.encode())
                            for h in fs))
                        l7_index[fs] = len(l7_sets)
                    l7 = l7_index[fs]
                entries.append((d, entry.deny, key.proto, key.identity,
                                key.port_lo, key.port_hi, l7))

        # packet stream restricted to ep 1
        packets, prior, now = [], [], 3000
        for i in range(120):
            p = random_packet(rng, prior)
            if p.ep_id != 1:
                continue
            packets.append((p, now))
            prior.append(p)
            now += 7

        scen = str(tmp_path / "scen.bin")
        outp = str(tmp_path / "out.bin")
        write_scenario(scen, ctx.ipcache.snapshot(),
                       (web.egress.enforced, web.ingress.enforced),
                       entries, l7_sets, packets)
        golden = run_goldengen(scen, outp)

        # Python oracle, sequential (same semantics goldengen implements)
        oracle = Oracle({1: web}, ctx.ipcache.snapshot())
        for i, (p, t) in enumerate(packets):
            v = oracle.classify(p, t)
            assert bool(golden.allow[i]) == v.allow, (i, p)
            assert int(golden.reason[i]) == int(v.drop_reason), (i, p)
            assert int(golden.status[i]) == int(v.ct_status), (i, p)
            assert int(golden.remote[i]) == v.remote_identity, (i, p)

        # device kernel, batch-of-1 (== sequential)
        tensors = {k: jnp.asarray(v) for k, v in snap.tensors().items()}
        ct = {k: jnp.asarray(v) for k, v in
              make_ct_arrays(CTConfig(capacity=4096)).items()}
        for i, (p, t) in enumerate(packets):
            b = {k: jnp.asarray(v) for k, v in
                 batch_from_records([p], snap.ep_slot_of).items()}
            out, ct, _ = classify_step(tensors, ct, b, jnp.uint32(t),
                                       world_index=snap.world_index)
            assert bool(np.asarray(out["allow"])[0]) == bool(golden.allow[i]), (i, p)
            assert int(np.asarray(out["reason"])[0]) == int(golden.reason[i]), (i, p)


class TestAfxdpRings:
    """The ring-draining packet path on memory-mocked AF_XDP rings: the same
    producer/consumer algebra shim_afxdp_bind maps from the kernel, backed by
    heap memory so the full frame lifecycle runs unprivileged:
    fill → (mock NIC) rx → shim_afxdp_poll parse/batch → verdicts →
    tx (forward) or fill (drop) → completion → fill."""

    def _ring_shim(self, n_frames=32, ring_size=32):
        from cilium_tpu.shim.bindings import FlowShim
        s = FlowShim(batch_size=16, timeout_us=1000)
        s.register_endpoint("192.168.1.10", 1)
        s.mock_rings_init(ring_size=ring_size, frame_size=2048,
                          n_frames=n_frames)
        return s

    def test_rx_drain_parse_and_batch(self):
        from cilium_tpu.shim.bindings import build_frame
        s = self._ring_shim()
        assert s.ring_fill_level() == 32
        for i in range(8):
            assert s.mock_rx_inject(build_frame(
                "192.168.1.10", f"10.0.0.{i}", 40000 + i, 443)) == 0
        assert s.ring_fill_level() == 24          # 8 frames now in rx
        drained = s.afxdp_poll(budget=256)
        assert drained == 8
        b = s.poll_batch(force=True)
        assert b is not None
        assert int(b["valid"].sum()) == 8
        assert (b["dport"][:8] == 443).all()
        s.close()

    def test_verdict_enforcement_tx_and_recycle(self):
        from cilium_tpu.shim.bindings import build_frame
        s = self._ring_shim()
        for i in range(6):
            s.mock_rx_inject(build_frame("192.168.1.10", f"10.0.0.{i}",
                                         41000 + i, 443))
        s.afxdp_poll()
        b = s.poll_batch(force=True)
        allow = np.zeros(16, dtype=bool)
        allow[:3] = True                          # pass 3, drop 3
        s.apply_verdicts(allow[: int(b["valid"].sum())])
        # dropped frames recycled straight to fill: 32 - 6 + 3 = 29
        assert s.ring_fill_level() == 29
        # passed frames sit in the tx ring; the mock NIC transmits them
        txed = s.mock_tx_drain()
        assert len(txed) == 3
        assert all(ln > 0 for _a, ln in txed)
        # completion → fill recycle happens on the next poll
        s.afxdp_poll()
        assert s.ring_fill_level() == 32          # no frame leaked
        st = s.stats()
        assert st["verdict_passes"] == 3 and st["verdict_drops"] == 3
        s.close()

    def test_parse_error_frames_recycle(self):
        s = self._ring_shim()
        assert s.mock_rx_inject(b"\x00" * 10) == 0   # runt frame
        assert s.afxdp_poll() == 1
        assert s.stats()["parse_errors"] == 1
        assert s.ring_fill_level() == 32             # recycled immediately
        s.close()

    def test_fill_exhaustion_backpressure(self):
        from cilium_tpu.shim.bindings import build_frame
        s = self._ring_shim(n_frames=4, ring_size=4)
        f = build_frame("192.168.1.10", "10.0.0.1", 40000, 443)
        for _ in range(4):
            assert s.mock_rx_inject(f) == 0
        import errno
        assert s.mock_rx_inject(f) == -errno.ENOSPC   # no free frames
        s.afxdp_poll()
        b = s.poll_batch(force=True)
        s.apply_verdicts(np.zeros(int(b["valid"].sum()), dtype=bool))
        assert s.ring_fill_level() == 4               # all recycled
        s.close()

    def test_ring_path_to_classifier_parity(self):
        """End-to-end: mocked NIC frames → ring drain → batch → jit classify
        → verdict bitmap → enforcement. The ring path must produce the same
        records (and therefore verdicts) as the direct feed_frame path."""
        import jax.numpy as jnp
        from cilium_tpu.compile.ct_layout import CTConfig, make_ct_arrays
        from cilium_tpu.compile.snapshot import build_snapshot
        from cilium_tpu.kernels.classify import make_classify_fn
        from cilium_tpu.model.endpoint import Endpoint
        from cilium_tpu.model.identity import IdentityAllocator
        from cilium_tpu.model.ipcache import IPCache
        from cilium_tpu.model.labels import Labels
        from cilium_tpu.model.rules import parse_rule
        from cilium_tpu.policy import PolicyContext, Repository
        from cilium_tpu.policy.selectorcache import SelectorCache
        from cilium_tpu.shim.bindings import build_frame

        alloc = IdentityAllocator()
        ctx = PolicyContext(allocator=alloc,
                            selector_cache=SelectorCache(alloc),
                            ipcache=IPCache())
        repo = Repository(ctx)
        lbls = Labels.parse(["k8s:app=web"])
        ident = alloc.allocate(lbls)
        ctx.ipcache.upsert("192.168.1.10/32", ident.id)
        ep = Endpoint(ep_id=1, labels=lbls, identity_id=ident.id)
        repo.add([parse_rule({
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toCIDR": ["10.0.0.0/8"],
                        "toPorts": [{"ports": [
                            {"port": "443", "protocol": "TCP"}]}]}]})])
        snap = build_snapshot(repo, ctx, [ep], CTConfig(capacity=1024))
        tensors = {k: jnp.asarray(v) for k, v in snap.tensors().items()}
        ct = {k: jnp.asarray(v) for k, v in
              make_ct_arrays(CTConfig(capacity=1024)).items()}
        fn = make_classify_fn(donate_ct=False)

        s = self._ring_shim()
        # 443 to 10/8 allowed; 80 dropped; dst outside 10/8 dropped
        frames = [build_frame("192.168.1.10", "10.1.1.1", 40000, 443),
                  build_frame("192.168.1.10", "10.1.1.1", 40001, 80),
                  build_frame("192.168.1.10", "11.1.1.1", 40002, 443)]
        for f in frames:
            assert s.mock_rx_inject(f) == 0
        s.afxdp_poll()
        b = s.poll_batch(force=True)
        # ep_id raw → slot mapping (bindings leave it to the caller)
        b["ep_slot"][:] = 0
        b["valid"] = b.pop("_ep_raw") != 0
        b.pop("_frame_idx")
        dev = {k: jnp.asarray(v) for k, v in b.items()}
        out, _ct2, _ctr = fn(tensors, ct, dev,
                             jnp.uint32(100), jnp.int32(snap.world_index))
        allow = np.asarray(out["allow"])[: 16]
        assert bool(allow[0]) and not bool(allow[1]) and not bool(allow[2])
        s.apply_verdicts(allow[:3])
        assert len(s.mock_tx_drain()) == 1            # only the allowed one
        s.afxdp_poll()
        assert s.ring_fill_level() == 32
        s.close()


class TestPcap:
    """pcap write/read/replay (BASELINE cfg1's ingest source)."""

    def test_roundtrip_and_replay(self, tmp_path):
        from cilium_tpu.shim.bindings import FlowShim, build_frame
        from cilium_tpu.shim.pcap import read_pcap, replay_pcap, write_pcap
        frames = [build_frame("192.168.1.10", f"10.0.0.{i}", 40000 + i, 443)
                  for i in range(10)]
        path = str(tmp_path / "t.pcap")
        assert write_pcap(path, frames) == 10
        back = list(read_pcap(path))
        assert back == frames
        s = FlowShim(batch_size=4, timeout_us=0)
        s.register_endpoint("192.168.1.10", 1)
        batches = replay_pcap(s, path, 4)
        assert sum(int((b["_ep_raw"] != 0).sum()) for b in batches) == 10
        assert all((b["dport"][b["_ep_raw"] != 0] == 443).all()
                   for b in batches)
        s.close()

    def test_synthesized_capture_parses_clean(self, tmp_path):
        from cilium_tpu.shim.bindings import FlowShim
        from cilium_tpu.shim.pcap import replay_pcap, synthesize_pcap
        path = str(tmp_path / "syn.pcap")
        n = synthesize_pcap(path, 512, seed=3)
        assert n == 512
        s = FlowShim(batch_size=128, timeout_us=0)
        s.register_endpoint("192.168.0.10", 1)
        batches = replay_pcap(s, path, 128)
        st = s.stats()
        assert st["parse_errors"] == 0
        assert st["frames_parsed"] == 512
        got = sum(int((b["_ep_raw"] != 0).sum()) for b in batches)
        assert got == 512
        b0 = batches[0]
        assert (b0["direction"][b0["_ep_raw"] != 0] == 0).all()  # egress
        assert (b0["proto"][b0["_ep_raw"] != 0] == 6).all()
        s.close()


class TestTsan:
    def test_ring_lifecycle_under_tsan(self, tmp_path):
        """SURVEY §5 race detection: the ring path runs clean under
        ThreadSanitizer (the shim's `go test -race` analog). TSan must be
        LD_PRELOADed (static TLS), so this drives a subprocess."""
        import glob
        tsan_rt = sorted(glob.glob("/lib/x86_64-linux-gnu/libtsan.so*")
                         + glob.glob("/usr/lib/x86_64-linux-gnu/libtsan.so*"))
        if not tsan_rt:
            pytest.skip("no libtsan runtime in this image")
        subprocess.run(["make", "-C", SHIM_DIR, "-s", "tsan"], check=True)
        code = (
            "from cilium_tpu.shim.bindings import FlowShim, build_frame\n"
            "import numpy as np\n"
            "s = FlowShim(batch_size=8, timeout_us=0)\n"
            "s.register_endpoint('192.168.1.10', 1)\n"
            "s.mock_rings_init(ring_size=16, frame_size=2048, n_frames=16)\n"
            "for i in range(6):\n"
            "    assert s.mock_rx_inject(build_frame(\n"
            "        '192.168.1.10', f'10.0.0.{i}', 40000+i, 443)) == 0\n"
            "assert s.afxdp_poll() == 6\n"
            "b = s.poll_batch(force=True)\n"
            "s.apply_verdicts(np.array([True]*3 + [False]*3))\n"
            "assert len(s.mock_tx_drain()) == 3\n"
            "s.afxdp_poll()\n"
            "assert s.ring_fill_level() == 16\n"
            "print('TSAN_OK')\n")
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["LD_PRELOAD"] = tsan_rt[-1]
        env["CILIUM_TPU_SHIM_LIB"] = os.path.join(
            SHIM_DIR, "libflowshim-tsan.so")
        env["TSAN_OPTIONS"] = "halt_on_error=1 exitcode=66"
        import sys as _sys
        proc = subprocess.run([_sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=120,
                              cwd="/root/repo", env=env)
        assert proc.returncode == 0, (proc.returncode, proc.stderr[-2000:])
        assert "TSAN_OK" in proc.stdout
        assert "WARNING: ThreadSanitizer" not in proc.stderr


# --------------------------------------------------------------------------- #
# Privileged real-XSK path (upstream PRIVILEGED_TESTS analog, VERDICT r05
# weak #5): everything above exercises the ring algebra on heap-backed mock
# rings; this tier binds a REAL AF_XDP socket on a veth pair and pushes
# frames through the kernel. Gated on CILIUM_TPU_PRIVILEGED_TESTS=1 (needs
# CAP_NET_ADMIN to create the veth and CAP_NET_RAW/bpf for the XSK) — a
# plain skip everywhere else.
# --------------------------------------------------------------------------- #
PRIVILEGED = os.environ.get("CILIUM_TPU_PRIVILEGED_TESTS") == "1"


@pytest.mark.skipif(
    not PRIVILEGED,
    reason="real-XSK veth test; set CILIUM_TPU_PRIVILEGED_TESTS=1 "
           "(requires CAP_NET_ADMIN/CAP_NET_RAW)")
class TestPrivilegedXSK:
    VETH_A, VETH_B = "ctpu-xsk0", "ctpu-xsk1"

    def _ip(self, *args):
        subprocess.run(["ip", *args], check=True, capture_output=True,
                       text=True)

    def test_veth_xsk_bind_fill_and_rx(self):
        """bind → fill-ring population → frames in at the veth peer →
        afxdp_poll drain. Where the kernel delivers to the XSK the parsed
        records must match the frames; where it cannot (no XDP redirect
        program support on the kernel), the bound-socket poll path must
        still run clean — that subset is asserted unconditionally."""
        import errno
        import socket as pysock
        import time as _time
        from cilium_tpu.shim.bindings import FlowShim, build_frame

        try:
            self._ip("link", "add", self.VETH_A, "type", "veth",
                     "peer", "name", self.VETH_B)
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            pytest.skip(f"cannot create veth pair: {e}")
        shim = tx = None
        try:
            self._ip("link", "set", self.VETH_A, "up")
            self._ip("link", "set", self.VETH_B, "up")
            shim = FlowShim(batch_size=32, timeout_us=0)
            shim.register_endpoint("192.168.7.10", 1)
            rc = shim.afxdp_bind(self.VETH_A, 0)
            if rc != 0:
                pytest.skip(f"afxdp_bind({self.VETH_A}) -> {rc}; kernel "
                            "lacks AF_XDP support in this environment")
            # the bind must have pre-populated the fill ring — the kernel
            # cannot deliver a frame without posted umem
            assert shim.ring_fill_level() > 0

            tx = pysock.socket(pysock.AF_PACKET, pysock.SOCK_RAW)
            tx.bind((self.VETH_B, 0))
            frame = build_frame("192.168.7.10", "10.1.2.3", 41000, 443)
            harvested = None
            deadline = _time.time() + 3.0
            while _time.time() < deadline and harvested is None:
                tx.send(frame)
                rc = shim.afxdp_poll(budget=64)
                # a clean drain or an empty ring — never a hard error on a
                # live socket
                assert rc >= 0 or rc in (-errno.EAGAIN, -errno.EWOULDBLOCK)
                b = shim.poll_batch(force=True)
                if b is not None and int(b["valid"].sum()):
                    harvested = b
                else:
                    _time.sleep(0.01)
            if harvested is None:
                # bind + fill + poll all ran against the real XSK; rx
                # delivery additionally needs an XDP redirect program,
                # which this kernel/driver combination did not provide
                pytest.skip("XSK bound and polled clean on "
                            f"{self.VETH_A}, but no rx delivery (no XDP "
                            "redirect program support)")
            i = int(np.nonzero(harvested["valid"])[0][0])
            assert harvested["sport"][i] == 41000
            assert harvested["dport"][i] == 443
            assert harvested["proto"][i] == C.PROTO_TCP
            assert harvested["direction"][i] == C.DIR_EGRESS
            shim.apply_verdicts(np.ones(int(harvested["valid"].sum()),
                                        dtype=bool))
        finally:
            if tx is not None:
                tx.close()
            if shim is not None:
                shim.close()
            subprocess.run(["ip", "link", "del", self.VETH_A],
                           capture_output=True)
