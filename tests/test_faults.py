"""Fault-injection harness + supervised degradation tests.

Covers the FaultInjector registry itself, then each injection point's
recovery contract: regen failure → DEGRADED + last-good serving,
controller/trigger backoff schedules, clustermesh peer flap + prefix
hand-off + clock skew, corrupt checkpoint → cold-start fallback, and the
hardened API socket. The end-to-end chaos scenario runs via the CLI (fast
subset on the fake datapath in tier-1; the full jit run is `slow` and is
what `make chaos` executes).
"""

import json
import os
import socket
import stat

import pytest

from cilium_tpu.cli.main import main as cli_main
from cilium_tpu.kernels.records import batch_from_records
from cilium_tpu.runtime import checkpoint as ckpt
from cilium_tpu.runtime import faults as faults_mod
from cilium_tpu.runtime.api import APIServer, UnixAPIClient
from cilium_tpu.runtime.clustermesh import ClusterMesh
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.controller import Controller, Trigger, backoff_delay
from cilium_tpu.runtime.datapath import FakeDatapath
from cilium_tpu.runtime.engine import Engine
from cilium_tpu.runtime.faults import (FAULTS, FaultInjected, FaultInjector,
                                       FaultSpec)
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr
from oracle import PacketRecord

POLICY = [{
    "endpointSelector": {"matchLabels": {"app": "web"}},
    "egress": [{"toCIDR": ["10.0.0.0/8"],
                "toPorts": [{"ports": [{"port": "443",
                                        "protocol": "TCP"}]}]}],
}]


@pytest.fixture(autouse=True)
def _clean_faults():
    """The FAULTS singleton is process-wide state: reset around every test
    so an armed point never leaks into an unrelated test."""
    FAULTS.reset()
    yield
    FAULTS.reset()


def small_engine(**kw):
    kw.setdefault("ct_capacity", 4096)
    kw.setdefault("auto_regen", False)
    cfg = DaemonConfig(**kw)
    return Engine(cfg, datapath=FakeDatapath(cfg))


def pkt(src, dst, sp, dp, ep_id=1, direction=C.DIR_EGRESS):
    s16, sv6 = parse_addr(src)
    d16, dv6 = parse_addr(dst)
    return PacketRecord(s16, d16, sp, dp, C.PROTO_TCP, C.TCP_SYN,
                        sv6 or dv6, ep_id, direction)


def web_engine():
    eng = small_engine()
    eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
    eng.apply_policy(POLICY)
    return eng


def classify_allows(eng, slot_of, now=100):
    out = eng.classify(batch_from_records(
        [pkt("192.168.1.10", "10.1.2.3", 40000, 443),    # allowed
         pkt("192.168.1.10", "10.1.2.3", 40001, 80),     # denied port
         pkt("192.168.1.10", "8.8.8.8", 40002, 443)],    # denied CIDR
        slot_of), now=now)
    return [bool(a) for a in out["allow"]]


# --------------------------------------------------------------------------- #
class TestFaultInjector:
    def test_fail_n_times_then_passes(self):
        inj = FaultInjector(env={})
        inj.arm("regen.compile", mode="fail", times=2)
        for _ in range(2):
            with pytest.raises(FaultInjected):
                inj.fire("regen.compile")
        inj.fire("regen.compile")                  # spec exhausted
        st = inj.stats()["regen.compile"]
        assert st["fired"] == 3 and st["trips"] == 2 and st["armed"]

    def test_fail_forever_and_disarm(self):
        inj = FaultInjector(env={})
        inj.arm("checkpoint.write", mode="fail")   # times=None → every fire
        for _ in range(5):
            with pytest.raises(FaultInjected):
                inj.fire("checkpoint.write")
        inj.disarm("checkpoint.write")
        inj.fire("checkpoint.write")

    def test_prob_is_seed_deterministic(self):
        def pattern(seed):
            inj = FaultInjector(env={})
            inj.arm("api.handler", mode="prob", prob=0.5, seed=seed)
            out = []
            for _ in range(64):
                try:
                    inj.fire("api.handler")
                    out.append(0)
                except FaultInjected:
                    out.append(1)
            return out

        assert pattern(7) == pattern(7)            # no wall-clock anywhere
        assert pattern(7) != pattern(8)
        assert 0 < sum(pattern(7)) < 64            # actually probabilistic

    def test_delay_mode_sleeps_instead_of_raising(self, monkeypatch):
        slept = []
        monkeypatch.setattr(faults_mod.time, "sleep",
                            lambda s: slept.append(s))
        inj = FaultInjector(env={})
        inj.arm("shim.rx_ring", mode="delay", delay_s=0.25)
        inj.fire("shim.rx_ring")
        assert slept == [0.25]

    def test_unknown_point_and_bad_spec_rejected(self):
        inj = FaultInjector(env={})
        with pytest.raises(ValueError, match="unknown injection point"):
            inj.arm("no.such.point")
        with pytest.raises(ValueError):
            FaultSpec(mode="explode")
        with pytest.raises(ValueError):
            FaultSpec(mode="prob", prob=1.5)
        with pytest.raises(ValueError, match="bad fault entry"):
            inj.load_spec("regen.compile")         # no '='

    def test_env_var_grammar(self):
        inj = FaultInjector(env={
            faults_mod.ENV_VAR: "regen.compile=fail:10;"
                                "clustermesh.peer_read=prob:0.5:seed=7,"
                                "shim.rx_ring=delay:0.01"})
        armed = inj.armed()
        assert armed["regen.compile"].mode == "fail"
        assert armed["regen.compile"].times == 10
        assert armed["clustermesh.peer_read"].prob == 0.5
        assert armed["clustermesh.peer_read"].seed == 7
        assert armed["shim.rx_ring"].delay_s == 0.01

    def test_bad_multi_entry_spec_arms_nothing(self):
        """All-or-nothing arming: a 400 on entry N must not leave entries
        1..N-1 live on a production agent."""
        inj = FaultInjector(env={})
        with pytest.raises(ValueError, match="unknown injection point"):
            inj.load_spec("regen.compile=fail;no.such.point=fail")
        assert inj.armed() == {}
        with pytest.raises(ValueError, match="bad fault entry"):
            inj.load_spec("regen.compile=fail:2:bogus=1")
        assert inj.armed() == {}

    def test_inject_context_manager_restores_previous(self):
        inj = FaultInjector(env={})
        inj.arm("regen.compile", mode="fail", times=99)
        with inj.inject("regen.compile", mode="delay", delay_s=0.0):
            assert inj.armed()["regen.compile"].mode == "delay"
        assert inj.armed()["regen.compile"].mode == "fail"
        with inj.inject("api.handler", mode="fail"):
            assert "api.handler" in inj.armed()
        assert "api.handler" not in inj.armed()    # was not armed before

    def test_register_point(self):
        faults_mod.register_point("test.extra", "self-registered point")
        try:
            inj = FaultInjector(env={})
            inj.arm("test.extra", mode="fail", times=1)
            with pytest.raises(FaultInjected):
                inj.fire("test.extra")
        finally:
            faults_mod.POINTS.pop("test.extra", None)


# --------------------------------------------------------------------------- #
class TestEngineDegradation:
    def test_regen_storm_serves_last_good(self):
        """The acceptance scenario: 10 consecutive compile failures, zero
        classify errors, DEGRADED with the failure count, then recovery."""
        eng = web_engine()
        slot_of = eng.active.snapshot.ep_slot_of
        baseline = classify_allows(eng, slot_of)
        assert baseline == [True, False, False]
        assert eng.health()["state"] == C.HEALTH_OK

        FAULTS.arm("regen.compile", mode="fail", times=10)
        for i in range(10):
            eng._mark_dirty()                      # classify retries compile
            assert classify_allows(eng, slot_of, now=200 + i) == baseline
        h = eng.health()
        assert h["state"] == C.HEALTH_DEGRADED
        assert h["consecutive_regen_failures"] == 10
        assert "FaultInjected" in h["last_regen_error"]
        assert eng.metrics.counters["regen_failures_total"] == 10
        assert eng.metrics.gauges["engine_degraded"] == 1

        # 11th attempt: the fail:10 spec is exhausted → recovery
        compiled = eng.regenerate(force=True)
        assert compiled is not None
        h = eng.health()
        assert h["state"] == C.HEALTH_OK
        assert h["consecutive_regen_failures"] == 0
        assert eng.metrics.gauges["engine_degraded"] == 0
        assert classify_allows(eng, slot_of, now=300) == baseline

    def test_stale_when_policy_committed_but_uncompilable(self):
        eng = web_engine()
        _ = eng.active
        FAULTS.arm("regen.compile", mode="fail")
        # committed policy change bumps repo.revision past the active
        # snapshot: verdicts are correct for an OLDER policy world → STALE
        eng.apply_policy([{
            "endpointSelector": {"matchLabels": {"app": "web"}},
            "egress": [{"toCIDR": ["172.16.0.0/12"]}]}])
        eng.regenerate()
        h = eng.health()
        assert h["state"] == C.HEALTH_STALE
        assert h["repo_revision"] > h["active_revision"]
        FAULTS.disarm()
        eng.regenerate(force=True)
        assert eng.health()["state"] == C.HEALTH_OK

    def test_cold_start_failure_still_raises(self):
        """With no last-good snapshot there is nothing to serve: the very
        first regeneration failing must surface, not degrade silently."""
        eng = small_engine()
        eng.add_endpoint(["k8s:app=web"], ips=("192.168.1.10",), ep_id=1)
        FAULTS.arm("regen.compile", mode="fail", times=1)
        with pytest.raises(FaultInjected):
            eng.regenerate(force=True)
        eng.regenerate(force=True)                 # retry succeeds
        assert eng.health()["state"] == C.HEALTH_OK

    def test_health_probe_carries_engine_state(self):
        eng = web_engine()
        _ = eng.active
        report = eng.health_probe(now=100)
        assert report["engine"]["state"] == C.HEALTH_OK
        FAULTS.arm("regen.compile", mode="fail")
        eng._mark_dirty()
        report = eng.health_probe(now=101)
        assert report["engine"]["state"] == C.HEALTH_DEGRADED
        assert report[1]["reachable"] in (True, False)   # probe still ran


# --------------------------------------------------------------------------- #
class TestBackoff:
    def test_backoff_delay_schedule(self):
        assert backoff_delay(0, 1.0, 60.0) == 0.0
        assert backoff_delay(1, 1.0, 60.0, rng=None) == 1.0
        assert backoff_delay(4, 1.0, 60.0, rng=None) == 8.0
        assert backoff_delay(50, 1.0, 60.0, rng=None) == 60.0   # capped

    def test_backoff_jitter_bounded_and_deterministic(self):
        import random
        d1 = [backoff_delay(n, 1.0, 60.0, random.Random(3))
              for n in range(1, 8)]
        d2 = [backoff_delay(n, 1.0, 60.0, random.Random(3))
              for n in range(1, 8)]
        assert d1 == d2                            # seeded → replayable
        for n, d in enumerate(d1, start=1):
            base = min(60.0, 2.0 ** (n - 1))
            assert base <= d <= base * 1.1 + 1e-9
        # the cap is a hard ceiling — jitter never pushes past it
        assert backoff_delay(50, 1.0, 60.0, random.Random(1)) == 60.0

    def test_controller_backoff_counts_and_recovery(self):
        boom = [True]

        def flaky():
            if boom[0]:
                raise RuntimeError("store down")

        c = Controller("test-ctrl", flaky, interval=5.0,
                       backoff_base=0.5, backoff_max=8.0)
        for n in range(1, 6):
            c.run_once()
            assert c.status.consecutive_failures == n
            base = min(8.0, 0.5 * (2 ** (n - 1)))
            assert base <= c.status.last_backoff_s <= base * 1.1 + 1e-9
        assert c.status.failure_count == 5
        assert "store down" in c.status.last_error
        boom[0] = False
        c.run_once()
        assert c.status.consecutive_failures == 0
        assert c.status.last_backoff_s == 5.0      # back to the interval
        assert c.status.success_count == 1

    def test_controller_schedule_is_replayable(self):
        def always_fails():
            raise RuntimeError("x")

        def schedule(name):
            c = Controller(name, always_fails, interval=1.0)
            out = []
            for _ in range(6):
                c.run_once()
                out.append(c.status.last_backoff_s)
            return out

        assert schedule("ctrl-a") == schedule("ctrl-a")   # seeded from name
        assert schedule("ctrl-a") != schedule("ctrl-b")   # de-synchronized

    def test_next_delay_peek_is_side_effect_free(self):
        def always_fails():
            raise RuntimeError("x")

        observed = Controller("peek", always_fails, interval=1.0)
        replay = Controller("peek", always_fails, interval=1.0)
        a_delays, b_delays = [], []
        for _ in range(5):
            observed.run_once()
            a_delays.append(observed.status.last_backoff_s)
            assert observed.next_delay() == observed.next_delay()  # stable
            replay.run_once()
            b_delays.append(replay.status.last_backoff_s)
        # peeking at one controller's schedule must not shift it off the
        # identical-seed replay that never peeked
        assert a_delays == b_delays

    def test_trigger_sync_failure_counting(self):
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("regen exploded")

        t = Trigger(fn, sync=True)
        for _ in range(2):
            with pytest.raises(RuntimeError):
                t()
        assert t.consecutive_failures == 2
        assert "regen exploded" in t.last_error
        t()
        assert t.consecutive_failures == 0 and t.last_error == ""


# --------------------------------------------------------------------------- #
class TestCheckpointRobustness:
    def test_corrupt_state_falls_back_to_cold_start(self, tmp_path):
        eng = web_engine()
        _ = eng.active
        ckpt.save(eng, str(tmp_path))
        with open(tmp_path / "state.json", "r+") as f:
            f.write("{torn")
        fresh = small_engine()
        assert ckpt.restore(fresh, str(tmp_path)) is False
        assert not fresh.endpoints and len(fresh.repo) == 0   # untouched
        with pytest.raises(ckpt.CheckpointCorrupt):
            ckpt.restore(small_engine(), str(tmp_path), strict=True)

    def test_checksum_catches_field_tampering(self, tmp_path):
        eng = web_engine()
        _ = eng.active
        ckpt.save(eng, str(tmp_path))
        state = json.loads((tmp_path / "state.json").read_text())
        state["revision"] = state["revision"] + 7  # valid JSON, wrong body
        (tmp_path / "state.json").write_text(json.dumps(state))
        assert ckpt.restore(small_engine(), str(tmp_path)) is False

    def test_pre_checksum_checkpoints_still_restore(self, tmp_path):
        eng = web_engine()
        _ = eng.active
        ckpt.save(eng, str(tmp_path))
        state = json.loads((tmp_path / "state.json").read_text())
        del state["checksum"]                      # an older writer's file
        (tmp_path / "state.json").write_text(json.dumps(state))
        fresh = small_engine()
        assert ckpt.restore(fresh, str(tmp_path)) is True
        assert 1 in fresh.endpoints

    def test_corrupt_ct_drops_flows_keeps_control_plane(self, tmp_path):
        eng = web_engine()
        slot_of = eng.active.snapshot.ep_slot_of
        assert classify_allows(eng, slot_of) == [True, False, False]
        assert eng.ct_stats(now=100)["live"] == 1
        ckpt.save(eng, str(tmp_path))
        (tmp_path / "ct.npz").write_bytes(b"not a zipfile at all")
        fresh = small_engine()
        assert ckpt.restore(fresh, str(tmp_path)) is True
        assert 1 in fresh.endpoints and len(fresh.repo) == 1
        assert fresh.ct_stats(now=100)["live"] == 0    # CT was dropped
        # and the restored engine still classifies correctly
        assert classify_allows(
            fresh, fresh.active.snapshot.ep_slot_of, now=200
        ) == [True, False, False]

    def test_injected_write_fault_leaves_no_partial_state(self, tmp_path):
        eng = web_engine()
        _ = eng.active
        ckpt.save(eng, str(tmp_path))
        good = (tmp_path / "state.json").read_bytes()
        eng.classify(batch_from_records(
            [pkt("192.168.1.10", "10.1.2.3", 41000, 443)],
            eng.active.snapshot.ep_slot_of), now=150)
        FAULTS.arm("checkpoint.write", mode="fail", times=1)
        with pytest.raises(FaultInjected):
            ckpt.save(eng, str(tmp_path))
        # the old checkpoint is intact, byte for byte, and restorable
        assert (tmp_path / "state.json").read_bytes() == good
        assert not [n for n in os.listdir(tmp_path)
                    if n.startswith((".state-", ".ct-"))]   # no tmp litter
        assert ckpt.restore(small_engine(), str(tmp_path)) is True


# --------------------------------------------------------------------------- #
class TestClusterMeshRecovery:
    @staticmethod
    def write_peer(store, node, gen, entries, published_at=None):
        import time as _time
        doc = {"format_version": 1, "node": node, "generation": gen,
               "published_at": (_time.time() if published_at is None
                                else published_at),
               "entries": entries}
        tmp = os.path.join(store, f".{node}-tmp")
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, os.path.join(store, f"{node}.json"))

    def test_peer_flap_holds_state_and_converges(self, tmp_path):
        eng = web_engine()
        mesh = ClusterMesh(eng, str(tmp_path), "local", stale_after_s=300.0)
        self.write_peer(str(tmp_path), "peer1", 1,
                        {"10.99.0.5/32": {"labels": ["k8s:app=db"]}})
        mesh.sync()
        ident0 = eng.ctx.ipcache.get("10.99.0.5/32")
        assert ident0 is not None
        FAULTS.arm("clustermesh.peer_read", mode="prob", prob=0.7, seed=7)
        for gen in range(2, 14):
            self.write_peer(str(tmp_path), "peer1", gen,
                            {"10.99.0.5/32": {"labels": ["k8s:app=db"]}})
            mesh.sync()
            # a flapping read NEVER withdraws within the lease window
            assert eng.ctx.ipcache.get("10.99.0.5/32") == ident0
        FAULTS.disarm()
        mesh.sync()
        assert eng.ctx.ipcache.get("10.99.0.5/32") == ident0

    def test_prefix_handoff_survives_withdrawal_pass(self, tmp_path):
        """Regression (ADVICE round-5): a prefix claimed by two peers (pod
        move overlap) must survive the departing peer's withdrawal — the
        old code's `if prefix in held: continue` left a permanent ipcache
        hole after the hand-off."""
        eng = web_engine()
        mesh = ClusterMesh(eng, str(tmp_path), "local", stale_after_s=300.0)
        entries = {"10.99.0.7/32": {"labels": ["k8s:app=cache"]}}
        self.write_peer(str(tmp_path), "peer-a", 1, entries)
        self.write_peer(str(tmp_path), "peer-b", 1, entries)
        mesh.sync()
        ident = eng.ctx.ipcache.get("10.99.0.7/32")
        assert ident is not None
        # peer-a departs cleanly; its withdrawal pass deletes the ipcache
        # entry out from under peer-b's still-live claim
        os.unlink(tmp_path / "peer-a.json")
        mesh.sync()
        assert eng.ctx.ipcache.get("10.99.0.7/32") == ident
        # and the identity still resolves through a real LPM lookup
        assert eng.ctx.ipcache.lookup("10.99.0.7") == ident

    def test_clock_skew_does_not_withdraw_live_peer(self, tmp_path,
                                                    monkeypatch):
        """Regression (ADVICE round-5): staleness is judged from OUR lease
        clock (advanced on generation change), never from the peer-written
        published_at — a peer whose clock is behind must not be withdrawn
        while it is making progress."""
        import cilium_tpu.runtime.clustermesh as cm
        eng = web_engine()
        mesh = ClusterMesh(eng, str(tmp_path), "local", stale_after_s=60.0)
        clock = [1_000_000.0]
        monkeypatch.setattr(cm.time, "time", lambda: clock[0])
        entries = {"10.99.0.9/32": {"labels": ["k8s:app=mq"]}}
        # the peer's clock is 10 000 s behind ours — published_at looks
        # ancient on every single heartbeat
        for gen in range(1, 6):
            self.write_peer(str(tmp_path), "peer1", gen, entries,
                            published_at=clock[0] - 10_000.0)
            mesh.sync()
            assert eng.ctx.ipcache.get("10.99.0.9/32") is not None
            clock[0] += 30.0                       # under the 60 s lease
        # now the peer truly dies: generation stops advancing → the local
        # lease ages out and the state is withdrawn
        clock[0] += 120.0
        mesh.sync()
        assert eng.ctx.ipcache.get("10.99.0.9/32") is None

    def test_unreadable_file_holds_until_lease_expiry(self, tmp_path,
                                                      monkeypatch):
        import cilium_tpu.runtime.clustermesh as cm
        eng = web_engine()
        mesh = ClusterMesh(eng, str(tmp_path), "local", stale_after_s=60.0)
        clock = [1_000_000.0]
        monkeypatch.setattr(cm.time, "time", lambda: clock[0])
        self.write_peer(str(tmp_path), "peer1", 1,
                        {"10.99.0.11/32": {"labels": ["k8s:app=db"]}})
        mesh.sync()
        assert eng.ctx.ipcache.get("10.99.0.11/32") is not None
        FAULTS.arm("clustermesh.peer_read", mode="fail")   # every read fails
        clock[0] += 30.0
        mesh.sync()                                # inside lease: held
        assert eng.ctx.ipcache.get("10.99.0.11/32") is not None
        clock[0] += 60.0
        mesh.sync()                                # lease expired: withdrawn
        assert eng.ctx.ipcache.get("10.99.0.11/32") is None


# --------------------------------------------------------------------------- #
class TestAPIHardening:
    def make_server(self, tmp_path, name="api.sock"):
        sock = str(tmp_path / name)
        eng = web_engine()
        _ = eng.active
        eng.config = DaemonConfig(ct_capacity=4096, auto_regen=False,
                                  api_socket=sock)
        srv = APIServer(eng, sock)
        srv.start()
        return eng, srv, sock

    def test_socket_permissions(self, tmp_path):
        _eng, srv, sock = self.make_server(tmp_path / "sub")
        try:
            mode = stat.S_IMODE(os.stat(sock).st_mode)
            assert mode == 0o600                   # owner-only
            dmode = stat.S_IMODE(os.stat(tmp_path / "sub").st_mode)
            assert dmode & 0o027 == 0              # no group-w, no other
        finally:
            srv.stop()

    def test_live_socket_is_not_stolen(self, tmp_path):
        _eng, srv, sock = self.make_server(tmp_path)
        try:
            eng2 = web_engine()
            _ = eng2.active
            srv2 = APIServer(eng2, sock)
            with pytest.raises(RuntimeError, match="refusing to steal"):
                srv2.start()
            # the original server is untouched
            code, doc = UnixAPIClient(sock).get("/v1/healthz")
            assert code == 200 and doc["state"] == C.HEALTH_OK
        finally:
            srv.stop()

    def test_stale_socket_is_reclaimed(self, tmp_path):
        sock = str(tmp_path / "stale.sock")
        dead = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        dead.bind(sock)                            # bound but never listening
        dead.close()                               # → connect() refused
        assert os.path.exists(sock)
        _eng, srv, _ = self.make_server(tmp_path, "stale.sock")
        try:
            code, _doc = UnixAPIClient(sock).get("/v1/healthz")
            assert code == 200
        finally:
            srv.stop()

    def test_faults_routes_and_handler_fault(self, tmp_path):
        _eng, srv, sock = self.make_server(tmp_path)
        client = UnixAPIClient(sock)
        try:
            code, stats = client.get("/v1/faults")
            assert code == 200 and "regen.compile" in stats
            code, doc = client.post("/v1/faults",
                                    {"spec": "api.handler=fail"})
            assert code == 200 and doc["armed"] == 1
            code, doc = client.get("/v1/status")   # normal route: 500s now
            assert code == 500 and "FaultInjected" in doc["error"]
            # the faults route itself stays exempt so the chaos driver can
            # observe and disarm mid-storm
            code, stats = client.get("/v1/faults")
            assert code == 200 and stats["api.handler"]["armed"]
            code, _doc = client.post("/v1/faults", {"disarm": "*"})
            assert code == 200
            code, _doc = client.get("/v1/status")
            assert code == 200
            code, doc = client.post(
                "/v1/faults", {"spec": "shim.rx_ring=fail;nope=fail"})
            assert code == 400
            code, stats = client.get("/v1/faults")   # nothing half-armed
            assert code == 200 and not stats["shim.rx_ring"]["armed"]
        finally:
            srv.stop()

    def test_healthz_reports_degradation_live(self, tmp_path):
        eng, srv, sock = self.make_server(tmp_path)
        client = UnixAPIClient(sock)
        try:
            code, _doc = client.post(
                "/v1/faults", {"spec": "regen.compile=fail:3"})
            assert code == 200
            for _ in range(3):
                code, _doc = client.post("/v1/regenerate")
                assert code == 200                 # served from last-good
            code, h = client.get("/v1/healthz")
            assert code == 200
            assert h["status"] == "degraded"
            assert h["state"] == C.HEALTH_DEGRADED
            assert h["consecutive_regen_failures"] == 3
            code, _doc = client.post("/v1/regenerate")   # spec exhausted
            code, h = client.get("/v1/healthz")
            assert h["status"] == "ok" and h["state"] == C.HEALTH_OK
        finally:
            srv.stop()


# --------------------------------------------------------------------------- #
class TestShimFault:
    def test_rx_ring_fault_is_one_failed_poll(self):
        from cilium_tpu.shim.bindings import FlowShim
        try:
            shim = FlowShim(batch_size=8, timeout_us=0)
        except OSError:
            pytest.skip("shim library not built")
        try:
            FAULTS.arm("shim.rx_ring", mode="fail", times=1)
            with pytest.raises(FaultInjected):
                shim.poll_batch(now_us=1, force=True)
            # next poll drains normally — nothing was lost, nothing wedged
            assert shim.poll_batch(now_us=2, force=True) is None
        finally:
            shim.close()


# --------------------------------------------------------------------------- #
class TestChaosCLI:
    def test_faults_list(self, capsys):
        rc = cli_main(["faults", "list"])
        out = capsys.readouterr().out
        assert rc == 0
        for point in ("regen.compile", "shim.rx_ring",
                      "clustermesh.peer_read", "checkpoint.write",
                      "api.handler"):
            assert point in out

    def test_faults_list_json(self, capsys):
        rc = cli_main(["faults", "list", "-o", "json"])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert "regen.compile" in doc

    def test_chaos_scenario_fake_datapath(self, capsys):
        """Fast tier-1 subset of `make chaos`: the full scripted scenario
        on the oracle-backed fake datapath."""
        rc = cli_main(["faults", "chaos", "--datapath", "fake",
                       "--failures", "10", "-o", "json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0, doc
        assert doc["ok"] is True
        phases = {p["phase"]: p for p in doc["phases"]}
        assert set(phases) == {"regen-storm", "regen-recovery", "peer-flap",
                               "pipeline-storm", "stall-storm", "breaker",
                               "ct-restart", "checkpoint-corruption",
                               "qos-enqueue-failsafe", "dns-poison"}
        assert all(p["ok"] for p in doc["phases"])
        assert "0 classify errors" in phases["regen-storm"]["detail"]
        assert "0 errors, 0 verdict divergences" in \
            phases["pipeline-storm"]["detail"]
        assert "0 verdict divergences" in \
            phases["qos-enqueue-failsafe"]["detail"]
        assert "0 verdict divergences" in phases["dns-poison"]["detail"]
        # the guard phases: a watchdog restart actually happened and the
        # breaker opened within its threshold budget
        assert "watchdog restart" in phases["stall-storm"]["detail"]
        assert "3/3 post-restart submissions matched baseline" in \
            phases["stall-storm"]["detail"]
        assert "probe closed breaker" in phases["breaker"]["detail"]

    @pytest.mark.slow
    def test_chaos_scenario_jit_datapath(self, capsys):
        """`make chaos` equivalent: the same scenario through the real
        compiled (jit) device path under JAX_PLATFORMS=cpu."""
        rc = cli_main(["faults", "chaos", "--datapath", "jit",
                       "--failures", "10", "-o", "json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0, doc
        assert doc["ok"] is True

    def test_chaos_live_agent(self, tmp_path, capsys):
        """`faults chaos --api`: drive the storm against a live agent over
        its REST socket end-to-end."""
        sock = str(tmp_path / "agent.sock")
        eng = web_engine()
        _ = eng.active
        srv = APIServer(eng, sock)
        srv.start()
        try:
            rc = cli_main(["faults", "chaos", "--api", sock,
                           "--failures", "5", "-o", "json"])
            doc = json.loads(capsys.readouterr().out)
            assert rc == 0, doc
            assert doc["ok"] is True
            assert {p["phase"] for p in doc["phases"]} == {
                "baseline", "arm", "regen-storm", "regen-recovery"}
        finally:
            srv.stop()

    def test_faults_arm_disarm_cli(self, tmp_path, capsys):
        sock = str(tmp_path / "agent.sock")
        eng = web_engine()
        _ = eng.active
        srv = APIServer(eng, sock)
        srv.start()
        try:
            rc = cli_main(["faults", "arm", "--api", sock,
                           "regen.compile=fail:2"])
            assert rc == 0
            assert json.loads(capsys.readouterr().out)["armed"] == 1
            rc = cli_main(["faults", "list", "--api", sock, "-o", "json"])
            doc = json.loads(capsys.readouterr().out)
            assert doc["regen.compile"]["armed"] is True
            rc = cli_main(["faults", "disarm", "--api", sock])
            assert rc == 0
            capsys.readouterr()
            cli_main(["faults", "list", "--api", sock, "-o", "json"])
            doc = json.loads(capsys.readouterr().out)
            assert doc["regen.compile"]["armed"] is False
        finally:
            srv.stop()
