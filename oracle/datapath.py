"""Exact sequential/batch semantic model of the per-packet datapath.

Pipeline per packet (mirrors SURVEY.md §3.3, the bpf_lxc.c handle_xgress
shape: parse → ipcache LPM → conntrack → policy ladder → CT create/update):

1. remote address = dst (egress) / src (ingress); ipcache LPM → remote
   security identity (miss → reserved:world).
2. Conntrack lookup on the normalized tuple:
   - forward key hit   → ESTABLISHED (skip the policy ladder)
   - reverse key hit   → REPLY       (skip the policy ladder)
   - neither           → NEW         (run the ladder)
   Expired entries count as misses. Entries created by an allowed NEW packet.
3. Policy: the MapState precedence ladder (deny-wins → most-specific allow →
   default deny iff direction enforced).
4. L7-lite: packets carrying request tokens (method != NONE) whose *current*
   policy cell is REDIRECT are matched against that cell's http rules — for
   NEW and ESTABLISHED flows alike (the per-request proxy-decision analog:
   upstream's proxy applies the current rules to every request, including on
   connections opened before a policy change). Token-less packets (e.g. the
   TCP handshake) pass at L4. Established flows bypass L3/L4 policy (deny
   included) via the CT hit, exactly like the datapath; only the L7 check
   follows current rules.

Batch semantics — THE CONTRACT FOR THE TPU KERNELS:
- `sequential` mode: packets are processed one at a time, CT effects visible
  to the next packet. This is what the real eBPF datapath does.
- `snapshot` mode: all packets see the CT state from batch start; CT effects
  are applied afterwards as an order-independent aggregate (flags OR, counter
  sums, expiry recomputed from aggregated flags). This is what a data-parallel
  TPU batch computes. For batch size 1 the two modes coincide (test-enforced);
  verdict divergence is possible only for intra-batch flow interleavings and
  is measured, not hidden (see tests/test_parity.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence, Tuple

from cilium_tpu.compile.lpm import pack_pfx
from cilium_tpu.model.ipcache import lpm_lookup, lpm_lookup_pfx
from cilium_tpu.policy.repository import EndpointPolicy
from cilium_tpu.utils import constants as C




# --------------------------------------------------------------------------- #
# Packet record — the 64B fixed record the AF_XDP shim emits (shim/ doc).
# --------------------------------------------------------------------------- #
@dataclass(frozen=True)
class PacketRecord:
    src_addr: bytes                 # 16B normalized (v4-mapped)
    dst_addr: bytes                 # 16B normalized
    src_port: int                   # 0 for port-less protos
    dst_port: int                   # ICMP: the ICMP type
    proto: int                      # IP protocol number
    tcp_flags: int = 0              # low byte of TCP flags; 0 otherwise
    is_ipv6: bool = False
    ep_id: int = 0                  # local endpoint the packet belongs to
    direction: int = C.DIR_EGRESS
    # L7-lite tokens (from the shim's HTTP tokenizer); method NONE → no tokens
    http_method: int = C.HTTP_METHOD_ANY  # method id, 255 = no tokens
    http_path: bytes = b""

    @property
    def has_l7_tokens(self) -> bool:
        return self.http_method != C.HTTP_METHOD_ANY or bool(self.http_path)


@dataclass(frozen=True)
class Verdict:
    allow: bool
    drop_reason: int                # C.DropReason
    ct_status: int                  # C.CTStatus
    remote_identity: int
    redirect: bool = False          # went through L7-lite matching
    matched_key: Optional[object] = None  # MapStateKey for trace
    # CT exhaustion: a NEW allowed flow whose bounded-table probe window
    # stayed full of unevictable entries even after the tail-eviction
    # round — denied with DropReason.CT_FULL (fail closed on tracking
    # exhaustion; see ConntrackTable's bounded mode)
    ct_full: bool = False
    # service LB rewrites (bpf/lib/lb.h analog): forward DNAT applied before
    # classification; reply un-DNAT from the CT entry's rev-NAT id
    svc: bool = False
    nat_dst: bytes = b""            # translated dst (16B) when svc
    nat_dport: int = 0
    rnat: bool = False
    rnat_src: bytes = b""           # VIP to restore as reply src
    rnat_sport: int = 0
    # match provenance (ISSUE 11) — the numeric evidence columns the device
    # emits, computed here from the SAME compiled snapshot geometry so the
    # parity suite / shadow auditor can bit-compare them:
    #   matched_rule: resolved policy-cell coordinate
    #     (id_class * n_port_classes + port_class); -1 when no ladder ran
    #     (unenforced direction, invalid row, NO_SERVICE) or when this
    #     oracle was built without provenance tables (Oracle.for_snapshot
    #     wires them; the bare constructor does not).
    #   lpm_prefix: (prefix_slot << 8) | plen of the winning ipcache entry
    #     (compile/lpm.pack_pfx); -1 on LPM miss / no provenance.
    # ct_state_pre needs no field: it is ``ct_status`` (the probe class
    # as-of classification), which the Verdict already carries.
    matched_rule: int = -1
    lpm_prefix: int = -1


# --------------------------------------------------------------------------- #
# Conntrack
# --------------------------------------------------------------------------- #
CTKey = Tuple[bytes, bytes, int, int, int, int]  # (src,dst,sport,dport,proto,open_dir)


@dataclass
class CTEntry:
    expiry: int
    created: int
    flags: int = 0                  # CT_FLAG_*
    pkts_fwd: int = 0
    pkts_rev: int = 0
    rev_nat: int = 0    # stable rev-NAT id + 1 (see compile/lb.LBTables);
                        # 0 = no service DNAT
    slot: int = -1      # bounded-table slot (hash placement); -1 = unbounded


def _tcp_lifetime(flags: int) -> int:
    if flags & (C.CT_FLAG_TX_CLOSING | C.CT_FLAG_RX_CLOSING):
        return C.CT_LIFETIME_CLOSE
    if flags & C.CT_FLAG_SEEN_NON_SYN:
        return C.CT_LIFETIME_TCP
    return C.CT_LIFETIME_SYN


def _flag_delta(proto: int, tcp_flags: int, is_reply: bool) -> int:
    """CT flag bits contributed by one observed packet."""
    if proto != C.PROTO_TCP:
        return 0
    delta = 0
    if tcp_flags & (C.TCP_FIN | C.TCP_RST):
        delta |= C.CT_FLAG_RX_CLOSING if is_reply else C.CT_FLAG_TX_CLOSING
        if tcp_flags & C.TCP_RST:
            delta |= C.CT_FLAG_RX_CLOSING | C.CT_FLAG_TX_CLOSING
    if not (tcp_flags & C.TCP_SYN):
        delta |= C.CT_FLAG_SEEN_NON_SYN
    return delta


def _entry_expiry(proto: int, flags: int, now: int) -> int:
    if proto == C.PROTO_TCP:
        return now + _tcp_lifetime(flags)
    return now + C.CT_LIFETIME_NONTCP


def _ct_expirable(proto: int, flags: int) -> bool:
    """Which live entries a saturated insert may tail-evict — the host
    mirror of kernels/conntrack.ct_evictable: everything except
    established TCP (SEEN_NON_SYN set, not closing). Under a SYN/UDP flood
    the attack entries are all in the evictable class, so they churn among
    themselves while established flows survive table exhaustion."""
    return not (proto == C.PROTO_TCP
                and (flags & C.CT_FLAG_SEEN_NON_SYN)
                and not (flags & (C.CT_FLAG_TX_CLOSING
                                  | C.CT_FLAG_RX_CLOSING)))


def _key_words(key: CTKey):
    """CTKey → the device's 10-word uint32 CT key (compile/ct_layout)."""
    import numpy as np
    w = np.zeros((10,), dtype=np.uint32)
    w[0:4] = np.frombuffer(key[0], dtype=">u4")
    w[4:8] = np.frombuffer(key[1], dtype=">u4")
    w[8] = ((key[2] << 16) | key[3]) & 0xFFFFFFFF
    w[9] = ((key[4] << 8) | key[5]) & 0xFFFFFFFF
    return w


class ConntrackTable:
    """Host-exact CT table. The device table must agree on lookup results,
    flags, and expiry for every key (counters too, in snapshot mode).

    ``capacity`` (power of two) arms the **bounded mode**: entries occupy
    hash slots computed with the device's exact hash
    (kernels/hashing.hash_words_np over the ct_layout key words) and the
    same ``probe_depth`` linear window, so insert success/failure — and the
    insert-when-full tail eviction — agree with the device table slot for
    slot. The default (capacity=None) stays the unbounded dict of the
    original contract: creates never fail (tests that never saturate keep
    their exact old semantics)."""

    def __init__(self, capacity: Optional[int] = None, probe_depth: int = 8):
        self.entries: Dict[CTKey, CTEntry] = {}
        if capacity is not None and capacity & (capacity - 1):
            raise ValueError("CT capacity must be a power of two")
        self.capacity = capacity
        self.probe_depth = probe_depth
        # slot → (key, entry) physical occupancy; a slot is free iff empty
        # or its OWN entry object is expired (a re-created key may leave a
        # stale expired claim behind, exactly like the device's stale key
        # words in an expired slot)
        self._slots: Optional[List[Optional[Tuple[CTKey, CTEntry]]]] = \
            [None] * capacity if capacity is not None else None
        self.insert_fail = 0            # creates that found no slot
        self.evicted = 0                # live entries tail-evicted

    @property
    def bounded(self) -> bool:
        return self._slots is not None

    @staticmethod
    def fwd_key(p: PacketRecord) -> CTKey:
        return (p.src_addr, p.dst_addr, p.src_port, p.dst_port, p.proto,
                p.direction)

    @staticmethod
    def rev_key(p: PacketRecord) -> CTKey:
        return (p.dst_addr, p.src_addr, p.dst_port, p.src_port, p.proto,
                1 - p.direction)

    # -- bounded-mode slot mechanics ------------------------------------------
    def base_slot(self, key: CTKey) -> int:
        return int(self.base_slots([key])[0])

    def base_slots(self, keys: Sequence[CTKey]):
        """Vectorized base-slot hash for a batch of keys (one numpy hash
        call — classify_batch_snapshot claims whole batches through this)."""
        import numpy as np
        from cilium_tpu.kernels.hashing import hash_words_np
        w = np.stack([_key_words(k) for k in keys])
        return (hash_words_np(w) & np.uint32(self.capacity - 1)).astype(int)

    def _slot_free(self, s: int, now: int) -> bool:
        occ = self._slots[s]
        return occ is None or occ[1].expiry <= now

    def _displace(self, s: int) -> None:
        """Forget slot ``s``'s occupant (claimed by a new entry). The dict
        entry is removed only when it still IS this occupant — a re-created
        key's live entry elsewhere must survive its stale claim dying."""
        occ = self._slots[s]
        if occ is not None and self.entries.get(occ[0]) is occ[1]:
            del self.entries[occ[0]]
        self._slots[s] = None

    def install(self, key: CTKey, entry: CTEntry, slot: int) -> None:
        """Place ``entry`` at ``slot`` (bounded mode; the slot must have
        been claimed through create()/claim_parallel()). An expired
        occupant is displaced — the device analog physically overwrites
        its words."""
        if self._slots[slot] is not None:
            self._displace(slot)
        entry.slot = slot
        self._slots[slot] = (key, entry)
        self.entries[key] = entry

    def _find_slot(self, key: CTKey, now: int,
                   protected: Optional[set] = None) -> Tuple[int, bool]:
        """→ (slot, evicts_live) for one create against current state, or
        (-1, False) when the window is exhausted. Mirrors the device probe
        order exactly: free slots first (earliest probe offset), then the
        tail-eviction victim — smallest expiry among live evictable
        unprotected occupants, ties to the earliest offset."""
        cap = self.capacity
        base = self.base_slot(key)
        for r in range(self.probe_depth):
            s = (base + r) % cap
            if self._slot_free(s, now):
                return s, False
        best_s, best_e = -1, None
        for r in range(self.probe_depth):
            s = (base + r) % cap
            if protected is not None and s in protected:
                continue
            k2, e2 = self._slots[s]
            if not _ct_expirable(k2[4], e2.flags):
                continue
            if best_e is None or e2.expiry < best_e:
                best_s, best_e = s, e2.expiry
        return best_s, best_s >= 0

    # -- the dict-facing contract ---------------------------------------------
    def probe(self, p: PacketRecord, now: int) -> Tuple[int, Optional[CTKey]]:
        """(CTStatus, hit key) against current state; expired = miss."""
        k = self.fwd_key(p)
        e = self.entries.get(k)
        if e is not None and e.expiry > now:
            return C.CTStatus.ESTABLISHED, k
        k = self.rev_key(p)
        e = self.entries.get(k)
        if e is not None and e.expiry > now:
            return C.CTStatus.REPLY, k
        return C.CTStatus.NEW, None

    def update(self, key: CTKey, p: PacketRecord, is_reply: bool, now: int) -> None:
        e = self.entries[key]
        e.flags |= _flag_delta(p.proto, p.tcp_flags, is_reply)
        e.expiry = _entry_expiry(p.proto, e.flags, now)
        if is_reply:
            e.pkts_rev += 1
        else:
            e.pkts_fwd += 1

    def create(self, p: PacketRecord, now: int,
               rev_nat: int = 0) -> Optional[CTKey]:
        """Create the forward entry. Bounded mode may fail: returns None
        when the probe window is exhausted even after the eviction round —
        the caller classifies the packet DROP CT_FULL (fail closed)."""
        key = self.fwd_key(p)
        flags = _flag_delta(p.proto, p.tcp_flags, is_reply=False)
        entry = CTEntry(
            expiry=_entry_expiry(p.proto, flags, now),
            created=now,
            flags=flags,
            pkts_fwd=1,
            rev_nat=rev_nat,
        )
        if self.bounded:
            s, evicts = self._find_slot(key, now)
            if s < 0:
                self.insert_fail += 1
                return None
            if evicts:
                self.evicted += 1
            self.install(key, entry, s)
        else:
            self.entries[key] = entry
        return key

    def claim_parallel(self, creations: List[Tuple[int, CTKey]], now: int,
                       protected: set) -> Tuple[Dict[CTKey, int], set, int]:
        """Snapshot-batch slot claiming: the EXACT parallel-round protocol
        of kernels/conntrack.ct_insert_new, at packet granularity —
        per-round free-slot attempts with lowest-packet-index conflict
        resolution, duplicate-key adoption sweeps, then one tail-eviction
        round (victim = min expiry among live evictable unclaimed
        unprotected window slots, ties to the earliest probe offset,
        contested victims to the lowest packet index).

        ``creations`` is [(packet_index, fwd_key)] in ascending index order
        for every allowed NEW packet; ``protected`` is the set of slots any
        packet of this batch probe-hit. Returns (claims: key → slot with
        victims displaced and physical claims installed as stale-free
        placeholders, failed_indices, n_evicted). The caller installs the
        aggregated entries at the claimed slots afterwards."""
        cap = self.capacity
        if not creations:
            return {}, set(), 0
        bases = self.base_slots([k for _i, k in creations])
        pend = [(i, k, int(b)) for (i, k), b in zip(creations, bases)]
        claimed: Dict[int, CTKey] = {}          # slot → key (this batch)
        claims: Dict[CTKey, int] = {}
        for r in range(self.probe_depth):
            if r > 0:
                # adoption: a lower-indexed duplicate of my key may have
                # won my previous round's target
                still = []
                for i, k, b in pend:
                    sprev = (b + r - 1) % cap
                    if claimed.get(sprev) == k:
                        continue                 # adopted
                    still.append((i, k, b))
                pend = still
            round_claims: Dict[int, Tuple[int, CTKey]] = {}
            for i, k, b in pend:
                s = (b + r) % cap
                if s in claimed:
                    continue
                if self._slot_free(s, now):
                    if s not in round_claims or i < round_claims[s][0]:
                        round_claims[s] = (i, k)
            won = {i for s, (i, _k) in round_claims.items()}
            for s, (_i, k) in round_claims.items():
                claimed[s] = k
                claims[k] = s
            pend = [(i, k, b) for i, k, b in pend if i not in won]
        # final adoption sweep
        still = []
        for i, k, b in pend:
            if any(claimed.get((b + r) % cap) == k
                   for r in range(self.probe_depth)):
                continue
            still.append((i, k, b))
        pend = still
        # tail-eviction round (batch-start occupancy throughout)
        evict_claims: Dict[int, Tuple[int, CTKey]] = {}
        for i, k, b in pend:
            best_s, best_e = -1, None
            for r in range(self.probe_depth):
                s = (b + r) % cap
                if s in claimed or s in protected:
                    continue
                occ = self._slots[s]
                if occ is None or occ[1].expiry <= now:
                    continue                     # free slots are all claimed
                if not _ct_expirable(occ[0][4], occ[1].flags):
                    continue
                if best_e is None or occ[1].expiry < best_e:
                    best_s, best_e = s, occ[1].expiry
            if best_s >= 0 and (best_s not in evict_claims
                                or i < evict_claims[best_s][0]):
                evict_claims[best_s] = (i, k)
        n_evicted = 0
        won = set()
        for s, (i, k) in evict_claims.items():
            self._displace(s)
            n_evicted += 1
            self.evicted += 1
            claimed[s] = k
            claims[k] = s
            won.add(i)
        pend = [(i, k, b) for i, k, b in pend if i not in won]
        # adoption of evict-winners' duplicates
        still = []
        for i, k, b in pend:
            if any(claimed.get((b + r) % cap) == k
                   for r in range(self.probe_depth)):
                continue
            still.append((i, k, b))
        failed = {i for i, _k, _b in still}
        self.insert_fail += len(failed)
        return claims, failed, n_evicted

    def sweep(self, now: int) -> int:
        """GC expired entries (upstream: ctmap GC); returns count removed."""
        dead = [k for k, e in self.entries.items() if e.expiry <= now]
        for k in dead:
            del self.entries[k]
        if self._slots is not None:
            for s, occ in enumerate(self._slots):
                if occ is not None and occ[1].expiry <= now:
                    self._slots[s] = None
        return len(dead)

    def __len__(self) -> int:
        return len(self.entries)


# --------------------------------------------------------------------------- #
# L7-lite matching
# --------------------------------------------------------------------------- #
def l7_match(http_rules, method: int, path: bytes) -> bool:
    """True iff any rule admits (method, path): method exact-or-any AND the
    rule's path is a byte-prefix of the request path."""
    for rule in http_rules:
        m_ok = (not rule.method) or (C.HTTP_METHOD_IDS.get(rule.method) == method)
        p = rule.path.encode()
        if m_ok and path[: len(p)] == p:
            return True
    return False


# --------------------------------------------------------------------------- #
# The oracle
# --------------------------------------------------------------------------- #
@dataclass(frozen=True)
class ProvenanceTables:
    """The compiled-geometry slices the oracle needs to emit the SAME
    numeric provenance the device does: identity-class / port-class maps
    for matched_rule, and the prefix-slot enumeration for lpm_prefix.
    Built by :meth:`Oracle.for_snapshot`; a bare-constructed oracle has
    none and emits -1 (provenance unknown, never wrong)."""
    class_of: object               # np [n_identities] identity idx → class
    index_of: Dict[int, int]       # identity id → dense index
    port_table: object             # np [families, 65536] → port class
    n_port_classes: int
    pfx_slot_of: Dict[str, int]    # canonical prefix → slot

    @classmethod
    def from_snapshot(cls, snap) -> "ProvenanceTables":
        return cls(class_of=snap.id_classes.class_of,
                   index_of=dict(snap.id_classes.index_of),
                   port_table=snap.port_classes.table,
                   n_port_classes=snap.port_classes.n_classes,
                   pfx_slot_of=dict(snap.lpm.pfx_slot_of))


class Oracle:
    @classmethod
    def for_snapshot(cls, snap, ct: Optional[ConntrackTable] = None
                     ) -> "Oracle":
        """Oracle over one compiled PolicySnapshot — the ONE place the
        snapshot→oracle construction (slot-aligned policies, compiled
        ipcache, the n_frontends LB gate, the provenance tables) lives,
        shared by the fake datapath and the shadow auditor so their
        replays can never be built against differently-wired oracles."""
        return cls(dict(zip(snap.ep_ids, snap.policies)), snap.ipcache,
                   ct=ct, lb=snap.lb if snap.lb.n_frontends else None,
                   prov=ProvenanceTables.from_snapshot(snap))

    def __init__(self, policies: Dict[int, EndpointPolicy],
                 ipcache_entries: Dict[str, int],
                 ct: Optional[ConntrackTable] = None,
                 lb=None, prov: Optional[ProvenanceTables] = None):
        self.policies = policies
        self.ipcache_entries = dict(ipcache_entries)
        self.ct = ct if ct is not None else ConntrackTable()
        # Service LB state: a compiled compile/lb.LBTables (control-plane
        # input, like the policy snapshot). None = no services.
        self.lb = lb
        self.prov = prov
        self._frontends: Dict[Tuple[bytes, int, int], int] = {}
        if lb is not None:
            from cilium_tpu.utils.ip import parse_addr
            for i, fe in enumerate(lb.frontends):
                addr16, _ = parse_addr(fe.addr)
                self._frontends[(addr16, fe.port, fe.proto)] = i

    # -- service LB (mirrors kernels/lb.py; bpf/lib/lb.h analog) ------------
    def _translate(self, p: PacketRecord
                   ) -> Tuple[PacketRecord, int, bool]:
        """→ (possibly-DNAT'ed packet, rev_nat value (stable id + 1),
        no_backend)."""
        if self.lb is None:
            return p, 0, False
        fe_idx = self._frontends.get((p.dst_addr, p.dst_port, p.proto))
        if fe_idx is None:
            return p, 0, False
        import numpy as np
        from cilium_tpu.compile.lb import lb_select_words_np
        from cilium_tpu.kernels.hashing import hash_words_np
        from cilium_tpu.utils.ip import addr_to_words, words_to_addr
        batch1 = {
            "src": np.array([addr_to_words(p.src_addr)], dtype=np.uint32),
            "dst": np.array([addr_to_words(p.dst_addr)], dtype=np.uint32),
            "sport": np.array([p.src_port], dtype=np.int32),
            "dport": np.array([p.dst_port], dtype=np.int32),
            "proto": np.array([p.proto], dtype=np.int32),
        }
        m = self.lb.maglev.shape[1]
        slot = int(hash_words_np(lb_select_words_np(batch1))[0]) % m
        be = int(self.lb.maglev[int(self.lb.fe_service[fe_idx]), slot])
        if be < 0:
            return p, 0, True
        new_dst = words_to_addr(self.lb.be_addr[be])
        p2 = replace(p, dst_addr=new_dst, dst_port=int(self.lb.be_port[be]))
        return p2, int(self.lb.fe_rnat_id[fe_idx]) + 1, False

    def _rnat_fields(self, entry: Optional[CTEntry], p: PacketRecord) -> Dict:
        """Reply un-DNAT output fields from a hit CT entry. Stale ids whose
        service is gone resolve to an invalid row → no rewrite."""
        if entry is None or entry.rev_nat == 0 or self.lb is None:
            return {}
        from cilium_tpu.utils.ip import words_to_addr
        rid = entry.rev_nat - 1
        if rid >= self.lb.rnat_valid.shape[0] or not self.lb.rnat_valid[rid]:
            return {}
        return {
            "rnat": True,
            "rnat_src": words_to_addr(self.lb.rnat_addr[rid]),
            "rnat_sport": int(self.lb.rnat_port[rid]),
        }

    # -- helpers ------------------------------------------------------------
    def _remote_identity(self, p: PacketRecord) -> int:
        return self._remote_identity_pfx(p)[0]

    def _remote_identity_pfx(self, p: PacketRecord) -> Tuple[int, int]:
        """One LPM walk → (remote identity, packed lpm_prefix provenance).
        The identity and the prefix come from the SAME winning entry — the
        host mirror of the device trie's paired value/provenance planes."""
        from cilium_tpu.utils.ip import addr_to_str
        remote = p.dst_addr if p.direction == C.DIR_EGRESS else p.src_addr
        ident, prefix, plen = lpm_lookup_pfx(self.ipcache_entries,
                                             addr_to_str(remote))
        if self.prov is None or prefix is None:
            return ident, -1
        slot = self.prov.pfx_slot_of.get(prefix, -1)
        return ident, (pack_pfx(slot, plen) if slot >= 0 else -1)

    def _rule_of(self, remote_id: int, proto: int, dport: int) -> int:
        """matched_rule for one (remote, proto, dport): the resolved
        verdict-cell coordinate id_class * n_port_classes + port_class —
        the same gathers the device ladder runs (kernels/policy.py), over
        the same compiled tables."""
        prov = self.prov
        if prov is None:
            return -1
        idx = prov.index_of.get(remote_id)
        if idx is None:
            return -1
        id_cls = int(prov.class_of[idx])
        fam = C.proto_family(proto)
        pcls = int(prov.port_table[fam, min(max(dport, 0), 65535)])
        return id_cls * prov.n_port_classes + pcls

    def _evaluate(self, p: PacketRecord, remote_id: int):
        """Current-policy evaluation → (enforced, lookup_result | None).
        lookup_result is None when the direction is unenforced."""
        pol = self.policies.get(p.ep_id)
        if pol is None:
            return None  # unknown endpoint — fail closed
        dirpol = pol.direction(p.direction)
        if not dirpol.enforced:
            return (False, None)
        return (True, dirpol.lookup(remote_id, p.proto, p.dst_port))

    def _verdict_for(self, p: PacketRecord, remote_id: int, status: int,
                     pfx: int = -1) -> Tuple[Verdict, bool]:
        """(verdict, create_entry) against the current CT probe result,
        with the provenance columns attached: ``pfx`` is the packed
        lpm_prefix from the caller's LPM walk; matched_rule follows the
        device mask exactly — a value wherever the direction is enforced
        (CT-hit rows included: the ladder is computed branch-free either
        way), -1 otherwise."""
        verdict, create = self._verdict_core(p, remote_id, status)
        mr = -1
        if self.prov is not None:
            pol = self.policies.get(p.ep_id)
            if pol is not None and pol.direction(p.direction).enforced:
                mr = self._rule_of(remote_id, p.proto, p.dst_port)
        return replace(verdict, matched_rule=mr, lpm_prefix=pfx), create

    def _verdict_core(self, p: PacketRecord, remote_id: int, status: int
                      ) -> Tuple[Verdict, bool]:
        """(verdict, create_entry) against the current CT probe result."""
        ev = self._evaluate(p, remote_id)
        if ev is None:
            return Verdict(False, C.DropReason.INVALID_IDENTITY, status,
                           remote_id), False
        enforced, res = ev
        cell_redirect = (res is not None
                         and res.decision == C.VERDICT_REDIRECT)
        l7_fail = (cell_redirect and p.has_l7_tokens
                   and not l7_match(res.entry.l7_rules, p.http_method,
                                    p.http_path))
        key = res.key if res is not None else None

        if status != C.CTStatus.NEW:
            # CT hit bypasses L3/L4 policy; only current-cell L7 applies.
            if l7_fail:
                return Verdict(False, C.DropReason.POLICY_L7, status,
                               remote_id, redirect=True, matched_key=key), False
            return Verdict(True, C.DropReason.OK, status, remote_id,
                           redirect=cell_redirect, matched_key=key), False

        if not enforced:
            return Verdict(True, C.DropReason.OK, status, remote_id), True
        if res.decision == C.VERDICT_DENY:
            return Verdict(False, C.DropReason.POLICY_DENY, status, remote_id,
                           matched_key=key), False
        if res.decision == C.VERDICT_MISS:
            return Verdict(False, C.DropReason.POLICY, status, remote_id,
                           matched_key=key), False
        if cell_redirect:
            if l7_fail:
                return Verdict(False, C.DropReason.POLICY_L7, status,
                               remote_id, redirect=True, matched_key=key), False
            return Verdict(True, C.DropReason.OK, status, remote_id,
                           redirect=True, matched_key=key), True
        return Verdict(True, C.DropReason.OK, status, remote_id,
                       matched_key=key), True

    # -- audit replay (observe/audit.py shadow-oracle parity) ----------------
    def replay(self, p: PacketRecord, status: int,
               ct_full: bool = False) -> Tuple[Verdict, bool]:
        """Re-derive the verdict for one packet given an externally observed
        CT probe result — the shadow-audit replay entry point.

        Runs the exact classify() chain — service DNAT, ipcache LPM, the
        policy precedence ladder, L7-lite matching — but takes ``status``
        (the CT state the datapath saw *as of classification*, captured at
        finalize) as the conntrack truth instead of probing ``self.ct``,
        and mutates nothing: no CT create/update, safe to call from a
        background auditor long after the live table has moved on.

        Returns ``(verdict, create)`` where ``create`` is the CT-delta the
        datapath must have applied for this row (True: an allowed NEW
        packet creates its forward entry). Reply un-DNAT fields are NOT
        reconstructed (they come from the live CT entry's rev_nat id, which
        is not part of the captured probe input); callers check them for
        structural consistency instead of bit-equality.

        ``ct_full`` is the captured CT-exhaustion signal — like ``status``,
        a datapath-internal table fact as-of classification (the insert's
        probe window stayed saturated with unevictable entries after the
        eviction round). It only ever EXCUSES a create the replay itself
        demands: the verdict flips to the CT_FULL deny exactly when the
        policy chain would have allowed-and-created, so a datapath that
        flags ct_full on any other row still mismatches."""
        tp, rev_nat, no_backend = self._translate(p)
        if no_backend:
            rid, pfx = self._remote_identity_pfx(p)
            return Verdict(False, C.DropReason.NO_SERVICE, C.CTStatus.NEW,
                           rid, lpm_prefix=pfx), False
        remote_id, pfx = self._remote_identity_pfx(tp)
        verdict, create = self._verdict_for(tp, remote_id, status, pfx=pfx)
        if ct_full and create and verdict.allow:
            verdict = replace(verdict, allow=False,
                              drop_reason=C.DropReason.CT_FULL,
                              ct_full=True)
            create = False
        if rev_nat:
            verdict = replace(verdict, svc=True, nat_dst=tp.dst_addr,
                              nat_dport=tp.dst_port)
        return verdict, create

    # -- sequential (true eBPF per-packet semantics) ------------------------
    def classify(self, p: PacketRecord, now: int) -> Verdict:
        tp, rev_nat, no_backend = self._translate(p)
        if no_backend:
            # kernel mirror: the packet is masked out of the datapath, so
            # its CT status reads NEW; remote identity from the VIP itself
            rid, pfx = self._remote_identity_pfx(p)
            return Verdict(False, C.DropReason.NO_SERVICE, C.CTStatus.NEW,
                           rid, lpm_prefix=pfx)
        remote_id, pfx = self._remote_identity_pfx(tp)
        status, hit_key = self.ct.probe(tp, now)
        verdict, create = self._verdict_for(tp, remote_id, status, pfx=pfx)
        extra: Dict = {}
        if rev_nat:
            extra.update(svc=True, nat_dst=tp.dst_addr,
                         nat_dport=tp.dst_port)
        if status == C.CTStatus.REPLY:
            extra.update(self._rnat_fields(self.ct.entries.get(hit_key), tp))
        if status != C.CTStatus.NEW:
            if verdict.allow:
                self.ct.update(hit_key, tp,
                               is_reply=(status == C.CTStatus.REPLY), now=now)
        elif create:
            if self.ct.create(tp, now, rev_nat=rev_nat) is None:
                # bounded table exhausted even after the eviction round:
                # the flow is untrackable → fail closed (device mirror)
                verdict = replace(verdict, allow=False,
                                  drop_reason=C.DropReason.CT_FULL,
                                  ct_full=True)
        return replace(verdict, **extra) if extra else verdict

    def classify_batch_sequential(self, packets: List[PacketRecord],
                                  now: int) -> List[Verdict]:
        return [self.classify(p, now) for p in packets]

    # -- snapshot (data-parallel TPU batch semantics) -----------------------
    def classify_batch_snapshot(self, packets: List[PacketRecord],
                                now: int) -> List[Verdict]:
        # Phase 1: all verdicts against the CT snapshot at batch start.
        verdicts: List[Verdict] = []
        probes: List[Tuple[int, Optional[CTKey]]] = []
        tps: List[PacketRecord] = []
        rev_nats: List[int] = []
        for p in packets:
            tp, rev_nat, no_backend = self._translate(p)
            tps.append(tp)
            rev_nats.append(rev_nat)
            if no_backend:
                rid, pfx = self._remote_identity_pfx(p)
                verdicts.append(Verdict(False, C.DropReason.NO_SERVICE,
                                        C.CTStatus.NEW, rid,
                                        lpm_prefix=pfx))
                probes.append((C.CTStatus.NEW, None))
                continue
            remote_id, pfx = self._remote_identity_pfx(tp)
            status, hit_key = self.ct.probe(tp, now)
            probes.append((status, hit_key))
            verdict, _create = self._verdict_for(tp, remote_id, status,
                                                 pfx=pfx)
            extra: Dict = {}
            if rev_nat:
                extra.update(svc=True, nat_dst=tp.dst_addr,
                             nat_dport=tp.dst_port)
            if status == C.CTStatus.REPLY:
                extra.update(self._rnat_fields(self.ct.entries.get(hit_key),
                                               tp))
            verdicts.append(replace(verdict, **extra) if extra else verdict)

        # Phase 1.5 (bounded tables): parallel slot claiming for creates —
        # the exact round protocol of kernels/conntrack.ct_insert_new, so a
        # saturated table fails (and tail-evicts) the same flows the device
        # does. Slots any packet of this batch probe-hit are protected from
        # eviction; packets whose claim fails flip to the CT_FULL deny
        # BEFORE aggregation (their create never happens).
        claims: Dict[CTKey, int] = {}
        if self.ct.bounded:
            protected = set()
            for status, hit_key in probes:
                if hit_key is not None:
                    e = self.ct.entries.get(hit_key)
                    if e is not None and e.slot >= 0:
                        protected.add(e.slot)
            creations = [(i, ConntrackTable.fwd_key(tps[i]))
                         for i, (v, (status, _hk)) in enumerate(
                             zip(verdicts, probes))
                         if status == C.CTStatus.NEW and v.allow]
            claims, failed, _n_evicted = self.ct.claim_parallel(
                creations, now, protected)
            for i in failed:
                verdicts[i] = replace(verdicts[i], allow=False,
                                      drop_reason=C.DropReason.CT_FULL,
                                      ct_full=True)

        # Phase 2: order-independent aggregate CT effects.
        #   For each touched key: flags |= OR of deltas; counters += sums;
        #   expiry recomputed once from aggregated flags.
        agg: Dict[CTKey, Dict] = {}

        def touch(key: CTKey):
            return agg.setdefault(key, {
                "flag_delta": 0, "fwd": 0, "rev": 0, "create": False,
                "rev_nat": 0,
            })

        for p, rev_nat, v, (status, hit_key) in zip(tps, rev_nats, verdicts,
                                                    probes):
            if not v.allow:
                continue
            if status == C.CTStatus.ESTABLISHED:
                a = touch(hit_key)
                a["flag_delta"] |= _flag_delta(p.proto, p.tcp_flags, False)
                a["fwd"] += 1
            elif status == C.CTStatus.REPLY:
                a = touch(hit_key)
                a["flag_delta"] |= _flag_delta(p.proto, p.tcp_flags, True)
                a["rev"] += 1
            else:
                a = touch(ConntrackTable.fwd_key(p))
                a["flag_delta"] |= _flag_delta(p.proto, p.tcp_flags, False)
                a["fwd"] += 1
                a["create"] = True
                a["rev_nat"] = max(a["rev_nat"], rev_nat)

        for key, a in agg.items():
            entry = self.ct.entries.get(key)
            if entry is not None and entry.expiry <= now and a["create"]:
                entry = None  # expired slot is replaced, not updated
            if entry is None:
                if not a["create"]:
                    continue
                entry = CTEntry(expiry=0, created=now,
                                rev_nat=a["rev_nat"])
                if self.ct.bounded:
                    slot = claims.get(key)
                    if slot is None:
                        continue     # claim failed: verdict already CT_FULL
                    self.ct.install(key, entry, slot)
                else:
                    self.ct.entries[key] = entry
            proto = key[4]
            entry.flags |= a["flag_delta"]
            entry.pkts_fwd += a["fwd"]
            entry.pkts_rev += a["rev"]
            entry.expiry = _entry_expiry(proto, entry.flags, now)
        return verdicts
