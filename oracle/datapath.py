"""Exact sequential/batch semantic model of the per-packet datapath.

Pipeline per packet (mirrors SURVEY.md §3.3, the bpf_lxc.c handle_xgress
shape: parse → ipcache LPM → conntrack → policy ladder → CT create/update):

1. remote address = dst (egress) / src (ingress); ipcache LPM → remote
   security identity (miss → reserved:world).
2. Conntrack lookup on the normalized tuple:
   - forward key hit   → ESTABLISHED (skip the policy ladder)
   - reverse key hit   → REPLY       (skip the policy ladder)
   - neither           → NEW         (run the ladder)
   Expired entries count as misses. Entries created by an allowed NEW packet.
3. Policy: the MapState precedence ladder (deny-wins → most-specific allow →
   default deny iff direction enforced).
4. L7-lite: entries with http rules mark the CT entry `redirect`; packets
   carrying request tokens (method != NONE) on a redirect flow are matched
   against the http rules each time (the per-request proxy-decision analog);
   token-less packets (e.g. the TCP handshake) pass at L4.

Batch semantics — THE CONTRACT FOR THE TPU KERNELS:
- `sequential` mode: packets are processed one at a time, CT effects visible
  to the next packet. This is what the real eBPF datapath does.
- `snapshot` mode: all packets see the CT state from batch start; CT effects
  are applied afterwards as an order-independent aggregate (flags OR, counter
  sums, expiry recomputed from aggregated flags). This is what a data-parallel
  TPU batch computes. For batch size 1 the two modes coincide (test-enforced);
  verdict divergence is possible only for intra-batch flow interleavings and
  is measured, not hidden (see tests/test_parity.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple

from cilium_tpu.model.ipcache import lpm_lookup
from cilium_tpu.policy.repository import EndpointPolicy
from cilium_tpu.utils import constants as C

CT_NO_L7 = 0  # l7_id value meaning "no redirect"


# --------------------------------------------------------------------------- #
# Packet record — the 64B fixed record the AF_XDP shim emits (shim/ doc).
# --------------------------------------------------------------------------- #
@dataclass(frozen=True)
class PacketRecord:
    src_addr: bytes                 # 16B normalized (v4-mapped)
    dst_addr: bytes                 # 16B normalized
    src_port: int                   # 0 for port-less protos
    dst_port: int                   # ICMP: the ICMP type
    proto: int                      # IP protocol number
    tcp_flags: int = 0              # low byte of TCP flags; 0 otherwise
    is_ipv6: bool = False
    ep_id: int = 0                  # local endpoint the packet belongs to
    direction: int = C.DIR_EGRESS
    # L7-lite tokens (from the shim's HTTP tokenizer); method NONE → no tokens
    http_method: int = C.HTTP_METHOD_ANY  # method id, 255 = no tokens
    http_path: bytes = b""

    @property
    def has_l7_tokens(self) -> bool:
        return self.http_method != C.HTTP_METHOD_ANY or bool(self.http_path)


@dataclass(frozen=True)
class Verdict:
    allow: bool
    drop_reason: int                # C.DropReason
    ct_status: int                  # C.CTStatus
    remote_identity: int
    redirect: bool = False          # went through L7-lite matching
    matched_key: Optional[object] = None  # MapStateKey for trace


# --------------------------------------------------------------------------- #
# Conntrack
# --------------------------------------------------------------------------- #
CTKey = Tuple[bytes, bytes, int, int, int, int]  # (src,dst,sport,dport,proto,open_dir)


@dataclass
class CTEntry:
    expiry: int
    created: int
    flags: int = 0                  # CT_FLAG_*
    redirect_l7_id: int = CT_NO_L7  # non-zero → L7-lite flow, id into l7 sets
    pkts_fwd: int = 0
    pkts_rev: int = 0


def _tcp_lifetime(flags: int) -> int:
    if flags & (C.CT_FLAG_TX_CLOSING | C.CT_FLAG_RX_CLOSING):
        return C.CT_LIFETIME_CLOSE
    if flags & C.CT_FLAG_SEEN_NON_SYN:
        return C.CT_LIFETIME_TCP
    return C.CT_LIFETIME_SYN


def _flag_delta(proto: int, tcp_flags: int, is_reply: bool) -> int:
    """CT flag bits contributed by one observed packet."""
    if proto != C.PROTO_TCP:
        return 0
    delta = 0
    if tcp_flags & (C.TCP_FIN | C.TCP_RST):
        delta |= C.CT_FLAG_RX_CLOSING if is_reply else C.CT_FLAG_TX_CLOSING
        if tcp_flags & C.TCP_RST:
            delta |= C.CT_FLAG_RX_CLOSING | C.CT_FLAG_TX_CLOSING
    if not (tcp_flags & C.TCP_SYN):
        delta |= C.CT_FLAG_SEEN_NON_SYN
    return delta


def _entry_expiry(proto: int, flags: int, now: int) -> int:
    if proto == C.PROTO_TCP:
        return now + _tcp_lifetime(flags)
    return now + C.CT_LIFETIME_NONTCP


class ConntrackTable:
    """Host-exact CT table. The device table must agree on lookup results,
    flags, and expiry for every key (counters too, in snapshot mode)."""

    def __init__(self):
        self.entries: Dict[CTKey, CTEntry] = {}

    @staticmethod
    def fwd_key(p: PacketRecord) -> CTKey:
        return (p.src_addr, p.dst_addr, p.src_port, p.dst_port, p.proto,
                p.direction)

    @staticmethod
    def rev_key(p: PacketRecord) -> CTKey:
        return (p.dst_addr, p.src_addr, p.dst_port, p.src_port, p.proto,
                1 - p.direction)

    def probe(self, p: PacketRecord, now: int) -> Tuple[int, Optional[CTKey]]:
        """(CTStatus, hit key) against current state; expired = miss."""
        k = self.fwd_key(p)
        e = self.entries.get(k)
        if e is not None and e.expiry > now:
            return C.CTStatus.ESTABLISHED, k
        k = self.rev_key(p)
        e = self.entries.get(k)
        if e is not None and e.expiry > now:
            return C.CTStatus.REPLY, k
        return C.CTStatus.NEW, None

    def update(self, key: CTKey, p: PacketRecord, is_reply: bool, now: int) -> None:
        e = self.entries[key]
        e.flags |= _flag_delta(p.proto, p.tcp_flags, is_reply)
        e.expiry = _entry_expiry(p.proto, e.flags, now)
        if is_reply:
            e.pkts_rev += 1
        else:
            e.pkts_fwd += 1

    def create(self, p: PacketRecord, now: int, l7_id: int = CT_NO_L7) -> CTKey:
        key = self.fwd_key(p)
        flags = _flag_delta(p.proto, p.tcp_flags, is_reply=False)
        self.entries[key] = CTEntry(
            expiry=_entry_expiry(p.proto, flags, now),
            created=now,
            flags=flags,
            redirect_l7_id=l7_id,
            pkts_fwd=1,
        )
        return key

    def sweep(self, now: int) -> int:
        """GC expired entries (upstream: ctmap GC); returns count removed."""
        dead = [k for k, e in self.entries.items() if e.expiry <= now]
        for k in dead:
            del self.entries[k]
        return len(dead)

    def __len__(self) -> int:
        return len(self.entries)


# --------------------------------------------------------------------------- #
# L7-lite matching
# --------------------------------------------------------------------------- #
def l7_match(http_rules, method: int, path: bytes) -> bool:
    """True iff any rule admits (method, path): method exact-or-any AND the
    rule's path is a byte-prefix of the request path."""
    for rule in http_rules:
        m_ok = (not rule.method) or (C.HTTP_METHOD_IDS.get(rule.method) == method)
        p = rule.path.encode()
        if m_ok and path[: len(p)] == p:
            return True
    return False


# --------------------------------------------------------------------------- #
# The oracle
# --------------------------------------------------------------------------- #
class Oracle:
    def __init__(self, policies: Dict[int, EndpointPolicy],
                 ipcache_entries: Dict[str, int],
                 ct: Optional[ConntrackTable] = None):
        self.policies = policies
        self.ipcache_entries = dict(ipcache_entries)
        self.ct = ct if ct is not None else ConntrackTable()
        # l7 sets are interned per-policy at lookup time: id = index+1 into
        # this list (0 = no redirect), shared across endpoints.
        self.l7_sets: List[frozenset] = []
        self._l7_index: Dict[frozenset, int] = {}

    # -- helpers ------------------------------------------------------------
    def _l7_id(self, rules: frozenset) -> int:
        idx = self._l7_index.get(rules)
        if idx is None:
            self.l7_sets.append(rules)
            idx = len(self.l7_sets)  # 1-based; 0 = none
            self._l7_index[rules] = idx
        return idx

    def _remote_identity(self, p: PacketRecord) -> int:
        from cilium_tpu.utils.ip import addr_to_str
        remote = p.dst_addr if p.direction == C.DIR_EGRESS else p.src_addr
        return lpm_lookup(self.ipcache_entries, addr_to_str(remote))

    def _policy_verdict(self, p: PacketRecord, remote_id: int):
        """(allow, drop_reason, redirect, l7_id, matched_key)."""
        pol = self.policies.get(p.ep_id)
        if pol is None:
            return False, C.DropReason.INVALID_IDENTITY, False, CT_NO_L7, None
        dirpol = pol.direction(p.direction)
        if not dirpol.enforced:
            return True, C.DropReason.OK, False, CT_NO_L7, None
        res = dirpol.lookup(remote_id, p.proto, p.dst_port)
        if res.decision == C.VERDICT_DENY:
            return False, C.DropReason.POLICY_DENY, False, CT_NO_L7, res.key
        if res.decision == C.VERDICT_MISS:
            return False, C.DropReason.POLICY, False, CT_NO_L7, res.key
        if res.decision == C.VERDICT_REDIRECT:
            l7_id = self._l7_id(res.entry.l7_rules)
            if p.has_l7_tokens:
                ok = l7_match(res.entry.l7_rules, p.http_method, p.http_path)
                reason = C.DropReason.OK if ok else C.DropReason.POLICY_L7
                return ok, reason, True, l7_id, res.key
            return True, C.DropReason.OK, True, l7_id, res.key
        return True, C.DropReason.OK, False, CT_NO_L7, res.key

    # -- sequential (true eBPF per-packet semantics) ------------------------
    def classify(self, p: PacketRecord, now: int) -> Verdict:
        remote_id = self._remote_identity(p)
        status, hit_key = self.ct.probe(p, now)

        if status != C.CTStatus.NEW:
            entry = self.ct.entries[hit_key]
            # Established L7-lite flows re-check tokens per request.
            if entry.redirect_l7_id != CT_NO_L7 and p.has_l7_tokens:
                rules = self.l7_sets[entry.redirect_l7_id - 1]
                if not l7_match(rules, p.http_method, p.http_path):
                    return Verdict(False, C.DropReason.POLICY_L7, status,
                                   remote_id, redirect=True)
            self.ct.update(hit_key, p, is_reply=(status == C.CTStatus.REPLY),
                           now=now)
            return Verdict(True, C.DropReason.OK, status, remote_id,
                           redirect=entry.redirect_l7_id != CT_NO_L7)

        allow, reason, redirect, l7_id, key = self._policy_verdict(p, remote_id)
        if allow:
            self.ct.create(p, now, l7_id=l7_id)
        return Verdict(allow, reason, C.CTStatus.NEW, remote_id,
                       redirect=redirect, matched_key=key)

    def classify_batch_sequential(self, packets: List[PacketRecord],
                                  now: int) -> List[Verdict]:
        return [self.classify(p, now) for p in packets]

    # -- snapshot (data-parallel TPU batch semantics) -----------------------
    def classify_batch_snapshot(self, packets: List[PacketRecord],
                                now: int) -> List[Verdict]:
        # Phase 1: all verdicts against the CT snapshot at batch start.
        # l7_ids[i] carries the policy-computed l7 id for NEW packets so
        # phase 2 never re-runs the ladder.
        verdicts: List[Verdict] = []
        probes: List[Tuple[int, Optional[CTKey]]] = []
        l7_ids: List[int] = []
        for p in packets:
            remote_id = self._remote_identity(p)
            status, hit_key = self.ct.probe(p, now)
            probes.append((status, hit_key))
            if status != C.CTStatus.NEW:
                l7_ids.append(CT_NO_L7)
                entry = self.ct.entries[hit_key]
                if entry.redirect_l7_id != CT_NO_L7 and p.has_l7_tokens:
                    rules = self.l7_sets[entry.redirect_l7_id - 1]
                    if not l7_match(rules, p.http_method, p.http_path):
                        verdicts.append(Verdict(False, C.DropReason.POLICY_L7,
                                                status, remote_id, redirect=True))
                        continue
                verdicts.append(Verdict(True, C.DropReason.OK, status, remote_id,
                                        redirect=entry.redirect_l7_id != CT_NO_L7))
            else:
                allow, reason, redirect, l7_id, key = self._policy_verdict(
                    p, remote_id)
                l7_ids.append(l7_id)
                verdicts.append(Verdict(allow, reason, C.CTStatus.NEW, remote_id,
                                        redirect=redirect, matched_key=key))

        # Phase 2: order-independent aggregate CT effects.
        #   For each touched key: flags |= OR of deltas; counters += sums;
        #   expiry recomputed once from aggregated flags.
        agg: Dict[CTKey, Dict] = {}

        def touch(key: CTKey):
            return agg.setdefault(key, {
                "flag_delta": 0, "fwd": 0, "rev": 0,
                "create": None, "l7_id": CT_NO_L7,
            })

        for p, v, (status, hit_key), l7_id in zip(packets, verdicts, probes,
                                                  l7_ids):
            if status == C.CTStatus.ESTABLISHED and v.allow:
                a = touch(hit_key)
                a["flag_delta"] |= _flag_delta(p.proto, p.tcp_flags, False)
                a["fwd"] += 1
            elif status == C.CTStatus.REPLY and v.allow:
                a = touch(hit_key)
                a["flag_delta"] |= _flag_delta(p.proto, p.tcp_flags, True)
                a["rev"] += 1
            elif status == C.CTStatus.NEW and v.allow:
                key = ConntrackTable.fwd_key(p)
                a = touch(key)
                a["flag_delta"] |= _flag_delta(p.proto, p.tcp_flags, False)
                a["fwd"] += 1
                if a["create"] is None:
                    # l7 id of the *winning* (first) creator
                    a["create"] = p
                    a["l7_id"] = l7_id

        for key, a in agg.items():
            entry = self.ct.entries.get(key)
            if entry is not None and entry.expiry <= now and a["create"] is not None:
                entry = None  # expired slot is replaced, not updated
            if entry is None:
                if a["create"] is None:
                    continue
                entry = CTEntry(expiry=0, created=now,
                                redirect_l7_id=a["l7_id"])
                self.ct.entries[key] = entry
            proto = key[4]
            entry.flags |= a["flag_delta"]
            entry.pkts_fwd += a["fwd"]
            entry.pkts_rev += a["rev"]
            entry.expiry = _entry_expiry(proto, entry.flags, now)
        return verdicts
