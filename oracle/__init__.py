"""The eBPF-semantics oracle: slow, exact, pure-Python reference model of the
datapath verdict function. Per SURVEY.md §0 verification protocol step 2, the
reference mount was empty, so THIS MODEL IS THE PARITY CONTRACT — the TPU
kernels must agree with it bit-for-bit, and every report must say so.
"""

from oracle.datapath import (
    ConntrackTable, CTEntry, Oracle, PacketRecord, Verdict,
)

__all__ = ["ConntrackTable", "CTEntry", "Oracle", "PacketRecord", "Verdict"]
