"""SelectorCache: selector → live identity set, updated incrementally
(analog of upstream ``pkg/policy`` SelectorCache — SURVEY.md §2: "identity↔
selector incremental index").

Selectors are any object with ``matches(labels) -> bool`` (EndpointSelector,
the special ClusterSelector, …). The cache subscribes to the
IdentityAllocator; each registered selector keeps a materialized set of
matching identity ids, so MapState computation is a set read, not a scan.
Users subscribe per-selector to drive incremental endpoint regeneration.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

from cilium_tpu.model.identity import Identity, IdentityAllocator
from cilium_tpu.model.labels import Labels, SOURCE_K8S
from cilium_tpu.model.selectors import EndpointSelector, MatchExpression
from cilium_tpu.utils import constants as C


@dataclass(frozen=True)
class ClusterSelector:
    """Matches the 'cluster' entity: any cluster-managed workload identity
    (has a k8s-source label) or cluster-infrastructure reserved identity.
    Not expressible as a single label selector, hence its own type."""

    _RESERVED = ("host", "remote-node", "health", "init", "ingress",
                 "kube-apiserver", "unmanaged")

    def matches(self, labels: Labels) -> bool:
        if any(l.source == SOURCE_K8S for l in labels):
            return True
        return any(labels.has("reserved", name) for name in self._RESERVED)

    def __str__(self) -> str:
        return "entity:cluster"


_ENTITY_SELECTOR_TABLE = {
    "all": (EndpointSelector(),),
    "world": (EndpointSelector.from_labels({"reserved:world": ""}),),
    "host": (EndpointSelector.from_labels({"reserved:host": ""}),),
    "remote-node": (EndpointSelector.from_labels({"reserved:remote-node": ""}),),
    "health": (EndpointSelector.from_labels({"reserved:health": ""}),),
    "init": (EndpointSelector.from_labels({"reserved:init": ""}),),
    "unmanaged": (EndpointSelector.from_labels({"reserved:unmanaged": ""}),),
    "kube-apiserver": (EndpointSelector.from_labels({"reserved:kube-apiserver": ""}),),
    "ingress": (EndpointSelector.from_labels({"reserved:ingress": ""}),),
    "cluster": (ClusterSelector(),),
}


def entity_selectors(name: str):
    """Expand an entity name into selector objects."""
    try:
        return _ENTITY_SELECTOR_TABLE[name]
    except KeyError:
        raise ValueError(f"unknown entity {name!r}")


def cidr_selector(cidr: str, excepts: Tuple[str, ...] = ()) -> EndpointSelector:
    """Selector matching all CIDR identities at-or-below ``cidr`` while
    excluding those at-or-below any except prefix (works because CIDR
    identities carry labels for every parent prefix)."""
    return EndpointSelector(
        match_labels=((f"cidr:{cidr}", ""),),
        match_expressions=tuple(
            MatchExpression(key=f"cidr:{e}", operator="DoesNotExist")
            for e in excepts),
    )


class CachedSelector:
    """A registered selector + its materialized identity set."""

    def __init__(self, selector, cache: "SelectorCache"):
        self.selector = selector
        self._cache = cache
        self._ids: Set[int] = set()
        self._subscribers: List[Callable[[Set[int], Set[int]], None]] = []
        self.refcount = 0

    @property
    def identities(self) -> frozenset:
        return frozenset(self._ids)

    def subscribe(self, fn: Callable[[Set[int], Set[int]], None]) -> None:
        """fn(added_ids, removed_ids) fires on incremental identity changes."""
        self._subscribers.append(fn)

    def _apply(self, added: Set[int], removed: Set[int]) -> None:
        self._ids |= added
        self._ids -= removed
        for fn in list(self._subscribers):
            fn(added, removed)


class SelectorCache:
    def __init__(self, allocator: IdentityAllocator):
        self._lock = threading.RLock()
        self._allocator = allocator
        self._selectors: Dict[str, CachedSelector] = {}
        # Observe identities; replay=True seeds current identities.
        allocator.add_observer(self._on_identities, replay=False)

    def _key(self, selector) -> str:
        return f"{type(selector).__name__}:{selector}"

    def add_selector(self, selector) -> CachedSelector:
        """Register (or ref) a selector; materializes its identity set."""
        with self._lock:
            key = self._key(selector)
            cached = self._selectors.get(key)
            if cached is None:
                cached = CachedSelector(selector, self)
                matched = {
                    ident.id for ident in self._allocator.all()
                    if selector.matches(ident.labels)
                }
                cached._apply(matched, set())
                self._selectors[key] = cached
            cached.refcount += 1
            return cached

    def remove_selector(self, cached: CachedSelector) -> None:
        with self._lock:
            cached.refcount -= 1
            if cached.refcount <= 0:
                self._selectors.pop(self._key(cached.selector), None)

    def _on_identities(self, added: List[Identity], removed: List[Identity]) -> None:
        with self._lock:
            for cached in self._selectors.values():
                add_ids = {i.id for i in added if cached.selector.matches(i.labels)}
                rem_ids = {i.id for i in removed if i.id in cached._ids}
                if add_ids or rem_ids:
                    cached._apply(add_ids, rem_ids)

    def __len__(self) -> int:
        return len(self._selectors)
