"""Policy engine: Repository + SelectorCache + MapState (analog of upstream
``pkg/policy``). Its output — per-endpoint, per-direction MapState — is the
input of the tensor compiler (``cilium_tpu/compile``), exactly as upstream's
MapState is the desired contents of the per-endpoint policymap.
"""

from cilium_tpu.policy.selectorcache import SelectorCache, entity_selectors
from cilium_tpu.policy.mapstate import MapState, MapStateEntry, MapStateKey
from cilium_tpu.policy.repository import (
    Repository, EndpointPolicy, DirectionPolicy, PolicyContext,
)

__all__ = [
    "SelectorCache", "entity_selectors",
    "MapState", "MapStateEntry", "MapStateKey",
    "Repository", "EndpointPolicy", "DirectionPolicy", "PolicyContext",
]
