"""Policy Repository + per-endpoint resolution (analog of upstream
``pkg/policy`` Repository.ResolvePolicy → EndpointPolicy, SURVEY.md §3.2).

Resource model (mirrors upstream): selectors and CIDR identities are owned by
**rules at insertion time** — ``add``/``replace_by_labels`` materialize each
rule's peer selectors into the SelectorCache, allocate local CIDR identities,
and upsert the ipcache (§3.2: "ipcache/identity: CIDR rules allocate local
CIDR identities"); removing a rule releases them (identities are refcounted,
the ipcache entry is deleted when the last reference drops). ``resolve`` is
then a pure read: it never allocates, so it is order-independent and
leak-free. ``toServices`` backends are re-materialized whenever the service
registry changes (the k8s-service-watcher analog), bumping the revision so
endpoints regenerate.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

from cilium_tpu.model.endpoint import Endpoint
from cilium_tpu.model.fqdn import FQDNCache
from cilium_tpu.model.identity import Identity, IdentityAllocator
from cilium_tpu.model.ipcache import IPCache
from cilium_tpu.model.labels import Labels
from cilium_tpu.model.rules import Rule, RuleBlock
from cilium_tpu.model.services import ServiceRegistry
from cilium_tpu.policy.mapstate import (
    MapState, MapStateEntry, MapStateKey, PORT_WILDCARD,
)
from cilium_tpu.policy.selectorcache import (
    CachedSelector, SelectorCache, cidr_selector, entity_selectors,
)
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import normalize_prefix


@dataclass
class PolicyContext:
    """Everything the repository needs besides the rules."""
    allocator: IdentityAllocator
    selector_cache: SelectorCache
    ipcache: IPCache
    services: ServiceRegistry = field(default_factory=ServiceRegistry)
    fqdn_cache: FQDNCache = field(default_factory=FQDNCache)
    enforcement_mode: str = C.ENFORCEMENT_DEFAULT
    allow_localhost: bool = True


@dataclass
class DirectionPolicy:
    enforced: bool
    mapstate: MapState

    def lookup(self, remote_id: int, proto: int, dport: int):
        return self.mapstate.lookup(remote_id, proto, dport)


@dataclass
class EndpointPolicy:
    ep_id: int
    identity_id: int
    revision: int
    egress: DirectionPolicy
    ingress: DirectionPolicy

    def direction(self, d: int) -> DirectionPolicy:
        return self.egress if d == C.DIR_EGRESS else self.ingress


@dataclass
class _BlockResources:
    """Materialized peer side of one rule block."""
    wildcard: bool
    selectors: List[CachedSelector]


@dataclass
class _RuleResources:
    """Everything a rule owns while resident in the repository."""
    blocks: Dict[int, _BlockResources] = field(default_factory=dict)  # id(block)→res
    allocations: List[Tuple[Identity, str]] = field(default_factory=list)
    has_services: bool = False
    has_fqdns: bool = False


@dataclass(frozen=True)
class RuleChange:
    """One changelog record (consumed by the incremental tensor updater).
    ``kind``: 'add' | 'remove' | 'refresh' (same rule, re-materialized
    resources — the toServices/toFQDNs watcher path)."""
    revision: int
    kind: str
    rule: Rule


CHANGELOG_MAX = 4096         # history window; overflow → full-rebuild fallback


class Repository:
    """Rule store with revisioning, resource ownership, and notification."""

    def __init__(self, ctx: PolicyContext):
        self._lock = threading.RLock()
        self._ctx = ctx
        self._rules: List[Rule] = []
        self._resources: Dict[int, _RuleResources] = {}  # id(rule) → resources
        self._revision = 1
        self._observers: List[Callable[[int], None]] = []
        self._changes: Deque[RuleChange] = deque()
        self._changes_dropped = False    # a record fell off the window
        # fqdn refresh coalescing (ISSUE 18): the cache observer fires
        # per mutation; a DNS storm's N observes must fold into ONE
        # re-materialization per regen cycle, not N. The observer only
        # marks pending; flush_fqdn_refresh() (regen entry points) runs
        # the single _refresh_rules. Counters are monotone — the engine
        # delta-folds them into fqdn_* metric families.
        self._fqdn_refresh_pending = False
        self.fqdn_refresh_coalesced = 0
        self.fqdn_identities_created = 0
        self._fqdn_ident_ids: set = set()   # ids ever created for FQDN IPs
        ctx.services.add_observer(self._on_services_changed)
        ctx.fqdn_cache.add_observer(self._on_fqdns_changed)

    # -- rule management ----------------------------------------------------
    @property
    def revision(self) -> int:
        return self._revision

    def add_observer(self, obs: Callable[[int], None]) -> None:
        """obs(new_revision) fires after any rule change (regen trigger)."""
        self._observers.append(obs)

    def _bump(self) -> int:
        self._revision += 1
        rev = self._revision
        for obs in list(self._observers):
            obs(rev)
        return rev

    def bump_revision(self) -> int:
        """Advance the revision with NO rule change and NO observer
        notification — the re-mesh fence (ISSUE 19). A mesh geometry
        change invalidates every revision-stamped steering pre-bin (the
        feeder's ``_shard`` column hashes mod the OLD flow-axis width);
        bumping here makes the stale stamps visibly stale. The caller owns
        the forced recompile — going through the observers would only
        debounce it behind the regen trigger."""
        with self._lock:
            self._revision += 1
            return self._revision

    def _record(self, kind: str, rule: Rule) -> None:
        """Changelog append (pre-bump: records carry the revision the change
        will land in)."""
        self._changes.append(RuleChange(self._revision + 1, kind, rule))
        while len(self._changes) > CHANGELOG_MAX:
            self._changes.popleft()
            self._changes_dropped = True

    def changes_since(self, revision: int) -> Optional[List[RuleChange]]:
        """Changelog records with revision > ``revision``, or None when the
        window no longer reaches back that far (caller must full-rebuild).
        Consuming does not clear; call prune_changes(revision) after a
        successful snapshot at that revision."""
        with self._lock:
            if self._changes_dropped:
                return None
            return [c for c in self._changes if c.revision > revision]

    def prune_changes(self, revision: int) -> None:
        """Drop records at or before ``revision`` (already reflected in a
        compiled snapshot); resets the overflow marker once drained."""
        with self._lock:
            while self._changes and self._changes[0].revision <= revision:
                self._changes.popleft()
            if not self._changes:
                self._changes_dropped = False

    def add(self, rules: Sequence[Rule]) -> int:
        with self._lock:
            for rule in rules:
                self._record("add", rule)
                self._rules.append(rule)
                self._resources[id(rule)] = self._materialize(rule)
            return self._bump()

    def replace_by_labels(self, match: Labels, rules: Sequence[Rule]) -> int:
        """Replace all rules carrying every label in ``match`` (the CNP
        update path — upstream ReplaceByLabels). An empty match set would
        silently delete every rule, so it is rejected; use ``clear()``."""
        with self._lock:
            want = set(match.to_strings())
            if not want:
                raise ValueError(
                    "replace_by_labels with empty labels would match every "
                    "rule; use clear() to drop all rules")
            kept: List[Rule] = []
            for r in self._rules:
                if want.issubset(set(r.labels.to_strings())):
                    self._record("remove", r)
                    self._release(self._resources.pop(id(r)))
                else:
                    kept.append(r)
            self._rules = kept
            for rule in rules:
                self._record("add", rule)
                self._rules.append(rule)
                self._resources[id(rule)] = self._materialize(rule)
            return self._bump()

    def delete_by_labels(self, match: Labels) -> int:
        return self.replace_by_labels(match, [])

    def clear(self) -> int:
        """Remove every rule (releasing owned resources)."""
        with self._lock:
            for rule in self._rules:
                self._record("remove", rule)
                self._release(self._resources.pop(id(rule)))
            self._rules = []
            return self._bump()

    def all_rules(self) -> List[Rule]:
        with self._lock:
            return list(self._rules)

    def __len__(self) -> int:
        return len(self._rules)

    # -- resource materialization -------------------------------------------
    def rules_selecting_identities(self, ident_ids) -> List[Rule]:
        """Resident rules whose peer-side selectors currently resolve any of
        ``ident_ids`` — the cheap prefilter for incremental identity growth
        (compile/incremental, ISSUE 12): one set intersection per cached
        selector, no rule expansion. Wildcard blocks are excluded: their
        contribution key is ``IDENTITY_ANY``, which identity growth cannot
        change."""
        wanted = set(ident_ids)
        with self._lock:
            out: List[Rule] = []
            for rule in self._rules:
                res = self._resources.get(id(rule))
                if res is None:
                    continue
                for block_res in res.blocks.values():
                    if any(cached.identities & wanted
                           for cached in block_res.selectors):
                        out.append(rule)
                        break
            return out

    def _materialize(self, rule: Rule) -> _RuleResources:
        res = _RuleResources()
        for block in (rule.ingress + rule.ingress_deny
                      + rule.egress + rule.egress_deny):
            res.blocks[id(block)] = self._materialize_block(block, res)
        return res

    def _materialize_block(self, block: RuleBlock,
                           res: _RuleResources) -> _BlockResources:
        ctx = self._ctx
        peer = block.peer
        wildcard = peer.is_empty
        selector_objs: List[object] = []
        for sel in peer.endpoints:
            if sel.is_wildcard:
                # an explicit empty fromEndpoints selector ({}) matches all
                # endpoints; upstream compiles it to the ANY key as well
                wildcard = True
            else:
                selector_objs.append(sel)
        for ent in peer.entities:
            for sel in entity_selectors(ent):
                if getattr(sel, "is_wildcard", False):
                    wildcard = True
                else:
                    selector_objs.append(sel)
        for cs in peer.cidrs:
            # Materialize CIDR identities + ipcache entries for the prefix and
            # its excepts (excepts get identities so LPM resolves them to
            # something the selector does NOT match).
            for prefix in (cs.cidr, *cs.excepts):
                ident = ctx.allocator.allocate_cidr(prefix)
                ctx.ipcache.upsert(prefix, ident.id)
                res.allocations.append((ident, prefix))
            selector_objs.append(cidr_selector(cs.cidr, cs.excepts))
        for svc_sel in peer.services:
            res.has_services = True
            # toServices: resolve matching services to their backend IPs and
            # treat each backend as a host-prefix CIDR peer (upstream resolves
            # ToServices through the service cache into selector identities).
            for svc in ctx.services.match(svc_sel):
                for backend_ip in svc.backend_ips:
                    prefix = normalize_prefix(
                        f"{backend_ip}/128" if ":" in backend_ip
                        else f"{backend_ip}/32")
                    ident = ctx.allocator.allocate_cidr(prefix)
                    ctx.ipcache.upsert(prefix, ident.id)
                    res.allocations.append((ident, prefix))
                    selector_objs.append(cidr_selector(prefix))
        for fq in peer.fqdns:
            res.has_fqdns = True
            # toFQDNs: every IP the DNS cache has learned for a matching
            # name becomes a host-prefix CIDR peer (upstream: pkg/fqdn
            # NameManager → ipcache CIDR identities). Names learned later
            # re-materialize via the cache observer.
            for ip in ctx.fqdn_cache.lookup_selector(fq):
                prefix = normalize_prefix(
                    f"{ip}/128" if ":" in ip else f"{ip}/32")
                ident = ctx.allocator.allocate_cidr(prefix)
                ctx.ipcache.upsert(prefix, ident.id)
                res.allocations.append((ident, prefix))
                selector_objs.append(cidr_selector(prefix))
                # learning accounting: allocator ids are never reused,
                # so first-sight of an id == one FQDN-learned identity
                if ident.id not in self._fqdn_ident_ids:
                    self._fqdn_ident_ids.add(ident.id)
                    self.fqdn_identities_created += 1
        cached = [ctx.selector_cache.add_selector(s) for s in selector_objs]
        return _BlockResources(wildcard=wildcard, selectors=cached)

    def _release(self, res: _RuleResources) -> None:
        ctx = self._ctx
        for block_res in res.blocks.values():
            for cached in block_res.selectors:
                ctx.selector_cache.remove_selector(cached)
        for ident, prefix in res.allocations:
            if ctx.allocator.release(ident):
                ctx.ipcache.delete(prefix)

    def _refresh_rules(self, predicate) -> None:
        """Re-materialize resources of rules matching ``predicate``.
        Materialize-before-release: a resource present in both the old and
        new materialization (e.g. a DNS TTL tick re-learning the same IPs)
        keeps its refcount above zero throughout, so its identity and
        ipcache entry survive — a no-op refresh leaves the ipcache/identity
        state (and therefore the LPM geometry) completely untouched."""
        with self._lock:
            changed = False
            for rule in self._rules:
                res = self._resources.get(id(rule))
                if res is None or not predicate(res):
                    continue
                self._record("refresh", rule)
                new_res = self._materialize(rule)
                self._release(res)
                self._resources[id(rule)] = new_res
                changed = True
            if changed:
                self._bump()

    def _on_services_changed(self) -> None:
        """Service registry changed: re-materialize rules with toServices
        (the k8s service-watcher → policy-recompute path)."""
        self._refresh_rules(lambda res: res.has_services)

    def _on_fqdns_changed(self) -> None:
        """DNS cache changed: mark toFQDNs rules for re-materialization.

        Deliberately does NOT refresh inline (the pre-ISSUE-18 behavior):
        one storm burst of N observes would run N full re-materializations.
        Instead the refresh is debounced to one per regen cycle — the mark
        still wakes the engine's regen trigger (observers fire at the
        CURRENT revision; the real bump happens in the flushed refresh),
        and every observe after the first while a refresh is pending
        counts as coalesced."""
        with self._lock:
            if self._fqdn_refresh_pending:
                self.fqdn_refresh_coalesced += 1
                return
            self._fqdn_refresh_pending = True
            rev = self._revision
        for obs in list(self._observers):
            obs(rev)

    def flush_fqdn_refresh(self) -> bool:
        """Run the deferred toFQDNs re-materialization, if one is pending.
        Idempotent and cheap when clean; called at every regen entry point
        (engine regeneration, :meth:`resolve`, :meth:`changes_since`) so
        readers never see a stale materialization."""
        with self._lock:
            if not self._fqdn_refresh_pending:
                return False
            self._fqdn_refresh_pending = False
        self._refresh_rules(lambda res: res.has_fqdns)
        return True

    # -- resolution (pure read) ---------------------------------------------
    def resolve(self, endpoint: Endpoint) -> EndpointPolicy:
        """Compute the endpoint's EndpointPolicy at the current revision.
        Allocation-free: all resources were materialized at rule insert
        (a pending coalesced toFQDNs refresh is flushed first, so a
        resolve never reads a stale materialization)."""
        self.flush_fqdn_refresh()
        with self._lock:
            rules = [r for r in self._rules if r.selects(endpoint.labels)]
            revision = self._revision

            mode = endpoint.enforcement or self._ctx.enforcement_mode
            if mode == C.ENFORCEMENT_ALWAYS:
                enforce_in = enforce_eg = True
            elif mode == C.ENFORCEMENT_NEVER:
                enforce_in = enforce_eg = False
            else:
                enforce_in = any(r.enforces_ingress for r in rules)
                enforce_eg = any(r.enforces_egress for r in rules)

            ingress = MapState()
            egress = MapState()
            for rule in rules:
                for direction, key, entry in self._rule_contributions(rule):
                    (egress if direction == C.DIR_EGRESS else ingress).add(
                        key, entry)

            # Host bypass: traffic from the local host to endpoints is always
            # allowed unless host-firewall semantics are requested (upstream:
            # LocalHostAllowed / option.Config.AlwaysAllowLocalhost).
            if self._ctx.allow_localhost and enforce_in:
                ingress.add(
                    MapStateKey(C.IDENTITY_HOST, C.PROTO_ANY, *PORT_WILDCARD),
                    MapStateEntry(deny=False, derived_from=("allow-localhost",)),
                )

            return EndpointPolicy(
                ep_id=endpoint.ep_id,
                identity_id=endpoint.identity_id,
                revision=revision,
                egress=DirectionPolicy(enforce_eg, egress),
                ingress=DirectionPolicy(enforce_in, ingress),
            )

    def _rule_contributions(self, rule: Rule
                            ) -> List[Tuple[int, MapStateKey, MapStateEntry]]:
        """All (direction, key, entry) contributions of one resident rule —
        raw, pre-merge. ``resolve`` folds them into MapStates; the
        incremental updater diffs them per rule (SURVEY.md §7 step 3)."""
        res = self._resources[id(rule)]
        tag = (rule.description or ",".join(rule.labels.to_strings())
               or "<unlabeled>")
        out: List[Tuple[int, MapStateKey, MapStateEntry]] = []
        for direction, blocks, deny in (
                (C.DIR_INGRESS, rule.ingress, False),
                (C.DIR_INGRESS, rule.ingress_deny, True),
                (C.DIR_EGRESS, rule.egress, False),
                (C.DIR_EGRESS, rule.egress_deny, True)):
            for block in blocks:
                self._expand(
                    lambda k, e, d=direction: out.append((d, k, e)),
                    block, res, deny=deny, tag=tag)
        return out

    def expand_rule_for(self, rule: Rule, endpoint: Endpoint
                        ) -> List[Tuple[int, MapStateKey, MapStateEntry]]:
        """Contributions ``rule`` makes to ``endpoint``'s policy — empty if
        the rule does not select it. Pure read (resources must already be
        materialized, i.e. the rule is resident)."""
        with self._lock:
            if id(rule) not in self._resources \
                    or not rule.selects(endpoint.labels):
                return []
            return self._rule_contributions(rule)

    def _expand(self, sink, block: RuleBlock, res: _RuleResources,
                deny: bool, tag: str) -> None:
        """Expand one rule block, emitting raw (key, entry) contributions
        through ``sink(key, entry)``."""
        block_res = res.blocks[id(block)]

        # Port side → list of (proto, lo, hi, l7_rules).
        port_specs: List[Tuple[int, int, int, Optional[frozenset]]] = []
        for pr in block.to_ports:
            l7 = frozenset(pr.http) if pr.http else None
            if not pr.ports:
                port_specs.append((C.PROTO_ANY, *PORT_WILDCARD, l7))
            for pp in pr.ports:
                lo, hi = pp.port_range
                for proto in pp.protocols():
                    port_specs.append((proto, lo, hi, l7))
        for icmp in block.icmps:
            proto = C.PROTO_ICMP if icmp.family == "IPv4" else C.PROTO_ICMP6
            port_specs.append((proto, icmp.icmp_type, icmp.icmp_type, None))
        if not port_specs:
            port_specs.append((C.PROTO_ANY, *PORT_WILDCARD, None))

        def emit(identity: int):
            for proto, lo, hi, l7 in port_specs:
                if proto == C.PROTO_ANY:
                    key = MapStateKey(identity, C.PROTO_ANY, *PORT_WILDCARD)
                else:
                    key = MapStateKey(identity, proto, lo, hi)
                sink(key, MapStateEntry(deny=deny,
                                        l7_rules=None if deny else l7,
                                        derived_from=(tag,)))

        if block_res.wildcard:
            emit(C.IDENTITY_ANY)
        for cached in block_res.selectors:
            for ident_id in sorted(cached.identities):
                emit(ident_id)
