"""MapState: the desired per-endpoint, per-direction verdict state
(analog of upstream ``pkg/policy`` MapState / ``pkg/maps/policymap`` contents
— SURVEY.md §2 calls this "the central artifact").

A MapState is a set of entries keyed ``(identity, proto, port_lo, port_hi)``
with identity 0 = wildcard-ANY and proto 0 = any protocol. Values carry
allow/deny (+ optional L7-lite http rule set). Two consumers:

- the **oracle** (and `policy trace` CLI) evaluate the sparse entries with the
  precedence ladder in :func:`MapState.lookup` — the semantic contract;
- the **tensor compiler** resolves that same ladder *at compile time* into a
  dense ``verdict[id_class, port_class]`` tensor, so the device does gathers,
  not ladder walks. Parity between the two paths is test-enforced.

Precedence contract (matching upstream's documented semantics):
1. any matching DENY entry denies, regardless of specificity;
2. otherwise the most specific matching ALLOW wins (identity-specific over
   wildcard, proto/port-specific over wild, narrower port range over wider);
3. no match → default deny when the direction is enforced, else allow.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, FrozenSet, Iterable, List, Optional, Tuple

from cilium_tpu.model.rules import HTTPRule
from cilium_tpu.utils import constants as C

PORT_WILDCARD: Tuple[int, int] = (0, 65535)


@dataclass(frozen=True, order=True)
class MapStateKey:
    identity: int          # remote security identity; 0 == ANY
    proto: int             # 0 == any protocol
    port_lo: int = 0       # inclusive; for ICMP this is the ICMP type
    port_hi: int = 65535   # inclusive

    def __post_init__(self):
        if self.proto == C.PROTO_ANY and (self.port_lo, self.port_hi) != PORT_WILDCARD:
            raise ValueError("proto-wildcard entries must have wildcard ports")
        if not (0 <= self.port_lo <= self.port_hi <= 65535):
            raise ValueError(f"bad port range {self.port_lo}-{self.port_hi}")

    @property
    def is_port_wild(self) -> bool:
        return (self.port_lo, self.port_hi) == PORT_WILDCARD

    def covers(self, remote_id: int, proto: int, dport: int) -> bool:
        if self.identity != C.IDENTITY_ANY and self.identity != remote_id:
            return False
        if self.proto != C.PROTO_ANY and self.proto != proto:
            return False
        return self.port_lo <= dport <= self.port_hi

    def specificity(self) -> int:
        """Ladder rank: id-specific(4) + proto-specific(2) + port-specific(1)."""
        return ((self.identity != C.IDENTITY_ANY) * 4
                + (self.proto != C.PROTO_ANY) * 2
                + (not self.is_port_wild) * 1)


@dataclass(frozen=True)
class MapStateEntry:
    deny: bool = False
    # None → plain L4 allow; frozenset of HTTPRule → L7-lite redirect.
    l7_rules: Optional[FrozenSet[HTTPRule]] = None
    derived_from: Tuple[str, ...] = ()

    @property
    def is_redirect(self) -> bool:
        return not self.deny and self.l7_rules is not None


def _merge(old: MapStateEntry, new: MapStateEntry) -> MapStateEntry:
    """Merge two contributions to the same key.

    deny wins; else a plain allow shadows an L7 allow (the wider permission);
    else union the L7 rule sets.
    """
    if old.deny or new.deny:
        winner = old if old.deny else new
        other = new if old.deny else old
        return replace(winner, derived_from=winner.derived_from + other.derived_from)
    derived = old.derived_from + new.derived_from
    if old.l7_rules is None or new.l7_rules is None:
        return MapStateEntry(deny=False, l7_rules=None, derived_from=derived)
    return MapStateEntry(deny=False, l7_rules=old.l7_rules | new.l7_rules,
                         derived_from=derived)


@dataclass(frozen=True)
class LookupResult:
    decision: int                      # C.VERDICT_{MISS,ALLOW,DENY,REDIRECT}
    key: Optional[MapStateKey] = None
    entry: Optional[MapStateEntry] = None


class _OverlayEntries:
    """Copy-on-write entry store: an immutable shared ``base`` dict plus a
    private ``over``-ride dict and ``dead`` tombstone set. The incremental
    compiler's per-cycle plane copy was a full dict copy per touched plane
    (the ~1.3ms 50k-entry copy that dominated warm-geometry rule adds);
    an overlay copy is O(dirty keys) — the base is shared by every
    emitted snapshot and NEVER mutated, which is what keeps previously
    emitted (frozen) snapshots immutable without paying O(entries) per
    cycle.

    Invariants: ``over`` and ``dead`` are disjoint; ``dead ⊆ base``;
    ``_n`` is the live count. Depth never exceeds one — a copy of an
    overlay shares the same flat base (copying the dirty sets), and
    :meth:`MapState.overlay_copy` folds to a flat dict once the dirty set
    outgrows its budget (amortized O(1) full copies over a churn run)."""

    __slots__ = ("base", "over", "dead", "_n")

    def __init__(self, base: Dict[MapStateKey, MapStateEntry],
                 over: Optional[Dict[MapStateKey, MapStateEntry]] = None,
                 dead: Optional[set] = None):
        self.base = base
        self.over = over if over is not None else {}
        self.dead = dead if dead is not None else set()
        self._n = (len(base) - len(self.dead)
                   + sum(1 for k in self.over if k not in base))

    def dirty(self) -> int:
        return len(self.over) + len(self.dead)

    def flatten(self) -> Dict[MapStateKey, MapStateEntry]:
        d = {k: v for k, v in self.base.items() if k not in self.dead}
        d.update(self.over)
        return d

    # -- mapping protocol (the subset MapState uses) --------------------------
    def get(self, key, default=None):
        v = self.over.get(key)
        if v is not None:
            return v
        if key in self.dead:
            return default
        return self.base.get(key, default)

    def __contains__(self, key) -> bool:
        return key in self.over or (key not in self.dead
                                    and key in self.base)

    def __len__(self) -> int:
        return self._n

    def __setitem__(self, key, value) -> None:
        if key not in self.over:
            if key in self.dead:
                self.dead.discard(key)
                self._n += 1
            elif key not in self.base:
                self._n += 1
        self.over[key] = value

    def pop(self, key, default=None):
        v = self.over.pop(key, None)
        if v is not None:
            self._n -= 1
            if key in self.base:
                self.dead.add(key)      # still shadow the base entry
            return v
        if key in self.dead or key not in self.base:
            return default
        self.dead.add(key)
        self._n -= 1
        return self.base[key]

    def items(self):
        for k, v in self.base.items():
            if k not in self.dead and k not in self.over:
                yield k, v
        yield from self.over.items()


#: process-wide overlay occupancy accounting (ISSUE 13): MapState overlays
#: are per-(endpoint, direction) and transient, so the resource ledger
#: samples this module-level aggregate instead of chasing instances —
#: last/max dirty-key counts seen at overlay_copy time and how often the
#: fold budget actually forced an O(entries) flatten.
_OVERLAY_STATS = {"last_dirty": 0, "max_dirty": 0, "folds": 0, "copies": 0}


def overlay_stats() -> Dict[str, int]:
    """The mapstate-overlay ledger sample: (dirty keys vs fold budget)."""
    return {"fold_budget": MapState.OVERLAY_FOLD_KEYS, **_OVERLAY_STATS}


class MapState:
    """Mutable builder + queryable container of MapState entries."""

    #: overlay fold budget: a copy whose dirty set exceeds this flattens
    #: into a fresh base dict (one O(entries) copy, amortized over the
    #: delta cycles since the last fold)
    OVERLAY_FOLD_KEYS = 4096

    def __init__(self):
        self._entries: Dict[MapStateKey, MapStateEntry] = {}

    def overlay_copy(self, fold_budget: Optional[int] = None) -> "MapState":
        """O(dirty-keys) copy-on-write clone: the clone shares this
        mapstate's (flat) base read-only and takes private copies of the
        dirty sets, so mutating the clone never disturbs this instance —
        the incremental compiler's per-cycle plane copy. Folds back to a
        flat dict when the dirty set outgrows ``fold_budget``."""
        budget = self.OVERLAY_FOLD_KEYS if fold_budget is None \
            else fold_budget
        ms = MapState()
        e = self._entries
        _OVERLAY_STATS["copies"] += 1
        if isinstance(e, _OverlayEntries):
            dirty = e.dirty()
            _OVERLAY_STATS["last_dirty"] = dirty
            _OVERLAY_STATS["max_dirty"] = max(_OVERLAY_STATS["max_dirty"],
                                              dirty)
            if dirty > budget:
                _OVERLAY_STATS["folds"] += 1
                ms._entries = _OverlayEntries(e.flatten())
            else:
                ms._entries = _OverlayEntries(e.base, dict(e.over),
                                              set(e.dead))
        else:
            _OVERLAY_STATS["last_dirty"] = 0
            ms._entries = _OverlayEntries(e)
        return ms

    # -- build --------------------------------------------------------------
    def add(self, key: MapStateKey, entry: MapStateEntry) -> None:
        old = self._entries.get(key)
        self._entries[key] = _merge(old, entry) if old is not None else entry

    def set_entry(self, key: MapStateKey, entry: MapStateEntry) -> None:
        """Replace (no merge) — the incremental updater writes pre-merged
        entries (merge_contributions) directly."""
        self._entries[key] = entry

    def delete_entry(self, key: MapStateKey) -> bool:
        return self._entries.pop(key, None) is not None

    # -- query --------------------------------------------------------------
    def lookup(self, remote_id: int, proto: int, dport: int) -> LookupResult:
        """The precedence ladder (see module docstring). Deterministic."""
        best_key: Optional[MapStateKey] = None
        best_entry: Optional[MapStateEntry] = None
        deny_key: Optional[MapStateKey] = None
        deny_entry: Optional[MapStateEntry] = None
        for key, entry in self._entries.items():
            if not key.covers(remote_id, proto, dport):
                continue
            if entry.deny:
                if deny_key is None or _rank(key) > _rank(deny_key):
                    deny_key, deny_entry = key, entry
                continue
            if best_key is None or _rank(key) > _rank(best_key):
                best_key, best_entry = key, entry
        if deny_entry is not None:
            return LookupResult(C.VERDICT_DENY, deny_key, deny_entry)
        if best_entry is None:
            return LookupResult(C.VERDICT_MISS)
        decision = C.VERDICT_REDIRECT if best_entry.is_redirect else C.VERDICT_ALLOW
        return LookupResult(decision, best_key, best_entry)

    def items(self) -> List[Tuple[MapStateKey, MapStateEntry]]:
        return sorted(self._entries.items(), key=lambda kv: kv[0])

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: MapStateKey) -> bool:
        return key in self._entries

    def get(self, key: MapStateKey) -> Optional[MapStateEntry]:
        return self._entries.get(key)


def _rank(key: MapStateKey) -> Tuple[int, int, int, int, int]:
    """Total order for 'most specific wins': higher tuple = more specific.

    Ties beyond specificity: narrower port range, then higher port_lo, then
    identity/proto. The (specificity, -width, port_lo) prefix is *provably
    total for candidates covering the same packet*: two same-cell candidates
    with equal specificity share identity (both exact-id on that identity, or
    both ANY) and proto (proto families don't overlap), so equal width + equal
    port_lo forces equal port_hi, i.e. the same key — already merged. This
    lets the tensor compiler encode the rank as one scalar
    (spec << 33 | (65535-width) << 16 | port_lo) and resolve precedence with a
    vectorized max, bit-identical to this ladder (compile/policy_image.py).
    """
    width = key.port_hi - key.port_lo
    return (key.specificity(), -width, key.port_lo, key.identity, key.proto)


def merge_contributions(entries: Iterable[MapStateEntry]
                        ) -> Optional[MapStateEntry]:
    """Fold independent contributions to one key with the same precedence
    `_merge` applies pairwise (deny wins; plain allow shadows L7; else L7
    union) — the semantic result is order-independent. Returns None for an
    empty fold (key should be deleted)."""
    out: Optional[MapStateEntry] = None
    for e in entries:
        out = e if out is None else _merge(out, e)
    return out


def rank_scalar(key: MapStateKey) -> int:
    """The scalar encoding of :func:`_rank` used by the tensor compiler."""
    width = key.port_hi - key.port_lo
    return (key.specificity() << 33) | ((65535 - width) << 16) | key.port_lo
