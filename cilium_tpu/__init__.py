"""cilium_tpu — a TPU-native packet-classification framework.

A from-scratch reimplementation of the capabilities of Cilium's eBPF datapath
(reference: carlanton/cilium; see SURVEY.md — the reference mount was empty, so
SURVEY.md's reconstructed semantics + the in-repo oracle are the parity contract),
re-designed TPU-first:

- ``model/``    — labels, security identities, CNP-compatible rule schema, ipcache
                  (analog of upstream ``pkg/labels``, ``pkg/identity``,
                  ``pkg/policy/api``, ``pkg/ipcache``).
- ``policy/``   — Repository + SelectorCache + MapState computation
                  (analog of ``pkg/policy``).
- ``compile/``  — the "loader": MapState/ipcache/CT-config → dense device tensor
                  images (analog of ``pkg/datapath/loader`` — XLA replaces clang).
- ``kernels/``  — batched JAX/Pallas datapath kernels: LPM gather, policy lookup,
                  conntrack probe, L7-lite match, fused classify step (analog of
                  ``bpf/``).
- ``runtime/``  — host engine: snapshot double-buffering with revision fencing,
                  update controller, checkpoint/resume, metrics, flow log (analog
                  of ``pkg/datapath``, ``pkg/endpoint`` regeneration, monitor/Hubble).
- ``parallel/`` — device mesh + shard_map strategies: batch DP with RSS-style CT
                  sharding, rule-space row sharding (analog of per-CPU maps / RSS).
- ``shim/``     — C++ AF_XDP front end + ctypes bindings (analog of ``bpf_xdp.c``
                  XDP hook, rebuilt as a userspace shim feeding the TPU).
- ``cli/``      — inspect/trace/bench commands (analog of ``cilium-dbg``).
"""

__version__ = "0.1.0"
