"""Observability + adaptive control (the Hubble observer/metrics analog,
SURVEY.md §3.6, plus the adaptive-batching controller SURVEY §3.4 names).

Three parts behind one package:

- ``trace``       — low-overhead sampled span recorder for the serving path
                    (admission → microbatch → stage → dispatch → finalize,
                    pack/transfer/compute inside the datapath, regen/compile
                    in the engine). Unsampled events pay one counter.
- ``flowmetrics`` — Hubble-metrics analog: vectorized per-batch verdict /
                    drop-reason / protocol / port / identity aggregation
                    into windowed time-series (no per-record Python).
- ``autotune``    — a controller that closes the loop: consumes the
                    queue-wait and fill-ratio histograms the pipeline
                    already exports and adjusts ``pipeline_flush_ms`` and
                    the active bucket floor within configured bounds
                    (hysteresis + capped steps, off by default).
- ``audit``       — shadow-oracle parity auditor: counter-samples finalized
                    batches, replays them against ``oracle/datapath.py`` in
                    a background controller, and compares verdicts
                    bit-for-bit — the paper's parity claim as a continuous
                    production observable.
- ``blackbox``    — always-on bounded flight recorder: guard/regen/audit
                    event ring + verdict summaries + span tail, frozen into
                    an exportable JSON debug bundle on anomaly.
"""

from cilium_tpu.observe.trace import TRACER, Tracer  # noqa: F401
from cilium_tpu.observe.flowmetrics import FlowMetrics  # noqa: F401
from cilium_tpu.observe.autotune import Autotuner  # noqa: F401
from cilium_tpu.observe.audit import ShadowAuditor  # noqa: F401
from cilium_tpu.observe.blackbox import FlightRecorder  # noqa: F401

__all__ = ["TRACER", "Tracer", "FlowMetrics", "Autotuner", "ShadowAuditor",
           "FlightRecorder"]
