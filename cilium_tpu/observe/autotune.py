"""Histogram-driven pipeline autotuning (SURVEY.md §3.4 "adaptive batching";
ROADMAP open item: "autoscale ``pipeline_flush_ms`` / bucket choice from
observed queue-wait histograms").

The pipeline already exports exactly the signals a controller needs — the
``pipeline_queue_wait_seconds`` histogram and the fill/bucket row counters —
so the autotuner is a pure consumer: each :meth:`step` diffs those against
its previous snapshot (so every decision is about the *last interval*, not
the process lifetime) and nudges two knobs inside configured bounds:

- ``flush_ms`` — queue-wait p99 over budget → flush sooner (down); fill
  ratio under target while p99 is comfortably under half the budget →
  coalesce longer (up).
- ``min_bucket`` (the smallest dispatch shape) — deadline-dominated flushes
  at low fill → smaller floor (less padding per dispatch); near-full
  dispatches everywhere → larger floor (fewer, bigger device steps).
- ``lane_bucket`` (the latency lane's dispatch floor; armed only under
  multi-tenant QoS — zero otherwise and the arm never runs) — lane wait
  p99 over its budget or lane fill far under target → smaller lane shapes
  (less padding, faster small dispatches); lane batches near-filling the
  bucket while p99 is comfortable → grow toward ``min_bucket`` (the lane
  converges back to bulk shapes when it carries bulk-sized traffic). The
  autotuner arbitrates the lane/bulk split INSIDE its existing bounds:
  ``lane_bucket`` never exceeds ``min_bucket`` and never drops under the
  lane floor, with the same hysteresis/dead-band discipline.

Stability over reactivity: a change needs ``hysteresis`` *consecutive*
same-direction intervals, steps are capped multiplicative factors, and the
up/down conditions leave a dead band between them — the combination is what
the oscillation test in ``tests/test_observe.py`` pins. Decisions are
themselves traced (``autotune.decision`` events) and counted, so a
misbehaving controller is observable through the subsystem it drives.
Disabled by default (``DaemonConfig.autotune_enabled``).
"""

from __future__ import annotations

import logging
from collections import deque
from typing import Deque, Dict, List, Optional

from cilium_tpu.observe.trace import TRACER, Tracer
from cilium_tpu.runtime.metrics import (Metrics, quantile_from,
                                        quantile_is_empty)

log = logging.getLogger("cilium_tpu.autotune")

QUEUE_WAIT_HIST = "pipeline_queue_wait_seconds"
LANE_WAIT_HIST = "pipeline_lane_wait_seconds"

#: smallest latency-lane dispatch shape the autotuner will choose (pow2)
LANE_BUCKET_FLOOR = 8


class Autotuner:
    """``pipeline`` needs ``stats()`` (with ``fill_rows``/``bucket_rows``/
    ``flush_reasons``), ``flush_ms``/``min_bucket``/``max_bucket`` and the
    ``set_flush_ms``/``set_min_bucket`` setters — the real Pipeline, or a
    stub in tests."""

    def __init__(self, pipeline, metrics: Metrics,
                 tracer: Optional[Tracer] = None, *,
                 flush_ms_min: float = 0.5, flush_ms_max: float = 20.0,
                 min_bucket_floor: Optional[int] = None,
                 target_fill: float = 0.7,
                 queue_wait_p99_budget_ms: float = 10.0,
                 lane_wait_p99_budget_ms: Optional[float] = None,
                 lane_bucket_floor: int = LANE_BUCKET_FLOOR,
                 hysteresis: int = 3, step_factor: float = 1.5,
                 min_interval_batches: int = 4):
        if flush_ms_min <= 0 or flush_ms_max < flush_ms_min:
            raise ValueError("need 0 < flush_ms_min <= flush_ms_max")
        if hysteresis < 1 or step_factor <= 1.0:
            raise ValueError("hysteresis >= 1 and step_factor > 1 required")
        self.pipeline = pipeline
        self.metrics = metrics
        self.tracer = tracer if tracer is not None else TRACER
        self.flush_ms_min = flush_ms_min
        self.flush_ms_max = flush_ms_max
        self.min_bucket_floor = min_bucket_floor
        self.target_fill = target_fill
        self.budget_ms = queue_wait_p99_budget_ms
        # the lane is the latency product: default its budget to half the
        # bulk queue-wait budget rather than growing a config knob
        self.lane_budget_ms = lane_wait_p99_budget_ms \
            if lane_wait_p99_budget_ms is not None \
            else 0.5 * queue_wait_p99_budget_ms
        self.lane_bucket_floor = max(1, int(lane_bucket_floor))
        self.hysteresis = hysteresis
        self.step_factor = step_factor
        self.min_interval_batches = min_interval_batches

        self._last_counts: Optional[List[int]] = None
        self._last_fill = (0, 0)
        self._last_dispatched = 0
        self._last_reasons: Dict[str, int] = {}
        self._flush_streak = 0          # +n consecutive "up", -n "down"
        self._bucket_streak = 0
        self._lane_streak = 0
        self._last_lane = (0, 0)        # (lane_fill_rows, lane_bucket_rows)
        self._last_lane_counts: Optional[List[int]] = None
        # bounded decision history (the /v1/status surface only shows the
        # tail; a long-lived daemon must not accumulate dicts forever)
        self.adjustments: Deque[Dict] = deque(maxlen=64)
        self.adjustments_total = 0

    # -- the control step ----------------------------------------------------
    def step(self) -> Optional[Dict]:
        """One control interval. Returns the observation/decision record,
        or None when there is not enough fresh signal to act on."""
        pl = self.pipeline
        hist = self.metrics.histograms.get(QUEUE_WAIT_HIST)
        if hist is None:
            return None
        buckets, counts, _total, _n = hist.snapshot()
        stats = pl.stats()
        fill_rows = stats.get("fill_rows", 0)
        bucket_rows = stats.get("bucket_rows", 0)
        dispatched = stats.get("dispatched_batches", 0)

        reasons = dict(stats.get("flush_reasons", {}))

        if self._last_counts is None:
            # first step: baseline only — a decision needs an interval
            self._remember(counts, fill_rows, bucket_rows, dispatched,
                           reasons)
            return None
        d_counts = [c - p for c, p in zip(counts, self._last_counts)]
        d_dispatched = dispatched - self._last_dispatched
        d_fill = fill_rows - self._last_fill[0]
        d_bucket = bucket_rows - self._last_fill[1]
        d_reasons = {k: v - self._last_reasons.get(k, 0)
                     for k, v in reasons.items()}
        if d_dispatched < self.min_interval_batches or d_bucket <= 0:
            return None                  # idle interval: keep the baseline
        self._remember(counts, fill_rows, bucket_rows, dispatched, reasons)

        p99 = quantile_from(buckets, d_counts, 0.99)
        if quantile_is_empty(p99):
            # batches dispatched but zero queue-wait observations this
            # interval (a histogram reset / scrape race): no signal to act
            # on — and the NaN sentinel must never reach the comparisons
            # below, where every branch would silently read False
            return None
        p99_ms = p99 * 1e3
        fill = d_fill / d_bucket
        obs = {"queue_wait_p99_ms": round(p99_ms, 3),
               "fill_ratio": round(fill, 4),
               "flush_ms": pl.flush_ms, "min_bucket": pl.min_bucket,
               "interval_batches": d_dispatched, "adjusted": []}

        # -- flush_ms --------------------------------------------------------
        if p99_ms > self.budget_ms:
            want = -1
        elif fill < self.target_fill and p99_ms < 0.5 * self.budget_ms:
            want = +1
        else:
            want = 0
        self._flush_streak = self._advance(self._flush_streak, want)
        if abs(self._flush_streak) >= self.hysteresis:
            old = pl.flush_ms
            new = old * self.step_factor if self._flush_streak > 0 \
                else old / self.step_factor
            new = min(self.flush_ms_max, max(self.flush_ms_min, new))
            if new != old:
                pl.set_flush_ms(new)
                self._decide(obs, "flush_ms", old, new)
            self._flush_streak = 0

        # -- min_bucket (the active bucket-set floor) ------------------------
        floor = self.min_bucket_floor or 1
        deadline_frac = d_reasons.get("deadline", 0) \
            / max(1, sum(d_reasons.values()))
        if fill < self.target_fill and deadline_frac > 0.5 \
                and pl.min_bucket > floor:
            bwant = -1
        elif fill >= 0.95 and pl.min_bucket < pl.max_bucket:
            bwant = +1
        else:
            bwant = 0
        self._bucket_streak = self._advance(self._bucket_streak, bwant)
        if abs(self._bucket_streak) >= self.hysteresis:
            old_b = pl.min_bucket
            new_b = old_b * 2 if self._bucket_streak > 0 else old_b // 2
            new_b = min(pl.max_bucket, max(floor, new_b))
            if new_b != old_b:
                pl.set_min_bucket(new_b)
                self._decide(obs, "min_bucket", old_b, new_b)
                lane = getattr(pl, "lane_bucket", 0)
                if lane > new_b:
                    # the "lane_bucket never exceeds min_bucket" invariant
                    # is enforced HERE, not left to the lane arm — its own
                    # shrink path needs `hysteresis` consecutive lane
                    # signals pointing down, which may never come, and the
                    # lane would dispatch above the bulk floor for many
                    # intervals meanwhile (largest power of two <= the new
                    # floor, since the lane shape must stay pow2)
                    clamped = 1 << (new_b.bit_length() - 1)
                    pl.set_lane_bucket(clamped)
                    self._decide(obs, "lane_bucket", lane, clamped)
                    self._lane_streak = 0
            self._bucket_streak = 0

        # -- lane_bucket (latency-lane floor; QoS only — zero disarms) -------
        if getattr(pl, "lane_bucket", 0):
            self._lane_step(pl, obs, stats)
            self.metrics.set_gauge("autotune_lane_bucket", pl.lane_bucket)

        self.metrics.set_gauge("autotune_flush_ms", pl.flush_ms)
        self.metrics.set_gauge("autotune_min_bucket", pl.min_bucket)
        return obs

    def _lane_step(self, pl, obs: Dict, stats: Dict) -> None:
        """The lane/bulk arbitration arm. Same interval-diff + streak
        machinery as the bulk knobs, driven by the lane's own signals:
        ``pipeline_lane_wait_seconds`` p99 and the lane fill ratio
        (lane_fill_rows / lane_bucket_rows over the interval)."""
        lane_fill = stats.get("lane_fill_rows", 0)
        lane_rows = stats.get("lane_bucket_rows", 0)
        d_lfill = lane_fill - self._last_lane[0]
        d_lrows = lane_rows - self._last_lane[1]
        self._last_lane = (lane_fill, lane_rows)
        p99_ms = None
        hist = self.metrics.histograms.get(LANE_WAIT_HIST)
        if hist is not None:
            buckets, counts, _total, _n = hist.snapshot()
            prev = self._last_lane_counts
            self._last_lane_counts = list(counts)
            if prev is not None and len(prev) == len(counts):
                p99 = quantile_from(
                    buckets, [c - p for c, p in zip(counts, prev)], 0.99)
                if not quantile_is_empty(p99):
                    p99_ms = p99 * 1e3
        if d_lrows <= 0:
            self._lane_streak = 0        # idle lane: no signal, no drift
            return
        fill = d_lfill / d_lrows
        obs["lane_bucket"] = pl.lane_bucket
        obs["lane_fill_ratio"] = round(fill, 4)
        if p99_ms is not None:
            obs["lane_wait_p99_ms"] = round(p99_ms, 3)
        floor = self.lane_bucket_floor
        ceil = pl.min_bucket              # the lane never exceeds bulk's floor
        over = p99_ms is not None and p99_ms > self.lane_budget_ms
        calm = p99_ms is None or p99_ms < 0.5 * self.lane_budget_ms
        if (over or fill < 0.5 * self.target_fill) \
                and pl.lane_bucket > floor:
            lwant = -1
        elif fill >= 0.9 and calm and pl.lane_bucket < ceil:
            lwant = +1
        else:
            lwant = 0
        self._lane_streak = self._advance(self._lane_streak, lwant)
        if abs(self._lane_streak) >= self.hysteresis:
            old = pl.lane_bucket
            new = old * 2 if self._lane_streak > 0 else old // 2
            new = min(ceil, max(floor, new))
            if new != old:
                pl.set_lane_bucket(new)
                self._decide(obs, "lane_bucket", old, new)
            self._lane_streak = 0

    # -- helpers -------------------------------------------------------------
    @staticmethod
    def _advance(streak: int, want: int) -> int:
        if want == 0:
            return 0
        if (streak > 0) == (want > 0) and streak != 0:
            return streak + want
        return want

    def _remember(self, counts, fill_rows, bucket_rows, dispatched,
                  reasons) -> None:
        self._last_counts = list(counts)
        self._last_fill = (fill_rows, bucket_rows)
        self._last_dispatched = dispatched
        self._last_reasons = reasons

    def _decide(self, obs: Dict, knob: str, old, new) -> None:
        rec = {"knob": knob, "old": old, "new": new,
               "queue_wait_p99_ms": obs["queue_wait_p99_ms"],
               "fill_ratio": obs["fill_ratio"]}
        self.adjustments.append(rec)
        self.adjustments_total += 1
        obs["adjusted"].append(rec)
        obs[knob] = new
        self.metrics.inc_counter("autotune_adjustments_total")
        self.tracer.event("autotune.decision", **rec)
        log.info("autotune: %s %s -> %s (qw_p99=%.2fms fill=%.2f)",
                 knob, old, new, obs["queue_wait_p99_ms"],
                 obs["fill_ratio"])

    def status(self) -> Dict:
        lane = getattr(self.pipeline, "lane_bucket", 0)
        return {
            "flush_ms": self.pipeline.flush_ms,
            "min_bucket": self.pipeline.min_bucket,
            "bounds": {"flush_ms": [self.flush_ms_min, self.flush_ms_max],
                       "min_bucket": [self.min_bucket_floor or 1,
                                      self.pipeline.max_bucket],
                       **({"lane_bucket": [self.lane_bucket_floor,
                                           self.pipeline.min_bucket]}
                          if lane else {})},
            **({"lane_bucket": lane} if lane else {}),
            "adjustments": list(self.adjustments)[-20:],
            "adjustments_total": self.adjustments_total,
        }
