"""Histogram-driven pipeline autotuning (SURVEY.md §3.4 "adaptive batching";
ROADMAP open item: "autoscale ``pipeline_flush_ms`` / bucket choice from
observed queue-wait histograms").

The pipeline already exports exactly the signals a controller needs — the
``pipeline_queue_wait_seconds`` histogram and the fill/bucket row counters —
so the autotuner is a pure consumer: each :meth:`step` diffs those against
its previous snapshot (so every decision is about the *last interval*, not
the process lifetime) and nudges two knobs inside configured bounds:

- ``flush_ms`` — queue-wait p99 over budget → flush sooner (down); fill
  ratio under target while p99 is comfortably under half the budget →
  coalesce longer (up).
- ``min_bucket`` (the smallest dispatch shape) — deadline-dominated flushes
  at low fill → smaller floor (less padding per dispatch); near-full
  dispatches everywhere → larger floor (fewer, bigger device steps).

Stability over reactivity: a change needs ``hysteresis`` *consecutive*
same-direction intervals, steps are capped multiplicative factors, and the
up/down conditions leave a dead band between them — the combination is what
the oscillation test in ``tests/test_observe.py`` pins. Decisions are
themselves traced (``autotune.decision`` events) and counted, so a
misbehaving controller is observable through the subsystem it drives.
Disabled by default (``DaemonConfig.autotune_enabled``).
"""

from __future__ import annotations

import logging
from collections import deque
from typing import Deque, Dict, List, Optional

from cilium_tpu.observe.trace import TRACER, Tracer
from cilium_tpu.runtime.metrics import (Metrics, quantile_from,
                                        quantile_is_empty)

log = logging.getLogger("cilium_tpu.autotune")

QUEUE_WAIT_HIST = "pipeline_queue_wait_seconds"


class Autotuner:
    """``pipeline`` needs ``stats()`` (with ``fill_rows``/``bucket_rows``/
    ``flush_reasons``), ``flush_ms``/``min_bucket``/``max_bucket`` and the
    ``set_flush_ms``/``set_min_bucket`` setters — the real Pipeline, or a
    stub in tests."""

    def __init__(self, pipeline, metrics: Metrics,
                 tracer: Optional[Tracer] = None, *,
                 flush_ms_min: float = 0.5, flush_ms_max: float = 20.0,
                 min_bucket_floor: Optional[int] = None,
                 target_fill: float = 0.7,
                 queue_wait_p99_budget_ms: float = 10.0,
                 hysteresis: int = 3, step_factor: float = 1.5,
                 min_interval_batches: int = 4):
        if flush_ms_min <= 0 or flush_ms_max < flush_ms_min:
            raise ValueError("need 0 < flush_ms_min <= flush_ms_max")
        if hysteresis < 1 or step_factor <= 1.0:
            raise ValueError("hysteresis >= 1 and step_factor > 1 required")
        self.pipeline = pipeline
        self.metrics = metrics
        self.tracer = tracer if tracer is not None else TRACER
        self.flush_ms_min = flush_ms_min
        self.flush_ms_max = flush_ms_max
        self.min_bucket_floor = min_bucket_floor
        self.target_fill = target_fill
        self.budget_ms = queue_wait_p99_budget_ms
        self.hysteresis = hysteresis
        self.step_factor = step_factor
        self.min_interval_batches = min_interval_batches

        self._last_counts: Optional[List[int]] = None
        self._last_fill = (0, 0)
        self._last_dispatched = 0
        self._last_reasons: Dict[str, int] = {}
        self._flush_streak = 0          # +n consecutive "up", -n "down"
        self._bucket_streak = 0
        # bounded decision history (the /v1/status surface only shows the
        # tail; a long-lived daemon must not accumulate dicts forever)
        self.adjustments: Deque[Dict] = deque(maxlen=64)
        self.adjustments_total = 0

    # -- the control step ----------------------------------------------------
    def step(self) -> Optional[Dict]:
        """One control interval. Returns the observation/decision record,
        or None when there is not enough fresh signal to act on."""
        pl = self.pipeline
        hist = self.metrics.histograms.get(QUEUE_WAIT_HIST)
        if hist is None:
            return None
        buckets, counts, _total, _n = hist.snapshot()
        stats = pl.stats()
        fill_rows = stats.get("fill_rows", 0)
        bucket_rows = stats.get("bucket_rows", 0)
        dispatched = stats.get("dispatched_batches", 0)

        reasons = dict(stats.get("flush_reasons", {}))

        if self._last_counts is None:
            # first step: baseline only — a decision needs an interval
            self._remember(counts, fill_rows, bucket_rows, dispatched,
                           reasons)
            return None
        d_counts = [c - p for c, p in zip(counts, self._last_counts)]
        d_dispatched = dispatched - self._last_dispatched
        d_fill = fill_rows - self._last_fill[0]
        d_bucket = bucket_rows - self._last_fill[1]
        d_reasons = {k: v - self._last_reasons.get(k, 0)
                     for k, v in reasons.items()}
        if d_dispatched < self.min_interval_batches or d_bucket <= 0:
            return None                  # idle interval: keep the baseline
        self._remember(counts, fill_rows, bucket_rows, dispatched, reasons)

        p99 = quantile_from(buckets, d_counts, 0.99)
        if quantile_is_empty(p99):
            # batches dispatched but zero queue-wait observations this
            # interval (a histogram reset / scrape race): no signal to act
            # on — and the NaN sentinel must never reach the comparisons
            # below, where every branch would silently read False
            return None
        p99_ms = p99 * 1e3
        fill = d_fill / d_bucket
        obs = {"queue_wait_p99_ms": round(p99_ms, 3),
               "fill_ratio": round(fill, 4),
               "flush_ms": pl.flush_ms, "min_bucket": pl.min_bucket,
               "interval_batches": d_dispatched, "adjusted": []}

        # -- flush_ms --------------------------------------------------------
        if p99_ms > self.budget_ms:
            want = -1
        elif fill < self.target_fill and p99_ms < 0.5 * self.budget_ms:
            want = +1
        else:
            want = 0
        self._flush_streak = self._advance(self._flush_streak, want)
        if abs(self._flush_streak) >= self.hysteresis:
            old = pl.flush_ms
            new = old * self.step_factor if self._flush_streak > 0 \
                else old / self.step_factor
            new = min(self.flush_ms_max, max(self.flush_ms_min, new))
            if new != old:
                pl.set_flush_ms(new)
                self._decide(obs, "flush_ms", old, new)
            self._flush_streak = 0

        # -- min_bucket (the active bucket-set floor) ------------------------
        floor = self.min_bucket_floor or 1
        deadline_frac = d_reasons.get("deadline", 0) \
            / max(1, sum(d_reasons.values()))
        if fill < self.target_fill and deadline_frac > 0.5 \
                and pl.min_bucket > floor:
            bwant = -1
        elif fill >= 0.95 and pl.min_bucket < pl.max_bucket:
            bwant = +1
        else:
            bwant = 0
        self._bucket_streak = self._advance(self._bucket_streak, bwant)
        if abs(self._bucket_streak) >= self.hysteresis:
            old_b = pl.min_bucket
            new_b = old_b * 2 if self._bucket_streak > 0 else old_b // 2
            new_b = min(pl.max_bucket, max(floor, new_b))
            if new_b != old_b:
                pl.set_min_bucket(new_b)
                self._decide(obs, "min_bucket", old_b, new_b)
            self._bucket_streak = 0

        self.metrics.set_gauge("autotune_flush_ms", pl.flush_ms)
        self.metrics.set_gauge("autotune_min_bucket", pl.min_bucket)
        return obs

    # -- helpers -------------------------------------------------------------
    @staticmethod
    def _advance(streak: int, want: int) -> int:
        if want == 0:
            return 0
        if (streak > 0) == (want > 0) and streak != 0:
            return streak + want
        return want

    def _remember(self, counts, fill_rows, bucket_rows, dispatched,
                  reasons) -> None:
        self._last_counts = list(counts)
        self._last_fill = (fill_rows, bucket_rows)
        self._last_dispatched = dispatched
        self._last_reasons = reasons

    def _decide(self, obs: Dict, knob: str, old, new) -> None:
        rec = {"knob": knob, "old": old, "new": new,
               "queue_wait_p99_ms": obs["queue_wait_p99_ms"],
               "fill_ratio": obs["fill_ratio"]}
        self.adjustments.append(rec)
        self.adjustments_total += 1
        obs["adjusted"].append(rec)
        obs[knob] = new
        self.metrics.inc_counter("autotune_adjustments_total")
        self.tracer.event("autotune.decision", **rec)
        log.info("autotune: %s %s -> %s (qw_p99=%.2fms fill=%.2f)",
                 knob, old, new, obs["queue_wait_p99_ms"],
                 obs["fill_ratio"])

    def status(self) -> Dict:
        return {
            "flush_ms": self.pipeline.flush_ms,
            "min_bucket": self.pipeline.min_bucket,
            "bounds": {"flush_ms": [self.flush_ms_min, self.flush_ms_max],
                       "min_bucket": [self.min_bucket_floor or 1,
                                      self.pipeline.max_bucket]},
            "adjustments": list(self.adjustments)[-20:],
            "adjustments_total": self.adjustments_total,
        }
