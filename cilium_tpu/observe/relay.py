"""Flow relay: hubble-relay-style fan-in over N observer rings
(SURVEY.md §3.6; ROADMAP item 3's aggregation point).

A relay owns one :class:`~cilium_tpu.observe.observer.FlowObserver` per
named source (today: the engines of a sharded single-host mesh or N local
engine processes' flowlogs; tomorrow: the per-host observers of the
clustermesh tier — the merge logic is source-agnostic, so the multi-host
PR swaps transports, not this code) and serves the same two read modes the
single observer does:

- **one-shot** (:meth:`observe`): query every source with the same
  allow/deny filter lists, k-way merge on ``(time, seq)`` (each source's
  seq is monotonic, so the merge is a mergesort of already-sorted runs),
  newest-last, each record tagged ``node=<source>``.
- **follow** (:meth:`poll`): per-source seq cursors advance independently;
  each poll merges the new records and *re-emits every source's gap
  markers* (tagged with the node) — fan-in never hides loss.

Per-source lag is a first-class gauge: ``relay_source_lag{source=...}`` is
how many records a source has appended past the relay's cursor
(post-poll it reads 0 unless the poll truncated), the "is one host
falling behind" signal an operator watches before a multi-host follower
starts dropping.
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Sequence

from cilium_tpu.observe.observer import FlowFilter, FlowObserver
from cilium_tpu.runtime.flowlog import FlowLog


class FlowRelay:
    def __init__(self, sources: Dict[str, object], metrics=None):
        """``sources``: name → FlowObserver | FlowLog (flowlogs are
        wrapped). Names become the ``node`` tag on merged records."""
        self.observers: Dict[str, FlowObserver] = {}
        for name, src in sources.items():
            if isinstance(src, FlowLog):
                src = FlowObserver(src)
            self.observers[name] = src
        self.metrics = metrics
        self._cursors: Dict[str, int] = {name: 0 for name in self.observers}
        self.polls_total = 0
        self.gaps_total = 0

    # -- merge core -----------------------------------------------------------
    @staticmethod
    def _merge(per_source: Dict[str, List[Dict]]) -> List[Dict]:
        """k-way mergesort on (time, seq, node): each source list is
        already seq-sorted (ring order), so heapq.merge is O(total·log k).
        Gap markers carry no time — they sort at the head of their
        source's run (loss is announced before the records after it)."""
        runs = []
        for name, flows in per_source.items():
            run = []
            for r in flows:
                r = dict(r)
                r["node"] = name
                run.append(r)
            runs.append(run)
        return list(heapq.merge(
            *runs, key=lambda r: (r.get("time", -1), r.get("seq", -1),
                                  r.get("node", ""))))

    def observe(self, allow: Sequence[FlowFilter] = (),
                deny: Sequence[FlowFilter] = (), last: int = 0) -> Dict:
        """One-shot fan-in: same filters against every source, merged
        newest-last, bounded by ``last`` AFTER the merge (so the window is
        global, not per-source)."""
        per, stats = {}, {}
        for name, obs in self.observers.items():
            # last=0 means the full retained window: lift the observer's
            # default one-shot cap to the source ring's size so "bounded
            # AFTER the merge" holds (no silent per-source truncation)
            res = obs.observe(allow, deny, last=last,
                              limit=max(len(obs.flowlog), 1))
            per[name] = res["flows"]
            stats[name] = {"matched": res["matched"],
                           "scanned": res["scanned"],
                           "newest_seq": res["cursor"]}
        merged = self._merge(per)
        if last and len(merged) > last:
            merged = merged[-last:]
        return {"flows": merged, "sources": stats}

    def poll(self, allow: Sequence[FlowFilter] = (),
             deny: Sequence[FlowFilter] = (),
             limit: int = 4096) -> Dict:
        """Follow-mode fan-in: advance every source's cursor, merge the new
        records, surface every gap, export per-source lag gauges."""
        per: Dict[str, List[Dict]] = {}
        gaps: List[Dict] = []
        lags: Dict[str, int] = {}
        for name, obs in self.observers.items():
            res = obs.observe(allow, deny, since=self._cursors[name],
                              limit=limit)
            self._cursors[name] = res["cursor"]
            run = []
            if res["gap"] is not None:
                g = dict(res["gap"])
                g["node"] = name
                gaps.append(g)
                self.gaps_total += 1
                run.append(g)
            run.extend(res["flows"])
            per[name] = run
            lag = max(0, obs.flowlog.newest_seq - self._cursors[name])
            lags[name] = lag
            if self.metrics is not None:
                self.metrics.set_gauge(
                    f'relay_source_lag{{source="{name}"}}', lag)
        self.polls_total += 1
        if self.metrics is not None:
            self.metrics.inc_counter("relay_polls_total")
            if gaps:
                self.metrics.inc_counter("relay_source_gaps_total",
                                         len(gaps))
        return {"flows": self._merge(per), "gaps": gaps, "lag": lags}

    def cursors(self) -> Dict[str, int]:
        return dict(self._cursors)

    def stats(self) -> Dict:
        return {
            "sources": sorted(self.observers),
            "cursors": dict(self._cursors),
            "polls": self.polls_total,
            "gaps": self.gaps_total,
        }
