"""Flow relay: hubble-relay-style fan-in over N observer rings
(SURVEY.md §3.6; ROADMAP item 3's aggregation point).

A relay owns one :class:`~cilium_tpu.observe.observer.FlowObserver` per
named source (today: the engines of a sharded single-host mesh or N local
engine processes' flowlogs; tomorrow: the per-host observers of the
clustermesh tier — the merge logic is source-agnostic, so the multi-host
PR swaps transports, not this code) and serves the same two read modes the
single observer does:

- **one-shot** (:meth:`observe`): query every source with the same
  allow/deny filter lists, k-way merge on ``(time, seq)`` (each source's
  seq is monotonic, so the merge is a mergesort of already-sorted runs),
  newest-last, each record tagged ``node=<source>``.
- **follow** (:meth:`poll`): per-source seq cursors advance independently;
  each poll merges the new records and *re-emits every source's gap
  markers* (tagged with the node) — fan-in never hides loss.

Per-source lag is a first-class gauge: ``relay_source_lag{source=...}`` is
how many records a source has appended past the relay's cursor
(post-poll it reads 0 unless the poll truncated), the "is one host
falling behind" signal an operator watches before a multi-host follower
starts dropping.
"""

from __future__ import annotations

import heapq
import json
import os
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from cilium_tpu.observe.observer import (FlowFilter, FlowObserver,
                                         compose_mask)
from cilium_tpu.runtime.flowlog import FlowLog
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_addr

_PROTO_IDS = {v: k for k, v in C.PROTO_NAMES.items()}
_DIR_IDS = {v: k for k, v in C.DIR_NAMES.items()}


class JsonlTailObserver:
    """File-tail flow source: incrementally reads a node's flowlog JSONL
    sink (the ``hubble export`` analog, ``DaemonConfig.flowlog_path``) and
    serves the same ``observe()`` surface :class:`FlowObserver` does — the
    cross-PROCESS/HOST transport for :class:`FlowRelay` (ISSUE 12: one
    observer surface spans the mesh; each engine proc exports its flowlog
    to a file, the relay host tails them all).

    Loss stays explicit end to end: a bounded retained window that wraps
    past a follower's cursor produces the same structured gap marker the
    in-memory ring does, and a truncated/rotated file (resumed from byte
    0) re-syncs rather than re-emitting old records."""

    def __init__(self, path: str, capacity: int = 16384):
        self.path = path
        self.capacity = capacity
        self._offset = 0               # byte offset consumed so far
        self._partial = ""             # trailing unterminated line
        self._records: deque = deque(maxlen=capacity)
        self.newest_seq = 0
        self._oldest_seq = 0           # oldest RETAINED seq (0 = none yet)
        # a RESTARTED writer resets its ring seq to 1; the tail keeps its
        # own monotonic stream by rebasing (synthetic seq = raw + base) —
        # a seq regression is a restart, never a duplicate to drop
        self._last_raw_seq = 0
        self._seq_base = 0
        self.writer_restarts = 0
        self.parse_errors = 0
        self.polls = 0

    # lag duck-typing: FlowRelay reads ``obs.flowlog.newest_seq`` /
    # ``len(obs.flowlog)`` — this source is its own ring
    @property
    def flowlog(self) -> "JsonlTailObserver":
        return self

    def __len__(self) -> int:
        return len(self._records)

    def poll_file(self) -> int:
        """Consume newly-appended bytes; returns records ingested."""
        self.polls += 1
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return 0                   # not written yet / unreachable
        if size < self._offset:
            # truncated or rotated: resume from the top of the new file
            self._offset = 0
            self._partial = ""
        if size == self._offset:
            return 0
        with open(self.path, "r") as f:
            f.seek(self._offset)
            data = f.read()
            self._offset = f.tell()
        data = self._partial + data
        lines = data.split("\n")
        self._partial = lines.pop()    # "" when data ended in \n
        n = 0
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                self.parse_errors += 1
                continue
            raw = int(rec.get("seq", 0))
            if raw and raw <= self._last_raw_seq:
                # seq regression = the WRITER restarted (a fresh engine's
                # ring starts over at 1; same-process rotation keeps
                # counting) — rebase so the tail's stream stays monotonic
                # and the new session's records are kept, not dropped
                self._seq_base = self.newest_seq
                self.writer_restarts += 1
            self._last_raw_seq = raw
            if raw:
                rec = dict(rec)
                rec["seq"] = raw + self._seq_base
            self._records.append(rec)  # deque(maxlen) evicts the oldest
            self.newest_seq = max(self.newest_seq, int(rec.get("seq", 0)))
            n += 1
        if self._records:
            self._oldest_seq = int(self._records[0].get("seq", 0))
        return n

    @staticmethod
    def _cols_from(records: List[Dict]) -> Dict[str, np.ndarray]:
        """Rendered records → the raw column dict FlowFilter masks read
        (only built when filters are armed)."""
        n = len(records)
        cols = {
            "seq": np.fromiter((r.get("seq", 0) for r in records),
                               np.int64, n),
            "time": np.fromiter((r.get("time", 0) for r in records),
                                np.int64, n),
            "allow": np.fromiter(
                (r.get("verdict") == "FORWARDED" for r in records),
                bool, n),
            "reason": np.fromiter(
                (r.get("drop_reason", 0) for r in records), np.int64, n),
            "endpoint_id": np.fromiter(
                (r.get("endpoint_id", -1) for r in records), np.int64, n),
            "remote_identity": np.fromiter(
                (r.get("remote_identity", -1) for r in records),
                np.int64, n),
            "proto": np.fromiter(
                (_PROTO_IDS.get(r.get("proto"), -1) for r in records),
                np.int64, n),
            "sport": np.fromiter(
                (r.get("src_port", -1) for r in records), np.int64, n),
            "dport": np.fromiter(
                (r.get("dst_port", -1) for r in records), np.int64, n),
            "matched_rule": np.fromiter(
                (r.get("matched_rule", -1) for r in records), np.int64, n),
            "direction": np.fromiter(
                (_DIR_IDS.get(r.get("direction"), -1) for r in records),
                np.int64, n),
        }
        addr = np.zeros((n, 8), dtype=np.uint32)
        for i, r in enumerate(records):
            for base, key in ((0, "src_ip"), (4, "dst_ip")):
                try:
                    a16, _ = parse_addr(str(r.get(key, "")))
                except (ValueError, OSError):
                    continue
                addr[i, base:base + 4] = np.frombuffer(a16, dtype=">u4")
        cols["src"] = addr[:, :4]
        cols["dst"] = addr[:, 4:]
        return cols

    def observe(self, allow: Sequence[FlowFilter] = (),
                deny: Sequence[FlowFilter] = (),
                last: int = 0, since: Optional[int] = None,
                limit: int = 4096) -> Dict:
        """Same contract as :meth:`FlowObserver.observe` (one-shot /
        follow with explicit gap markers), over the tailed file."""
        self.poll_file()
        follow = since is not None
        recs = list(self._records)
        gap = None
        if follow:
            if since and since > 0 and self._oldest_seq > since + 1:
                # same structured marker FlowLog.gap_marker emits: loss is
                # a record in the stream, not cursor arithmetic
                gap = {"gap": True, "dropped": self._oldest_seq - since - 1,
                       "resume_seq": self._oldest_seq}
            recs = [r for r in recs if int(r.get("seq", 0)) > since]
        scanned = len(recs)
        if recs and (allow or deny):
            m = compose_mask(self._cols_from(recs), allow, deny)
            recs = [r for r, keep in zip(recs, m) if keep]
        matched = len(recs)
        cap = last if (last and not follow) else limit
        truncated = bool(cap and len(recs) > cap)
        if truncated:
            recs = recs[-cap:] if not follow else recs[:cap]
        if follow and truncated and recs:
            cursor = int(recs[-1].get("seq", since or 0))
        else:
            cursor = self.newest_seq
        return {"flows": [dict(r) for r in recs], "cursor": cursor,
                "gap": gap, "matched": matched, "scanned": scanned}

    def stats(self) -> Dict:
        return {"path": self.path, "offset": self._offset,
                "retained": len(self._records),
                "newest_seq": self.newest_seq,
                "parse_errors": self.parse_errors, "polls": self.polls}


class FlowRelay:
    def __init__(self, sources: Dict[str, object], metrics=None):
        """``sources``: name → FlowObserver | FlowLog (wrapped) |
        JsonlTailObserver (or anything with the ``observe()`` contract).
        Names become the ``node`` tag on merged records."""
        self.observers: Dict[str, FlowObserver] = {}
        for name, src in sources.items():
            if isinstance(src, FlowLog):
                src = FlowObserver(src)
            self.observers[name] = src
        self.metrics = metrics
        self._cursors: Dict[str, int] = {name: 0 for name in self.observers}
        self.polls_total = 0
        self.gaps_total = 0

    # -- merge core -----------------------------------------------------------
    @staticmethod
    def _merge(per_source: Dict[str, List[Dict]]) -> List[Dict]:
        """k-way mergesort on (time, seq, node): each source list is
        already seq-sorted (ring order), so heapq.merge is O(total·log k).
        Gap markers carry no time — they sort at the head of their
        source's run (loss is announced before the records after it)."""
        runs = []
        for name, flows in per_source.items():
            run = []
            for r in flows:
                r = dict(r)
                r["node"] = name
                run.append(r)
            runs.append(run)
        return list(heapq.merge(
            *runs, key=lambda r: (r.get("time", -1), r.get("seq", -1),
                                  r.get("node", ""))))

    def observe(self, allow: Sequence[FlowFilter] = (),
                deny: Sequence[FlowFilter] = (), last: int = 0) -> Dict:
        """One-shot fan-in: same filters against every source, merged
        newest-last, bounded by ``last`` AFTER the merge (so the window is
        global, not per-source)."""
        per, stats = {}, {}
        for name, obs in self.observers.items():
            # last=0 means the full retained window: lift the observer's
            # default one-shot cap to the source ring's size so "bounded
            # AFTER the merge" holds (no silent per-source truncation)
            res = obs.observe(allow, deny, last=last,
                              limit=max(len(obs.flowlog), 1))
            per[name] = res["flows"]
            stats[name] = {"matched": res["matched"],
                           "scanned": res["scanned"],
                           "newest_seq": res["cursor"]}
        merged = self._merge(per)
        if last and len(merged) > last:
            merged = merged[-last:]
        return {"flows": merged, "sources": stats}

    def poll(self, allow: Sequence[FlowFilter] = (),
             deny: Sequence[FlowFilter] = (),
             limit: int = 4096) -> Dict:
        """Follow-mode fan-in: advance every source's cursor, merge the new
        records, surface every gap, export per-source lag gauges."""
        per: Dict[str, List[Dict]] = {}
        gaps: List[Dict] = []
        lags: Dict[str, int] = {}
        for name, obs in self.observers.items():
            res = obs.observe(allow, deny, since=self._cursors[name],
                              limit=limit)
            self._cursors[name] = res["cursor"]
            run = []
            if res["gap"] is not None:
                g = dict(res["gap"])
                g["node"] = name
                gaps.append(g)
                self.gaps_total += 1
                run.append(g)
            run.extend(res["flows"])
            per[name] = run
            lag = max(0, obs.flowlog.newest_seq - self._cursors[name])
            lags[name] = lag
            if self.metrics is not None:
                self.metrics.set_gauge(
                    f'relay_source_lag{{source="{name}"}}', lag)
        self.polls_total += 1
        if self.metrics is not None:
            self.metrics.inc_counter("relay_polls_total")
            if gaps:
                self.metrics.inc_counter("relay_source_gaps_total",
                                         len(gaps))
        return {"flows": self._merge(per), "gaps": gaps, "lag": lags}

    def cursors(self) -> Dict[str, int]:
        return dict(self._cursors)

    def stats(self) -> Dict:
        return {
            "sources": sorted(self.observers),
            "cursors": dict(self._cursors),
            "polls": self.polls_total,
            "gaps": self.gaps_total,
        }
