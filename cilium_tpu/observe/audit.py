"""Shadow-oracle parity audit: verdict provenance as a production observable.

The paper's headline claim — flow verdicts bit-identical to the eBPF
datapath semantics — is pinned at test time by tests/test_parity.py, but
the serving stack reshapes verdicts continuously (steered staging, pooled
wire buffers, dispatch-time slot remaps, per-mesh restarts) and none of
that machinery is exercised by a one-shot test under the exact policy
revision a production batch classified against. This module makes parity a
*runtime* signal:

- **Capture at finalize** (:meth:`ShadowAuditor.maybe_capture`, called from
  the engine's finalize path): counter-sampled — the same deterministic
  sampling discipline as ``observe/trace.py``, one counter draw per
  finalized batch on the unsampled path — a sampled batch's valid rows are
  copied out together with everything replay needs *despite CT mutation*:
  the columnar rows as classified (post slot-remap, post steering), the
  captured out columns (whose ``status`` IS the CT probe result as-of
  classification), the snapshot the batch classified under (revision
  fence), the per-row flow-shard id, and the wire format in use. The
  capture pool is bounded: when the background replay lags, new captures
  are dropped and counted in ``parity_audit_skipped_total`` — the serving
  path never blocks on its own auditor.
- **Replay in the background** (:meth:`step`, driven by the engine's
  ``parity-audit`` controller): each captured row is rebuilt into an
  oracle :class:`PacketRecord` and re-derived through
  ``oracle.Oracle.replay`` — service DNAT, ipcache LPM, the policy ladder,
  L7 matching — with the captured CT status as the conntrack truth, then
  compared bit-for-bit: allow, drop reason, remote identity, redirect,
  and the service DNAT target. A diverging row's implied CT delta
  (create/update/none, derived from allow+status) is reported alongside
  as the conntrack consequence of the wrong verdict. Reply un-DNAT
  fields are checked structurally (rnat requires REPLY status) since the
  rev-NAT id lives in the live CT entry, not the probe input.
- **Accounting**: ``parity_audit_checked_total`` (rows replayed),
  ``parity_audit_skipped_total`` (pool-saturation drops),
  ``parity_audit_mismatched_total{revision="N"}`` (one labeled series per
  offending policy revision — sum over labels for the total). A mismatch
  folds into ``Engine.health()`` as DEGRADED and fires ``on_mismatch`` —
  the engine points that at the flight recorder
  (``observe/blackbox.py``), freezing a debug bundle that carries the
  offending rows and revision.

Fault tolerance is the design constraint: every entry point the serving
path touches is wrapped never-raise (errors are counted + throttled-
logged), the capture pool is bounded, and the replay side runs in a
supervised controller — a crashed, wedged, or killed auditor degrades to
``skipped`` accounting, never to a stalled pipeline. The ``audit.corrupt``
fault point (runtime/faults.py) deliberately flips the captured allow bits
so chaos drills can prove the detector actually detects.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

import numpy as np

from cilium_tpu.observe.trace import TRACER, Tracer
from cilium_tpu.runtime.faults import FAULTS, FaultInjected
from cilium_tpu.runtime.metrics import Metrics
from cilium_tpu.utils import constants as C

log = logging.getLogger("cilium_tpu.audit")

#: out columns a capture snapshots (the verdict surface the replay compares;
#: rnat fields ride along for the structural consistency check; ct_full is
#: the CT-exhaustion signal — same truth class as status, a table fact
#: as-of classification that replay takes as given and may only use to
#: EXCUSE a create it would itself demand). The provenance columns
#: (matched_rule / lpm_prefix / ct_state_pre, ISSUE 11) are part of the
#: audited surface: a verdict that is right for the wrong reason — correct
#: allow bit, wrong winning rule or prefix — is a provenance mismatch.
AUDIT_OUT_KEYS = ("allow", "reason", "status", "ct_full", "remote_identity",
                  "redirect", "svc", "nat_dst", "nat_dport", "rnat",
                  "matched_rule", "lpm_prefix", "ct_state_pre")

#: batch columns a capture snapshots (the classify inputs; ``_``-prefixed
#: staging extras are deliberately excluded — they are transport metadata,
#: not semantics)
AUDIT_ROW_KEYS = ("src", "dst", "sport", "dport", "proto", "tcp_flags",
                  "is_v6", "ep_slot", "direction", "http_method",
                  "http_path")

#: detail records retained per auditor (memory bound; the flight recorder
#: bundle carries the tail)
MAX_MISMATCH_RECORDS = 16


def _ct_delta(allow: bool, status: int, create: bool) -> str:
    """The CT mutation this verdict implies — what the device table must
    have done for this row. Reported with a mismatch as its conntrack
    consequence: the datapath does not expose its per-row mutation
    decision, so the delta is DERIVED from (allow, status), never an
    independent bit-compare — it diverges exactly when ``allow`` does,
    and then tells the operator which table tear the wrong verdict
    caused (a phantom create, or a dropped update)."""
    if not allow:
        return "none"
    if status == C.CTStatus.NEW:
        return "create" if create else "none"
    return "update"


class _Capture:
    """One sampled finalized batch, frozen for replay."""

    __slots__ = ("rows", "out", "snap", "now", "shard", "wire", "n_rows",
                 "t_mono", "corrupted")

    def __init__(self, rows, out, snap, now, shard, wire, n_rows, corrupted):
        self.rows = rows            # {col: np.ndarray[k]} copied valid rows
        self.out = out              # {col: np.ndarray[k]} captured verdicts
        self.snap = snap            # the PolicySnapshot classified against
        self.now = now
        self.shard = shard          # np.ndarray[k] flow-shard per row (or None)
        self.wire = wire            # wire-format tag ("fake"/"v4"/"wide"/"l7")
        self.n_rows = n_rows        # valid rows in the source batch
        self.t_mono = time.monotonic()
        self.corrupted = corrupted  # audit.corrupt fault flipped the bits


class ShadowAuditor:
    """Counter-sampled capture + background oracle replay; see module doc.

    Constructed once per engine. ``maybe_capture`` is the serving-path
    entry (never raises); ``step`` is the controller body (replays pending
    captures, bounded by ``budget``)."""

    def __init__(self, *, sample_rate: float = 1 / 64,
                 pool_batches: int = 8, max_rows: int = 512,
                 n_shards: int = 1,
                 metrics: Optional[Metrics] = None,
                 tracer: Optional[Tracer] = None,
                 on_mismatch: Optional[Callable[[Dict], None]] = None):
        if pool_batches < 1 or max_rows < 1:
            raise ValueError("pool_batches and max_rows must be >= 1")
        self.metrics = metrics if metrics is not None else Metrics()
        self.tracer = tracer if tracer is not None else TRACER
        self.on_mismatch = on_mismatch
        self._pool = pool_batches
        self._max_rows = max_rows
        self._n_shards = n_shards
        self._lock = threading.Lock()
        self._pending: Deque[_Capture] = deque()
        self._events = itertools.count()
        self.configure(sample_rate=sample_rate)
        # replay-side oracle cache: keyed by snapshot identity, exactly the
        # FakeDatapath revision-fencing discipline — a capture replays
        # against the snapshot it classified under, never a newer one
        self._oracle = None
        self._oracle_snap = None

        # stats (reads via stats(); writes under self._lock)
        self.captured_batches = 0
        self.checked_rows = 0
        self.checked_batches = 0
        self.mismatched_rows = 0
        self.mismatched_batches = 0
        self.skipped_batches = 0
        self.capture_errors = 0
        self.replay_errors = 0
        self.last_mismatch_revision: Optional[int] = None
        self.mismatches: Deque[Dict] = deque(maxlen=MAX_MISMATCH_RECORDS)

    # -- configuration -------------------------------------------------------
    def configure(self, sample_rate: Optional[float] = None) -> None:
        if sample_rate is not None:
            if sample_rate <= 0:
                self._every = 0
            elif sample_rate >= 1.0:
                self._every = 1
            else:
                self._every = max(1, round(1.0 / sample_rate))

    @property
    def sample_rate(self) -> float:
        return 0.0 if self._every == 0 else 1.0 / self._every

    # -- serving-path side (never raises) ------------------------------------
    def maybe_capture(self, batch: Dict[str, np.ndarray],
                      out: Dict[str, np.ndarray], snap, now: int,
                      steered: bool = False) -> None:
        """Counter-sampled capture of one finalized batch. The unsampled
        path pays one counter draw + a modulo; the sampled path copies the
        valid rows (bounded by ``max_rows``) into the replay pool.
        ``steered``: the batch is in the sharded pipeline's steered
        geometry (row → flow shard = row // seg_cap), which is what makes
        per-shard mismatch attribution meaningful. Any internal failure is
        counted, never propagated — the auditor can never take the
        serving path down with it."""
        every = self._every
        if every == 0:
            return
        n = next(self._events)
        if every != 1 and n % every:
            return
        try:
            self._capture(batch, out, snap, now, steered)
        except Exception:   # noqa: BLE001 — the serving path is sacred
            with self._lock:
                self.capture_errors += 1
                errs = self.capture_errors
            self.metrics.inc_counter("parity_audit_capture_errors_total")
            if errs <= 3 or errs % 100 == 0:
                log.exception("audit capture failed (%d); serving "
                              "unaffected", errs)

    def _capture(self, batch, out, snap, now, steered: bool) -> None:
        with self._lock:
            if len(self._pending) >= self._pool:
                # the replay side is lagging (or wedged/dead): shed the
                # capture, never the batch — skipped accounting is the
                # proof the auditor was saturated, not silently idle
                self.skipped_batches += 1
                self.metrics.inc_counter("parity_audit_skipped_total")
                return
        valid = np.asarray(batch["valid"])
        idx = np.nonzero(valid)[0]
        if idx.size == 0:
            return
        if idx.size > self._max_rows:
            idx = idx[: self._max_rows]   # deterministic prefix, bounded copy
        rows = {k: np.asarray(batch[k])[idx].copy()
                for k in AUDIT_ROW_KEYS if k in batch}
        out_rows = {k: np.asarray(out[k])[idx].copy()
                    for k in AUDIT_OUT_KEYS if k in out}
        # the corruption drill: an armed ``audit.corrupt`` point flips the
        # captured allow bits — a stand-in for a datapath/kernels bug the
        # auditor exists to catch (the capture is a copy; live verdicts
        # are untouched)
        corrupted = False
        try:
            FAULTS.fire("audit.corrupt")
        except FaultInjected:
            out_rows["allow"] = ~out_rows["allow"]
            corrupted = True
        shard = None
        if steered and self._n_shards > 1:
            # a steered bucket's row i lives in segment i // seg_cap — the
            # per-chip attribution for mismatch labels (sync/control-plane
            # batches are NOT in steered geometry; they capture shard-less)
            seg = max(1, valid.shape[0] // self._n_shards)
            shard = (idx // seg).astype(np.int32)
        wire = "wide" if bool(rows.get("is_v6", np.False_).any()) else "v4"
        if bool((rows["http_method"] != C.HTTP_METHOD_ANY).any()
                or rows["http_path"].any()):
            wire = "l7"
        cap = _Capture(rows, out_rows, snap, now, shard, wire,
                       int(idx.size), corrupted)
        with self._lock:
            if len(self._pending) >= self._pool:
                self.skipped_batches += 1
                self.metrics.inc_counter("parity_audit_skipped_total")
                return
            self._pending.append(cap)
            self.captured_batches += 1
        self.metrics.inc_counter("parity_audit_captured_total")
        self.metrics.set_gauge("parity_audit_pending", len(self._pending))

    # -- replay side (background controller) ---------------------------------
    def step(self, budget: Optional[int] = None) -> Dict:
        """Replay pending captures against the oracle (the ``parity-audit``
        controller body). Bounded by ``budget`` batches (None = drain).
        Returns a summary dict; replay failures are counted + logged and
        never abort the sweep."""
        replayed = mismatched = 0
        while budget is None or replayed < budget:
            with self._lock:
                cap = self._pending.popleft() if self._pending else None
            if cap is None:
                break
            try:
                with self.tracer.span(self.tracer.current(), "audit.replay",
                                      rows=cap.n_rows):
                    mismatched += self._replay(cap)
            except Exception:   # noqa: BLE001 — supervised degradation
                with self._lock:
                    self.replay_errors += 1
                    errs = self.replay_errors
                self.metrics.inc_counter("parity_audit_replay_errors_total")
                if errs <= 3 or errs % 100 == 0:
                    log.exception("audit replay failed (%d)", errs)
            replayed += 1
        self.metrics.set_gauge("parity_audit_pending", len(self._pending))
        return {"replayed": replayed, "mismatched": mismatched,
                "pending": len(self._pending)}

    def _oracle_for(self, snap):
        from oracle import Oracle
        if self._oracle is None or self._oracle_snap is not snap:
            # the shared snapshot→oracle construction (the fake datapath
            # uses the same classmethod); the CT table stays empty — it is
            # never probed, replay() takes the captured status instead
            self._oracle = Oracle.for_snapshot(snap)
            self._oracle_snap = snap
        return self._oracle

    def _replay(self, cap: _Capture) -> int:
        from cilium_tpu.runtime.datapath import _records_from_batch
        oracle = self._oracle_for(cap.snap)
        rows = dict(cap.rows)
        rows["valid"] = np.ones((cap.n_rows,), dtype=bool)
        records = _records_from_batch(rows, cap.snap.ep_ids)
        out = cap.out
        bad_rows: List[Dict] = []
        for i, p in enumerate(records):
            got_allow = bool(out["allow"][i])
            got_status = int(out["status"][i])
            if p is None or p.ep_id == -1:
                # slot out of range for this snapshot: fail-closed upstream
                # should have invalidated the row; audit it as must-deny
                if got_allow:
                    bad_rows.append({"row": i, "field": "allow",
                                     "want": False, "got": True,
                                     "why": "unknown endpoint slot"})
                continue
            got_ct_full = bool(out["ct_full"][i]) if "ct_full" in out \
                else False
            verdict, create = oracle.replay(p, got_status,
                                            ct_full=got_ct_full)
            diffs = {}
            if bool(verdict.allow) != got_allow:
                diffs["allow"] = (bool(verdict.allow), got_allow)
            if int(verdict.drop_reason) != int(out["reason"][i]):
                diffs["reason"] = (int(verdict.drop_reason),
                                   int(out["reason"][i]))
            if int(verdict.remote_identity) != int(out["remote_identity"][i]):
                diffs["remote_identity"] = (int(verdict.remote_identity),
                                            int(out["remote_identity"][i]))
            if "redirect" in out and \
                    bool(verdict.redirect) != bool(out["redirect"][i]):
                diffs["redirect"] = (bool(verdict.redirect),
                                     bool(out["redirect"][i]))
            if "svc" in out and bool(verdict.svc) != bool(out["svc"][i]):
                diffs["svc"] = (bool(verdict.svc), bool(out["svc"][i]))
            if verdict.svc and "nat_dst" in out:
                want_nat = np.frombuffer(verdict.nat_dst, dtype=">u4")
                if not np.array_equal(want_nat,
                                      out["nat_dst"][i].astype(">u4")) \
                        or int(verdict.nat_dport) != int(out["nat_dport"][i]):
                    diffs["nat"] = ((want_nat.tolist(),
                                     int(verdict.nat_dport)),
                                    (out["nat_dst"][i].tolist(),
                                     int(out["nat_dport"][i])))
            # CT-delta annotation (see _ct_delta): the mutation the
            # replayed verdict demands vs the one the captured verdict
            # implies — only ever differs alongside an allow diff, where
            # it names the resulting table tear
            want_delta = _ct_delta(bool(verdict.allow), got_status, create)
            got_delta = _ct_delta(got_allow, got_status, True)
            if want_delta != got_delta:
                diffs["ct_delta"] = (want_delta, got_delta)
            # provenance columns (ISSUE 11): the replayed oracle names the
            # winning rule/prefix from the same compiled tables — right
            # verdict for the wrong reason still mismatches
            if "matched_rule" in out and \
                    int(verdict.matched_rule) != int(out["matched_rule"][i]):
                diffs["matched_rule"] = (int(verdict.matched_rule),
                                         int(out["matched_rule"][i]))
            if "lpm_prefix" in out and \
                    int(verdict.lpm_prefix) != int(out["lpm_prefix"][i]):
                diffs["lpm_prefix"] = (int(verdict.lpm_prefix),
                                       int(out["lpm_prefix"][i]))
            # ct_state_pre is an alias of the captured probe class by
            # contract — structural, like rnat below
            if "ct_state_pre" in out and \
                    int(out["ct_state_pre"][i]) != got_status:
                diffs["ct_state_pre"] = (got_status,
                                         int(out["ct_state_pre"][i]))
            # structural rnat check: reply un-DNAT without a REPLY CT hit
            # is impossible by construction
            if "rnat" in out and bool(out["rnat"][i]) \
                    and got_status != C.CTStatus.REPLY:
                diffs["rnat"] = ("status==REPLY required", got_status)
            if diffs:
                bad_rows.append({
                    "row": i,
                    "diffs": {k: {"want": w, "got": g}
                              for k, (w, g) in diffs.items()},
                    "shard": int(cap.shard[i]) if cap.shard is not None
                    else 0,
                    "flow": {
                        "sport": int(p.src_port), "dport": int(p.dst_port),
                        "proto": int(p.proto), "ep_id": int(p.ep_id),
                        "direction": int(p.direction),
                        "status": got_status,
                    },
                })
        rev = int(cap.snap.revision)
        with self._lock:
            self.checked_batches += 1
            self.checked_rows += cap.n_rows
        self.metrics.inc_counter("parity_audit_checked_total", cap.n_rows)
        if not bad_rows:
            return 0
        detail = {
            "revision": rev,
            "now": cap.now,
            "wire": cap.wire,
            "rows_checked": cap.n_rows,
            "rows_mismatched": len(bad_rows),
            "shards": sorted({r["shard"] for r in bad_rows}),
            "corrupt_injected": cap.corrupted,
            "rows": bad_rows[:8],        # bounded detail; counts carry the rest
            "age_s": round(time.monotonic() - cap.t_mono, 3),
        }
        with self._lock:
            self.mismatched_rows += len(bad_rows)
            self.mismatched_batches += 1
            self.last_mismatch_revision = rev
            self.mismatches.append(detail)
        # one labeled series per offending revision: summing over labels in
        # PromQL gives the total, and the label answers "which policy world
        # diverged" without a bundle fetch
        self.metrics.inc_counter(
            f'parity_audit_mismatched_total{{revision="{rev}"}}',
            len(bad_rows))
        self.tracer.event("audit.mismatch", revision=rev,
                          rows=len(bad_rows), wire=cap.wire)
        log.error("PARITY MISMATCH: %d/%d rows diverged from the shadow "
                  "oracle at revision %d (wire=%s)", len(bad_rows),
                  cap.n_rows, rev, cap.wire)
        if self.on_mismatch is not None:
            try:
                self.on_mismatch(detail)
            except Exception:   # noqa: BLE001 — the sink must not kill audit
                log.exception("audit on_mismatch sink failed")
        return len(bad_rows)

    def rearm(self) -> None:
        """Operator re-arm after a mismatch was investigated (the debug
        bundle's ``--clear`` path): zero the mismatch state so health()
        returns to OK and the NEXT divergence degrades it again.
        checked/skipped accounting is deliberately kept — it is history,
        not an alarm."""
        with self._lock:
            self.mismatched_rows = 0
            self.mismatched_batches = 0
            self.last_mismatch_revision = None
            self.mismatches.clear()

    # -- introspection -------------------------------------------------------
    @property
    def healthy(self) -> bool:
        return self.mismatched_rows == 0

    def stats(self) -> Dict:
        with self._lock:
            return {
                "sample_rate": self.sample_rate,
                "captured_batches": self.captured_batches,
                "checked_batches": self.checked_batches,
                "checked_rows": self.checked_rows,
                "mismatched_batches": self.mismatched_batches,
                "mismatched_rows": self.mismatched_rows,
                "skipped_batches": self.skipped_batches,
                "capture_errors": self.capture_errors,
                "replay_errors": self.replay_errors,
                "pending": len(self._pending),
                "pool_batches": self._pool,
                "last_mismatch_revision": self.last_mismatch_revision,
            }
