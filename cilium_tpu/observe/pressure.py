"""Resource pressure ledger: one registry for every bounded structure.

Upstream Cilium exports ``cilium_bpf_map_pressure`` because the datapath's
failure modes are *capacity* failures — a full CT map or policy map drops
traffic long before CPU saturates, and the PR 10 DDoS work proved the same
holds here (CT_FULL, steer_overflow, admission sheds). Occupancy accounting
was ad-hoc before this module: ``ct_occupancy`` a fraction,
``pipeline_staging_free`` an absolute, trace/flowlog/blackbox rings wrapping
silently, wire pools and patch budgets reporting nothing. The ledger makes
"which bounded structure runs out first, and when" a first-class question:

- **Registration.** Engine-side *providers* (one callable per subsystem)
  return ``{resource: (capacity, occupancy[, pressure])}`` samples;
  :meth:`ResourceLedger.poll` sweeps them on the ``resource-ledger``
  controller's cadence (or a deterministic driver's logical clock). A
  provider that raises is counted and skipped — the ledger can observe a
  dying subsystem without joining it.
- **One labeled family.** Every resource exports
  ``ciliumtpu_resource_{occupancy,capacity,high_water,pressure}{resource=}``
  (+ ``resource_eta_seconds`` while a finite forecast exists), replacing
  the per-subsystem gauge zoo for capacity questions. Pressure is
  occupancy/capacity unless the provider supplies the canonical fraction
  itself — the CT provider hands through the ``ct_occupancy`` gauge
  verbatim, so the two surfaces can never disagree (the cfg6 bench gates
  on exact equality).
- **Time-to-exhaustion.** Per resource, a bounded window of (t, occupancy)
  samples yields a growth rate; ``eta_s = (capacity - occupancy) / rate``
  while the resource is growing. An ETA under ``eta_warn_s`` fires one
  ``resource-pressure`` flight-recorder event (latched — re-arms when the
  forecast clears); a resource that *then actually exhausts* (pressure ≥
  1.0) fires ``resource-exhaustion``, a strict-freeze kind — forecasted
  and ignored is the anomaly, commanded shedding is not.
- **Deregistration sweeps gauges.** A departed resource (pipeline closed,
  engine stopped, mesh resized) drops its whole label family via
  ``Metrics.drop_gauge`` — the same sweep departed clustermesh peers get —
  so a dead structure can never keep exporting a healthy-looking reading.

Consumers: ``Engine.health()`` folds pressured resources in as the
``RESOURCE_PRESSURE`` detail, the overload ladder takes ``max_pressure``
(CT excluded — it is already the ladder's own signal) as its fourth latch,
``GET /v1/resources`` + ``cilium-tpu top`` render the live table, and the
cfg6 bench artifact carries per-resource high-water + the HBM ledger.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

log = logging.getLogger("cilium_tpu.pressure")

#: resources excluded from the overload ladder's resource term: the CT
#: table and the admission queue are already the ladder's own signals
#: (double-lighting one cause would double its severity), and the audit
#: pool saturates by design under sampling-1.0 drills — the fourth latch
#: must mean "some OTHER bounded structure is about to fail".
LADDER_EXCLUDE = frozenset(("ct_table", "admission_queue", "audit_pool"))

#: gauge families every resource exports (the ``resource=`` label rides in
#: the name, runtime/metrics.py renders one TYPE line per base family)
GAUGE_FAMILIES = ("resource_occupancy", "resource_capacity",
                  "resource_high_water", "resource_pressure",
                  "resource_eta_seconds")

#: a provider returns {resource: (capacity, occupancy)} or
#: {resource: (capacity, occupancy, pressure)} — the 3-tuple form hands
#: through a canonical pressure fraction (the CT provider's ct_occupancy)
Sample = Tuple
Provider = Callable[[], Optional[Dict[str, Sample]]]


class _ResourceState:
    __slots__ = ("capacity", "occupancy", "pressure", "high_water",
                 "window", "eta_s", "forecast_latched", "exhaust_fired",
                 "provider", "last_poll")

    def __init__(self, provider: str, window: int):
        self.capacity = 0.0
        self.occupancy = 0.0
        self.pressure = 0.0
        self.high_water = 0.0
        self.window: deque = deque(maxlen=max(2, window))
        self.eta_s: Optional[float] = None
        self.forecast_latched = False      # resource-pressure event out
        self.exhaust_fired = False         # strict freeze already fired
        self.provider = provider
        self.last_poll = 0


class ResourceLedger:
    """Central (resource, capacity, occupancy, high_water) registry with
    windowed time-to-exhaustion forecasting. Thread-safe; ``poll`` is the
    only sampler (providers are swept, never push)."""

    def __init__(self, metrics=None, *, window: int = 16,
                 warn: float = 0.8, crit: float = 0.95,
                 eta_warn_s: float = 120.0,
                 event_sink: Optional[Callable] = None):
        if not 0.0 < warn < crit <= 1.0:
            raise ValueError("need 0 < warn < crit <= 1")
        if window < 2:
            raise ValueError("eta window must hold >= 2 samples")
        if eta_warn_s <= 0:
            raise ValueError("eta_warn_s must be > 0")
        self.metrics = metrics
        self.warn = warn
        self.crit = crit
        self.eta_warn_s = eta_warn_s
        self._window = window
        #: flight-recorder hook (Engine wires blackbox.record_event);
        #: called OUTSIDE the ledger lock, exceptions swallowed
        self._event_sink = event_sink
        self._lock = threading.Lock()
        self._providers: Dict[str, Provider] = {}
        self._state: Dict[str, _ResourceState] = {}
        self.polls_total = 0
        self.provider_errors_total = 0
        self.forecasts_total = 0
        self.exhaustions_total = 0

    # -- registration --------------------------------------------------------
    def register(self, name: str, provider: Provider) -> None:
        """Attach a provider. Re-registering a name replaces the callable
        (an engine-restarted pipeline keeps its resource history)."""
        with self._lock:
            self._providers[name] = provider

    def deregister(self, name: str) -> List[str]:
        """Detach a provider and sweep every resource it owned: state
        dropped AND the whole exported label family removed via
        ``Metrics.drop_gauge`` — a frozen last value would keep exporting
        a healthy-looking reading for a dead structure (the departed-
        clustermesh-peer lesson). Returns the swept resource names."""
        with self._lock:
            self._providers.pop(name, None)
            gone = [r for r, st in self._state.items()
                    if st.provider == name]
            for r in gone:
                del self._state[r]
            # drop the gauges INSIDE the ledger lock (metrics locks are
            # leaves): a concurrent _fold for the same resource serializes
            # against this — it can never re-pin a swept family, because
            # it re-checks provider registration under the same lock
            self._drop_families_locked(gone)
        return gone

    def _drop_families_locked(self, resources: Iterable[str]) -> None:
        if self.metrics is None:
            return
        for r in resources:
            for fam in GAUGE_FAMILIES:
                self.metrics.drop_gauge(f'{fam}{{resource="{r}"}}')

    def deregister_all(self) -> List[str]:
        """Engine shutdown: sweep everything (register/deregister symmetry
        under engine restart is what the tier-1 restart test pins)."""
        with self._lock:
            names = list(self._providers)
        gone: List[str] = []
        for n in names:
            gone.extend(self.deregister(n))
        return gone

    # -- sampling ------------------------------------------------------------
    def poll(self, now: Optional[float] = None) -> Dict:
        """One ledger sweep. ``now`` (seconds, any monotone clock) defaults
        to ``time.monotonic()``; deterministic drivers (the cfg6 bench, the
        pressure soak) pass their logical clock so ETA math is replayable.
        Returns the full report (the ``/v1/resources`` document)."""
        if now is None:
            now = time.monotonic()
        events: List[Tuple[str, Dict]] = []
        with self._lock:
            self.polls_total += 1
            tick = self.polls_total
            providers = list(self._providers.items())
        ok_providers = set()
        for pname, provider in providers:
            try:
                samples = provider()
            except Exception:   # noqa: BLE001 — observe, never join, a
                log.exception("resource provider %r failed", pname)  # dying
                with self._lock:                                     # subsys
                    self.provider_errors_total += 1
                continue
            ok_providers.add(pname)
            if not samples:
                continue
            for rname, sample in samples.items():
                events.extend(self._fold(pname, rname, sample, now))
        # staleness sweep: a resource its (healthy) provider stopped
        # reporting — the pipeline closed, the incremental compiler was
        # discarded — is DEPARTED, and its frozen last pressure must not
        # keep the health detail / ladder latch lit on a healthy engine.
        # A provider that ERRORED this poll sweeps nothing (a transient
        # failure is not a departure — its last good readings stand).
        with self._lock:
            stale = [r for r, st in self._state.items()
                     if st.provider in ok_providers and st.last_poll < tick]
            for r in stale:
                del self._state[r]
            self._drop_families_locked(stale)
        report = self.report()
        for kind, attrs in events:
            self._emit(kind, attrs)
        return report

    def _fold(self, pname: str, rname: str, sample: Sample,
              now: float) -> List[Tuple[str, Dict]]:
        capacity = float(sample[0])
        occupancy = float(sample[1])
        explicit_p = float(sample[2]) if len(sample) > 2 else None
        events: List[Tuple[str, Dict]] = []
        with self._lock:
            if pname not in self._providers:
                # the provider was deregistered between its sample call
                # and this fold: folding would resurrect a swept resource
                # no future poll could ever clean up again
                return events
            st = self._state.get(rname)
            if st is None:
                st = self._state[rname] = _ResourceState(pname,
                                                         self._window)
            st.provider = pname
            st.capacity = capacity
            st.occupancy = occupancy
            # the provider's canonical fraction wins (the CT provider hands
            # the ct_occupancy gauge through VERBATIM — the bench gates on
            # the two surfaces never disagreeing); otherwise derive
            st.pressure = explicit_p if explicit_p is not None \
                else occupancy / capacity if capacity > 0 else 0.0
            st.high_water = max(st.high_water, occupancy)
            st.window.append((now, occupancy))
            st.eta_s = self._eta_locked(st)
            st.last_poll = self.polls_total
            # forecast latch: one resource-pressure event per excursion;
            # re-arm only once the forecast has genuinely cleared
            if st.eta_s is not None and st.eta_s <= self.eta_warn_s \
                    and st.pressure >= self.warn:
                if not st.forecast_latched:
                    st.forecast_latched = True
                    self.forecasts_total += 1
                    events.append(("resource-pressure", {
                        "resource": rname,
                        "eta_s": round(st.eta_s, 1),
                        "occupancy": round(occupancy, 2),
                        "capacity": capacity,
                        "pressure": round(st.pressure, 4)}))
            elif st.forecast_latched and st.pressure < self.warn:
                # pressure-based hysteresis: the excursion is over once the
                # resource is back under warn — a fresh climb is a fresh
                # forecast (stale window samples must not pin the latch)
                st.forecast_latched = False
                st.exhaust_fired = False
            # forecast-then-exhaustion is the strict-freeze anomaly: the
            # ledger SAID this would run out and then it did — commanded
            # shedding narrates, an ignored forecast freezes evidence
            if st.forecast_latched and not st.exhaust_fired \
                    and st.pressure >= 1.0:
                st.exhaust_fired = True
                self.exhaustions_total += 1
                events.append(("resource-exhaustion", {
                    "resource": rname,
                    "occupancy": round(occupancy, 2),
                    "capacity": capacity,
                    "high_water": round(st.high_water, 2)}))
            # export INSIDE the ledger lock (metrics locks are leaves):
            # a concurrent deregister's family sweep serializes against
            # this write instead of racing it, and the exported five
            # values are always one poll's consistent snapshot
            self._export_locked(rname, st)
        return events

    @staticmethod
    def _eta_locked(st: _ResourceState) -> Optional[float]:
        """Windowed growth rate → seconds until occupancy == capacity.
        None while the resource is flat/shrinking or already full (an
        exhausted resource has no *forecast* — its pressure says it all)."""
        if len(st.window) < 2:
            return None
        t0, o0 = st.window[0]
        t1, o1 = st.window[-1]
        if t1 <= t0:
            return None
        rate = (o1 - o0) / (t1 - t0)
        headroom = st.capacity - o1
        if rate <= 0 or headroom <= 0:
            return None
        return headroom / rate

    def _export_locked(self, rname: str, st: _ResourceState) -> None:
        if self.metrics is None:
            return
        lbl = f'{{resource="{rname}"}}'
        values = {
            f"resource_occupancy{lbl}": st.occupancy,
            f"resource_capacity{lbl}": st.capacity,
            f"resource_high_water{lbl}": st.high_water,
            f"resource_pressure{lbl}": round(st.pressure, 6),
        }
        if st.eta_s is not None:
            values[f"resource_eta_seconds{lbl}"] = round(st.eta_s, 1)
            drop = ()
        else:
            # a stale finite ETA is a false alarm pinned forever — sweep
            # the series the moment the forecast clears
            drop = (f"resource_eta_seconds{lbl}",)
        # one lock acquisition for the whole family (the <2% polling
        # attestation is the budget this spends)
        self.metrics.set_gauges(values, drop=drop)

    def _emit(self, kind: str, attrs: Dict) -> None:
        if self._event_sink is None:
            return
        try:
            self._event_sink(kind, **attrs)
        except Exception:   # noqa: BLE001
            log.exception("resource event sink failed")

    # -- read side -----------------------------------------------------------
    def resources(self) -> List[str]:
        with self._lock:
            return sorted(self._state)

    def max_pressure(self, exclude: Iterable[str] = ()) -> float:
        """The worst pressure fraction across registered resources (the
        overload ladder's fourth latch signal; CT is excluded there — it
        is already the ladder's own signal)."""
        ex = frozenset(exclude)
        with self._lock:
            return max((st.pressure for r, st in self._state.items()
                        if r not in ex), default=0.0)

    def pressured(self, threshold: Optional[float] = None) -> List[str]:
        thr = self.warn if threshold is None else threshold
        with self._lock:
            return sorted(r for r, st in self._state.items()
                          if st.pressure >= thr)

    def report(self) -> Dict:
        """The ``/v1/resources`` / ``cilium-tpu top`` document: one row per
        resource plus the ledger's own accounting."""
        with self._lock:
            rows = {
                r: {
                    "capacity": st.capacity,
                    "occupancy": st.occupancy,
                    "pressure": round(st.pressure, 6),
                    "high_water": st.high_water,
                    "eta_s": round(st.eta_s, 1)
                    if st.eta_s is not None else None,
                    "forecast": st.forecast_latched,
                    "provider": st.provider,
                } for r, st in sorted(self._state.items())
            }
            max_p = max((st.pressure for st in self._state.values()),
                        default=0.0)
        return {
            "resources": rows,
            "max_pressure": round(max_p, 6),
            "pressured": [r for r, d in rows.items()
                          if d["pressure"] >= self.warn],
            "thresholds": {"warn": self.warn, "crit": self.crit,
                           "eta_warn_s": self.eta_warn_s},
            "polls_total": self.polls_total,
            "provider_errors_total": self.provider_errors_total,
            "forecasts_total": self.forecasts_total,
            "exhaustions_total": self.exhaustions_total,
        }

    def status(self) -> Dict:
        """The small health-surface summary Engine.health() folds in."""
        with self._lock:
            pressured = sorted(
                (r for r, st in self._state.items()
                 if st.pressure >= self.warn),
                key=lambda r: -self._state[r].pressure)
            max_p = max((st.pressure for st in self._state.values()),
                        default=0.0)
            etas = [(r, st.eta_s) for r, st in self._state.items()
                    if st.eta_s is not None]
            crit = any(st.pressure >= self.crit
                       for st in self._state.values())
        min_eta = min(etas, key=lambda kv: kv[1]) if etas else None
        return {
            "pressured": pressured,
            "max_pressure": round(max_p, 6),
            "critical": crit,
            "min_eta": ({"resource": min_eta[0],
                         "eta_s": round(min_eta[1], 1)}
                        if min_eta is not None else None),
            "registered": len(self._state),
        }
