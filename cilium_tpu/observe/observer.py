"""Vectorized flow-observe engine (the Hubble ``Observe()``/``FlowFilter``
analog — SURVEY.md §3.6: parser → Flow record → ``Observe()`` streams).

The flowlog ring is columnar (runtime/flowlog.py); this module filters it
the same way the datapath classifies — numpy mask composition over whole
columns, no per-row Python until a row has already matched. A query is a
pair of filter lists exactly like Hubble's API: the **allowlist** ORs its
filters, the **denylist** subtracts, every field inside one filter ANDs.

Fields mirror Hubble's FlowFilter where this datapath has the concept:
verdict, drop reason, endpoint, (remote) identity, protocol, ports, CIDR
on either/src/dst address, direction — plus the ISSUE 11 provenance field
``matched_rule`` (the resolved policy-cell coordinate), so "show me every
flow this rule decided" is a first-class query.

Two read modes:

- **one-shot** (``observe``): filter the whole retained ring, newest-last,
  bounded by ``last``.
- **follow** (``observe(since=cursor)`` / ``FollowCursor``): seq-cursor
  polling with *explicit gap accounting* — when the ring wraps past the
  cursor the response carries a structured ``gap`` record (count of lost
  rows, resume seq) and the ``flowlog_follow_gaps_total`` counter moves;
  a follower can never silently lose records.

``observe/relay.py`` fans N of these in (the hubble-relay analog).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import lru_cache
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from cilium_tpu.runtime.flowlog import FlowLog, render_flow
from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import parse_prefix

#: canonical-prefix parse, cached: follow-mode polls re-evaluate the same
#: armed filters every cadence tick — string parsing must not be per-poll
_parsed_prefix = lru_cache(maxsize=1024)(parse_prefix)


@lru_cache(maxsize=1024)
def _cidr_parts(cidr: str) -> Tuple[np.ndarray, np.ndarray]:
    """cidr → (per-word netmask [4] uint32, masked net words [4] uint32),
    cached so a poll pays three vector ops per armed prefix, not the
    parse + mask construction."""
    net16, plen, _v6 = _parsed_prefix(cidr)
    net = np.frombuffer(net16, dtype=">u4").astype(np.uint32)
    bits = np.clip(plen - np.arange(4) * 32, 0, 32)
    maskw = (((1 << bits) - 1) << (32 - bits)).astype(np.uint32)
    netm = net & maskw
    maskw.flags.writeable = False
    netm.flags.writeable = False
    return maskw, netm


def _isin(col: np.ndarray, vals: Tuple[int, ...]) -> np.ndarray:
    """np.isin with the single-value fast path (the common filter shape —
    one verdict, one port, one rule — where isin's sort machinery costs
    more than the compare)."""
    if len(vals) == 1:
        return col == vals[0]
    return np.isin(col, vals)

_VERDICTS = ("FORWARDED", "DROPPED")

#: name → drop-reason int (accepts ints too)
_REASON_IDS = {r.name: int(r) for r in C.DropReason}
_PROTO_IDS = {v.upper(): k for k, v in C.PROTO_NAMES.items()}
_DIR_IDS = {"egress": C.DIR_EGRESS, "ingress": C.DIR_INGRESS}


def _cidr_cols_mask(words: np.ndarray, cidr: str) -> np.ndarray:
    """words [k,4] uint32 (16B normalized, v4-mapped) ∈ cidr — the
    vectorized mirror of model.ipcache's per-prefix compare."""
    maskw, netm = _cidr_parts(cidr)
    return ((words & maskw) == netm).all(axis=1)


@dataclass(frozen=True)
class FlowFilter:
    """One Hubble-shaped filter: every set field must match (AND); list
    fields match any element (OR within the field). Values are
    pre-normalized to ints/canonical prefixes by :func:`parse_filters` or
    the constructor's callers."""
    verdict: Optional[str] = None            # FORWARDED | DROPPED
    reasons: Tuple[int, ...] = ()            # DropReason ints
    endpoints: Tuple[int, ...] = ()          # local endpoint ids
    identities: Tuple[int, ...] = ()         # remote security identities
    protos: Tuple[int, ...] = ()             # IP protocol numbers
    ports: Tuple[int, ...] = ()              # src OR dst port
    sports: Tuple[int, ...] = ()
    dports: Tuple[int, ...] = ()
    cidrs: Tuple[str, ...] = ()              # src OR dst in any
    src_cidrs: Tuple[str, ...] = ()
    dst_cidrs: Tuple[str, ...] = ()
    rules: Tuple[int, ...] = ()              # matched_rule coordinates
    direction: Optional[int] = None

    def mask(self, cols: Dict[str, np.ndarray]) -> np.ndarray:
        # lazy AND chain: the first field mask IS m (no ones() alloc +
        # extra & per poll tick — this runs per armed filter per cadence)
        m = None

        def land(x):
            nonlocal m
            m = x if m is None else m & x

        if self.verdict is not None:
            land(cols["allow"] if self.verdict == "FORWARDED"
                 else ~cols["allow"])
        if self.reasons:
            land(_isin(cols["reason"], self.reasons))
        if self.endpoints:
            land(_isin(cols["endpoint_id"], self.endpoints))
        if self.identities:
            land(_isin(cols["remote_identity"], self.identities))
        if self.protos:
            land(_isin(cols["proto"], self.protos))
        if self.ports:
            land(_isin(cols["sport"], self.ports)
                 | _isin(cols["dport"], self.ports))
        if self.sports:
            land(_isin(cols["sport"], self.sports))
        if self.dports:
            land(_isin(cols["dport"], self.dports))
        if self.rules:
            land(_isin(cols["matched_rule"], self.rules))
        if self.direction is not None:
            land(cols["direction"] == self.direction)
        for group, which in ((self.cidrs, "any"), (self.src_cidrs, "src"),
                             (self.dst_cidrs, "dst")):
            if not group:
                continue
            gm = None
            for cidr in group:
                if which in ("any", "src"):
                    cm = _cidr_cols_mask(cols["src"], cidr)
                    gm = cm if gm is None else gm | cm
                if which in ("any", "dst"):
                    cm = _cidr_cols_mask(cols["dst"], cidr)
                    gm = cm if gm is None else gm | cm
            land(gm)
        if m is None:
            m = np.ones(cols["seq"].shape[0], dtype=bool)
        return m


def compose_mask(cols: Dict[str, np.ndarray],
                 allow: Sequence[FlowFilter] = (),
                 deny: Sequence[FlowFilter] = ()) -> np.ndarray:
    """Hubble semantics: OR of the allowlist (empty = everything) minus the
    OR of the denylist."""
    if allow:
        m = allow[0].mask(cols)          # the common one-filter query
        for f in allow[1:]:
            m = m | f.mask(cols)
    else:
        m = np.ones(cols["seq"].shape[0], dtype=bool)
    # NON-inplace: a single verdict-only filter's mask IS the snapshot's
    # allow column (the lazy chain returns views when it can) — mutating
    # it here would corrupt the very rows about to be rendered
    for f in deny:
        m = m & ~f.mask(cols)
    return m


def _ints(val, names: Optional[Dict[str, int]] = None) -> Tuple[int, ...]:
    out = []
    for part in str(val).split(","):
        part = part.strip()
        if not part:
            continue
        if names is not None and part.upper() in names:
            out.append(names[part.upper()])
        elif part.lstrip("-").isdigit():
            out.append(int(part))
        else:
            raise ValueError(f"cannot parse {part!r}")
    return tuple(out)


def _verdict(val: str) -> str:
    v = str(val).upper()
    if v not in _VERDICTS:
        raise ValueError(f"verdict must be one of {_VERDICTS}")
    return v


def _cidr_list(val: str) -> Tuple[str, ...]:
    parts = tuple(p for p in str(val).split(",") if p)
    for p in parts:
        _parsed_prefix(p)        # bad CIDR → ValueError here (a 400), not
    return parts                 # a 500 out of mask() at scan time


#: query-param / CLI-flag name → FlowFilter field + value parser
_PARAM_FIELDS = {
    "verdict": ("verdict", _verdict),
    "reason": ("reasons", lambda v: _ints(v, _REASON_IDS)),
    "endpoint": ("endpoints", _ints),
    "identity": ("identities", _ints),
    "proto": ("protos", lambda v: _ints(v, _PROTO_IDS)),
    "port": ("ports", _ints),
    "sport": ("sports", _ints),
    "dport": ("dports", _ints),
    "cidr": ("cidrs", _cidr_list),
    "src_cidr": ("src_cidrs", _cidr_list),
    "dst_cidr": ("dst_cidrs", _cidr_list),
    "rule": ("rules", _ints),
    "direction": ("direction", lambda v: _DIR_IDS[v.lower()]),
}


def parse_filters(params: Dict[str, str]
                  ) -> Tuple[List[FlowFilter], List[FlowFilter]]:
    """Flat query params → (allowlist, denylist). Every recognized key
    contributes to ONE allow filter (AND semantics across params, the
    common CLI case); each ``not_``-prefixed KEY builds its own deny
    filter, so independent exclusions OR (Hubble denylist semantics —
    ``not_verdict=FORWARDED&not_dport=53`` excludes all FORWARDED flows
    AND all dport-53 flows, not just their intersection). Unknown keys
    are ignored (the API route owns its non-filter params like
    last/since/follow)."""
    allow_kw: Dict[str, object] = {}
    deny_pairs: List[Tuple[str, object]] = []
    for key, raw in params.items():
        neg = key.startswith("not_")
        base = key[4:] if neg else key
        spec = _PARAM_FIELDS.get(base)
        if spec is None:
            if neg:
                # a not_-prefixed key is always MEANT as a deny filter —
                # silently dropping a typo'd one would fail open, streaming
                # exactly the flows the operator tried to exclude
                raise ValueError(f"unknown deny filter {key!r}")
            continue
        fld, parse = spec
        try:
            if neg and fld in ("verdict", "direction"):
                # scalar fields don't comma-split in their parser; repeated
                # --not flags accumulate comma-joined, so each part is its
                # own deny filter (deny FORWARDED,DROPPED = deny both)
                deny_pairs.extend((fld, parse(p.strip()))
                                  for p in str(raw).split(",") if p.strip())
            elif neg:
                deny_pairs.append((fld, parse(raw)))
            else:
                allow_kw[fld] = parse(raw)
        except (KeyError, ValueError) as e:
            raise ValueError(f"bad filter {key}={raw!r}: {e}") from None
    allow = [FlowFilter(**allow_kw)] if allow_kw else []
    deny = [FlowFilter(**{fld: val}) for fld, val in deny_pairs]
    return allow, deny


class FlowObserver:
    """Vectorized observe over one flowlog ring; see module docstring."""

    def __init__(self, flowlog: FlowLog, metrics=None):
        self.flowlog = flowlog
        self.metrics = metrics
        self.queries_total = 0
        self.rows_scanned = 0
        self.rows_matched = 0

    def observe(self, allow: Sequence[FlowFilter] = (),
                deny: Sequence[FlowFilter] = (),
                last: int = 0, since: Optional[int] = None,
                limit: int = 4096) -> Dict:
        """One observe pass. ``since`` not None is follow mode (records
        with seq > since, oldest first, explicit gap marker when the ring
        wrapped past the cursor); otherwise one-shot (newest ``last``
        matching records, oldest first). Returns {"flows", "cursor",
        "gap", "matched", "scanned"} — ``cursor`` is the seq to poll from
        next (in follow mode it only advances past rows actually
        RETURNED, so a limit-truncated poll resumes without loss)."""
        follow = since is not None
        cols, oldest, newest = self.flowlog.snapshot_columns(
            since_seq=since if follow else 0)
        scanned = int(cols["seq"].shape[0])
        # one gap contract for every follower (FlowLog.gap_marker): a real
        # cursor the ring wrapped past gets an explicit structured marker
        gap = self.flowlog.gap_marker(since, oldest) if follow else None
        if scanned == 0 and gap is None:
            # idle-poll fast path: nothing new past the cursor
            self.queries_total += 1
            if self.metrics is not None:
                self.metrics.inc_counter("observer_queries_total")
            return {"flows": [], "cursor": int(newest), "gap": None,
                    "matched": 0, "scanned": 0}
        m = compose_mask(cols, allow, deny)
        idx = np.nonzero(m)[0]
        matched = int(idx.size)
        cap = last if (last and not follow) else limit
        truncated = bool(cap and idx.size > cap)
        if truncated:
            # one-shot keeps the newest window; follow keeps the oldest
            # (the cursor advances through the rest on the next poll)
            idx = idx[-cap:] if not follow else idx[:cap]
        flows = [render_flow(cols, int(j)) for j in idx]
        if follow:
            # advance past every row scanned UNLESS truncated — then only
            # past the last returned row, so nothing is skipped
            cursor = int(cols["seq"][idx[-1]]) if truncated and idx.size \
                else int(newest)
        else:
            cursor = int(newest)
        self.queries_total += 1
        self.rows_scanned += scanned
        self.rows_matched += matched
        if self.metrics is not None:
            self.metrics.inc_counter("observer_queries_total")
            self.metrics.inc_counter("observer_rows_scanned_total", scanned)
            self.metrics.inc_counter("observer_rows_matched_total", matched)
        return {"flows": flows, "cursor": cursor, "gap": gap,
                "matched": matched, "scanned": scanned}

    def stats(self) -> Dict:
        return {"queries": self.queries_total,
                "rows_scanned": self.rows_scanned,
                "rows_matched": self.rows_matched,
                "follow_gaps": self.flowlog.follow_gaps,
                "follow_gap_records": self.flowlog.follow_gap_records}


@dataclass
class FollowCursor:
    """Follow-mode convenience: holds the seq cursor and the armed filters;
    each :meth:`poll` returns new matching flows (gap marker included as a
    leading record when the ring wrapped past us)."""
    observer: FlowObserver
    allow: Sequence[FlowFilter] = ()
    deny: Sequence[FlowFilter] = ()
    cursor: int = 0                # seq of the last record consumed
    gaps: int = 0
    dropped: int = 0

    def poll(self, limit: int = 4096) -> List[Dict]:
        res = self.observer.observe(self.allow, self.deny,
                                    since=self.cursor, limit=limit)
        out: List[Dict] = []
        if res["gap"] is not None:
            self.gaps += 1
            self.dropped += res["gap"]["dropped"]
            out.append(res["gap"])
        out.extend(res["flows"])
        self.cursor = res["cursor"]
        return out
