"""Sampled span tracing for the serving path (the host-side half of
SURVEY.md §5 "tracing/profiling"; ``jax.profiler.trace`` covers the device
half, ``metrics.span`` keeps cheap aggregate timers).

Why another mechanism: SpanStat aggregates (count/total/max) cannot answer
"which *stage* made cfg4's p99 3.8x its p50" — that needs per-occurrence
records with a shared trace id across stages, and it must cost ~nothing on
the hot path. So:

- **Sampling is a counter, not an RNG.** ``maybe_sample()`` draws from an
  ``itertools.count`` (atomic under the GIL) and returns a trace id every
  Nth event (N = round(1/sample_rate)); every other event pays one
  ``next()`` + a modulo. Deterministic → tests can assert exact sample
  counts.
- **Fixed-capacity ring.** Spans land in a preallocated ring (drop-oldest);
  recording takes a lock only on the *sampled* path.
- **Trace context is a thread-local.** The pipeline worker (or classify
  caller) enters ``context(trace_id)``; downstream layers (the datapath's
  pack/transfer/compute split) attach spans to whatever trace is current
  without any signature changes across the DatapathBackend boundary.

One process-wide instance (``TRACER``) mirrors the ``FAULTS`` singleton so
instrumentation points need no plumbing; independent ``Tracer`` objects
exist for unit tests.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

#: span tuple layout: (trace_id, name, t0_monotonic, duration_s, attrs|None)
_Span = Tuple[int, str, float, float, Optional[dict]]

DEFAULT_CAPACITY = 4096

#: Per-kernel attribution span names for the classify interior (ROADMAP
#: item 2). A single jit cannot be split from the host, so the serving
#: path's ``datapath.compute`` span carries a ``fused`` attr naming the
#: executor (JITDatapath), while ``bench.py --kernels`` times each stage as
#: its own jitted program under these span names — the artifact's
#: per-kernel p50/p99 all flow through this tracer, same as the pipeline
#: stage split. One name per fused kernel plus the whole interior.
KERNEL_SPAN_LPM = "datapath.kernel.lpm"
KERNEL_SPAN_CT_PROBE = "datapath.kernel.ct_probe"
KERNEL_SPAN_POLICY_L7 = "datapath.kernel.policy_l7"
KERNEL_SPAN_FULL = "datapath.kernel.full_step"
KERNEL_SPANS = (KERNEL_SPAN_LPM, KERNEL_SPAN_CT_PROBE,
                KERNEL_SPAN_POLICY_L7, KERNEL_SPAN_FULL)

#: Live-state fast-path span names (ROADMAP item 3). PATCH_APPLY_SPAN
#: wraps the device-side scatter-apply of a sparse policy delta
#: (JITDatapath.place_patch — the "device-apply" half of a live rule
#: update; the host compile half rides the existing engine.regen.patch
#: span). CT_GC_SPAN wraps one overlapped chunk-sweep enqueue
#: (JITDatapath.sweep_step). bench.py --update-storm reads both out of the
#: tracer summary for the artifact's host/device latency split.
PATCH_APPLY_SPAN = "datapath.patch.apply"
CT_GC_SPAN = "datapath.ct.gc"


class _NullSpan:
    """Shared no-op context for unsampled events (no allocation per call)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _SpanCtx:
    __slots__ = ("_tracer", "_tid", "_name", "_attrs", "_t0")

    def __init__(self, tracer: "Tracer", tid: int, name: str, attrs):
        self._tracer = tracer
        self._tid = tid
        self._name = name
        self._attrs = attrs

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._tracer.record(self._tid, self._name, self._t0,
                            time.monotonic() - self._t0, self._attrs)
        return False


#: thread-local trace context: (tracer, trace_id) of the innermost
#: ``Tracer.context`` block. Module-level (not per-Tracer) so downstream
#: layers attach spans to whichever tracer set the context — a Pipeline
#: constructed with an injected test tracer still gets its datapath spans.
_ACTIVE = threading.local()


def active() -> Tuple["Tracer", Optional[int]]:
    """The cross-layer read point: (tracer, trace_id) of the thread's
    current trace context, or (TRACER, None) when none is set."""
    entry = getattr(_ACTIVE, "entry", None)
    return entry if entry is not None else (TRACER, None)


class _TraceCtx:
    """Sets/restores the thread-local current trace context."""

    __slots__ = ("_tracer", "_tid", "_prev")

    def __init__(self, tracer: "Tracer", tid: Optional[int]):
        self._tracer = tracer
        self._tid = tid

    def __enter__(self):
        self._prev = getattr(_ACTIVE, "entry", None)
        _ACTIVE.entry = (self._tracer, self._tid)
        return self._tid

    def __exit__(self, *exc):
        _ACTIVE.entry = self._prev
        return False


class Tracer:
    def __init__(self, sample_rate: float = 0.0,
                 capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._events = itertools.count()
        self._trace_ids = itertools.count(1)
        self._ring: List[Optional[_Span]] = []
        self._widx = 0
        self._filled = 0           # occupied ring slots (O(1) stats read)
        self.sampled_total = 0     # counter-sampled events (maybe_sample)
        self.forced_total = 0      # always-traced events (regen, autotune)
        # drop-oldest accounting (ISSUE 13): the ring used to wrap
        # silently — a span summary over a storm looked complete while
        # thousands of spans had been overwritten. Every overwritten slot
        # counts; wraps counts full ring cycles.
        self.spans_dropped_total = 0
        self.ring_wraps = 0
        self.configure(sample_rate=sample_rate, capacity=capacity)

    # -- configuration -------------------------------------------------------
    def configure(self, sample_rate: Optional[float] = None,
                  capacity: Optional[int] = None) -> None:
        """Set the sampling rate (0 disables tracing entirely; 1.0 samples
        every event; 1/64 samples every 64th) and/or the ring capacity."""
        with self._lock:
            if sample_rate is not None:
                if sample_rate <= 0:
                    self._every = 0                  # disabled
                elif sample_rate >= 1.0:
                    self._every = 1
                else:
                    self._every = max(1, round(1.0 / sample_rate))
            if capacity is not None:
                if capacity < 1:
                    raise ValueError("trace capacity must be >= 1")
                # reallocate (discarding spans) only on an actual change —
                # re-stating the current capacity must not wipe the ring
                if capacity != len(self._ring):
                    self._ring = [None] * capacity
                    self._widx = 0
                    self._filled = 0

    @property
    def enabled(self) -> bool:
        return self._every > 0

    @property
    def sample_rate(self) -> float:
        return 0.0 if self._every == 0 else 1.0 / self._every

    def reset(self) -> None:
        with self._lock:
            self._ring = [None] * len(self._ring)
            self._widx = 0
            self._filled = 0
            self.sampled_total = 0
            self.forced_total = 0
            self.spans_dropped_total = 0
            self.ring_wraps = 0
            self._events = itertools.count()
            self._trace_ids = itertools.count(1)

    # -- hot path ------------------------------------------------------------
    def maybe_sample(self) -> Optional[int]:
        """The per-event sampling decision. Unsampled cost: one atomic
        counter draw + a modulo — this is what the hot path pays."""
        every = self._every
        if every == 0:
            return None
        n = next(self._events)
        if every != 1 and n % every:
            return None
        # the sampled branch is the rare one — fine to take the lock here
        # (an unlocked += would lose increments across producer threads)
        with self._lock:
            self.sampled_total += 1
        return next(self._trace_ids)

    def force_sample(self) -> Optional[int]:
        """A trace id regardless of the sampling counter (rare events worth
        always recording — regenerations, autotune decisions). Still None
        when tracing is disabled outright."""
        if self._every == 0:
            return None
        with self._lock:
            # a separate counter: forced traces (regen, autotune decisions)
            # must not skew the sampled-submission count that coverage math
            # (sampled_total x 1/rate ~= submissions) relies on
            self.forced_total += 1
        return next(self._trace_ids)

    def span(self, trace_id: Optional[int], name: str, **attrs):
        """Context manager recording one span when ``trace_id`` is not None
        (the no-op path allocates nothing)."""
        if trace_id is None:
            return _NULL_SPAN
        return _SpanCtx(self, trace_id, name, attrs or None)

    def record(self, trace_id: Optional[int], name: str, t0: float,
               duration_s: float, attrs: Optional[dict] = None) -> None:
        if trace_id is None:
            return
        with self._lock:
            ring = self._ring
            overwrote = ring[self._widx] is not None
            if not overwrote:
                self._filled += 1
            else:
                # drop-oldest: the evicted span is LOST to every later
                # summary/export — count it so /v1/trace can say how much
                # of the story the ring no longer holds
                self.spans_dropped_total += 1
            ring[self._widx] = (trace_id, name, t0, duration_s, attrs)
            self._widx = (self._widx + 1) % len(ring)
            # a wrap is a completed cycle of LOSS, so the initial free
            # fill doesn't count — keeps drops == wraps * capacity (+
            # the partial cycle) mutually consistent
            if self._widx == 0 and overwrote:
                self.ring_wraps += 1

    def event(self, name: str, **attrs) -> Optional[int]:
        """Record a zero-duration decision event (always, when enabled)."""
        tid = self.force_sample()
        if tid is not None:
            self.record(tid, name, time.monotonic(), 0.0, attrs or None)
        return tid

    # -- trace-context propagation -------------------------------------------
    def current(self) -> Optional[int]:
        """The thread's current trace id (whichever tracer set it)."""
        entry = getattr(_ACTIVE, "entry", None)
        return entry[1] if entry is not None else None

    def context(self, trace_id: Optional[int]) -> _TraceCtx:
        """Make ``trace_id`` the thread's current trace for the with-block
        (downstream spans attach via :func:`active` / :meth:`current`)."""
        return _TraceCtx(self, trace_id)

    # -- read side -----------------------------------------------------------
    def _snapshot(self) -> List[_Span]:
        """Ring contents oldest→newest."""
        with self._lock:
            ring, w = list(self._ring), self._widx
        ordered = ring[w:] + ring[:w]
        return [s for s in ordered if s is not None]

    def spans(self, limit: int = 100, name: Optional[str] = None,
              trace_id: Optional[int] = None) -> List[Dict]:
        out = []
        for tid, nm, t0, dur, attrs in self._snapshot():
            if name is not None and nm != name:
                continue
            if trace_id is not None and tid != trace_id:
                continue
            d = {"trace_id": tid, "name": nm, "start_mono": round(t0, 6),
                 "duration_ms": round(dur * 1e3, 6)}
            if attrs:
                d["attrs"] = attrs
            out.append(d)
        return out[-limit:]

    def summary(self) -> Dict[str, Dict]:
        """Per-stage aggregate over the spans currently in the ring:
        count + p50/p99/max/total (ms) — the bench/CLI surface."""
        by_name: Dict[str, List[float]] = {}
        for _tid, nm, _t0, dur, _attrs in self._snapshot():
            by_name.setdefault(nm, []).append(dur)
        out = {}
        for nm in sorted(by_name):
            v = np.asarray(by_name[nm], dtype=np.float64) * 1e3
            out[nm] = {
                "count": int(v.size),
                "p50_ms": round(float(np.percentile(v, 50)), 4),
                "p99_ms": round(float(np.percentile(v, 99)), 4),
                "max_ms": round(float(v.max()), 4),
                "total_ms": round(float(v.sum()), 4),
            }
        return out

    def stats(self) -> Dict:
        # O(1) under the lock — this runs on every /v1/status scrape and
        # must not stall hot-path record() for a full-ring scan
        with self._lock:
            recorded = self._filled
            capacity = len(self._ring)
        return {
            "enabled": self.enabled,
            "sample_rate": self.sample_rate,
            "sampled_total": self.sampled_total,
            "forced_total": self.forced_total,
            "spans_in_ring": recorded,
            "capacity": capacity,
            # drop-oldest accounting (ISSUE 13): spans overwritten before
            # any export saw them + full ring cycles — the "how much of
            # the story is gone" fields the CLI prints
            "spans_dropped_total": self.spans_dropped_total,
            "ring_wraps": self.ring_wraps,
        }


#: process-wide tracer (the FAULTS-singleton idiom): instrumentation points
#: in pipeline/engine/datapath use this; DaemonConfig.trace_* configures it.
TRACER = Tracer()
