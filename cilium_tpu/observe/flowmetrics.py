"""Flow-metrics aggregation (the Hubble-metrics analog, SURVEY.md §3.6:
verdict/drop/protocol/port aggregations over the observed flow stream).

``FlowLog`` keeps per-record detail for ``monitor``; this module keeps the
*aggregates* a dashboard scrapes: per-batch verdict / drop-reason /
protocol / destination-port / remote-identity counts bucketed into aligned
time windows. Everything is vectorized over the already-extracted batch
columns (``np.bincount`` / ``np.unique`` on the valid rows) — there is no
per-record Python on this path, so it can sit beside ``FlowLog.append_batch``
on the pipelined serving path.

Cardinality is bounded: reason/proto axes are fixed-size bincounts; the
open-ended port/identity axes are capped per window (drop-smallest into an
``other`` bucket) so a port scan cannot balloon host memory.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from cilium_tpu.utils import constants as C

#: per-window cap on the open-ended axes (ports, identities); the smallest
#: counts collapse into "other" past this
AXIS_CAP = 256


def _top_uniques(values: np.ndarray):
    """(keys, counts, dropped_total) for the AXIS_CAP most frequent
    values — the vectorized truncation that keeps the lock-held dict merge
    bounded no matter how many distinct ports/identities one batch holds
    (a port scan yields thousands of uniques per batch)."""
    keys, counts = np.unique(values, return_counts=True)
    if keys.size <= AXIS_CAP:
        return keys, counts, 0
    sel = np.argpartition(counts, -AXIS_CAP)[-AXIS_CAP:]
    dropped = int(counts.sum() - counts[sel].sum())
    return keys[sel], counts[sel], dropped


def _merge_counts(dst: Dict[int, int], keys: np.ndarray,
                  counts: np.ndarray) -> None:
    for k, n in zip(keys.tolist(), counts.tolist()):
        dst[k] = dst.get(k, 0) + n


def _merge_capped(dst: Dict[int, int], keys: np.ndarray,
                  counts: np.ndarray, other: int) -> int:
    """Merge for the CUMULATIVE aggregate: established keys accumulate
    forever; once ``dst`` is at capacity, counts for keys it has never
    seen fold into ``other``. Never evicts — an exported Prometheus
    series must stay monotone between scrapes, so first-kept beats
    top-k here (the windows keep top-k; they are ephemeral JSON)."""
    for k, n in zip(keys.tolist(), counts.tolist()):
        if k in dst:
            dst[k] += n
        elif len(dst) < AXIS_CAP:
            dst[k] = n
        else:
            other += n
    return other


def _prune(dst: Dict[int, int], other: int) -> int:
    """Cap ``dst`` at AXIS_CAP entries; returns the new ``other`` total."""
    if len(dst) <= AXIS_CAP:
        return other
    keep = sorted(dst.items(), key=lambda kv: kv[1], reverse=True)
    for k, n in keep[AXIS_CAP:]:
        other += n
        del dst[k]
    return other


class _Window:
    __slots__ = ("start", "forwarded", "dropped", "reasons", "protos",
                 "ports", "identities", "ports_other", "identities_other")

    def __init__(self, start: int):
        self.start = start
        self.forwarded = 0
        self.dropped = 0
        self.reasons = np.zeros(C.DROP_REASON_BINS, dtype=np.int64)
        self.protos = np.zeros(256, dtype=np.int64)
        self.ports: Dict[int, int] = {}
        self.identities: Dict[int, int] = {}
        self.ports_other = 0
        self.identities_other = 0


def _reason_name(r: int) -> str:
    try:
        return C.DropReason(r).name
    except ValueError:
        return str(r)


class FlowMetrics:
    def __init__(self, window_s: int = 10, n_windows: int = 60,
                 top_k: int = 10):
        if window_s < 1 or n_windows < 1:
            raise ValueError("window_s and n_windows must be >= 1")
        self.window_s = int(window_s)
        self.n_windows = n_windows
        self.top_k = top_k
        self._lock = threading.Lock()
        self._windows: deque = deque(maxlen=n_windows)
        self._totals = _Window(0)
        self.batches_total = 0

    # -- hot path ------------------------------------------------------------
    def add_batch(self, batch: Dict[str, np.ndarray],
                  out: Dict[str, np.ndarray], now: int) -> None:
        """Aggregate one classified batch. Vectorized column math happens
        outside the lock; only the merge into the window is serialized."""
        valid = np.asarray(batch["valid"])
        idxs = np.nonzero(valid)[0]
        if idxs.size == 0:
            return
        allow = np.asarray(out["allow"])[idxs]
        n_fwd = int(allow.sum())
        n_drop = int(idxs.size) - n_fwd
        reasons = np.bincount(
            np.asarray(out["reason"])[idxs[~allow]].astype(np.int64),
            minlength=C.DROP_REASON_BINS) if n_drop else None
        protos = np.bincount(
            np.asarray(batch["proto"])[idxs].astype(np.int64) & 0xFF,
            minlength=256)
        ports, port_n, port_rest = _top_uniques(
            np.asarray(batch["dport"])[idxs])
        idents, ident_n, ident_rest = _top_uniques(
            np.asarray(out["remote_identity"])[idxs])

        wstart = int(now) - int(now) % self.window_s
        with self._lock:
            self.batches_total += 1
            w = self._window_for(wstart)
            t = self._totals
            for agg in (w, t):
                agg.forwarded += n_fwd
                agg.dropped += n_drop
                if reasons is not None:
                    agg.reasons += reasons[:C.DROP_REASON_BINS]
                agg.protos += protos
            # window: top-k semantics, evicting prune (ephemeral JSON)
            _merge_counts(w.ports, ports, port_n)
            _merge_counts(w.identities, idents, ident_n)
            w.ports_other = _prune(w.ports, w.ports_other + port_rest)
            w.identities_other = _prune(w.identities,
                                        w.identities_other + ident_rest)
            # totals: monotone semantics — never evict an exported series
            t.ports_other = _merge_capped(t.ports, ports, port_n,
                                          t.ports_other + port_rest)
            t.identities_other = _merge_capped(
                t.identities, idents, ident_n,
                t.identities_other + ident_rest)

    def _window_for(self, wstart: int) -> _Window:
        """Current window, advancing the ring as the clock crosses window
        boundaries (out-of-order ``now`` within the retained ring lands in
        its own window; older than the ring lands in the oldest kept)."""
        for w in reversed(self._windows):
            if w.start == wstart:
                return w
        if self._windows and wstart < self._windows[0].start:
            return self._windows[0]
        w = _Window(wstart)
        if self._windows and wstart < self._windows[-1].start:
            # rare out-of-order batch inside the retained range: insert sorted
            items = sorted([*self._windows, w], key=lambda x: x.start)
            self._windows = deque(items[-self.n_windows:],
                                  maxlen=self.n_windows)
        else:
            self._windows.append(w)
        return w

    # -- read side -----------------------------------------------------------
    def _doc(self, w: _Window) -> Dict:
        nz = np.nonzero(w.reasons)[0]
        top_ports = sorted(w.ports.items(), key=lambda kv: kv[1],
                           reverse=True)[:self.top_k]
        top_ids = sorted(w.identities.items(), key=lambda kv: kv[1],
                         reverse=True)[:self.top_k]
        pnz = np.nonzero(w.protos)[0]
        return {
            "window_start": w.start,
            "window_s": self.window_s,
            "forwarded": w.forwarded,
            "dropped": w.dropped,
            "drop_reasons": {_reason_name(int(r)): int(w.reasons[r])
                             for r in nz},
            "protos": {C.PROTO_NAMES.get(int(p), str(int(p))):
                       int(w.protos[p]) for p in pnz},
            "top_ports": [{"port": int(p), "count": n}
                          for p, n in top_ports],
            "top_identities": [{"identity": int(i), "count": n}
                               for i, n in top_ids],
        }

    def series(self, last: int = 0) -> List[Dict]:
        """Windowed time-series, oldest first (the /v1/flows/metrics body).
        Docs are built under the lock — the newest window is live and a
        mid-merge read would be internally inconsistent."""
        with self._lock:
            docs = [self._doc(w) for w in self._windows]
        return docs[-last:] if last else docs

    def totals(self) -> Dict:
        with self._lock:
            doc = self._doc(self._totals)
        doc.pop("window_start")
        doc.pop("window_s")
        doc["batches"] = self.batches_total
        return doc

    def render_prometheus(self) -> str:
        """Cumulative totals in Prometheus text format (appended after
        ``Metrics.render_prometheus`` by the engine's exporters)."""
        with self._lock:
            t = self._totals
            lines = [
                "# TYPE ciliumtpu_flow_verdicts_total counter",
                f'ciliumtpu_flow_verdicts_total{{verdict="FORWARDED"}} '
                f"{t.forwarded}",
                f'ciliumtpu_flow_verdicts_total{{verdict="DROPPED"}} '
                f"{t.dropped}",
            ]
            nz = np.nonzero(t.reasons)[0]
            if nz.size:
                lines.append("# TYPE ciliumtpu_flow_drops_total counter")
                lines.extend(
                    f'ciliumtpu_flow_drops_total{{reason="{_reason_name(int(r))}"}} '
                    f"{int(t.reasons[r])}" for r in nz)
            pnz = np.nonzero(t.protos)[0]
            if pnz.size:
                lines.append("# TYPE ciliumtpu_flow_proto_total counter")
                lines.extend(
                    f'ciliumtpu_flow_proto_total{{proto='
                    f'"{C.PROTO_NAMES.get(int(p), str(int(p)))}"}} '
                    f"{int(t.protos[p])}" for p in pnz)
            for label, counts, other in (
                    ("port", t.ports, t.ports_other),
                    ("identity", t.identities, t.identities_other)):
                if not counts and not other:
                    continue
                # export every retained entry (bounded at AXIS_CAP) and the
                # pruned remainder — both monotone between scrapes, which a
                # Prometheus counter must be (folding a recomputed top-k
                # tail into "other" per scrape would make it sawtooth)
                lines.append(f"# TYPE ciliumtpu_flow_{label}_total counter")
                lines.extend(
                    f'ciliumtpu_flow_{label}_total{{{label}="{k}"}} {n}'
                    for k, n in sorted(counts.items(), key=lambda kv: kv[1],
                                       reverse=True))
                if other:
                    lines.append(
                        f'ciliumtpu_flow_{label}_total{{{label}="other"}} '
                        f"{other}")
        return "\n".join(lines) + "\n"
