"""Always-on bounded flight recorder: the debuggability half of verdict
provenance.

When the serving path misbehaves in production — a parity mismatch, a
breaker opening, a watchdog restart, a shed spike — the evidence is gone by
the time an operator attaches: the span ring has wrapped, the stats have
moved on, the offending rows were recycled. The flight recorder keeps a
small, always-on tail of exactly that evidence and **freezes** it into an
exportable JSON debug bundle the moment an anomaly fires:

- **Event ring** (bounded deque): guard/breaker/watchdog transitions and
  sheds (fed by the pipeline's ``event_sink``), regeneration revisions,
  parity-audit verdicts — each with wall + monotonic timestamps.
- **Verdict summaries** (last N finalized batches): rows/allowed/dropped +
  top drop reasons — what the datapath was answering right before the
  anomaly, without retaining the batches themselves.
- **Stats snapshots**: periodic pipeline/feeder/health snapshots noted by
  the engine's observability flush.
- **Span-ring tail**: the tracer's recent spans are folded into the bundle
  at freeze time (per-stage latency context around the anomaly).

Freeze policy: the FIRST anomaly wins — its bundle is the root-cause
record and is kept until explicitly cleared (``clear()`` / the API's
``?clear=1``), while ``freezes_total`` counts every anomaly since. Sheds
are too frequent to freeze individually; a *spike* (``shed_spike``
sheds within ``shed_window_s``) freezes once.

Export surfaces: ``Engine.debug_bundle()`` → ``GET /v1/debug/bundle`` and
``cilium-tpu debug-bundle``. Everything here is lock-leaf and never-raise:
the recorder can observe a dying pipeline without joining it.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

import numpy as np

from cilium_tpu.runtime.metrics import Metrics
from cilium_tpu.utils import constants as C

log = logging.getLogger("cilium_tpu.blackbox")

#: event kinds that freeze the recorder on sight. Breaker events are NOT
#: here: they arrive as kind="breaker" with old/new attrs and freeze only
#: on the new=="open" transition (record_event's special case) — a close
#: or half-open probe is recovery, not an anomaly. Sheds freeze only as a
#: spike (see module docstring). Watchdog restarts and hard-fails both
#: arrive as kind="watchdog" (the action attr distinguishes them).
#: Overload-ladder transitions (kind="overload") and CT-emergency-GC
#: events (kind="ct-emergency") are recorded but never freeze: they are
#: COMMANDED degradation, the system doing its job under attack. Resource-
#: ledger forecasts (kind="resource-pressure", ISSUE 13) likewise only
#: narrate — but a forecast-then-exhaustion ("resource-exhaustion": the
#: ledger predicted the structure would fill and then it did) is the
#: capacity anomaly this recorder exists for, and freezes strictly.
#: device loss and the fenced re-mesh that answers it (ISSUE 19) are
#: exactly the anomalies a flight recorder exists for: the freeze keeps
#: the dispatch-failure lead-up and the geometry transition in one bundle
FREEZE_KINDS = frozenset(("watchdog", "parity-mismatch",
                          "resource-exhaustion", "device-loss", "remesh"))

#: shed reasons judged against the RELAXED spike threshold: deliberate
#: overload shedding (admission priority eviction, harvest-time SHED-NEW,
#: stale-at-ingest) fires at storm rate by design — freezing on it would
#: blind the recorder exactly during the attack it should be narrating.
#: Everything else (flush-time deadline sheds, steer_overflow) keeps the
#: strict threshold: those spiking IS the anomaly.
RELAXED_SHED_REASONS = frozenset(("priority", "ingest", "shed-new"))


class FlightRecorder:
    def __init__(self, *, capacity: int = 256, verdict_batches: int = 64,
                 stats_snapshots: int = 8,
                 shed_spike: int = 64, shed_window_s: float = 5.0,
                 shed_spike_relaxed: int = 4096,
                 span_tail: int = 128,
                 metrics: Optional[Metrics] = None,
                 tracer=None):
        if capacity < 1 or verdict_batches < 1:
            raise ValueError("capacity and verdict_batches must be >= 1")
        if shed_spike < 1 or shed_spike_relaxed < 1:
            raise ValueError("shed_spike thresholds must be >= 1")
        self.metrics = metrics if metrics is not None else Metrics()
        self.tracer = tracer
        self._lock = threading.Lock()
        self._events: Deque[Dict] = deque(maxlen=capacity)
        self._verdicts: Deque[Dict] = deque(maxlen=verdict_batches)
        self._stats: Deque[Dict] = deque(maxlen=stats_snapshots)
        self._span_tail = span_tail
        self._shed_spike = shed_spike
        self._shed_window_s = shed_window_s
        # split by shed reason: deliberate overload sheds (priority /
        # ingest / shed-new) spike against their own, much higher
        # threshold so a commanded SHED-NEW storm narrates instead of
        # freezing the recorder on every window
        self._shed_times: Deque[float] = deque(maxlen=max(1, shed_spike))
        self._shed_times_relaxed: Deque[float] = deque(
            maxlen=max(1, shed_spike_relaxed))
        self._frozen: Optional[Dict] = None
        self.freezes_total = 0
        self.events_total = 0

    # -- feed side (never raises) --------------------------------------------
    def record_event(self, kind: str, **attrs) -> None:
        """One guard/regen/audit event into the ring. Auto-freezes on
        anomalous kinds; shed events freeze only as a spike."""
        try:
            evt = {"t": time.time(), "mono": time.monotonic(),
                   "kind": kind, **attrs}
            with self._lock:
                self._events.append(evt)
                self.events_total += 1
            if kind == "shed":
                self._note_shed(evt["mono"], attrs.get("reason"))
                return
            if kind in FREEZE_KINDS or \
                    (kind == "breaker" and attrs.get("new") == "open"):
                self.freeze(f"{kind}:{attrs.get('reason', attrs.get('new', ''))}"
                            .rstrip(":"), detail=attrs)
        except Exception:   # noqa: BLE001 — the recorder must never bite
            log.exception("flight recorder event failed")

    def _note_shed(self, mono: float, reason: Optional[str]) -> None:
        times = self._shed_times_relaxed \
            if reason in RELAXED_SHED_REASONS else self._shed_times
        times.append(mono)
        if len(times) == times.maxlen \
                and mono - times[0] <= self._shed_window_s:
            self.freeze("shed-spike", detail={
                "sheds": len(times),
                "reason_class": "relaxed"
                if times is self._shed_times_relaxed else "strict",
                "last_reason": reason,
                "window_s": round(mono - times[0], 3)})
            times.clear()

    def record_verdicts(self, out: Dict[str, np.ndarray], n_valid: int,
                        now: int) -> None:
        """Per-finalized-batch verdict summary (vectorized; no row copies).
        Cheap enough to run always-on beside the metrics fold."""
        try:
            allow = np.asarray(out["allow"])
            reasons = np.asarray(out["reason"])
            dropped = reasons[~allow & (reasons != 0)]
            top: Dict[str, int] = {}
            if dropped.size:
                vals, counts = np.unique(dropped, return_counts=True)
                order = np.argsort(counts)[::-1][:4]
                for v, c in zip(vals[order], counts[order]):
                    try:
                        name = C.DropReason(int(v)).name
                    except ValueError:
                        name = str(int(v))
                    top[name] = int(c)
            summary = {"t": time.time(), "now": now, "rows": n_valid,
                       "allowed": int(allow.sum()),
                       "dropped": int(dropped.size), "top_reasons": top}
            # match provenance (ISSUE 11): which policy cells / ipcache
            # prefixes / CT classes the batch's drops concentrate on — a
            # frozen bundle explains its anomalous flows without a replay
            drop_m = ~allow & (reasons != 0)
            for col, key in (("matched_rule", "top_drop_rules"),
                             ("lpm_prefix", "top_drop_prefixes"),
                             ("ct_state_pre", "drop_ct_states")):
                if col not in out:
                    continue
                vals = np.asarray(out[col])[drop_m]
                vals = vals[vals >= 0] if col != "ct_state_pre" else vals
                if not vals.size:
                    continue
                u, c = np.unique(vals, return_counts=True)
                order = np.argsort(c)[::-1][:4]
                summary[key] = {int(u[i]): int(c[i]) for i in order}
            with self._lock:
                self._verdicts.append(summary)
        except Exception:   # noqa: BLE001
            log.exception("flight recorder verdict summary failed")

    def note_stats(self, doc: Dict) -> None:
        """Periodic stats snapshot (pipeline/feeder/health); the engine's
        observability flush feeds this so a frozen bundle carries the state
        trajectory, not just the instant of the anomaly."""
        try:
            with self._lock:
                self._stats.append({"t": time.time(), **doc})
        except Exception:   # noqa: BLE001
            log.exception("flight recorder stats snapshot failed")

    # -- freeze / export ------------------------------------------------------
    def freeze(self, reason: str, detail: Optional[Dict] = None) -> Dict:
        """Freeze the current tail into the debug bundle. First anomaly
        wins (its bundle is the root-cause record); later freezes only
        count. Returns the live-built bundle either way."""
        bundle = self._build(reason, detail)
        with self._lock:
            self.freezes_total += 1
            first = self._frozen is None
            if first:
                self._frozen = bundle
        self.metrics.inc_counter(
            f'blackbox_freezes_total{{reason="{reason.split(":", 1)[0]}"}}')
        if first:
            log.warning("flight recorder FROZE a debug bundle: %s", reason)
        return bundle

    def _build(self, reason: str, detail: Optional[Dict]) -> Dict:
        with self._lock:
            events = list(self._events)
            verdicts = list(self._verdicts)
            stats = list(self._stats)
        spans: List[Dict] = []
        span_stats: Dict = {}
        if self.tracer is not None:
            try:
                spans = self.tracer.spans(limit=self._span_tail)
                span_stats = self.tracer.stats()
            except Exception:   # noqa: BLE001
                pass
        return {
            "reason": reason,
            "frozen_at": time.time(),
            "detail": detail or {},
            "events": events,
            "verdict_summaries": verdicts,
            "stats_snapshots": stats,
            "spans": spans,
            "trace_stats": span_stats,
        }

    def bundle(self, extra: Optional[Dict] = None,
               clear: bool = False) -> Dict:
        """The export surface: the frozen bundle when an anomaly captured
        one, else a live snapshot. ``extra`` (live engine state gathered by
        the caller) is attached at fetch time — freeze itself never calls
        back into the pipeline, so it can run from any thread without
        lock-order concerns. ``clear=True`` re-arms the recorder after the
        fetch."""
        with self._lock:
            frozen = self._frozen
        doc = dict(frozen) if frozen is not None \
            else self._build("live-snapshot", None)
        doc["frozen"] = frozen is not None
        doc["freezes_total"] = self.freezes_total
        if extra:
            doc["engine"] = extra
        if clear:
            self.clear()
        return doc

    def clear(self) -> None:
        with self._lock:
            self._frozen = None

    def stats(self) -> Dict:
        with self._lock:
            return {
                "events_in_ring": len(self._events),
                "events_capacity": self._events.maxlen,
                "events_total": self.events_total,
                "verdict_summaries": len(self._verdicts),
                "freezes_total": self.freezes_total,
                "frozen": self._frozen is not None,
                "frozen_reason": self._frozen["reason"]
                if self._frozen else None,
            }
