"""The Engine: the daemon's core object (analog of upstream cilium-agent's
daemon wiring + endpoint regeneration pipeline, SURVEY.md §3.1/§3.2).

Owns the control-plane state (allocator, ipcache, repository, endpoints),
compiles PolicySnapshots, places them on device, and serves classification.

Concurrency/atomicity model (the analog of per-endpoint policymap atomicity +
regeneration revisions): the active compiled snapshot is swapped by a single
reference assignment under a lock — a batch classifies against exactly one
snapshot revision, never a torn update. Regeneration is driven by a debounced
Trigger on repository/ipcache changes; CT sweeping by a periodic controller.
Device arrays are a cache of host truth: on device loss the engine can
re-materialize everything from host state (upstream philosophy: "BPF maps are
re-populatable from agent state").
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from cilium_tpu.compile.ct_layout import CTConfig
from cilium_tpu.compile.lb import LBConfig
from cilium_tpu.compile.snapshot import PolicySnapshot, build_snapshot
from cilium_tpu.model.endpoint import Endpoint
from cilium_tpu.model.identity import IdentityAllocator
from cilium_tpu.model.ipcache import IPCache
from cilium_tpu.model.labels import Labels
from cilium_tpu.model.rules import parse_rules
from cilium_tpu.model.services import ServiceRegistry
from cilium_tpu.observe.audit import ShadowAuditor
from cilium_tpu.observe.blackbox import FlightRecorder
from cilium_tpu.observe.flowmetrics import FlowMetrics
from cilium_tpu.observe.pressure import LADDER_EXCLUDE, ResourceLedger
from cilium_tpu.observe.trace import TRACER
from cilium_tpu.policy.repository import PolicyContext, Repository
from cilium_tpu.policy.selectorcache import SelectorCache
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.controller import ControllerManager, Trigger
from cilium_tpu.runtime.datapath import DatapathBackend, StalePlacement
from cilium_tpu.runtime.faults import FAULTS
from cilium_tpu.runtime.flowlog import FlowLog
from cilium_tpu.runtime.metrics import Metrics
from cilium_tpu.utils import constants as C


@dataclass
class CompiledSnapshot:
    """A snapshot placed on the datapath: what a batch classifies against."""
    snapshot: PolicySnapshot
    tensors: Dict            # the backend's placed handle (device arrays)
    world_index: int
    revision: int


class Engine:
    """Depends only on the DatapathBackend boundary for everything device-
    or semantics-executing (SURVEY.md §1 layer 3: the Datapath/Loader plugin
    boundary). Constructed with a FakeDatapath it never imports jax."""

    def __init__(self, config: Optional[DaemonConfig] = None,
                 datapath: Optional[DatapathBackend] = None):
        self.config = config or DaemonConfig()
        if datapath is None:
            from cilium_tpu.runtime.datapath import JITDatapath
            datapath = JITDatapath(self.config)
        self.datapath = datapath

        alloc = IdentityAllocator()
        from cilium_tpu.model.fqdn import FQDNCache
        self.ctx = PolicyContext(
            allocator=alloc,
            selector_cache=SelectorCache(alloc),
            ipcache=IPCache(),
            services=ServiceRegistry(),
            enforcement_mode=self.config.enforcement_mode,
            allow_localhost=self.config.allow_localhost,
            fqdn_cache=FQDNCache(
                min_ttl=self.config.fqdn_min_ttl,
                max_names=self.config.fqdn_max_names,
                max_ips_per_name=self.config.fqdn_max_ips_per_name),
        )
        self.repo = Repository(self.ctx)
        self.endpoints: Dict[int, Endpoint] = {}
        self._next_ep_id = 1

        self.metrics = Metrics()
        self.flowlog = FlowLog(self.config.flowlog_capacity,
                               self.config.flowlog_mode,
                               sink_path=self.config.flowlog_path or None,
                               metrics=self.metrics)
        # the vectorized flow-observe engine over the columnar ring
        # (observe/observer.py — the Hubble Observe()/FlowFilter analog);
        # /v1/flows/observe and `cilium-tpu observe` serve through it
        from cilium_tpu.observe.observer import FlowObserver
        self.observer = FlowObserver(self.flowlog, metrics=self.metrics)
        # per-rule hit/drop counters (the unused-rule / policy-drift
        # signal): matched_rule provenance → capped-cardinality labeled
        # counters, resolved to rule tags lazily per snapshot
        self._rule_fold_lock = threading.Lock()
        self._rule_label_memo: Dict[int, str] = {}   # coord → label
        self._rule_label_snap: Optional[PolicySnapshot] = None
        self._rule_labels_seen: set = set()          # global cardinality cap
        # observe/: span tracer + Hubble-metrics-analog windowed flow
        # aggregation. The tracer is process-wide; an engine only
        # configures it when ITS config turns tracing on — constructing a
        # second engine with the rate-0 default must not silently disable
        # (or wipe the span ring of) tracing another engine enabled
        if self.config.trace_sample_rate > 0:
            TRACER.configure(sample_rate=self.config.trace_sample_rate,
                             capacity=self.config.trace_capacity)
        self.tracer = TRACER
        self.flowmetrics = FlowMetrics(
            window_s=self.config.flowmetrics_window_s,
            n_windows=self.config.flowmetrics_windows,
            top_k=self.config.flowmetrics_top_k)
        # verdict provenance (observe/audit.py + observe/blackbox.py): the
        # flight recorder is always on (bounded rings, freeze on anomaly);
        # the shadow auditor is constructed unconditionally but samples
        # nothing until audit_enabled arms it (tests/chaos re-arm via
        # auditor.configure) — its capture path costs one attribute read
        # per finalized batch when disarmed
        self.blackbox = FlightRecorder(
            capacity=self.config.blackbox_events,
            verdict_batches=self.config.blackbox_verdicts,
            shed_spike=self.config.blackbox_shed_spike,
            shed_window_s=self.config.blackbox_shed_window_s,
            shed_spike_relaxed=self.config.blackbox_shed_spike_relaxed,
            metrics=self.metrics, tracer=TRACER)
        self.auditor = ShadowAuditor(
            sample_rate=self.config.audit_sample_rate
            if self.config.audit_enabled else 0.0,
            pool_batches=self.config.audit_pool_batches,
            max_rows=self.config.audit_max_rows,
            n_shards=getattr(self.datapath, "pipeline_shards", 1),
            metrics=self.metrics,
            on_mismatch=self._on_parity_mismatch)
        # resource pressure ledger (observe/pressure.py; ISSUE 13): every
        # bounded structure registers (capacity, occupancy, high_water)
        # here; the resource-ledger controller polls, the labeled
        # resource_* families + /v1/resources + `cilium-tpu top` export,
        # health() folds pressure in as RESOURCE_PRESSURE, and the
        # overload ladder takes the worst non-CT pressure as its fourth
        # latch. Forecast events narrate to the flight recorder.
        self.ledger = ResourceLedger(
            metrics=self.metrics,
            window=self.config.resource_eta_window,
            warn=self.config.resource_pressure_warn,
            crit=self.config.resource_pressure_crit,
            eta_warn_s=self.config.resource_eta_warn_s,
            event_sink=self.blackbox.record_event)
        self._last_update_stats = None   # incremental UpdateStats (budgets)
        self._hbm_budget = None          # attached verifier budget_doc
        # multi-tenant QoS (cilium_tpu/qos): the tenant table is built
        # once from config and threaded into the pipeline (weighted-fair
        # admission + latency lane) and the feeder (harvest-time tenant
        # stamping). None when qos_enabled is off — every consumer then
        # takes its pre-QoS FIFO path, byte-identical to today.
        if self.config.qos_enabled:
            from cilium_tpu.qos import TenantTable
            self.qos = TenantTable.from_spec(
                self.config.qos_tenants,
                assign=self.config.qos_assign,
                default_weight=self.config.qos_default_weight,
                default_cap=self.config.qos_tenant_cap_batches)
        else:
            self.qos = None
        self._register_resources()
        self.controllers = ControllerManager()

        self._lock = threading.RLock()
        self._active: Optional[CompiledSnapshot] = None
        # regeneration-needed flag. An Event, not a bare bool: observers
        # (repo/ipcache/services) mark it from their mutators' threads
        # WITHOUT the engine lock — Event.set() is atomic, so a mark can
        # never be lost to a torn read/write interleaving (VERDICT r05
        # weak #6)
        self._dirty_event = threading.Event()
        self._dirty_event.set()
        self._autotuner = None     # observe/autotune controller state
        # supervised degradation: regen failures never tear down serving —
        # classify continues on the last-good snapshot while these track
        # the failure streak for health_probe()/metrics
        self._regen_failures = 0
        self._last_regen_error = ""
        self._inc = None           # IncrementalCompiler, seeded on full build
        self._api = None           # APIServer when config.api_socket set
        self._mesh = None          # ClusterMesh when cluster_store set
        self._pipeline = None      # ingestion Pipeline, started on demand
        self._pipeline_stopped = False   # stop() bars lazy restart
        self._pipeline_sharded = False   # pipeline delivers steered batches
        self._feeder = None        # shim/feeder.py harvest thread
        self._dns_proxy = None     # fqdn/proxy.py learning tap (feeder)
        self._pack_stats_seen: Dict[str, int] = {}  # scrape-delta baseline
        self._pack_fold_lock = threading.Lock()     # concurrent scrapes
        self._remap_snap = None    # dispatch-time slot-LUT cache key
        self._remap_lut: Optional[np.ndarray] = None
        # overload ladder (pipeline/guard.OverloadLadder; the `overload`
        # controller feeds it) + shed-rate bookkeeping for its shed signal
        self._overload = None
        self._overload_shed_prev = 0
        self._overload_shed_t: Optional[float] = None
        # CT emergency-GC latch (hysteresis: enters at ct_pressure_high,
        # exits at ct_pressure_low; armed by sweep()/sweep_step())
        self._ct_emergency = False
        # mesh self-healing (ISSUE 19): device loss → fenced re-mesh onto
        # survivors → CT salvage → hysteretic re-admission. The grace-
        # window fingerprint filter shares the feeder's exact established-
        # flow update/lookup discipline (shim/feeder.py) but is engine-
        # owned: the window must work for direct submit() producers too.
        from cilium_tpu.shim.feeder import EstablishedFingerprints
        self._remesh_lock = threading.Lock()
        self._remesh_last: Optional[Dict] = None
        self._heal_ok_since: Optional[float] = None   # probe-pass streak
        self._salvage_until = 0.0    # monotonic end of the grace window
        self._salvage_fp = EstablishedFingerprints()

        self._regen_trigger = Trigger(self._mark_dirty_and_regen,
                                      min_interval=self.config.regen_debounce_s,
                                      sync=not self.config.auto_regen)
        # health prober source address → reserved health identity
        # (cilium-health analog; upstream allocates health endpoint IPs)
        self.ctx.ipcache.upsert(f"{C.HEALTH_PROBE_IP}/32", C.IDENTITY_HEALTH)

        self.repo.add_observer(lambda rev: self._regen_trigger())
        self.ctx.ipcache.add_observer(self._mark_dirty)
        # LB-only service changes (no toServices rule referencing them) still
        # need a recompile: the frontend/Maglev tensors live in the snapshot
        self.ctx.services.add_observer(self._mark_dirty)

    # -- endpoint lifecycle (thin pkg/endpoint analog) ------------------------
    def add_endpoint(self, labels: Sequence[str], ips: Sequence[str] = (),
                     ep_id: Optional[int] = None,
                     enforcement: Optional[str] = None) -> Endpoint:
        with self._lock:
            if ep_id is None:
                ep_id = self._next_ep_id
            if ep_id in self.endpoints:
                raise ValueError(f"endpoint {ep_id} already exists")
            self._next_ep_id = max(self._next_ep_id, ep_id + 1)
            lbls = Labels.parse(labels)
            ident = self.ctx.allocator.allocate(lbls)
            ep = Endpoint(ep_id=ep_id, labels=lbls, ips=tuple(ips),
                          identity_id=ident.id, enforcement=enforcement)
            self.endpoints[ep_id] = ep
            for ip in ips:
                prefix = f"{ip}/128" if ":" in ip else f"{ip}/32"
                self.ctx.ipcache.upsert(prefix, ident.id)
            self._mark_dirty()
            return ep

    def remove_endpoint(self, ep_id: int) -> bool:
        with self._lock:
            ep = self.endpoints.pop(ep_id, None)
            if ep is None:
                return False
            for ip in ep.ips:
                prefix = f"{ip}/128" if ":" in ip else f"{ip}/32"
                self.ctx.ipcache.delete(prefix)
            ident = self.ctx.allocator.get(ep.identity_id)
            if ident is not None:
                self.ctx.allocator.release(ident)
            self._mark_dirty()
            return True

    # -- FQDN policy (pkg/fqdn analog) -----------------------------------------
    def observe_dns(self, name: str, ips: Sequence[str], ttl: int = 3600,
                    now: Optional[int] = None) -> bool:
        """Feed one DNS answer into the FQDN cache (the programmatic stand-in
        for upstream's DNS-proxy observation path). Newly learned IPs
        re-materialize toFQDNs rules → regeneration."""
        if now is None:
            # the cache's clock, not wall time: materialization filters
            # expiries through fqdn_cache.clock, and the two must agree
            now = int(self.ctx.fqdn_cache.clock())
        return self.ctx.fqdn_cache.observe(name, ips, ttl, now)

    # -- services (pkg/service analog) -----------------------------------------
    def upsert_service(self, svc) -> None:
        """Add/replace a Service (frontends+backends program the LB tensors
        at the next regeneration; upstream: service upsert → lbmap writes)."""
        self.ctx.services.upsert(svc)

    def delete_service(self, namespace: str, name: str) -> bool:
        return self.ctx.services.delete(namespace, name)

    # -- policy ----------------------------------------------------------------
    def apply_policy(self, docs) -> int:
        """Ingest CNP-style rule documents (list/dict/JSON string)."""
        return self.repo.add(parse_rules(docs))

    def replace_policy(self, match_labels: Sequence[str], docs) -> int:
        return self.repo.replace_by_labels(Labels.parse(match_labels),
                                           parse_rules(docs) if docs else [])

    # -- regeneration (the loader path) ----------------------------------------
    @property
    def _dirty(self) -> bool:
        """Read-only view; all writes go through ``_dirty_event`` directly
        (the clear-before-compile ordering in ``_regenerate_locked`` is
        load-bearing — no second write path)."""
        return self._dirty_event.is_set()

    def _mark_dirty(self, *_args) -> None:
        self._dirty_event.set()

    def _mark_dirty_and_regen(self) -> None:
        self._dirty_event.set()
        if self.config.auto_regen:
            try:
                self.regenerate()
            except Exception:
                # regenerate() only raises through its supervised
                # degradation when there is no last-good snapshot (cold
                # start); it has already counted/logged the failure — keep
                # the trigger alive and surface that NOTHING is serving
                logging.getLogger("cilium_tpu.engine").exception(
                    "regeneration failed with no last-good snapshot; "
                    "nothing is being served")

    def regenerate(self, force: bool = False) -> CompiledSnapshot:
        """Compile current control-plane state and swap it in atomically.

        With ``config.incremental`` the regeneration first tries to patch
        the active snapshot through the repository changelog
        (compile/incremental.IncrementalCompiler — the upstream analog of
        incremental policymap diffs, SURVEY.md §3.2); geometry gates fall
        back to the full compiler and re-seed the patcher."""
        with self._lock:
            if not (self._dirty_event.is_set() or force) \
                    and self._active is not None:
                return self._active
            was_dirty = self._dirty_event.is_set()
            try:
                return self._regenerate_locked(force)
            except Exception as e:  # noqa: BLE001 — supervised degradation
                # the failed compile consumed nothing: restore the dirty
                # mark it cleared so the next classify/trigger retries. A
                # *forced* regen from a clean engine stays clean — its
                # failure owes no retry (and marks set by observers
                # mid-compile are already on the event, never cleared here)
                if was_dirty:
                    self._dirty_event.set()
                self._regen_failures += 1
                self._last_regen_error = f"{type(e).__name__}: {e}"
                self.metrics.inc_counter("regen_failures_total")
                self.metrics.set_gauge("engine_degraded", 1)
                self.metrics.set_gauge("regen_consecutive_failures",
                                       self._regen_failures)
                if self._active is not None:
                    # serving survives: the last-good snapshot keeps
                    # answering (verdicts stay bit-identical to the last
                    # successfully compiled state); _dirty stays set so the
                    # next classify/trigger retries the compile
                    logging.getLogger("cilium_tpu.engine").warning(
                        "regeneration failed (%d consecutive), serving "
                        "last-good snapshot rev %d: %s",
                        self._regen_failures, self._active.revision,
                        self._last_regen_error)
                    return self._active
                raise   # cold start: nothing compiled yet, nothing to serve

    def _regenerate_locked(self, force: bool) -> CompiledSnapshot:
        """The compile+place body of :meth:`regenerate` (lock held)."""
        # flush the coalesced FQDN refresh FIRST — before the dirty-event
        # clear and before the incremental compiler computes its identity
        # delta — so N debounced cache observes materialize as ONE rule
        # refresh whose identity growth/retirement this very cycle sees
        self.repo.flush_fqdn_refresh()
        # clear BEFORE compiling: a concurrent observer marking dirty
        # mid-compile must survive into the next regeneration (clearing
        # after the swap would lose that mark)
        self._dirty_event.clear()
        # regenerations are rare and always worth a trace when tracing is
        # on; the context makes the datapath's nested spans (the
        # datapath.patch.apply scatter) attach to this regeneration
        trace_id = TRACER.force_sample()
        with TRACER.context(trace_id):
            return self._regen_traced(trace_id, force)

    def _regen_traced(self, trace_id, force: bool) -> CompiledSnapshot:
        FAULTS.fire("regen.compile")
        eps = sorted(self.endpoints.values(), key=lambda e: e.ep_id)
        ct_cfg = CTConfig(self.config.ct_capacity,
                          self.config.probe_depth)
        lb_cfg = LBConfig(maglev_m=self.config.maglev_m)

        snap = patch = None
        if (self._inc is not None and self._active is not None
                and not force):
            # NB: lb_cfg is deliberately not passed — LB geometry is
            # fixed at daemon start; LB content changes gate via
            # services_revision
            with TRACER.span(trace_id, "engine.regen.patch"), \
                    self.metrics.span("snapshot_patch").timer():
                result = self._inc.try_update(ct_cfg, endpoints=eps)
            if result is not None:
                snap, patch, stats = result
                self.metrics.inc_counter("regen_incremental_total")
                self.metrics.set_gauge("regen_last_rows_patched",
                                       stats.rows_recomputed)
                # the PR 9 budget headroom the resource ledger samples
                # (patch_budget / ident_growth rows)
                self._last_update_stats = stats
                if stats.retired_identities:
                    self.metrics.inc_counter(
                        "fqdn_identities_retired_total",
                        stats.retired_identities)
            else:
                logging.getLogger("cilium_tpu.engine").debug(
                    "incremental fallback: %s", self._inc.last_fallback)

        full_build = snap is None
        if full_build:
            with TRACER.span(trace_id, "engine.regen.compile"), \
                    self.metrics.span("snapshot_compile").timer():
                snap = build_snapshot(self.repo, self.ctx, eps,
                                      ct_cfg, lb_cfg)
            self.metrics.inc_counter("regen_full_total")

        try:
            with TRACER.span(trace_id, "engine.regen.place",
                             incremental=patch is not None), \
                    self.metrics.span("device_place").timer():
                if patch is not None and self._active is not None:
                    if patch.is_noop:
                        tensors = self._active.tensors
                    else:
                        tensors = self.datapath.place_patch(
                            self._active.tensors, snap, patch)
                else:
                    tensors = self.datapath.place(snap)
        except Exception:
            # the incremental compiler already advanced past this
            # revision; keeping it would let a retry pair the new
            # snapshot with never-patched device tensors (silent stale
            # policy). Discard — the retry takes the full-build path.
            self._inc = None
            raise
        if full_build and self.config.incremental:
            # seed only after placement succeeded (same staleness trap)
            from cilium_tpu.compile.incremental import \
                IncrementalCompiler
            self._inc = IncrementalCompiler(
                self.repo, self.ctx, eps, snap,
                delta_budget_rows=self.config.patch_delta_rows,
                rebase_rows=self.config.patch_rebase_rows)
        self.repo.prune_changes(snap.revision)
        compiled = CompiledSnapshot(
            snapshot=snap, tensors=tensors,
            world_index=snap.world_index, revision=snap.revision)
        self._active = compiled            # atomic swap (revision fence);
        # _dirty_event was cleared up top — NOT re-cleared here, so a
        # concurrent mark during this compile still forces the next regen
        if self._regen_failures:
            logging.getLogger("cilium_tpu.engine").info(
                "regeneration recovered after %d failures (rev %d)",
                self._regen_failures, snap.revision)
        self._regen_failures = 0
        self._last_regen_error = ""
        for ep in self.endpoints.values():
            ep.policy_revision = snap.revision
        self.metrics.set_gauge("policy_revision", snap.revision)
        self.metrics.set_gauge("policy_image_bytes", snap.nbytes)
        self.metrics.set_gauge("engine_degraded", 0)
        self.metrics.set_gauge("regen_consecutive_failures", 0)
        # flight recorder: the revision trail is what makes a frozen bundle
        # attributable ("which policy world were these verdicts from")
        self.blackbox.record_event("regen", revision=snap.revision,
                                   incremental=patch is not None
                                   and not full_build)
        return compiled

    @property
    def active(self) -> CompiledSnapshot:
        if self._active is None or self._dirty:
            return self.regenerate()
        return self._active

    # -- datapath ---------------------------------------------------------------
    #: StalePlacement retries before giving up: each retry blocks on the
    #: engine lock, so one attempt per concurrently-landing patch — more
    #: than a few in a row means something is wedged, not racing
    _STALE_RETRIES = 4

    def _await_regen(self) -> None:
        """A StalePlacement means a delta patch donated the captured
        handle's buffers mid-regeneration; acquiring the engine lock blocks
        until that regeneration's atomic swap has landed, after which
        ``self.active`` is the freshly patched snapshot."""
        with self._lock:
            pass

    def classify(self, batch: Dict[str, np.ndarray],
                 now: Optional[int] = None) -> Dict[str, np.ndarray]:
        """Classify one batch (dict-of-arrays, kernels/records layout).
        Returns the out pytree as numpy; CT and counters update internally."""
        if now is None:
            now = int(time.time())
        trace_id = TRACER.maybe_sample()
        with TRACER.context(trace_id), \
                TRACER.span(trace_id, "engine.classify"), \
                self.metrics.span("classify").timer():
            for attempt in range(self._STALE_RETRIES):
                active = self.active
                try:
                    out, counters = self.datapath.classify(
                        active.tensors, active.snapshot, batch, now)
                    break
                except StalePlacement:
                    # a live patch landed between capturing the handle and
                    # enqueueing: retry against the post-patch snapshot
                    # (same as having dispatched a moment later)
                    if attempt == self._STALE_RETRIES - 1:
                        raise
                    self._await_regen()
        n_valid = int(np.asarray(batch["valid"]).sum())
        self.metrics.add_batch(counters, n_valid)
        self.flowlog.append_batch(batch, out, now,
                                  active.snapshot.ep_ids)
        self.flowmetrics.add_batch(batch, out, now)
        self._observe_batch(batch, out, active.snapshot, now, n_valid)
        return out

    # -- verdict provenance (observe/audit.py + observe/blackbox.py) ------------
    def _observe_batch(self, batch, out, snap, now: int, n_valid: int,
                       steered: bool = False) -> None:
        """Per-finalized-batch provenance hooks: flight-recorder verdict
        summary (always on) + shadow-audit counter-sampled capture. Both
        are internally never-raise; the serving path cannot be taken down
        by its own observers."""
        self.blackbox.record_verdicts(out, n_valid, now)
        self.auditor.maybe_capture(batch, out, snap, now, steered=steered)
        self._fold_rule_hits(out, snap)

    # -- per-rule hit/drop counters (ISSUE 11) ----------------------------------
    def _fold_rule_hits(self, out, snap) -> None:
        """matched_rule provenance → ``policy_rule_hits_total{rule=...}`` /
        ``policy_rule_drops_total{rule=...}``: the unused-rule /
        policy-drift signal. Vectorized (one bincount pass per verdict
        class, Python only over the batch's DISTINCT coordinates — a batch
        matches a handful of rules, not a handful of thousands) and
        capped-cardinality (``rule_metrics_max`` distinct label values
        process-wide, overflow under ``rule="other"``). Never-raise: the
        serving path cannot be taken down by its own observers."""
        cap = self.config.rule_metrics_max
        if cap <= 0 or not isinstance(out, dict) \
                or "matched_rule" not in out:
            return
        try:
            mr = np.asarray(out["matched_rule"])
            allow = np.asarray(out["allow"])
            ran = mr >= 0
            if not bool(ran.any()):
                return
            coords = mr[ran].astype(np.int64)
            allowed = allow[ran]
            # bincount over unique-compacted indices: O(batch), never
            # O(max coordinate) — a 50k-rule world's cell space would
            # otherwise allocate megabyte count arrays per finalized batch
            uniq, inv = np.unique(coords, return_inverse=True)
            hits = np.bincount(inv[allowed], minlength=uniq.size)
            drops = np.bincount(inv[~allowed], minlength=uniq.size)
            with self._rule_fold_lock:
                if snap is not self._rule_label_snap:
                    # labels are coordinate-space-relative; a new snapshot
                    # re-resolves (already-seen label STRINGS keep their
                    # series — the cap is on strings, not snapshots)
                    self._rule_label_memo.clear()
                    self._rule_label_snap = snap
                for u in range(uniq.size):
                    c = int(uniq[u])
                    label = self._rule_label_memo.get(c)
                    if label is None:
                        label = self._resolve_rule_label(c, snap, cap)
                        self._rule_label_memo[c] = label
                    if hits[u]:
                        self.metrics.inc_counter(
                            f'policy_rule_hits_total{{rule="{label}"}}',
                            int(hits[u]))
                    if drops[u]:
                        self.metrics.inc_counter(
                            f'policy_rule_drops_total{{rule="{label}"}}',
                            int(drops[u]))
        except Exception:   # noqa: BLE001
            log.exception("per-rule hit fold failed")
            self.metrics.inc_counter("rule_metrics_errors_total")

    def _resolve_rule_label(self, coord: int, snap, cap: int) -> str:
        """One matched_rule coordinate → its stable label:
        ``ic<id_class>/pc<port_class>[/id<representative identity>]``.
        Holds ``_rule_fold_lock``."""
        npc = max(1, snap.port_classes.n_classes)
        ic, pc = divmod(coord, npc)
        label = f"ic{ic}/pc{pc}"
        if 0 <= ic < snap.id_classes.n_classes:
            rep = int(snap.id_classes.representative[ic])
            if rep >= 0:
                label += f"/id{rep}"
        if label not in self._rule_labels_seen:
            if len(self._rule_labels_seen) >= cap:
                return "other"
            self._rule_labels_seen.add(label)
        return label

    def explain_provenance(self, flows: Sequence[Dict]) -> Dict:
        """Legend for the provenance coordinates in ``flows`` (observe API
        / CLI): matched_rule → id-class/port-class/representative-identity,
        lpm_prefix → the canonical ipcache prefix — resolved against the
        ACTIVE snapshot. Records predating the current revision may name
        coordinates the legend cannot resolve; they return ``resolved:
        False`` rather than a wrong answer."""
        active = self.active
        snap = active.snapshot if active is not None else None
        rules: Dict[str, Dict] = {}
        prefixes: Dict[str, Dict] = {}
        for r in flows:
            mr = int(r.get("matched_rule", -1))
            if mr >= 0 and str(mr) not in rules:
                if snap is not None:
                    npc = max(1, snap.port_classes.n_classes)
                    ic, pc = divmod(mr, npc)
                    ok = 0 <= ic < snap.id_classes.n_classes
                    rep = int(snap.id_classes.representative[ic]) \
                        if ok else -1
                    rules[str(mr)] = {
                        "resolved": ok, "id_class": ic, "port_class": pc,
                        "rep_identity": rep,
                        "label": self._rule_label_memo.get(
                            mr, f"ic{ic}/pc{pc}")}
                else:
                    rules[str(mr)] = {"resolved": False}
            lp = int(r.get("lpm_prefix", -1))
            if lp >= 0 and str(lp) not in prefixes:
                if snap is not None:
                    d = snap.lpm.describe(lp)
                    d["resolved"] = d["prefix"] is not None
                    prefixes[str(lp)] = d
                else:
                    prefixes[str(lp)] = {"resolved": False}
        return {"rules": rules, "prefixes": prefixes,
                "revision": active.revision if active is not None else -1}

    def _on_parity_mismatch(self, detail: Dict) -> None:
        """Auditor mismatch sink: narrate to the flight recorder (which
        freezes a debug bundle on this kind — the offending rows + revision
        ride in the detail) and pin the degraded flag health() folds in."""
        self.metrics.set_gauge("parity_audit_degraded", 1)
        self.blackbox.record_event("parity-mismatch", **detail)

    def _pipeline_event(self, kind: str, **attrs) -> None:
        """Pipeline guard-event sink → flight recorder (breaker
        transitions, watchdog restarts, sheds)."""
        self.blackbox.record_event(kind, **attrs)

    def audit_step(self, budget: Optional[int] = None) -> Optional[Dict]:
        """One parity-audit replay sweep (the ``parity-audit`` controller
        body; also directly callable from tests/drills)."""
        return self.auditor.step(budget=budget)

    def debug_bundle(self, clear: bool = False) -> Dict:
        """The flight-recorder export: the frozen anomaly bundle when one
        exists (parity mismatch, breaker open, watchdog restart, shed
        spike), else a live snapshot — enriched with the engine state an
        operator needs to replay it (health, pipeline/feeder stats, audit
        counters + mismatch details, active revision). ``clear=True`` is
        the operator re-arm: the recorder unfreezes AND the auditor's
        mismatch state resets, so health() returns to OK and the next
        divergence degrades/freezes afresh."""
        active = self._active
        extra = {
            "health": self.health(),
            "active_revision": active.revision if active else None,
            "pipeline": self.pipeline_stats(),
            "feeder": self.feeder_stats(),
            "audit": self.auditor.stats(),
            "audit_mismatches": list(self.auditor.mismatches),
            "blackbox": self.blackbox.stats(),
        }
        doc = self.blackbox.bundle(extra=extra, clear=clear)
        if clear:
            self.auditor.rearm()
            self.metrics.set_gauge("parity_audit_degraded", 0)
        return doc

    # -- pipelined ingestion (pipeline/scheduler.py) ----------------------------
    def start_pipeline(self):
        """The async ingestion path beside :meth:`classify`: a bounded-queue
        scheduler that coalesces sub-full submissions into bucketed shapes
        and overlaps host staging/transfer with the previous batch's device
        compute (``DatapathBackend.classify_async``). Created lazily; knobs
        come from ``DaemonConfig.pipeline_*``."""
        with self._lock:
            if self._pipeline is None:
                from cilium_tpu.pipeline import Pipeline, PipelineClosed
                if self._pipeline_stopped:
                    raise PipelineClosed(
                        "engine stopped; no new pipeline submissions")
                cfg = self.config
                # flow-sharded backends (the multi-chip mesh) want batches
                # pre-steered: the pipeline's staging ring grows per-shard
                # segments and steers at stage-write time, so one submit()
                # saturates every chip behind the one admission queue.
                # With device-side RSS (rss_mode="device") pipeline_shards
                # is 1 — rows stage contiguously in arrival order, direct
                # bucket-shaped dispatch comes back, and the shard_map
                # body's ppermute exchange owns flow→shard resolution; the
                # mesh size rides along for the per-mesh guard surface.
                shards = getattr(self.datapath, "pipeline_shards", 1)
                rss = getattr(self.datapath, "rss_state", None) or {}
                rss_mode = rss.get("mode", "host")
                mesh_shards = rss.get("shards", shards)
                self._pipeline_sharded = shards > 1
                min_bucket = min(cfg.pipeline_min_bucket, cfg.batch_size)
                if rss_mode == "device":
                    # every bucket must divide the mesh's flow axis (each
                    # chip takes an equal pow2 arrival-order slice)
                    min_bucket = max(min_bucket, mesh_shards)
                self._pipeline = Pipeline(
                    self._pipeline_dispatch, metrics=self.metrics,
                    max_bucket=cfg.batch_size,
                    min_bucket=min_bucket,
                    queue_batches=cfg.pipeline_queue_batches,
                    admission=cfg.pipeline_admission,
                    block_timeout_s=cfg.pipeline_block_timeout_s,
                    flush_ms=cfg.pipeline_flush_ms,
                    inflight=cfg.pipeline_inflight,
                    deadline_ms=cfg.pipeline_deadline_ms,
                    breaker_threshold=cfg.pipeline_breaker_threshold,
                    breaker_cooldown_s=cfg.pipeline_breaker_cooldown_s,
                    stall_timeout_s=cfg.pipeline_stall_timeout_s,
                    max_restarts=cfg.pipeline_max_restarts,
                    restart_backoff_s=cfg.pipeline_restart_backoff_s,
                    n_shards=shards,
                    shard_fn=self._pipeline_shard_of if shards > 1
                    else None,
                    shard_headroom=cfg.pipeline_shard_headroom,
                    # pre-binned shards are only trusted while the binning
                    # revision is still active (LB changes move the
                    # post-DNAT steer hash)
                    shard_rev_fn=(lambda: self._active.revision
                                  if self._active is not None else -1)
                    if shards > 1 else None,
                    mesh_shards=mesh_shards,
                    rss_mode=rss_mode,
                    event_sink=self._pipeline_event,
                    # device-loss park protocol (ISSUE 19): a DeviceLost
                    # dispatch failure parks the worker and signals here
                    # instead of spending restart budget on a chip that
                    # will not come back; the mesh-heal controller answers
                    # with the fenced re-mesh. Unsharded (or healing
                    # disabled): no callback — DeviceLost degrades to the
                    # breaker path like any other dispatch failure.
                    on_device_loss=self._on_device_loss
                    if self._pipeline_sharded and cfg.remesh_enabled
                    else None,
                    qos=self.qos,
                    # the lane shape must stay a valid bucket: within
                    # [1, min_bucket] and, on a device-RSS mesh, still
                    # divisible across the flow axis (>= mesh_shards)
                    lane_bucket=min(max(cfg.qos_lane_bucket,
                                        mesh_shards
                                        if rss_mode == "device" else 1),
                                    min_bucket)
                    if self.qos is not None else 0)
            return self._pipeline

    def submit(self, batch: Dict[str, np.ndarray],
               now: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               ingest_mono: Optional[float] = None):
        """Admit one batch into the ingestion pipeline; returns a Ticket
        whose ``result()`` is bit-identical to what :meth:`classify` would
        return for the same batch in the same order. ``deadline_ms``
        bounds staleness (default ``config.pipeline_deadline_ms``); a
        submission the worker cannot serve in time is shed with
        ``PipelineDeadlineExceeded``. ``ingest_mono`` (monotonic seconds)
        is the producer's harvest stamp — it rides the ticket so
        verdict-apply can compute true ingest→verdict latency. Raises
        ``PipelineUnavailable`` while the dispatch circuit breaker is open
        or after the pipeline hard-failed (watchdog restart budget
        exhausted)."""
        return self.start_pipeline().submit(batch, now=now,
                                            deadline_ms=deadline_ms,
                                            ingest_mono=ingest_mono)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait until every pipeline submission so far has resolved."""
        pl = self._pipeline           # local ref: stop() may null the field
        if pl is None:
            return True
        return pl.drain(timeout=timeout)

    def pipeline_stats(self) -> Optional[Dict]:
        pl = self._pipeline
        return pl.stats() if pl is not None else None

    def _pipeline_shard_of(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        """Per-row flow-shard ids for the sharded staging ring: the
        direction-normalized flow hash over the ACTIVE snapshot's LB tables
        (service flows steer by their post-DNAT tuple — the same
        translation the datapath and the shim run), mod the mesh's flow
        axis. Called from the pipeline worker at stage-write time for rows
        the producer didn't pre-bin."""
        from cilium_tpu.parallel.mesh import flow_shard_of
        snap = self.active.snapshot
        lb = snap.lb if snap.lb.n_frontends else None
        return flow_shard_of(batch, self.datapath.pipeline_shards, lb=lb)

    def _pipeline_dispatch(self, batch: Dict[str, np.ndarray], now: int,
                           steer_rev: Optional[int] = None):
        """One microbatch through the datapath (called from the pipeline
        worker). Captures the active snapshot per dispatch — same revision
        fencing as classify — and defers metrics/flow-log to finalize, when
        the verdicts are actually on the host. ``steer_rev`` (sharded
        pipelines) is the revision the bucket was steered under; when a
        regen slipped in between stage-write and now, the batch is handed
        to the datapath un-pre-steered so it re-steers against THIS
        snapshot's LB tables (counted ``pack_fallback_steered`` — rare and
        attributable) instead of stranding service flows' CT entries on
        the wrong shard."""
        for attempt in range(self._STALE_RETRIES):
            active = self.active
            raw = batch.get("_ep_raw")
            if raw is not None and raw.any():
                # shim-fed rows carry their raw endpoint ids (raw != 0):
                # re-map them onto THIS dispatch's snapshot — slots are
                # re-enumerated on regen, so a harvest-time mapping can go
                # stale in the queue and classify rows under another
                # endpoint's policy. Unknown ids fail closed; rows without a
                # raw id (non-shim producers coalesced into the same bucket)
                # keep their submitted ep_slot untouched. Vectorized via the
                # same per-snapshot LUT the feeder uses (cached; one worker
                # thread calls this, no lock needed). Re-runs on a
                # StalePlacement retry: the mapping must follow the
                # snapshot actually classifying.
                from cilium_tpu.shim.feeder import build_slot_lut, \
                    map_raw_slots
                snap = active.snapshot
                if snap is not self._remap_snap:
                    self._remap_lut = build_slot_lut(snap.ep_slot_of)
                    self._remap_snap = snap
                slots = map_raw_slots(raw, snap.ep_slot_of, self._remap_lut)
                has = raw != 0
                good = has & (slots >= 0)
                batch["ep_slot"][good] = slots[good]
                batch["valid"] &= ~(has & (slots < 0))
            try:
                with self.metrics.span("pipeline_dispatch").timer():
                    # a sharded pipeline's staging ring delivers rows already
                    # grouped into per-shard segments: the datapath packs
                    # them in place and ships each chip its own segment —
                    # verdicts come back in the steered geometry, un-steered
                    # per-ticket by the pipeline's finalize gather. The kwarg
                    # rides only on sharded engines so duck-typed 4-arg
                    # backends stay compatible.
                    if self._pipeline_sharded:
                        fin = self.datapath.classify_async(
                            active.tensors, active.snapshot, batch, now,
                            pre_steered=steer_rev is not None
                            and steer_rev == active.revision)
                    else:
                        fin = self.datapath.classify_async(
                            active.tensors, active.snapshot, batch, now)
                break
            except StalePlacement:
                # a live delta patch donated the captured handle's buffers
                # between capture and enqueue — wait out the regeneration
                # swap and dispatch against the patched snapshot
                if attempt == self._STALE_RETRIES - 1:
                    raise
                self._await_regen()

        def finalize():
            out, counters = fin()
            if batch.get("_canary") is not None:
                # scheduler recovery canary (ISSUE 19): synthetic
                # all-invalid rows proving the datapath round-trips after
                # a restart/re-mesh — invisible to every accounting
                # surface (no metrics, flow log, observers, or grace-
                # window learning)
                return out
            n_valid = int(np.asarray(batch["valid"]).sum())
            self.metrics.add_batch(counters, n_valid)
            self.flowlog.append_batch(batch, out, now,
                                      active.snapshot.ep_ids)
            self.flowmetrics.add_batch(batch, out, now)
            # the finalize capture hook: batch/out are still the live
            # (un-recycled) staging views here — the audit copy happens
            # before the scheduler recycles the buffer
            self._observe_batch(batch, out, active.snapshot, now, n_valid,
                                steered=self._pipeline_sharded)
            # CT-salvage grace window (ISSUE 19): strictly AFTER the
            # audit capture — the auditor judges the datapath's raw
            # verdict (oracle parity must stay exact), while the APPLIED
            # verdict rides the bounded established-fingerprint grace
            return self._ct_salvage_apply(batch, out)
        return finalize

    # -- async shim ingestion (shim/feeder.py) ----------------------------------
    def start_feeder(self, shim):
        """Attach an async shim→pipeline feeder: a harvest thread polls
        ``shim`` on a budget, submits harvested batches into the ingestion
        pipeline (reusable poll buffers, no per-poll allocation), and
        applies verdicts FIFO as tickets resolve — replacing the
        synchronous poll→classify→apply loop. Knobs: ``ingest_*`` in
        DaemonConfig. Stopped (drained) by :meth:`stop`."""
        with self._lock:
            if self._feeder is not None:
                return self._feeder
            from cilium_tpu.shim.feeder import ShimFeeder
            cfg = self.config
            if shim.batch_size > cfg.batch_size:
                # fail fast: a harvest batch that can't fit the pipeline's
                # largest bucket would reject EVERY submission — the feeder
                # would run "healthy" while fail-closing 100% of traffic
                raise ValueError(
                    f"shim batch_size {shim.batch_size} exceeds the "
                    f"pipeline's max bucket (batch_size={cfg.batch_size})")
            self.start_pipeline()
            if cfg.fqdn_proxy_enabled and self._dns_proxy is None:
                # in-band DNS plane: the learning tap rides the feeder's
                # verdict-apply path (fqdn/proxy.py — fail-open, counted)
                from cilium_tpu.fqdn.proxy import DNSProxy
                self._dns_proxy = DNSProxy(
                    self.ctx.fqdn_cache, metrics=self.metrics,
                    min_ttl=cfg.fqdn_min_ttl, port=cfg.fqdn_proxy_port)
            self._feeder = ShimFeeder(
                shim, self,
                pool_batches=cfg.ingest_pool_batches,
                poll_budget=cfg.ingest_poll_budget,
                idle_sleep_s=cfg.ingest_idle_sleep_s,
                slo_ms=cfg.slo_e2e_ms,
                # steered mesh: harvest computes the flow-shard hash during
                # ep-slot mapping (vectorized, shares flow_shard_of) so the
                # staging ring's flush-time scatter is a copy, not a
                # re-hash — the feeder IS the software RSS. With
                # rss_mode="device" pipeline_shards is 1: pre-binning
                # disappears from the harvest path entirely (the in-kernel
                # ppermute exchange owns flow→shard resolution).
                n_shards=getattr(self.datapath, "pipeline_shards", 1),
                metrics=self.metrics, tracer=self.tracer,
                # SHED-NEW harvest drops narrate to the flight recorder
                # (the relaxed shed-spike class) like pipeline sheds do
                event_sink=self._pipeline_event,
                # QoS armed: harvest stamps the per-row tenant id the
                # admission queue's weighted-fair scheduling keys on
                qos=self.qos,
                # DNS plane armed: poll buffers grow the payload columns
                # and the verdict-apply path taps the learning proxy
                fqdn=self._dns_proxy).start()
            return self._feeder

    def feeder_stats(self) -> Optional[Dict]:
        fd = self._feeder
        return fd.stats() if fd is not None else None

    def _ct_pressure_update(self, occ_frac: float) -> None:
        """Hysteresis latch for the CT emergency-GC mode: enter above
        ``ct_pressure_high``, exit below ``ct_pressure_low``. Transitions
        are gauged, counted and narrated to the flight recorder (recorded,
        never frozen — commanded degradation is the system working)."""
        cfg = self.config
        if not self._ct_emergency and occ_frac >= cfg.ct_pressure_high:
            self._ct_emergency = True
            self.metrics.set_gauge("ct_emergency_gc", 1)
            self.metrics.inc_counter("ct_emergency_entries_total")
            self.blackbox.record_event("ct-emergency", action="enter",
                                       occupancy=round(occ_frac, 4))
        elif self._ct_emergency and occ_frac <= cfg.ct_pressure_low:
            self._ct_emergency = False
            self.metrics.set_gauge("ct_emergency_gc", 0)
            self.blackbox.record_event("ct-emergency", action="exit",
                                       occupancy=round(occ_frac, 4))

    # -- mesh self-healing (ISSUE 19): device-loss detection → fenced
    # re-mesh onto survivors → CT salvage → hysteretic re-admission ----------
    def _on_device_loss(self, device: int, reason: str) -> None:
        """Pipeline → engine device-loss signal. Runs on the pipeline
        worker mid-failure handling, so it must not call back into the
        pipeline — the freezing ``device-loss`` flight-recorder event
        already rode the event sink; here only the attribution counter
        and the heal-hysteresis reset (a chip that just died restarts
        its probe-pass streak from zero)."""
        self.metrics.inc_counter(
            f'device_loss_total{{device="{device}"}}')
        self._heal_ok_since = None

    def remesh_step(self) -> Optional[Dict]:
        """One tick of the ``mesh-heal`` controller (directly callable
        from the cfg10 bench/tests for deterministic driving).

        DOWN: any latched-dead ordinal still in the serving set triggers
        a fenced re-mesh onto the survivors — the wedged in-flight window
        is rejected, queued submissions survive, CT salvages, and the
        bounded established-fingerprint grace window arms.

        UP: configured-but-departed ordinals are canary-probed
        (``probe_device``: the chaos drill first, then a real host→device
        round trip); only after every probe has passed continuously for
        ``remesh_heal_hysteresis_s`` does the reverse re-mesh re-admit
        them — a flapping chip re-zeroes the streak via
        :meth:`_on_device_loss` and never thrashes the mesh."""
        cfg = self.config
        dp = self.datapath
        mh = getattr(dp, "mesh_health", None)
        if not cfg.remesh_enabled or mh is None:
            return None
        h = mh()
        if h["configured"] <= 1:
            return None
        live = list(h["live_ordinals"])
        dead = set(h["dead_ordinals"])
        doc: Dict = {"configured": h["configured"], "live": len(live),
                     "remesh": None}
        dead_live = [o for o in live if o in dead]
        if dead_live:
            target = [o for o in live if o not in dead]
            if not target:
                # every shard latched dead: nothing to re-mesh onto —
                # the parked pipeline's guard surface tells that story
                doc["remesh"] = "no-survivors"
                return doc
            doc["remesh"] = self._remesh_to(target, reason="device-loss")
            return doc
        departed = [o for o in range(h["configured"]) if o not in live]
        if not departed:
            self._heal_ok_since = None
            return doc
        healthy = [o for o in departed if dp.probe_device(o)]
        if not healthy:
            self._heal_ok_since = None
            return doc
        now = time.monotonic()
        if self._heal_ok_since is None:
            self._heal_ok_since = now
        doc["heal_ok_s"] = round(now - self._heal_ok_since, 3)
        if now - self._heal_ok_since >= cfg.remesh_heal_hysteresis_s:
            for o in healthy:
                dp.note_device_healed(o)
            self._heal_ok_since = None
            doc["remesh"] = self._remesh_to(sorted(live + healthy),
                                            reason="heal")
        return doc

    def _remesh_to(self, target, reason: str) -> Optional[Dict]:
        """Fenced re-mesh to exactly the ``target`` ordinals: fence the
        pipeline generation (wedged in-flight window rejected with an
        attributable PipelineError, queued submissions survive), swap the
        datapath mesh with CT salvage, then recompile and re-place the
        snapshot onto the survivor mesh under a BUMPED revision — the
        bump is the steering fence: every pre-binned ``_shard`` stamp
        hashed mod the old flow-axis width becomes visibly stale and is
        re-steered at stage-write. The scheduler adopts the returned
        geometry (n_shards / mesh_shards / re-clamped min_bucket) and
        restarts dispatch canary-first."""
        dp = self.datapath
        with self._remesh_lock:
            old = int(dp.n_flow_shards)
            result: Dict = {}

            def rebuild():
                with self._lock:
                    active = self._active
                    res = dp.remesh(
                        target,
                        fence_handle=active.tensors
                        if active is not None else None,
                        salvage_floor=self._ct_salvage_arrays())
                    result.update(res)
                    if not res.get("noop"):
                        # the incremental compiler's patch path targets
                        # the now-fenced placement: discard it and force
                        # a full compile+place on the NEW mesh. A compile
                        # failure here raises through — the scheduler
                        # restarts the generation and the breaker narrates
                        # the doubly-degraded state; serving the fenced
                        # handle would only StalePlacement forever.
                        self._inc = None
                        self.repo.bump_revision()
                        self._dirty_event.set()
                        self._regenerate_locked(force=True)
                new_mesh = int(dp.n_flow_shards)
                min_bucket = min(self.config.pipeline_min_bucket,
                                 self.config.batch_size)
                rss = getattr(dp, "rss_state", None) or {}
                if rss.get("mode") == "device":
                    # every bucket must still divide the (new) flow axis
                    min_bucket = max(min_bucket, new_mesh)
                return {"n_shards": getattr(dp, "pipeline_shards", 1),
                        "mesh_shards": new_mesh,
                        "min_bucket": min_bucket}

            pl = self._pipeline
            if pl is not None:
                geom = pl.remesh(rebuild, reason=reason)
            else:
                # no pipeline (classify-only engines, unit drills): the
                # datapath swap alone is the whole fence
                geom = rebuild()
                self._pipeline_event(
                    "remesh", ok=True, reason=reason,
                    n_shards=geom["n_shards"],
                    mesh_shards=geom["mesh_shards"])
            if result.get("noop"):
                return result
            new = int(result.get("to", dp.n_flow_shards))
            self.metrics.inc_counter(
                f'datapath_remesh_total{{from="{old}",to="{new}"}}')
            if new < old:
                # the grace window arms only on the DEGRADE direction:
                # healing back carries the whole salvaged table with it
                self._salvage_until = time.monotonic() \
                    + self.config.remesh_grace_s
            self._remesh_last = {**result, "reason": reason,
                                 "geometry": geom, "t": time.time()}
            return self._remesh_last

    def _ct_salvage_apply(self, batch: Dict[str, np.ndarray],
                          out: Dict[str, np.ndarray]
                          ) -> Dict[str, np.ndarray]:
        """Post-audit verdict overlay for the bounded post-remesh grace
        window. Every finalized batch feeds the established-fingerprint
        filter (the window must be warm BEFORE a loss); while the window
        is open, denied rows whose fingerprint was stamped established
        pre-loss flip to allow — established flows ride over the lost
        shard's CT while forward packets cold-learn entries on the
        survivor mesh. Counted ``ct_salvage_grace_hits_total``; never
        raises; copies on flip (the auditor holds the raw arrays)."""
        try:
            self._salvage_fp.note(batch, out)
            if time.monotonic() >= self._salvage_until:
                return out
            allow = np.asarray(out["allow"])
            m = (np.asarray(batch["valid"]) & ~allow
                 & self._salvage_fp.hits(batch))
            n = int(m.sum())
            if not n:
                return out
            out = dict(out)
            allow = allow.copy()
            allow[m] = True
            reason = np.asarray(out["reason"]).copy()
            reason[m] = 0
            out["allow"] = allow
            out["reason"] = reason
            self.metrics.inc_counter("ct_salvage_grace_hits_total", n)
        except Exception:   # noqa: BLE001 — overlay, never load-bearing
            log.exception("ct-salvage grace overlay failed")
        return out

    def _ct_salvage_arrays(self) -> Optional[Dict[str, np.ndarray]]:
        """The archive salvage floor for a re-mesh whose device gather
        fails (the chip died holding the collective): the newest
        ct-snapshot archive, or None when the controller is off / has not
        written one — the re-mesh then falls through to a cold table."""
        d = self.config.ct_snapshot_dir
        if not d:
            return None
        from cilium_tpu.runtime import checkpoint
        newest = checkpoint.newest_ct_archive(d)
        if newest is None:
            return None
        return checkpoint.load_ct_archive(newest)

    def ct_snapshot_step(self, now: Optional[float] = None
                         ) -> Optional[Dict]:
        """One tick of the ``ct-snapshot`` controller: gather the CT
        table to host and write one timestamped archive (atomic,
        self-describing format, pruned to ``ct_snapshot_keep``) — the
        bounded-staleness salvage floor. The ``device.collective`` chaos
        point fails the gather here exactly like it fails a re-mesh
        gather; controller supervision backs off, the archive ages, and
        ``checkpoint_age_seconds`` / CHECKPOINT_STALE make the lost
        redundancy visible (the ``finally`` keeps the age gauge honest
        even on a failing tick; -1 = no archive yet)."""
        cfg = self.config
        if not cfg.ct_snapshot_dir:
            return None
        from cilium_tpu.runtime import checkpoint as ckpt
        if now is None:
            now = time.time()
        doc = None
        try:
            FAULTS.fire("device.collective")
            arrays = self.datapath.ct_arrays()
            path = ckpt.save_ct_archive(cfg.ct_snapshot_dir, arrays,
                                        keep=cfg.ct_snapshot_keep)
            doc = {"path": path,
                   "entries": int((arrays["expiry"] > 0).sum())}
        finally:
            age = ckpt.ct_archive_age_s(cfg.ct_snapshot_dir, now=now)
            self.metrics.set_gauge(
                "checkpoint_age_seconds",
                round(age, 3) if age is not None else -1.0)
        return doc

    def remesh_status(self) -> Dict:
        """Operator surface for the self-healing plane (``/v1/status`` /
        CLI): mesh width + per-device health from the datapath, the last
        re-mesh record, cumulative salvage stats, and the live grace
        window."""
        dp = self.datapath
        mh = getattr(dp, "mesh_health", None)
        doc: Dict = {"enabled": bool(self.config.remesh_enabled),
                     "mesh": mh() if mh is not None else None,
                     "last_remesh": self._remesh_last,
                     "stats": dict(getattr(dp, "remesh_stats", {}) or {})}
        grace = self._salvage_until - time.monotonic()
        doc["salvage_grace_remaining_s"] = round(grace, 3) \
            if grace > 0 else 0.0
        return doc

    def sweep(self, now: Optional[int] = None) -> int:
        """CT garbage collection, host-driven whole-table mode (upstream
        ctmap GC): blocks on the device sweep. The ct-gc controller only
        runs this for backends without the overlapped device sweep (or
        with ``ct_gc_overlap`` off); it remains directly callable for
        tests/CLI. In emergency mode (occupancy latched above
        ``ct_pressure_high``) the sweep runs with the effective TTL
        slashed — entries within ``ct_gc_emergency_ttl_slash_s`` of expiry
        are reclaimed early."""
        if now is None:
            now = int(time.time())
        eff_now = now + (self.config.ct_gc_emergency_ttl_slash_s
                         if self._ct_emergency else 0)
        reclaimed = self.datapath.sweep(eff_now)
        self.metrics.set_gauge("ct_last_sweep_reclaimed", reclaimed)
        if reclaimed:
            self.metrics.inc_counter("ct_gc_reclaimed_total", reclaimed)
        # occupancy export: on the JIT backend ct_stats copies the expiry
        # column host-side under the classify lock (~4MB at the default
        # capacity) — acceptable at this path's sweep_interval_s cadence,
        # and the reason the overlapped sweep_step derives occupancy
        # on-device instead of ever calling this
        st = self.datapath.ct_stats(now)
        occ = st["live"] / max(1, st["capacity"])
        self.metrics.set_gauge("ct_occupancy", round(occ, 6))
        self._ct_pressure_update(occ)
        return reclaimed

    def sweep_step(self, now: Optional[int] = None) -> Optional[Dict]:
        """One tick of the overlapped device-side CT GC (the ``ct-gc``
        controller body on capable backends): enqueue a donated chunk sweep
        that interleaves with live classify steps, harvest the previous
        tick's reclaimed/occupancy scalars, and export them
        (``ct_gc_reclaimed_total`` counter, ``ct_occupancy`` gauge — a
        live/capacity FRACTION). The ``ct.gc`` fault point drills the
        controller's supervised backoff.

        EMERGENCY mode (hysteresis latch on occupancy, see
        ``_ct_pressure_update``): the tick runs ``ct_gc_emergency_chunks``
        chunk sweeps instead of one, each with the effective TTL slashed
        by ``ct_gc_emergency_ttl_slash_s`` — full-rate reclamation that
        eats a flood's short-lived entries while leaving established
        flows' 21600s lifetimes untouched. Occupancy always measures on
        the REAL clock (only the sweep threshold is slashed): a slashed
        count would exclude live entries the sweep has not reached yet,
        read artificially low, and flap the enter/exit latch under a
        sustained flood."""
        FAULTS.fire("ct.gc")
        if now is None:
            now = int(time.time())
        cfg = self.config
        emergency = self._ct_emergency
        chunks = cfg.ct_gc_emergency_chunks if emergency else 1
        slash = cfg.ct_gc_emergency_ttl_slash_s if emergency else 0
        # GC ticks are rare: always trace one (the datapath.ct.gc span
        # needs a context to attach to)
        reclaimed = 0
        with TRACER.context(TRACER.force_sample()):
            for _ in range(chunks):
                # the slash rides only the SWEEP clock; occupancy keeps
                # measuring at the real `now` (a slashed count would read
                # low and flap the pressure latch)
                st = self.datapath.sweep_step(now, cfg.ct_gc_chunk_rows,
                                              ttl_slash_s=slash)
                reclaimed += st["reclaimed"]
        if emergency:
            self.metrics.inc_counter("ct_emergency_sweeps_total", chunks)
        if reclaimed:
            self.metrics.inc_counter("ct_gc_reclaimed_total", reclaimed)
        if st["live"] >= 0:
            occ = st["live"] / max(1, cfg.ct_capacity)
            self.metrics.set_gauge("ct_occupancy", round(occ, 6))
            self._ct_pressure_update(occ)
        self.metrics.set_gauge("ct_gc_epoch", st["epoch"])
        self.metrics.set_gauge("ct_gc_cursor", st["cursor"])
        st = dict(st)
        st["reclaimed"] = reclaimed
        st["emergency"] = emergency
        return st

    def overload_step(self) -> Optional[Dict]:
        """One tick of the overload-ladder controller (the ``overload``
        controller body; directly callable from the cfg6 bench/tests for
        deterministic logical-time driving). Folds queue occupancy,
        shed+admission-drop rate, and CT occupancy into the
        pipeline/guard.OverloadLadder state machine and propagates the
        state to the shedding sites: the admission queue (priority
        shedding at PRESSURE, fail-fast at OVERLOAD) and the shim feeder
        (harvest-time SHED-NEW). Transitions are gauged, counted, and
        recorded as flight-recorder events (never frozen — the ladder IS
        the system surviving). The ``overload.decide`` fault point drills
        the controller's supervised backoff: a failing decider leaves the
        last propagated state standing."""
        FAULTS.fire("overload.decide")
        cfg = self.config
        if self._overload is None:
            from cilium_tpu.pipeline.guard import OverloadLadder
            self._overload = OverloadLadder(
                queue_high=cfg.overload_queue_high,
                queue_low=cfg.overload_queue_low,
                shed_high=cfg.overload_shed_rate_high,
                shed_low=cfg.overload_shed_rate_low,
                ct_high=cfg.ct_pressure_high,
                ct_low=cfg.ct_pressure_low,
                resource_high=cfg.overload_resource_high,
                resource_low=cfg.overload_resource_low,
                up_ticks=cfg.overload_up_ticks,
                down_ticks=cfg.overload_down_ticks)
        pl = self._pipeline
        ps = pl.stats() if pl is not None else None
        fd = self._feeder
        # the feeder's harvest-time prio sheds ride the shed signal too:
        # under SHED-NEW the queue pressure vanishes BY DESIGN (that is the
        # relief), and without this term the ladder would descend mid-storm
        # and oscillate — sustained harvest shedding keeps the rung held
        # until the flood actually stops
        fd_shed = fd.prio_shed_rows if fd is not None else 0
        if ps is not None:
            queue_frac = ps["queue_depth"] / max(1, ps["queue_max"])
            shed_now = ps["shed_total"] + ps["admission_drops"] + fd_shed
        else:
            queue_frac = 0.0
            shed_now = self._overload_shed_prev
        t = time.monotonic()
        dt = (t - self._overload_shed_t) if self._overload_shed_t \
            else cfg.overload_interval_s
        rate = max(0, shed_now - self._overload_shed_prev) / max(dt, 1e-3)
        self._overload_shed_prev = shed_now
        self._overload_shed_t = t
        ct_occ = float(self.metrics.gauges.get("ct_occupancy", 0.0))
        # the ledger's fourth latch: worst NON-CT failure-class pressure
        # (CT and the admission queue are already the ladder's own
        # signals; graceful-degradation pools report pressure 0 anyway)
        exclude = LADDER_EXCLUDE
        if self.qos is not None:
            # per-tenant queue rows must not light the GLOBAL ladder: one
            # tenant hitting its own cap is isolation working as designed
            # (the aggregate admission_queue signal already covers real
            # queue pressure) — tenant-scoped relief happens at the
            # admission sites (over_share fail-fast, pressure-ordered
            # victim selection), not on the cluster-wide rung
            exclude = tuple(LADDER_EXCLUDE) + tuple(
                f"qos_tenant_queue_{n}"
                for n in self.qos.tenants().values())
        res_p = self.ledger.max_pressure(exclude=exclude)
        state, changed = self._overload.observe(queue_frac, rate, ct_occ,
                                                resource_pressure=res_p)
        if pl is not None:
            pl.set_overload_state(state)
        fd = self._feeder
        if fd is not None:
            fd.set_overload_state(state)
        self.metrics.set_gauge("overload_state", state)
        if changed:
            from cilium_tpu.pipeline.guard import OVERLOAD_STATE_NAMES
            name = OVERLOAD_STATE_NAMES[state]
            self.metrics.inc_counter(
                f'overload_transitions_total{{to="{name}"}}')
            self.blackbox.record_event(
                "overload", state=name,
                queue_frac=round(queue_frac, 4),
                shed_rate=round(rate, 2),
                ct_occupancy=round(ct_occ, 4),
                resource_pressure=round(res_p, 4))
        return self._overload.status()

    def overload_status(self) -> Optional[Dict]:
        ov = self._overload
        return ov.status() if ov is not None else None

    def qos_status(self) -> Optional[Dict]:
        """The multi-tenant QoS document (``/v1/status`` row): tenant
        table (weights/lanes/caps/assignments) plus the live per-tenant
        admission picture when the pipeline is up. None when QoS is off —
        the status document stays byte-identical to the pre-QoS shape."""
        if self.qos is None:
            return None
        doc: Dict = dict(self.qos.stats())
        pl = self._pipeline
        if pl is not None:
            ps = pl.stats()
            doc["tenants"] = ps.get("tenants", {})
            doc["lane_bucket"] = ps.get("lane_bucket", 0)
            doc["lane_fill_rows"] = ps.get("lane_fill_rows", 0)
            doc["lane_bucket_rows"] = ps.get("lane_bucket_rows", 0)
        return doc

    def fqdn_status(self) -> Dict:
        """The in-band DNS plane document (``status.fqdn``): cache
        occupancy/bounds/high-water, proxy learning counters (frames
        seen, answers observed, parse errors — the fail-open loss
        signal), and the repository's refresh-coalescing / identity
        lifecycle counters."""
        doc: Dict = {
            "proxy_enabled": bool(self.config.fqdn_proxy_enabled),
            "cache": self.ctx.fqdn_cache.stats(),
            "refresh_coalesced": self.repo.fqdn_refresh_coalesced,
            "identities_created": self.repo.fqdn_identities_created,
        }
        px = self._dns_proxy
        if px is not None:
            doc["proxy"] = px.stats()
        return doc

    # -- resource pressure ledger (observe/pressure.py; ISSUE 13) --------------
    # Provider contract: each returns {resource: (capacity, occupancy)} or
    # (capacity, occupancy, pressure) — the 3-tuple hands through a
    # canonical pressure fraction. Structures that degrade GRACEFULLY at
    # full occupancy (drop-oldest rings, LRU caches, backpressure pools)
    # report explicit pressure 0.0: occupancy/high-water stay visible but
    # "full" is their steady state, not a capacity failure — only
    # structures whose exhaustion sheds/fails (CT, queue, shard segments,
    # budgets) carry failure-signal pressure.
    def _register_resources(self) -> None:
        self.ledger.register("ct", self._res_ct)
        self.ledger.register("pipeline", self._res_pipeline)
        self.ledger.register("feeder", self._res_feeder)
        if self.qos is not None:
            self.ledger.register("qos", self._res_qos)
        self.ledger.register("compile", self._res_compile)
        self.ledger.register("observe", self._res_observe)
        self.ledger.register("datapath", self._res_datapath)
        self.ledger.register("fqdn", self._res_fqdn)

    def _res_ct(self) -> Dict:
        # the ct_occupancy gauge IS the canonical fraction: hand it
        # through verbatim so the resource row and the gauge can never
        # disagree (the cfg6 bench gates on exact equality)
        occ = float(self.metrics.gauges.get("ct_occupancy", 0.0))
        cap = self.config.ct_capacity
        return {"ct_table": (cap, occ * cap, occ)}

    def _res_pipeline(self) -> Dict:
        pl = self._pipeline
        if pl is None:
            return {}
        ps = pl.occupancy_stats()
        out = {
            "admission_queue": (ps["queue_max"], ps["queue_depth"]),
            # staging slots backpressure by design (acquire blocks):
            # informational
            "staging_slots": (ps["staging_slots"],
                              ps["staging_slots"] - ps["staging_free"],
                              0.0),
            # capacity is the ring's REAL aggregate (n_shards * seg_cap
            # when sharded — headroom makes that exceed max_bucket, and
            # staged rows legitimately pass max_bucket before a segment
            # fills; max_bucket as capacity would read >100%)
            "staging_ring": (ps["stage_rows"], ps["staged_rows"], 0.0),
        }
        if ps.get("n_shards", 1) > 1:
            # the binding sharded constraint: ONE overfull segment sheds
            # the whole submission (steer_overflow) — a real capacity
            # failure, unlike the flush-on-full aggregate ring
            out["staging_segment_peak"] = (
                ps["shard_capacity"], max(ps["shard_fill"], default=0))
        return out

    def _res_feeder(self) -> Dict:
        fd = self._feeder
        if fd is None:
            return {}
        st = fd.stats()
        # pool exhaustion = FIFO backpressure on the oldest ticket (by
        # design) — informational occupancy, not failure pressure
        return {"feeder_pool": (self.config.ingest_pool_batches,
                                st.get("pending", 0), 0.0)}

    def _res_qos(self) -> Dict:
        # per-tenant admission-queue rows (active tenants only): a tenant
        # that drains/departs stops reporting and the ledger's staleness
        # sweep drops its whole gauge family — the departed-subject
        # discipline (a frozen depth for a gone tenant reads as load).
        # Capped tenants carry real pressure (cap exhaustion sheds, the
        # tenant_cap class); uncapped tenants report informational 0.0 —
        # their bound is the global queue, already a ladder signal.
        pl = self._pipeline
        if pl is None or self.qos is None:
            return {}
        out: Dict = {}
        for name, (cap, depth) in \
                pl.occupancy_stats().get("tenants", {}).items():
            if cap > 0:
                out[f"qos_tenant_queue_{name}"] = (cap, depth)
            else:
                out[f"qos_tenant_queue_{name}"] = (
                    self.config.pipeline_queue_batches, depth, 0.0)
        return out

    def _res_compile(self) -> Dict:
        from cilium_tpu.policy.mapstate import overlay_stats
        cfg = self.config
        st = self._last_update_stats
        # PR 9 patch budgets: all informational (explicit pressure 0.0).
        # At-budget means delta cycles fall back to full uploads/rebuilds
        # — a perf cliff, commanded and graceful, never traffic loss. And
        # delta_rows/new_identities are the LAST cycle's consumption, not
        # a standing occupancy: letting them carry failure pressure would
        # pin health/the ladder's resource latch on an idle engine until
        # the next (unrelated) update happened to be smaller.
        out = {
            "patch_budget": (cfg.patch_delta_rows,
                             st.delta_rows if st is not None else 0, 0.0),
            "ident_growth": (512 if self._inc is None
                             else self._inc.IDENT_GROWTH_MAX,
                             st.new_identities if st is not None else 0,
                             0.0),
            # delta-path retirement (ISSUE 18): same last-cycle-consumption
            # semantics as ident_growth — at budget, the cycle fell back to
            # a full rebuild, a commanded perf cliff
            "ident_retire": (512 if self._inc is None
                             else self._inc.IDENT_RETIRE_MAX,
                             getattr(st, "retired_identities", 0)
                             if st is not None else 0,
                             0.0),
        }
        inc = self._inc
        if inc is not None:
            out["patch_overlay"] = (cfg.patch_rebase_rows,
                                    len(inc._overlay), 0.0)  # noqa: SLF001
        # mapstate overlay folds at budget BY DESIGN (one amortized
        # O(entries) flatten) — occupancy/high-water visibility only; a
        # pre-fold dirty count past the budget must not read as an
        # exhaustion (it would strict-freeze the recorder on commanded
        # behavior)
        ovs = overlay_stats()
        out["mapstate_overlay"] = (ovs["fold_budget"], ovs["last_dirty"],
                                   0.0)
        return out

    def _res_fqdn(self) -> Dict:
        # the FQDN cache bound sheds GRACEFULLY (oldest-expiry eviction,
        # never a crash or a dropped reply) — informational 0.0, but
        # occupancy/high-water/ETA stay visible so a spoofed-response
        # storm pinning the bound is attributable before identities churn
        cache = self.ctx.fqdn_cache
        if cache.max_names <= 0:
            return {}  # unbounded: no capacity to report against
        st = cache.stats()
        return {"fqdn_cache": (cache.max_names, st["names"], 0.0)}

    def _res_observe(self) -> Dict:
        ts = self.tracer.stats()
        bs = self.blackbox.stats()
        aud = self.auditor.stats()
        return {
            # drop-oldest rings wrap by design: informational (their loss
            # accounting lives in spans_dropped_total / follow gaps)
            "trace_ring": (ts["capacity"], ts["spans_in_ring"], 0.0),
            "flowlog_ring": (self.flowlog.capacity, len(self.flowlog),
                             0.0),
            "blackbox_events": (bs["events_capacity"],
                                bs["events_in_ring"], 0.0),
            # the audit capture pool saturating means the replay loop is
            # lagging live capture — real pressure (skips are counted,
            # but sustained skipping blinds the parity contract)
            "audit_pool": (aud["pool_batches"], aud["pending"]),
        }

    def _res_datapath(self) -> Dict:
        out: Dict = {}
        dp = self.datapath
        ws = getattr(dp, "wire_pool_stats", None)
        if ws is not None:
            s = ws()
            # occupancy = buffers checked out with in-flight batches;
            # pool misses allocate (shed to GC) — informational
            out["wire_pool"] = (s["capacity"], s["in_flight"], 0.0)
        hl = getattr(dp, "hbm_ledger", None)
        if hl is not None and self.config.max_hbm_bytes > 0:
            out["hbm"] = (self.config.max_hbm_bytes,
                          hl()["device_bytes"])
        rs = getattr(dp, "rss_exchange_stats", None)
        if rs is not None:
            s = rs()
            if s is not None:
                # device-RSS ppermute exchange buffers: transient per
                # dispatch and sized by the bucket shape — informational
                # occupancy against the worst case at batch_size (a full
                # bucket is the steady serving state, not a failure)
                out["rss_exchange"] = (s["capacity"], s["in_use"], 0.0)
        mh = getattr(dp, "mesh_health", None)
        if mh is not None:
            h = mh()
            if h["configured"] > 1:
                # mesh_width (ISSUE 19): occupancy = devices actually
                # serving, capacity = configured width. A missing chip IS
                # failure pressure, so hand the missing fraction through
                # explicitly — the default occupancy/capacity convention
                # would read the full healthy mesh as the pressured state
                out["mesh_width"] = (
                    h["configured"], h["live"],
                    (h["configured"] - h["live"]) / h["configured"])
        import sys as _sys
        cls_mod = _sys.modules.get("cilium_tpu.kernels.classify")
        if cls_mod is not None:
            cs = cls_mod.fn_cache_stats()
            # LRU: full-with-evictions is a retrace cost, not a failure
            out["classify_fn_cache"] = (cs["cap"], cs["size"], 0.0)
        return out

    def resource_step(self, now: Optional[float] = None) -> Dict:
        """One ledger sweep (the ``resource-ledger`` controller body;
        directly callable from benches/tests with a logical clock for
        deterministic ETA math). Exports the labeled resource_* gauge
        families and fires forecast events; returns the full report."""
        FAULTS.fire("resource.poll")
        return self.ledger.poll(now)

    def resources(self) -> Dict:
        """The ``GET /v1/resources`` document: the ledger's READ side (the
        last controller sweep) plus the device-memory ledger. Deliberately
        side-effect-free — a scrape or a tight ``top --interval`` loop
        must not fire the resource.poll fault point, skew the ETA windows'
        sampling cadence, or be the thing that fires a freeze event; the
        ``resource-ledger`` controller owns sampling."""
        report = self.ledger.report()
        report["hbm"] = self.hbm_status()
        return report

    def hbm_status(self) -> Dict:
        """Live HBM ledger (JIT backends; None on the jax-free fake) plus
        the attached offline verifier budget report — the two surfaces
        ISSUE 13 requires to cite the same numbers."""
        hl = getattr(self.datapath, "hbm_ledger", None)
        return {
            "ledger": hl() if hl is not None else None,
            "max_hbm_bytes": self.config.max_hbm_bytes or None,
            "verifier": self._hbm_budget,
        }

    def note_verifier_budget(self, doc: Dict) -> None:
        """Attach an offline ``compile/verifier.budget_doc`` summary so
        status/bench surfaces cite the same HBM numbers the ``verify
        --max-hbm-bytes`` gate judged."""
        self._hbm_budget = doc

    # -- multi-host sync (runtime/clustermesh.py) -------------------------------
    def attach_mesh(self, store_dir: Optional[str] = None,
                    node_name: Optional[str] = None):
        """Create (or return) this engine's ClusterMesh WITHOUT starting the
        sync controller — deterministic drivers (``bench.py --cluster``,
        tests, chaos drills) tick ``mesh.step()`` themselves;
        ``start_background`` wires the controller on top. Arguments default
        to the config's ``cluster_store``/``node_name``."""
        with self._lock:
            if self._mesh is None:
                from cilium_tpu.runtime.clustermesh import ClusterMesh
                self._mesh = ClusterMesh(
                    self, store_dir or self.config.cluster_store,
                    node_name or self.config.node_name,
                    stale_after_s=self.config.cluster_stale_after_s,
                    staleness_budget_s=self.config.cluster_staleness_budget_s)
            return self._mesh

    def mesh_status(self) -> Optional[Dict]:
        m = self._mesh
        return m.status() if m is not None else None

    def start_background(self) -> None:
        """Start the periodic controllers and (when configured) the REST API
        server on its unix socket (SURVEY.md §3.1 "api server up")."""
        if self.config.api_socket and self._api is None:
            from cilium_tpu.runtime.api import APIServer
            self._api = APIServer(self, self.config.api_socket)
            self._api.start()
        if self.config.cluster_store and self.config.node_name:
            self.attach_mesh()
            self.controllers.update(
                "clustermesh-sync", self._mesh.step,
                interval=self.config.cluster_sync_interval_s)
        if self.config.ct_gc_overlap \
                and hasattr(self.datapath, "sweep_step"):
            # overlapped device-side epoch GC: small donated chunk sweeps
            # interleaved with classify at a tight cadence, reclaim counts
            # harvested one tick late (the double buffer) — classify is
            # never stalled behind a whole-table sweep
            self.controllers.update("ct-gc", self.sweep_step,
                                    interval=self.config.ct_gc_interval_s)
        else:
            self.controllers.update("ct-gc", lambda: self.sweep(),
                                    interval=self.config.sweep_interval_s)
        # expired DNS names must revoke their identities (upstream: fqdn
        # cache GC controller); expire() notifies → re-materialize → regen
        self.controllers.update(
            "fqdn-gc",
            lambda: self.ctx.fqdn_cache.expire(
                int(self.ctx.fqdn_cache.clock())),
            interval=self.config.sweep_interval_s)
        if self.config.flowlog_path or self.config.metrics_path:
            self.controllers.update(
                "obs-flush", self.flush_observability,
                interval=self.config.obs_flush_interval_s)
        if self.config.overload_enabled:
            # the degradation ladder (pipeline/guard.OverloadLadder):
            # queue/shed/CT pressure → OK/PRESSURE/OVERLOAD/SHED-NEW,
            # propagated to the admission queue and the feeder — a
            # supervised controller like every other (a crashing decider
            # backs off; the last propagated state stands)
            self.controllers.update(
                "overload", self.overload_step,
                interval=self.config.overload_interval_s)
        if self.config.resource_ledger_enabled:
            # the resource pressure ledger (observe/pressure.py): one
            # sweep of every registered bounded structure per interval —
            # labeled gauge export, high-water, time-to-exhaustion
            # forecasts into the flight recorder. Supervised like every
            # controller: a crashing poll backs off and the last exported
            # pressure stands.
            self.controllers.update(
                "resource-ledger", self.resource_step,
                interval=self.config.resource_interval_s)
        if self.config.autotune_enabled:
            # the closed loop (observe/autotune.py): queue-wait + fill
            # histograms → bounded flush_ms / bucket-floor adjustments
            self.controllers.update(
                "pipeline-autotune", self._autotune_step,
                interval=self.config.autotune_interval_s)
        if self.config.audit_enabled:
            # the shadow-oracle replay loop (observe/audit.py): supervised
            # like every controller — a crashing/wedged replay backs off
            # and the bounded capture pool degrades to `skipped`, never to
            # a stalled serving path
            self.controllers.update(
                "parity-audit", lambda: self.audit_step(budget=64),
                interval=self.config.audit_interval_s)
        if self.config.remesh_enabled \
                and getattr(self.datapath, "mesh_health", None) is not None:
            # mesh self-healing (ISSUE 19): down-remesh on latched device
            # loss, canary-probe departed chips, hysteretic re-admission
            self.controllers.update(
                "mesh-heal", self.remesh_step,
                interval=self.config.remesh_interval_s)
        if self.config.ct_snapshot_dir:
            # bounded-staleness CT archive — the salvage floor a device-
            # loss re-mesh falls back to when the gather collective fails
            self.controllers.update(
                "ct-snapshot", self.ct_snapshot_step,
                interval=self.config.ct_snapshot_interval_s)

    def _autotune_step(self):
        """One autotune control interval (controller body). No-ops until
        the ingestion pipeline exists; rebinds if the pipeline was
        recreated."""
        pl = self._pipeline
        if pl is None:
            return None
        if self._autotuner is None or self._autotuner.pipeline is not pl:
            from cilium_tpu.observe.autotune import Autotuner
            cfg = self.config
            self._autotuner = Autotuner(
                pl, self.metrics,
                flush_ms_min=cfg.autotune_flush_ms_min,
                flush_ms_max=cfg.autotune_flush_ms_max,
                min_bucket_floor=min(cfg.pipeline_min_bucket,
                                     cfg.batch_size),
                target_fill=cfg.autotune_target_fill,
                queue_wait_p99_budget_ms=cfg.autotune_queue_wait_p99_ms,
                hysteresis=cfg.autotune_hysteresis,
                step_factor=cfg.autotune_step_factor)
        return self._autotuner.step()

    def autotune_status(self) -> Optional[Dict]:
        at = self._autotuner
        return at.status() if at is not None else None

    def health(self) -> Dict:
        """Engine health summary (the supervised-degradation surface).

        States:
          OK        — the active snapshot is the current compiled state
          DEGRADED  — regeneration is failing; serving the last-good
                      snapshot, which is still semantically current — OR
                      the serving pipeline is degraded (breaker open,
                      watchdog restart in progress, or hard-failed) while
                      the synchronous classify path still answers
          STALE     — regeneration is failing AND committed policy changes
                      (repo revision > active revision) cannot be compiled:
                      verdicts are correct for an older policy world

        When the ingestion pipeline exists its guard state
        (ok/breaker-open/restarting/failed — pipeline/guard.py) is folded
        in under the ``pipeline`` key and into the overall ``state``, plus
        the ``pipeline_state`` gauge."""
        with self._lock:
            active = self._active
            state = C.HEALTH_OK
            if self._regen_failures:
                state = C.HEALTH_DEGRADED
                if active is not None and self.repo.revision > active.revision:
                    state = C.HEALTH_STALE
            doc = {
                "state": state,
                "consecutive_regen_failures": self._regen_failures,
                "last_regen_error": self._last_regen_error,
                "active_revision": active.revision if active else None,
                "repo_revision": self.repo.revision,
            }
            pl = self._pipeline
        aud = self.auditor
        if not aud.healthy:
            # a parity mismatch means verdicts diverged from the semantic
            # oracle under a live revision: serving still answers (the
            # sampled mismatch does not prove every verdict wrong), but
            # the daemon is provably not bit-identical — DEGRADED until an
            # operator pulls the debug bundle and re-arms
            doc["audit"] = {
                "mismatched_rows": aud.mismatched_rows,
                "checked_rows": aud.checked_rows,
                "last_mismatch_revision": aud.last_mismatch_revision,
            }
            if doc["state"] == C.HEALTH_OK:
                doc["state"] = C.HEALTH_DEGRADED
        mesh = self._mesh
        if mesh is not None:
            ms = mesh.status()
            # MESH_STALE is a DETAIL, not a serving failure: classify keeps
            # answering from last-good remote state (partition never fails
            # closed on established remote flows) — but the operator must
            # see that the remote view may be behind the mesh
            doc["mesh"] = {
                "state": ms["state"],
                "store_ok": ms["store_ok"],
                "peers": len(ms["peers"]),
                "remote_entries": ms["remote_entries"],
                "last_good_pass_age_s": ms["last_good_pass_age_s"],
                "replication_lag_p99_s": ms["replication_lag_p99_s"],
            }
            if ms["state"] == C.MESH_STALE \
                    and doc["state"] == C.HEALTH_OK:
                doc["state"] = C.HEALTH_DEGRADED
        ov = self._overload
        if ov is not None:
            ost = ov.status()
            # the ladder is COMMANDED degradation: PRESSURE still reports
            # OK (the system is coping by reordering sheds), but OVERLOAD
            # and SHED-NEW mean traffic is being refused wholesale — an
            # operator-attention state
            doc["overload"] = {
                "state": ost["state"],
                "level": ost["level"],
                "since_s": ost["since_s"],
                "inputs": ost["inputs"],
            }
            from cilium_tpu.pipeline.guard import OVERLOAD_OVERLOAD
            if ost["level"] >= OVERLOAD_OVERLOAD \
                    and doc["state"] == C.HEALTH_OK:
                doc["state"] = C.HEALTH_DEGRADED
        mhf = getattr(self.datapath, "mesh_health", None)
        if mhf is not None:
            mw = mhf()
            if mw["configured"] > 1 and (mw["dead_ordinals"]
                                         or mw["live"] < mw["configured"]):
                # device loss (ISSUE 19): serving continues on the
                # survivor mesh — degraded, one fault from losing
                # redundancy — with the live grace window attached so an
                # operator can tell salvage-covered from cold-learning
                grace = self._salvage_until - time.monotonic()
                doc["devices"] = {
                    "detail": C.DEVICE_LOST,
                    "configured": mw["configured"],
                    "live": mw["live"],
                    "dead": mw["dead_ordinals"],
                    "salvage_grace_remaining_s":
                        round(grace, 3) if grace > 0 else 0.0,
                }
                if doc["state"] == C.HEALTH_OK:
                    doc["state"] = C.HEALTH_DEGRADED
        cfg = self.config
        if cfg.ct_snapshot_dir and cfg.checkpoint_max_age_s > 0:
            from cilium_tpu.runtime.checkpoint import ct_archive_age_s
            age = ct_archive_age_s(cfg.ct_snapshot_dir)
            if age is None or age > cfg.checkpoint_max_age_s:
                # CHECKPOINT_STALE (ISSUE 19): the salvage floor a
                # device-loss re-mesh would fall back to no longer
                # reflects recent flows (or was never written)
                doc["checkpoint"] = {
                    "detail": C.CHECKPOINT_STALE,
                    "age_s": round(age, 1) if age is not None else None,
                    "max_age_s": cfg.checkpoint_max_age_s,
                }
                if doc["state"] == C.HEALTH_OK:
                    doc["state"] = C.HEALTH_DEGRADED
        rs = self.ledger.status()
        if rs["pressured"]:
            # RESOURCE_PRESSURE detail (ISSUE 13): some bounded structure
            # is past its warn fraction — the which-runs-out-first answer,
            # with the soonest exhaustion forecast attached. Warn-level
            # pressure is an attention state, not a failure; CRITICAL
            # pressure (past resource_pressure_crit) degrades health like
            # a mesh/pipeline fault would
            doc["resources"] = {
                "detail": C.RESOURCE_PRESSURE,
                "pressured": rs["pressured"],
                "max_pressure": rs["max_pressure"],
                "min_eta": rs["min_eta"],
                "critical": rs["critical"],
            }
            if rs["critical"] and doc["state"] == C.HEALTH_OK:
                doc["state"] = C.HEALTH_DEGRADED
        if pl is not None:
            # outside the engine lock: pipeline stats take the pipeline
            # lock and must stay a leaf in the lock order; one snapshot
            # carries state, restarts and breaker together
            ps = pl.stats()
            pstate = ps["state"]
            doc["pipeline"] = {
                "state": pstate,
                "restarts": ps["restarts"],
                "breaker": ps["breaker"],
                # per-mesh guard surface: a non-ok state fences this many
                # chips at once (no half-mesh verdicts) — the MESH size,
                # which device-RSS pipelines keep even though their
                # staging ring is unsharded
                "shards": ps.get("mesh_shards") or ps.get("n_shards", 1),
                "rss_mode": ps.get("rss_mode", "host"),
            }
            from cilium_tpu.pipeline.guard import PIPELINE_STATES
            self.metrics.set_gauge("pipeline_state",
                                   PIPELINE_STATES.get(pstate, -1))
            if pstate in ("breaker-open", "restarting", "failed",
                          "device-lost") \
                    and doc["state"] == C.HEALTH_OK:
                doc["state"] = C.HEALTH_DEGRADED
        return doc

    def health_probe(self, now: Optional[int] = None) -> Dict:
        """Datapath health check (cilium-health analog): classify one ICMP
        echo probe from the reserved health identity to every endpoint with
        an IP, through the real device path. Returns
        {ep_id: {reachable, reason, ct_state}, "engine": health()}; a
        probe's verdict follows
        policy exactly like real traffic (an endpoint whose ingress denies
        the health identity reports unreachable — same as upstream when
        health checks are not whitelisted)."""
        from oracle import PacketRecord
        from cilium_tpu.kernels.records import batch_from_records
        from cilium_tpu.utils.ip import parse_addr

        if now is None:
            now = int(time.time())
        src16, _ = parse_addr(C.HEALTH_PROBE_IP)
        eps = [ep for ep in sorted(self.endpoints.values(),
                                   key=lambda e: e.ep_id) if ep.ips]
        if not eps:
            return {"engine": self.health()}
        recs = []
        for ep in eps:
            dst16, v6 = parse_addr(ep.ips[0])
            recs.append(PacketRecord(
                src16, dst16, 0, C.ICMP_ECHO_REQUEST,
                C.PROTO_ICMP6 if v6 else C.PROTO_ICMP, 0, v6,
                ep.ep_id, C.DIR_INGRESS))
        out = self.classify(
            batch_from_records(recs, self.active.snapshot.ep_slot_of),
            now=now)
        report = {}
        for i, ep in enumerate(eps):
            report[ep.ep_id] = {
                "reachable": bool(out["allow"][i]),
                "reason": C.DropReason(int(out["reason"][i])).name,
                "ct_state": C.CTStatus(int(out["status"][i])).name,
            }
        self.metrics.set_gauge(
            "health_reachable_endpoints",
            sum(1 for r in report.values() if r["reachable"]))
        report["engine"] = self.health()
        return report

    def profile_classify(self, batch: Dict[str, np.ndarray], trace_dir: str,
                         now: Optional[int] = None,
                         repeats: int = 3) -> Dict[str, np.ndarray]:
        """Run ``repeats`` classify steps under ``jax.profiler.trace`` →
        an XProf/TensorBoard trace in ``trace_dir`` (SURVEY.md §5
        tracing/profiling: the device half; host stage timers live in
        metrics.span). Requires the JIT backend — with a fake datapath
        there is no device program to profile."""
        import jax
        with jax.profiler.trace(trace_dir):
            for i in range(repeats):
                out = self.classify(dict(batch),
                                    now=None if now is None else now + i)
        return out

    def render_metrics(self) -> str:
        """The full Prometheus exposition: device/host metrics plus the
        flow-metrics totals (one text body for /v1/metrics and the
        textfile exporter)."""
        # zero-copy ingestion attribution: fold the datapath's monotone
        # pack/upload ints in as real counters (delta since last scrape —
        # a *_total gauge would trip PromQL counter semantics). The
        # fallback split exports as ONE labeled counter family so residual
        # allocating packs are attributable: disabled (zero-copy off),
        # steered (sharded batch arrived un-steered), shape (unpoolable
        # row count).
        pack = getattr(self.datapath, "pack_stats", None)
        if pack:
            with self._pack_fold_lock:   # API scrape vs textfile flush
                for k, v in pack.items():
                    d = v - self._pack_stats_seen.get(k, 0)
                    if d:
                        if k.startswith("pack_fallback_"):
                            reason = k[len("pack_fallback_"):]
                            name = ("datapath_pack_fallback_total"
                                    f'{{reason="{reason}"}}')
                        else:
                            name = f"datapath_{k}_total"
                        self.metrics.inc_counter(name, d)
                        self._pack_stats_seen[k] = v
        # live-patch attribution (delta scatter-applies vs full re-places,
        # rows moved, stale-placement fence trips) — same delta-fold
        patch = getattr(self.datapath, "patch_stats", None)
        if patch:
            with self._pack_fold_lock:
                for k, v in patch.items():
                    d = v - self._pack_stats_seen.get(f"patch:{k}", 0)
                    if d:
                        self.metrics.inc_counter(f"datapath_{k}_total", d)
                        self._pack_stats_seen[f"patch:{k}"] = v
        # make_classify_fn memo cache (kernels/classify): size gauge +
        # eviction counter, folded only when the jax-backed module is
        # actually loaded — a fake-datapath engine must stay jax-free
        import sys as _sys
        cls_mod = _sys.modules.get("cilium_tpu.kernels.classify")
        if cls_mod is not None:
            cs = cls_mod.fn_cache_stats()
            self.metrics.set_gauge("classify_fn_cache_size", cs["size"])
            with self._pack_fold_lock:
                d = cs["evictions"] - self._pack_stats_seen.get(
                    "fn_cache:evictions", 0)
                if d:
                    self.metrics.inc_counter(
                        "classify_fn_cache_evictions_total", d)
                    self._pack_stats_seen["fn_cache:evictions"] = \
                        cs["evictions"]
        # trace-ring drop accounting (ISSUE 13): the tracer's drop-oldest
        # overwrites + full wraps as real counters (same delta-fold as the
        # pack stats — the tracer's own totals are process-lifetime ints)
        ts = self.tracer.stats()
        with self._pack_fold_lock:
            for key, name in (("spans_dropped_total",
                               "trace_spans_dropped_total"),
                              ("ring_wraps", "trace_ring_wraps_total")):
                d = ts[key] - self._pack_stats_seen.get(f"trace:{key}", 0)
                if d > 0:
                    self.metrics.inc_counter(name, d)
                    self._pack_stats_seen[f"trace:{key}"] = ts[key]
                elif d < 0:
                    # the process-wide tracer was reset (tests/operator
                    # re-arm): re-baseline so future losses keep counting
                    # instead of waiting out the old watermark
                    self._pack_stats_seen[f"trace:{key}"] = ts[key]
        # in-band DNS plane: the repository's process-lifetime ints
        # (coalesced refreshes, toFQDNs identities materialized) folded as
        # real counters — same delta-fold discipline as the pack stats.
        # fqdn_observed_total / fqdn_parse_errors_total are incremented
        # live by the proxy; fqdn_identities_retired_total by the regen
        # path — only the repo's counters need folding here.
        with self._pack_fold_lock:
            for val, name in (
                    (self.repo.fqdn_refresh_coalesced,
                     "fqdn_refresh_coalesced_total"),
                    (self.repo.fqdn_identities_created,
                     "fqdn_identities_created_total")):
                d = val - self._pack_stats_seen.get(f"fqdn:{name}", 0)
                if d > 0:
                    self.metrics.inc_counter(name, d)
                    self._pack_stats_seen[f"fqdn:{name}"] = val
        # feeder liveness/occupancy as first-class gauge families (the
        # monotone feeder_*_total counters are already incremented live by
        # the feeder itself; these are the fields that existed only in
        # feeder_stats() — a scrape must see them without the status API)
        fd = self.feeder_stats()
        if fd is not None:
            self.metrics.set_gauge("feeder_alive", 1 if fd["alive"] else 0)
            self.metrics.set_gauge("feeder_pool_free", fd["pool_free"])
            self.metrics.set_gauge("feeder_pending", fd["pending"])
        return (self.metrics.render_prometheus()
                + self.flowmetrics.render_prometheus())

    def flush_observability(self) -> None:
        """Flush the flow-log sink and write the Prometheus text file (the
        hubble-export + node-exporter-textfile analog). Also callable
        directly for synchronous export. Each flush also notes a stats
        snapshot into the flight recorder, so a later frozen bundle
        carries the state trajectory leading up to its anomaly."""
        self.blackbox.note_stats({
            "pipeline": self.pipeline_stats(),
            "feeder": self.feeder_stats(),
            "audit": self.auditor.stats(),
        })
        if self.config.flowlog_path:
            self.flowlog.flush_sink()
        if self.config.metrics_path:
            import os
            import tempfile
            d = os.path.dirname(self.config.metrics_path) or "."
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=d, prefix=".metrics-")
            with os.fdopen(fd, "w") as f:
                f.write(self.render_metrics())
            os.replace(tmp, self.config.metrics_path)

    def stop(self) -> None:
        with self._lock:
            fd, self._feeder = self._feeder, None
        if fd is not None:
            # feeder first: it drains the shim and applies remaining
            # verdicts THROUGH the still-open pipeline
            fd.stop()
        with self._lock:
            pl, self._pipeline = self._pipeline, None
            self._pipeline_stopped = True    # submit() must not resurrect it
        if pl is not None:
            # clean shutdown: queued submissions are classified, not dropped
            pl.close(timeout=30.0)
        self.controllers.stop_all()
        # deregister every ledger resource (drops the whole exported
        # resource_* label family per resource): a stopped engine must not
        # leave frozen pressure series behind for the next engine sharing
        # this process's textfile/scrape surface
        self.ledger.deregister_all()
        self._regen_trigger.cancel()
        if self._api is not None:
            self._api.stop()
            self._api = None
        if self._mesh is not None:
            self._mesh.withdraw()
            self._mesh = None

    # -- introspection ----------------------------------------------------------
    def ct_stats(self, now: Optional[int] = None) -> Dict[str, int]:
        if now is None:
            now = int(time.time())
        return self.datapath.ct_stats(now)

    def ct_arrays(self) -> Dict[str, np.ndarray]:
        """Host copy of the CT table (checkpoint/inspection)."""
        return self.datapath.ct_arrays()

    def load_ct_arrays(self, arrays: Dict[str, np.ndarray]) -> None:
        self.datapath.load_ct_arrays(arrays)
