"""Daemon configuration (analog of upstream ``pkg/option.DaemonConfig`` +
``pkg/defaults`` — one frozen dataclass, sourced file < env < CLI flags,
with the runtime-mutable subset limited to what upstream allows at runtime
(policy enforcement mode)."""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Dict, Optional

from cilium_tpu.utils import constants as C

ENV_PREFIX = "CILIUM_TPU_"


@dataclass
class DaemonConfig:
    # --- policy semantics (part of the parity contract) ---
    enforcement_mode: str = C.ENFORCEMENT_DEFAULT
    allow_localhost: bool = True
    # --- datapath geometry ---
    ct_capacity: int = 1 << 20
    probe_depth: int = 8
    batch_size: int = 8192
    v4_only: bool = False
    maglev_m: int = 251            # Maglev table size (prime; prod: 16381)
    # --- device/runtime ---
    device: str = "auto"           # auto | cpu | tpu
    n_shards: int = 1              # data-parallel flow shards (mesh size)
    rule_shards: int = 1           # rule-space (verdict-row) shards
    # flow-to-shard resolution for the sharded mesh (n_shards > 1):
    # "host" = the classic steered path (feeder pre-binning + staging-ring
    # scatter; a flow MUST land on its CT shard before dispatch);
    # "device" = device-side RSS — each chip classifies whatever rows
    # arrive on it and cross-shard CT lookups/inserts resolve with a ring
    # ppermute exchange inside the shard_map body (parallel/exchange.py).
    # Device mode deletes the host steer/scatter from the hot path, the
    # steer_overflow shed class, and the skewed-flood imbalance failure
    # mode; verdicts stay bit-identical to the steered path.
    rss_mode: str = "host"         # host | device
    donate_ct: bool = True
    # Pallas megakernel selector for the classify interior (kernels/fused.py):
    # "auto" compiles the fused path on TPU and keeps the jnp reference
    # elsewhere; "on" forces it everywhere (Pallas interpret mode off-TPU —
    # the CPU-CI bit-identity configuration); "off" pins the jnp reference.
    fused_kernels: str = "auto"    # auto | on | off
    # --- lifecycle ---
    state_dir: str = "/var/run/cilium-tpu"
    sweep_interval_s: float = 30.0
    regen_debounce_s: float = 0.1
    auto_regen: bool = True
    # incremental regeneration: patch the compiled snapshot through the
    # repository changelog instead of full recompiles (geometry changes
    # still fall back to a full build — compile/incremental.py gates)
    incremental: bool = True
    # --- live policy patching (the sub-ms device-resident fast path) ---
    # delta_patch: scatter-apply sparse (rows, values) verdict deltas onto
    # the device-resident image with donated buffers (JITDatapath) instead
    # of round-tripping whole planes through device_put; False restores the
    # whole-tensor re-place. patch_delta_rows gates a single patch (more
    # touched rows → full verdict upload); patch_rebase_rows bounds the
    # host-side row overlay before the incremental compiler folds it into
    # a fresh dense base (one amortized O(image) copy).
    delta_patch: bool = True
    patch_delta_rows: int = 1024
    patch_rebase_rows: int = 4096
    # --- overlapped device-side CT GC (kernels/conntrack.ct_sweep_chunk) ---
    # ct_gc_overlap: the ct-gc controller drives a double-buffered chunked
    # epoch sweep that interleaves with classify steps (enqueue under the
    # classify lock, reclaim counts harvested a tick later) instead of the
    # host-blocking whole-table sweep; chunk_rows per tick (pow2), at
    # ct_gc_interval_s cadence. Backends without device sweeps (the fake)
    # keep the host sweep at sweep_interval_s.
    ct_gc_overlap: bool = True
    ct_gc_chunk_rows: int = 1 << 16
    ct_gc_interval_s: float = 2.0
    # --- CT pressure / emergency GC (adversarial-load survival) ---
    # occupancy (live/capacity) above ct_pressure_high arms EMERGENCY GC:
    # each ct-gc tick runs ct_gc_emergency_chunks chunk sweeps with the
    # effective TTL shortened by ct_gc_emergency_ttl_slash_s (entries
    # within that many seconds of expiry are reclaimed early — a SYN
    # flood's 60s entries die fast, established 21600s flows are
    # untouched); hysteresis exits below ct_pressure_low. The same
    # thresholds feed the overload ladder's CT signal.
    ct_pressure_high: float = 0.85
    ct_pressure_low: float = 0.6
    ct_gc_emergency_chunks: int = 8
    ct_gc_emergency_ttl_slash_s: int = 45
    # --- zero-copy ingestion (kernels/records.py out= + shim/feeder.py) ---
    # in-place pack into preallocated wire rings + L7 path-dict upload
    # cache (JITDatapath); False restores per-batch allocation
    zero_copy_ingest: bool = True
    ingest_pool_batches: int = 4        # feeder harvest buffers in flight
    ingest_poll_budget: int = 256       # rx descriptors per afxdp_poll
    ingest_idle_sleep_s: float = 0.0005  # feeder park when rings are empty
    # --- ingestion pipeline (pipeline/scheduler.py) ---
    pipeline_queue_batches: int = 64    # bounded submission queue (batches)
    pipeline_admission: str = "block"   # block (up to timeout) | drop
    pipeline_block_timeout_s: float = 1.0
    pipeline_flush_ms: float = 2.0      # microbatch coalesce deadline
    pipeline_min_bucket: int = 256      # smallest dispatch shape (pow2)
    pipeline_inflight: int = 2          # overlapped batches in flight
    # sharded staging (n_shards > 1): per-shard segment capacity =
    # pow2(batch_size / n_shards) * headroom — slack for flow-hash skew
    # before a submission sheds with reason="steer_overflow" (pow2)
    pipeline_shard_headroom: int = 4
    # --- pipeline guard (pipeline/guard.py): overload + self-healing ---
    pipeline_deadline_ms: float = 0.0   # per-submission deadline (0 = none)
    pipeline_request_timeout_s: float = 10.0  # REST/CLI Ticket.result bound
    pipeline_breaker_threshold: int = 20  # consecutive failures → open
    pipeline_breaker_cooldown_s: float = 5.0  # open → half-open probe delay
    # heartbeat age → watchdog restart; a generation's FIRST dispatch gets
    # 4x this budget (COLD_DISPATCH_GRACE) so a cold-shape XLA compile can
    # never look like a device stall and restart-loop a healthy daemon
    pipeline_stall_timeout_s: float = 30.0
    pipeline_max_restarts: int = 3      # restart budget, then hard-failed
    pipeline_restart_backoff_s: float = 0.2  # base (capped exponential)
    # --- overload ladder (pipeline/guard.OverloadLadder; the supervised
    # degradation state machine OK → PRESSURE → OVERLOAD → SHED-NEW) ---
    # the `overload` controller folds queue occupancy, shed rate and CT
    # occupancy into the ladder each interval and propagates the state to
    # the admission queue (priority shedding) and the shim feeder
    # (harvest-time SHED-NEW). Signals latch with per-signal hysteresis
    # (high to light, low to clear); the ladder climbs one rung per
    # up_ticks pressured intervals and descends one per down_ticks calm.
    overload_enabled: bool = True
    overload_interval_s: float = 0.5
    overload_queue_high: float = 0.75   # queue_depth/queue_batches to light
    overload_queue_low: float = 0.25
    overload_shed_rate_high: float = 50.0   # sheds+admission drops per sec
    overload_shed_rate_low: float = 5.0
    overload_up_ticks: int = 2
    overload_down_ticks: int = 6
    # --- api ---
    api_socket: str = ""           # unix-socket REST path ("" = disabled)
    # --- multi-host sync (clustermesh analog; runtime/clustermesh.py) ---
    cluster_store: str = ""        # shared store dir ("" = single-host)
    node_name: str = ""            # this node's name in the store
    cluster_sync_interval_s: float = 5.0
    # peer lease: a peer whose generation stops progressing for this long
    # (judged on OUR clock — skew-immune) is withdrawn (etcd lease analog)
    cluster_stale_after_s: float = 60.0
    # store-partition budget: no successful store pass for this long →
    # health() degrades with the MESH_STALE detail (last-good remote state
    # keeps serving throughout — partition never fails closed)
    cluster_staleness_budget_s: float = 15.0
    # --- observability ---
    flowlog_capacity: int = 16384
    flowlog_mode: str = "drops"    # all | drops | none
    flowlog_path: str = ""         # JSONL sink ("" = in-memory ring only)
    metrics_path: str = ""         # Prometheus text file ("" = disabled)
    obs_flush_interval_s: float = 5.0
    # capped {rule=} label cardinality for policy_rule_{hits,drops}_total:
    # a 50k-rule world must not mint 50k Prometheus series; coordinates
    # past the cap aggregate under rule="other" (0 disables the family)
    rule_metrics_max: int = 128
    # --- observe/: tracing, flow metrics, autotune ---
    trace_sample_rate: float = 0.0   # 0 off; 1/64 samples every 64th event
    trace_capacity: int = 4096       # span ring size
    flowmetrics_window_s: int = 10   # flow-metrics aggregation window
    flowmetrics_windows: int = 60    # retained windows (10min at 10s)
    flowmetrics_top_k: int = 10      # ports/identities reported per window
    autotune_enabled: bool = False   # closed-loop pipeline tuning (opt-in)
    autotune_interval_s: float = 5.0
    autotune_flush_ms_min: float = 0.5
    autotune_flush_ms_max: float = 20.0
    autotune_target_fill: float = 0.7
    autotune_queue_wait_p99_ms: float = 10.0   # p99 queue-wait budget
    autotune_hysteresis: int = 3     # consecutive intervals before a step
    autotune_step_factor: float = 1.5  # capped multiplicative step
    # --- observe/: shadow-oracle parity audit (observe/audit.py) ---
    audit_enabled: bool = False      # background parity-audit controller
    audit_sample_rate: float = 0.015625  # 1/64 of finalized batches captured
    audit_pool_batches: int = 8      # bounded capture pool (overflow=skipped)
    audit_max_rows: int = 512        # rows captured per sampled batch
    audit_interval_s: float = 1.0    # parity-audit controller interval
    # --- observe/: flight recorder (observe/blackbox.py; always on) ---
    blackbox_events: int = 256       # guard/regen/audit event ring
    blackbox_verdicts: int = 64      # last-N per-batch verdict summaries
    blackbox_shed_spike: int = 64    # sheds within the window that freeze
    blackbox_shed_window_s: float = 5.0
    # deliberate-overload sheds (priority eviction, SHED-NEW, stale-at-
    # ingest) fire at storm rate BY DESIGN: they get this relaxed spike
    # threshold so a commanded SHED-NEW storm cannot freeze the recorder
    # every window, while flush/steer_overflow keep the strict one above
    blackbox_shed_spike_relaxed: int = 4096
    # --- resource pressure ledger (observe/pressure.py; ISSUE 13) ---
    # every bounded structure registers (capacity, occupancy, high_water)
    # with the central ledger; the resource-ledger controller polls at
    # resource_interval_s, exporting the labeled resource_* gauge families
    # and a windowed time-to-exhaustion forecast per resource. warn feeds
    # the RESOURCE_PRESSURE health detail (and the forecast gate); crit
    # degrades health(); an ETA under resource_eta_warn_s fires the
    # resource-pressure flight-recorder event (strict freeze only on
    # forecast-then-exhaustion). overload_resource_{high,low} is the
    # ladder's fourth latch signal (max non-CT pressure).
    resource_ledger_enabled: bool = True
    resource_interval_s: float = 2.0
    resource_pressure_warn: float = 0.8
    resource_pressure_crit: float = 0.95
    resource_eta_window: int = 16        # (t, occupancy) samples per ETA fit
    resource_eta_warn_s: float = 120.0   # forecast threshold (seconds)
    overload_resource_high: float = 0.9
    overload_resource_low: float = 0.7
    # device-memory budget for the live HBM ledger's `hbm` resource row
    # (JIT backends only; 0 = report without a budget). The OFFLINE check
    # stays `cilium-tpu verify --max-hbm-bytes` — same machinery, one
    # number (compile/verifier.py budget_doc).
    max_hbm_bytes: int = 0
    # --- end-to-end latency SLO (shim harvest → verdict apply) ---
    # burn threshold for ingest_e2e_slo_burn_total (+{shard=...}); 0 keeps
    # the e2e histograms exporting but disables burn counting
    slo_e2e_ms: float = 0.0
    # --- multi-tenant QoS (cilium_tpu/qos; weighted-fair admission) ---
    # default-off: with qos_enabled=False the admission queue is the
    # plain FIFO deque, byte-identical to the pre-QoS pipeline. Armed,
    # the feeder stamps a per-row tenant id (endpoint → tenant LUT), the
    # admission queue goes per-tenant deficit-round-robin (weights from
    # qos_tenants), and lane-tagged tenants bypass deadline microbatching
    # at the qos_lane_bucket dispatch shape.
    qos_enabled: bool = False
    # tenant spec: "name=weight[:lane][:cap=N],..." — e.g.
    # "gold=4:lane,silver=2,bulk=1:cap=8" (cap in queue batches)
    qos_tenants: str = ""
    # static endpoint→tenant assignment: "ep_id=tenant,..." (dynamic
    # assignment goes through Engine.qos.assign at runtime)
    qos_assign: str = ""
    qos_default_weight: float = 1.0  # weight of the default tenant
    qos_lane_bucket: int = 64        # latency-lane dispatch shape (pow2)
    # per-tenant queue occupancy cap in batches for tenants without an
    # explicit :cap= (0 = uncapped; the global queue bound still applies)
    qos_tenant_cap_batches: int = 0
    # --- in-band DNS plane (cilium_tpu/fqdn; ISSUE 18) ---
    # fqdn_proxy_enabled arms the feeder's verdict-apply DNS tap: rows
    # whose verdict carries the DNS L7 redirect class get their harvested
    # response payloads (_dns_payload/_dns_len poll-buffer columns)
    # parsed and fed to the FQDN cache. Fail-open by construction — a
    # parse failure (or the armed fqdn.parse fault) loses learning, never
    # the reply. Off: the feeder allocates no payload columns and the
    # serving path is byte-identical to pre-ISSUE-18.
    fqdn_proxy_enabled: bool = False
    fqdn_proxy_port: int = 53        # the redirect class's DNS port
    # min-TTL floor (upstream tofqdns-min-ttl): short-TTL records are
    # clamped so churn-happy names don't thrash rule re-materialization
    fqdn_min_ttl: int = 0
    # FQDNCache bounds (upstream tofqdns-endpoint-max-ip-per-hostname
    # class): oldest-expiry eviction past either cap; 0 = unbounded
    fqdn_max_names: int = 4096
    fqdn_max_ips_per_name: int = 64
    # --- mesh self-healing (ISSUE 19; runtime/datapath.remesh + the
    # engine's mesh-heal controller) ---
    # remesh_enabled arms the recovery path on sharded JIT backends: a
    # dead-device dispatch signature (DeviceLost) fences the pipeline
    # generation, shrinks the mesh to the surviving devices, salvages the
    # surviving shards' CT, and resumes degraded — and a healed device
    # (probe canary passing for remesh_heal_hysteresis_s) re-meshes back
    # to full width. Off: device loss stays breaker/restart territory.
    remesh_enabled: bool = True
    remesh_interval_s: float = 0.5      # mesh-heal controller poll
    # bounded established-fingerprint grace window after a LOSS remesh:
    # the lost shard's flows classify NEW on-device (their CT is gone)
    # but recently-applied established verdicts flip back to allow at
    # verdict-apply, counted ct_salvage_grace_hits_total, until the
    # window closes and cold-learned CT has taken over
    remesh_grace_s: float = 30.0
    # a healed device must hold a passing probe canary this long before
    # the reverse remesh (anti-flap hysteresis); each failed probe resets
    remesh_heal_hysteresis_s: float = 10.0
    # --- ct-snapshot controller (bounded-staleness CT archive: the
    # salvage floor when the remesh gather itself fails) ---
    ct_snapshot_dir: str = ""           # archive directory ("" = disabled)
    ct_snapshot_interval_s: float = 30.0
    ct_snapshot_keep: int = 2           # newest archives retained
    # checkpoint freshness budget: newest CT archive older than this →
    # checkpoint_age_seconds gauge + CHECKPOINT_STALE health detail
    # (0 = no freshness contract)
    checkpoint_max_age_s: float = 0.0

    def __post_init__(self):
        if self.enforcement_mode not in C.ENFORCEMENT_MODES:
            raise ValueError(f"bad enforcement mode {self.enforcement_mode!r}")
        if self.ct_capacity & (self.ct_capacity - 1):
            raise ValueError("ct_capacity must be a power of two")
        if self.flowlog_mode not in ("all", "drops", "none"):
            raise ValueError(f"bad flowlog mode {self.flowlog_mode!r}")
        if self.fused_kernels not in ("auto", "on", "off"):
            raise ValueError(
                f"bad fused_kernels mode {self.fused_kernels!r} "
                "(auto | on | off)")
        if self.rss_mode not in ("host", "device"):
            raise ValueError(
                f"bad rss_mode {self.rss_mode!r} (host | device)")
        if self.pipeline_admission not in ("block", "drop"):
            raise ValueError(
                f"bad pipeline admission {self.pipeline_admission!r}")
        if (self.pipeline_min_bucket <= 0
                or self.pipeline_min_bucket & (self.pipeline_min_bucket - 1)):
            raise ValueError("pipeline_min_bucket must be a power of two")
        if self.pipeline_inflight < 1 or self.pipeline_queue_batches < 1:
            raise ValueError(
                "pipeline_inflight and pipeline_queue_batches must be >= 1")
        if (self.pipeline_shard_headroom < 1
                or self.pipeline_shard_headroom
                & (self.pipeline_shard_headroom - 1)):
            raise ValueError(
                "pipeline_shard_headroom must be a power of two >= 1")
        if self.ingest_pool_batches < 1 or self.ingest_poll_budget < 1:
            raise ValueError(
                "ingest_pool_batches and ingest_poll_budget must be >= 1")
        if self.ingest_idle_sleep_s < 0:
            raise ValueError("ingest_idle_sleep_s must be >= 0")
        if self.pipeline_deadline_ms < 0:
            raise ValueError("pipeline_deadline_ms must be >= 0 (0 = none)")
        if self.pipeline_request_timeout_s <= 0:
            raise ValueError("pipeline_request_timeout_s must be > 0")
        if self.pipeline_breaker_threshold < 1:
            raise ValueError("pipeline_breaker_threshold must be >= 1")
        if self.pipeline_breaker_cooldown_s <= 0 \
                or self.pipeline_stall_timeout_s <= 0:
            raise ValueError("pipeline_breaker_cooldown_s and "
                             "pipeline_stall_timeout_s must be > 0")
        if self.pipeline_max_restarts < 0 \
                or self.pipeline_restart_backoff_s <= 0:
            raise ValueError("pipeline_max_restarts must be >= 0 and "
                             "pipeline_restart_backoff_s > 0")
        if self.patch_delta_rows < 1 or self.patch_rebase_rows < 1:
            raise ValueError(
                "patch_delta_rows and patch_rebase_rows must be >= 1")
        if (self.ct_gc_chunk_rows < 1
                or self.ct_gc_chunk_rows & (self.ct_gc_chunk_rows - 1)):
            raise ValueError("ct_gc_chunk_rows must be a power of two")
        if self.ct_gc_interval_s <= 0:
            raise ValueError("ct_gc_interval_s must be > 0")
        if not 0.0 <= self.ct_pressure_low < self.ct_pressure_high <= 1.0:
            raise ValueError(
                "need 0 <= ct_pressure_low < ct_pressure_high <= 1")
        if self.ct_gc_emergency_chunks < 1 \
                or self.ct_gc_emergency_ttl_slash_s < 0:
            raise ValueError("ct_gc_emergency_chunks must be >= 1 and "
                             "ct_gc_emergency_ttl_slash_s >= 0")
        if self.overload_interval_s <= 0:
            raise ValueError("overload_interval_s must be > 0")
        if not 0.0 <= self.overload_queue_low \
                < self.overload_queue_high <= 1.0:
            raise ValueError(
                "need 0 <= overload_queue_low < overload_queue_high <= 1")
        if not 0.0 <= self.overload_shed_rate_low \
                < self.overload_shed_rate_high:
            raise ValueError("need 0 <= overload_shed_rate_low < "
                             "overload_shed_rate_high")
        if self.overload_up_ticks < 1 or self.overload_down_ticks < 1:
            raise ValueError(
                "overload_up_ticks and overload_down_ticks must be >= 1")
        if self.blackbox_shed_spike_relaxed < 1:
            raise ValueError("blackbox_shed_spike_relaxed must be >= 1")
        if not 0.0 <= self.trace_sample_rate <= 1.0:
            raise ValueError("trace_sample_rate must be in [0, 1]")
        if self.trace_capacity < 1:
            raise ValueError("trace_capacity must be >= 1")
        if self.flowmetrics_window_s < 1 or self.flowmetrics_windows < 1:
            raise ValueError(
                "flowmetrics_window_s and flowmetrics_windows must be >= 1")
        if not 0 < self.autotune_flush_ms_min <= self.autotune_flush_ms_max:
            raise ValueError(
                "need 0 < autotune_flush_ms_min <= autotune_flush_ms_max")
        if self.autotune_hysteresis < 1 or self.autotune_step_factor <= 1.0:
            raise ValueError("autotune_hysteresis must be >= 1 and "
                             "autotune_step_factor > 1")
        if not 0.0 < self.autotune_target_fill <= 1.0:
            raise ValueError("autotune_target_fill must be in (0, 1]")
        if self.autotune_queue_wait_p99_ms <= 0:
            raise ValueError("autotune_queue_wait_p99_ms must be > 0")
        if self.autotune_interval_s <= 0:
            raise ValueError("autotune_interval_s must be > 0")
        if not 0.0 <= self.audit_sample_rate <= 1.0:
            raise ValueError("audit_sample_rate must be in [0, 1]")
        if self.audit_pool_batches < 1 or self.audit_max_rows < 1:
            raise ValueError(
                "audit_pool_batches and audit_max_rows must be >= 1")
        if self.audit_interval_s <= 0:
            raise ValueError("audit_interval_s must be > 0")
        if self.blackbox_events < 1 or self.blackbox_verdicts < 1 \
                or self.blackbox_shed_spike < 1:
            raise ValueError("blackbox_events, blackbox_verdicts and "
                             "blackbox_shed_spike must be >= 1")
        if self.blackbox_shed_window_s <= 0:
            raise ValueError("blackbox_shed_window_s must be > 0")
        if self.resource_interval_s <= 0:
            raise ValueError("resource_interval_s must be > 0")
        if not 0.0 < self.resource_pressure_warn \
                < self.resource_pressure_crit <= 1.0:
            raise ValueError("need 0 < resource_pressure_warn < "
                             "resource_pressure_crit <= 1")
        if self.resource_eta_window < 2:
            raise ValueError("resource_eta_window must be >= 2")
        if self.resource_eta_warn_s <= 0:
            raise ValueError("resource_eta_warn_s must be > 0")
        if not 0.0 <= self.overload_resource_low \
                < self.overload_resource_high <= 1.0:
            raise ValueError("need 0 <= overload_resource_low < "
                             "overload_resource_high <= 1")
        if self.max_hbm_bytes < 0:
            raise ValueError("max_hbm_bytes must be >= 0 (0 = no budget)")
        if self.slo_e2e_ms < 0:
            raise ValueError("slo_e2e_ms must be >= 0 (0 = no burn "
                             "counting)")
        if self.cluster_stale_after_s <= 0 \
                or self.cluster_staleness_budget_s <= 0:
            raise ValueError("cluster_stale_after_s and "
                             "cluster_staleness_budget_s must be > 0")
        if self.qos_lane_bucket <= 0 \
                or self.qos_lane_bucket & (self.qos_lane_bucket - 1):
            raise ValueError("qos_lane_bucket must be a power of two")
        if self.qos_default_weight < 0:
            raise ValueError("qos_default_weight must be >= 0")
        if self.qos_tenant_cap_batches < 0:
            raise ValueError("qos_tenant_cap_batches must be >= 0 "
                             "(0 = uncapped)")
        if not 0 < self.fqdn_proxy_port < 65536:
            raise ValueError("fqdn_proxy_port must be in [1, 65535]")
        if self.fqdn_min_ttl < 0:
            raise ValueError("fqdn_min_ttl must be >= 0")
        if self.fqdn_max_names < 0 or self.fqdn_max_ips_per_name < 0:
            raise ValueError("fqdn_max_names and fqdn_max_ips_per_name "
                             "must be >= 0 (0 = unbounded)")
        if self.remesh_interval_s <= 0:
            raise ValueError("remesh_interval_s must be > 0")
        if self.remesh_grace_s < 0:
            raise ValueError("remesh_grace_s must be >= 0 (0 = no grace "
                             "window)")
        if self.remesh_heal_hysteresis_s < 0:
            raise ValueError("remesh_heal_hysteresis_s must be >= 0")
        if self.ct_snapshot_interval_s <= 0:
            raise ValueError("ct_snapshot_interval_s must be > 0")
        if self.ct_snapshot_keep < 1:
            raise ValueError("ct_snapshot_keep must be >= 1")
        if self.checkpoint_max_age_s < 0:
            raise ValueError("checkpoint_max_age_s must be >= 0 "
                             "(0 = no freshness contract)")
        if self.qos_enabled or self.qos_tenants or self.qos_assign:
            # parse eagerly so a malformed spec fails at config load, not
            # mid-flood inside the admission path
            from cilium_tpu.qos.tenancy import (parse_assign_spec,
                                                parse_tenant_spec)
            parse_tenant_spec(self.qos_tenants)
            parse_assign_spec(self.qos_assign)

    # -- sources -------------------------------------------------------------
    @classmethod
    def load(cls, config_file: Optional[str] = None,
             env: Optional[Dict[str, str]] = None,
             argv: Optional[list] = None) -> "DaemonConfig":
        """file < env < flags, like upstream's viper layering."""
        values: Dict = {}
        if config_file:
            with open(config_file) as f:
                values.update(json.load(f))
        env = os.environ if env is None else env
        for f_ in dataclasses.fields(cls):
            key = ENV_PREFIX + f_.name.upper()
            if key in env:
                values[f_.name] = _coerce(f_.type, env[key])
        if argv is not None:
            parser = argparse.ArgumentParser(prog="cilium-tpu-agent")
            for f_ in dataclasses.fields(cls):
                parser.add_argument(f"--{f_.name.replace('_', '-')}",
                                    dest=f_.name, default=None)
            ns = parser.parse_args(argv)
            for f_ in dataclasses.fields(cls):
                v = getattr(ns, f_.name)
                if v is not None:
                    values[f_.name] = _coerce(f_.type, v)
        known = {f_.name for f_ in dataclasses.fields(cls)}
        unknown = set(values) - known
        if unknown:
            raise ValueError(f"unknown config keys: {sorted(unknown)}")
        return cls(**values)


def _coerce(typ, raw):
    if isinstance(raw, str):
        t = str(typ)
        if "bool" in t:
            return raw.lower() in ("1", "true", "yes", "on")
        if "int" in t:
            return int(raw)
        if "float" in t:
            return float(raw)
    return raw
