"""The Datapath plugin boundary (upstream: ``pkg/datapath/types``'s
``Datapath``/``Loader`` interfaces; the fake mirrors ``pkg/datapath/fake``).

SURVEY.md §1 layer 3: "the Datapath/Loader Go interfaces — this is the
plugin boundary the TPU backend targets", and §4: control-plane tests
"replay recorded fixtures into a daemon with fake datapath" — the TPU
backend slots in exactly like that fake. Concretely: the Engine owns the
control plane (rules, identities, ipcache, endpoints) and compiles
``PolicySnapshot``s; everything device- or semantics-executing sits behind
``DatapathBackend``:

- ``JITDatapath`` — the production backend: snapshots placed as jax device
  arrays, batches classified by the fused jit kernel, conntrack as donated
  device buffers.
- ``FakeDatapath`` — jax-free. Records every placed snapshot (what upstream
  tests assert map/table contents against) and classifies via the semantics
  oracle, so control-plane tests exercise the full rules → verdict contract
  with no device, no XLA, no jit cache.

The Engine never imports jax when constructed with a FakeDatapath — that is
the test that the boundary is real.
"""

from __future__ import annotations

import abc
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from cilium_tpu.compile.ct_layout import CTConfig, make_ct_arrays
from cilium_tpu.compile.snapshot import PolicySnapshot
from cilium_tpu.observe.trace import (CT_GC_SPAN, PATCH_APPLY_SPAN,
                                      active as active_trace)
from cilium_tpu.pipeline.guard import DeviceLost
from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.faults import FAULTS
from cilium_tpu.utils import constants as C

OutArrays = Dict[str, np.ndarray]

#: clean (non-v6, compact-slot) batches required before place() may narrow
#: a widened wire format: narrowing under sustained wide traffic would
#: retrace every regen (the shape-flapping the sticky flags exist to stop)
WIRE_RESET_CLEAN_BATCHES = 64

CT_SCHEMA_KEYS = frozenset(
    ("keys", "expiry", "created", "flags", "pkts_fwd", "pkts_rev", "rev_nat"))

#: ct.npz schema version written by checkpoints; normalize_ct_arrays
#: upgrades anything older it still understands (v1 lacked rev_nat)
CT_FORMAT_VERSION = 2


class StalePlacement(RuntimeError):
    """The placed handle's device buffers were donated away by a later
    ``place_patch`` (the sub-ms delta path updates the device-resident
    policy image in place). Raised from the classify enqueue path when a
    caller captured the old handle before the patch landed but enqueued
    after — the revision fence that guarantees no batch ever classifies
    against a torn or deleted image. Callers retry with the engine's
    current active snapshot; semantically identical to having dispatched a
    moment later."""


#: substrings that mark a dispatch exception as a DEAD-ACCELERATOR failure
#: rather than the transient dispatch errors the breaker/backoff machinery
#: owns. The first entry is the chaos drill (runtime/faults.py
#: ``device.fail``); the rest are the signatures real runtimes emit when a
#: chip drops off the bus (PJRT/XLA status strings, the ICI-link variants a
#: pod slice reports when a neighbor dies). Matching is case-sensitive on
#: purpose: these are literal runtime status tokens, and loosening the
#: match risks classifying a user exception that merely *mentions* devices.
_DEAD_DEVICE_MARKERS = (
    "device.fail",
    "DEVICE_UNAVAILABLE",
    "device unavailable",
    "Device or resource busy",
    "hardware failure",
    "data transfer failed between devices",
    "chip has been disabled",
)

#: ``dev=K`` riding in the exception text attributes the loss to a flow-
#: shard ordinal (the fault drill arms it via message=dev=K; a real
#: runtime's status may or may not name the chip)
_DEAD_DEVICE_ORDINAL = re.compile(r"\bdev=(\d+)\b")


def dead_device_of(exc: BaseException) -> Optional[int]:
    """Classify a dispatch exception: ``None`` means transient (breaker /
    retry territory — NOT a device loss), an int means a dead-accelerator
    signature. The int is the flow-shard ordinal the failure names, or -1
    when the signature carries no attribution (the caller then treats the
    whole mesh generation as suspect and probes)."""
    text = f"{type(exc).__name__}: {exc}"
    if not any(m in text for m in _DEAD_DEVICE_MARKERS):
        return None
    m = _DEAD_DEVICE_ORDINAL.search(text)
    return int(m.group(1)) if m else -1


class PlacedTensors(dict):
    """A placed snapshot handle: the device-tensor dict plus the donation
    fence. ``dead`` flips (under the datapath's classify lock) the moment a
    delta patch donates this handle's verdict buffer into its successor —
    enqueueing against a dead handle raises :class:`StalePlacement` instead
    of reading a deleted buffer."""
    __slots__ = ("dead",)

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.dead = False


def resolve_fused(config: DaemonConfig) -> Tuple[bool, bool]:
    """``DaemonConfig.fused_kernels`` → (fused, interpret) for the Pallas
    classify-interior kernels (kernels/fused.py). ``auto`` compiles the
    fused path only on TPU (the jnp reference is the right executor for
    CPU/interpret anyway); ``on`` forces the fused path everywhere, in
    Pallas interpret mode off-TPU — the configuration CPU CI uses to pin
    the fused kernels bit-identical to the reference and the oracle."""
    mode = config.fused_kernels
    if mode == "off":
        return False, False
    import jax
    on_tpu = jax.default_backend() == "tpu"
    if mode == "on":
        return True, not on_tpu
    return (True, False) if on_tpu else (False, False)   # auto


def normalize_ct_arrays(arrays: Dict[str, np.ndarray]
                        ) -> Dict[str, np.ndarray]:
    """Validate/upgrade a ct_layout checkpoint to the current schema —
    backend-independent (the schema belongs to the checkpoint format, not to
    any one backend). Strips the embedded format-version stamp, backfills
    the rev_nat column for checkpoints written before service rev-NAT
    existed (format 1), and raises on any other mismatch — including a
    format stamp NEWER than this build understands (restoring a
    future-format CT would silently mis-read columns; dropping it loudly
    is the checkpoint path's fail-closed)."""
    if "__ct_format__" in arrays:
        arrays = dict(arrays)
        fmt = int(np.asarray(arrays.pop("__ct_format__")).reshape(-1)[0])
        if fmt > CT_FORMAT_VERSION:
            raise ValueError(
                f"CT checkpoint format {fmt} is newer than this build's "
                f"{CT_FORMAT_VERSION}")
    if "rev_nat" not in arrays and "expiry" in arrays:
        arrays = dict(arrays)
        arrays["rev_nat"] = np.zeros_like(arrays["expiry"])
    if set(arrays.keys()) != CT_SCHEMA_KEYS:
        raise ValueError(f"CT arrays mismatch: {sorted(arrays)} != "
                         f"{sorted(CT_SCHEMA_KEYS)}")
    return arrays


def _records_from_batch(b: Dict[str, np.ndarray], ep_ids) -> list:
    """Batch dict → oracle PacketRecords (inverse of batch_from_records);
    invalid rows become None so callers keep indices aligned. Lives here —
    not in kernels/ — because only the oracle-backed fake needs it and
    kernels/ must stay importable without the oracle package."""
    from oracle import PacketRecord
    n = b["valid"].shape[0]
    out: list = []
    for i in range(n):
        if not b["valid"][i]:
            out.append(None)
            continue
        slot = int(b["ep_slot"][i])
        path = bytes(b["http_path"][i])
        path = path[:path.index(0)] if 0 in path else path
        out.append(PacketRecord(
            b["src"][i].astype(">u4").tobytes(),
            b["dst"][i].astype(">u4").tobytes(),
            int(b["sport"][i]), int(b["dport"][i]), int(b["proto"][i]),
            int(b["tcp_flags"][i]), bool(b["is_v6"][i]),
            ep_ids[slot] if slot < len(ep_ids) else -1,
            int(b["direction"][i]), int(b["http_method"][i]), path))
    return out


class DatapathBackend(abc.ABC):
    """What the Engine needs from a datapath. The backend owns conntrack
    state (the device-side analog of pinned BPF maps): it survives snapshot
    swaps and is exportable/restorable for checkpointing."""

    @abc.abstractmethod
    def place(self, snap: PolicySnapshot) -> Any:
        """Materialize a compiled snapshot for classification; returns an
        opaque placed handle the Engine passes back to classify()."""

    def place_patch(self, placed: Any, snap: PolicySnapshot,
                    patch) -> Any:
        """Materialize an incrementally-updated snapshot given the previous
        placed handle and a compile.incremental.SnapshotPatch. Default: full
        re-place (semantically always correct); the JIT backend overrides
        with device-side index updates."""
        return self.place(snap)

    @abc.abstractmethod
    def classify(self, placed: Any, snap: PolicySnapshot,
                 batch: Dict[str, np.ndarray], now: int
                 ) -> Tuple[OutArrays, OutArrays]:
        """Classify one batch against a placed snapshot. Returns
        (out, counters) as numpy: out has at least allow/reason/status/
        remote_identity; counters has by_reason_dir [C.COUNTER_CELLS]
        (reasons x directions) + insert_fail."""

    @property
    def pipeline_shards(self) -> int:
        """Flow-shard count the ingestion pipeline should steer for: > 1
        means the backend serves a flow-sharded mesh and wants pipeline
        batches delivered pre-steered (rows grouped into equal per-shard
        segments along dim 0). 1 = no steering (the default; FakeDatapath
        and single-chip JIT)."""
        return 1

    def classify_async(self, placed: Any, snap: PolicySnapshot,
                       batch: Dict[str, np.ndarray], now: int,
                       pre_steered: bool = False):
        """Enqueue one batch and return a zero-argument *finalize* callable
        that blocks until the verdicts are ready and returns the same
        (out, counters) tuple ``classify`` would.

        The contract the pipeline scheduler builds on: everything ordering-
        sensitive (CT mutation order) happens before this returns, so the
        caller may stage/pack/transfer the NEXT batch while the device is
        still computing this one. The default runs the backend's synchronous
        ``classify`` eagerly (FakeDatapath: a plain queue — no device, no
        overlap to win); the JIT backend overrides it with real async
        dispatch.

        ``pre_steered``: the caller already grouped rows into
        ``pipeline_shards`` equal segments by flow-shard (the sharded
        staging ring); outputs come back in the same steered row geometry —
        the caller owns un-steering. Meaningless (and ignored) on backends
        with ``pipeline_shards == 1``, where row order carries no placement
        semantics."""
        res = self.classify(placed, snap, batch, now)
        return lambda: res

    @abc.abstractmethod
    def sweep(self, now: int) -> int:
        """Conntrack GC; returns reclaimed entry count."""

    @abc.abstractmethod
    def ct_stats(self, now: int) -> Dict[str, int]: ...

    @abc.abstractmethod
    def ct_arrays(self) -> Dict[str, np.ndarray]:
        """Host copy of the CT table in the ct_layout schema."""

    @abc.abstractmethod
    def load_ct_arrays(self, arrays: Dict[str, np.ndarray]) -> None: ...


class JITDatapath(DatapathBackend):
    """Production backend: XLA-compiled fused classify over device arrays.

    With ``DaemonConfig.n_shards``/``rule_shards`` > 1 the backend serves
    through a ('flows','rules') device mesh (SURVEY.md §2 parallelism rows
    1-2): batches are steered on the host by the direction-normalized flow
    hash (the RSS analog, vectorized), the conntrack table lives sharded
    along its slot axis (one independent power-of-two table per flow shard),
    and verdict id-class rows are sharded over the rules axis with one psum
    combining (kernels/policy.py). Checkpoint export/import transparently
    rehashes entries into the active shard layout (parallel/mesh.py
    rehash_ct_arrays), so a single-chip checkpoint restores onto a mesh and
    vice versa."""

    def __init__(self, config: Optional[DaemonConfig] = None):
        self.config = config or DaemonConfig()
        if self.config.device == "cpu":
            import os
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax
        import jax.numpy as jnp
        self._jnp = jnp
        self.n_flow_shards = max(1, self.config.n_shards)
        self.n_rule_shards = max(1, self.config.rule_shards)
        self._sharded = self.n_flow_shards * self.n_rule_shards > 1
        ct_host = make_ct_arrays(CTConfig(self.config.ct_capacity,
                                          self.config.probe_depth))
        # Pallas megakernel selector (kernels/fused.py): trace-time static,
        # so both classify fns below bake the choice into their jit keys
        self._fused, self._fused_interpret = resolve_fused(self.config)
        # device-side RSS (rss_mode="device", parallel/exchange.py): rows
        # arrive on chips in plain FIFO order and cross-shard CT resolves
        # with the in-kernel ring ppermute exchange — no host steering, no
        # pre-binning, no un-steer. Host mode keeps the classic steered
        # path. Only meaningful on a flow-sharded mesh.
        self._rss_device = (self.config.rss_mode == "device"
                            and self.n_flow_shards > 1)
        if self._sharded:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from cilium_tpu.parallel.mesh import (
                make_mesh, make_sharded_classify_fn,
                make_unsteered_classify_fn, shard_ct_arrays)
            if self.n_flow_shards & (self.n_flow_shards - 1):
                raise ValueError("n_shards must be a power of two (each CT "
                                 "shard is a power-of-two hash table)")
            self._mesh = make_mesh(self.n_flow_shards, self.n_rule_shards)
            # mesh self-healing (ISSUE 19): flow shard i serves on row i of
            # this CONFIGURED device grid for the life of the process —
            # remesh() re-derives survivor meshes from it and device-health
            # ordinals index into it. The grid never shrinks; only
            # _live_ordinals (the serving subset) does.
            self._configured_devices = [
                list(row) for row in np.asarray(self._mesh.devices).reshape(
                    self.n_flow_shards, self.n_rule_shards)]
            self._live_ordinals: List[int] = list(range(self.n_flow_shards))
            self._ct_sharding = NamedSharding(self._mesh, P("flows"))
            self._repl_sharding = NamedSharding(self._mesh, P())
            # packed wire rows shard over 'flows': each chip receives only
            # its own segment of the pooled wire buffer
            self._batch_sharding = NamedSharding(self._mesh, P("flows"))
            self._verdict_sharding = NamedSharding(
                self._mesh, P(None, None, "rules", None))
            shard_ct_arrays(ct_host, self.n_flow_shards)
            self._ct = {k: jax.device_put(v, self._ct_sharding)
                        for k, v in ct_host.items()}
            make_fn = (make_unsteered_classify_fn if self._rss_device
                       else make_sharded_classify_fn)
            self._classify = make_fn(
                self._mesh,
                probe_depth=self.config.probe_depth,
                v4_only=self.config.v4_only,
                donate_ct=self.config.donate_ct,
                fused=self._fused,
                fused_interpret=self._fused_interpret)
            # per-survivor-set geometry cache: healing back onto a device
            # set the process already served reuses that set's mesh +
            # jitted classify — re-tracing on every down/up flap would
            # stall serving for seconds each transition
            self._mesh_cache: Dict[Tuple[int, ...], tuple] = {
                tuple(self._live_ordinals): (
                    self._mesh, self._ct_sharding, self._repl_sharding,
                    self._batch_sharding, self._verdict_sharding,
                    self._classify)}
        else:
            from cilium_tpu.kernels.classify import make_classify_fn
            self._ct = {k: jnp.asarray(v) for k, v in ct_host.items()}
            # production single-chip path is transfer-bound: ship batches in
            # the packed wire format (one contiguous buffer, not 12 arrays;
            # round-2 fix that previously only bench.py used)
            self._classify = make_classify_fn(
                probe_depth=self.config.probe_depth,
                v4_only=self.config.v4_only,
                donate_ct=self.config.donate_ct,
                packed=True,
                fused=self._fused,
                fused_interpret=self._fused_interpret)
            self._configured_devices = None
            self._live_ordinals = [0]
            self._mesh_cache = {}
        # the flow-shard width the operator CONFIGURED; n_flow_shards is
        # the width currently SERVING (remesh shrinks/restores it)
        self._configured_flow_shards = self.n_flow_shards
        # per-ordinal health records latched by the dead-device classifier
        # and cleared on heal — the engine folds these into health() and
        # the mesh_width ledger row
        self.device_health: Dict[int, Dict[str, Any]] = {}
        # CT capacity currently on device: remesh clamps it to the largest
        # per-shard power of two the survivor count divides into
        # (parallel/mesh.degraded_ct_capacity) — sweep cursors and restores
        # must use THIS, not config.ct_capacity
        self._ct_capacity = int(self.config.ct_capacity)
        self.remesh_stats: Dict[str, int] = {
            "remesh_total": 0,
            "remesh_ct_salvaged": 0,     # live entries carried across
            "remesh_ct_lost": 0,         # live entries on lost shards
            "remesh_ct_dropped": 0,      # rehash probe-window casualties
            "remesh_gather_failures": 0,  # device.collective / gather died
        }
        # donated CT buffers make concurrent classify a use-after-donate;
        # serialize the device step (host-side controllers may call in)
        self._ct_lock = threading.Lock()
        # wire-format stickiness: each (format, shape) is a separate XLA
        # trace (seconds), so per-batch content must not flap the choice —
        # once L7/v6 traffic is seen the wider format stays, and L7 dict
        # geometry (path words, dict rows) only grows. place() resets the
        # flags when a NEW snapshot provably has no L7/v6 surface, so a
        # transient burst doesn't tax every future batch forever.
        self._wire_l7 = False
        self._wire_wide = False        # v6 or >14-bit ep_slot seen
        self._l7_path_words = 1
        self._l7_dict_rows = 1
        # zero-copy staging: a checkout/return pool of wire buffers the
        # pack kernels fill in place, keyed by (rows, words). A buffer may
        # be aliased by the backend until its batch finalizes
        # (device_put/asarray can be zero-copy or async on some backends),
        # so a buffer returns to the pool only in ITS OWN batch's finalize
        # — never by rotation, which dispatch retries could wrap early. A
        # pool miss allocates (steady state refills the pool; fault storms
        # just shed buffers to the GC); only power-of-two row counts (the
        # serving shapes) are pooled, so arbitrary control-plane batch
        # sizes (health probes, pcap tails) can't grow it unboundedly.
        self._pack_lock = threading.Lock()
        self._wire_pool_cap = max(4, self.config.pipeline_inflight + 2)
        self._wire_pool: Dict[Tuple[int, int], list] = {}
        # poolable buffers currently checked out with in-flight batches
        # (the resource ledger's wire_pool occupancy; a lazily-filling
        # pool makes cap-minus-free overstate wildly at startup)
        self._wire_out = 0
        # batches since the last v6/wide-slot batch: the place() narrowing
        # only fires after a clean run, so steady v6 traffic can never
        # reset-flap the wire shape across regens
        self._batches_since_wide = 0
        # L7 path-dict upload cache: real traffic repeats the same path set
        # batch after batch — an unchanged dict is never re-transferred
        self._path_dict_host: Optional[np.ndarray] = None
        self._path_dict_dev = None
        # attribution counters (Engine renders them as labeled Prometheus
        # counters). The fallback split is the answer to "why did this
        # batch allocate": ``disabled`` = zero_copy_ingest off, ``steered``
        # = a sharded batch arrived un-steered (the sync/control-plane
        # entry, which steers with an allocating regroup), ``shape`` = a
        # non-power-of-two row count the pool refuses to hold. The serving
        # path — pipelined, pre-steered, pow2 buckets — must show only
        # ``pack_inplace``; the sharded soak asserts ``steered`` stays 0.
        self.pack_stats: Dict[str, int] = {
            "pack_inplace": 0,            # packed into a pooled wire buffer
            "pack_fallback_disabled": 0,  # zero-copy ingest turned off
            "pack_fallback_steered": 0,   # un-steered sharded batch
            "pack_fallback_shape": 0,     # unpoolable (non-pow2) row count
            "upload_cache_hits": 0,       # path dict served from device cache
            "upload_cache_misses": 0,
            "wire_flag_resets": 0,        # place() narrowed the wire format
        }
        # live-patch attribution: how each place_patch applied (delta =
        # donated device scatter; full = whole-tensor re-upload) and how
        # often the StalePlacement fence actually fired (a caller captured
        # the pre-patch handle and enqueued post-donation — rare, retried)
        self.patch_stats: Dict[str, int] = {
            "patch_delta": 0,
            "patch_full": 0,
            "patch_rows": 0,              # rows scatter-applied, cumulative
            "patch_stale_fences": 0,
            "patch_scatter_errors": 0,    # failed scatters self-healed by
                                          # a full verdict re-upload
        }
        # device-memory ledger (ROADMAP item 6 groundwork / ISSUE 13): the
        # live-placement half of the HBM truth the offline verifier's
        # memory_analysis() budgets describe. Bytes per placed tensor
        # group, re-accounted on every place/place_patch (reading .nbytes
        # off a dozen device arrays — no transfers), CT at construction/
        # restore, the wire pool on demand. hbm_ledger() is the export.
        self._hbm_lock = threading.Lock()
        self._hbm_groups: Dict[str, int] = {}
        self._hbm_places = 0
        self._hbm_patches = 0
        # device-RSS exchange accounting: the ring ppermute's gathered
        # request/reply buffers are transient per-dispatch device tensors;
        # the ledger's ``exchange`` group carries the PEAK bytes any
        # dispatched bucket materialized (the budget-relevant number),
        # rss_exchange_stats() the last/peak occupancy pair
        self._exchange_last_bytes = 0
        self._exchange_peak_bytes = 0
        self._account_ct_hbm()
        self._scatter_fn = None            # jitted donated row scatter
        # overlapped CT GC (kernels/conntrack.ct_sweep_chunk): cursor into
        # the slot space + the previous tick's un-materialized device
        # scalars (the double buffer — harvested one tick later so the
        # enqueue path never blocks on the device)
        self._gc_fn = None
        self._gc_chunk = 0
        self._gc_cursor = 0
        self._gc_epoch = 0
        self._gc_pending = None            # (reclaimed_dev, live_dev)
        self._gc_reclaimed_total = 0
        self._gc_last_live = -1

    @property
    def pipeline_shards(self) -> int:
        """The ingestion pipeline steers for the flow axis only — rule
        shards replicate the batch, so a rules-only mesh needs no row
        grouping at all. With device-side RSS (rss_mode="device") the
        answer is 1: row order carries NO placement semantics — the
        pipeline stages contiguously, the feeder skips pre-binning, and
        the shard_map body's ppermute exchange owns flow→shard
        resolution."""
        if self._rss_device:
            return 1
        return self.n_flow_shards if self._sharded else 1

    @property
    def rss_state(self) -> Dict[str, Any]:
        """Operator-facing RSS-mode surface: where flow→shard resolution
        runs ("host" steering vs the "device" ppermute exchange), the
        mesh's flow-axis size, and whether device mode is actually active
        (it needs a flow-sharded mesh)."""
        return {
            "mode": "device" if self._rss_device else "host",
            "shards": self.n_flow_shards if self._sharded else 1,
            "active": self._rss_device,
        }

    def rss_exchange_stats(self) -> Optional[Dict[str, int]]:
        """Exchange-buffer occupancy for the resource ledger (device RSS
        only): bytes the last dispatched bucket's gathered request/reply
        buffers materialized across the mesh against the worst case at
        ``batch_size`` — the device-transient twin of the wire pool's
        host-side row."""
        if not self._rss_device:
            return None
        from cilium_tpu.parallel.exchange import exchange_bytes
        cap = exchange_bytes(self.config.batch_size, self.n_flow_shards)
        with self._hbm_lock:
            # capacity tracks the largest bucket actually dispatched when
            # a caller runs bigger-than-batch_size buckets (bench A/B
            # shape-parity runs) — occupancy must never exceed capacity
            return {"capacity": max(cap, self._exchange_peak_bytes),
                    "in_use": self._exchange_last_bytes,
                    "peak": self._exchange_peak_bytes}

    @property
    def fused_state(self) -> Dict[str, Any]:
        """Operator-facing view of the megakernel selector: the configured
        mode, whether the fused path is active, and whether it runs in
        Pallas interpret mode (off-TPU ``fused_kernels=on`` — the CI
        bit-identity configuration, not a serving configuration)."""
        return {
            "mode": self.config.fused_kernels,
            "active": self._fused,
            "interpret": self._fused_interpret,
        }

    def _maybe_reset_wire_flags(self, snap: PolicySnapshot) -> None:
        """Un-stick the widened wire formats when the NEW snapshot provably
        has no surface that needs them: with zero L7 rule sets, http tokens
        cannot affect any verdict (no mapstate cell references an L7 set —
        and widening is policy-gated, so the flag cannot re-stick while the
        surface stays empty), and with no v6 prefixes + all ep slots under
        the compact cap, the 4-word v4 wire is sufficient. The wide reset
        additionally requires WIRE_RESET_CLEAN_BATCHES batches without v6
        traffic, so sustained v6 flows can never reset-flap the shape
        across regens — only a genuinely transient burst un-sticks."""
        from cilium_tpu.kernels.records import PACK4_EP_SLOT_MAX
        # under the pack lock: a concurrent classify_async reads/widens the
        # same flags there — a reset landing between its widen and its
        # format choice would mispack the in-flight batch
        with self._pack_lock:
            reset = False
            if self._wire_l7 and snap.l7.n_sets == 0:
                self._wire_l7 = False
                self._l7_path_words = 1
                self._l7_dict_rows = 1
                self._path_dict_host = None
                self._path_dict_dev = None
                reset = True
            # slots run 0..len-1, so the compact wire fits through
            # len == PACK4_EP_SLOT_MAX + 1 inclusive
            if self._wire_wide \
                    and len(snap.ep_ids) - 1 <= PACK4_EP_SLOT_MAX \
                    and self._batches_since_wide >= WIRE_RESET_CLEAN_BATCHES \
                    and not any(":" in p for p in snap.ipcache):
                self._wire_wide = False
                reset = True
            if reset:
                self.pack_stats["wire_flag_resets"] += 1

    # -- HBM ledger (ISSUE 13: the live half of the verifier's offline
    # memory_analysis() budgets; ROADMAP item 6's hardware-truth landing
    # zone) -------------------------------------------------------------------
    @staticmethod
    def _hbm_group(name: str) -> str:
        """Placed-tensor name → ledger group. The groups mirror how an
        operator reasons about device memory: the verdict image (the thing
        place_patch scatters), the LPM tries (the IPv6-at-scale risk), and
        the remaining policy planes."""
        if name == "verdict":
            return "verdict"
        if name.startswith("lpm"):
            return "tries"
        return "policy"

    def _account_placed(self, placed: Dict, patched: bool) -> None:
        groups = {"verdict": 0, "tries": 0, "policy": 0}
        for k, v in placed.items():
            groups[self._hbm_group(k)] += int(getattr(v, "nbytes", 0))
        with self._hbm_lock:
            self._hbm_groups.update(groups)
            if patched:
                self._hbm_patches += 1
            else:
                self._hbm_places += 1

    def _account_ct_hbm(self) -> None:
        n = sum(int(getattr(v, "nbytes", 0)) for v in self._ct.values())
        with self._hbm_lock:
            self._hbm_groups["ct"] = n

    def hbm_ledger(self) -> Dict[str, Any]:
        """Bytes per placed tensor group, live: verdict image / LPM tries /
        policy planes / CT table (device-resident) plus the pooled wire
        staging buffers (host-pinned; flagged so the two kinds are never
        summed into one misleading number). Re-accounted per
        place/place_patch — this is the number ``--max-hbm-bytes`` budgets
        and the resource ledger's ``hbm`` row cite."""
        with self._pack_lock:
            wire = sum(b.nbytes for pool in self._wire_pool.values()
                       for b in pool)
            wire_keys = len(self._wire_pool)
        with self._hbm_lock:
            groups = dict(self._hbm_groups)
            places, patches = self._hbm_places, self._hbm_patches
        device_total = sum(groups.values())
        groups["wire_pool"] = wire
        return {
            "groups": groups,
            "device_bytes": device_total,
            "host_pool_bytes": wire,
            "wire_pool_keys": wire_keys,
            "places_total": places,
            "patches_total": patches,
        }

    def wire_pool_stats(self) -> Dict[str, int]:
        """Pool occupancy for the resource ledger: buffers currently
        checked out (in flight with a dispatched batch — counted at
        checkout/release, since cap-minus-free overstates on a lazily
        filled pool) against the pool's total slots across active
        (rows, words) keys."""
        with self._pack_lock:
            keys = max(1, len(self._wire_pool))
            free = sum(len(p) for p in self._wire_pool.values())
            out = self._wire_out
        cap = self._wire_pool_cap * keys
        return {"capacity": max(cap, out), "free": free,
                "in_flight": out, "keys": keys}

    def place(self, snap: PolicySnapshot) -> Dict:
        jnp = self._jnp
        self._maybe_reset_wire_flags(snap)
        if not self._sharded:
            placed = PlacedTensors(
                {k: jnp.asarray(v) for k, v in snap.tensors().items()})
            self._account_placed(placed, patched=False)
            return placed
        import jax
        from cilium_tpu.parallel.mesh import pad_snapshot_tensors
        tensors = pad_snapshot_tensors(snap.tensors(), self.n_rule_shards)
        placed = PlacedTensors({k: jax.device_put(
            v, self._verdict_sharding if k == "verdict"
            else self._repl_sharding) for k, v in tensors.items()})
        self._account_placed(placed, patched=False)
        return placed

    def _put_tensor(self, name, v):
        if not self._sharded:
            return self._jnp.asarray(v)
        import jax
        return jax.device_put(
            v, self._verdict_sharding if name == "verdict"
            else self._repl_sharding)

    def _scatter_rows(self, verdict, rows, vals):
        """Donated scatter-apply of a sparse verdict delta: the jit is
        created once; jax's shape cache keys the (Kp, n_cols) buckets.
        Padded rows carry an out-of-range slot index and drop."""
        if self._scatter_fn is None:
            import jax

            def _apply(v, r, x):
                return v.at[r[:, 0], r[:, 1], r[:, 2]].set(x, mode="drop")
            self._scatter_fn = jax.jit(_apply, donate_argnums=(0,))
        return self._scatter_fn(verdict, rows, vals)

    #: delta row-count buckets are padded to powers of two so a policy
    #: storm's varying patch sizes reuse a handful of scatter traces
    #: instead of compiling one program per distinct K
    _PATCH_PAD_SLOT = 1 << 30          # OOB slot index → mode="drop"

    def place_patch(self, placed, snap: PolicySnapshot, patch) -> Dict:
        """Incremental device update (SURVEY.md §7 step 3 / ROADMAP item 3a):
        re-upload only tensors the patch names; when the compiler shipped a
        sparse (rows, values) delta, scatter-apply it onto the
        device-resident verdict image with a DONATED buffer — the policy
        image mutates in place on device, no host round trip of any plane.

        Donation is fenced: under the classify lock the old handle is
        marked dead before its verdict buffer is donated, so a concurrent
        classify that captured the old handle either enqueued before the
        patch (XLA's buffer usage-holds sequence its reads ahead of the
        donated write) or observes ``dead`` and raises
        :class:`StalePlacement` for the caller to retry against the new
        active snapshot — no batch can ever classify against a torn or
        deleted image."""
        jnp = self._jnp
        self._maybe_reset_wire_flags(snap)
        tracer, trace_id = active_trace()

        new_placed = PlacedTensors(placed)
        if patch.full_tensors:
            # selective host materialization: only the named tensors are
            # read (a delta-emitted snapshot's dense image stays lazy)
            tensors = snap.tensors(only=frozenset(patch.full_tensors))
            if self._sharded and "verdict" in tensors:
                from cilium_tpu.parallel.mesh import pad_snapshot_tensors
                tensors = pad_snapshot_tensors(tensors, self.n_rule_shards)
            for name in patch.full_tensors:
                if name in tensors:
                    new_placed[name] = self._put_tensor(name, tensors[name])

        if patch.verdict_rows and "verdict" not in patch.full_tensors:
            use_delta = (self.config.delta_patch
                         and patch.delta_rows is not None)
            if use_delta:
                rows_np, vals_np = patch.delta_rows, patch.delta_vals
                k = rows_np.shape[0]
                # pow2 padding: a storm's varying patch sizes reuse a few
                # scatter traces; padded rows carry an OOB slot and drop
                kp = 1 << (k - 1).bit_length() if k > 1 else 1
                if kp != k:
                    pad_rows = np.zeros((kp - k, 3), dtype=np.int32)
                    pad_rows[:, 0] = self._PATCH_PAD_SLOT
                    rows_np = np.concatenate([rows_np, pad_rows])
                    vals_np = np.concatenate(
                        [vals_np, np.zeros((kp - k,) + vals_np.shape[1:],
                                           dtype=vals_np.dtype)])
                with tracer.span(trace_id, PATCH_APPLY_SPAN, rows=k):
                    if self._sharded:
                        import jax
                        rows_dev = jax.device_put(rows_np,
                                                  self._repl_sharding)
                        vals_dev = jax.device_put(vals_np,
                                                  self._repl_sharding)
                    else:
                        rows_dev = jnp.asarray(rows_np)
                        vals_dev = jnp.asarray(vals_np)
                    scatter_failed = False
                    with self._ct_lock:
                        # the fence: dead flips atomically with the
                        # donation — any enqueue that comes later sees it
                        # and retries against the new active snapshot
                        if isinstance(placed, PlacedTensors):
                            placed.dead = True
                        try:
                            new_placed["verdict"] = self._scatter_rows(
                                placed["verdict"], rows_dev, vals_dev)
                        except Exception:   # noqa: BLE001 — accounted below
                            # the donation may already have consumed the
                            # old buffer AND the handle is marked dead: a
                            # raise here would leave regenerate()'s
                            # serve-last-good degradation pinned on a
                            # handle every classify refuses. Self-heal
                            # with a full verdict upload of the NEW
                            # snapshot (outside the lock) instead.
                            scatter_failed = True
                    if scatter_failed:
                        # attribution: the healed patch COUNTS AS FULL —
                        # patch_delta must only ever mean "the donated
                        # scatter actually ran" (the bench's delta-underuse
                        # gate reads it as exactly that)
                        self.patch_stats["patch_scatter_errors"] += 1
                        self.patch_stats["patch_full"] += 1
                        v = snap.tensors(only=frozenset(("verdict",)))
                        if self._sharded:
                            from cilium_tpu.parallel.mesh import \
                                pad_snapshot_tensors
                            v = pad_snapshot_tensors(v, self.n_rule_shards)
                        new_placed["verdict"] = self._put_tensor(
                            "verdict", v["verdict"])
                    else:
                        self.patch_stats["patch_delta"] += 1
                        self.patch_stats["patch_rows"] += k
            else:
                # legacy path (delta_patch off, or a patch without the
                # payload): functional row update from host-gathered
                # values — no donation, the old handle stays live
                rows = np.asarray(patch.verdict_rows, dtype=np.int32)
                vals = snap.tensors(only=frozenset(("verdict",)))[
                    "verdict"][rows[:, 0], rows[:, 1], rows[:, 2]]
                new_placed["verdict"] = placed["verdict"].at[
                    rows[:, 0], rows[:, 1], rows[:, 2]].set(
                        jnp.asarray(vals))
                self.patch_stats["patch_full"] += 1
        else:
            self.patch_stats["patch_full"] += 1
        self._account_placed(new_placed, patched=True)
        return new_placed

    def classify(self, placed, snap, batch, now):
        return self.classify_async(placed, snap, batch, now)()

    #: the exact key-set the sharded dict dispatch ships — shard_map
    #: in_specs mirror this pytree, so staging-ring extras (``_ep_raw``)
    #: must be filtered out before the call
    _BATCH_KEYS = ("src", "dst", "sport", "dport", "proto", "tcp_flags",
                   "is_v6", "ep_slot", "direction", "http_method",
                   "http_path", "valid")

    def _pack_wire(self, b, snap, pooled: bool, fallback_reason: str):
        """The shared zero-copy pack: widen-then-choose the sticky wire
        format under the pack lock, check out a pooled wire buffer, pack in
        place. Returns (wire, path_dict_or_None, wire_key, wire_buf) —
        wire_key is None when the pack allocated (the buffer then just
        sheds to the GC instead of returning to the pool).

        The lock covers only widen-then-choose + the pool checkout (a
        concurrent place() reset can only land before or after this batch's
        whole format decision, never between); the column writes themselves
        run outside it — they touch only the private wire_buf, and
        serializing them would double pack latency whenever a control-plane
        classify (health probe, CLI) overlaps the pipeline worker. L7
        widening is POLICY-gated: with zero L7 rule sets, tokens cannot
        affect any verdict — shipping them is pure wire waste, and skipping
        them keeps tokenized traffic under an L7-free policy on the compact
        wire permanently (no reset/re-widen retrace flap across regens).

        ``pooled=False`` skips the pool entirely and counts the batch under
        ``pack_fallback_{fallback_reason}`` — for paths whose zero-copy
        chain already broke upstream (the allocating steer of an un-steered
        sharded batch)."""
        from cilium_tpu.kernels.records import (
            PACK4_EP_SLOT_MAX, _path_words_of, pack_batch, pack_batch_l7dict,
            pack_batch_v4, wire_words_for)
        batch_l7 = bool(
            (b["http_method"] != C.HTTP_METHOD_ANY).any()
            or b["http_path"].any())
        batch_wide = bool(
            b["is_v6"].any()
            or int(b["ep_slot"].max(initial=0)) > PACK4_EP_SLOT_MAX)
        path_dict = None
        n_rows = int(b["valid"].shape[0])
        zero_copy = self.config.zero_copy_ingest and pooled
        with self._pack_lock:
            if snap.l7.n_sets > 0:
                self._wire_l7 |= batch_l7
            self._wire_wide |= batch_wide
            self._batches_since_wide = 0 if batch_wide \
                else self._batches_since_wide + 1
            use_l7, use_wide = self._wire_l7, self._wire_wide
            if use_l7:
                self._l7_path_words = max(self._l7_path_words,
                                          _path_words_of(b["http_path"]))
                l7_path_words = self._l7_path_words
                l7_min_rows = self._l7_dict_rows
            words = wire_words_for(use_l7, use_wide)
            wire_buf = self._wire_buf(n_rows, words) if zero_copy else None
            wire_key = (n_rows, words) if wire_buf is not None else None
            if wire_buf is not None:
                self.pack_stats["pack_inplace"] += 1
            elif not self.config.zero_copy_ingest:
                self.pack_stats["pack_fallback_disabled"] += 1
            else:
                self.pack_stats[
                    f"pack_fallback_{fallback_reason}"] += 1
        try:
            if use_l7:
                wire, path_dict = pack_batch_l7dict(
                    b, path_words=l7_path_words, min_rows=l7_min_rows,
                    force_full=use_wide, out=wire_buf)
                with self._pack_lock:       # dict geometry stays grow-only
                    self._l7_dict_rows = max(self._l7_dict_rows,
                                             path_dict.shape[0])
            elif not use_wide:
                wire = pack_batch_v4(b, out=wire_buf)
            else:
                wire = pack_batch(b, l7=False, out=wire_buf)
        except BaseException:
            # the checkout already counted this buffer in flight; a pack
            # that dies here never reaches a finalize to release it
            self._wire_buf_shed(wire_key)
            raise
        return wire, path_dict, wire_key, wire_buf

    @staticmethod
    def _columnar(batch):
        """Already-columnar staged batches (the pipeline's staging ring,
        the shim feeder's harvest buffers) skip the per-batch dict copy;
        only mixed/jax-array pytrees still pay the conversion."""
        if all(type(v) is np.ndarray for v in batch.values()):
            return batch
        return {k: np.asarray(v) for k, v in batch.items()}

    def classify_async(self, placed, snap, batch, now, pre_steered=False):
        """Async dispatch (SURVEY.md §5 / the pipeline's overlap stage):
        host packing + transfer + XLA enqueue happen here, synchronously and
        in CT order; the returned finalize materializes the out pytree to
        numpy, which is where the host actually blocks on the device. Only
        the donated CT buffers need the lock — ``out``/``counters`` are
        fresh (non-donated) device arrays, safe to read after the lock is
        released, and XLA sequences the donated-CT dependency chain across
        in-flight steps by itself."""
        jnp = self._jnp
        if self._sharded:
            if self._rss_device:
                # device-side RSS: no steering anywhere — rows ship in
                # arrival order and the shard_map body's ppermute exchange
                # resolves CT ownership (pre_steered is meaningless)
                return self._classify_async_device(placed, snap, batch, now)
            return self._classify_async_sharded(placed, snap, batch, now,
                                                pre_steered=pre_steered)
        # observe/trace: the pack/transfer/compute split attaches to the
        # caller's current trace context (pipeline worker or
        # Engine.classify), whichever tracer instance set it
        tracer, trace_id = active_trace()
        with tracer.span(trace_id, "datapath.pack"):
            b = self._columnar(batch)
            wire, path_dict, wire_key, wire_buf = self._pack_wire(
                b, snap, pooled=True, fallback_reason="shape")
        try:
            with tracer.span(trace_id, "datapath.transfer",
                             bytes=int(wire.nbytes)):
                # chaos points: a wedged/failed host→device link (hang mode
                # is what the pipeline watchdog drill stalls on), and the CT
                # insert phase of this dispatch (a trip rejects the batch —
                # tickets fail closed, FIFO intact — the ddos-smoke drill)
                FAULTS.fire("datapath.transfer")
                FAULTS.fire("ct.insert")
                if path_dict is not None:
                    dev_batch = (jnp.asarray(wire),
                                 self._upload_path_dict(path_dict))
                else:
                    dev_batch = jnp.asarray(wire)
                with self._ct_lock:
                    self._check_placed(placed)
                    # a PlacedTensors handle is a dict SUBCLASS (not a
                    # registered pytree): hand jit the plain-dict view
                    out, new_ct, counters = self._classify(
                        dict(placed), self._ct, dev_batch, jnp.uint32(now),
                        jnp.int32(snap.world_index))
                    self._ct = new_ct
        except BaseException:
            self._wire_buf_shed(wire_key)    # finalize will never run
            raise

        def finalize():
            # the ``fused`` tag attributes compute time to the executor
            # that produced it (Pallas megakernels vs the jnp reference) —
            # the per-kernel split itself lives in bench.py --kernels,
            # since stages inside one jit are not separately timeable
            try:
                with tracer.span(trace_id, "datapath.compute",
                                 fused=int(self._fused)):
                    out_np = {k: np.asarray(v) for k, v in out.items()}
                    counters_np = {k: np.asarray(v)
                                   for k, v in counters.items()}
            except BaseException:
                # a failed materialization (device error) never releases:
                # shed the checkout count, the buffer goes to the GC
                self._wire_buf_shed(wire_key)
                raise
            if wire_key is not None:
                # the device is provably done with this batch (out_np is
                # materialized): the wire buffer is safe to reuse now —
                # and ONLY now (a dispatch that never finalizes simply
                # sheds its buffer to the GC)
                self._wire_buf_release(wire_key, wire_buf)
            return out_np, counters_np
        return finalize

    def _wire_buf(self, rows: int, words: int) -> Optional[np.ndarray]:
        """Checkout a pooled wire buffer (pack lock held); it returns to
        the pool in its batch's finalize. Pool misses allocate (the pool
        refills in steady state). Non-power-of-two row counts — rare
        control-plane batches (health probes, pcap tails) — return None:
        pooling every distinct size ever seen would grow without bound."""
        if rows & (rows - 1):
            return None
        self._wire_out += 1
        pool = self._wire_pool.get((rows, words))
        if pool:
            return pool.pop()
        return np.empty((rows, words), dtype=np.uint32)

    def _wire_buf_release(self, key: Tuple[int, int],
                          buf: np.ndarray) -> None:
        with self._pack_lock:
            self._wire_out = max(0, self._wire_out - 1)
            pool = self._wire_pool.setdefault(key, [])
            if len(pool) < self._wire_pool_cap:
                pool.append(buf)

    def _wire_buf_shed(self, wire_key) -> None:
        """A dispatch died between checkout and finalize (fault trip,
        transfer failure): the buffer itself sheds to the GC — it may be
        aliased by an aborted transfer, never re-pool it — but the
        in-flight count must come back down or the wire_pool ledger row
        reports phantom occupancy forever."""
        if wire_key is None:
            return
        with self._pack_lock:
            self._wire_out = max(0, self._wire_out - 1)

    def _upload_path_dict(self, path_dict: np.ndarray):
        """Device copy of the L7 path dict, cached by content: serving
        traffic repeats a small stable path set, so in steady state the
        dict (host-compared — same cost as one pack column) is uploaded
        once and every later batch reuses the device array."""
        with self._pack_lock:
            cached_host = self._path_dict_host
            cached_dev = self._path_dict_dev
        # compare + upload OUTSIDE the lock: array_equal is O(dict) and
        # the upload is a host→device transfer — neither may serialize
        # concurrent pack work (concurrent misses just race to fill the
        # cache; last write wins, both uploads are correct)
        if (cached_dev is not None and cached_host is not None
                and cached_host.shape == path_dict.shape
                and np.array_equal(cached_host, path_dict)):
            with self._pack_lock:
                self.pack_stats["upload_cache_hits"] += 1
            return cached_dev
        if self._sharded:
            import jax
            dev = jax.device_put(path_dict, self._repl_sharding)
        else:
            dev = self._jnp.asarray(path_dict)
        with self._pack_lock:
            self.pack_stats["upload_cache_misses"] += 1
            # the dict is a fresh np.unique product (never pool-aliased):
            # safe to retain as the comparison baseline without a copy
            self._path_dict_host = path_dict
            self._path_dict_dev = dev
        return dev

    def _classify_async_sharded(self, placed, snap, batch, now,
                                pre_steered=False):
        """The meshed overlap stage. Pre-steered batches (the pipeline's
        sharded staging ring delivers rows already grouped into equal
        per-shard segments) pack IN PLACE into one pooled wire buffer whose
        segments are exactly the per-chip transfers (P('flows') splits dim
        0 on the segment boundaries) — the per-batch steer→allocate→pack
        chain of the pre-PR-6 path is gone, and finalize returns outputs in
        the steered geometry (the caller un-steers; the pipeline does it
        per-slice while gathering ticket rows, which IS the
        unsteer-on-finalize that keeps FIFO verdicts bit-identical).

        Un-steered batches (the synchronous control-plane entry: health
        probes, CLI classify) steer here with the classic allocating
        regroup — counted ``pack_fallback_steered`` so a residual allocating
        dispatch on the serving path is attributable — and finalize
        un-steers back to the caller's row order. A rules-only mesh
        (n_flow_shards == 1) needs no row grouping at all: every batch
        counts as pre-steered."""
        import jax
        from cilium_tpu.parallel.mesh import steer_batch, unsteer_outputs
        jnp = self._jnp
        tracer, trace_id = active_trace()
        pre = pre_steered or self.n_flow_shards == 1
        scatter = None
        with tracer.span(trace_id, "datapath.pack",
                         shards=self.n_flow_shards):
            b = self._columnar(batch)
            if not pre:
                # steering must hash the post-DNAT tuple (service flows' CT
                # entries live under the translated tuple) — the same
                # translation the shim/feeder runs when it pre-bins
                lb = snap.lb if snap.lb.n_frontends else None
                with tracer.span(trace_id, "datapath.steer"):
                    b, scatter, _per = steer_batch(
                        b, self.n_flow_shards, lb=lb, round_to_pow2=True)
            n_rows = int(b["valid"].shape[0])
            if n_rows % self.n_flow_shards:
                raise ValueError(
                    f"pre-steered batch rows ({n_rows}) must divide into "
                    f"{self.n_flow_shards} flow shards")
            if not self.config.zero_copy_ingest:
                # legacy dict dispatch (12 P('flows') column transfers);
                # shard_map in_specs mirror the exact key-set, so staging
                # extras must not ride along
                with self._pack_lock:
                    self.pack_stats["pack_fallback_disabled"] += 1
                wire = path_dict = None
                wire_key = wire_buf = None
                dict_batch = {k: b[k] for k in self._BATCH_KEYS}
                nbytes = sum(v.nbytes for v in dict_batch.values())
            else:
                dict_batch = None
                # attribution: a pre-steered batch that still allocates can
                # only do so for a pool-unfriendly shape; only the
                # allocating-regroup path above earns the "steered" label
                wire, path_dict, wire_key, wire_buf = self._pack_wire(
                    b, snap, pooled=pre,
                    fallback_reason="shape" if pre else "steered")
                nbytes = int(wire.nbytes)
        try:
            with tracer.span(trace_id, "datapath.transfer", bytes=nbytes,
                             shards=self.n_flow_shards):
                FAULTS.fire("datapath.transfer")
                FAULTS.fire("ct.insert")
                self._fire_device_fault()
                if dict_batch is not None:
                    dev_batch = dict_batch   # the jit shards the columns
                elif path_dict is not None:
                    dev_batch = (jax.device_put(wire, self._batch_sharding),
                                 self._upload_path_dict(path_dict))
                else:
                    dev_batch = jax.device_put(wire, self._batch_sharding)
                with self._ct_lock:
                    self._check_placed(placed)
                    out, new_ct, counters = self._classify(
                        dict(placed), self._ct, dev_batch, jnp.uint32(now),
                        jnp.int32(snap.world_index))
                    self._ct = new_ct
        except BaseException as e:
            self._wire_buf_shed(wire_key)    # finalize will never run
            self._maybe_device_lost(e)       # dead-chip signature? reclassify
            raise

        def finalize():
            try:
                with tracer.span(trace_id, "datapath.compute",
                                 fused=int(self._fused)):
                    out_np = {k: np.asarray(v) for k, v in out.items()}
                    counters_np = {k: np.asarray(v)
                                   for k, v in counters.items()}
            except BaseException as e:
                self._wire_buf_shed(wire_key)  # failed materialization
                self._maybe_device_lost(e)
                raise
            if wire_key is not None:
                self._wire_buf_release(wire_key, wire_buf)
            if scatter is not None:
                out_np = unsteer_outputs(out_np, scatter)
            return out_np, counters_np
        return finalize

    def _classify_async_device(self, placed, snap, batch, now):
        """The device-RSS overlap stage: the batch ships in plain ARRIVAL
        order — packed in place into one pooled wire buffer whose equal
        per-chip slices are the transfers (P('flows') splits dim 0) — and
        flow→shard resolution happens inside the shard_map body via the
        ring ppermute CT exchange (parallel/exchange.py). There is no
        steer span, no scatter, and no un-steer: outputs come back in the
        same FIFO row order they were submitted in.

        The only shape contract is divisibility: each chip takes an equal
        pow2 arrival-order slice, so arbitrary-size control-plane batches
        (health probes, CLI classify) pad to the next pow2 multiple of
        the mesh with invalid rows (mirroring the steered path's
        round_to_pow2 trace discipline) and finalize truncates the
        padding. Pipeline buckets are pow2 >= the mesh by construction
        (the engine clamps min_bucket) and ship unpadded."""
        import jax
        from cilium_tpu.parallel.exchange import exchange_bytes
        jnp = self._jnp
        tracer, trace_id = active_trace()
        n = self.n_flow_shards
        with tracer.span(trace_id, "datapath.pack",
                         shards=n, rss="device"):
            b = self._columnar(batch)
            orig_rows = int(b["valid"].shape[0])
            rows = orig_rows
            if rows % n or rows & (rows - 1):
                from cilium_tpu.kernels.records import empty_batch
                per = 1 << max(0, (-(-rows // n) - 1).bit_length())
                rows = per * n
                pb = empty_batch(rows)
                for k, col in pb.items():
                    col[:orig_rows] = b[k]
                b = pb
            if not self.config.zero_copy_ingest:
                with self._pack_lock:
                    self.pack_stats["pack_fallback_disabled"] += 1
                wire = path_dict = None
                wire_key = wire_buf = None
                dict_batch = {k: b[k] for k in self._BATCH_KEYS}
                nbytes = sum(v.nbytes for v in dict_batch.values())
            else:
                dict_batch = None
                wire, path_dict, wire_key, wire_buf = self._pack_wire(
                    b, snap, pooled=True, fallback_reason="shape")
                nbytes = int(wire.nbytes)
        # exchange-buffer accounting (the HBM ledger's ``exchange`` group):
        # per-mesh bytes the ring materializes for this bucket shape
        ex_bytes = exchange_bytes(rows, n)
        with self._hbm_lock:
            self._exchange_last_bytes = ex_bytes
            if ex_bytes > self._exchange_peak_bytes:
                self._exchange_peak_bytes = ex_bytes
                self._hbm_groups["exchange"] = ex_bytes
        try:
            with tracer.span(trace_id, "datapath.transfer", bytes=nbytes,
                             shards=n):
                FAULTS.fire("datapath.transfer")
                FAULTS.fire("ct.insert")
                self._fire_device_fault()
                if dict_batch is not None:
                    dev_batch = dict_batch   # the jit shards the columns
                elif path_dict is not None:
                    dev_batch = (jax.device_put(wire, self._batch_sharding),
                                 self._upload_path_dict(path_dict))
                else:
                    dev_batch = jax.device_put(wire, self._batch_sharding)
                with self._ct_lock:
                    self._check_placed(placed)
                    out, new_ct, counters = self._classify(
                        dict(placed), self._ct, dev_batch, jnp.uint32(now),
                        jnp.int32(snap.world_index))
                    self._ct = new_ct
        except BaseException as e:
            self._wire_buf_shed(wire_key)    # finalize will never run
            self._maybe_device_lost(e)       # dead-chip signature? reclassify
            raise

        def finalize():
            try:
                with tracer.span(trace_id, "datapath.compute",
                                 fused=int(self._fused)):
                    out_np = {k: np.asarray(v) for k, v in out.items()}
                    counters_np = {k: np.asarray(v)
                                   for k, v in counters.items()}
            except BaseException as e:
                self._wire_buf_shed(wire_key)  # failed materialization
                self._maybe_device_lost(e)
                raise
            if wire_key is not None:
                self._wire_buf_release(wire_key, wire_buf)
            if orig_rows != rows:
                # padded control-plane batch: outputs are already FIFO —
                # dropping the invalid tail is the whole "un-steer"
                out_np = {k: v[:orig_rows] for k, v in out_np.items()}
            return out_np, counters_np
        return finalize

    def _check_placed(self, placed) -> None:
        """Classify-lock-held donation fence: refuse to enqueue against a
        handle whose buffers a delta patch donated away."""
        if isinstance(placed, PlacedTensors) and placed.dead:
            self.patch_stats["patch_stale_fences"] += 1
            raise StalePlacement(
                "placed snapshot was delta-patched in place; re-capture "
                "the active snapshot and retry")

    def sweep(self, now: int) -> int:
        from cilium_tpu.kernels import conntrack as ctk
        with self._ct_lock:
            new_ct, n = ctk.ct_sweep(self._ct, self._jnp.uint32(now))
            self._ct = new_ct
        return int(n)

    def sweep_step(self, now: int, chunk_rows: int,
                   ttl_slash_s: int = 0) -> Dict[str, int]:
        """One tick of the overlapped device-side epoch GC (SURVEY.md §2
        "pipelined device-side epoch sweep"; ROADMAP item 3c).

        Each tick enqueues a donated chunk sweep over
        ``[cursor, cursor + chunk_rows)`` of the slot space — interleaving
        with classify steps under the same lock discipline as the classify
        dispatch itself (the enqueue is microseconds; XLA sequences the
        donated-CT dependency chain) — and *harvests the previous tick's*
        reclaimed/occupancy scalars, which resolved on-device while traffic
        ran. That one-tick-late readback is the double buffer: the host
        never blocks on sweep compute inside the enqueue path, and the
        whole-table stop-the-world sync of the old host-driven
        ``sweep()`` is gone.

        ``ttl_slash_s`` (emergency GC) pushes the SWEEP clock that far
        into the future — entries within that many seconds of expiry are
        reclaimed early — while the occupancy count stays on the real
        clock (a slashed count would read low and flap the pressure
        latch's exit hysteresis).

        Returns {"reclaimed", "live", "cursor", "epoch", "chunk_rows"};
        ``live`` is -1 until the first harvest lands."""
        import functools
        jnp = self._jnp
        if self._gc_fn is None or self._gc_chunk != chunk_rows:
            import jax
            from cilium_tpu.kernels.conntrack import ct_sweep_chunk
            self._gc_chunk = chunk_rows
            self._gc_fn = jax.jit(
                functools.partial(ct_sweep_chunk, chunk_rows=chunk_rows),
                donate_argnums=(0,))
        # harvest the previous tick's scalars (long since resolved)
        if self._gc_pending is not None:
            n_dev, live_dev = self._gc_pending
            self._gc_reclaimed_total += int(n_dev)
            self._gc_last_live = int(live_dev)
            reclaimed = int(n_dev)
            self._gc_pending = None
        else:
            reclaimed = 0
        # the LIVE capacity: a remesh clamps the table to the survivor
        # count's power-of-two geometry, and a cursor wrapping on the
        # configured size would sweep past the end of the shrunken table
        cap = int(self._ct_capacity)
        tracer, trace_id = active_trace()
        with tracer.span(trace_id, CT_GC_SPAN,
                         cursor=self._gc_cursor, chunk=chunk_rows):
            with self._ct_lock:
                new_ct, n_dev, live_dev = self._gc_fn(
                    self._ct, jnp.uint32(now + ttl_slash_s),
                    jnp.uint32(self._gc_cursor),
                    count_now=jnp.uint32(now))
                self._ct = new_ct
        self._gc_pending = (n_dev, live_dev)
        cursor = self._gc_cursor
        self._gc_cursor = (self._gc_cursor + chunk_rows) % cap
        if self._gc_cursor <= cursor:
            self._gc_epoch += 1            # wrapped: one full epoch swept
        return {
            "reclaimed": reclaimed,
            "reclaimed_total": self._gc_reclaimed_total,
            "live": self._gc_last_live,
            "cursor": cursor,
            "epoch": self._gc_epoch,
            "chunk_rows": chunk_rows,
        }

    def ct_stats(self, now: int) -> Dict[str, int]:
        # _ct buffers are donated into classify/sweep: reading outside the
        # lock can observe deleted device arrays mid-swap. Copy inside.
        with self._ct_lock:
            expiry = np.asarray(self._ct["expiry"])
        return {
            "capacity": int(expiry.shape[0]),
            "live": int((expiry > now).sum()),
            "stale": int(((expiry > 0) & (expiry <= now)).sum()),
        }

    def ct_arrays(self) -> Dict[str, np.ndarray]:
        with self._ct_lock:
            return {k: np.asarray(v) for k, v in self._ct.items()}

    def load_ct_arrays(self, arrays: Dict[str, np.ndarray]) -> None:
        import logging
        from cilium_tpu.parallel.mesh import rehash_ct_arrays
        jnp = self._jnp
        arrays = normalize_ct_arrays(arrays)
        # re-place entries for THIS backend's probe geometry: imported tables
        # may come from a different shard count or the dense fake export
        arrays, dropped = rehash_ct_arrays(
            arrays, self.n_flow_shards, self.config.probe_depth,
            capacity=self._ct_capacity)
        if dropped:
            logging.getLogger("cilium_tpu.datapath").warning(
                "load_ct_arrays: %d entries dropped (probe window exhausted "
                "during rehash into %d shard(s))", dropped,
                self.n_flow_shards)
        with self._ct_lock:
            if self._sharded:
                import jax
                self._ct = {k: jax.device_put(v, self._ct_sharding)
                            for k, v in arrays.items()}
            else:
                self._ct = {k: jnp.asarray(v) for k, v in arrays.items()}
        self._account_ct_hbm()

    # -- mesh self-healing (ISSUE 19: device loss → fenced re-mesh onto
    # survivors → CT salvage → hysteretic re-admission) ----------------------
    def _fire_device_fault(self) -> None:
        """The ``device.fail`` drill, fired on every sharded dispatch. A
        trip naming an ordinal that already LEFT the serving mesh is
        swallowed: the dead chip cannot hurt a mesh it is no longer part
        of — that swallow is what lets degraded serving run with the fault
        still armed (disarming the point is the drill's heal signal). Trips
        naming a live ordinal, or naming none, propagate into the dispatch
        failure path where :meth:`_maybe_device_lost` reclassifies them."""
        try:
            FAULTS.fire("device.fail")
        except BaseException as e:
            dev = dead_device_of(e)
            if dev is not None and dev >= 0 \
                    and dev not in self._live_ordinals:
                return
            raise

    def _maybe_device_lost(self, exc: BaseException) -> None:
        """Dispatch-failure triage (the detection half of ISSUE 19):
        transient errors return untouched — the caller's breaker/backoff
        machinery owns those — while a dead-accelerator signature latches
        the per-device health record and re-raises as
        :class:`~cilium_tpu.pipeline.guard.DeviceLost`, the signal the
        pipeline parks its worker on and the engine re-meshes from."""
        if isinstance(exc, DeviceLost):
            raise exc
        dev = dead_device_of(exc)
        if dev is None:
            return
        self.note_device_loss(dev, reason=str(exc))
        raise DeviceLost(
            f"dead-device signature in sharded dispatch: {exc}",
            device=dev) from exc

    def note_device_loss(self, ordinal: int, reason: str = "") -> None:
        """Latch a per-device health record. The FIRST loss's evidence is
        kept (a storm of failures off one dead chip must not churn the
        record the debug bundle will cite); an unattributed loss (-1)
        records nothing — the engine's probe pass owns attribution then."""
        if ordinal < 0 or ordinal >= self._configured_flow_shards:
            return
        rec = self.device_health.get(ordinal)
        if rec is not None and rec.get("state") == "dead":
            return
        self.device_health[ordinal] = {
            "state": "dead", "since": time.time(),
            "reason": str(reason)[:200]}

    def note_device_healed(self, ordinal: int) -> None:
        rec = self.device_health.get(ordinal)
        if rec is not None:
            rec.update(state="live", since=time.time(), reason="")

    def probe_device(self, ordinal: int) -> bool:
        """Heal canary for one CONFIGURED chip: the chaos drill first (an
        armed ``device.fail`` naming this ordinal — or attributing to no
        ordinal at all — means still dead), then a real host→device round
        trip against the chip itself. Never raises: a failed probe IS the
        answer."""
        try:
            FAULTS.fire("device.fail")
        except BaseException as e:   # noqa: BLE001 — trip text is the verdict
            dev = dead_device_of(e)
            if dev is None or dev < 0 or dev == ordinal:
                return False
        if self._configured_devices is None:
            return True
        try:
            import jax
            dev0 = self._configured_devices[ordinal][0]
            np.asarray(jax.device_put(np.ones(8, np.uint8), dev0))
        except Exception:   # noqa: BLE001 — a failed probe IS the answer
            return False
        return True

    def mesh_health(self) -> Dict[str, Any]:
        """Operator-facing mesh-width surface: configured vs currently
        SERVING flow shards, which ordinals serve, and the per-device
        health records — what ``Engine.health()`` and the ``mesh_width``
        resource-ledger row render."""
        dead = sorted(o for o, r in self.device_health.items()
                      if r.get("state") == "dead")
        return {
            "configured": self._configured_flow_shards,
            "live": self.n_flow_shards if self._sharded else 1,
            "live_ordinals": list(self._live_ordinals),
            "dead_ordinals": dead,
            "devices": {int(k): dict(v)
                        for k, v in self.device_health.items()},
        }

    def remesh(self, live_ordinals, fence_handle=None,
               salvage_floor: Optional[Dict[str, np.ndarray]] = None
               ) -> Dict[str, Any]:
        """Shrink (or re-grow) the serving mesh to exactly the given
        CONFIGURED flow-shard ordinals, salvaging the conntrack table
        across the transition. Runs under the classify lock — the caller
        (Engine._remesh_to) has already fenced the pipeline generation, so
        nothing is dispatching concurrently; a racing CONTROL-PLANE
        classify that captured the pre-remesh placed handle hits the
        ``fence_handle.dead`` flip and retries via StalePlacement.

        CT salvage order (each fallback counted in ``remesh_stats``):
        device gather (``device.collective`` is the chaos point) → the
        caller's ``salvage_floor`` archive (the ct-snapshot controller's
        bounded-staleness npz) → a cold table. On a successful gather the
        LOST shards' slots are zeroed first: on real hardware that state
        died with the chip, and the CPU rig must not get a free pass the
        grace window was built to cover.

        The new table's capacity is ``degraded_ct_capacity`` — the largest
        per-shard power of two the survivor count divides into — and
        surviving entries rehash into it (probe-window casualties counted
        ``remesh_ct_dropped``). The caller re-places the active snapshot
        onto the new mesh afterwards."""
        if not self._sharded or self._configured_devices is None:
            raise ValueError("remesh requires a flow-sharded mesh")
        live = sorted({int(o) for o in live_ordinals})
        if not live:
            raise ValueError("remesh needs at least one surviving shard")
        bad = [o for o in live
               if not 0 <= o < self._configured_flow_shards]
        if bad:
            raise ValueError(f"remesh ordinals out of range: {bad}")
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from cilium_tpu.parallel.mesh import (
            degraded_ct_capacity, drop_ct_shard, make_mesh,
            make_sharded_classify_fn, make_unsteered_classify_fn,
            rehash_ct_arrays, shard_ct_arrays)
        n_new = len(live)
        started = time.monotonic()
        with self._ct_lock:
            old_live = list(self._live_ordinals)
            old_n = len(old_live)
            if live == old_live:
                return {"from": old_n, "to": n_new, "noop": True,
                        "live_ordinals": live}
            # 1) salvage: gather the current table to host. The gather is
            # itself a collective over a possibly-degraded mesh — its
            # failure (chaos: device.collective) falls back to the archive
            # floor, then cold.
            source = "device"
            arrays: Optional[Dict[str, np.ndarray]] = None
            try:
                FAULTS.fire("device.collective")
                # np.array (not asarray): the gather view off a jax buffer
                # is read-only, and the lost-shard zeroing below mutates
                arrays = {k: np.array(v) for k, v in self._ct.items()}
            except Exception:   # noqa: BLE001 — counted, archive/cold floor
                self.remesh_stats["remesh_gather_failures"] += 1
            lost = 0
            if arrays is not None:
                for pos, o in enumerate(old_live):
                    if o not in live:
                        lost += drop_ct_shard(arrays, pos, old_n)
            elif salvage_floor is not None:
                source = "archive"
                arrays = {k: np.array(v) for k, v in salvage_floor.items()}
            else:
                source = "cold"
                arrays = make_ct_arrays(CTConfig(self.config.ct_capacity,
                                                 self.config.probe_depth))
            new_cap = degraded_ct_capacity(self.config.ct_capacity, n_new)
            arrays, dropped = rehash_ct_arrays(
                arrays, n_new, self.config.probe_depth, capacity=new_cap)
            salvaged = int((arrays["expiry"] > 0).sum())
            # 2) survivor geometry, cached per exact device set: healing
            # back onto a set that served before reuses its jitted classify
            key = tuple(live)
            cached = self._mesh_cache.get(key)
            if cached is None:
                devices = [d for o in live
                           for d in self._configured_devices[o]]
                mesh = make_mesh(n_new, self.n_rule_shards, devices=devices)
                make_fn = (make_unsteered_classify_fn if self._rss_device
                           else make_sharded_classify_fn)
                cached = (
                    mesh,
                    NamedSharding(mesh, P("flows")),
                    NamedSharding(mesh, P()),
                    NamedSharding(mesh, P("flows")),
                    NamedSharding(mesh, P(None, None, "rules", None)),
                    make_fn(mesh,
                            probe_depth=self.config.probe_depth,
                            v4_only=self.config.v4_only,
                            donate_ct=self.config.donate_ct,
                            fused=self._fused,
                            fused_interpret=self._fused_interpret))
                self._mesh_cache[key] = cached
            (self._mesh, self._ct_sharding, self._repl_sharding,
             self._batch_sharding, self._verdict_sharding,
             self._classify) = cached
            shard_ct_arrays(arrays, n_new)    # divisibility fail-fast
            self._ct = {k: jax.device_put(v, self._ct_sharding)
                        for k, v in arrays.items()}
            self.n_flow_shards = n_new
            self._live_ordinals = live
            self._ct_capacity = new_cap
            # the overlapped GC's jitted chunk sweep and the donated
            # verdict scatter both baked the OLD geometry — drop them and
            # restart the sweep cursor in the new slot space
            self._gc_fn = None
            self._gc_cursor = 0
            self._gc_pending = None
            self._scatter_fn = None
            self.remesh_stats["remesh_total"] += 1
            self.remesh_stats["remesh_ct_salvaged"] += salvaged
            self.remesh_stats["remesh_ct_lost"] += lost
            self.remesh_stats["remesh_ct_dropped"] += dropped
            # fence: flips atomically with the geometry swap, so a control-
            # plane classify holding the old handle can never enqueue
            # against the old mesh's shardings
            if isinstance(fence_handle, PlacedTensors):
                fence_handle.dead = True
        self._account_ct_hbm()
        return {"from": old_n, "to": n_new, "live_ordinals": live,
                "ct_capacity": new_cap, "ct_salvaged": salvaged,
                "ct_lost": lost, "ct_dropped": dropped,
                "salvage_source": source,
                "took_s": round(time.monotonic() - started, 3)}


class FakeDatapath(DatapathBackend):
    """Jax-free backend for control-plane tests (pkg/datapath/fake analog).

    ``place`` records the snapshot + its would-be device images (numpy) in
    ``self.placed`` so tests can assert "map contents" exactly like upstream
    control-plane tests assert policymap/lbmap state. ``classify`` runs the
    semantics oracle over the same snapshot, so verdicts follow the real
    contract — a second, independent implementation behind the same
    boundary. Conntrack is the oracle's exact table; the array view is
    reconstructed on demand in the ct_layout schema."""

    PLACED_KEEP = 64                     # placement history cap (memory bound)

    def __init__(self, config: Optional[DaemonConfig] = None):
        from oracle import ConntrackTable
        self.config = config or DaemonConfig()
        # [(snapshot, tensors_np)], in order; a long-lived engine with
        # auto-regen would otherwise grow this without bound — keep the most
        # recent PLACED_KEEP (tests only assert against recent placements)
        self.placed = []
        self.placed_total = 0            # placements ever (incl. evicted)
        # BOUNDED oracle table (device hash, same probe window) so the
        # fake exhibits the device's exact CT-exhaustion semantics — at the
        # configured capacity a saturating test sees the same tail
        # evictions and CT_FULL denies the jnp kernel computes, slot for
        # slot (single-chip layout; the sharded mesh's per-shard tables
        # hash differently and are out of the fake's scope)
        self._ct_table = ConntrackTable(capacity=self.config.ct_capacity,
                                        probe_depth=self.config.probe_depth)
        self._oracle = None
        self._oracle_snap = None         # snapshot the cached oracle is for
        self.ct_export_truncated = 0     # entries dropped by ct_arrays()
        self._lock = threading.Lock()

    # -- helpers -------------------------------------------------------------
    def _oracle_for(self, snap: PolicySnapshot):
        """Oracle for EXACTLY ``snap`` — cached by snapshot identity, so a
        batch is always evaluated against the snapshot revision the Engine
        captured (revision fencing: a concurrent place() of a newer snapshot
        must not retarget an in-flight batch)."""
        from oracle import Oracle
        if self._oracle is None or self._oracle_snap is not snap:
            # CT persists across snapshot swaps: the table, not the oracle,
            # owns connection state
            oracle = Oracle.for_snapshot(snap, ct=self._ct_table)
            self._oracle, self._oracle_snap = oracle, snap
        return self._oracle

    # -- DatapathBackend -----------------------------------------------------
    def place(self, snap: PolicySnapshot) -> Dict:
        tensors = snap.tensors()         # numpy, no device
        with self._lock:
            self.placed.append((snap, tensors))
            self.placed_total += 1
            if len(self.placed) > self.PLACED_KEEP:
                del self.placed[:-self.PLACED_KEEP]
        return tensors

    def classify(self, placed, snap, batch, now):
        FAULTS.fire("ct.insert")      # same drill point as the JIT path
        with self._lock:
            oracle = self._oracle_for(snap)
            records = _records_from_batch(batch, snap.ep_ids)
            live = [p for p in records if p is not None]
            # counter baselines BEFORE the classify mutates the table
            evicted0 = self._ct_table.evicted
            fail0 = self._ct_table.insert_fail
            verdicts = iter(oracle.classify_batch_snapshot(live, now))
            n = len(records)
            out = {
                "allow": np.zeros(n, bool),
                "reason": np.zeros(n, np.int32),
                "status": np.zeros(n, np.int32),
                "ct_full": np.zeros(n, bool),
                "remote_identity": np.zeros(n, np.int32),
                "redirect": np.zeros(n, bool),
                # provenance columns (invalid rows: -1 like the device's
                # valid mask; ct_state_pre 0 == CTStatus.NEW, matching the
                # kernel's valid-masked est/reply)
                "matched_rule": np.full(n, -1, np.int32),
                "lpm_prefix": np.full(n, -1, np.int32),
                "ct_state_pre": np.zeros(n, np.int32),
                "svc": np.zeros(n, bool),
                "nat_dst": np.zeros((n, 4), np.uint32),
                "nat_dport": np.zeros(n, np.int32),
                "rnat": np.zeros(n, bool),
                "rnat_src": np.zeros((n, 4), np.uint32),
                "rnat_sport": np.zeros(n, np.int32),
            }
            counters = {"by_reason_dir": np.zeros(C.COUNTER_CELLS, np.uint32),
                        "insert_fail": np.uint32(0)}
            for i, p in enumerate(records):
                if p is None:
                    continue
                v = next(verdicts)
                out["allow"][i] = v.allow
                out["reason"][i] = v.drop_reason
                out["status"][i] = v.ct_status
                out["ct_full"][i] = v.ct_full
                out["remote_identity"][i] = v.remote_identity
                out["redirect"][i] = v.redirect
                out["matched_rule"][i] = v.matched_rule
                out["lpm_prefix"][i] = v.lpm_prefix
                out["ct_state_pre"][i] = v.ct_status
                out["svc"][i] = v.svc
                if v.nat_dst:
                    out["nat_dst"][i] = np.frombuffer(v.nat_dst, dtype=">u4")
                out["nat_dport"][i] = v.nat_dport
                out["rnat"][i] = v.rnat
                if v.rnat_src:
                    out["rnat_src"][i] = np.frombuffer(v.rnat_src, dtype=">u4")
                out["rnat_sport"][i] = v.rnat_sport
                counters["by_reason_dir"][int(v.drop_reason) * 2
                                          + p.direction] += 1
            counters["insert_fail"] = np.uint32(
                self._ct_table.insert_fail - fail0)
            counters["ct_evicted"] = np.uint32(
                self._ct_table.evicted - evicted0)
            return out, counters

    def sweep(self, now: int) -> int:
        with self._lock:
            return self._ct_table.sweep(now)

    def ct_stats(self, now: int) -> Dict[str, int]:
        with self._lock:
            live = sum(1 for e in self._ct_table.entries.values()
                       if e.expiry > now)
            return {
                "capacity": self.config.ct_capacity,
                "live": live,
                "stale": len(self._ct_table.entries) - live,
            }

    def ct_arrays(self) -> Dict[str, np.ndarray]:
        """Oracle CT → ct_layout arrays. Bounded tables (the default)
        export each entry at its REAL hash slot — the same placement the
        single-chip device computes; entries without one (legacy restores)
        fall back to the old dense-from-0 layout."""
        import logging
        from cilium_tpu.kernels.records import ct_key_words
        cap = self.config.ct_capacity
        arrays = make_ct_arrays(CTConfig(cap, self.config.probe_depth))
        with self._lock:
            items = list(self._ct_table.entries.items())
            overflow = len(items) - cap
            if overflow > 0:
                # a bounded table can never overflow; an unbounded legacy
                # dict can — never lose flows silently, and when forced
                # to, drop the soonest-to-expire (deterministic)
                self.ct_export_truncated += overflow
                logging.getLogger("cilium_tpu.datapath").warning(
                    "FakeDatapath.ct_arrays: %d CT entries exceed "
                    "ct_capacity=%d; dropping the soonest-expiring from "
                    "the export", overflow, cap)
                items.sort(key=lambda kv: kv[1].expiry, reverse=True)
                items = items[:cap]
        hash_slots = all(0 <= e.slot < cap for _k, e in items)
        for dense, (key, e) in enumerate(items):
            slot = e.slot if hash_slots else dense
            src, dst, sport, dport, proto, d = key
            one = {
                "src": np.frombuffer(src, dtype=">u4").reshape(1, 4),
                "dst": np.frombuffer(dst, dtype=">u4").reshape(1, 4),
                "sport": np.array([sport]), "dport": np.array([dport]),
                "proto": np.array([proto]), "direction": np.array([d]),
            }
            arrays["keys"][slot] = ct_key_words(one)[0]
            arrays["expiry"][slot] = e.expiry
            arrays["created"][slot] = e.created
            arrays["flags"][slot] = e.flags
            arrays["pkts_fwd"][slot] = e.pkts_fwd
            arrays["pkts_rev"][slot] = e.pkts_rev
            arrays["rev_nat"][slot] = e.rev_nat
        return arrays

    def load_ct_arrays(self, arrays: Dict[str, np.ndarray]) -> None:
        """ct_layout arrays → oracle CT entries (inverse of ct_arrays).
        Entries re-place into this table's hash layout (the imported
        arrays may come from a different shard count or a dense legacy
        export); entries whose probe window is already full drop with a
        warning — the same contract as the JIT backend's rehash."""
        import logging
        from oracle import ConntrackTable, CTEntry
        from cilium_tpu.utils.ip import words_to_addr
        arrays = normalize_ct_arrays(arrays)   # validate BEFORE clearing
        cap = self.config.ct_capacity
        pd = self.config.probe_depth
        with self._lock:
            table = ConntrackTable(capacity=cap, probe_depth=pd)
            expiry = arrays["expiry"]
            loaded = []
            for slot in np.nonzero(expiry > 0)[0]:
                w = arrays["keys"][slot]
                key = (words_to_addr(w[0:4]), words_to_addr(w[4:8]),
                       int(w[8]) >> 16, int(w[8]) & 0xFFFF,
                       int(w[9]) >> 8, int(w[9]) & 0xFF)
                loaded.append((key, CTEntry(
                    expiry=int(expiry[slot]),
                    created=int(arrays["created"][slot]),
                    flags=int(arrays["flags"][slot]),
                    pkts_fwd=int(arrays["pkts_fwd"][slot]),
                    pkts_rev=int(arrays["pkts_rev"][slot]),
                    rev_nat=int(arrays["rev_nat"][slot]))))
            dropped = 0
            if loaded:
                bases = table.base_slots([k for k, _e in loaded])
                for (key, entry), base in zip(loaded, bases):
                    placed = False
                    for r in range(pd):
                        s = (int(base) + r) % cap
                        if table._slots[s] is None:     # noqa: SLF001
                            table.install(key, entry, s)
                            placed = True
                            break
                    if not placed:
                        dropped += 1
            self._ct_table = table
            # the cached oracle closed over the old table object
            self._oracle = None
            self._oracle_snap = None
            if dropped:
                logging.getLogger("cilium_tpu.datapath").warning(
                    "FakeDatapath.load_ct_arrays: %d entries dropped "
                    "(probe window exhausted during re-place)", dropped)
