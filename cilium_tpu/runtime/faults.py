"""Fault-injection registry (SURVEY-style chaos harness for the runtime).

A process-wide :data:`FAULTS` singleton owns a set of **named injection
points** wired into the serving/control paths:

======================  =====================================================
point                   fires in
======================  =====================================================
``regen.compile``       ``Engine.regenerate()`` — before snapshot compile
``shim.rx_ring``        ``FlowShim.poll_batch()`` / ``afxdp_poll()``
``clustermesh.peer_read``  ``ClusterMesh._read_peers()`` — per peer file
``checkpoint.write``    ``checkpoint.save()`` — between tmp write and rename
``api.handler``         REST dispatch (every method) in ``api._Handler``
``pipeline.dispatch``   per-microbatch dispatch in the ingestion pipeline
                        worker (``pipeline/scheduler.py``)
``pipeline.finalize``   per-batch finalize in the pipeline worker (trips
                        reject the batch; ``hang`` stalls for the watchdog)
``datapath.transfer``   host→device transfer enqueue inside
                        ``JITDatapath.classify_async``
``audit.corrupt``       shadow-audit capture (``observe/audit.py``): a trip
                        flips the captured allow bits so the parity auditor
                        must detect the divergence
``ct.gc``               one overlapped CT-GC tick (``Engine.sweep_step``) —
                        trips drill the ct-gc controller's supervised backoff
======================  =====================================================

Each point can be **armed** with one spec:

* ``fail`` (``times=N``): raise :class:`FaultInjected` on the first N fires
  (``times=None`` → every fire).
* ``prob`` (``prob=P, seed=S``): raise with probability P from a private
  seeded ``random.Random`` — fully deterministic, no wall clock.
* ``delay`` (``delay_s=T``): inject latency (sleep) instead of failing.
* ``hang`` (``delay_s=T, times=N``): a **cooperative stall** — the firing
  thread blocks inside the point (simulating a wedged device call) until
  the point is disarmed or the cap T (hard-clamped to
  :data:`HANG_HARD_CAP_S`) elapses, then returns normally. This is what
  drives the pipeline watchdog's stall detection without ever being able
  to deadlock a test.

Activation is either programmatic (the :meth:`FaultInjector.inject` context
manager, used by tests) or via the environment::

    CILIUM_TPU_FAULTS="regen.compile=fail:10;clustermesh.peer_read=prob:0.5:seed=7"

Grammar: ``point=mode[:arg][:key=val]...`` entries joined by ``;`` or ``,``.
``fail:N`` sets times, ``prob:P`` sets probability, ``delay:T`` sets seconds.
The agent process parses the variable at import of this module, so a chaos
scenario can target a real daemon with zero code changes.

Everything is thread-safe; ``fire()`` on an un-armed point is a dict lookup
plus a counter bump, cheap enough for control-path call sites (it is NOT in
the per-packet device path).
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

ENV_VAR = "CILIUM_TPU_FAULTS"

# the registry of known points (arm() validates against this so a typo'd
# scenario fails loudly instead of silently injecting nothing)
POINTS: Dict[str, str] = {
    "regen.compile": "snapshot compile inside Engine.regenerate()",
    "shim.rx_ring": "rx-ring poll in shim bindings (poll_batch/afxdp_poll)",
    "clustermesh.peer_read": "per-peer store file read in ClusterMesh",
    "checkpoint.write": "pre-rename window of each atomic checkpoint file "
                        "write (tmp written, rename pending)",
    "api.handler": "REST request dispatch in the unix-socket API server",
    "pipeline.dispatch": "per-microbatch dispatch in the ingestion "
                         "pipeline worker (trips are retried — batches "
                         "delay, never drop — until the circuit breaker "
                         "opens; hang mode stalls for the watchdog)",
    "pipeline.finalize": "per-batch finalize in the ingestion pipeline "
                         "worker (trips reject the batch's tickets and "
                         "feed the circuit breaker; hang mode stalls for "
                         "the watchdog)",
    "datapath.transfer": "host→device transfer enqueue in "
                         "JITDatapath.classify_async (serial classify and "
                         "the pipeline both route through it)",
    "audit.corrupt": "shadow-audit capture in observe/audit.py: an armed "
                     "trip flips the CAPTURED allow bits (the copy, never "
                     "the live verdicts) — simulates a datapath parity bug "
                     "so chaos drills prove the auditor detects, health "
                     "degrades, and a flight-recorder bundle freezes",
    "resource.poll": "one resource-pressure ledger sweep "
                     "(Engine.resource_step): trips exercise the "
                     "resource-ledger controller's supervised backoff — "
                     "serving and the last exported pressure gauges must "
                     "be untouched by a wedged/failing poll",
    "ct.gc": "one tick of the overlapped device-side CT GC "
             "(Engine.sweep_step): trips exercise the ct-gc controller's "
             "supervised backoff — classify traffic and CT correctness "
             "must be untouched by a wedged/failing sweep",
    "ct.insert": "the CT insert phase of one classify dispatch "
                 "(JITDatapath.classify_async / FakeDatapath.classify): a "
                 "trip rejects the batch — tickets fail closed in FIFO "
                 "order, the breaker is fed — drilling verdict-FIFO "
                 "survival while a DDoS flood saturates the table",
    "overload.decide": "one tick of the overload-ladder controller "
                       "(Engine.overload_step): trips drill the supervised "
                       "backoff — the ladder state must HOLD (no flap to "
                       "OK, no spurious escalation) while the decider "
                       "itself is failing",
    "clustermesh.store_list": "the whole-store directory listing in "
                              "ClusterMesh._read_peers (a dead NFS mount — "
                              "the store PARTITION, vs peer_read's "
                              "single-file flake): trips make the mesh "
                              "serve last-good remote state and, past the "
                              "staleness budget, degrade health with the "
                              "MESH_STALE detail — never fail closed on "
                              "established remote flows",
    "fqdn.parse": "the DNS response decode inside the feeder's learning "
                  "tap (fqdn/proxy.observe_batch): a trip loses LEARNING "
                  "for that batch's DNS rows — counted in "
                  "fqdn_parse_errors_total — while the replies keep their "
                  "verdicts bit-identical (the fail-open contract a "
                  "broken parser must honor; chaos phase dns-poison)",
    "device.fail": "a dead accelerator in the flow-shard mesh: fired on "
                   "every sharded classify dispatch AND by "
                   "JITDatapath.probe_device. Arm with message=dev=K to "
                   "name the victim ordinal — the datapath's real-error "
                   "classifier (dead_device_of) recognizes the trip as a "
                   "dead-device signature (NOT breaker/backoff territory), "
                   "latches the per-device health record, and raises "
                   "DeviceLost so the engine fences and re-meshes onto the "
                   "survivors. A trip naming an ordinal already OUT of the "
                   "serving mesh is swallowed (a dead chip cannot hurt a "
                   "mesh it is not in) — that is what lets degraded serving "
                   "continue while the fault stays armed, and what makes "
                   "disarming it the bench's heal signal",
    "device.collective": "the host CT gather inside JITDatapath.remesh "
                         "(the salvage collective): a trip means the "
                         "surviving shards' tables could not be gathered — "
                         "salvage falls back to the ct-snapshot archive "
                         "floor (bounded staleness) or a cold table, "
                         "counted in ct_salvage_source_total",
}

#: hard clamp on ``hang`` stalls: whatever cap a scenario asks for, a
#: hung thread is always released — tests and chaos drills cannot deadlock
HANG_HARD_CAP_S = 30.0


class FaultInjected(RuntimeError):
    """Raised by an armed injection point. A plain RuntimeError subclass so
    every existing failure-isolation path (controllers, degraded regen,
    route handlers) treats it exactly like a real fault."""


def register_point(name: str, description: str) -> None:
    """Declare a new injection point (subsystems added later self-register)."""
    POINTS[name] = description


@dataclass
class FaultSpec:
    mode: str                      # fail | prob | delay
    times: Optional[int] = None    # fail: trip the first N fires (None=all)
    prob: float = 0.0              # prob: trip probability per fire
    delay_s: float = 0.0           # delay: injected latency
    seed: int = 0                  # prob: RNG seed (determinism)
    message: str = ""

    def __post_init__(self):
        if self.mode not in ("fail", "prob", "delay", "hang"):
            raise ValueError(f"bad fault mode {self.mode!r}")
        if self.mode == "prob" and not (0.0 <= self.prob <= 1.0):
            raise ValueError(f"bad fault probability {self.prob!r}")
        if not (0.0 <= self.delay_s):
            raise ValueError(f"bad fault delay {self.delay_s!r}")


@dataclass
class _Armed:
    spec: FaultSpec
    rng: random.Random = field(default_factory=random.Random)
    fires: int = 0                 # times the point was reached while armed
    trips: int = 0                 # times the fault actually triggered


class FaultInjector:
    """Process-wide injection-point registry; see module docstring."""

    def __init__(self, env: Optional[Dict[str, str]] = None):
        self._lock = threading.Lock()
        self._armed: Dict[str, _Armed] = {}
        self._fired: Dict[str, int] = {}    # total fires per point (always)
        env = os.environ if env is None else env
        if env.get(ENV_VAR):
            self.load_spec(env[ENV_VAR])

    # -- arming ------------------------------------------------------------
    def arm(self, point: str, mode: str = "fail", times: Optional[int] = None,
            prob: float = 0.0, delay_s: float = 0.0, seed: int = 0,
            message: str = "") -> None:
        if point not in POINTS:
            raise ValueError(f"unknown injection point {point!r}; known: "
                             f"{sorted(POINTS)}")
        spec = FaultSpec(mode=mode, times=times, prob=prob,
                         delay_s=delay_s, seed=seed, message=message)
        with self._lock:
            self._armed[point] = _Armed(spec, random.Random(seed))

    def disarm(self, point: Optional[str] = None) -> None:
        """Disarm one point (or everything with ``point=None``)."""
        with self._lock:
            if point is None:
                self._armed.clear()
            else:
                self._armed.pop(point, None)

    def inject(self, point: str, **kw) -> "_InjectCtx":
        """Context manager: arm on enter, restore the previous arming on
        exit — scenario steps nest safely."""
        return _InjectCtx(self, point, kw)

    def load_spec(self, text: str) -> int:
        """Parse a ``CILIUM_TPU_FAULTS`` string and arm every entry.
        Returns the number of points armed. All-or-nothing: every entry is
        parsed and validated before ANY point is armed, so a 400 on a bad
        multi-entry spec never leaves earlier entries live on the agent."""
        parsed = []
        for entry in text.replace(",", ";").split(";"):
            entry = entry.strip()
            if not entry:
                continue
            if "=" not in entry:
                raise ValueError(f"bad fault entry {entry!r} "
                                 "(want point=mode[:arg][:k=v]...)")
            point, _, rest = entry.partition("=")
            point = point.strip()
            parts = rest.split(":")
            mode, args = parts[0], parts[1:]
            kw: Dict = {"mode": mode}
            for a in args:
                if "=" in a:
                    k, _, v = a.partition("=")
                    kw[k] = v
                elif mode == "fail":
                    kw["times"] = a
                elif mode == "prob":
                    kw["prob"] = a
                elif mode in ("delay", "hang"):
                    kw["delay_s"] = a
            if "times" in kw:
                kw["times"] = int(kw["times"])
            if "prob" in kw:
                kw["prob"] = float(kw["prob"])
            if "delay_s" in kw:
                kw["delay_s"] = float(kw["delay_s"])
            if "seed" in kw:
                kw["seed"] = int(kw["seed"])
            if point not in POINTS:
                raise ValueError(f"unknown injection point {point!r}; "
                                 f"known: {sorted(POINTS)}")
            try:
                spec = FaultSpec(**kw)
            except TypeError as e:     # unknown k=v key
                raise ValueError(f"bad fault entry {entry!r}: {e}") from None
            parsed.append((point, spec))
        with self._lock:
            for point, spec in parsed:
                self._armed[point] = _Armed(spec, random.Random(spec.seed))
        return len(parsed)

    # -- firing ------------------------------------------------------------
    def fire(self, point: str) -> None:
        """Call at an injection site. Raises FaultInjected / sleeps /
        stalls when the point is armed and the spec trips; otherwise a
        cheap no-op."""
        delay = None
        hang_cap = None
        with self._lock:
            self._fired[point] = self._fired.get(point, 0) + 1
            armed = self._armed.get(point)
            if armed is None:
                return
            armed.fires += 1
            spec = armed.spec
            if spec.mode in ("fail", "delay", "hang"):
                if spec.times is not None and armed.trips >= spec.times:
                    return
            elif spec.mode == "prob":
                if armed.rng.random() >= spec.prob:
                    return
            armed.trips += 1
            if spec.mode == "delay":
                delay = spec.delay_s
            elif spec.mode == "hang":
                hang_cap = min(spec.delay_s or HANG_HARD_CAP_S,
                               HANG_HARD_CAP_S)
        if delay is not None:
            time.sleep(delay)
            return
        if hang_cap is not None:
            self._hang(point, hang_cap)
            return
        raise FaultInjected(
            f"injected fault at {point}"
            + (f": {spec.message}" if spec.message else ""))

    def _hang(self, point: str, cap_s: float) -> None:
        """The cooperative stall: block in small increments until the
        point is disarmed (a chaos driver releasing its victims) or the
        hard cap elapses, then return normally — the caller proceeds as if
        the device finally answered."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < cap_s:
            time.sleep(min(0.02, cap_s))
            with self._lock:
                armed = self._armed.get(point)
                if armed is None or armed.spec.mode != "hang":
                    return

    # -- introspection -----------------------------------------------------
    def armed(self) -> Dict[str, FaultSpec]:
        with self._lock:
            return {p: a.spec for p, a in self._armed.items()}

    def stats(self) -> Dict[str, Dict]:
        """Per-point fire/trip counts (all known points, armed or not)."""
        with self._lock:
            out: Dict[str, Dict] = {}
            for point in sorted(set(POINTS) | set(self._fired)
                                | set(self._armed)):
                armed = self._armed.get(point)
                out[point] = {
                    "description": POINTS.get(point, ""),
                    "fired": self._fired.get(point, 0),
                    "armed": armed is not None,
                    "mode": armed.spec.mode if armed else None,
                    "trips": armed.trips if armed else 0,
                }
            return out

    def reset(self) -> None:
        """Disarm everything and zero the counters (test isolation)."""
        with self._lock:
            self._armed.clear()
            self._fired.clear()


class _InjectCtx:
    def __init__(self, injector: FaultInjector, point: str, kw: Dict):
        self._injector = injector
        self._point = point
        self._kw = kw
        self._prev: Optional[_Armed] = None

    def __enter__(self) -> FaultInjector:
        with self._injector._lock:
            self._prev = self._injector._armed.get(self._point)
        self._injector.arm(self._point, **self._kw)
        return self._injector

    def __exit__(self, *exc) -> None:
        with self._injector._lock:
            if self._prev is None:
                self._injector._armed.pop(self._point, None)
            else:
                self._injector._armed[self._point] = self._prev


#: the process-wide injector every instrumented site fires through
FAULTS = FaultInjector()
