"""Multi-host control-plane sync — the clustermesh analog (SURVEY.md §5
distributed backend: "DCN/host network carries control-plane sync (policy
snapshots to peer hosts, the etcd analog — keep it a simple gRPC/file
protocol)"; upstream: ``pkg/clustermesh`` syncing ipcache/identities between
clusters through etcd).

Protocol: a shared store directory (NFS/object-store mount — the DCN-visible
rendezvous; explicitly NOT a reimplementation of etcd, per SURVEY §5's
non-goal). Each node atomically publishes ``<store>/<node>.json``:

    {"node", "generation", "published_at",
     "entries": {prefix: {"labels": [...]}}}

carrying its local endpoints' IP prefixes with their LABEL SETS — labels,
not numeric identities, cross the wire, exactly like upstream clustermesh:
identity numbering is node-local, so the receiver allocates its own identity
for each remote label set (same labels ⇒ same identity ⇒ remote pods are
selectable by normal fromEndpoints/toEndpoints policy).

Each node polls peers' files (a controller with backoff — the watch analog)
and reconciles: new prefixes allocate+upsert, withdrawn prefixes release;
a peer whose file goes stale (no heartbeat within ``stale_after_s``) is
treated as failed and its state withdrawn (upstream: etcd lease expiry).

Partition / conflict contract (ISSUE 12 — the serving-tier semantics):

* **Store partition** (``clustermesh.store_list`` / ``clustermesh.peer_read``
  faults, a dead NFS mount): the node serves its LAST-GOOD remote state —
  established remote flows never fail closed because the control plane went
  away. Past ``staleness_budget_s`` without a successful store pass the mesh
  reports :data:`~cilium_tpu.utils.constants.MESH_STALE` and
  ``Engine.health()`` degrades; heal clears it on the next good pass.
* **Conflicting prefix claims** (two live peers claiming one prefix — a pod
  mid-move, a misconfigured node): resolved DETERMINISTICALLY everywhere by
  highest ``generation``, ties broken by lexicographically-first node name;
  the losing claim is not ingested anywhere (withdrawn if previously held),
  so the mesh converges to one owner instead of split-braining per node.
  Losers count into ``clustermesh_conflicts_total{prefix_winner=...}``.
  A prefix owned LOCALLY (one of this node's own endpoints) always beats
  any remote claim.
* **Lagging peer**: ``clustermesh_peer_lag_seconds{peer=...}`` gauges the
  time since a peer's generation last progressed (judged on OUR clock —
  skew-immune), and every observed generation step samples
  ``clustermesh_replication_lag_seconds`` (publish→ingest delay, clamped at
  zero — a peer whose wall clock runs ahead must not read negative);
  :meth:`status` surfaces the windowed p99.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import time
from collections import deque
from typing import TYPE_CHECKING, Callable, Dict, List, Optional, Tuple

import numpy as np

from cilium_tpu.model.labels import Labels
from cilium_tpu.runtime.faults import FAULTS, FaultInjected
from cilium_tpu.utils import constants as C

if TYPE_CHECKING:
    from cilium_tpu.runtime.engine import Engine

log = logging.getLogger("cilium_tpu.clustermesh")

FORMAT_VERSION = 1

#: replication-lag samples retained for the windowed p99 in :meth:`status`
LAG_WINDOW = 512


class ClusterMesh:
    """Publishes this node's endpoint map and ingests peers' into the local
    identity allocator + ipcache. Owned by the Engine; driven by the
    ``clustermesh-sync`` controller."""

    def __init__(self, engine: "Engine", store_dir: str, node_name: str,
                 stale_after_s: float = 60.0,
                 staleness_budget_s: float = 15.0,
                 clock: Optional[Callable[[], float]] = None):
        if not node_name or "/" in node_name or node_name.startswith("."):
            raise ValueError(f"bad node name {node_name!r}")
        self.engine = engine
        self.store_dir = store_dir
        self.node_name = node_name
        self.stale_after_s = stale_after_s
        self.staleness_budget_s = staleness_budget_s
        # test/chaos hooks: ``clock`` replaces the wall clock for EVERY
        # mesh judgment (leases, staleness, publish stamps);
        # ``publish_skew_s`` skews only the published_at stamp — the
        # cross-node wall-clock-skew drill (leases stay on the local
        # clock, which is the design's whole skew defense)
        self._clock = clock
        self.publish_skew_s = 0.0
        self._generation = 0
        # peer → {prefix: (identity, labels_key)} we ingested (for release)
        self._ingested: Dict[str, Dict[str, object]] = {}
        # peer → (doc, lease_ts): a transiently unreadable file (NFS
        # hiccup) must NOT read as departure — the lease (stale_after_s),
        # not one failed read, decides withdrawal. lease_ts is OUR clock,
        # advanced only when the peer's generation changes: judging
        # staleness from the peer-written published_at would withdraw a
        # live peer whose clock is skewed behind ours (etcd leases are
        # likewise granted on the server's clock, not the client's).
        self._last_good: Dict[str, Tuple[Dict, float]] = {}
        # prefix → (winner_node, losers): currently-observed conflicting
        # claims, so each distinct conflict counts once, not once per sync
        self._conflicts: Dict[str, Tuple[str, Tuple[str, ...]]] = {}
        # node → generation its lease EXPIRED at: a crashed peer's file
        # stays in the store, and without this memory the next sync would
        # re-cache it as "fresh" (lease renewed) and resurrect the dead
        # peer every other pass — only real generation PROGRESS (the node
        # actually restarting/publishing) clears the tombstone
        self._expired: Dict[str, object] = {}
        self._store_ok = True          # last listing attempt succeeded
        self._last_pass_ok: float = self._now()   # last good store pass
        self._repl_lag = deque(maxlen=LAG_WINDOW)  # publish→ingest seconds
        os.makedirs(store_dir, exist_ok=True)
        self._sweep_tmp_litter()

    def _now(self) -> float:
        # late-bound so tests monkeypatching time.time still work
        return self._clock() if self._clock is not None else time.time()

    def _drop_peer_gauge(self, node: str) -> None:
        # a departed peer's frozen lag gauge would keep exporting a small,
        # healthy-looking value forever — remove it with the peer
        self.engine.metrics.drop_gauge(
            f'clustermesh_peer_lag_seconds{{peer="{node}"}}')

    # -- publish ------------------------------------------------------------
    def _own_entries(self) -> Dict[str, Dict]:
        entries: Dict[str, Dict] = {}
        for ep in self.engine.endpoints.values():
            labels = list(ep.labels.to_strings())
            for ip in ep.ips:
                prefix = f"{ip}/128" if ":" in ip else f"{ip}/32"
                entries[prefix] = {"labels": labels}
        return entries

    def _sweep_tmp_litter(self) -> None:
        """Startup hygiene: a writer that crashed between ``mkstemp`` and
        ``os.replace`` leaves a ``.``-prefixed tmp file behind forever (the
        store is append-only otherwise). Sweep OUR OWN litter
        unconditionally (we are this node's only writer) and other nodes'
        only once it is old enough that no live publish can still be
        racing its rename window."""
        try:
            names = os.listdir(self.store_dir)
        except OSError:
            return                     # store unreachable: sync() will say so
        now = time.time()              # mtimes are real fs time, not _clock
        own_prefix = f".{self.node_name}-"
        swept = 0
        for name in names:
            if not name.startswith("."):
                continue
            path = os.path.join(self.store_dir, name)
            try:
                old = now - os.path.getmtime(path) > max(
                    self.stale_after_s, 60.0)
                if name.startswith(own_prefix) or old:
                    os.unlink(path)
                    swept += 1
            except OSError:
                continue               # already gone / unreadable: not ours
        if swept:
            log.info("clustermesh: swept %d stale tmp file(s) from %s",
                     swept, self.store_dir)
            self.engine.metrics.inc_counter(
                "clustermesh_tmp_swept_total", swept)

    def publish(self) -> None:
        """Write this node's state atomically (tmp + rename — readers never
        see a torn file; the single-file-per-writer layout makes the store
        safely multi-writer without locks). A failed write never leaves
        tmp litter behind."""
        self._generation += 1
        doc = {
            "format_version": FORMAT_VERSION,
            "node": self.node_name,
            "generation": self._generation,
            "published_at": self._now() + self.publish_skew_s,
            "entries": self._own_entries(),
        }
        fd, tmp = tempfile.mkstemp(dir=self.store_dir,
                                   prefix=f".{self.node_name}-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, os.path.join(self.store_dir,
                                         f"{self.node_name}.json"))
        except BaseException:
            # json.dump / replace failed: the doc never landed — remove the
            # tmp so a crash-looping publisher cannot fill the store with
            # litter (the startup sweep is the backstop, not the plan)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # -- ingest -------------------------------------------------------------
    def _read_peers(self) -> Dict[str, Dict]:
        peers: Dict[str, Dict] = {}
        now = self._now()
        listing_ok = True
        try:
            FAULTS.fire("clustermesh.store_list")
            names = os.listdir(self.store_dir)
        except (OSError, FaultInjected) as e:
            # whole store unreachable (partition): hold last-good state —
            # established remote flows must keep classifying; status()
            # reports MESH_STALE once the staleness budget is spent
            log.warning("clustermesh: store unreachable (%s); holding "
                        "last-known peer state", e)
            names = []
            listing_ok = False
        seen = set()
        for name in names:
            if not name.endswith(".json") or name.startswith("."):
                continue
            node = name[: -len(".json")]
            if node == self.node_name:
                continue
            seen.add(node)
            path = os.path.join(self.store_dir, name)
            try:
                FAULTS.fire("clustermesh.peer_read")
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError, FaultInjected) as e:
                log.warning("clustermesh: unreadable peer file %s: %s "
                            "(holding last-known state)", name, e)
                doc = None
            if doc is not None and doc.get("node") != node:
                # the doc's self-declared node MUST match the filename stem:
                # a file claiming to be another node would otherwise be
                # ingested under the wrong peer's ledger — and withdrawn
                # wholesale on the next sync as a spoofed withdrawal
                log.warning("clustermesh: peer file %s claims node %r — "
                            "spoofed or misplaced, ignored (holding "
                            "last-known state)", name, doc.get("node"))
                self.engine.metrics.inc_counter(
                    "clustermesh_spoofed_peer_files_total")
                doc = None
            if doc is not None:
                if doc.get("format_version") != FORMAT_VERSION:
                    log.warning("clustermesh: peer %s speaks format %r, "
                                "skipped", node, doc.get("format_version"))
                    # a real doc in an unknown format supersedes anything
                    # cached — keeping serving the old doc would pin stale
                    # identities for the lease duration
                    self._last_good.pop(node, None)
                    self._drop_peer_gauge(node)
                    continue
                if node in self._expired \
                        and doc.get("generation") == self._expired[node]:
                    continue           # tombstoned: the file is a dead
                                       # peer's last word, not a heartbeat
                self._expired.pop(node, None)
                cached = self._last_good.get(node)
                if (cached is None
                        or doc.get("generation") != cached[0].get("generation")):
                    ts = now               # progress observed: renew lease
                    # replication lag: publish→ingest delay for this
                    # generation step. Clamped at zero — a peer whose wall
                    # clock runs AHEAD of ours must not produce negative
                    # samples (leases are already skew-immune; this metric
                    # is best-effort wall truth)
                    lag = max(0.0, now - float(doc.get("published_at", now)))
                    self._repl_lag.append(lag)
                    self.engine.metrics.histogram(
                        "clustermesh_replication_lag_seconds").observe(lag)
                else:
                    ts = cached[1]         # unchanged generation: lease ages
                self._last_good[node] = (doc, ts)
        for node, (doc, ts) in list(self._last_good.items()):
            if listing_ok and node not in seen:
                # file explicitly gone from a healthy store: the peer's
                # clean withdraw() — immediate removal (etcd delete analog)
                del self._last_good[node]
                self._expired.pop(node, None)
                self._drop_peer_gauge(node)
                continue
            if listing_ok and now - ts > self.stale_after_s:
                # expired lease: treated as withdrawn — and tombstoned at
                # this generation, so the lingering file of a crashed peer
                # cannot resurrect it (only generation progress can).
                # Expiry requires a HEALTHY listing: while the store is
                # partitioned no heartbeat is observable at all, and
                # expiring peers then would turn a control-plane outage
                # into a data-plane one (established remote flows failing
                # closed — the exact thing the partition contract forbids).
                # After heal, a peer whose generation did not progress
                # expires on the first good pass.
                self._expired[node] = doc.get("generation")
                del self._last_good[node]
                self._drop_peer_gauge(node)
                continue
            peers[node] = doc
            self.engine.metrics.set_gauge(
                f'clustermesh_peer_lag_seconds{{peer="{node}"}}',
                round(max(0.0, now - ts), 3))
        if listing_ok:
            self._last_pass_ok = now
        self._store_ok = listing_ok
        self.engine.metrics.set_gauge("clustermesh_store_ok",
                                      1 if listing_ok else 0)
        return peers

    # -- conflict resolution -------------------------------------------------
    def _resolve_claims(self, peers: Dict[str, Dict]
                        ) -> Dict[str, Dict[str, Dict]]:
        """Peers' raw docs → per-peer EFFECTIVE entry maps with conflicting
        prefix claims resolved deterministically: highest generation wins,
        ties broken by lexicographically-first node name — the same answer
        on every node of the mesh, so a losing claim is withdrawn
        everywhere rather than split-brained per node. Prefixes this node
        itself publishes (live local endpoints) always beat remote claims.
        New conflicts count into
        ``clustermesh_conflicts_total{prefix_winner=...}`` once per
        distinct (prefix, winner, losers) observation."""
        local = set(self._own_entries())
        effective: Dict[str, Dict[str, Dict]] = {n: {} for n in peers}
        conflicts_now: Dict[str, Tuple[str, Tuple[str, ...]]] = {}
        claims: Dict[str, List[Tuple[int, str, Dict]]] = {}
        for node, doc in peers.items():
            for prefix, entry in doc.get("entries", {}).items():
                claims.setdefault(prefix, []).append(
                    (int(doc.get("generation", 0)), node, entry))
        for prefix, cs in claims.items():
            if prefix in local:
                # local endpoints own their prefixes unconditionally: a
                # remote claim is a conflict we lose nothing to
                conflicts_now[prefix] = (
                    self.node_name, tuple(sorted(n for _g, n, _e in cs)))
                continue
            if len(cs) == 1:
                _g, node, entry = cs[0]
                effective[node][prefix] = entry
                continue
            cs_sorted = sorted(cs, key=lambda c: (-c[0], c[1]))
            _g, winner, entry = cs_sorted[0]
            effective[winner][prefix] = entry
            conflicts_now[prefix] = (
                winner, tuple(sorted(n for _g2, n, _e2 in cs_sorted[1:])))
        for prefix, (winner, losers) in conflicts_now.items():
            if self._conflicts.get(prefix) == (winner, losers):
                continue               # already counted this exact conflict
            log.warning("clustermesh: conflicting claims on %s: winner=%s "
                        "losers=%s (highest-generation-then-node-name)",
                        prefix, winner, ",".join(losers))
            self.engine.metrics.inc_counter(
                f'clustermesh_conflicts_total{{prefix_winner="{winner}"}}',
                len(losers))
        self._conflicts = conflicts_now
        return effective

    def sync(self) -> Tuple[int, int]:
        """One reconcile pass: ingest peers, withdraw the departed.
        Returns (n_added, n_removed) ipcache entries."""
        ctx = self.engine.ctx
        peers = self._read_peers()
        effective = self._resolve_claims(peers)
        # prefix → claiming node across ALL effective peers, PLUS this
        # node's own endpoints: a withdrawal must not punch an ipcache
        # hole under a prefix another peer still (or newly) claims — the
        # hand-off case — and a remote→LOCAL hand-off (the pod moved to
        # us: _resolve_claims strips local prefixes from every peer's
        # effective map, so without the local set the old remote mapping's
        # withdrawal would delete the live local endpoint's entry)
        claimed = {p for entries in effective.values() for p in entries}
        claimed |= set(self._own_entries())
        added = removed = 0
        deferred_release = []
        with self.engine._lock:            # noqa: SLF001 — same lifecycle
            # withdrawals: peers gone/stale, entries they dropped, or
            # claims they just LOST to a higher-generation peer (the
            # conflict loser is withdrawn everywhere, not split-brained)
            for node in list(self._ingested):
                peer_entries = effective.get(node, {})
                held = self._ingested[node]
                for prefix in list(held):
                    new = peer_entries.get(prefix)
                    old_ident, old_labels = held[prefix]
                    if new is not None \
                            and tuple(sorted(new["labels"])) == old_labels:
                        continue
                    # the prefix no longer belongs to this peer's pod:
                    # remove the mapping unless another live claim covers
                    # it (a stale IP mapping would grant the old pod's
                    # permissions to whoever reuses the address). The
                    # identity release is DEFERRED past the additions pass
                    # so a hand-off (same labels, new peer) re-refs the
                    # same identity instead of minting a new number.
                    deferred_release.append(old_ident)
                    if prefix not in claimed:
                        ctx.ipcache.delete(prefix)
                    del held[prefix]
                    removed += 1
                if not held:
                    del self._ingested[node]
            # additions/updates
            for node in sorted(effective):
                held = self._ingested.setdefault(node, {})
                for prefix, entry in effective[node].items():
                    key = tuple(sorted(entry["labels"]))
                    if prefix in held:
                        # unchanged claim (label mismatches were removed
                        # above) — but on a prefix hand-off (pod moved
                        # between peers) the departing peer's withdrawal
                        # pass just deleted the ipcache entry out from
                        # under our still-live claim. Re-upsert when the
                        # entry is missing (upsert is idempotent) instead
                        # of short-circuiting into a permanent hole.
                        ident, _key = held[prefix]
                        if ctx.ipcache.get(prefix) is None:
                            ctx.ipcache.upsert(prefix, ident.id)
                            added += 1
                        continue
                    ident = ctx.allocator.allocate(Labels.parse(
                        list(entry["labels"])))
                    ctx.ipcache.upsert(prefix, ident.id)
                    held[prefix] = (ident, key)
                    added += 1
                if not self._ingested[node]:
                    del self._ingested[node]
            for ident in deferred_release:
                ctx.allocator.release(ident)
        if added or removed:
            self.engine.metrics.set_gauge(
                "clustermesh_remote_entries",
                sum(len(h) for h in self._ingested.values()))
        self.engine.metrics.set_gauge("clustermesh_peers",
                                      len(self._ingested))
        self.engine.metrics.set_gauge(
            "clustermesh_mesh_stale", 1 if self.is_stale() else 0)
        return added, removed

    def step(self) -> None:
        """One controller tick: publish our state, ingest everyone else's."""
        self.publish()
        self.sync()

    # -- introspection -------------------------------------------------------
    def is_stale(self) -> bool:
        """True once the staleness budget is spent without a good store
        pass — the MESH_STALE health detail. Last-good remote state keeps
        serving regardless (never fail closed on established remote
        flows); stale only says the view may be behind the mesh."""
        return self._now() - self._last_pass_ok > self.staleness_budget_s

    def replication_lag_p99(self) -> float:
        """Windowed p99 of observed publish→ingest replication lag."""
        # list(deque) is a single C-level copy (GIL-atomic) — safe against
        # the sync thread appending concurrently
        samples = list(self._repl_lag)
        if not samples:
            return 0.0
        return float(np.percentile(np.asarray(samples), 99))

    def status(self) -> Dict:
        """The mesh health/lag surface (folded into ``Engine.health()`` and
        ``/v1/status``): per-peer generation + lag, store reachability,
        staleness verdict, conflict map, replication-lag p99.

        Called from the API/health threads while the ``clustermesh-sync``
        controller mutates peer state — every shared dict is read through
        one C-level (GIL-atomic) copy, never iterated live: individual
        values are immutable once stored (docs are never mutated in place,
        ``_conflicts`` is replaced wholesale), so the copy is a consistent
        snapshot without taking the engine lock on a path that must stay
        responsive while the store hangs."""
        now = self._now()
        stale = self.is_stale()
        peers = {}
        for node, (doc, ts) in dict(self._last_good).items():
            peers[node] = {
                "generation": int(doc.get("generation", 0)),
                "entries": len(doc.get("entries", {})),
                "lag_s": round(max(0.0, now - ts), 3),
            }
        conflicts = self._conflicts
        return {
            "state": C.MESH_STALE if stale else C.HEALTH_OK,
            "node": self.node_name,
            "generation": self._generation,
            "store_ok": self._store_ok,
            "last_good_pass_age_s": round(max(0.0, now - self._last_pass_ok),
                                          3),
            "staleness_budget_s": self.staleness_budget_s,
            "peers": peers,
            "remote_entries": sum(len(h)
                                  for h in list(self._ingested.values())),
            "conflicts": {p: {"winner": w, "losers": list(ls)}
                          for p, (w, ls) in sorted(conflicts.items())},
            "replication_lag_p99_s": round(self.replication_lag_p99(), 6),
        }

    def remote_view(self) -> Dict[str, Dict]:
        """The ingested remote world, keyed by prefix — identity numbers
        are node-local, so cross-node convergence is judged on (peer,
        labels), which this view carries (the bench/tests' convergence
        probe). Reads GIL-atomic copies, same as :meth:`status`."""
        out: Dict[str, Dict] = {}
        for node, held in dict(self._ingested).items():
            for prefix, (ident, labels_key) in dict(held).items():
                out[prefix] = {"peer": node, "labels": list(labels_key),
                               "identity": ident.id}
        return out

    def withdraw(self) -> None:
        """Remove this node's published state (clean shutdown). A failed
        unlink is LOUD: a node that cannot withdraw looks exactly like one
        that did to every peer — until the lease expires — so the failure
        is logged and counted instead of silently swallowed."""
        # departed-subject gauge sweep (ISSUE 13): detaching the mesh
        # deregisters every peer this node was tracking — their lag gauges
        # must go with them (expiry/tombstone paths already sweep their own
        # peer; a withdraw mid-tracking would otherwise pin every live
        # peer's last lag forever)
        for node in list(self._last_good) + list(self._ingested):
            self._drop_peer_gauge(node)
        path = os.path.join(self.store_dir, f"{self.node_name}.json")
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass                       # never published / already withdrawn
        except OSError as e:
            log.warning(
                "clustermesh: withdraw failed for %s: %s — peers will keep "
                "serving this node's last claims for up to the full lease "
                "(%.0fs)", path, e, self.stale_after_s)
            self.engine.metrics.inc_counter(
                "clustermesh_withdraw_errors_total")
