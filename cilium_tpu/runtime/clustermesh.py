"""Multi-host control-plane sync — the clustermesh analog (SURVEY.md §5
distributed backend: "DCN/host network carries control-plane sync (policy
snapshots to peer hosts, the etcd analog — keep it a simple gRPC/file
protocol)"; upstream: ``pkg/clustermesh`` syncing ipcache/identities between
clusters through etcd).

Protocol: a shared store directory (NFS/object-store mount — the DCN-visible
rendezvous; explicitly NOT a reimplementation of etcd, per SURVEY §5's
non-goal). Each node atomically publishes ``<store>/<node>.json``:

    {"node", "generation", "published_at",
     "entries": {prefix: {"labels": [...]}}}

carrying its local endpoints' IP prefixes with their LABEL SETS — labels,
not numeric identities, cross the wire, exactly like upstream clustermesh:
identity numbering is node-local, so the receiver allocates its own identity
for each remote label set (same labels ⇒ same identity ⇒ remote pods are
selectable by normal fromEndpoints/toEndpoints policy).

Each node polls peers' files (a controller with backoff — the watch analog)
and reconciles: new prefixes allocate+upsert, withdrawn prefixes release;
a peer whose file goes stale (no heartbeat within ``stale_after_s``) is
treated as failed and its state withdrawn (upstream: etcd lease expiry).
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import time
from typing import TYPE_CHECKING, Dict, Optional, Tuple

from cilium_tpu.model.labels import Labels
from cilium_tpu.runtime.faults import FAULTS, FaultInjected

if TYPE_CHECKING:
    from cilium_tpu.runtime.engine import Engine

log = logging.getLogger("cilium_tpu.clustermesh")

FORMAT_VERSION = 1


class ClusterMesh:
    """Publishes this node's endpoint map and ingests peers' into the local
    identity allocator + ipcache. Owned by the Engine; driven by the
    ``clustermesh-sync`` controller."""

    def __init__(self, engine: "Engine", store_dir: str, node_name: str,
                 stale_after_s: float = 60.0):
        if not node_name or "/" in node_name or node_name.startswith("."):
            raise ValueError(f"bad node name {node_name!r}")
        self.engine = engine
        self.store_dir = store_dir
        self.node_name = node_name
        self.stale_after_s = stale_after_s
        self._generation = 0
        # peer → {prefix: (identity, labels_key)} we ingested (for release)
        self._ingested: Dict[str, Dict[str, object]] = {}
        # peer → (doc, lease_ts): a transiently unreadable file (NFS
        # hiccup) must NOT read as departure — the lease (stale_after_s),
        # not one failed read, decides withdrawal. lease_ts is OUR clock,
        # advanced only when the peer's generation changes: judging
        # staleness from the peer-written published_at would withdraw a
        # live peer whose clock is skewed behind ours (etcd leases are
        # likewise granted on the server's clock, not the client's).
        self._last_good: Dict[str, Tuple[Dict, float]] = {}
        os.makedirs(store_dir, exist_ok=True)

    # -- publish ------------------------------------------------------------
    def _own_entries(self) -> Dict[str, Dict]:
        entries: Dict[str, Dict] = {}
        for ep in self.engine.endpoints.values():
            labels = list(ep.labels.to_strings())
            for ip in ep.ips:
                prefix = f"{ip}/128" if ":" in ip else f"{ip}/32"
                entries[prefix] = {"labels": labels}
        return entries

    def publish(self) -> None:
        """Write this node's state atomically (tmp + rename — readers never
        see a torn file; the single-file-per-writer layout makes the store
        safely multi-writer without locks)."""
        self._generation += 1
        doc = {
            "format_version": FORMAT_VERSION,
            "node": self.node_name,
            "generation": self._generation,
            "published_at": time.time(),
            "entries": self._own_entries(),
        }
        fd, tmp = tempfile.mkstemp(dir=self.store_dir,
                                   prefix=f".{self.node_name}-")
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, os.path.join(self.store_dir,
                                     f"{self.node_name}.json"))

    # -- ingest -------------------------------------------------------------
    def _read_peers(self) -> Dict[str, Dict]:
        peers: Dict[str, Dict] = {}
        now = time.time()
        listing_ok = True
        try:
            names = os.listdir(self.store_dir)
        except OSError as e:           # whole store unreachable: hold state
            log.warning("clustermesh: store unreadable (%s); holding "
                        "last-known peer state", e)
            names = []
            listing_ok = False
        seen = set()
        for name in names:
            if not name.endswith(".json") or name.startswith("."):
                continue
            node = name[: -len(".json")]
            if node == self.node_name:
                continue
            seen.add(node)
            path = os.path.join(self.store_dir, name)
            try:
                FAULTS.fire("clustermesh.peer_read")
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError, FaultInjected) as e:
                log.warning("clustermesh: unreadable peer file %s: %s "
                            "(holding last-known state)", name, e)
                doc = None
            if doc is not None:
                if doc.get("format_version") != FORMAT_VERSION:
                    log.warning("clustermesh: peer %s speaks format %r, "
                                "skipped", node, doc.get("format_version"))
                    # a real doc in an unknown format supersedes anything
                    # cached — keeping serving the old doc would pin stale
                    # identities for the lease duration
                    self._last_good.pop(node, None)
                    continue
                cached = self._last_good.get(node)
                if (cached is None
                        or doc.get("generation") != cached[0].get("generation")):
                    ts = now               # progress observed: renew lease
                else:
                    ts = cached[1]         # unchanged generation: lease ages
                self._last_good[node] = (doc, ts)
        for node, (doc, ts) in list(self._last_good.items()):
            if listing_ok and node not in seen:
                # file explicitly gone from a healthy store: the peer's
                # clean withdraw() — immediate removal (etcd delete analog)
                del self._last_good[node]
                continue
            if now - ts > self.stale_after_s:
                del self._last_good[node]
                continue               # expired lease: treated as withdrawn
            peers[node] = doc
        return peers

    def sync(self) -> Tuple[int, int]:
        """One reconcile pass: ingest peers, withdraw the departed.
        Returns (n_added, n_removed) ipcache entries."""
        ctx = self.engine.ctx
        peers = self._read_peers()
        added = removed = 0
        with self.engine._lock:            # noqa: SLF001 — same lifecycle
            # withdrawals: peers gone/stale, or entries they dropped
            for node in list(self._ingested):
                peer_entries = (peers.get(node) or {}).get("entries", {})
                held = self._ingested[node]
                for prefix in list(held):
                    new = peer_entries.get(prefix)
                    old_ident, old_labels = held[prefix]
                    if new is not None \
                            and tuple(sorted(new["labels"])) == old_labels:
                        continue
                    # the prefix belongs to the departed peer pod: remove it
                    # unconditionally (the identity may survive via other
                    # refs — e.g. a local pod with the same labels — but a
                    # stale IP mapping would grant the old pod's permissions
                    # to whoever reuses the address)
                    ctx.allocator.release(old_ident)
                    ctx.ipcache.delete(prefix)
                    del held[prefix]
                    removed += 1
                if not held:
                    del self._ingested[node]
            # additions/updates
            for node, doc in peers.items():
                held = self._ingested.setdefault(node, {})
                for prefix, entry in doc.get("entries", {}).items():
                    key = tuple(sorted(entry["labels"]))
                    if prefix in held:
                        # unchanged claim (label mismatches were removed
                        # above) — but on a prefix hand-off (pod moved
                        # between peers) the departing peer's withdrawal
                        # pass just deleted the ipcache entry out from
                        # under our still-live claim. Re-upsert when the
                        # entry is missing (upsert is idempotent) instead
                        # of short-circuiting into a permanent hole.
                        ident, _key = held[prefix]
                        if ctx.ipcache.get(prefix) is None:
                            ctx.ipcache.upsert(prefix, ident.id)
                            added += 1
                        continue
                    ident = ctx.allocator.allocate(Labels.parse(
                        list(entry["labels"])))
                    ctx.ipcache.upsert(prefix, ident.id)
                    held[prefix] = (ident, key)
                    added += 1
        if added or removed:
            self.engine.metrics.set_gauge(
                "clustermesh_remote_entries",
                sum(len(h) for h in self._ingested.values()))
        self.engine.metrics.set_gauge("clustermesh_peers",
                                      len(self._ingested))
        return added, removed

    def step(self) -> None:
        """One controller tick: publish our state, ingest everyone else's."""
        self.publish()
        self.sync()

    def withdraw(self) -> None:
        """Remove this node's published state (clean shutdown)."""
        try:
            os.unlink(os.path.join(self.store_dir,
                                   f"{self.node_name}.json"))
        except OSError:
            pass
