"""Multi-host serving harness: N engine processes over one clustermesh
store (ISSUE 12 / ROADMAP item 3 — the "millions of users" horizontal
axis). Used by ``bench.py --cluster N`` and the cluster chaos tests.

Each node is a real OS process (``multiprocessing`` *spawn* — a fresh
interpreter per node, so jax state, the FAULTS singleton, identity
numbering and the engine lock are all genuinely per-host) running a full
Engine with its own datapath, mesh, auditor and flowlog. The supervisor
drives them over pipes with a tiny command protocol; faults are armed
*inside* a node (each process owns its own injector), which is exactly the
partition topology a real deployment has — one node's dead NFS mount is
invisible to the others.

The harness is deterministic by construction: nothing ticks on wall-clock
controllers — the driver commands every ``mesh.step()`` and regeneration
explicitly, so a chaos sequence (partition N syncs, kill a peer, conflict
two claims) replays identically."""

from __future__ import annotations

import multiprocessing as mp
import os
import time
import traceback
from typing import Dict, List, Optional, Tuple


# --------------------------------------------------------------------------- #
# the node worker (runs in a spawned child process)
# --------------------------------------------------------------------------- #
def _flow_records(flows: List[Dict]):
    from cilium_tpu.utils import constants as C
    from cilium_tpu.utils.ip import parse_addr
    from oracle import PacketRecord
    recs = []
    for f in flows:
        s16, _ = parse_addr(f["src"])
        d16, v6 = parse_addr(f["dst"])
        recs.append(PacketRecord(
            s16, d16, int(f.get("sport", 40000)), int(f["dport"]),
            int(f.get("proto", C.PROTO_TCP)),
            int(f.get("flags", C.TCP_SYN)), v6, int(f["ep_id"]),
            C.DIR_INGRESS if f.get("direction", "ingress") == "ingress"
            else C.DIR_EGRESS))
    return recs


def _node_worker(conn, node_name: str, store_dir: str,
                 overrides: Optional[Dict], datapath: str) -> None:
    """One mesh node: build the engine, answer supervisor commands until
    ``stop`` (clean shutdown + withdraw) or ``exit_dirty`` (simulated
    crash: the published file stays behind for the lease to expire)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from cilium_tpu.kernels.records import batch_from_records
    from cilium_tpu.runtime.config import DaemonConfig
    from cilium_tpu.runtime.engine import Engine
    from cilium_tpu.runtime.faults import FAULTS

    kw = dict(ct_capacity=1 << 13, auto_regen=False,
              cluster_store=store_dir, node_name=node_name,
              cluster_stale_after_s=60.0, cluster_staleness_budget_s=15.0,
              audit_enabled=True, audit_sample_rate=1.0,
              audit_pool_batches=64, flowlog_mode="all")
    kw.update(overrides or {})
    cfg = DaemonConfig(**kw)
    if datapath == "fake":
        from cilium_tpu.runtime.datapath import FakeDatapath
        eng = Engine(cfg, datapath=FakeDatapath(cfg))
    else:
        eng = Engine(cfg)              # JITDatapath
    eng.auditor.configure(sample_rate=1.0)
    mesh = eng.attach_mesh()

    def classify(flows: List[Dict], now: int) -> Dict:
        batch = batch_from_records(_flow_records(flows),
                                   eng.active.snapshot.ep_slot_of)
        out = eng.classify(batch, now=now)
        return {k: np.asarray(out[k]).tolist()
                for k in ("allow", "reason", "remote_identity",
                          "matched_rule")}

    def drain_audit() -> Dict:
        for _ in range(200):
            step = eng.audit_step(budget=128)
            if not step or (not step.get("replayed")
                            and not step.get("pending")):
                break
        return eng.auditor.stats()

    running = True
    while running:
        try:
            cmd, args = conn.recv()
        except (EOFError, OSError):
            break
        try:
            if cmd == "ping":
                res = {"node": node_name, "pid": os.getpid()}
            elif cmd == "add_ep":
                ep = eng.add_endpoint(args["labels"],
                                      ips=tuple(args.get("ips", ())),
                                      ep_id=args.get("ep_id"))
                res = {"ep_id": ep.ep_id, "identity": ep.identity_id}
            elif cmd == "remove_ep":
                res = {"removed": eng.remove_endpoint(args["ep_id"])}
            elif cmd == "policy":
                res = {"revision": eng.apply_policy(args["docs"])}
            elif cmd == "step":
                # publish + ingest + regenerate: one full control-plane
                # tick, reporting whether the regen took the delta path
                added = removed = 0
                for _ in range(int(args.get("n", 1))):
                    mesh.publish()
                    a, r = mesh.sync()
                    added += a
                    removed += r
                eng.regenerate()
                res = {"added": added, "removed": removed,
                       "regen_incremental": eng.metrics.counters.get(
                           "regen_incremental_total", 0),
                       "regen_full": eng.metrics.counters.get(
                           "regen_full_total", 0)}
            elif cmd == "regen":
                # explicit regeneration (warm/seed the incremental
                # compiler BEFORE remote entries arrive, so a later step's
                # ingest provably rides the delta-patch path)
                compiled = eng.regenerate(force=bool(args.get("force")))
                res = {"revision": compiled.revision}
            elif cmd == "classify":
                res = classify(args["flows"], int(args.get("now", 1000)))
            elif cmd == "serve":
                flows = args["flows"]
                batches = int(args.get("batches", 20))
                now = int(args.get("now", 1000))
                classify(flows, now - 1)   # warmup: XLA compile is not fps
                allowed = rows = 0
                t0 = time.monotonic()
                for i in range(batches):
                    out = classify(flows, now + i)
                    allowed += sum(out["allow"])
                    rows += len(flows)
                dt = max(time.monotonic() - t0, 1e-9)
                res = {"rows": rows, "allowed": allowed,
                       "elapsed_s": dt, "fps": rows / dt}
            elif cmd == "audit":
                res = drain_audit()
            elif cmd == "status":
                res = {"health": eng.health(),
                       "mesh": mesh.status(),
                       "remote_view": mesh.remote_view(),
                       "counters": {
                           k: v for k, v in eng.metrics.counters.items()
                           if k.startswith(("regen_", "clustermesh_"))},
                       "audit": eng.auditor.stats()}
            elif cmd == "arm":
                FAULTS.arm(args["point"], **args.get("spec", {}))
                res = {"armed": args["point"]}
            elif cmd == "disarm":
                FAULTS.disarm(args.get("point"))
                res = {"disarmed": args.get("point")}
            elif cmd == "skew":
                # cross-node wall-clock skew drill: only the published_at
                # stamp moves — leases stay on each node's local clock
                mesh.publish_skew_s = float(args["seconds"])
                res = {"publish_skew_s": mesh.publish_skew_s}
            elif cmd == "flush":
                eng.flush_observability()
                res = {"flushed": True}
            elif cmd == "stop":
                eng.stop()             # clean: withdraws the node file
                res = {"stopped": True}
                running = False
            elif cmd == "exit_dirty":
                res = {"exited": True}  # crash: no withdraw, file stays
                running = False
            else:
                raise ValueError(f"unknown command {cmd!r}")
            conn.send(("ok", res))
        except Exception:
            conn.send(("err", traceback.format_exc()))
    conn.close()


# --------------------------------------------------------------------------- #
# supervisor side
# --------------------------------------------------------------------------- #
class ClusterNode:
    """Handle on one spawned node process."""

    def __init__(self, name: str, store_dir: str,
                 overrides: Optional[Dict] = None, datapath: str = "jit",
                 ctx: Optional[mp.context.BaseContext] = None):
        self.name = name
        self.store_dir = store_dir
        self.overrides = dict(overrides or {})
        self.datapath = datapath
        ctx = ctx or mp.get_context("spawn")
        self._conn, child = ctx.Pipe()
        self.proc = ctx.Process(
            target=_node_worker,
            args=(child, name, store_dir, self.overrides, datapath),
            daemon=True, name=f"cluster-node-{name}")
        self.proc.start()
        child.close()

    def call(self, cmd: str, timeout: float = 300.0, **args):
        """One command round-trip; raises on worker error or timeout (a
        dead/hung node must fail the driver loudly, not hang it)."""
        self._conn.send((cmd, args))
        if not self._conn.poll(timeout):
            raise TimeoutError(
                f"node {self.name}: no reply to {cmd!r} in {timeout}s")
        status, payload = self._conn.recv()
        if status != "ok":
            raise RuntimeError(f"node {self.name} {cmd!r} failed:\n{payload}")
        return payload

    @property
    def alive(self) -> bool:
        return self.proc.is_alive()

    def kill(self) -> None:
        """Hard-kill (peer-crash drill): no withdraw, the published file
        stays until the peers' leases expire."""
        self.proc.terminate()
        self.proc.join(timeout=10)
        self._conn.close()

    def stop(self, timeout: float = 60.0) -> None:
        try:
            self.call("stop", timeout=timeout)
        except Exception:   # noqa: BLE001 — best-effort stop RPC; terminate() below is the backstop
            pass
        self.proc.join(timeout=timeout)
        if self.proc.is_alive():
            self.proc.terminate()
            self.proc.join(timeout=10)
        self._conn.close()


class ClusterSupervisor:
    """Owns N node processes + the ledger of what each node published, so
    convergence ("every node's remote view equals the union of its peers'
    local entries") is checkable without trusting the thing under test."""

    def __init__(self, store_dir: str, node_names: List[str],
                 overrides: Optional[Dict[str, Dict]] = None,
                 datapath: str = "jit"):
        self.store_dir = store_dir
        self.datapath = datapath
        self.overrides = overrides or {}
        self._ctx = mp.get_context("spawn")
        self.nodes: Dict[str, ClusterNode] = {}
        # node → {prefix: sorted labels}: the supervisor's own truth of
        # what each node publishes (fed by add_endpoint/remove_endpoint)
        self.ledger: Dict[str, Dict[str, Tuple[str, ...]]] = {
            n: {} for n in node_names}
        for n in node_names:
            self.nodes[n] = self._spawn(n)

    def _spawn(self, name: str) -> ClusterNode:
        return ClusterNode(name, self.store_dir,
                           overrides=self.overrides.get(name),
                           datapath=self.datapath, ctx=self._ctx)

    # -- cluster-wide ops ---------------------------------------------------
    def add_endpoint(self, node: str, labels: List[str], ips: List[str],
                     ep_id: Optional[int] = None) -> Dict:
        res = self.nodes[node].call("add_ep", labels=labels, ips=ips,
                                    ep_id=ep_id)
        for ip in ips:
            prefix = f"{ip}/128" if ":" in ip else f"{ip}/32"
            self.ledger[node][prefix] = tuple(sorted(labels))
        return res

    def remove_endpoint(self, node: str, ep_id: int,
                        ips: List[str] = ()) -> Dict:
        res = self.nodes[node].call("remove_ep", ep_id=ep_id)
        for ip in ips:
            prefix = f"{ip}/128" if ":" in ip else f"{ip}/32"
            self.ledger[node].pop(prefix, None)
        return res

    def broadcast(self, cmd: str, timeout: float = 300.0,
                  only: Optional[List[str]] = None, **args) -> Dict:
        out = {}
        for name, node in self.nodes.items():
            if only is not None and name not in only:
                continue
            if not node.alive:
                continue
            out[name] = node.call(cmd, timeout=timeout, **args)
        return out

    def expected_remote(self, node: str,
                        exclude: Tuple[str, ...] = ()) -> Dict:
        """What ``node`` should see once converged: the union of every
        OTHER live node's ledger (conflicting claims excluded — the bench
        asserts those separately with the deterministic-winner rule)."""
        want: Dict[str, Tuple[str, ...]] = {}
        for peer, entries in self.ledger.items():
            if peer == node or peer in exclude:
                continue
            if not self.nodes[peer].alive:
                continue
            want.update(entries)
        return want

    def views(self, only: Optional[List[str]] = None) -> Dict[str, Dict]:
        """node → {prefix: labels tuple} as actually ingested."""
        out = {}
        for name, res in self.broadcast("status", only=only).items():
            out[name] = {p: tuple(v["labels"])
                         for p, v in res["remote_view"].items()}
        return out

    def converge(self, max_rounds: int = 12,
                 exclude: Tuple[str, ...] = ()) -> int:
        """Step every live node until each one's remote view matches the
        supervisor's ledger (or the round budget runs out). Returns the
        number of rounds taken; raises on non-convergence."""
        live = [n for n, node in self.nodes.items()
                if node.alive and n not in exclude]
        for rnd in range(1, max_rounds + 1):
            self.broadcast("step", only=live)
            views = self.views(only=live)
            if all(views[n] == self.expected_remote(n, exclude=exclude)
                   for n in live):
                return rnd
        raise AssertionError(
            f"mesh did not converge in {max_rounds} rounds: "
            f"{ {n: sorted(views[n]) for n in live} } vs expected "
            f"{ {n: sorted(self.expected_remote(n, exclude=exclude)) for n in live} }")

    def restart(self, name: str) -> ClusterNode:
        """Replace a (killed) node with a fresh process under the same
        node name — the peer-restart drill. The ledger keeps the node's
        entries only if the caller re-adds its endpoints."""
        old = self.nodes.get(name)
        if old is not None and old.alive:
            old.kill()
        self.ledger[name] = {}         # fresh process = empty endpoint set
        self.nodes[name] = self._spawn(name)
        return self.nodes[name]

    def stop_all(self) -> None:
        for node in self.nodes.values():
            if node.alive:
                node.stop()
            else:
                try:
                    node._conn.close()
                except Exception:   # noqa: BLE001 — closing a pipe to a dead node; nothing to account
                    pass
