"""Flow log ring (Hubble-lite: SURVEY.md §2 "Minimal analog: flow log with
identity/verdict annotation"). Fixed-capacity host ring buffer of flow
records appended per batch; renderable as JSON lines for the CLI, with an
optional JSONL file sink (the ``hubble export`` analog) that the
``cilium-tpu monitor`` command reads.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Iterator, List, Optional

import numpy as np

from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import addr_to_str, words_to_addr

SINK_ROTATE_BYTES = 64 << 20      # rotate the JSONL sink at 64MB (keep .1)
SINK_BUF_MAX = 65536              # cap pending sink lines (drop-oldest)


class FlowLog:
    def __init__(self, capacity: int = 16384, mode: str = "drops",
                 sink_path: Optional[str] = None):
        self.capacity = capacity
        self.mode = mode
        self.sink_path = sink_path
        self._lock = threading.Lock()
        self._ring: List[Dict] = []
        self._next = 0
        self._seq = 0                  # monotonic record id (live follow)
        self._sink_buf: List[str] = []
        self.sink_dropped = 0          # lines shed when _sink_buf hit its cap
        self.total_seen = 0

    def append_batch(self, batch: Dict[str, np.ndarray],
                     out: Dict[str, np.ndarray], now: int,
                     ep_ids: tuple) -> None:
        if self.mode == "none":
            return
        allow = np.asarray(out["allow"])
        reason = np.asarray(out["reason"])
        status = np.asarray(out["status"])
        rid = np.asarray(out["remote_identity"])
        valid = np.asarray(batch["valid"])
        if self.mode == "drops":
            pick = valid & ~allow
        else:
            pick = valid
        idxs = np.nonzero(pick)[0]
        self.total_seen += int(valid.sum())
        if idxs.size == 0:
            return
        src = np.asarray(batch["src"])
        dst = np.asarray(batch["dst"])
        records = []
        for i in idxs:
            ep_slot = int(batch["ep_slot"][i])
            records.append({
                "time": int(now),
                "verdict": "FORWARDED" if allow[i] else "DROPPED",
                "drop_reason": int(reason[i]),
                "drop_reason_desc": C.DropReason(int(reason[i])).name,
                "ct_state": C.CTStatus(int(status[i])).name,
                "src_ip": addr_to_str(words_to_addr(src[i])),
                "dst_ip": addr_to_str(words_to_addr(dst[i])),
                "src_port": int(batch["sport"][i]),
                "dst_port": int(batch["dport"][i]),
                "proto": C.PROTO_NAMES.get(int(batch["proto"][i]),
                                           str(int(batch["proto"][i]))),
                "direction": C.DIR_NAMES[int(batch["direction"][i])],
                "endpoint_id": ep_ids[ep_slot] if ep_slot < len(ep_ids) else -1,
                "remote_identity": int(rid[i]),
            })
        with self._lock:
            for rec in records:
                self._seq += 1
                rec["seq"] = self._seq
                if len(self._ring) < self.capacity:
                    self._ring.append(rec)
                else:
                    self._ring[self._next] = rec
                self._next = (self._next + 1) % self.capacity
            if self.sink_path is not None:
                self._sink_buf.extend(json.dumps(r) for r in records)
                # Bound host memory if flush_sink isn't running (engine used
                # without controllers, or drop storms outpacing the flush
                # interval): shed oldest, count the shed.
                excess = len(self._sink_buf) - SINK_BUF_MAX
                if excess > 0:
                    del self._sink_buf[:excess]
                    self.sink_dropped += excess

    def flush_sink(self) -> int:
        """Append buffered records to the JSONL sink (called by the
        observability controller; cheap no-op when nothing is pending).
        Rotates to ``<path>.1`` past SINK_ROTATE_BYTES."""
        with self._lock:
            if not self._sink_buf or self.sink_path is None:
                return 0
            lines, self._sink_buf = self._sink_buf, []
        d = os.path.dirname(self.sink_path)
        if d:
            os.makedirs(d, exist_ok=True)
        try:
            if (os.path.exists(self.sink_path)
                    and os.path.getsize(self.sink_path) > SINK_ROTATE_BYTES):
                os.replace(self.sink_path, self.sink_path + ".1")
        except OSError:
            pass
        with open(self.sink_path, "a") as f:
            f.write("\n".join(lines) + "\n")
        return len(lines)

    def tail(self, n: int = 100, **filters) -> List[Dict]:
        """Last ``n`` records, newest last. ``filters`` narrow by exact
        field match (verdict=, endpoint_id=, src_ip=, dst_port=, ...)."""
        with self._lock:
            if len(self._ring) < self.capacity:
                items = self._ring[:]
            else:
                items = self._ring[self._next:] + self._ring[:self._next]
        if filters:
            items = [r for r in items
                     if all(r.get(k) == v for k, v in filters.items())]
        return items[-n:]

    def since(self, seq: int, limit: int = 1000, **filters) -> List[Dict]:
        """Records with seq > ``seq``, oldest first (live-follow cursor; the
        API's /v1/flows?since= and `monitor --api -f` poll this)."""
        with self._lock:
            if len(self._ring) < self.capacity:
                items = self._ring[:]
            else:
                items = self._ring[self._next:] + self._ring[:self._next]
        out = [r for r in items if r.get("seq", 0) > seq
               and all(r.get(k) == v for k, v in filters.items())]
        return out[:limit]

    def to_jsonl(self, n: int = 100) -> str:
        return "\n".join(json.dumps(r) for r in self.tail(n))

    def __len__(self) -> int:
        return len(self._ring)
