"""Flow log ring (Hubble-lite: SURVEY.md §2 "Minimal analog: flow log with
identity/verdict annotation"). Fixed-capacity host ring buffer of flow
records appended per batch; renderable as JSON lines for the CLI.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, Iterator, List

import numpy as np

from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import addr_to_str, words_to_addr


class FlowLog:
    def __init__(self, capacity: int = 16384, mode: str = "drops"):
        self.capacity = capacity
        self.mode = mode
        self._lock = threading.Lock()
        self._ring: List[Dict] = []
        self._next = 0
        self.total_seen = 0

    def append_batch(self, batch: Dict[str, np.ndarray],
                     out: Dict[str, np.ndarray], now: int,
                     ep_ids: tuple) -> None:
        if self.mode == "none":
            return
        allow = np.asarray(out["allow"])
        reason = np.asarray(out["reason"])
        status = np.asarray(out["status"])
        rid = np.asarray(out["remote_identity"])
        valid = np.asarray(batch["valid"])
        if self.mode == "drops":
            pick = valid & ~allow
        else:
            pick = valid
        idxs = np.nonzero(pick)[0]
        self.total_seen += int(valid.sum())
        if idxs.size == 0:
            return
        src = np.asarray(batch["src"])
        dst = np.asarray(batch["dst"])
        records = []
        for i in idxs:
            ep_slot = int(batch["ep_slot"][i])
            records.append({
                "time": int(now),
                "verdict": "FORWARDED" if allow[i] else "DROPPED",
                "drop_reason": int(reason[i]),
                "drop_reason_desc": C.DropReason(int(reason[i])).name,
                "ct_state": C.CTStatus(int(status[i])).name,
                "src_ip": addr_to_str(words_to_addr(src[i])),
                "dst_ip": addr_to_str(words_to_addr(dst[i])),
                "src_port": int(batch["sport"][i]),
                "dst_port": int(batch["dport"][i]),
                "proto": C.PROTO_NAMES.get(int(batch["proto"][i]),
                                           str(int(batch["proto"][i]))),
                "direction": C.DIR_NAMES[int(batch["direction"][i])],
                "endpoint_id": ep_ids[ep_slot] if ep_slot < len(ep_ids) else -1,
                "remote_identity": int(rid[i]),
            })
        with self._lock:
            for rec in records:
                if len(self._ring) < self.capacity:
                    self._ring.append(rec)
                else:
                    self._ring[self._next] = rec
                self._next = (self._next + 1) % self.capacity

    def tail(self, n: int = 100) -> List[Dict]:
        with self._lock:
            if len(self._ring) < self.capacity:
                items = self._ring[:]
            else:
                items = self._ring[self._next:] + self._ring[:self._next]
        return items[-n:]

    def to_jsonl(self, n: int = 100) -> str:
        return "\n".join(json.dumps(r) for r in self.tail(n))

    def __len__(self) -> int:
        return len(self._ring)
