"""Flow log ring (Hubble-lite: SURVEY.md §2 "Minimal analog: flow log with
identity/verdict annotation"). Fixed-capacity host ring buffer of flow
records appended per batch; renderable as JSON lines for the CLI, with an
optional JSONL file sink (the ``hubble export`` analog) that the
``cilium-tpu monitor`` command reads.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Iterator, List, Optional

import numpy as np

from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import addr_to_str, words_to_addr

SINK_ROTATE_BYTES = 64 << 20      # rotate the JSONL sink at 64MB (keep .1)
SINK_BUF_MAX = 65536              # cap pending sink lines (drop-oldest)
APPEND_BATCH_MAX = 4096           # records extracted per batch (keep newest)

# enum-name lookup tables: building an IntEnum per record is measurable in
# a drop storm; these are the hot-path equivalents of DropReason(x).name
_REASON_NAMES = {int(r): r.name for r in C.DropReason}
_STATUS_NAMES = {int(s): s.name for s in C.CTStatus}


class FlowLog:
    def __init__(self, capacity: int = 16384, mode: str = "drops",
                 sink_path: Optional[str] = None):
        self.capacity = capacity
        self.mode = mode
        self.sink_path = sink_path
        self._lock = threading.Lock()
        self._ring: List[Dict] = []
        self._next = 0
        self._seq = 0                  # monotonic record id (live follow)
        self._sink_buf: List[str] = []
        self.sink_dropped = 0          # lines shed when _sink_buf hit its cap
        self.extract_shed = 0          # records past APPEND_BATCH_MAX per batch
        self.total_seen = 0

    def append_batch(self, batch: Dict[str, np.ndarray],
                     out: Dict[str, np.ndarray], now: int,
                     ep_ids: tuple) -> None:
        if self.mode == "none":
            return
        allow = np.asarray(out["allow"])
        reason = np.asarray(out["reason"])
        status = np.asarray(out["status"])
        rid = np.asarray(out["remote_identity"])
        valid = np.asarray(batch["valid"])
        if self.mode == "drops":
            pick = valid & ~allow
        else:
            pick = valid
        idxs = np.nonzero(pick)[0]
        self.total_seen += int(valid.sum())
        if idxs.size == 0:
            return
        if idxs.size > APPEND_BATCH_MAX:
            # a drop storm can select a whole 64k batch; extracting dicts
            # for all of it would dominate the pipelined hot path. Keep the
            # newest rows (the ring is drop-oldest anyway) and account.
            self.extract_shed += int(idxs.size) - APPEND_BATCH_MAX
            idxs = idxs[-APPEND_BATCH_MAX:]
        # hot fields pulled column-wise in one vectorized gather each —
        # per-element numpy scalar indexing was the dominant cost here
        allow_l = allow[idxs].tolist()
        reason_l = reason[idxs].tolist()
        status_l = status[idxs].tolist()
        rid_l = rid[idxs].tolist()
        sport_l = np.asarray(batch["sport"])[idxs].tolist()
        dport_l = np.asarray(batch["dport"])[idxs].tolist()
        proto_l = np.asarray(batch["proto"])[idxs].tolist()
        dir_l = np.asarray(batch["direction"])[idxs].tolist()
        slot_l = np.asarray(batch["ep_slot"])[idxs].tolist()
        src_rows = np.asarray(batch["src"])[idxs]
        dst_rows = np.asarray(batch["dst"])[idxs]
        now = int(now)
        n_eps = len(ep_ids)
        records = []
        for j in range(len(allow_l)):
            ep_slot = slot_l[j]
            r, s = reason_l[j], status_l[j]
            records.append({
                "time": now,
                "verdict": "FORWARDED" if allow_l[j] else "DROPPED",
                "drop_reason": r,
                "drop_reason_desc": _REASON_NAMES.get(r, str(r)),
                "ct_state": _STATUS_NAMES.get(s, str(s)),
                "src_ip": addr_to_str(words_to_addr(src_rows[j])),
                "dst_ip": addr_to_str(words_to_addr(dst_rows[j])),
                "src_port": sport_l[j],
                "dst_port": dport_l[j],
                "proto": C.PROTO_NAMES.get(proto_l[j], str(proto_l[j])),
                "direction": C.DIR_NAMES[dir_l[j]],
                "endpoint_id": ep_ids[ep_slot] if ep_slot < n_eps else -1,
                "remote_identity": rid_l[j],
            })
        with self._lock:
            for rec in records:
                self._seq += 1
                rec["seq"] = self._seq
                if len(self._ring) < self.capacity:
                    self._ring.append(rec)
                else:
                    self._ring[self._next] = rec
                self._next = (self._next + 1) % self.capacity
            if self.sink_path is not None:
                self._sink_buf.extend(json.dumps(r) for r in records)
                # Bound host memory if flush_sink isn't running (engine used
                # without controllers, or drop storms outpacing the flush
                # interval): shed oldest, count the shed.
                excess = len(self._sink_buf) - SINK_BUF_MAX
                if excess > 0:
                    del self._sink_buf[:excess]
                    self.sink_dropped += excess

    def flush_sink(self) -> int:
        """Append buffered records to the JSONL sink (called by the
        observability controller; cheap no-op when nothing is pending).
        Rotates to ``<path>.1`` past SINK_ROTATE_BYTES."""
        with self._lock:
            if not self._sink_buf or self.sink_path is None:
                return 0
            lines, self._sink_buf = self._sink_buf, []
        d = os.path.dirname(self.sink_path)
        if d:
            os.makedirs(d, exist_ok=True)
        try:
            if (os.path.exists(self.sink_path)
                    and os.path.getsize(self.sink_path) > SINK_ROTATE_BYTES):
                os.replace(self.sink_path, self.sink_path + ".1")
        except OSError:
            pass
        with open(self.sink_path, "a") as f:
            f.write("\n".join(lines) + "\n")
        return len(lines)

    def tail(self, n: int = 100, **filters) -> List[Dict]:
        """Last ``n`` records, newest last. ``filters`` narrow by exact
        field match (verdict=, endpoint_id=, src_ip=, dst_port=, ...)."""
        with self._lock:
            if len(self._ring) < self.capacity:
                items = self._ring[:]
            else:
                items = self._ring[self._next:] + self._ring[:self._next]
        if filters:
            items = [r for r in items
                     if all(r.get(k) == v for k, v in filters.items())]
        return items[-n:]

    def since(self, seq: int, limit: int = 1000, **filters) -> List[Dict]:
        """Records with seq > ``seq``, oldest first (live-follow cursor; the
        API's /v1/flows?since= and `monitor --api -f` poll this)."""
        with self._lock:
            if len(self._ring) < self.capacity:
                items = self._ring[:]
            else:
                items = self._ring[self._next:] + self._ring[:self._next]
        out = [r for r in items if r.get("seq", 0) > seq
               and all(r.get(k) == v for k, v in filters.items())]
        return out[:limit]

    def to_jsonl(self, n: int = 100) -> str:
        return "\n".join(json.dumps(r) for r in self.tail(n))

    def __len__(self) -> int:
        return len(self._ring)
