"""Flow log ring (Hubble-lite: SURVEY.md §2 "Minimal analog: flow log with
identity/verdict annotation").

Columnar since ISSUE 11: the ring is a struct-of-arrays over fixed-capacity
numpy columns — one vectorized slice write per appended batch instead of a
Python dict per record, and the provenance columns the classify interior now
emits (``matched_rule``, ``lpm_prefix``, ``ct_state_pre``) ride along at
int32 cost. Dict records are *rendered on demand* (tail/since/sink/observer
hits), so the hot append path never materializes them; the vectorized
observer (observe/observer.py) filters straight on the column arrays with
numpy masks and renders only matching rows.

Follow-mode loss is explicit: ``since()`` prepends a structured
``{"gap": True, "dropped": N}`` marker (and counts
``flowlog_follow_gaps_total``) whenever the cursor predates the oldest
retained record — a follower can never silently skip a wraparound.

Renderable as JSON lines for the CLI, with an optional JSONL file sink (the
``hubble export`` analog) that the ``cilium-tpu monitor`` command reads.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from cilium_tpu.utils import constants as C
from cilium_tpu.utils.ip import addr_to_str, parse_addr, words_to_addr

SINK_ROTATE_BYTES = 64 << 20      # rotate the JSONL sink at 64MB (keep .1)
SINK_BUF_MAX = 65536              # cap pending sink lines (drop-oldest)
APPEND_BATCH_MAX = 4096           # records extracted per batch (keep newest)

# enum-name lookup tables: building an IntEnum per record is measurable in
# a drop storm; these are the hot-path equivalents of DropReason(x).name
_REASON_NAMES = {int(r): r.name for r in C.DropReason}
_STATUS_NAMES = {int(s): s.name for s in C.CTStatus}
_NAME_TO_STATUS = {v: k for k, v in _STATUS_NAMES.items()}
_NAME_TO_PROTO = {v: k for k, v in C.PROTO_NAMES.items()}
_NAME_TO_DIR = {v: k for k, v in C.DIR_NAMES.items()}
#: rendered field → raw int column for the exact-match read filters
_INT_FILTER_COLS = {"drop_reason": "reason", "src_port": "sport",
                    "dst_port": "dport", "endpoint_id": "endpoint_id",
                    "remote_identity": "remote_identity",
                    "matched_rule": "matched_rule",
                    "lpm_prefix": "lpm_prefix", "seq": "seq", "time": "time"}


def _filter_mask(cols: Dict[str, np.ndarray], filters: Dict
                 ) -> Tuple[np.ndarray, Dict]:
    """Vectorized pre-filter for the ``tail()``/``since()`` exact-match
    surface: maps rendered-field filters (verdict=, dst_port=, src_ip=,
    ...) onto raw-column comparisons so only matching rows are rendered
    to dicts — not the whole retained ring per query. Returns ``(mask,
    residual)``; residual keys (unknown fields, off-domain values) still
    dict-match post-render, preserving the original semantics exactly."""
    m = np.ones(int(cols["seq"].shape[0]), dtype=bool)
    residual: Dict = {}
    for k, v in filters.items():
        if k == "verdict" and v in ("FORWARDED", "DROPPED"):
            m = m & (cols["allow"] == (v == "FORWARDED"))
        elif k in _INT_FILTER_COLS and isinstance(v, int) \
                and not isinstance(v, bool):
            m = m & (cols[_INT_FILTER_COLS[k]] == v)
        elif k in ("ct_state", "ct_state_pre") and v in _NAME_TO_STATUS:
            col = "status" if k == "ct_state" else "ct_state_pre"
            m = m & (cols[col] == _NAME_TO_STATUS[v])
        elif k == "proto" and v in _NAME_TO_PROTO:
            m = m & (cols["proto"] == _NAME_TO_PROTO[v])
        elif k == "direction" and v in _NAME_TO_DIR:
            m = m & (cols["direction"] == _NAME_TO_DIR[v])
        elif k in ("src_ip", "dst_ip"):
            try:
                a16, _fam = parse_addr(str(v))
            except (ValueError, OSError):
                residual[k] = v          # unparseable → matches nothing,
                continue                 # same as the rendered compare
            w = np.frombuffer(a16, dtype=">u4").astype(np.uint32)
            m = m & (cols[k[:3]] == w).all(axis=1)
        else:
            residual[k] = v
    return m, residual

#: scalar int32 ring columns filled straight from batch/out arrays.
#: Physically they share ONE [capacity, len] int32 matrix (with the int64
#: pair seq/time, the 8-word src+dst block and the allow bools as three
#: more): a ring append or a snapshot window is then 4 contiguous slice
#: ops instead of 18 per-column ones — the difference between a follow
#: poll costing ~10us and ~30us. Readers still see a per-name dict; the
#: values are column VIEWS into the copied blocks.
_I32_COLS = ("reason", "status", "matched_rule", "lpm_prefix",
             "remote_identity", "sport", "dport", "proto", "direction",
             "endpoint_id", "ct_state_pre")
_I32_AT = {name: j for j, name in enumerate(_I32_COLS)}


def render_flow(cols: Dict[str, np.ndarray], j: int) -> Dict:
    """One ring row → the wire-format record dict (the shape the API/CLI
    and the JSONL sink have always used, plus the provenance fields)."""
    r = int(cols["reason"][j])
    s = int(cols["status"][j])
    return {
        "time": int(cols["time"][j]),
        "verdict": "FORWARDED" if cols["allow"][j] else "DROPPED",
        "drop_reason": r,
        "drop_reason_desc": _REASON_NAMES.get(r, str(r)),
        "ct_state": _STATUS_NAMES.get(s, str(s)),
        "src_ip": addr_to_str(words_to_addr(cols["src"][j])),
        "dst_ip": addr_to_str(words_to_addr(cols["dst"][j])),
        "src_port": int(cols["sport"][j]),
        "dst_port": int(cols["dport"][j]),
        "proto": C.PROTO_NAMES.get(int(cols["proto"][j]),
                                   str(int(cols["proto"][j]))),
        "direction": C.DIR_NAMES[int(cols["direction"][j])],
        "endpoint_id": int(cols["endpoint_id"][j]),
        "remote_identity": int(cols["remote_identity"][j]),
        # match provenance (ISSUE 11): the evidence behind the verdict —
        # resolved policy-cell coordinate, packed winning-prefix slot/len,
        # CT probe class as-of classification
        "matched_rule": int(cols["matched_rule"][j]),
        "lpm_prefix": int(cols["lpm_prefix"][j]),
        "ct_state_pre": _STATUS_NAMES.get(
            int(cols["ct_state_pre"][j]), str(int(cols["ct_state_pre"][j]))),
        "seq": int(cols["seq"][j]),
    }


class FlowLog:
    def __init__(self, capacity: int = 16384, mode: str = "drops",
                 sink_path: Optional[str] = None, metrics=None):
        self.capacity = capacity
        self.mode = mode
        self.sink_path = sink_path
        self.metrics = metrics         # optional runtime/metrics.Metrics
        self._lock = threading.Lock()
        # the four packed ring blocks (see _I32_COLS note above)
        self._i32 = np.zeros((capacity, len(_I32_COLS)), dtype=np.int32)
        self._i64 = np.zeros((capacity, 2), dtype=np.int64)   # seq, time
        self._addr = np.zeros((capacity, 8), dtype=np.uint32)  # src+dst
        self._allow = np.zeros(capacity, dtype=bool)
        # shared zero-row view per column: the idle-poll fast path (a
        # follow cadence mostly finds nothing new; fresh empty copies per
        # tick would dominate an idle follower's cost)
        self._empty_cols = self._as_cols(self._i32[:0], self._i64[:0],
                                         self._addr[:0], self._allow[:0])
        self._next = 0                 # slot the next record lands in
        self._count = 0                # live records (<= capacity)
        self._seq = 0                  # monotonic record id (live follow)
        self._sink_buf: List[str] = []
        self.sink_dropped = 0          # lines shed when _sink_buf hit its cap
        self.extract_shed = 0          # records past APPEND_BATCH_MAX per batch
        self.follow_gaps = 0           # since() cursors that crossed a wrap
        self.follow_gap_records = 0    # records those cursors lost
        self.total_seen = 0

    @staticmethod
    def _as_cols(i32: np.ndarray, i64: np.ndarray, addr: np.ndarray,
                 allow: np.ndarray) -> Dict[str, np.ndarray]:
        """Per-name column views over the packed blocks (the read/render
        schema every consumer sees)."""
        cols = {"seq": i64[:, 0], "time": i64[:, 1], "allow": allow,
                "src": addr[:, :4], "dst": addr[:, 4:]}
        for name, j in _I32_AT.items():
            cols[name] = i32[:, j]
        return cols

    def append_batch(self, batch: Dict[str, np.ndarray],
                     out: Dict[str, np.ndarray], now: int,
                     ep_ids: tuple) -> None:
        if self.mode == "none":
            return
        allow = np.asarray(out["allow"])
        valid = np.asarray(batch["valid"])
        if self.mode == "drops":
            pick = valid & ~allow
        else:
            pick = valid
        idxs = np.nonzero(pick)[0]
        self.total_seen += int(valid.sum())
        if idxs.size == 0:
            return
        if idxs.size > APPEND_BATCH_MAX:
            # a drop storm can select a whole 64k batch; extracting columns
            # for all of it would dominate the pipelined hot path. Keep the
            # newest rows (the ring is drop-oldest anyway) and account.
            self.extract_shed += int(idxs.size) - APPEND_BATCH_MAX
            idxs = idxs[-APPEND_BATCH_MAX:]
        k = idxs.size
        # columnar extraction: one vectorized gather per column, no
        # per-record Python. Unknown endpoint slots map to -1 exactly like
        # the old per-record rendering did.
        ep_lut = np.fromiter(ep_ids, dtype=np.int64,
                             count=len(ep_ids)) if ep_ids else \
            np.zeros(0, dtype=np.int64)
        slots = np.asarray(batch["ep_slot"])[idxs].astype(np.int64)
        known = (slots >= 0) & (slots < ep_lut.shape[0])
        ep_col = np.where(known, ep_lut[np.clip(slots, 0,
                                                max(0, ep_lut.shape[0] - 1))]
                          if ep_lut.size else -1, -1)
        at = _I32_AT
        st_i32 = np.empty((k, len(_I32_COLS)), dtype=np.int32)
        st_i32[:, at["reason"]] = np.asarray(out["reason"])[idxs]
        status = np.asarray(out["status"])[idxs]
        st_i32[:, at["status"]] = status
        st_i32[:, at["matched_rule"]] = np.asarray(
            out["matched_rule"])[idxs] if "matched_rule" in out else -1
        st_i32[:, at["lpm_prefix"]] = np.asarray(
            out["lpm_prefix"])[idxs] if "lpm_prefix" in out else -1
        st_i32[:, at["ct_state_pre"]] = np.asarray(
            out["ct_state_pre"])[idxs] if "ct_state_pre" in out else status
        st_i32[:, at["remote_identity"]] = \
            np.asarray(out["remote_identity"])[idxs]
        st_i32[:, at["sport"]] = np.asarray(batch["sport"])[idxs]
        st_i32[:, at["dport"]] = np.asarray(batch["dport"])[idxs]
        st_i32[:, at["proto"]] = np.asarray(batch["proto"])[idxs]
        st_i32[:, at["direction"]] = np.asarray(batch["direction"])[idxs]
        st_i32[:, at["endpoint_id"]] = ep_col
        st_i64 = np.empty((k, 2), dtype=np.int64)
        st_i64[:, 1] = int(now)
        st_addr = np.empty((k, 8), dtype=np.uint32)
        st_addr[:, :4] = np.asarray(batch["src"])[idxs]
        st_addr[:, 4:] = np.asarray(batch["dst"])[idxs]
        st_allow = allow[idxs]
        cap = self.capacity
        with self._lock:
            seq0 = self._seq + 1
            self._seq += k
            st_i64[:, 0] = np.arange(seq0, seq0 + k, dtype=np.int64)
            if k > cap:
                # one batch larger than the whole ring: only the newest
                # ``cap`` records survive the wrap anyway — trim the head
                # (their seqs are consumed, so followers see the loss as a
                # gap) and keep the two-slice write below correct
                st_i32, st_i64 = st_i32[k - cap:], st_i64[k - cap:]
                st_addr, st_allow = st_addr[k - cap:], st_allow[k - cap:]
                k = cap
            pos = self._next
            # wraparound = at most two contiguous slice writes per block
            first = min(k, cap - pos)
            for ring, st in ((self._i32, st_i32), (self._i64, st_i64),
                             (self._addr, st_addr), (self._allow, st_allow)):
                ring[pos:pos + first] = st[:first]
                if first < k:
                    ring[: k - first] = st[first:]
            self._next = (pos + k) % cap
            self._count = min(cap, self._count + k)
        if self.sink_path is not None:
            # render/dumps OUTSIDE the ring lock: the staged columns are
            # call-local and seq-stamped, and a 4096-record drop storm's
            # string formatting must not block every snapshot_columns
            # reader (observer polls, /v1/flows, relay) for its duration
            staged = self._as_cols(st_i32, st_i64, st_addr, st_allow)
            lines = [json.dumps(render_flow(staged, j)) for j in range(k)]
            with self._lock:
                self._sink_buf.extend(lines)
                # Bound host memory if flush_sink isn't running (engine used
                # without controllers, or drop storms outpacing the flush
                # interval): shed oldest, count the shed.
                excess = len(self._sink_buf) - SINK_BUF_MAX
                if excess > 0:
                    del self._sink_buf[:excess]
                    self.sink_dropped += excess

    # -- columnar access (observe/observer.py) --------------------------------
    def snapshot_columns(self, since_seq: int = 0
                         ) -> Tuple[Dict[str, np.ndarray], int, int]:
        """Consistent copy of the retained columns oldest→newest, restricted
        to records with seq > ``since_seq``. Returns (cols, oldest_seq,
        newest_seq) where oldest_seq is the first seq still retained (0 when
        empty) — the caller derives gap accounting from it. This is the
        vectorized read surface: one concatenate per column, no dicts."""
        with self._lock:
            n = self._count
            if n == 0:
                return (self._empty_cols, 0, self._seq)
            start = (self._next - n) % self.capacity
            oldest = self._seq - n + 1
            skip = 0
            if since_seq >= oldest:
                skip = min(n, int(since_seq - oldest + 1))
            take = n - skip
            if take <= 0:
                return (self._empty_cols, oldest, self._seq)
            s = (start + skip) % self.capacity
            first = min(take, self.capacity - s)

            def cut(ring):
                if first < take:
                    return np.concatenate(
                        [ring[s:s + first], ring[: take - first]])
                return ring[s:s + first].copy()
            return (self._as_cols(cut(self._i32), cut(self._i64),
                                  cut(self._addr), cut(self._allow)),
                    oldest, self._seq)

    def _note_gap(self, dropped: int) -> None:
        self.follow_gaps += 1
        self.follow_gap_records += dropped
        if self.metrics is not None:
            self.metrics.inc_counter("flowlog_follow_gaps_total")
            self.metrics.inc_counter("flowlog_follow_gap_records_total",
                                     dropped)

    def gap_marker(self, since: int, oldest: int) -> Optional[Dict]:
        """The ONE definition of the follow-gap contract, shared by
        ``since()`` and the observer: a cursor that predates the oldest
        retained record lost ``oldest - since - 1`` records to ring
        wraparound → a structured ``{"gap": True, "dropped": N,
        "resume_seq": S}`` marker (counted via ``_note_gap``). ``since ==
        0`` is a FRESH attach ("give me what's retained"), not an
        established cursor — pre-history it never consumed is not loss —
        so it never gaps. Returns None when nothing was missed."""
        if since > 0 and oldest and since + 1 < oldest:
            dropped = int(oldest - since - 1)
            self._note_gap(dropped)
            return {"gap": True, "dropped": dropped,
                    "resume_seq": int(oldest)}
        return None

    # -- dict-rendering read surface (API/CLI compat) -------------------------
    def tail(self, n: int = 100, **filters) -> List[Dict]:
        """Last ``n`` records, newest last. ``filters`` narrow by exact
        field match (verdict=, endpoint_id=, src_ip=, dst_port=, ...)."""
        cols, _oldest, _newest = self.snapshot_columns()
        total = cols["seq"].shape[0]
        if not filters:
            lo = max(0, total - n)
            return [render_flow(cols, j) for j in range(lo, total)]
        m, residual = _filter_mask(cols, filters)
        idx = np.nonzero(m)[0]
        if not residual:
            idx = idx[-n:]               # render only what we return
        items = []
        for j in idx:
            r = render_flow(cols, int(j))
            if all(r.get(k) == v for k, v in residual.items()):
                items.append(r)
        return items[-n:]

    def since(self, seq: int, limit: int = 1000, **filters) -> List[Dict]:
        """Records with seq > ``seq``, oldest first (live-follow cursor; the
        API's /v1/flows?since= and `monitor --api -f` poll this).

        When the cursor predates the oldest retained record — the ring
        wrapped past the follower — the result is PREFIXED with a
        structured gap marker ``{"gap": True, "dropped": N,
        "resume_seq": S}`` (and ``flowlog_follow_gaps_total`` /
        ``flowlog_follow_gap_records_total`` count it), so loss is an
        explicit record in the stream, never an inference left to seq
        arithmetic."""
        cols, oldest, _newest = self.snapshot_columns(since_seq=seq)
        out: List[Dict] = []
        gap = self.gap_marker(seq, oldest)
        if gap is not None:
            out.append(gap)
        m, residual = _filter_mask(cols, filters)
        n_rec = 0
        for j in np.nonzero(m)[0]:
            r = render_flow(cols, int(j))
            if residual and not all(r.get(k) == v
                                    for k, v in residual.items()):
                continue
            out.append(r)
            n_rec += 1
            if n_rec >= limit:
                break
        return out

    def flush_sink(self) -> int:
        """Append buffered records to the JSONL sink (called by the
        observability controller; cheap no-op when nothing is pending).
        Rotates to ``<path>.1`` past SINK_ROTATE_BYTES."""
        with self._lock:
            if not self._sink_buf or self.sink_path is None:
                return 0
            lines, self._sink_buf = self._sink_buf, []
        d = os.path.dirname(self.sink_path)
        if d:
            os.makedirs(d, exist_ok=True)
        try:
            if (os.path.exists(self.sink_path)
                    and os.path.getsize(self.sink_path) > SINK_ROTATE_BYTES):
                os.replace(self.sink_path, self.sink_path + ".1")
        except OSError:
            pass
        with open(self.sink_path, "a") as f:
            f.write("\n".join(lines) + "\n")
        return len(lines)

    def to_jsonl(self, n: int = 100) -> str:
        return "\n".join(json.dumps(r) for r in self.tail(n))

    @property
    def newest_seq(self) -> int:
        with self._lock:
            return self._seq

    def __len__(self) -> int:
        return self._count
