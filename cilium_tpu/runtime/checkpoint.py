"""Checkpoint/resume (SURVEY.md §5: the analog of upstream's endpoint state
dir + pinned BPF maps — "connection survival across upgrades is a headline
feature").

A checkpoint is a directory:
  state.json   — config echo, revision, identity allocator state, ipcache
                 entries, endpoints, rule documents, services
  ct.npz       — the conntrack arrays (the pinned-ctmap analog: flows
                 survive an agent restart)

Resume rebuilds the engine's host state (identity numbering stable via the
allocator export), re-materializes rules, recompiles the snapshot, and
re-places the CT table — device arrays are a cache of host truth.
"""

from __future__ import annotations

import json
import os
import tempfile
from dataclasses import dataclass
from typing import TYPE_CHECKING, Callable, Dict, Optional

import numpy as np

from cilium_tpu.model.services import Backend, Frontend, Service

if TYPE_CHECKING:  # Engine pulls in jax; load_host() must stay jax-free
    from cilium_tpu.runtime.engine import Engine

STATE_FILE = "state.json"
CT_FILE = "ct.npz"
FORMAT_VERSION = 1


def save(engine: Engine, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    state = {
        "format_version": FORMAT_VERSION,
        "revision": engine.repo.revision,
        # verdict-relevant config: the CLI's trace/status must evaluate with
        # the agent's actual enforcement semantics, not defaults
        "config": {
            "enforcement_mode": engine.ctx.enforcement_mode,
            "allow_localhost": engine.ctx.allow_localhost,
        },
        "identity_state": engine.ctx.allocator.export_state(),
        "ipcache": engine.ctx.ipcache.snapshot(),
        "endpoints": [
            {"ep_id": ep.ep_id, "labels": list(ep.labels.to_strings()),
             "ips": list(ep.ips), "enforcement": ep.enforcement}
            for ep in sorted(engine.endpoints.values(), key=lambda e: e.ep_id)
        ],
        "rules": [r.raw for r in engine.repo.all_rules() if r.raw is not None],
        "services": [
            {"name": s.name, "namespace": s.namespace,
             "backends": list(s.backends),
             "frontends": [{"addr": f.addr, "port": f.port,
                            "proto": f.proto, "kind": f.kind}
                           for f in s.frontends],
             "lb_backends": [{"addr": b.addr, "port": b.port,
                              "weight": b.weight} for b in s.lb_backends]}
            for s in engine.ctx.services.all()
        ],
        # stable rev-NAT ids must survive restarts: restored CT entries
        # reference them
        "rnat_state": engine.ctx.services.export_rnat_state(),
        # DNS cache persists so toFQDNs identities survive a restart
        # (upstream: fqdn cache persistence)
        "dns_cache": engine.ctx.fqdn_cache.export_state(),
    }
    # write-then-rename so a crash never leaves a torn checkpoint
    fd, tmp = tempfile.mkstemp(dir=path, prefix=".state-")
    with os.fdopen(fd, "w") as f:
        json.dump(state, f)
    os.replace(tmp, os.path.join(path, STATE_FILE))

    ct = engine.ct_arrays()
    fd, tmp = tempfile.mkstemp(dir=path, prefix=".ct-", suffix=".npz")
    with os.fdopen(fd, "wb") as f:
        np.savez_compressed(f, **ct)
    os.replace(tmp, os.path.join(path, CT_FILE))


def _read_state(path: str) -> Dict:
    with open(os.path.join(path, STATE_FILE)) as f:
        state = json.load(f)
    if state.get("format_version") != FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint version "
                         f"{state.get('format_version')}")
    return state


def _rebuild_control_plane(state: Dict, ctx, repo,
                           add_endpoint: Callable,
                           apply_rules: Callable) -> None:
    """The single definition of "state dict → control-plane state", shared by
    engine restore and the CLI's host-only load so the two can never diverge.

    ``add_endpoint(ep_doc)`` and ``apply_rules(rule_docs)`` abstract the only
    difference between the two callers (engine methods vs plain objects).
    """
    cfg = state.get("config", {})
    if "enforcement_mode" in cfg:
        ctx.enforcement_mode = cfg["enforcement_mode"]
    if "allow_localhost" in cfg:
        ctx.allow_localhost = cfg["allow_localhost"]
    # identity numbering must be restored FIRST so that endpoint/CIDR
    # allocation below resolves to the same ids (idempotent via label lookup)
    ctx.allocator.restore_state(state["identity_state"])
    if "rnat_state" in state:
        ctx.services.restore_rnat_state(state["rnat_state"])
    if "dns_cache" in state:
        # before rules: toFQDNs materialization reads the cache
        ctx.fqdn_cache.restore_state(state["dns_cache"])
    for svc in state.get("services", []):
        # validate=False: restore must accept whatever the saving engine
        # accepted (incl. pre-validation checkpoints with conflicting
        # frontends) — a conflict then surfaces at the next regenerate.
        ctx.services.upsert(Service(
            name=svc["name"], namespace=svc["namespace"],
            backends=tuple(svc["backends"]),
            frontends=tuple(Frontend(**f)
                            for f in svc.get("frontends", [])),
            lb_backends=tuple(Backend(**b)
                              for b in svc.get("lb_backends", []))),
            validate=False)
    for ep in state["endpoints"]:
        add_endpoint(ep)
    if state["rules"]:
        apply_rules(state["rules"])
    # ipcache entries not re-derivable (e.g. manual upserts) are replayed
    current = ctx.ipcache.snapshot()
    for prefix, ident in state["ipcache"].items():
        if prefix not in current:
            ctx.ipcache.upsert(prefix, ident)


def _read_ct(path: str) -> Optional[Dict[str, np.ndarray]]:
    ct_path = os.path.join(path, CT_FILE)
    if not os.path.exists(ct_path):
        return None
    with np.load(ct_path) as npz:
        return {k: npz[k] for k in npz.files}


def restore(engine: Engine, path: str) -> None:
    """Restore host + CT state into a FRESH engine (no endpoints/rules yet)."""
    state = _read_state(path)
    if engine.endpoints or len(engine.repo):
        raise ValueError("restore requires a fresh engine")
    _rebuild_control_plane(
        state, engine.ctx, engine.repo,
        add_endpoint=lambda ep: engine.add_endpoint(
            ep["labels"], ep["ips"], ep_id=ep["ep_id"],
            enforcement=ep.get("enforcement")),
        apply_rules=engine.apply_policy)
    ct = _read_ct(path)
    if ct is not None:
        engine.load_ct_arrays(ct)
    engine.regenerate(force=True)


# --------------------------------------------------------------------------- #
# Host-only load (CLI inspection/trace): reconstructs the control-plane state
# with NO jax import and NO device placement — the analog of cilium-dbg
# reading agent state without touching the datapath.
# --------------------------------------------------------------------------- #
@dataclass
class HostState:
    ctx: "PolicyContext"
    repo: "Repository"
    endpoints: Dict[int, "Endpoint"]
    revision: int
    ct: Optional[Dict[str, np.ndarray]]     # raw CT arrays (None if absent)
    raw: Dict                               # the state.json document


def load_host(path: str) -> HostState:
    from cilium_tpu.model.endpoint import Endpoint
    from cilium_tpu.model.labels import Labels
    from cilium_tpu.model.identity import IdentityAllocator
    from cilium_tpu.model.ipcache import IPCache
    from cilium_tpu.model.rules import parse_rules
    from cilium_tpu.model.services import ServiceRegistry
    from cilium_tpu.policy.repository import PolicyContext, Repository
    from cilium_tpu.policy.selectorcache import SelectorCache

    state = _read_state(path)
    alloc = IdentityAllocator()
    ctx = PolicyContext(allocator=alloc,
                        selector_cache=SelectorCache(alloc),
                        ipcache=IPCache(), services=ServiceRegistry())
    repo = Repository(ctx)
    endpoints: Dict[int, Endpoint] = {}

    def add_endpoint(ep):
        lbls = Labels.parse(ep["labels"])
        ident = alloc.allocate(lbls)
        endpoints[ep["ep_id"]] = Endpoint(
            ep_id=ep["ep_id"], labels=lbls, ips=tuple(ep["ips"]),
            identity_id=ident.id, enforcement=ep.get("enforcement"))
        for ip in ep["ips"]:
            prefix = f"{ip}/128" if ":" in ip else f"{ip}/32"
            ctx.ipcache.upsert(prefix, ident.id)

    _rebuild_control_plane(state, ctx, repo, add_endpoint=add_endpoint,
                           apply_rules=lambda docs: repo.add(parse_rules(docs)))
    return HostState(ctx=ctx, repo=repo, endpoints=endpoints,
                     revision=state["revision"], ct=_read_ct(path), raw=state)
