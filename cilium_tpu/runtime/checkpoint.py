"""Checkpoint/resume (SURVEY.md §5: the analog of upstream's endpoint state
dir + pinned BPF maps — "connection survival across upgrades is a headline
feature").

A checkpoint is a directory:
  state.json   — config echo, revision, identity allocator state, ipcache
                 entries, endpoints, rule documents, services
  ct.npz       — the conntrack arrays (the pinned-ctmap analog: flows
                 survive an agent restart)

Resume rebuilds the engine's host state (identity numbering stable via the
allocator export), re-materializes rules, recompiles the snapshot, and
re-places the CT table — device arrays are a cache of host truth.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Dict, Optional

import numpy as np

from cilium_tpu.model.services import Backend, Frontend, Service
from cilium_tpu.runtime.engine import Engine

STATE_FILE = "state.json"
CT_FILE = "ct.npz"
FORMAT_VERSION = 1


def save(engine: Engine, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    state = {
        "format_version": FORMAT_VERSION,
        "revision": engine.repo.revision,
        "identity_state": engine.ctx.allocator.export_state(),
        "ipcache": engine.ctx.ipcache.snapshot(),
        "endpoints": [
            {"ep_id": ep.ep_id, "labels": list(ep.labels.to_strings()),
             "ips": list(ep.ips), "enforcement": ep.enforcement}
            for ep in sorted(engine.endpoints.values(), key=lambda e: e.ep_id)
        ],
        "rules": [r.raw for r in engine.repo.all_rules() if r.raw is not None],
        "services": [
            {"name": s.name, "namespace": s.namespace,
             "backends": list(s.backends),
             "frontends": [{"addr": f.addr, "port": f.port,
                            "proto": f.proto, "kind": f.kind}
                           for f in s.frontends],
             "lb_backends": [{"addr": b.addr, "port": b.port,
                              "weight": b.weight} for b in s.lb_backends]}
            for s in engine.ctx.services.all()
        ],
        # stable rev-NAT ids must survive restarts: restored CT entries
        # reference them
        "rnat_state": engine.ctx.services.export_rnat_state(),
    }
    # write-then-rename so a crash never leaves a torn checkpoint
    fd, tmp = tempfile.mkstemp(dir=path, prefix=".state-")
    with os.fdopen(fd, "w") as f:
        json.dump(state, f)
    os.replace(tmp, os.path.join(path, STATE_FILE))

    ct = engine.ct_arrays()
    fd, tmp = tempfile.mkstemp(dir=path, prefix=".ct-", suffix=".npz")
    with os.fdopen(fd, "wb") as f:
        np.savez_compressed(f, **ct)
    os.replace(tmp, os.path.join(path, CT_FILE))


def restore(engine: Engine, path: str) -> None:
    """Restore host + CT state into a FRESH engine (no endpoints/rules yet)."""
    with open(os.path.join(path, STATE_FILE)) as f:
        state = json.load(f)
    if state.get("format_version") != FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint version "
                         f"{state.get('format_version')}")
    if engine.endpoints or len(engine.repo):
        raise ValueError("restore requires a fresh engine")

    # identity numbering must be restored FIRST so that endpoint/CIDR
    # allocation below resolves to the same ids (idempotent via label lookup)
    engine.ctx.allocator.restore_state(state["identity_state"])
    if "rnat_state" in state:
        engine.ctx.services.restore_rnat_state(state["rnat_state"])
    for svc in state.get("services", []):
        engine.ctx.services.upsert(Service(
            name=svc["name"], namespace=svc["namespace"],
            backends=tuple(svc["backends"]),
            frontends=tuple(Frontend(**f)
                            for f in svc.get("frontends", [])),
            lb_backends=tuple(Backend(**b)
                              for b in svc.get("lb_backends", []))))
    for ep in state["endpoints"]:
        engine.add_endpoint(ep["labels"], ep["ips"], ep_id=ep["ep_id"],
                            enforcement=ep.get("enforcement"))
    if state["rules"]:
        engine.apply_policy(state["rules"])
    # ipcache entries not re-derivable (e.g. manual upserts) are replayed
    current = engine.ctx.ipcache.snapshot()
    for prefix, ident in state["ipcache"].items():
        if prefix not in current:
            engine.ctx.ipcache.upsert(prefix, ident)

    ct_path = os.path.join(path, CT_FILE)
    if os.path.exists(ct_path):
        with np.load(ct_path) as npz:
            engine.load_ct_arrays({k: npz[k] for k in npz.files})
    engine.regenerate(force=True)
