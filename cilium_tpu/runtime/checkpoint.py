"""Checkpoint/resume (SURVEY.md §5: the analog of upstream's endpoint state
dir + pinned BPF maps — "connection survival across upgrades is a headline
feature").

A checkpoint is a directory:
  state.json   — config echo, revision, identity allocator state, ipcache
                 entries, endpoints, rule documents, services
  ct.npz       — the conntrack arrays (the pinned-ctmap analog: flows
                 survive an agent restart)

Resume rebuilds the engine's host state (identity numbering stable via the
allocator export), re-materializes rules, recompiles the snapshot, and
re-places the CT table — device arrays are a cache of host truth.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import tempfile
import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Callable, Dict, Optional
from zipfile import BadZipFile       # np.load raises this on torn archives

import numpy as np

from cilium_tpu.model.services import Backend, Frontend, Service
from cilium_tpu.runtime.faults import FAULTS

if TYPE_CHECKING:  # Engine pulls in jax; load_host() must stay jax-free
    from cilium_tpu.runtime.engine import Engine

log = logging.getLogger("cilium_tpu.checkpoint")

STATE_FILE = "state.json"
CT_FILE = "ct.npz"
FORMAT_VERSION = 1


class CheckpointCorrupt(ValueError):
    """The checkpoint on disk fails validation (torn write, bit rot,
    unknown version). Callers fall back to cold start instead of serving
    from — or crashing on — a half-written state dir."""


def _state_checksum(state: Dict) -> str:
    """sha256 over the canonical JSON of everything except the checksum
    field itself. The body is JSON-round-tripped first so the hash is
    identical whether computed on the pre-serialization dict (save: int
    dict keys) or the loaded document (restore: the same keys as strings).
    """
    body = json.loads(json.dumps(
        {k: v for k, v in state.items() if k != "checksum"}, default=str))
    return hashlib.sha256(
        json.dumps(body, sort_keys=True).encode()).hexdigest()


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _atomic_write(dst: str, data, prefix: str) -> None:
    """tmp-file + fsync + rename + dir-fsync: the destination is either the
    complete old file or the complete new file, even across power loss.

    ``data`` is either bytes or a callable(fileobj) that streams the payload
    — the CT archive is tens of MB compressed, so it never sits in RAM.
    """
    d = os.path.dirname(dst)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=prefix)
    try:
        with os.fdopen(fd, "wb") as f:
            if callable(data):
                data(f)
            else:
                f.write(data)
            f.flush()
            os.fsync(f.fileno())
        # the injected crash lands in the worst window: tmp fully written,
        # rename not yet done — the guarantee under test is that dst stays
        # the complete old file and the tmp is cleaned up
        FAULTS.fire("checkpoint.write")
        os.replace(tmp, dst)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    dfd = os.open(d or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def save(engine: Engine, path: str) -> None:
    """Write an atomic, checksummed checkpoint.

    Order matters: ct.npz first, then state.json carrying ct.npz's sha256.
    A crash between the two renames leaves the old state.json whose
    ``ct_sha256`` no longer matches the new ct.npz — restore() then drops
    the CT (established flows lost, control-plane state intact) instead of
    pairing mismatched files.
    """
    os.makedirs(path, exist_ok=True)

    from cilium_tpu.runtime.datapath import CT_FORMAT_VERSION
    ct_path = os.path.join(path, CT_FILE)
    # the archive is self-describing: a version stamp rides inside the npz
    # so a CT file separated from its state.json (or restored by a newer/
    # older build) is still validated — normalize_ct_arrays upgrades older
    # formats it understands and refuses newer ones loudly
    _atomic_write(ct_path,
                  lambda f: np.savez_compressed(
                      f, __ct_format__=np.int32(CT_FORMAT_VERSION),
                      **engine.ct_arrays()),
                  ".ct-")
    ct_sha = _sha256_file(ct_path)

    state = {
        "format_version": FORMAT_VERSION,
        "revision": engine.repo.revision,
        # verdict-relevant config: the CLI's trace/status must evaluate with
        # the agent's actual enforcement semantics, not defaults
        "config": {
            "enforcement_mode": engine.ctx.enforcement_mode,
            "allow_localhost": engine.ctx.allow_localhost,
        },
        "identity_state": engine.ctx.allocator.export_state(),
        "ipcache": engine.ctx.ipcache.snapshot(),
        "endpoints": [
            {"ep_id": ep.ep_id, "labels": list(ep.labels.to_strings()),
             "ips": list(ep.ips), "enforcement": ep.enforcement}
            for ep in sorted(engine.endpoints.values(), key=lambda e: e.ep_id)
        ],
        "rules": [r.raw for r in engine.repo.all_rules() if r.raw is not None],
        "services": [
            {"name": s.name, "namespace": s.namespace,
             "backends": list(s.backends),
             "frontends": [{"addr": f.addr, "port": f.port,
                            "proto": f.proto, "kind": f.kind}
                           for f in s.frontends],
             "lb_backends": [{"addr": b.addr, "port": b.port,
                              "weight": b.weight} for b in s.lb_backends]}
            for s in engine.ctx.services.all()
        ],
        # stable rev-NAT ids must survive restarts: restored CT entries
        # reference them
        "rnat_state": engine.ctx.services.export_rnat_state(),
        # DNS cache persists so toFQDNs identities survive a restart
        # (upstream: fqdn cache persistence)
        "dns_cache": engine.ctx.fqdn_cache.export_state(),
        "ct_sha256": ct_sha,
        "ct_format": CT_FORMAT_VERSION,
    }
    state["checksum"] = _state_checksum(state)
    _atomic_write(os.path.join(path, STATE_FILE),
                  json.dumps(state).encode(), ".state-")


def _read_state(path: str) -> Dict:
    """Read + validate state.json; raises CheckpointCorrupt on any torn,
    bit-rotted, or unknown-version state file."""
    try:
        with open(os.path.join(path, STATE_FILE)) as f:
            state = json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        raise CheckpointCorrupt(f"unreadable checkpoint state: {e}") from e
    if state.get("format_version") != FORMAT_VERSION:
        raise CheckpointCorrupt(f"unsupported checkpoint version "
                                f"{state.get('format_version')}")
    # pre-checksum checkpoints (older writers) are accepted as-is
    if "checksum" in state and state["checksum"] != _state_checksum(state):
        raise CheckpointCorrupt("checkpoint state checksum mismatch")
    return state


def _rebuild_control_plane(state: Dict, ctx, repo,
                           add_endpoint: Callable,
                           apply_rules: Callable) -> None:
    """The single definition of "state dict → control-plane state", shared by
    engine restore and the CLI's host-only load so the two can never diverge.

    ``add_endpoint(ep_doc)`` and ``apply_rules(rule_docs)`` abstract the only
    difference between the two callers (engine methods vs plain objects).
    """
    cfg = state.get("config", {})
    if "enforcement_mode" in cfg:
        ctx.enforcement_mode = cfg["enforcement_mode"]
    if "allow_localhost" in cfg:
        ctx.allow_localhost = cfg["allow_localhost"]
    # identity numbering must be restored FIRST so that endpoint/CIDR
    # allocation below resolves to the same ids (idempotent via label lookup)
    ctx.allocator.restore_state(state["identity_state"])
    if "rnat_state" in state:
        ctx.services.restore_rnat_state(state["rnat_state"])
    if "dns_cache" in state:
        # before rules: toFQDNs materialization reads the cache
        ctx.fqdn_cache.restore_state(state["dns_cache"])
    for svc in state.get("services", []):
        # validate=False: restore must accept whatever the saving engine
        # accepted (incl. pre-validation checkpoints with conflicting
        # frontends) — a conflict then surfaces at the next regenerate.
        ctx.services.upsert(Service(
            name=svc["name"], namespace=svc["namespace"],
            backends=tuple(svc["backends"]),
            frontends=tuple(Frontend(**f)
                            for f in svc.get("frontends", [])),
            lb_backends=tuple(Backend(**b)
                              for b in svc.get("lb_backends", []))),
            validate=False)
    for ep in state["endpoints"]:
        add_endpoint(ep)
    if state["rules"]:
        apply_rules(state["rules"])
    # ipcache entries not re-derivable (e.g. manual upserts) are replayed
    current = ctx.ipcache.snapshot()
    for prefix, ident in state["ipcache"].items():
        if prefix not in current:
            ctx.ipcache.upsert(prefix, ident)


def _read_ct(path: str, expected_sha: Optional[str] = None
             ) -> Optional[Dict[str, np.ndarray]]:
    """Read ct.npz; a missing, corrupt, or checksum-mismatched CT file
    degrades to None (established flows lost; control-plane state is
    unaffected) — CT is a droppable cache, never worth failing a boot."""
    ct_path = os.path.join(path, CT_FILE)
    if not os.path.exists(ct_path):
        return None
    try:
        with open(ct_path, "rb") as f:
            raw = f.read()
        if expected_sha is not None \
                and hashlib.sha256(raw).hexdigest() != expected_sha:
            log.warning("checkpoint ct.npz checksum mismatch; dropping CT "
                        "(established flows will re-learn)")
            return None
        with np.load(io.BytesIO(raw)) as npz:
            arrays = {k: npz[k] for k in npz.files}
        # normalize_ct_arrays is the ONE authority on the archive format
        # (version stamp validation + schema upgrade); here its raise maps
        # to the checkpoint path's warn-and-drop semantics
        from cilium_tpu.runtime.datapath import normalize_ct_arrays
        return normalize_ct_arrays(arrays)
    except (OSError, ValueError, BadZipFile) as e:
        log.warning("checkpoint ct.npz unreadable (%s); dropping CT", e)
        return None


def restore(engine: Engine, path: str, strict: bool = False) -> bool:
    """Restore host + CT state into a FRESH engine (no endpoints/rules yet).

    Returns True when the checkpoint was restored. A corrupt checkpoint
    (torn write, checksum mismatch, unknown version) returns False with the
    engine untouched — the caller proceeds with a cold start — unless
    ``strict=True``, which re-raises the CheckpointCorrupt instead.
    Passing a non-fresh engine is a programming error and always raises.
    """
    if engine.endpoints or len(engine.repo):
        raise ValueError("restore requires a fresh engine")
    try:
        state = _read_state(path)
    except CheckpointCorrupt as e:
        if strict:
            raise
        log.warning("corrupt checkpoint at %s (%s); falling back to "
                    "cold start", path, e)
        return False
    _rebuild_control_plane(
        state, engine.ctx, engine.repo,
        add_endpoint=lambda ep: engine.add_endpoint(
            ep["labels"], ep["ips"], ep_id=ep["ep_id"],
            enforcement=ep.get("enforcement")),
        apply_rules=engine.apply_policy)
    ct = _read_ct(path, state.get("ct_sha256"))
    if ct is not None:
        engine.load_ct_arrays(ct)
    engine.regenerate(force=True)
    return True


# --------------------------------------------------------------------------- #
# CT snapshot archive (ISSUE 19): the ct-snapshot controller's bounded-
# staleness conntrack archive — the salvage FLOOR a device-loss re-mesh
# falls back to when the device gather itself fails (the chip died holding
# the collective). Same atomic-write + self-describing-format discipline as
# the full checkpoint's ct.npz; a directory of timestamped archives pruned
# to a small keep count.
# --------------------------------------------------------------------------- #
CT_ARCHIVE_PREFIX = "ct-"
CT_ARCHIVE_SUFFIX = ".npz"


def list_ct_archives(dirpath: str) -> list:
    """Archive paths, oldest → newest (timestamped names sort)."""
    try:
        names = sorted(n for n in os.listdir(dirpath)
                       if n.startswith(CT_ARCHIVE_PREFIX)
                       and n.endswith(CT_ARCHIVE_SUFFIX))
    except OSError:
        return []
    return [os.path.join(dirpath, n) for n in names]


def newest_ct_archive(dirpath: str) -> Optional[str]:
    paths = list_ct_archives(dirpath)
    return paths[-1] if paths else None


def ct_archive_age_s(dirpath: str,
                     now: Optional[float] = None) -> Optional[float]:
    """Age of the newest archive in seconds (mtime-based — survives a
    process restart, unlike an in-memory stamp); None when no archive
    exists yet."""
    newest = newest_ct_archive(dirpath)
    if newest is None:
        return None
    try:
        mtime = os.stat(newest).st_mtime
    except OSError:
        return None
    return max(0.0, (now if now is not None else time.time()) - mtime)


def save_ct_archive(dirpath: str, arrays: Dict[str, np.ndarray],
                    keep: int = 2) -> str:
    """Write one timestamped CT archive atomically and prune the directory
    to the ``keep`` newest. Returns the written path."""
    os.makedirs(dirpath, exist_ok=True)
    from cilium_tpu.runtime.datapath import CT_FORMAT_VERSION
    name = f"{CT_ARCHIVE_PREFIX}{time.time_ns()}{CT_ARCHIVE_SUFFIX}"
    dst = os.path.join(dirpath, name)
    _atomic_write(dst,
                  lambda f: np.savez_compressed(
                      f, __ct_format__=np.int32(CT_FORMAT_VERSION),
                      **arrays),
                  ".ct-archive-")
    for stale in list_ct_archives(dirpath)[:-max(1, keep)]:
        try:
            os.unlink(stale)
        except OSError:   # noqa: BLE001 — pruning is best-effort
            pass
    return dst


def load_ct_archive(path: str) -> Optional[Dict[str, np.ndarray]]:
    """Read one CT archive; corruption degrades to None (the re-mesh then
    falls through to a cold table — CT is a droppable cache, never worth
    failing a salvage)."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
        with np.load(io.BytesIO(raw)) as npz:
            arrays = {k: npz[k] for k in npz.files}
        from cilium_tpu.runtime.datapath import normalize_ct_arrays
        return normalize_ct_arrays(arrays)
    except (OSError, ValueError, BadZipFile) as e:
        log.warning("CT archive %s unreadable (%s); dropping", path, e)
        return None


# --------------------------------------------------------------------------- #
# Host-only load (CLI inspection/trace): reconstructs the control-plane state
# with NO jax import and NO device placement — the analog of cilium-dbg
# reading agent state without touching the datapath.
# --------------------------------------------------------------------------- #
@dataclass
class HostState:
    ctx: "PolicyContext"
    repo: "Repository"
    endpoints: Dict[int, "Endpoint"]
    revision: int
    ct: Optional[Dict[str, np.ndarray]]     # raw CT arrays (None if absent)
    raw: Dict                               # the state.json document


def load_host(path: str) -> HostState:
    from cilium_tpu.model.endpoint import Endpoint
    from cilium_tpu.model.labels import Labels
    from cilium_tpu.model.identity import IdentityAllocator
    from cilium_tpu.model.ipcache import IPCache
    from cilium_tpu.model.rules import parse_rules
    from cilium_tpu.model.services import ServiceRegistry
    from cilium_tpu.policy.repository import PolicyContext, Repository
    from cilium_tpu.policy.selectorcache import SelectorCache

    state = _read_state(path)
    alloc = IdentityAllocator()
    ctx = PolicyContext(allocator=alloc,
                        selector_cache=SelectorCache(alloc),
                        ipcache=IPCache(), services=ServiceRegistry())
    repo = Repository(ctx)
    endpoints: Dict[int, Endpoint] = {}

    def add_endpoint(ep):
        lbls = Labels.parse(ep["labels"])
        ident = alloc.allocate(lbls)
        endpoints[ep["ep_id"]] = Endpoint(
            ep_id=ep["ep_id"], labels=lbls, ips=tuple(ep["ips"]),
            identity_id=ident.id, enforcement=ep.get("enforcement"))
        for ip in ep["ips"]:
            prefix = f"{ip}/128" if ":" in ip else f"{ip}/32"
            ctx.ipcache.upsert(prefix, ident.id)

    _rebuild_control_plane(state, ctx, repo, add_endpoint=add_endpoint,
                           apply_rules=lambda docs: repo.add(parse_rules(docs)))
    return HostState(ctx=ctx, repo=repo, endpoints=endpoints,
                     revision=state["revision"], ct=_read_ct(path), raw=state)
