"""Host runtime: engine, config, controllers, checkpoint, metrics, flow log
(analogs of upstream ``daemon/``, ``pkg/option``, ``pkg/controller`` /
``pkg/trigger``, endpoint-state checkpointing, ``pkg/metrics``, Hubble-lite).
"""

from cilium_tpu.runtime.config import DaemonConfig
from cilium_tpu.runtime.engine import Engine

__all__ = ["DaemonConfig", "Engine"]
