"""Host runtime: engine, config, controllers, checkpoint, metrics, flow log
(analogs of upstream ``daemon/``, ``pkg/option``, ``pkg/controller`` /
``pkg/trigger``, endpoint-state checkpointing, ``pkg/metrics``, Hubble-lite).

``Engine`` is exported lazily: it pulls in jax, and the CLI's host-only
inspection path (checkpoint.load_host) must import without it.
"""

from cilium_tpu.runtime.config import DaemonConfig

__all__ = ["DaemonConfig", "Engine"]


def __getattr__(name):
    if name == "Engine":
        from cilium_tpu.runtime.engine import Engine
        return Engine
    raise AttributeError(name)
