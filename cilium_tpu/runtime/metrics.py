"""Metrics registry + Prometheus text rendering (analog of upstream
``pkg/metrics`` for the agent and the ``metricsmap`` per-verdict datapath
counters tensor — SURVEY.md §5: "counters tensor accumulated in-kernel
(drops by reason × direction), scraped to Prometheus text format").
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Tuple

import numpy as np

from cilium_tpu.utils import constants as C


class SpanStat:
    """Micro-span timing aggregate (upstream pkg/spanstat)."""

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0

    def observe(self, seconds: float) -> None:
        self.count += 1
        self.total_s += seconds
        self.max_s = max(self.max_s, seconds)

    class _Timer:
        def __init__(self, stat):
            self._stat = stat

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self._stat.observe(time.perf_counter() - self._t0)

    def timer(self) -> "_Timer":
        return SpanStat._Timer(self)


class Metrics:
    """Accumulates device counter outputs + host-side spans/gauges."""

    def __init__(self):
        self._lock = threading.Lock()
        self.by_reason_dir = np.zeros((512,), dtype=np.uint64)
        self.insert_fail = 0
        self.packets_total = 0
        self.batches_total = 0
        self.spans: Dict[str, SpanStat] = {}
        self.gauges: Dict[str, float] = {}
        self.counters: Dict[str, int] = {}

    def span(self, name: str) -> SpanStat:
        with self._lock:
            if name not in self.spans:
                self.spans[name] = SpanStat()
            return self.spans[name]

    def add_batch(self, counters: Dict, n_valid: int) -> None:
        with self._lock:
            self.by_reason_dir += np.asarray(
                counters["by_reason_dir"]).astype(np.uint64)
            self.insert_fail += int(counters["insert_fail"])
            self.packets_total += n_valid
            self.batches_total += 1

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = value

    def inc_counter(self, name: str, by: int = 1) -> None:
        """Named host-side counter (upstream: errors/warnings metrics —
        e.g. regeneration failures, sink drops)."""
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + by

    # -- rendering -----------------------------------------------------------
    def render_prometheus(self) -> str:
        """Prometheus text exposition format."""
        lines: List[str] = []
        with self._lock:
            lines.append("# HELP ciliumtpu_datapath_verdicts_total Verdicts "
                         "by drop reason and direction")
            lines.append("# TYPE ciliumtpu_datapath_verdicts_total counter")
            arr = self.by_reason_dir.reshape(256, 2)
            for reason in np.nonzero(arr.sum(axis=1))[0]:
                try:
                    rname = C.DropReason(int(reason)).name
                except ValueError:
                    rname = str(int(reason))
                for d in (0, 1):
                    if arr[reason, d]:
                        lines.append(
                            f'ciliumtpu_datapath_verdicts_total{{reason="{rname}",'
                            f'direction="{C.DIR_NAMES[d]}"}} {int(arr[reason, d])}')
            lines.append("# TYPE ciliumtpu_ct_insert_fail_total counter")
            lines.append(f"ciliumtpu_ct_insert_fail_total {self.insert_fail}")
            lines.append("# TYPE ciliumtpu_packets_total counter")
            lines.append(f"ciliumtpu_packets_total {self.packets_total}")
            lines.append("# TYPE ciliumtpu_batches_total counter")
            lines.append(f"ciliumtpu_batches_total {self.batches_total}")
            for name, v in sorted(self.counters.items()):
                lines.append(f"# TYPE ciliumtpu_{name} counter")
                lines.append(f"ciliumtpu_{name} {v}")
            for name, g in sorted(self.gauges.items()):
                lines.append(f"# TYPE ciliumtpu_{name} gauge")
                lines.append(f"ciliumtpu_{name} {g}")
            for name, s in sorted(self.spans.items()):
                lines.append(f"# TYPE ciliumtpu_{name}_seconds summary")
                lines.append(f"ciliumtpu_{name}_seconds_count {s.count}")
                lines.append(f"ciliumtpu_{name}_seconds_sum {s.total_s:.6f}")
                lines.append(f"ciliumtpu_{name}_seconds_max {s.max_s:.6f}")
        return "\n".join(lines) + "\n"
