"""Metrics registry + Prometheus text rendering (analog of upstream
``pkg/metrics`` for the agent and the ``metricsmap`` per-verdict datapath
counters tensor — SURVEY.md §5: "counters tensor accumulated in-kernel
(drops by reason × direction), scraped to Prometheus text format").
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, List, Sequence, Tuple

import numpy as np

from cilium_tpu.utils import constants as C


class SpanStat:
    """Micro-span timing aggregate (upstream pkg/spanstat)."""

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0

    def observe(self, seconds: float) -> None:
        self.count += 1
        self.total_s += seconds
        self.max_s = max(self.max_s, seconds)

    class _Timer:
        def __init__(self, stat):
            self._stat = stat

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self._stat.observe(time.perf_counter() - self._t0)

    def timer(self) -> "_Timer":
        return SpanStat._Timer(self)


# Default latency buckets (seconds): sub-ms queue waits up to multi-second
# stalls — the range the pipeline's queue-wait and batch-latency spans cover.
DEFAULT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)


class Histogram:
    """Prometheus histogram: cumulative ``_bucket`` counts + ``_sum`` /
    ``_count`` (the le-labelled exposition format). ``SpanStat`` stays the
    cheap count/total/max aggregate for existing spans; histograms are for
    distributions where percentiles matter (queue wait, batch latency)."""

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.buckets: Tuple[float, ...] = tuple(sorted(buckets))
        # counts[i] = observations <= buckets[i]; counts[-1] = +Inf bucket
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0.0
        self.count = 0
        # own lock (not the Metrics one): observe() is the pipeline hot
        # path; an unsynchronized render could otherwise scrape a bucket
        # count ahead of +Inf — a non-monotonic histogram Prometheus rejects
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self.counts[bisect.bisect_left(self.buckets, value)] += 1
            self.total += value
            self.count += 1

    def snapshot(self) -> Tuple[Tuple[float, ...], List[int], float, int]:
        """Consistent (buckets, counts, sum, count) for rendering."""
        with self._lock:
            return self.buckets, list(self.counts), self.total, self.count

    def quantile(self, q: float) -> float:
        """Approximate quantile from the bucket counts (linear within the
        winning bucket). For observations past the last finite boundary the
        boundary itself is returned — a histogram cannot do better. An
        empty histogram reads 0.0 — this is a *display* surface (status
        docs, bench artifacts), where "no observations yet" rendering as 0
        is the established convention; interval/delta consumers must use
        :func:`quantile_from` and test for :data:`EMPTY_QUANTILE`."""
        buckets, counts, _total, count = self.snapshot()
        if count == 0:
            return 0.0
        return quantile_from(buckets, counts, q)


#: the empty-window sentinel :func:`quantile_from` returns when the count
#: vector sums to zero. NaN, deliberately: every arithmetic comparison
#: against it is False, so a consumer that forgets to check cannot mistake
#: an idle interval for a zero-latency one (the pre-fix 0.0 return made a
#: scrape gap look like "queue wait collapsed" to the autotuner and would
#: read as "SLO met" to burn-rate math). Test with ``math.isnan`` /
#: :func:`quantile_is_empty`.
EMPTY_QUANTILE = float("nan")


def quantile_is_empty(value: float) -> bool:
    """True iff ``value`` is the :data:`EMPTY_QUANTILE` sentinel."""
    return value != value            # NaN is the only float unequal to itself


def quantile_from(buckets: Sequence[float], counts: Sequence[int],
                  q: float) -> float:
    """Quantile over a (buckets, counts) pair — shared by
    ``Histogram.quantile`` and consumers working on *delta* counts (the
    observe autotuner and the SLO burn math diff successive snapshots so
    each control interval is judged on its own distribution, not the
    process lifetime's). A zero-count window — two scrapes with no
    observations in between — returns :data:`EMPTY_QUANTILE` (NaN), never
    a fabricated 0.0."""
    count = sum(counts)
    if count == 0:
        return EMPTY_QUANTILE
    target = q * count
    acc = 0
    lo = 0.0
    for i, b in enumerate(buckets):
        if counts[i]:
            if acc + counts[i] >= target:
                frac = (target - acc) / counts[i]
                return lo + frac * (b - lo)
            acc += counts[i]
        lo = b
    return buckets[-1]


class Metrics:
    """Accumulates device counter outputs + host-side spans/gauges."""

    def __init__(self):
        self._lock = threading.Lock()
        # shape derived from the counter-tensor geometry in constants —
        # a DropReason added past the old hard-coded 512 can no longer
        # silently truncate (add_batch validates the incoming shape too)
        self.by_reason_dir = np.zeros((C.COUNTER_CELLS,), dtype=np.uint64)
        self.insert_fail = 0
        self.ct_evicted = 0
        self.packets_total = 0
        self.batches_total = 0
        self.spans: Dict[str, SpanStat] = {}
        self.histograms: Dict[str, Histogram] = {}
        self.gauges: Dict[str, float] = {}
        self.counters: Dict[str, int] = {}

    def span(self, name: str) -> SpanStat:
        with self._lock:
            if name not in self.spans:
                self.spans[name] = SpanStat()
            return self.spans[name]

    def histogram(self, name: str,
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        """Named histogram (created on first use; ``buckets`` only applies
        then). Name it like a Prometheus metric, e.g.
        ``pipeline_queue_wait_seconds``."""
        with self._lock:
            if name not in self.histograms:
                self.histograms[name] = Histogram(buckets)
            return self.histograms[name]

    def add_batch(self, counters: Dict, n_valid: int) -> None:
        arr = np.asarray(counters["by_reason_dir"])
        if arr.shape != self.by_reason_dir.shape:
            raise ValueError(
                f"by_reason_dir shape {arr.shape} != expected "
                f"{self.by_reason_dir.shape} (reasons x directions from "
                f"constants — kernel and metrics geometry diverged)")
        with self._lock:
            self.by_reason_dir += arr.astype(np.uint64)
            self.insert_fail += int(counters["insert_fail"])
            # optional: legacy counter dicts (older backends, tests)
            # predate the insert-when-full eviction accounting
            self.ct_evicted += int(counters.get("ct_evicted", 0))
            self.packets_total += n_valid
            self.batches_total += 1

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = value

    def set_gauges(self, values: Dict[str, float],
                   drop: Sequence[str] = ()) -> None:
        """Write a batch of gauges (and drop departed ones) under ONE lock
        acquisition — the resource ledger exports five families per
        resource per poll, and per-gauge locking was measurable against
        the <2% polling-overhead attestation."""
        with self._lock:
            self.gauges.update(values)
            for name in drop:
                self.gauges.pop(name, None)

    def drop_gauge(self, name: str) -> None:
        """Remove a labeled gauge whose subject is gone (e.g. a departed
        clustermesh peer) — a frozen last value would keep exporting a
        healthy-looking reading for a dead thing."""
        with self._lock:
            self.gauges.pop(name, None)

    def inc_counter(self, name: str, by: int = 1) -> None:
        """Named host-side counter (upstream: errors/warnings metrics —
        e.g. regeneration failures, sink drops)."""
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + by

    # -- rendering -----------------------------------------------------------
    def render_prometheus(self) -> str:
        """Prometheus text exposition format."""
        lines: List[str] = []
        with self._lock:
            lines.append("# HELP ciliumtpu_datapath_verdicts_total Verdicts "
                         "by drop reason and direction")
            lines.append("# TYPE ciliumtpu_datapath_verdicts_total counter")
            arr = self.by_reason_dir.reshape(C.DROP_REASON_BINS,
                                             C.N_DIRECTIONS)
            for reason in np.nonzero(arr.sum(axis=1))[0]:
                try:
                    rname = C.DropReason(int(reason)).name
                except ValueError:
                    rname = str(int(reason))
                for d in range(C.N_DIRECTIONS):
                    if arr[reason, d]:
                        lines.append(
                            f'ciliumtpu_datapath_verdicts_total{{reason="{rname}",'
                            f'direction="{C.DIR_NAMES[d]}"}} {int(arr[reason, d])}')
            lines.append("# TYPE ciliumtpu_ct_insert_fail_total counter")
            lines.append(f"ciliumtpu_ct_insert_fail_total {self.insert_fail}")
            lines.append("# TYPE ciliumtpu_ct_evicted_total counter")
            lines.append(f"ciliumtpu_ct_evicted_total {self.ct_evicted}")
            lines.append("# TYPE ciliumtpu_packets_total counter")
            lines.append(f"ciliumtpu_packets_total {self.packets_total}")
            lines.append("# TYPE ciliumtpu_batches_total counter")
            lines.append(f"ciliumtpu_batches_total {self.batches_total}")
            # counters may carry a label set in the name (e.g.
            # ``pipeline_shed_total{reason="flush"}``); the TYPE line is
            # emitted once per base metric, not per label combination
            typed = set()
            for name, v in sorted(self.counters.items()):
                base = name.split("{", 1)[0]
                if base not in typed:
                    lines.append(f"# TYPE ciliumtpu_{base} counter")
                    typed.add(base)
                lines.append(f"ciliumtpu_{name} {v}")
            # gauges may carry labels too (``pipeline_staged_rows{shard=..}``)
            # — one TYPE line per base metric, like the counters above
            gtyped = set()
            for name, g in sorted(self.gauges.items()):
                base = name.split("{", 1)[0]
                if base not in gtyped:
                    lines.append(f"# TYPE ciliumtpu_{base} gauge")
                    gtyped.add(base)
                lines.append(f"ciliumtpu_{name} {g}")
            for name, s in sorted(self.spans.items()):
                lines.append(f"# TYPE ciliumtpu_{name}_seconds summary")
                lines.append(f"ciliumtpu_{name}_seconds_count {s.count}")
                lines.append(f"ciliumtpu_{name}_seconds_sum {s.total_s:.6f}")
                lines.append(f"ciliumtpu_{name}_seconds_max {s.max_s:.6f}")
            # histograms may carry a label set in the name too (the
            # per-shard ingest e2e families, ``..._seconds{shard="3"}``):
            # one TYPE line per base metric, labels merged into each
            # bucket's le label and suffixed onto _sum/_count
            htyped = set()
            for name, h in sorted(self.histograms.items()):
                buckets, counts, total, count = h.snapshot()
                base, _, labels = name.partition("{")
                labels = labels.rstrip("}")
                lbl_prefix = f"{labels}," if labels else ""
                lbl_suffix = f"{{{labels}}}" if labels else ""
                if base not in htyped:
                    lines.append(f"# TYPE ciliumtpu_{base} histogram")
                    htyped.add(base)
                acc = 0
                for le, n in zip(buckets, counts):
                    acc += n
                    lines.append(f'ciliumtpu_{base}_bucket'
                                 f'{{{lbl_prefix}le="{le}"}} {acc}')
                lines.append(f'ciliumtpu_{base}_bucket'
                             f'{{{lbl_prefix}le="+Inf"}} {count}')
                lines.append(f"ciliumtpu_{base}_sum{lbl_suffix} {total:.6f}")
                lines.append(f"ciliumtpu_{base}_count{lbl_suffix} {count}")
        return "\n".join(lines) + "\n"
