"""Controllers and triggers (analog of upstream ``pkg/controller`` named
retry-loops with exponential backoff and ``pkg/trigger`` debounced triggers —
SURVEY.md §2: "Port pattern — drives incremental tensor updates").
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional


@dataclass
class ControllerStatus:
    name: str
    success_count: int = 0
    failure_count: int = 0
    consecutive_failures: int = 0
    last_error: str = ""
    last_success: float = 0.0


class Controller:
    """A named reconciliation loop: runs ``do_func`` every ``interval``
    seconds, retrying with exponential backoff on failure."""

    def __init__(self, name: str, do_func: Callable[[], None],
                 interval: float, backoff_base: float = 1.0,
                 backoff_max: float = 60.0):
        self.status = ControllerStatus(name)
        self._do = do_func
        self._interval = interval
        self._backoff_base = backoff_base
        self._backoff_max = backoff_max
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"ctrl-{self.status.name}")
        self._thread.start()

    def trigger(self) -> None:
        """Run now (out of schedule)."""
        self._wake.set()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread:
            self._thread.join(timeout=5)

    def run_once(self) -> None:
        """Synchronous single run (tests / manual mode)."""
        try:
            self._do()
            self.status.success_count += 1
            self.status.consecutive_failures = 0
            self.status.last_error = ""
            self.status.last_success = time.monotonic()
        except Exception as e:  # noqa: BLE001 — controllers isolate failures
            self.status.failure_count += 1
            self.status.consecutive_failures += 1
            self.status.last_error = f"{type(e).__name__}: {e}"

    def _run(self) -> None:
        while not self._stop.is_set():
            self.run_once()
            if self.status.consecutive_failures:
                delay = min(self._backoff_max,
                            self._backoff_base
                            * (2 ** (self.status.consecutive_failures - 1)))
            else:
                delay = self._interval
            self._wake.wait(timeout=delay)
            self._wake.clear()


class ControllerManager:
    def __init__(self):
        self._controllers: Dict[str, Controller] = {}

    def update(self, name: str, do_func: Callable[[], None],
               interval: float, start: bool = True, **kw) -> Controller:
        old = self._controllers.pop(name, None)
        if old:
            old.stop()
        ctrl = Controller(name, do_func, interval, **kw)
        self._controllers[name] = ctrl
        if start:
            ctrl.start()
        return ctrl

    def remove(self, name: str) -> None:
        ctrl = self._controllers.pop(name, None)
        if ctrl:
            ctrl.stop()

    def stop_all(self) -> None:
        for ctrl in list(self._controllers.values()):
            ctrl.stop()
        self._controllers.clear()

    def statuses(self):
        return {n: c.status for n, c in self._controllers.items()}


class Trigger:
    """Debounced trigger (upstream ``pkg/trigger``): many calls within
    ``min_interval`` coalesce into one invocation of ``fn``. ``sync=True``
    runs inline (deterministic tests)."""

    def __init__(self, fn: Callable[[], None], min_interval: float = 0.1,
                 sync: bool = False):
        self._fn = fn
        self._min_interval = min_interval
        self._sync = sync
        self._lock = threading.Lock()
        self._pending = False
        self._timer: Optional[threading.Timer] = None
        self.folds = 0     # calls coalesced

    def __call__(self) -> None:
        if self._sync:
            self._fn()
            return
        with self._lock:
            if self._pending:
                self.folds += 1
                return
            self._pending = True
            self._timer = threading.Timer(self._min_interval, self._fire)
            self._timer.daemon = True
            self._timer.start()

    def _fire(self) -> None:
        with self._lock:
            self._pending = False
        self._fn()

    def cancel(self) -> None:
        with self._lock:
            if self._timer:
                self._timer.cancel()
            self._pending = False
