"""Controllers and triggers (analog of upstream ``pkg/controller`` named
retry-loops with exponential backoff and ``pkg/trigger`` debounced triggers —
SURVEY.md §2: "Port pattern — drives incremental tensor updates").

Failure semantics mirror upstream controller runtime: a failing ``do_func``
never kills the loop; consecutive failures grow a capped exponential backoff
with deterministic jitter (seeded per controller name, so a chaos run
replays the exact same schedule), and the counts/last error are exposed in
``ControllerStatus`` for the health/metrics surfaces.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional


def backoff_delay(consecutive_failures: int, base: float, cap: float,
                  rng: Optional[random.Random] = None,
                  jitter: float = 0.1) -> float:
    """Capped exponential backoff with proportional jitter.

    ``jitter=0.1`` spreads the delay uniformly over [d, d*1.1] — enough to
    de-synchronize a fleet of controllers retrying the same failed store
    without making test schedules unpredictable (pass a seeded rng). The
    cap is re-applied after jitter: ``cap`` is a hard ceiling, never
    exceeded.
    """
    if consecutive_failures <= 0:
        return 0.0
    delay = min(cap, base * (2 ** (consecutive_failures - 1)))
    if rng is not None and jitter > 0:
        delay = min(cap, delay * (1.0 + jitter * rng.random()))
    return delay


@dataclass
class ControllerStatus:
    name: str
    success_count: int = 0
    failure_count: int = 0
    consecutive_failures: int = 0
    last_error: str = ""
    last_success: float = 0.0
    last_backoff_s: float = 0.0    # the delay chosen after the last run


class Controller:
    """A named reconciliation loop: runs ``do_func`` every ``interval``
    seconds, retrying with capped exponential backoff (+ deterministic
    jitter) on failure."""

    def __init__(self, name: str, do_func: Callable[[], None],
                 interval: float, backoff_base: float = 1.0,
                 backoff_max: float = 60.0, jitter: float = 0.1,
                 seed: Optional[int] = None):
        self.status = ControllerStatus(name)
        self._do = do_func
        self._interval = interval
        self._backoff_base = backoff_base
        self._backoff_max = backoff_max
        self._jitter = jitter
        # seeded from the name by default: the schedule is stable across
        # runs (chaos replay) yet differs between controllers (de-sync)
        self._rng = random.Random(name if seed is None else seed)
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"ctrl-{self.status.name}")
        self._thread.start()

    def trigger(self) -> None:
        """Run now (out of schedule)."""
        self._wake.set()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread:
            self._thread.join(timeout=5)

    def run_once(self) -> None:
        """Synchronous single run (tests / manual mode)."""
        try:
            self._do()
            self.status.success_count += 1
            self.status.consecutive_failures = 0
            self.status.last_error = ""
            self.status.last_success = time.monotonic()
        except Exception as e:  # noqa: BLE001 — controllers isolate failures
            self.status.failure_count += 1
            self.status.consecutive_failures += 1
            self.status.last_error = f"{type(e).__name__}: {e}"
        self.status.last_backoff_s = self._draw_delay()

    def _draw_delay(self) -> float:
        """Advance the schedule: draw the delay for the run that just
        finished, consuming one jitter sample from the seeded RNG."""
        if not self.status.consecutive_failures:
            return self._interval
        return backoff_delay(self.status.consecutive_failures,
                             self._backoff_base, self._backoff_max,
                             self._rng, self._jitter)

    def next_delay(self) -> float:
        """Peek at the delay the current failure streak would produce
        WITHOUT consuming the schedule RNG — observing the schedule (tests,
        status readers) must not shift the replayable delay sequence."""
        if not self.status.consecutive_failures:
            return self._interval
        state = self._rng.getstate()
        try:
            return backoff_delay(self.status.consecutive_failures,
                                 self._backoff_base, self._backoff_max,
                                 self._rng, self._jitter)
        finally:
            self._rng.setstate(state)

    def _run(self) -> None:
        while not self._stop.is_set():
            self.run_once()
            self._wake.wait(timeout=self.status.last_backoff_s)
            self._wake.clear()


class ControllerManager:
    def __init__(self):
        self._controllers: Dict[str, Controller] = {}

    def update(self, name: str, do_func: Callable[[], None],
               interval: float, start: bool = True, **kw) -> Controller:
        old = self._controllers.pop(name, None)
        if old:
            old.stop()
        ctrl = Controller(name, do_func, interval, **kw)
        self._controllers[name] = ctrl
        if start:
            ctrl.start()
        return ctrl

    def remove(self, name: str) -> None:
        ctrl = self._controllers.pop(name, None)
        if ctrl:
            ctrl.stop()

    def stop_all(self) -> None:
        for ctrl in list(self._controllers.values()):
            ctrl.stop()
        self._controllers.clear()

    def statuses(self):
        return {n: c.status for n, c in self._controllers.items()}


class Trigger:
    """Debounced trigger (upstream ``pkg/trigger``): many calls within
    ``min_interval`` coalesce into one invocation of ``fn``. ``sync=True``
    runs inline (deterministic tests).

    A failing ``fn`` no longer dies silently in its timer thread: the
    failure is counted, and in async mode the trigger re-arms itself with
    capped exponential backoff until ``fn`` succeeds (a crashed regeneration
    retries instead of waiting for the next external event)."""

    def __init__(self, fn: Callable[[], None], min_interval: float = 0.1,
                 sync: bool = False, backoff_base: float = 0.5,
                 backoff_max: float = 30.0, max_retries: Optional[int] = 8):
        self._fn = fn
        self._min_interval = min_interval
        self._sync = sync
        self._backoff_base = backoff_base
        self._backoff_max = backoff_max
        self._max_retries = max_retries
        self._rng = random.Random(0)
        self._lock = threading.Lock()
        self._pending = False
        self._timer: Optional[threading.Timer] = None
        self.folds = 0                 # calls coalesced
        self.consecutive_failures = 0
        self.last_error = ""

    def __call__(self) -> None:
        if self._sync:
            try:
                self._fn()
            except Exception as e:
                self.consecutive_failures += 1
                self.last_error = f"{type(e).__name__}: {e}"
                raise
            else:
                self.consecutive_failures = 0
                self.last_error = ""
            return
        self._schedule(self._min_interval)

    def _schedule(self, delay: float) -> None:
        with self._lock:
            if self._pending:
                self.folds += 1
                return
            self._pending = True
            self._timer = threading.Timer(delay, self._fire)
            self._timer.daemon = True
            self._timer.start()

    def _fire(self) -> None:
        with self._lock:
            self._pending = False
        try:
            self._fn()
        except Exception as e:  # noqa: BLE001 — trigger isolates failures
            self.consecutive_failures += 1
            self.last_error = f"{type(e).__name__}: {e}"
            if (self._max_retries is None
                    or self.consecutive_failures <= self._max_retries):
                self._schedule(backoff_delay(
                    self.consecutive_failures, self._backoff_base,
                    self._backoff_max, self._rng))
        else:
            self.consecutive_failures = 0
            self.last_error = ""

    def cancel(self) -> None:
        with self._lock:
            if self._timer:
                self._timer.cancel()
            self._pending = False
