"""Slim REST API over a unix socket (SURVEY.md §1 layer 7 "slim REST/gRPC
analog"; upstream: the cilium-agent `api/v1` go-swagger server on
/var/run/cilium/cilium.sock — §3.1 "api server up (unix socket REST)").

Stdlib-only (http.server over a Unix stream socket), JSON bodies, thread-per
-connection. The handlers read the LIVE engine — this is the difference from
the offline CLI, which reads checkpoint files; `cilium-tpu --api <sock>`
drives these routes (cli/commands.py).

Routes (all under /v1):
  GET  /v1/healthz            liveness + policy revision + degradation state
                              (OK/DEGRADED/STALE, consecutive regen failures)
  GET  /v1/status             agent summary (endpoints/identities/rules/CT)
  GET  /v1/endpoints          endpoint list
  GET  /v1/endpoints/<id>     one endpoint incl. per-direction policy size
  GET  /v1/identities         identity list
  GET  /v1/policy             rule documents
  POST /v1/policy             apply CNP-style rule documents (returns revision)
  POST /v1/policy/trace       {ep, direction, remote, dport, proto} → verdict
  GET  /v1/services           service/LB state
  GET  /v1/ct?limit=N&now=T   live conntrack entries
  GET  /v1/flows?last=N&verdict=V   flow log tail
  GET  /v1/flows/observe      vectorized filtered observe (the Hubble
                              Observe()/FlowFilter analog,
                              observe/observer.py): allow-filter params
                              verdict/reason/endpoint/identity/proto/
                              port/sport/dport/cidr/src_cidr/dst_cidr/
                              rule/direction (comma-lists OR within a
                              field, fields AND; ``not_``-prefixed params
                              build the denylist), last=N one-shot window,
                              since=SEQ follow mode with a structured
                              ``gap`` record on ring wraparound,
                              explain=1 attaches the provenance legend
                              (matched rule → id/port class + identity,
                              lpm_prefix → canonical ipcache prefix)
  GET  /v1/flows/metrics?last=N     windowed flow-metrics time-series +
                              cumulative totals (the hubble metrics analog)
  GET  /v1/trace?limit=N&name=S     sampled span ring + per-stage summary
                              (observe/trace.py; empty when tracing is off;
                              stats carry spans_dropped_total + ring_wraps —
                              the drop-oldest loss accounting)
  GET  /v1/resources          resource pressure ledger (observe/pressure.py):
                              one row per registered bounded structure —
                              capacity, occupancy, pressure, high-water,
                              time-to-exhaustion forecast — plus the
                              device-side HBM ledger (bytes per placed
                              tensor group) and any attached offline
                              verifier budget report. Backs `cilium-tpu top`
  GET  /v1/debug/bundle?clear=1     flight-recorder debug bundle
                              (observe/blackbox.py): the frozen anomaly
                              bundle when one exists (parity mismatch,
                              breaker open, watchdog restart, shed spike),
                              else a live snapshot; ?clear=1 re-arms the
                              recorder after the fetch
  GET  /v1/fqdn/cache         learned DNS names
  GET  /v1/metrics            Prometheus text (text/plain), incl. flow
                              metrics totals
  GET  /v1/config             daemon config echo (runtime-mutable subset)
  PATCH /v1/config            {"enforcement_mode": ...} (upstream: `cilium
                              config PolicyEnforcement=...`)
  GET  /v1/health             datapath health probe through real classify
  POST /v1/classify           serve a batch of flows through the ingestion
                              pipeline ({"records": [{src,dst,sport,dport,
                              proto,ep,direction},...]}); Ticket.result()
                              is bounded by config.pipeline_request_timeout_s
                              — overload shed (queue full / deadline) maps
                              to 429, breaker-open / hard-failed / timeout
                              to 503, always with a JSON error body
  POST /v1/regenerate         force a recompile
  GET  /v1/faults             fault-injection point list + fire/trip stats
  POST /v1/faults             arm ({"spec": "point=mode:..."}) or disarm
                              ({"disarm": "*"}) injection points (chaos CLI)
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
from http.server import BaseHTTPRequestHandler
from typing import TYPE_CHECKING, Dict, Optional, Tuple
from urllib.parse import unquote

from cilium_tpu.runtime.faults import FAULTS
from cilium_tpu.utils import constants as C

if TYPE_CHECKING:
    from cilium_tpu.runtime.engine import Engine


class _UnixHTTPServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True


class APIServer:
    """Owns the unix socket + serving thread; route logic in _Handler."""

    def __init__(self, engine: "Engine", socket_path: str):
        self.engine = engine
        self.socket_path = socket_path
        self._server: Optional[_UnixHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._server is not None:
            return
        d = os.path.dirname(self.socket_path)
        if d and not os.path.isdir(d):
            # owner + group only: the socket dir is the auth boundary
            # (upstream: /var/run/cilium is root:cilium 0750). chmod after
            # makedirs because the mode= arg is masked by the umask; only
            # dirs WE create are tightened — never a pre-existing shared
            # parent like /tmp
            os.makedirs(d, mode=0o750, exist_ok=True)
            os.chmod(d, 0o750)
        if os.path.exists(self.socket_path):
            # probe before unlinking: a live server answering on the path
            # means another agent owns it — error out instead of silently
            # stealing its socket (two agents would corrupt each other's
            # state dir); only a dead leftover from a crash is removed
            probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            probe.settimeout(0.5)
            try:
                probe.connect(self.socket_path)
            except (ConnectionRefusedError, FileNotFoundError):
                # ECONNREFUSED is the only proof of death (nobody accepts
                # on the path — a crashed agent's leftover, or a stray
                # regular file); reclaim it. ENOENT means the owner removed
                # it between our exists() check and the probe — same unlink,
                # but the path may already be gone
                try:
                    os.unlink(self.socket_path)
                except FileNotFoundError:
                    pass
            except OSError as e:
                # timeout / EAGAIN / EACCES: a live-but-busy owner (e.g.
                # mid-compile with a full backlog) looks exactly like this
                # — anything we cannot prove dead must not be stolen
                raise RuntimeError(
                    f"cannot prove the server on {self.socket_path} is "
                    f"dead ({e}); refusing to steal its socket")
            else:
                raise RuntimeError(
                    f"another server is live on {self.socket_path}; "
                    "refusing to steal its socket")
            finally:
                probe.close()
        engine = self.engine

        class Handler(_Handler):
            pass

        Handler.engine = engine
        # the API mutates policy (POST /v1/policy) and enforcement mode:
        # restrict to the owning user before serving a single request. The
        # umask makes the socket 0600 AT BIND — a chmod after bind would
        # leave a window where another user can connect and sit in the
        # listen backlog until serve_forever picks the connection up
        old_umask = os.umask(0o177)
        try:
            self._server = _UnixHTTPServer(self.socket_path, Handler)
        finally:
            os.umask(old_umask)
        os.chmod(self.socket_path, 0o600)    # belt and braces
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="cilium-tpu-api", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            if os.path.exists(self.socket_path):
                os.unlink(self.socket_path)


# --------------------------------------------------------------------------- #
# document builders (shapes shared with the offline CLI so the same text
# renderers work on live and checkpoint data)
# --------------------------------------------------------------------------- #
def status_doc(engine: "Engine") -> Dict:
    import time
    now = int(time.time())
    ct = engine.ct_stats(now)
    return {
        "revision": engine.repo.revision,
        "endpoints": len(engine.endpoints),
        "identities": len(list(engine.ctx.allocator.all())),
        "rules": len(engine.repo),
        "ipcache_entries": len(engine.ctx.ipcache.snapshot()),
        "services": len(engine.ctx.services.all()),
        "conntrack": {"capacity": ct["capacity"], "live": ct["live"]},
        "enforcement_mode": engine.ctx.enforcement_mode,
        # Pallas megakernel selector state (None on jax-free backends —
        # the oracle-backed fake has no kernels to fuse)
        "fused_kernels": getattr(engine.datapath, "fused_state", None),
        # flow→shard resolution surface (None on jax-free backends): host
        # steering vs the device-side ppermute exchange (rss_mode)
        "rss": getattr(engine.datapath, "rss_state", None),
        # None until the ingestion pipeline has been started
        "pipeline": engine.pipeline_stats(),
        # None until a shim feeder is attached (Engine.start_feeder)
        "feeder": engine.feeder_stats(),
        # None until the overload controller has observed an interval
        "overload": engine.overload_status(),
        # None unless multi-tenant QoS is armed (qos_enabled): tenant
        # table + live per-tenant admission queue depths/admitted shares
        "qos": engine.qos_status(),
        # in-band DNS plane (ISSUE 18): cache occupancy/bounds, proxy
        # learning/parse-error counters, refresh coalescing, identity
        # lifecycle — always present (the cache exists proxy or not)
        "fqdn": engine.fqdn_status(),
        # None until the autotune controller has run against a pipeline
        "autotune": engine.autotune_status(),
        "trace": engine.tracer.stats(),
        # verdict provenance: parity-audit counters + flight-recorder state
        "audit": engine.auditor.stats(),
        "blackbox": engine.blackbox.stats(),
        # vectorized flow-observe engine (observe/observer.py): query +
        # follow-gap accounting over the columnar flowlog ring
        "observer": engine.observer.stats(),
        # resource pressure ledger summary (observe/pressure.py): the
        # pressured list + soonest exhaustion forecast without the full
        # per-resource table (/v1/resources has that)
        "resources": engine.ledger.status(),
        # device-memory truth (ISSUE 13 satellite): the live HBM ledger
        # and the offline verifier budget report cite the same numbers
        "hbm": engine.hbm_status(),
        # None until a ClusterMesh is attached (cluster_store+node_name):
        # per-peer generation/lag, store reachability, staleness verdict,
        # conflict map, replication-lag p99 (runtime/clustermesh.status)
        "mesh": engine.mesh_status(),
    }


def endpoints_doc(engine: "Engine"):
    return [{"ep_id": ep.ep_id, "identity": ep.identity_id,
             "ips": list(ep.ips), "labels": list(ep.labels.to_strings()),
             "enforcement": ep.enforcement,
             "policy_revision": ep.policy_revision}
            for ep in sorted(engine.endpoints.values(),
                             key=lambda e: e.ep_id)]


def endpoint_doc(engine: "Engine", ep_id: int) -> Optional[Dict]:
    ep = engine.endpoints.get(ep_id)
    if ep is None:
        return None
    pol = engine.repo.resolve(ep)
    return {
        "ep_id": ep.ep_id, "identity": ep.identity_id,
        "ips": list(ep.ips), "labels": list(ep.labels.to_strings()),
        "enforcement": ep.enforcement,
        "policy_revision": pol.revision,
        "egress": {"enforced": pol.egress.enforced,
                   "entries": len(pol.egress.mapstate.items())},
        "ingress": {"enforced": pol.ingress.enforced,
                    "entries": len(pol.ingress.mapstate.items())},
    }


def identities_doc(engine: "Engine"):
    return [{"id": ident.id, "labels": list(ident.labels.to_strings()),
             "reserved": ident.id < C.CLUSTER_IDENTITY_BASE,
             "local": bool(ident.id & C.LOCAL_IDENTITY_SCOPE)}
            for ident in engine.ctx.allocator.all()]


def services_doc(engine: "Engine"):
    return [{"name": s.name, "namespace": s.namespace,
             "backends": list(s.backends),
             "frontends": [{"addr": f.addr, "port": f.port,
                            "proto": f.proto, "kind": f.kind}
                           for f in s.frontends]}
            for s in engine.ctx.services.all()]


def fqdn_doc(engine: "Engine"):
    return [{"name": name, "ips": {ip: exp for ip, exp in sorted(e.items())}}
            for name, e in engine.ctx.fqdn_cache.names()]


def ct_doc(engine: "Engine", limit: int, now: Optional[int]):
    import time
    import numpy as np
    from cilium_tpu.utils.ip import addr_to_str, words_to_addr
    arrays = engine.ct_arrays()
    if now is None:
        now = int(time.time())
    live = np.nonzero(arrays["expiry"] > now)[0][:limit]
    out = []
    for slot in live:
        w = arrays["keys"][slot]
        out.append({
            "src": addr_to_str(words_to_addr(w[0:4])),
            "dst": addr_to_str(words_to_addr(w[4:8])),
            "sport": int(w[8]) >> 16, "dport": int(w[8]) & 0xFFFF,
            "proto": C.PROTO_NAMES.get(int(w[9]) >> 8, str(int(w[9]) >> 8)),
            "expires_in": int(arrays["expiry"][slot]) - now,
            "pkts_fwd": int(arrays["pkts_fwd"][slot]),
            "pkts_rev": int(arrays["pkts_rev"][slot]),
        })
    return out


def serving_error(exc: BaseException) -> Optional[Tuple[int, Dict]]:
    """Map a pipeline serving failure to (http_status, json_body), or None
    for errors that are not part of the overload/degradation taxonomy
    (those stay 500s). Overload shed → 429 (retryable: the pipeline is
    healthy but this submission lost the overload race); unavailability →
    503 (the backend is sick/restarting — back off)."""
    from cilium_tpu.pipeline.guard import (PipelineDeadlineExceeded,
                                           PipelineDrop, PipelineError)
    doc = {"error": str(exc), "kind": type(exc).__name__}
    if isinstance(exc, (PipelineDrop, PipelineDeadlineExceeded)):
        return 429, doc
    # every other PipelineError (PipelineUnavailable, PipelineClosed,
    # restart rejections) and a bounded-wait timeout → 503
    if isinstance(exc, (PipelineError, TimeoutError)):
        return 503, doc
    return None


def classify_doc(engine: "Engine", body: Dict) -> Tuple[int, Dict]:
    """The REST serving path: build a batch from JSON flow records, submit
    it through the ingestion pipeline, wait bounded, return verdicts."""
    from oracle import PacketRecord
    from cilium_tpu.kernels.records import batch_from_records
    from cilium_tpu.utils.ip import parse_addr

    records = body.get("records")
    if not records or not isinstance(records, list):
        return 400, {"error": "classify requires a non-empty 'records' list"}
    names = {v.upper(): k for k, v in C.PROTO_NAMES.items()}
    recs = []
    for i, r in enumerate(records):
        missing = [k for k in ("src", "dst", "dport", "ep") if k not in r]
        if missing:
            return 400, {"error": f"record {i} missing {missing}"}
        proto = r.get("proto", "TCP")
        if isinstance(proto, str):
            proto = int(proto) if proto.isdigit() \
                else names.get(proto.upper())
            if proto is None:
                return 400, {"error": f"record {i}: unknown protocol"}
        try:
            s16, s6 = parse_addr(r["src"])
            d16, d6 = parse_addr(r["dst"])
        except Exception as e:   # noqa: BLE001 — caller-supplied addresses
            return 400, {"error": f"record {i}: bad address ({e})"}
        direction = C.DIR_INGRESS if r.get("direction") == "ingress" \
            else C.DIR_EGRESS
        try:
            recs.append(PacketRecord(
                s16, d16, int(r.get("sport", 0)), int(r["dport"]), proto,
                int(r.get("flags", C.TCP_SYN)), s6 or d6, int(r["ep"]),
                direction))
        except (TypeError, ValueError) as e:
            return 400, {"error": f"record {i}: bad numeric field ({e})"}
    try:
        now = int(body["now"]) if "now" in body else None
        deadline_ms = body.get("deadline_ms")
        if deadline_ms is not None:
            deadline_ms = float(deadline_ms)
    except (TypeError, ValueError) as e:
        return 400, {"error": f"bad now/deadline_ms ({e})"}
    snapshot = engine.active.snapshot
    batch = batch_from_records(recs, snapshot.ep_slot_of)
    try:
        ticket = engine.submit(batch, now=now, deadline_ms=deadline_ms)
        out = ticket.result(
            timeout=engine.config.pipeline_request_timeout_s)
    except Exception as exc:   # noqa: BLE001 — taxonomy-mapped below
        mapped = serving_error(exc)
        if mapped is None:
            raise
        return mapped
    verdicts = []
    for i in range(len(recs)):
        verdicts.append({
            "allow": bool(out["allow"][i]),
            "reason": C.DropReason(int(out["reason"][i])).name,
            "ct_state": C.CTStatus(int(out["status"][i])).name,
            "remote_identity": int(out["remote_identity"][i]),
        })
    return 200, {"count": len(verdicts), "verdicts": verdicts}


def trace_doc(engine: "Engine", body: Dict) -> Tuple[int, Dict]:
    from cilium_tpu.model.ipcache import lpm_lookup
    missing = [k for k in ("ep", "remote", "dport") if k not in body]
    if missing:
        return 400, {"error": f"trace requires {missing}"}
    ep = engine.endpoints.get(int(body.get("ep", -1)))
    if ep is None:
        return 404, {"error": f"endpoint {body.get('ep')} not found"}
    direction = C.DIR_EGRESS if body.get("direction", "egress") == "egress" \
        else C.DIR_INGRESS
    proto = body.get("proto", C.PROTO_TCP)
    if isinstance(proto, str):
        names = {v.upper(): k for k, v in C.PROTO_NAMES.items()}
        proto = int(proto) if proto.isdigit() else names.get(proto.upper())
        if proto is None:
            return 400, {"error": "unknown protocol"}
    remote_id = lpm_lookup(engine.ctx.ipcache.snapshot(), body["remote"])
    pol = engine.repo.resolve(ep)
    dirpol = pol.direction(direction)
    if not dirpol.enforced:
        return 200, {"verdict": "ALLOWED", "remote_identity": remote_id,
                     "reason": "direction not enforced (default mode)"}
    res = dirpol.lookup(remote_id, proto, int(body["dport"]))
    verdict = {C.VERDICT_DENY: ("DENIED", "explicit deny rule"),
               C.VERDICT_MISS: ("DENIED", "no rule matched (default deny)"),
               C.VERDICT_REDIRECT:
                   ("ALLOWED", "L7 redirect (http rules apply per request)"),
               C.VERDICT_ALLOW: ("ALLOWED", "allow rule matched")}
    v, reason = verdict[res.decision]
    doc = {"verdict": v, "reason": reason, "remote_identity": remote_id}
    if res.key is not None:
        doc["matched_key"] = {
            "identity": res.key.identity, "proto": res.key.proto,
            "port_lo": res.key.port_lo, "port_hi": res.key.port_hi}
        doc["derived_from"] = list(res.entry.derived_from)
    return 200, doc


# --------------------------------------------------------------------------- #
class _Handler(BaseHTTPRequestHandler):
    engine: "Engine" = None        # injected per-server subclass
    protocol_version = "HTTP/1.1"

    # unix sockets have no client address; BaseHTTPRequestHandler expects one
    def address_string(self):
        return "unix"

    def log_message(self, fmt, *args):   # quiet by default
        pass

    # -- plumbing -----------------------------------------------------------
    def _send_json(self, code: int, doc) -> None:
        body = json.dumps(doc, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, text: str) -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> Dict:
        n = int(self.headers.get("Content-Length", 0))
        if n == 0:
            return {}
        return json.loads(self.rfile.read(n))

    def _route(self) -> Tuple[str, Dict[str, str]]:
        path, _, query = self.path.partition("?")
        params = {}
        for part in query.split("&"):
            if "=" in part:
                k, _, v = part.partition("=")
                # the observe CLI percent-encodes filter values (CIDRs
                # carry '/'); decode so filters see the literal value
                k, v = unquote(k), unquote(v)
                if k.startswith("not_") and k in params:
                    # repeatable --not flags: same-key denies accumulate
                    # (the filter parsers comma-split multi-values)
                    params[k] += "," + v
                else:
                    params[k] = v
        return path.rstrip("/"), params

    # -- methods ------------------------------------------------------------
    def do_GET(self):          # noqa: N802 (http.server API)
        eng = self.engine
        path, q = self._route()
        try:
            if path == "/v1/faults":
                # exempt from api.handler faults: the chaos driver must be
                # able to observe/disarm while the fault storm is on
                return self._send_json(200, FAULTS.stats())
            FAULTS.fire("api.handler")
            if path == "/v1/healthz":
                health = eng.health()
                return self._send_json(200, {
                    "status": ("ok" if health["state"] == C.HEALTH_OK
                               else "degraded"),
                    "revision": eng.repo.revision, **health})
            if path == "/v1/status":
                return self._send_json(200, status_doc(eng))
            if path == "/v1/endpoints":
                return self._send_json(200, endpoints_doc(eng))
            if path.startswith("/v1/endpoints/"):
                try:
                    ep_id = int(path.rsplit("/", 1)[1])
                except ValueError:
                    return self._send_json(400, {"error": "bad endpoint id"})
                doc = endpoint_doc(eng, ep_id)
                if doc is None:
                    return self._send_json(404, {"error": "not found"})
                return self._send_json(200, doc)
            if path == "/v1/identities":
                return self._send_json(200, identities_doc(eng))
            if path == "/v1/policy":
                return self._send_json(200, [
                    r.raw for r in eng.repo.all_rules() if r.raw is not None])
            if path == "/v1/services":
                return self._send_json(200, services_doc(eng))
            if path == "/v1/fqdn/cache":
                return self._send_json(200, fqdn_doc(eng))
            if path == "/v1/ct":
                return self._send_json(200, ct_doc(
                    eng, int(q.get("limit", 64)),
                    int(q["now"]) if "now" in q else None))
            if path == "/v1/flows/observe":
                from cilium_tpu.observe.observer import parse_filters
                try:
                    allow, deny = parse_filters(q)
                except ValueError as e:
                    return self._send_json(400, {"error": str(e)})
                res = eng.observer.observe(
                    allow, deny,
                    last=int(q.get("last", 0)),
                    since=int(q["since"]) if "since" in q else None,
                    limit=int(q.get("limit", 4096)))
                if q.get("explain") in ("1", "true"):
                    res["legend"] = eng.explain_provenance(res["flows"])
                return self._send_json(200, res)
            if path == "/v1/flows/metrics":
                return self._send_json(200, {
                    "windows": eng.flowmetrics.series(
                        int(q.get("last", 0))),
                    "totals": eng.flowmetrics.totals(),
                })
            if path == "/v1/resources":
                return self._send_json(200, eng.resources())
            if path == "/v1/debug/bundle":
                return self._send_json(200, eng.debug_bundle(
                    clear=q.get("clear") in ("1", "true")))
            if path == "/v1/trace":
                return self._send_json(200, {
                    "stats": eng.tracer.stats(),
                    "summary": eng.tracer.summary(),
                    "spans": eng.tracer.spans(
                        limit=int(q.get("limit", 100)),
                        name=q.get("name")),
                })
            if path == "/v1/flows":
                filters = {}
                if "verdict" in q:
                    filters["verdict"] = q["verdict"]
                if "endpoint" in q:
                    filters["endpoint_id"] = int(q["endpoint"])
                if "since" in q:      # live-follow cursor (seq-based)
                    return self._send_json(200, eng.flowlog.since(
                        int(q["since"]), **filters))
                return self._send_json(200, eng.flowlog.tail(
                    int(q.get("last", 50)), **filters))
            if path == "/v1/metrics":
                return self._send_text(200, eng.render_metrics())
            if path == "/v1/config":
                import dataclasses
                return self._send_json(200, dataclasses.asdict(eng.config))
            if path == "/v1/health":
                return self._send_json(200, eng.health_probe())
            return self._send_json(404, {"error": "no such route"})
        except Exception as exc:   # route errors must not kill the server
            return self._send_json(500, {"error": repr(exc)})

    def do_POST(self):         # noqa: N802
        eng = self.engine
        path, _q = self._route()
        try:
            if path == "/v1/faults":
                # chaos driver: arm/disarm injection points in the LIVE
                # agent ({"spec": "point=mode:..."} / {"disarm": "*"|point})
                body = self._body()
                if "disarm" in body:
                    FAULTS.disarm(None if body["disarm"] in ("*", None)
                                  else body["disarm"])
                    return self._send_json(200, {"ok": True})
                try:
                    n = FAULTS.load_spec(body.get("spec", ""))
                except ValueError as e:
                    return self._send_json(400, {"error": str(e)})
                return self._send_json(200, {"ok": True, "armed": n})
            FAULTS.fire("api.handler")
            if path == "/v1/policy":
                body = self._body()
                rev = eng.apply_policy(body)
                eng.regenerate()
                return self._send_json(200, {"revision": rev})
            if path == "/v1/policy/trace":
                code, doc = trace_doc(eng, self._body())
                return self._send_json(code, doc)
            if path == "/v1/classify":
                code, doc = classify_doc(eng, self._body())
                return self._send_json(code, doc)
            if path == "/v1/regenerate":
                compiled = eng.regenerate(force=True)
                return self._send_json(200, {"revision": compiled.revision})
            return self._send_json(404, {"error": "no such route"})
        except Exception as exc:
            return self._send_json(500, {"error": repr(exc)})

    def do_PATCH(self):        # noqa: N802
        eng = self.engine
        path, _q = self._route()
        try:
            FAULTS.fire("api.handler")
            if path == "/v1/config":
                body = self._body()
                # validate the WHOLE request before mutating anything — a
                # 400 must mean "nothing changed" (enforcement mode is
                # security-critical state)
                unknown = set(body) - {"enforcement_mode"}
                if unknown:
                    return self._send_json(
                        400, {"error": f"not runtime-mutable: "
                                       f"{sorted(unknown)}"})
                mode = body.get("enforcement_mode")
                if mode is not None:
                    if mode not in C.ENFORCEMENT_MODES:
                        return self._send_json(
                            400, {"error": f"bad enforcement mode {mode!r}"})
                    # the runtime-mutable subset (upstream: `cilium config
                    # PolicyEnforcement=...`): change + recompile
                    eng.ctx.enforcement_mode = mode
                    eng.regenerate(force=True)
                return self._send_json(200, {"ok": True})
            return self._send_json(404, {"error": "no such route"})
        except Exception as exc:
            return self._send_json(500, {"error": repr(exc)})


# --------------------------------------------------------------------------- #
# client (used by the CLI's --api/live mode; stdlib http.client over AF_UNIX)
# --------------------------------------------------------------------------- #
class UnixAPIClient:
    def __init__(self, socket_path: str, timeout: float = 10.0):
        self.socket_path = socket_path
        self.timeout = timeout

    def request(self, method: str, path: str, body=None):
        import http.client
        import socket

        class _Conn(http.client.HTTPConnection):
            def __init__(conn, sock_path, timeout):
                super().__init__("localhost", timeout=timeout)
                conn._sock_path = sock_path

            def connect(conn):
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.settimeout(conn.timeout)
                s.connect(conn._sock_path)
                conn.sock = s

        conn = _Conn(self.socket_path, self.timeout)
        payload = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"} if payload else {}
        conn.request(method, path, body=payload, headers=headers)
        resp = conn.getresponse()
        data = resp.read()
        conn.close()
        ctype = resp.headers.get("Content-Type", "")
        if "json" in ctype:
            return resp.status, json.loads(data)
        return resp.status, data.decode()

    def get(self, path: str):
        return self.request("GET", path)

    def post(self, path: str, body=None):
        return self.request("POST", path, body)

    def patch(self, path: str, body=None):
        return self.request("PATCH", path, body)
