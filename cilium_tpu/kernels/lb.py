"""Device LB step: frontend hash-table lookup → Maglev backend select →
DNAT rewrite (the ``bpf/lib/lb.h`` analog, jnp executor of the semantics
defined in compile/lb.py's host mirrors — agreement is test-enforced).

Branch-free: every packet probes the frontend table; misses rewrite nothing.
Three gathers (table, maglev row, backend) + elementwise selects — XLA fuses
the selects into the surrounding classify pipeline.
"""

from __future__ import annotations

import jax.numpy as jnp

from cilium_tpu.kernels.hashing import hash_words_jnp


def lb_step(tensors, batch, probe_depth: int = 8):
    """→ (new_dst [N,4], new_dport [N], rev_nat [N], no_backend [N]).

    ``rev_nat`` is the frontend's STABLE rev-NAT id + 1 (0 = untranslated) —
    ids survive service churn, see compile/lb.LBTables; ``no_backend`` marks
    packets addressed to a frontend whose service has no backends (dropped
    with NO_SERVICE by the caller, upstream DROP_NO_SERVICE).
    """
    dst = batch["dst"]
    keys = jnp.stack([
        dst[:, 0], dst[:, 1], dst[:, 2], dst[:, 3],
        batch["dport"].astype(jnp.uint32), batch["proto"].astype(jnp.uint32),
    ], axis=-1).astype(jnp.uint32)

    tab_keys = tensors["lb_tab_keys"]
    tab_val = tensors["lb_tab_val"]
    cap = tab_keys.shape[0]
    base = (hash_words_jnp(keys) & jnp.uint32(cap - 1)).astype(jnp.int32)
    fe_idx = jnp.full(base.shape, -1, dtype=jnp.int32)
    for d in range(probe_depth):
        s = (base + d) & (cap - 1)
        eq = jnp.all(tab_keys[s] == keys, axis=-1) & (tab_val[s] >= 0)
        fe_idx = jnp.where((fe_idx < 0) & eq, tab_val[s], fe_idx)

    hit = (fe_idx >= 0) & batch["valid"]
    safe_fe = jnp.where(hit, fe_idx, 0)

    src = batch["src"]
    sel_words = jnp.stack([
        src[:, 0], src[:, 1], src[:, 2], src[:, 3],
        dst[:, 0], dst[:, 1], dst[:, 2], dst[:, 3],
        (batch["sport"].astype(jnp.uint32) << jnp.uint32(16))
        | batch["dport"].astype(jnp.uint32),
        batch["proto"].astype(jnp.uint32) << jnp.uint32(8),
    ], axis=-1).astype(jnp.uint32)
    m = tensors["lb_maglev"].shape[1]
    slot = (hash_words_jnp(sel_words) % jnp.uint32(m)).astype(jnp.int32)
    be = tensors["lb_maglev"][tensors["lb_fe_service"][safe_fe], slot]

    no_backend = hit & (be < 0)
    do = hit & (be >= 0)
    safe_be = jnp.where(do, be, 0)
    new_dst = jnp.where(do[:, None], tensors["lb_be_addr"][safe_be], dst)
    new_dport = jnp.where(do, tensors["lb_be_port"][safe_be], batch["dport"])
    rev_nat = jnp.where(do, tensors["lb_fe_rnat_id"][safe_fe] + 1,
                        0).astype(jnp.int32)
    return new_dst, new_dport, rev_nat, no_backend
