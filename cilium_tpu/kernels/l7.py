"""L7-lite tokenized HTTP match (BASELINE config 4; the envoy-bypass path).

Per packet: gather its rule set's tensors and do one vectorized
method+prefix compare across all rules of the set. Set id 0 (no redirect)
vacuously matches.

``l7_match_core`` is the *fusable core* shared verbatim by the XLA
reference and the fused Pallas verdict kernel (kernels/fused.py) — same
jnp ops, two compilation contexts, bit-identity by construction.
"""

from __future__ import annotations

import jax.numpy as jnp

from cilium_tpu.utils import constants as C


def l7_match_core(tensors, set_id, method, path):
    """set_id [N] int32 (0 = none), method [N] int32, path [N,64] uint8
    → matched [N] bool (True for set_id == 0)."""
    sid = jnp.clip(set_id, 0, tensors["l7_methods"].shape[0] - 1)
    m = tensors["l7_methods"][sid].astype(jnp.int32)        # [N,R]
    valid = tensors["l7_valid"][sid].astype(bool)           # [N,R]
    plen = tensors["l7_path_len"][sid]                      # [N,R]
    prefix = tensors["l7_path"][sid]                        # [N,R,64]
    m_ok = (m == C.HTTP_METHOD_ANY) | (m == method[:, None])
    pos = jnp.arange(prefix.shape[-1], dtype=jnp.int32)
    byte_ok = (prefix == path[:, None, :]) | (pos[None, None, :] >= plen[:, :, None])
    p_ok = byte_ok.all(axis=-1)
    any_rule = (valid & m_ok & p_ok).any(axis=-1)
    return jnp.where(set_id <= 0, True, any_rule)


def l7_match_batch(tensors, set_id, method, path):
    """set_id [N] int32 (0 = none), method [N] int32, path [N,64] uint8
    → matched [N] bool (True for set_id == 0)."""
    return l7_match_core(tensors, set_id, method, path)
