"""32-bit mixing hash over conntrack key words.

One definition, two executors: the jnp version runs inside the classify
kernel; the numpy version is the host mirror (checkpoint export/import and
tests). They must agree bit-for-bit — test-enforced on random keys.

The mix is a murmur3-style accumulate + fmix32 finalizer: good avalanche,
cheap on the VPU (shifts/xors/mults on uint32 lanes).
"""

from __future__ import annotations

import numpy as np

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_SEED = 0x9747B28C


def _rotl32(xp, x, r):
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _hash_words_generic(xp, words):
    """words: [..., K] uint32 → [...] uint32."""
    words = words.astype(xp.uint32)
    h = xp.full(words.shape[:-1], _SEED, dtype=xp.uint32)
    for i in range(words.shape[-1]):
        k = words[..., i] * np.uint32(_C1)
        k = _rotl32(xp, k, 15)
        k = k * np.uint32(_C2)
        h = h ^ k
        h = _rotl32(xp, h, 13)
        h = h * np.uint32(5) + np.uint32(0xE6546B64)
    # fmix32
    h = h ^ (h >> np.uint32(16))
    h = h * np.uint32(0x85EBCA6B)
    h = h ^ (h >> np.uint32(13))
    h = h * np.uint32(0xC2B2AE35)
    h = h ^ (h >> np.uint32(16))
    return h


def hash_words_np(words: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        return _hash_words_generic(np, np.asarray(words))


def hash_words_jnp(words):
    import jax.numpy as jnp
    return _hash_words_generic(jnp, words)
