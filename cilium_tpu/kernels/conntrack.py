"""Device conntrack: batched probe, deterministic parallel insert, aggregate
update, epoch sweep (upstream: bpf/lib/conntrack.h + pkg/maps/ctmap GC).

Design (SURVEY.md §7 "Hash tables on TPU"): fixed-capacity open addressing,
``PROBE_DEPTH`` linear probe slots, structure-of-arrays (compile/ct_layout).
All updates follow the *snapshot* batch semantics the oracle defines
(oracle/datapath.py classify_batch_snapshot): verdicts read the batch-start
state; effects are applied as an order-independent aggregate (flag-bit OR via
per-bit scatter-max, counter scatter-adds, expiry recomputed from aggregated
flags). Inserts resolve conflicts deterministically: per probe round, the
lowest packet index wins a free slot (scatter-min claim), duplicates of an
inserted key adopt the entry on the next round's check.

Insert-when-full (the adversarial-load contract, shared bit-for-bit with
oracle.ConntrackTable's bounded mode): a new flow whose probe window holds
no free slot performs ONE tail-eviction round — its victim is the window
slot with the smallest expiry among *evictable* entries (``ct_evictable``:
everything except established TCP — SYN-stage, closing, and non-TCP entries
are fair game, so a SYN flood churns among its own entries while
established flows survive), excluding slots claimed this batch and slots
any packet of this batch probe-hit (snapshot semantics: a slot being
updated by the batch is not evictable by the batch). Ties break to the
earliest probe offset; contested victims go to the lowest packet index.
Flows that still cannot obtain a slot fail the insert: counted
(``insert_fail``) and classified DROP ``CT_FULL`` by the caller — under
table exhaustion tracking fails CLOSED, the one place policy alone cannot
answer (an untracked "established-looking" flow would bypass the ladder
forever).
"""

from __future__ import annotations

import jax.numpy as jnp

from cilium_tpu.compile.ct_layout import PROBE_DEPTH
from cilium_tpu.kernels.hashing import hash_words_jnp
from cilium_tpu.kernels.records import ct_key_words_generic
from cilium_tpu.utils import constants as C


def ct_key_words_jnp(batch, reverse: bool = False):
    return ct_key_words_generic(jnp, batch, reverse)


def reverse_key_words_jnp(fwd_keys):
    """[N,10] forward CT key words → reverse orientation, derived by word
    ops instead of re-normalizing the tuple columns (the device twin of
    parallel/mesh._reverse_key_words): addr blocks swap, the port word
    rotates by 16 (sport<<16|dport → dport<<16|sport), and the direction
    byte flips (0 ↔ 1). Bit-identical to ``ct_key_words_jnp(reverse=True)``
    for any batch whose direction column is 0/1 — which the wire formats
    guarantee (direction rides a single bit)."""
    w8 = fwd_keys[:, 8]
    w9 = fwd_keys[:, 9]
    return jnp.concatenate([
        fwd_keys[:, 4:8], fwd_keys[:, 0:4],
        ((w8 << jnp.uint32(16)) | (w8 >> jnp.uint32(16)))[:, None],
        ((w9 & jnp.uint32(0xFFFFFF00))
         | (jnp.uint32(1) - (w9 & jnp.uint32(0xFF))))[:, None],
    ], axis=-1)


def ct_key_words_pair(batch):
    """→ (fwd_keys, rev_keys), both [N,10] uint32, sharing one pass over
    the tuple columns. ``classify_step`` previously normalized the same
    src/dst/port/proto fields twice (forward + reverse stacks); the reverse
    orientation is a cheap word permutation of the forward words, so the
    jnp fallback path wins this independently of any Pallas fusion."""
    fwd = ct_key_words_jnp(batch, reverse=False)
    return fwd, reverse_key_words_jnp(fwd)


def ct_probe_core(tab_keys, expiry, keys, now,
                  probe_depth: int = PROBE_DEPTH):
    """The fusable probe core over plain arrays (tab_keys [cap,10] uint32,
    expiry [cap] uint32): find each key's live slot → [N] int32 (-1 =
    miss). Shared verbatim by the XLA reference (``ct_probe``) and the
    fused Pallas probe-pair body (kernels/fused.py), which calls it twice
    on VMEM-resident table values — once per orientation — so the bucket
    loads never round-trip through HBM between the probes."""
    cap = expiry.shape[0]
    mask = cap - 1
    base = (hash_words_jnp(keys) & jnp.uint32(mask)).astype(jnp.int32)
    found = jnp.full(base.shape, -1, dtype=jnp.int32)
    for i in range(probe_depth):
        s = (base + i) & mask
        live = expiry[s] > now
        eq = jnp.all(tab_keys[s] == keys, axis=-1) & live
        found = jnp.where((found < 0) & eq, s, found)
    return found


def ct_probe(ct, keys, now, probe_depth: int = PROBE_DEPTH):
    """Find each key's live slot. → slot [N] int32 (-1 = miss)."""
    return ct_probe_core(ct["keys"], ct["expiry"], keys, now, probe_depth)


def _flag_delta(proto, tcp_flags, is_reply):
    """Vectorized mirror of oracle._flag_delta."""
    is_tcp = proto == C.PROTO_TCP
    fin_rst = (tcp_flags & (C.TCP_FIN | C.TCP_RST)) != 0
    rst = (tcp_flags & C.TCP_RST) != 0
    non_syn = (tcp_flags & C.TCP_SYN) == 0
    close_self = jnp.where(is_reply, C.CT_FLAG_RX_CLOSING, C.CT_FLAG_TX_CLOSING)
    delta = jnp.where(fin_rst, close_self, 0)
    delta = jnp.where(rst, delta | C.CT_FLAG_TX_CLOSING | C.CT_FLAG_RX_CLOSING,
                      delta)
    delta = jnp.where(non_syn, delta | C.CT_FLAG_SEEN_NON_SYN, delta)
    return jnp.where(is_tcp, delta, 0).astype(jnp.uint32)


def _lifetime(proto, flags):
    """Vectorized mirror of oracle lifetime rules. proto [M], flags [M]."""
    is_tcp = proto == C.PROTO_TCP
    closing = (flags & (C.CT_FLAG_TX_CLOSING | C.CT_FLAG_RX_CLOSING)) != 0
    non_syn = (flags & C.CT_FLAG_SEEN_NON_SYN) != 0
    tcp_life = jnp.where(closing, C.CT_LIFETIME_CLOSE,
                         jnp.where(non_syn, C.CT_LIFETIME_TCP,
                                   C.CT_LIFETIME_SYN))
    return jnp.where(is_tcp, tcp_life, C.CT_LIFETIME_NONTCP).astype(jnp.uint32)


def ct_evictable(slot_proto, flags):
    """Which live entries an exhausted insert may tail-evict: everything
    whose current lifetime class is NOT the established-TCP one — i.e.
    TCP entries still in the handshake (no SEEN_NON_SYN) or closing, and
    all non-TCP entries. One predicate, three executors (this jnp form,
    the oracle's ``_ct_expirable``, and — by shared-core construction —
    the fused path), so the protected class can never drift."""
    is_tcp = slot_proto == C.PROTO_TCP
    non_syn = (flags & jnp.uint32(C.CT_FLAG_SEEN_NON_SYN)) != 0
    closing = (flags & jnp.uint32(C.CT_FLAG_TX_CLOSING
                                  | C.CT_FLAG_RX_CLOSING)) != 0
    return ~(is_tcp & non_syn & ~closing)


def ct_insert_new(ct, keys, want_insert, now,
                  probe_depth: int = PROBE_DEPTH,
                  evict: bool = False, protected=None):
    """Deterministic parallel insert of new flows.

    Returns (new_keys, new_created, zero_mask, slot_of, fail, n_evicted):
    - ``zero_mask`` [cap] marks freshly-claimed slots whose value arrays
      (flags/counters) must be reset before aggregation;
    - ``slot_of`` [N] is the entry slot for every packet whose flow now has
      one (winner or adopted duplicate), else -1;
    - ``fail`` [N] marks flows that exhausted their probe window (with
      ``evict``: even after the eviction round);
    - ``n_evicted`` uint32 scalar: live entries tail-evicted this batch.

    ``evict`` arms the insert-when-full tail eviction (module docstring);
    ``protected`` [cap] bool marks slots the batch probe-hit (never
    evicted — snapshot semantics demand a slot being updated by this batch
    stays this batch's)."""
    cap = ct["expiry"].shape[0]
    mask = cap - 1
    n = keys.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    base = (hash_words_jnp(keys) & jnp.uint32(mask)).astype(jnp.int32)

    keys_arr = ct["keys"]
    created_arr = ct["created"]
    claimed = jnp.zeros((cap,), dtype=bool)
    zero_mask = jnp.zeros((cap,), dtype=bool)
    slot_of = jnp.full((n,), -1, dtype=jnp.int32)
    pending = want_insert

    for r in range(probe_depth):
        if r > 0:
            # adoption: my previous round's target may now hold my key,
            # inserted by a lower-indexed duplicate of my flow
            sprev = (base + (r - 1)) & mask
            adopted = (pending & claimed[sprev]
                       & jnp.all(keys_arr[sprev] == keys, axis=-1))
            slot_of = jnp.where(adopted, sprev, slot_of)
            pending = pending & ~adopted
        s = (base + r) & mask
        free = (ct["expiry"][s] <= now) & ~claimed[s]
        attempt = pending & free
        # lowest packet index wins each contested slot
        scat = jnp.where(attempt, s, cap)
        claim = jnp.full((cap + 1,), n, dtype=jnp.int32).at[scat].min(idx)
        winner = attempt & (claim[s] == idx)
        ws = jnp.where(winner, s, cap)
        keys_arr = keys_arr.at[ws].set(keys, mode="drop")
        created_arr = created_arr.at[ws].set(now, mode="drop")
        claimed = claimed.at[ws].set(True, mode="drop")
        zero_mask = zero_mask.at[ws].set(True, mode="drop")
        slot_of = jnp.where(winner, s, slot_of)
        pending = pending & ~winner

    # final adoption sweep: stragglers whose duplicate won at a slot they
    # already passed
    for r in range(probe_depth):
        s = (base + r) & mask
        adopted = (pending & claimed[s]
                   & jnp.all(keys_arr[s] == keys, axis=-1))
        slot_of = jnp.where(adopted, s, slot_of)
        pending = pending & ~adopted

    n_evicted = jnp.uint32(0)
    if evict:
        # tail-eviction round (batch-start state throughout): victim =
        # the window slot with the smallest expiry among live, evictable,
        # unclaimed, unprotected entries; ties break to the earliest probe
        # offset (strict <), contested victims to the lowest packet index
        exp0 = ct["expiry"]
        slot_proto = (ct["keys"][:, 9] >> jnp.uint32(8)).astype(jnp.int32)
        candidate = (exp0 > now) & ct_evictable(slot_proto, ct["flags"])
        if protected is not None:
            candidate = candidate & ~protected
        best_s = jnp.full((n,), -1, dtype=jnp.int32)
        best_e = jnp.full((n,), 0xFFFFFFFF, dtype=jnp.uint32)
        for r in range(probe_depth):
            s = (base + r) & mask
            cand = pending & candidate[s] & ~claimed[s]
            e = exp0[s]
            better = cand & ((best_s < 0) | (e < best_e))
            best_s = jnp.where(better, s, best_s)
            best_e = jnp.where(better, e, best_e)
        attempt = pending & (best_s >= 0)
        scat = jnp.where(attempt, best_s, cap)
        claim = jnp.full((cap + 1,), n, dtype=jnp.int32).at[scat].min(idx)
        bs = jnp.where(best_s >= 0, best_s, 0)
        winner = attempt & (claim[bs] == idx)
        ws = jnp.where(winner, best_s, cap)
        keys_arr = keys_arr.at[ws].set(keys, mode="drop")
        created_arr = created_arr.at[ws].set(now, mode="drop")
        claimed = claimed.at[ws].set(True, mode="drop")
        zero_mask = zero_mask.at[ws].set(True, mode="drop")
        slot_of = jnp.where(winner, best_s, slot_of)
        pending = pending & ~winner
        n_evicted = winner.sum().astype(jnp.uint32)
        # adoption: duplicates of an evict-winner's key ride its new slot
        for r in range(probe_depth):
            s = (base + r) & mask
            adopted = (pending & claimed[s]
                       & jnp.all(keys_arr[s] == keys, axis=-1))
            slot_of = jnp.where(adopted, s, slot_of)
            pending = pending & ~adopted

    return keys_arr, created_arr, zero_mask, slot_of, pending, n_evicted


def ct_apply(ct, batch, slot, is_reply, contrib, now,
             new_keys=None, new_created=None, zero_mask=None,
             rev_nat_vals=None):
    """Aggregate all allowed packets' effects into the table (snapshot
    semantics). ``slot`` [N] (-1 = none), ``contrib`` [N] bool.
    ``rev_nat_vals`` [N] int32: per-packet rev-NAT id to record for freshly
    created entries (0 = none; duplicates of one flow carry the same value,
    so the scatter-max is deterministic).

    Returns the new ct pytree.
    """
    cap = ct["expiry"].shape[0]
    keys_arr = new_keys if new_keys is not None else ct["keys"]
    created_arr = new_created if new_created is not None else ct["created"]
    flags = ct["flags"]
    fwd = ct["pkts_fwd"]
    rev = ct["pkts_rev"]
    rnat = ct["rev_nat"]
    if zero_mask is not None:
        zero32 = jnp.uint32(0)
        flags = jnp.where(zero_mask, zero32, flags)
        fwd = jnp.where(zero_mask, zero32, fwd)
        rev = jnp.where(zero_mask, zero32, rev)
        rnat = jnp.where(zero_mask, zero32, rnat)
    if rev_nat_vals is not None and zero_mask is not None:
        # only freshly created entries record a rev-NAT id (create-time
        # semantics, like upstream ct_create4's rev_nat_index)
        fresh = contrib & (slot >= 0) & zero_mask[jnp.where(slot >= 0, slot, 0)]
        rnat = rnat.at[jnp.where(fresh, slot, cap)].max(
            rev_nat_vals.astype(jnp.uint32), mode="drop")

    scat = jnp.where(contrib, slot, cap)  # OOB → dropped
    delta = _flag_delta(batch["proto"], batch["tcp_flags"], is_reply)
    # OR-accumulate flag bits: scatter-max each bit plane separately (a max
    # on the full word would clobber unrelated bits), then recombine
    acc = jnp.zeros_like(flags)
    for bit in (C.CT_FLAG_SEEN_NON_SYN, C.CT_FLAG_TX_CLOSING,
                C.CT_FLAG_RX_CLOSING):
        plane = flags & jnp.uint32(bit)
        has = ((delta & jnp.uint32(bit)) != 0).astype(jnp.uint32) * jnp.uint32(bit)
        plane = plane.at[scat].max(has, mode="drop")
        acc = acc | plane
    flags = acc
    one = jnp.ones_like(scat, dtype=jnp.uint32)
    fwd = fwd.at[jnp.where(contrib & ~is_reply, slot, cap)].add(one, mode="drop")
    rev = rev.at[jnp.where(contrib & is_reply, slot, cap)].add(one, mode="drop")

    touched = jnp.zeros((cap,), dtype=bool).at[scat].set(True, mode="drop")
    slot_proto = (keys_arr[:, 9] >> jnp.uint32(8)).astype(jnp.int32)
    new_expiry = now + _lifetime(slot_proto, flags)
    expiry = jnp.where(touched, new_expiry, ct["expiry"])

    return {
        "keys": keys_arr,
        "expiry": expiry,
        "created": created_arr,
        "flags": flags,
        "pkts_fwd": fwd,
        "pkts_rev": rev,
        "rev_nat": rnat,
    }


def _sweep_mask(ct, dead):
    """Clear every entry under ``dead`` [cap] bool → new ct pytree (shared
    by the whole-table sweep and the chunked epoch sweep)."""
    zero32 = jnp.uint32(0)
    new_ct = dict(ct)
    new_ct["expiry"] = jnp.where(dead, zero32, ct["expiry"])
    new_ct["keys"] = jnp.where(dead[:, None], zero32, ct["keys"])
    new_ct["flags"] = jnp.where(dead, zero32, ct["flags"])
    new_ct["pkts_fwd"] = jnp.where(dead, zero32, ct["pkts_fwd"])
    new_ct["pkts_rev"] = jnp.where(dead, zero32, ct["pkts_rev"])
    new_ct["created"] = jnp.where(dead, zero32, ct["created"])
    new_ct["rev_nat"] = jnp.where(dead, zero32, ct["rev_nat"])
    return new_ct


def ct_sweep(ct, now):
    """Epoch GC: clear expired entries (upstream ctmap GC — SURVEY.md §2
    "Pipelined device-side epoch sweep"). Returns (new_ct, n_reclaimed)."""
    dead = (ct["expiry"] <= now) & (ct["expiry"] != 0)
    return _sweep_mask(ct, dead), dead.sum()


def ct_sweep_chunk(ct, now, start, chunk_rows: int, count_now=None):
    """One chunk of the overlapped device-side epoch sweep: clear expired
    entries whose slot lies in ``[start, start + chunk_rows)`` (mod cap —
    the window wraps so a cursor can advance forever) and count the whole
    table's live occupancy in the same program.

    ``count_now`` (default: ``now``) is the clock the occupancy count
    uses. Emergency GC sweeps with a slashed clock (``now`` pushed into
    the future so short-TTL entries die early) but must keep MEASURING
    with the real clock — a slashed count would exclude genuinely-live
    entries the sweep has not reached, read artificially low, and flap
    the pressure latch's exit hysteresis.

    ``chunk_rows`` is trace-time static; ``start`` is traced, so one jitted
    program serves every cursor position. Semantics-free by construction:
    probes and inserts already treat ``expiry <= now`` slots as
    dead/claimable, so *when* a slot is physically cleared can never change
    a verdict — which is exactly what lets the GC overlap live classify
    steps instead of stopping the world.

    Returns (new_ct, n_reclaimed [uint32 scalar], n_live [uint32 scalar]).
    Both scalars are device values: the caller is expected NOT to block on
    them in the enqueue path (the double-buffered harvest reads them a tick
    later, when they are long since resolved)."""
    cap = ct["expiry"].shape[0]
    idx = jnp.arange(cap, dtype=jnp.uint32)
    off = (idx - start.astype(jnp.uint32)) % jnp.uint32(cap)
    in_win = off < jnp.uint32(min(chunk_rows, cap))
    expiry = ct["expiry"]
    dead = in_win & (expiry <= now) & (expiry != 0)
    live = (expiry > (now if count_now is None else count_now)) \
        .sum().astype(jnp.uint32)
    return _sweep_mask(ct, dead), dead.sum().astype(jnp.uint32), live
