"""Pallas TPU megakernels for the classify interior (ROADMAP item 2:
"close the compute ceilings with fused Pallas kernels").

Why: BENCH_r05's ``compute_only`` split shows cfg3 (lpm_heavy) at 209M
flows/s/chip against cfg2's 380M and cfg4 (l7_lite) paying p99 3.8x p50 —
the ``datapath.compute`` span attribution (PR 3) pins the gap on the
unfused LPM gather chain (4-level v4 / 16-level v6, each level a separate
XLA gather materializing [N] node/best intermediates in HBM), the policy
ladder's gather→select→gather round trips, and the double CT probe. SURVEY
§7 step 4 prescribes "jnp-first, Pallas only where fusion wins are proven"
— these are the proven sites.

Three kernels, each wrapping a *shared core* (the same jnp function the
reference path executes — kernels/lpm.lpm_walk_core,
kernels/conntrack.ct_probe_core, kernels/classify.classify_interior_core):

- ``lpm_lookup_fused``: the whole stride walk in one grid kernel. The node
  tables are kernel-resident; ``node``/``best`` stay in registers across
  all 4 (v4) / 16 (v6) levels and both families resolve in one launch —
  no [N] intermediates ever reach HBM.
- ``ct_probe_pair_fused``: forward and reverse probes share one residency
  of the CT key/expiry tables; both orientations' bucket loads and key
  compares run in a single kernel emitting ``(fwd_slot, rev_slot)``.
- ``policy_verdict_fused``: policy ladder gathers + L7 token matcher +
  verdict composition — ``decision → l7_cell → l7_match → allow/reason``
  never round-trips through HBM.

Because the kernel bodies call the *same* core functions the jnp reference
path runs, bit-identity between executors holds by construction; the
parity/fuzz suites (tests/test_fused.py) and the shadow-oracle auditor
(observe/audit.py, PR 7) enforce it continuously anyway. On CPU the
kernels run under ``interpret=True`` (the Pallas interpreter evaluates the
same jnp ops), which is how tier-1 CI pins the fused path without TPU
hardware.

Geometry gates: a stage only fuses when its tables fit the kernel-resident
budget (``fuse_plan``) — a 1M-entry CT table or a BGP-scale trie stays on
the XLA reference, which is semantically identical. The budget is
trace-time static (array shapes), so the plan can never flap per batch.
"""

from __future__ import annotations

import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cilium_tpu.kernels.classify import classify_interior_core
from cilium_tpu.kernels.conntrack import ct_probe_core
from cilium_tpu.kernels.lpm import lpm_walk_prov_core

#: per-stage kernel-resident table budget (bytes). ~VMEM-scale by default;
#: raise on hardware with the headroom, lower to force the jnp reference.
FUSED_TABLE_BYTES = int(os.environ.get(
    "CILIUM_TPU_FUSED_TABLE_BYTES", str(12 << 20)))

#: row-block size for the kernel grids (pow2; batches whose row count
#: divides evenly grid over blocks, anything else runs one block)
ROW_BLOCK = 1024

#: the snapshot tensors the verdict kernel keeps resident
POLICY_TENSOR_KEYS = ("id_class_of", "proto_family", "port_class",
                      "verdict", "enforced", "l7_methods", "l7_valid",
                      "l7_path_len", "l7_path")


class FusePlan(NamedTuple):
    """Per-stage fuse decision for one (snapshot, ct) geometry — computed
    from static shapes at trace time, so the executor choice is a property
    of the compiled program, never of batch contents."""
    lpm: bool
    ct: bool
    policy: bool

    @property
    def any(self) -> bool:
        return self.lpm or self.ct or self.policy


def _nbytes(a) -> int:
    return int(a.size) * a.dtype.itemsize


def fuse_plan(tensors, ct, v4_only: bool = False, rule_axis=None,
              budget: int = 0) -> FusePlan:
    """Which stages of this geometry fit the fused kernels. ``rule_axis``
    disables the verdict kernel (the rule-sharded ladder needs a psum that
    must stay in the surrounding shard_map body)."""
    budget = budget or FUSED_TABLE_BYTES
    lpm_bytes = _nbytes(tensors["lpm_v4"]) \
        + (0 if v4_only else _nbytes(tensors["lpm_v6"]))
    ct_bytes = _nbytes(ct["keys"]) + _nbytes(ct["expiry"])
    policy_bytes = sum(_nbytes(tensors[k]) for k in POLICY_TENSOR_KEYS)
    return FusePlan(
        lpm=lpm_bytes <= budget,
        ct=ct_bytes <= budget,
        policy=rule_axis is None and policy_bytes <= budget,
    )


def _row_grid(n: int):
    """(block_rows, n_blocks): grid over ROW_BLOCK-row blocks when the
    batch divides evenly (the pow2 serving shapes), else one block."""
    if n > ROW_BLOCK and n % ROW_BLOCK == 0:
        return ROW_BLOCK, n // ROW_BLOCK
    return n, 1


def _full(shape):
    """BlockSpec for a kernel-resident table: every grid step sees the
    whole array (block index 0 on every axis)."""
    nd = len(shape)
    return pl.BlockSpec(shape, lambda i, _nd=nd: (0,) * _nd)


def _rows(blk, trailing=()):
    """BlockSpec for a per-row array blocked along axis 0."""
    shape = (blk,) + tuple(trailing)
    pad = (0,) * len(trailing)
    return pl.BlockSpec(shape, lambda i, _p=pad: (i,) + _p)


def _smem_scalar():
    return pl.BlockSpec((1,), lambda i: (0,), memory_space=pltpu.SMEM)


# --------------------------------------------------------------------------- #
# (a) LPM stride walk
# --------------------------------------------------------------------------- #
def lpm_lookup_fused(lpm_v4, lpm_v6, addr_words, is_v6, default_index,
                     v4_only: bool = False, interpret: bool = False):
    """One grid kernel over row blocks: both families' stride walks with
    ``node``/``best``/``best_meta`` held in registers (see
    lpm.lpm_walk_prov_core — the same function the jnp reference runs) →
    (identity index [N] int32, packed lpm_prefix provenance [N] int32).
    ``default_index`` may be a traced scalar (it is the snapshot's world
    index); it rides in SMEM."""
    n = addr_words.shape[0]
    blk, grid = _row_grid(n)

    if v4_only:
        def kernel(default_ref, v4_ref, addr_ref, idx_ref, meta_ref):
            idx, meta = lpm_walk_prov_core(
                v4_ref[...], None, addr_ref[...], None, default_ref[0],
                v4_only=True)
            idx_ref[...] = idx
            meta_ref[...] = meta
        in_specs = [_smem_scalar(), _full(lpm_v4.shape), _rows(blk, (4,))]
        args = (jnp.asarray(default_index, jnp.int32).reshape(1),
                lpm_v4, addr_words)
    else:
        def kernel(default_ref, v4_ref, v6_ref, addr_ref, isv6_ref,
                   idx_ref, meta_ref):
            idx, meta = lpm_walk_prov_core(
                v4_ref[...], v6_ref[...], addr_ref[...], isv6_ref[...],
                default_ref[0], v4_only=False)
            idx_ref[...] = idx
            meta_ref[...] = meta
        in_specs = [_smem_scalar(), _full(lpm_v4.shape), _full(lpm_v6.shape),
                    _rows(blk, (4,)), _rows(blk)]
        args = (jnp.asarray(default_index, jnp.int32).reshape(1),
                lpm_v4, lpm_v6, addr_words, is_v6.astype(jnp.int32))

    out = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=[_rows(blk), _rows(blk)],
        out_shape=[jax.ShapeDtypeStruct((n,), jnp.int32),
                   jax.ShapeDtypeStruct((n,), jnp.int32)],
        interpret=interpret,
    )(*args)
    return out[0], out[1]


# --------------------------------------------------------------------------- #
# (b) fused CT probe pair
# --------------------------------------------------------------------------- #
def ct_probe_pair_fused(ct, fwd_keys, rev_keys, now, probe_depth: int,
                        interpret: bool = False):
    """Forward + reverse probes over one residency of the CT key/expiry
    tables → (fwd_slot, rev_slot), each [N] int32 (-1 = miss). The probe
    loop is conntrack.ct_probe_core — identical to the reference."""
    n = fwd_keys.shape[0]
    blk, grid = _row_grid(n)
    tab_keys, expiry = ct["keys"], ct["expiry"]

    def kernel(now_ref, tab_ref, exp_ref, fwd_ref, rev_ref,
               fwd_out, rev_out):
        tab = tab_ref[...]
        exp = exp_ref[...]
        now_s = now_ref[0]
        fwd_out[...] = ct_probe_core(tab, exp, fwd_ref[...], now_s,
                                     probe_depth)
        rev_out[...] = ct_probe_core(tab, exp, rev_ref[...], now_s,
                                     probe_depth)

    out = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[_smem_scalar(), _full(tab_keys.shape), _full(expiry.shape),
                  _rows(blk, (10,)), _rows(blk, (10,))],
        out_specs=[_rows(blk), _rows(blk)],
        out_shape=[jax.ShapeDtypeStruct((n,), jnp.int32),
                   jax.ShapeDtypeStruct((n,), jnp.int32)],
        interpret=interpret,
    )(jnp.asarray(now, jnp.uint32).reshape(1), tab_keys, expiry,
      fwd_keys, rev_keys)
    return out[0], out[1]


# --------------------------------------------------------------------------- #
# (c) policy ladder + L7 matcher + verdict composition
# --------------------------------------------------------------------------- #
def policy_verdict_fused(tensors, ep_slot, direction, id_idx, proto, dport,
                         http_method, http_path, est, reply, valid,
                         interpret: bool = False):
    """Steps 3-5 of classify_step in one kernel (the body IS
    classify.classify_interior_core over VMEM-resident tables) →
    (allow [N] bool, reason [N] int32, status [N] int32,
    redirect [N] bool, matched_rule [N] int32)."""
    n = valid.shape[0]
    blk, grid = _row_grid(n)
    # bool tables ride as uint8 (TPU-friendly); the core casts back — the
    # reference path sees real bools either way, so this is bit-neutral
    tabs = {
        "id_class_of": tensors["id_class_of"],
        "proto_family": tensors["proto_family"],
        "port_class": tensors["port_class"],
        "verdict": tensors["verdict"],
        "enforced": tensors["enforced"].astype(jnp.uint8),
        "l7_methods": tensors["l7_methods"],
        "l7_valid": tensors["l7_valid"].astype(jnp.uint8),
        "l7_path_len": tensors["l7_path_len"],
        "l7_path": tensors["l7_path"],
    }
    tab_names = tuple(tabs)

    def kernel(*refs):
        row_refs = refs[:10]
        tab_refs = refs[10:10 + len(tab_names)]
        (allow_ref, reason_ref, status_ref, redirect_ref,
         mrule_ref) = refs[10 + len(tab_names):]
        t = {name: ref[...] for name, ref in zip(tab_names, tab_refs)}
        t["enforced"] = t["enforced"].astype(bool)
        t["l7_valid"] = t["l7_valid"].astype(bool)
        (ep_r, dir_r, id_r, proto_r, dport_r, meth_r, path_r, est_r,
         reply_r, valid_r) = row_refs
        allow, reason, status, redirect, mrule = classify_interior_core(
            t, ep_r[...], dir_r[...], id_r[...], proto_r[...], dport_r[...],
            meth_r[...], path_r[...], est_r[...].astype(bool),
            reply_r[...].astype(bool), valid_r[...].astype(bool))
        allow_ref[...] = allow.astype(jnp.int32)
        reason_ref[...] = reason
        status_ref[...] = status
        redirect_ref[...] = redirect.astype(jnp.int32)
        mrule_ref[...] = mrule

    # row-arg order matches the kernel's unpacking above: ep, dir, id,
    # proto, dport, method, path, est, reply, valid
    row_args = (ep_slot, direction, id_idx, proto, dport, http_method,
                http_path, est.astype(jnp.int32), reply.astype(jnp.int32),
                valid.astype(jnp.int32))
    row_specs = [_rows(blk)] * 6 + [_rows(blk, (http_path.shape[1],))] \
        + [_rows(blk)] * 3
    tab_specs = [_full(tabs[k].shape) for k in tab_names]

    allow, reason, status, redirect, mrule = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=row_specs + tab_specs,
        out_specs=[_rows(blk)] * 5,
        out_shape=[jax.ShapeDtypeStruct((n,), jnp.int32)] * 5,
        interpret=interpret,
    )(*row_args, *(tabs[k] for k in tab_names))
    return allow.astype(bool), reason, status, redirect.astype(bool), mrule
