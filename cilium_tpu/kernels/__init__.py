"""Batched JAX datapath kernels (analog of upstream ``bpf/`` — SURVEY.md §2
native checklist item 1: "JAX/Pallas TPU kernels (LPM lookup, policy match,
conntrack probe, L7-lite token match) — device-native, not Python loops").

Everything here is shape-static, branch-free (masked select instead of
data-dependent control flow), and jit-compiled once per snapshot geometry.
"""
