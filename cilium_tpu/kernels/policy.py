"""Dense policy lookup: the whole wildcard ladder in three gathers
(upstream: bpf/lib/policy.h policy_can_access's 6-lookup ladder, resolved at
compile time by compile/policy_image.py)."""

from __future__ import annotations

import jax.numpy as jnp

from cilium_tpu.utils import constants as C


def policy_lookup_batch(tensors, ep_slot, direction, id_index, proto, dport,
                        rule_axis=None):
    """→ (decision [N] int32, l7_id [N] int32, enforced [N] bool).

    ``rule_axis``: name of a mesh axis over which the verdict tensor's
    id-class rows are sharded (the "tensor parallelism over rule space" of
    SURVEY.md §2's parallelism table). Each shard gathers rows it owns and a
    psum combines — one XLA collective, no gather of remote rows. Rows must
    be padded to a multiple of the axis size (compile/parallel handles it).
    """
    id_cls = tensors["id_class_of"][id_index]
    fam = tensors["proto_family"][jnp.clip(proto, 0, 255)]
    pcls = tensors["port_class"][fam, jnp.clip(dport, 0, 65535)]
    if rule_axis is None:
        cell = tensors["verdict"][ep_slot, direction, id_cls, pcls].astype(jnp.int32)
    else:
        import jax
        rows_local = tensors["verdict"].shape[2]
        ri = jax.lax.axis_index(rule_axis)
        local_idx = id_cls - ri * rows_local
        in_range = (local_idx >= 0) & (local_idx < rows_local)
        safe = jnp.clip(local_idx, 0, rows_local - 1)
        cell_local = jnp.where(
            in_range,
            tensors["verdict"][ep_slot, direction, safe, pcls].astype(jnp.int32),
            0)
        cell = jax.lax.psum(cell_local, rule_axis)
    enforced = tensors["enforced"][ep_slot, direction]
    decision = cell & C.VERDICT_DECISION_MASK
    l7_id = cell >> C.VERDICT_L7_SHIFT
    return decision, l7_id, enforced
