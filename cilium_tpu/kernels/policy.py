"""Dense policy lookup: the whole wildcard ladder in three gathers
(upstream: bpf/lib/policy.h policy_can_access's 6-lookup ladder, resolved at
compile time by compile/policy_image.py).

``policy_core`` is the *fusable core*: pure jnp over the snapshot's tensor
dict, shared verbatim by the XLA reference and the fused Pallas verdict
kernel (kernels/fused.py). Every gather is explicitly clipped then
flattened to a single-axis take — the clip reproduces jax's out-of-bounds
clamp semantics exactly (so garbage rows cannot diverge between the two
executors) and the flat form is the one gather shape Mosaic lowers.

Besides the cell, the lookup emits ``matched_rule``: the (id_class,
port_class) coordinate of the resolved verdict cell, packed
``id_cls * n_port_classes + port_cls`` — layout-independent (never an index
into the possibly rule-shard-padded image), identical across the jnp
reference, the fused kernel, the rule-sharded mesh and the host oracle by
construction. Callers mask it to -1 where no ladder ran (invalid row or
unenforced direction); together with the endpoint slot and direction it
names the exact policy-map row that decided the verdict (the flowlog /
observer provenance of ISSUE 11).
"""

from __future__ import annotations

import jax.numpy as jnp

from cilium_tpu.utils import constants as C


def policy_core(tensors, ep_slot, direction, id_index, proto, dport):
    """→ (decision [N] int32, l7_id [N] int32, enforced [N] bool,
    matched_rule [N] int32 — unmasked cell coordinate) against the dense
    (un-sharded) verdict image."""
    n_ids = tensors["id_class_of"].shape[0]
    id_cls = tensors["id_class_of"][jnp.clip(id_index, 0, n_ids - 1)]
    fam = tensors["proto_family"][jnp.clip(proto, 0, 255)]
    n_ports = tensors["port_class"].shape[1]
    pcls = tensors["port_class"].reshape(-1)[
        fam * n_ports + jnp.clip(dport, 0, n_ports - 1)]
    v = tensors["verdict"]
    n_eps, _, n_rows, n_cols = v.shape
    ep = jnp.clip(ep_slot, 0, n_eps - 1)
    d = jnp.clip(direction, 0, 1)
    cls = jnp.clip(id_cls, 0, n_rows - 1)
    pc = jnp.clip(pcls, 0, n_cols - 1)
    cell = v.reshape(-1)[((ep * 2 + d) * n_rows + cls) * n_cols
                         + pc].astype(jnp.int32)
    enforced = tensors["enforced"].reshape(-1)[ep * 2 + d].astype(bool)
    decision = cell & C.VERDICT_DECISION_MASK
    l7_id = cell >> C.VERDICT_L7_SHIFT
    matched_rule = (id_cls * n_cols + pcls).astype(jnp.int32)
    return decision, l7_id, enforced, matched_rule


def policy_lookup_batch(tensors, ep_slot, direction, id_index, proto, dport,
                        rule_axis=None):
    """→ (decision [N] int32, l7_id [N] int32, enforced [N] bool,
    matched_rule [N] int32).

    ``rule_axis``: name of a mesh axis over which the verdict tensor's
    id-class rows are sharded (the "tensor parallelism over rule space" of
    SURVEY.md §2's parallelism table). Each shard gathers rows it owns and a
    psum combines — one XLA collective, no gather of remote rows. Rows must
    be padded to a multiple of the axis size (compile/parallel handles it).
    ``matched_rule`` uses the GLOBAL id class (id_class_of is replicated),
    so its value is identical on every shard and to the un-sharded path —
    no collective needed.
    """
    if rule_axis is None:
        return policy_core(tensors, ep_slot, direction, id_index, proto,
                           dport)
    import jax
    id_cls = tensors["id_class_of"][id_index]
    fam = tensors["proto_family"][jnp.clip(proto, 0, 255)]
    pcls = tensors["port_class"][fam, jnp.clip(dport, 0, 65535)]
    rows_local = tensors["verdict"].shape[2]
    n_cols = tensors["verdict"].shape[3]
    ri = jax.lax.axis_index(rule_axis)
    local_idx = id_cls - ri * rows_local
    in_range = (local_idx >= 0) & (local_idx < rows_local)
    safe = jnp.clip(local_idx, 0, rows_local - 1)
    cell_local = jnp.where(
        in_range,
        tensors["verdict"][ep_slot, direction, safe, pcls].astype(jnp.int32),
        0)
    cell = jax.lax.psum(cell_local, rule_axis)
    enforced = tensors["enforced"][ep_slot, direction]
    decision = cell & C.VERDICT_DECISION_MASK
    l7_id = cell >> C.VERDICT_L7_SHIFT
    matched_rule = (id_cls * n_cols + pcls).astype(jnp.int32)
    return decision, l7_id, enforced, matched_rule
