"""Dense policy lookup: the whole wildcard ladder in three gathers
(upstream: bpf/lib/policy.h policy_can_access's 6-lookup ladder, resolved at
compile time by compile/policy_image.py)."""

from __future__ import annotations

import jax.numpy as jnp

from cilium_tpu.utils import constants as C


def policy_lookup_batch(tensors, ep_slot, direction, id_index, proto, dport):
    """→ (decision [N] int32, l7_id [N] int32, enforced [N] bool)."""
    id_cls = tensors["id_class_of"][id_index]
    fam = tensors["proto_family"][jnp.clip(proto, 0, 255)]
    pcls = tensors["port_class"][fam, jnp.clip(dport, 0, 65535)]
    cell = tensors["verdict"][ep_slot, direction, id_cls, pcls].astype(jnp.int32)
    enforced = tensors["enforced"][ep_slot, direction]
    decision = cell & C.VERDICT_DECISION_MASK
    l7_id = cell >> C.VERDICT_L7_SHIFT
    return decision, l7_id, enforced
