"""Batched LPM trie walk (the device half of compile/lpm.py).

Fixed-depth gather chain, no data-dependent control flow: dead paths idle in
the sentinel node. A mixed-family batch walks both tries and selects by the
family bit (mirroring upstream's two LPM maps); ``v4_only=True`` (static)
skips the 16-level v6 walk for pure-IPv4 workloads (BASELINE config 1).

``lpm_walk_prov_core`` is the *fusable core*: pure jnp over plain arrays, so
the exact same function executes (a) as the XLA reference here and (b) inside
the Pallas megakernel body (kernels/fused.py) over values read from refs —
bit-identity between the two paths holds by construction, not by test luck.
It returns BOTH the identity index and the packed match provenance
``(prefix_slot << 8) | plen`` carried in the trie's third plane
(compile/lpm.py): the walk that resolves the identity IS the walk that names
the winning prefix, so the two can never disagree. ``lpm_walk_core`` /
``lpm_lookup_batch`` keep the index-only contract for callers that do not
need provenance.

The per-level gather is flattened to a single-axis ``take`` (node*256+byte)
so the Mosaic lowering sees one supported gather per level instead of a 3-D
fancy index; in-range indices make it bit-identical to the 3-D form (node is
always a real node or the dead sentinel, byte is masked to 0..255).
"""

from __future__ import annotations

import jax.numpy as jnp

from cilium_tpu.compile.lpm import V4_LEVELS, V6_LEVELS


def _walk(nodes, addr_words, byte_index, levels, default_index):
    """nodes [n,256,3] int32; addr_words [N,4] uint32; byte_index(l) gives the
    byte position 0..15 in the 16-byte address for level l. ``node``,
    ``best`` and ``best_meta`` live in registers across the whole chain —
    nothing but the node-triple gather touches memory per level. Returns
    (best identity index [N], best packed provenance [N], -1 on miss)."""
    n_nodes = nodes.shape[0]
    dead = n_nodes - 1
    n = addr_words.shape[0]
    flat = nodes.reshape(-1, 3)
    node = jnp.zeros((n,), dtype=jnp.int32)
    # default_index may be a traced scalar (snapshot-dependent) — broadcast,
    # don't bake
    best = jnp.broadcast_to(jnp.asarray(default_index, jnp.int32), (n,))
    best_meta = jnp.full((n,), -1, dtype=jnp.int32)
    for level in range(levels):
        pos = byte_index(level)
        word = addr_words[:, pos // 4]
        b = ((word >> jnp.uint32(8 * (3 - pos % 4))) & jnp.uint32(0xFF)
             ).astype(jnp.int32)
        triple = flat[node * 256 + b]             # [N, 3]
        child, value, meta = triple[:, 0], triple[:, 1], triple[:, 2]
        hit = value >= 0
        best = jnp.where(hit, value, best)
        best_meta = jnp.where(hit, meta, best_meta)
        node = jnp.where(child >= 0, child, dead)
    return best, best_meta


def lpm_walk_prov_core(lpm_v4, lpm_v6, addr_words, is_v6, default_index,
                       v4_only: bool = False):
    """The fusable core: [N,4] v4-mapped address words → (identity index
    [N] int32, packed lpm_prefix provenance [N] int32, -1 on miss).
    ``is_v6`` may be bool or a 0/1 integer mask (the Pallas body ships it as
    int32). ``v4_only`` (static) elides the 16-level v6 chain."""
    r4, m4 = _walk(lpm_v4, addr_words, lambda l: 12 + l, V4_LEVELS,
                   default_index)
    if v4_only:
        return r4, m4
    r6, m6 = _walk(lpm_v6, addr_words, lambda l: l, V6_LEVELS, default_index)
    v6 = is_v6.astype(bool)
    return jnp.where(v6, r6, r4), jnp.where(v6, m6, m4)


def lpm_walk_core(lpm_v4, lpm_v6, addr_words, is_v6, default_index,
                  v4_only: bool = False):
    """Index-only view of :func:`lpm_walk_prov_core` (compat surface for
    callers that predate match provenance)."""
    return lpm_walk_prov_core(lpm_v4, lpm_v6, addr_words, is_v6,
                              default_index, v4_only=v4_only)[0]


def lpm_lookup_prov_batch(lpm_v4, lpm_v6, addr_words, is_v6,
                          default_index: int, v4_only: bool = False):
    """addr_words [N,4] uint32 (16-byte normalized, v4-mapped) →
    (identity index [N] int32, packed lpm_prefix [N] int32)."""
    return lpm_walk_prov_core(lpm_v4, lpm_v6, addr_words, is_v6,
                              default_index, v4_only=v4_only)


def lpm_lookup_batch(lpm_v4, lpm_v6, addr_words, is_v6, default_index: int,
                     v4_only: bool = False):
    """addr_words [N,4] uint32 (16-byte normalized, v4-mapped) → identity
    index [N] int32."""
    return lpm_walk_prov_core(lpm_v4, lpm_v6, addr_words, is_v6,
                              default_index, v4_only=v4_only)[0]
