"""Packet batch layout: the device-facing form of the shim's 64B records.

A batch is a dict-of-arrays pytree with a fixed size N (padded; ``valid``
masks real packets). The C++ shim emits exactly these columns (shim/ record
layout doc); tests build batches from oracle PacketRecords.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from cilium_tpu.utils import constants as C

BatchArrays = Dict[str, np.ndarray]


def empty_batch(n: int) -> BatchArrays:
    return {
        "src": np.zeros((n, 4), dtype=np.uint32),
        "dst": np.zeros((n, 4), dtype=np.uint32),
        "sport": np.zeros((n,), dtype=np.int32),
        "dport": np.zeros((n,), dtype=np.int32),
        "proto": np.zeros((n,), dtype=np.int32),
        "tcp_flags": np.zeros((n,), dtype=np.int32),
        "is_v6": np.zeros((n,), dtype=bool),
        "ep_slot": np.zeros((n,), dtype=np.int32),
        "direction": np.zeros((n,), dtype=np.int32),
        "http_method": np.full((n,), C.HTTP_METHOD_ANY, dtype=np.int32),
        "http_path": np.zeros((n, C.L7_PATH_MAXLEN), dtype=np.uint8),
        "valid": np.zeros((n,), dtype=bool),
    }


def _addr_words(addr16: bytes) -> np.ndarray:
    return np.frombuffer(addr16, dtype=">u4").astype(np.uint32)


def batch_from_records(records: Sequence, ep_slot_of: Dict[int, int],
                       pad_to: int = 0) -> BatchArrays:
    """Build a batch from oracle PacketRecords (tests / pcap replay).

    Records for endpoints unknown to the snapshot fail closed: they are left
    ``valid=False`` (never forwarded, no CT effects) — the batch-level analog
    of the oracle's INVALID_IDENTITY drop for unknown endpoints.
    """
    n = max(len(records), pad_to)
    b = empty_batch(n)
    for i, p in enumerate(records):
        b["src"][i] = _addr_words(p.src_addr)
        b["dst"][i] = _addr_words(p.dst_addr)
        b["sport"][i] = p.src_port
        b["dport"][i] = p.dst_port
        b["proto"][i] = p.proto
        b["tcp_flags"][i] = p.tcp_flags
        b["is_v6"][i] = p.is_ipv6
        slot = ep_slot_of.get(p.ep_id)
        if slot is None:
            continue  # fail closed: stays invalid
        b["ep_slot"][i] = slot
        b["direction"][i] = p.direction
        b["http_method"][i] = p.http_method
        pb = p.http_path[:C.L7_PATH_MAXLEN]
        if pb:
            b["http_path"][i, :len(pb)] = np.frombuffer(pb, dtype=np.uint8)
        b["valid"][i] = True
    return b


def ct_key_words_generic(xp, batch: Dict, reverse: bool = False):
    """[N, 10] uint32 conntrack key (see compile/ct_layout.py), forward or
    reverse orientation. One definition, two executors (xp = np on host,
    jnp on device) so the key layout cannot silently diverge between the
    device table and host checkpoint/export."""
    src, dst = ((batch["dst"], batch["src"]) if reverse
                else (batch["src"], batch["dst"]))
    sport, dport = ((batch["dport"], batch["sport"]) if reverse
                    else (batch["sport"], batch["dport"]))
    direction = (1 - batch["direction"]) if reverse else batch["direction"]
    words = [
        src[:, 0], src[:, 1], src[:, 2], src[:, 3],
        dst[:, 0], dst[:, 1], dst[:, 2], dst[:, 3],
        (sport.astype(xp.uint32) << xp.uint32(16)) | dport.astype(xp.uint32),
        (batch["proto"].astype(xp.uint32) << xp.uint32(8))
        | direction.astype(xp.uint32),
    ]
    return xp.stack(words, axis=-1)


def ct_key_words(batch: BatchArrays, reverse: bool = False) -> np.ndarray:
    return ct_key_words_generic(np, batch, reverse)
