"""Packet batch layout: the device-facing form of the shim's 64B records.

A batch is a dict-of-arrays pytree with a fixed size N (padded; ``valid``
masks real packets). The C++ shim emits exactly these columns (shim/ record
layout doc); tests build batches from oracle PacketRecords.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from cilium_tpu.utils import constants as C

BatchArrays = Dict[str, np.ndarray]


def empty_batch(n: int) -> BatchArrays:
    return {
        "src": np.zeros((n, 4), dtype=np.uint32),
        "dst": np.zeros((n, 4), dtype=np.uint32),
        "sport": np.zeros((n,), dtype=np.int32),
        "dport": np.zeros((n,), dtype=np.int32),
        "proto": np.zeros((n,), dtype=np.int32),
        "tcp_flags": np.zeros((n,), dtype=np.int32),
        "is_v6": np.zeros((n,), dtype=bool),
        "ep_slot": np.zeros((n,), dtype=np.int32),
        "direction": np.zeros((n,), dtype=np.int32),
        "http_method": np.full((n,), C.HTTP_METHOD_ANY, dtype=np.int32),
        "http_path": np.zeros((n, C.L7_PATH_MAXLEN), dtype=np.uint8),
        "valid": np.zeros((n,), dtype=bool),
    }


def _addr_words(addr16: bytes) -> np.ndarray:
    return np.frombuffer(addr16, dtype=">u4").astype(np.uint32)


def batch_from_records(records: Sequence, ep_slot_of: Dict[int, int],
                       pad_to: int = 0) -> BatchArrays:
    """Build a batch from oracle PacketRecords (tests / pcap replay)."""
    n = max(len(records), pad_to)
    b = empty_batch(n)
    for i, p in enumerate(records):
        b["src"][i] = _addr_words(p.src_addr)
        b["dst"][i] = _addr_words(p.dst_addr)
        b["sport"][i] = p.src_port
        b["dport"][i] = p.dst_port
        b["proto"][i] = p.proto
        b["tcp_flags"][i] = p.tcp_flags
        b["is_v6"][i] = p.is_ipv6
        b["ep_slot"][i] = ep_slot_of[p.ep_id]
        b["direction"][i] = p.direction
        b["http_method"][i] = p.http_method
        pb = p.http_path[:C.L7_PATH_MAXLEN]
        if pb:
            b["http_path"][i, :len(pb)] = np.frombuffer(pb, dtype=np.uint8)
        b["valid"][i] = True
    return b


def ct_key_words(batch: BatchArrays, reverse: bool = False) -> np.ndarray:
    """[N, 10] uint32 conntrack key (see compile/ct_layout.py), forward or
    reverse orientation. numpy version; kernels/conntrack.py mirrors in jnp."""
    src, dst = (batch["dst"], batch["src"]) if reverse else (batch["src"], batch["dst"])
    sport, dport = ((batch["dport"], batch["sport"]) if reverse
                    else (batch["sport"], batch["dport"]))
    direction = (1 - batch["direction"]) if reverse else batch["direction"]
    n = src.shape[0]
    words = np.zeros((n, 10), dtype=np.uint32)
    words[:, 0:4] = src
    words[:, 4:8] = dst
    words[:, 8] = (sport.astype(np.uint32) << 16) | dport.astype(np.uint32)
    words[:, 9] = (batch["proto"].astype(np.uint32) << 8) | direction.astype(np.uint32)
    return words
