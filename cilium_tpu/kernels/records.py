"""Packet batch layout: the device-facing form of the shim's 64B records.

A batch is a dict-of-arrays pytree with a fixed size N (padded; ``valid``
masks real packets). The C++ shim emits exactly these columns (shim/ record
layout doc); tests build batches from oracle PacketRecords.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from cilium_tpu.utils import constants as C

BatchArrays = Dict[str, np.ndarray]


def empty_batch(n: int) -> BatchArrays:
    return {
        "src": np.zeros((n, 4), dtype=np.uint32),
        "dst": np.zeros((n, 4), dtype=np.uint32),
        "sport": np.zeros((n,), dtype=np.int32),
        "dport": np.zeros((n,), dtype=np.int32),
        "proto": np.zeros((n,), dtype=np.int32),
        "tcp_flags": np.zeros((n,), dtype=np.int32),
        "is_v6": np.zeros((n,), dtype=bool),
        "ep_slot": np.zeros((n,), dtype=np.int32),
        "direction": np.zeros((n,), dtype=np.int32),
        "http_method": np.full((n,), C.HTTP_METHOD_ANY, dtype=np.int32),
        "http_path": np.zeros((n, C.L7_PATH_MAXLEN), dtype=np.uint8),
        "valid": np.zeros((n,), dtype=bool),
    }


def reset_batch_rows(b: BatchArrays, start: int, stop: int) -> None:
    """Restore rows [start:stop] of a reused batch dict to the
    ``empty_batch`` defaults (optional shim-side ``_*`` columns included).
    Every buffer-reuse site (staging-ring flush tails, reusable poll
    buffers) goes through here so a future column with a non-zero default
    cannot silently diverge between them — stale rows leaking into the
    wire-format probes is exactly the bug class this prevents."""
    for k, col in b.items():
        if k == "valid":
            col[start:stop] = False
        elif k == "http_method":
            col[start:stop] = C.HTTP_METHOD_ANY
        else:
            col[start:stop] = 0


def _addr_words(addr16: bytes) -> np.ndarray:
    return np.frombuffer(addr16, dtype=">u4").astype(np.uint32)


def batch_from_records(records: Sequence, ep_slot_of: Dict[int, int],
                       pad_to: int = 0) -> BatchArrays:
    """Build a batch from oracle PacketRecords (tests / pcap replay).

    Records for endpoints unknown to the snapshot fail closed: they are left
    ``valid=False`` (never forwarded, no CT effects) — the batch-level analog
    of the oracle's INVALID_IDENTITY drop for unknown endpoints.
    """
    n = max(len(records), pad_to)
    b = empty_batch(n)
    for i, p in enumerate(records):
        b["src"][i] = _addr_words(p.src_addr)
        b["dst"][i] = _addr_words(p.dst_addr)
        b["sport"][i] = p.src_port
        b["dport"][i] = p.dst_port
        b["proto"][i] = p.proto
        b["tcp_flags"][i] = p.tcp_flags
        b["is_v6"][i] = p.is_ipv6
        slot = ep_slot_of.get(p.ep_id)
        if slot is None:
            continue  # fail closed: stays invalid
        b["ep_slot"][i] = slot
        b["direction"][i] = p.direction
        b["http_method"][i] = p.http_method
        pb = p.http_path[:C.L7_PATH_MAXLEN]
        if pb:
            b["http_path"][i, :len(pb)] = np.frombuffer(pb, dtype=np.uint8)
        b["valid"][i] = True
    return b


def ct_key_words_generic(xp, batch: Dict, reverse: bool = False):
    """[N, 10] uint32 conntrack key (see compile/ct_layout.py), forward or
    reverse orientation. One definition, two executors (xp = np on host,
    jnp on device) so the key layout cannot silently diverge between the
    device table and host checkpoint/export."""
    src, dst = ((batch["dst"], batch["src"]) if reverse
                else (batch["src"], batch["dst"]))
    sport, dport = ((batch["dport"], batch["sport"]) if reverse
                    else (batch["sport"], batch["dport"]))
    direction = (1 - batch["direction"]) if reverse else batch["direction"]
    words = [
        src[:, 0], src[:, 1], src[:, 2], src[:, 3],
        dst[:, 0], dst[:, 1], dst[:, 2], dst[:, 3],
        (sport.astype(xp.uint32) << xp.uint32(16)) | dport.astype(xp.uint32),
        (batch["proto"].astype(xp.uint32) << xp.uint32(8))
        | direction.astype(xp.uint32),
    ]
    return xp.stack(words, axis=-1)


def ct_key_words(batch: BatchArrays, reverse: bool = False) -> np.ndarray:
    return ct_key_words_generic(np, batch, reverse)


# --------------------------------------------------------------------------- #
# Packed wire format: ONE contiguous uint32 array per batch.
#
# Transferring the batch as 12 separate arrays costs ~5x more wall time on a
# tunneled/PCIe link than one contiguous buffer (per-transfer overhead
# dominates); the classify step is transfer-bound, so the runtime packs on
# the host (vectorized numpy, ~free) and unpacks on device inside the jit
# (bit ops that XLA fuses into the pipeline). The C++ shim can emit this
# layout directly.
#
# Word layout per record:
#   0-3   src words          4-7  dst words
#   8     sport<<16 | dport
#   9     proto<<24 | tcp_flags<<16 | http_method<<8 | is_v6<<2|dir<<1|valid
#   10    ep_slot
#   11+   (L7 variant only) http_path as 16 big-endian uint32 words
# --------------------------------------------------------------------------- #
PACK_WORDS = 11
PACK_WORDS_L7 = PACK_WORDS + C.L7_PATH_MAXLEN // 4


def _out_view(out: Optional[np.ndarray], n: int, words: int) -> np.ndarray:
    """Resolve the ``out=`` contract shared by every pack kernel: a
    caller-owned uint32 buffer with >= n rows of exactly ``words`` columns
    (the staging ring preallocates at max_bucket rows and packs into the
    [:n] prefix). Returns the [:n] view to fill, or a fresh allocation when
    the caller passed none."""
    if out is None:
        return np.empty((n, words), dtype=np.uint32)
    if (out.dtype != np.uint32 or out.ndim != 2 or out.shape[0] < n
            or out.shape[1] != words):
        raise ValueError(
            f"pack out= buffer mismatch: need uint32 [>={n}, {words}], "
            f"got {out.dtype} {out.shape}")
    return out[:n]


def _path_words_of(paths: np.ndarray) -> int:
    """Smallest power-of-two word count covering the longest path in
    ``paths`` [N, 64]. L7 throughput is transfer-bound and most HTTP paths
    are short: shipping only the occupied prefix (rounded to a power of two
    so trace shapes stay few) cuts the wire size ~2-4x vs the fixed 64-byte
    block."""
    nz = np.nonzero(paths.any(axis=0))[0]
    maxlen = int(nz[-1]) + 1 if nz.size else 1
    words = -(-maxlen // 4)
    return min(1 << (words - 1).bit_length(), C.L7_PATH_MAXLEN // 4)


def _path_words_for(b: BatchArrays) -> int:
    return _path_words_of(b["http_path"])


def pack_batch(b: BatchArrays, l7: Optional[bool] = None,
               path_words: Optional[int] = None,
               out: Optional[np.ndarray] = None) -> np.ndarray:
    """Pack a batch dict → [N, 11] (or [N, 11+path_words] when l7) uint32.
    ``l7=None`` auto-detects: include the path block iff any record carries
    L7 tokens. ``path_words`` (power of two ≤ 16) sizes the path block;
    default = smallest power of two covering the batch's longest path.
    ``out=`` writes into a caller-owned buffer (see ``_out_view``) instead
    of allocating — the staging ring's steady-state zero-alloc path; the
    wire is bit-identical either way."""
    if l7 is None:
        l7 = bool((b["http_method"] != C.HTTP_METHOD_ANY).any()
                  or b["http_path"].any())
    if l7:
        if path_words is None:
            path_words = _path_words_for(b)
        path_words = min(path_words, C.L7_PATH_MAXLEN // 4)
        if b["http_path"][:, 4 * path_words:].any():
            raise ValueError(f"path_words={path_words} truncates a path")
    else:
        path_words = 0
    n = b["valid"].shape[0]
    out = _out_view(out, n, PACK_WORDS + path_words)
    out[:, 0:4] = b["src"]
    out[:, 4:8] = b["dst"]
    out[:, 8] = (b["sport"].astype(np.uint32) << 16) \
        | b["dport"].astype(np.uint32)
    out[:, 9] = (b["proto"].astype(np.uint32) << 24) \
        | (b["tcp_flags"].astype(np.uint32) << 16) \
        | (b["http_method"].astype(np.uint32) << 8) \
        | (b["is_v6"].astype(np.uint32) << 2) \
        | (b["direction"].astype(np.uint32) << 1) \
        | b["valid"].astype(np.uint32)
    out[:, 10] = b["ep_slot"].astype(np.uint32)
    if l7:
        p = b["http_path"][:, :4 * path_words].reshape(
            n, path_words, 4).astype(np.uint32)
        out[:, PACK_WORDS:] = ((p[:, :, 0] << 24) | (p[:, :, 1] << 16)
                               | (p[:, :, 2] << 8) | p[:, :, 3])
    return out


# Compact v4 variant: 4 words (16B) per record — for the v4-only, L7-free
# hot path (configs 1/2/3/5 traffic). The classify pipeline is transfer-
# bound, so bytes/record is the throughput knob: 16B vs 44B is ~2.7x more
# records per second through the same link.
#   0  src v4   1  dst v4   2  sport<<16|dport
#   3  proto<<24 | tcp_flags<<16 | ep_slot<<2 | dir<<1 | valid
# ep_slot therefore caps at 14 bits (16383 endpoints/node) in this format;
# the full format carries 32-bit slots.
PACK4_WORDS = 4
PACK4_EP_SLOT_MAX = (1 << 14) - 1


def pack_batch_v4(b: BatchArrays,
                  out: Optional[np.ndarray] = None) -> np.ndarray:
    """Pack a v4-only, L7-free batch dict → [N, 4] uint32. ``out=`` fills
    a caller-owned buffer in place (bit-identical wire, no allocation)."""
    if b["is_v6"].any():
        raise ValueError("pack_batch_v4: batch contains v6 records")
    if (b["ep_slot"] > PACK4_EP_SLOT_MAX).any():
        raise ValueError("pack_batch_v4: ep_slot exceeds 14-bit compact cap")
    n = b["valid"].shape[0]
    out = _out_view(out, n, PACK4_WORDS)
    out[:, 0] = b["src"][:, 3]
    out[:, 1] = b["dst"][:, 3]
    out[:, 2] = (b["sport"].astype(np.uint32) << 16) \
        | b["dport"].astype(np.uint32)
    out[:, 3] = (b["proto"].astype(np.uint32) << 24) \
        | (b["tcp_flags"].astype(np.uint32) << 16) \
        | (b["ep_slot"].astype(np.uint32) << 2) \
        | (b["direction"].astype(np.uint32) << 1) \
        | b["valid"].astype(np.uint32)
    return out


# --------------------------------------------------------------------------- #
# L7 dictionary wire format: (wire, path_dict) — real HTTP traffic repeats a
# small set of paths, so shipping the 64B path block per record wastes ~80%
# of the link. Instead: unique paths once per batch ([U, P] packed words,
# U padded to a power of two for trace stability) + a 16-bit dictionary
# index per record; the device gathers the path bytes back during unpack.
# cfg4 measurement (round 5): the L7 kernel runs >100M flows/s compute-only;
# the fixed-block wire capped it at ~1.3M. 20B/record vs 76-108B.
#
# v4-compact variant ([N, 5]): PACK4 words 0-3 + word 4 = method<<24|path_idx.
# full variant ([N, 12]): PACK words 0-10 + word 11 = path_idx (method is
# already in word 9).
# --------------------------------------------------------------------------- #
PACK4_L7_WORDS = PACK4_WORDS + 1
PACK_L7DICT_WORDS = PACK_WORDS + 1


def _pad_dict_rows(count: int, min_rows: int) -> int:
    """Dictionary row padding: next power of two ≥ max(count, min_rows) —
    shared by the path and address dictionaries so trace-shape policy can't
    silently diverge between them."""
    return 1 << max(0, (max(count, min_rows) - 1)).bit_length()


def _pack_path_dict(paths: np.ndarray, path_words: Optional[int],
                    min_rows: int = 1) -> Tuple[np.ndarray, np.ndarray]:
    """[N, 64] uint8 → (dict_words [U_pow2, P] uint32, index [N] int64).
    ``min_rows`` floors the padded row count (callers pin it grow-only so
    serving doesn't retrace when per-batch path diversity fluctuates)."""
    uniq, idx = np.unique(paths, axis=0, return_inverse=True)
    if uniq.shape[0] > 65536:
        raise ValueError("path dictionary overflow (>64k unique paths)")
    if path_words is None:
        path_words = _path_words_of(uniq)
    path_words = min(path_words, C.L7_PATH_MAXLEN // 4)
    if uniq[:, 4 * path_words:].any():
        raise ValueError(f"path_words={path_words} truncates a path")
    u_pad = _pad_dict_rows(uniq.shape[0], min_rows)
    p = np.zeros((u_pad, 4 * path_words), dtype=np.uint32)
    p[:uniq.shape[0]] = uniq[:, :4 * path_words]
    p = p.reshape(u_pad, path_words, 4)
    words = ((p[:, :, 0] << 24) | (p[:, :, 1] << 16)
             | (p[:, :, 2] << 8) | p[:, :, 3])
    return words, idx


def pack_batch_l7dict(b: BatchArrays, path_words: Optional[int] = None,
                      min_rows: int = 1, force_full: bool = False,
                      out: Optional[np.ndarray] = None
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Pack an L7 batch as (wire, path_dict). Picks the 5-word v4-compact
    wire when the batch qualifies, else the 12-word full wire
    (``force_full`` pins the full wire so serving paths don't flap formats
    batch-to-batch). ``out=`` fills a caller-owned wire buffer in place
    (its width must match the variant this batch selects; the path dict is
    always fresh — ``np.unique`` allocates regardless, and the upload layer
    dedups re-transfers by content instead)."""
    dict_words, idx = _pack_path_dict(b["http_path"], path_words, min_rows)
    n = b["valid"].shape[0]
    if not force_full and not b["is_v6"].any() \
            and not (b["ep_slot"] > PACK4_EP_SLOT_MAX).any():
        wire = _out_view(out, n, PACK4_L7_WORDS)
        wire[:, 0] = b["src"][:, 3]
        wire[:, 1] = b["dst"][:, 3]
        wire[:, 2] = (b["sport"].astype(np.uint32) << 16) \
            | b["dport"].astype(np.uint32)
        wire[:, 3] = (b["proto"].astype(np.uint32) << 24) \
            | (b["tcp_flags"].astype(np.uint32) << 16) \
            | (b["ep_slot"].astype(np.uint32) << 2) \
            | (b["direction"].astype(np.uint32) << 1) \
            | b["valid"].astype(np.uint32)
        wire[:, 4] = (b["http_method"].astype(np.uint32) << 24) \
            | idx.astype(np.uint32)
        return wire, dict_words
    wire = _out_view(out, n, PACK_L7DICT_WORDS)
    pack_batch(b, l7=False, out=wire[:, :PACK_WORDS])
    wire[:, PACK_WORDS] = idx.astype(np.uint32)
    return wire, dict_words


def _unpack_dict_paths_jnp(dict_words, idx):
    import jax.numpy as jnp
    words = dict_words[idx]                                # [N, P]
    n = words.shape[0]
    path = jnp.stack([(words >> 24) & 0xFF, (words >> 16) & 0xFF,
                      (words >> 8) & 0xFF, words & 0xFF],
                     axis=-1).reshape(n, -1).astype(jnp.uint8)
    pad = C.L7_PATH_MAXLEN - path.shape[1]
    if pad > 0:
        path = jnp.pad(path, ((0, 0), (0, pad)))
    return path


def unpack_batch_l7dict_jnp(wire, dict_words):
    """Device-side unpack of either l7-dict wire variant."""
    import jax.numpy as jnp
    if wire.shape[1] == PACK4_L7_WORDS:
        b = unpack_batch_v4_jnp(wire[:, :PACK4_WORDS])
        w4 = wire[:, 4]
        b["http_method"] = (w4 >> 24).astype(jnp.int32)
        b["http_path"] = _unpack_dict_paths_jnp(
            dict_words, (w4 & 0xFFFF).astype(jnp.int32))
        return b
    b = unpack_batch_jnp(wire[:, :PACK_WORDS])
    b["http_path"] = _unpack_dict_paths_jnp(
        dict_words, (wire[:, PACK_WORDS] & 0xFFFF).astype(jnp.int32))
    return b


# --------------------------------------------------------------------------- #
# Address-dictionary wire: (wire [N,3 or 4], addr_dict [U,4] [, path_dict]) —
# pod traffic repeats addresses heavily (a 65k-record cfg5 batch carries
# ~2000 distinct addresses), so shipping each unique 16B normalized address
# once and 16-bit indexes per record beats even the compact v4 wire
# (12B/record vs 16B) and beats the full wire ~3x for mixed v4/v6. Handles
# both families uniformly (the dict rows are v4-mapped/16B).
#
#   w0 = src_idx<<16 | dst_idx          (indexes into addr_dict)
#   w1 = sport<<16 | dport
#   w2 = proto<<24 | tcp_flags<<16 | ep_slot<<3 | is_v6<<2 | dir<<1 | valid
#   w3 = http_method<<16 | path_idx     (L7 variant only)
# --------------------------------------------------------------------------- #
PACKA_WORDS = 3
PACKA_L7_WORDS = 4
PACKA_EP_SLOT_MAX = (1 << 13) - 1


def addr_dict_ratio(b: BatchArrays) -> float:
    """Unique-address fraction of a batch (selection heuristic: the addr
    dict wins when addresses repeat; random-scan traffic where every
    record brings fresh addresses packs better with the flat formats)."""
    n = b["valid"].shape[0]
    if n == 0:
        return 1.0
    uniq = np.unique(np.concatenate([b["src"], b["dst"]]), axis=0)
    return uniq.shape[0] / (2 * n)


def pack_batch_addrdict(b: BatchArrays, l7: Optional[bool] = None,
                        min_addr_rows: int = 1,
                        path_words: Optional[int] = None,
                        min_path_rows: int = 1):
    """Pack a batch in the address-dictionary wire. Returns
    (wire [N,3], addr_dict) or, with L7 tokens, (wire [N,4], addr_dict,
    path_dict). ``min_*_rows`` floor the padded dictionary sizes (grow-only
    pinning for serving paths)."""
    n = b["valid"].shape[0]
    if (b["ep_slot"] > PACKA_EP_SLOT_MAX).any():
        raise ValueError("pack_batch_addrdict: ep_slot exceeds 13-bit cap")
    uniq, inv = np.unique(np.concatenate([b["src"], b["dst"]]), axis=0,
                          return_inverse=True)
    if uniq.shape[0] > 65536:
        raise ValueError("address dictionary overflow (>64k unique)")
    u_pad = _pad_dict_rows(uniq.shape[0], min_addr_rows)
    addr_dict = np.zeros((u_pad, 4), dtype=np.uint32)
    addr_dict[:uniq.shape[0]] = uniq
    src_idx = inv[:n].astype(np.uint32)
    dst_idx = inv[n:].astype(np.uint32)
    if l7 is None:
        l7 = bool((b["http_method"] != C.HTTP_METHOD_ANY).any()
                  or b["http_path"].any())
    wire = np.empty((n, PACKA_L7_WORDS if l7 else PACKA_WORDS),
                    dtype=np.uint32)
    wire[:, 0] = (src_idx << 16) | dst_idx
    wire[:, 1] = (b["sport"].astype(np.uint32) << 16) \
        | b["dport"].astype(np.uint32)
    wire[:, 2] = (b["proto"].astype(np.uint32) << 24) \
        | (b["tcp_flags"].astype(np.uint32) << 16) \
        | (b["ep_slot"].astype(np.uint32) << 3) \
        | (b["is_v6"].astype(np.uint32) << 2) \
        | (b["direction"].astype(np.uint32) << 1) \
        | b["valid"].astype(np.uint32)
    if not l7:
        return wire, addr_dict
    path_dict, path_idx = _pack_path_dict(b["http_path"], path_words,
                                          min_path_rows)
    wire[:, 3] = (b["http_method"].astype(np.uint32) << 16) \
        | path_idx.astype(np.uint32)
    return wire, addr_dict, path_dict


def unpack_batch_addrdict_jnp(wire, addr_dict, path_dict=None):
    """Device-side unpack of the address-dictionary wire."""
    import jax.numpy as jnp
    n = wire.shape[0]
    w2 = wire[:, 2]
    b = {
        "src": addr_dict[(wire[:, 0] >> 16).astype(jnp.int32)],
        "dst": addr_dict[(wire[:, 0] & 0xFFFF).astype(jnp.int32)],
        "sport": (wire[:, 1] >> 16).astype(jnp.int32),
        "dport": (wire[:, 1] & 0xFFFF).astype(jnp.int32),
        "proto": (w2 >> 24).astype(jnp.int32),
        "tcp_flags": ((w2 >> 16) & 0xFF).astype(jnp.int32),
        "ep_slot": ((w2 >> 3) & PACKA_EP_SLOT_MAX).astype(jnp.int32),
        "is_v6": ((w2 >> 2) & 1).astype(bool),
        "direction": ((w2 >> 1) & 1).astype(jnp.int32),
        "valid": (w2 & 1).astype(bool),
    }
    if wire.shape[1] == PACKA_L7_WORDS and path_dict is not None:
        w3 = wire[:, 3]
        b["http_method"] = ((w3 >> 16) & 0xFF).astype(jnp.int32)
        b["http_path"] = _unpack_dict_paths_jnp(
            path_dict, (w3 & 0xFFFF).astype(jnp.int32))
    else:
        b["http_method"] = jnp.full((n,), C.HTTP_METHOD_ANY,
                                    dtype=jnp.int32)
        b["http_path"] = jnp.zeros((n, C.L7_PATH_MAXLEN), dtype=jnp.uint8)
    return b


def unpack_batch_v4_jnp(packed):
    """Device-side unpack of the compact v4 format → standard batch dict
    (v4-mapped addresses: words [0, 0, 0xFFFF, addr])."""
    import jax.numpy as jnp
    n = packed.shape[0]
    w3 = packed[:, 3]
    zeros = jnp.zeros((n,), dtype=jnp.uint32)
    ffff = jnp.full((n,), 0xFFFF, dtype=jnp.uint32)
    return {
        "src": jnp.stack([zeros, zeros, ffff, packed[:, 0]], axis=-1),
        "dst": jnp.stack([zeros, zeros, ffff, packed[:, 1]], axis=-1),
        "sport": (packed[:, 2] >> 16).astype(jnp.int32),
        "dport": (packed[:, 2] & 0xFFFF).astype(jnp.int32),
        "proto": (w3 >> 24).astype(jnp.int32),
        "tcp_flags": ((w3 >> 16) & 0xFF).astype(jnp.int32),
        "http_method": jnp.full((n,), C.HTTP_METHOD_ANY, dtype=jnp.int32),
        "http_path": jnp.zeros((n, C.L7_PATH_MAXLEN), dtype=jnp.uint8),
        "is_v6": jnp.zeros((n,), dtype=bool),
        "direction": ((w3 >> 1) & 1).astype(jnp.int32),
        "valid": (w3 & 1).astype(bool),
        "ep_slot": ((w3 >> 2) & PACK4_EP_SLOT_MAX).astype(jnp.int32),
    }


def wire_words_for(use_l7: bool, use_wide: bool) -> int:
    """Wire width (uint32 words/record) the serving path ships for a given
    sticky-format decision — one ladder shared by the single-chip and the
    sharded pack paths so the format choice cannot diverge between them."""
    if use_l7:
        return PACK_L7DICT_WORDS if use_wide else PACK4_L7_WORDS
    return PACK_WORDS if use_wide else PACK4_WORDS


def unpack_wire_jnp(batch):
    """Device-side unpack of ANY packed wire form → the standard batch
    dict, dispatching on the wire's static width/pytree at trace time:
    tuple → dictionary wires (address or L7-path), [N,4] → compact v4,
    otherwise the full layout. Shared by the single-chip jit and the
    per-shard body of the meshed classify."""
    if isinstance(batch, (tuple, list)):
        wire = batch[0]
        if wire.shape[1] in (PACKA_WORDS, PACKA_L7_WORDS):
            # (wire, addr_dict[, path_dict]): address-dictionary wire
            return unpack_batch_addrdict_jnp(*batch)
        # (wire, path_dict): the L7 path-dictionary wire
        return unpack_batch_l7dict_jnp(*batch)
    if batch.shape[1] == PACK4_WORDS:
        return unpack_batch_v4_jnp(batch)
    return unpack_batch_jnp(batch)


def unpack_batch_jnp(packed):
    """Device-side unpack (inside jit) → the standard batch dict. The L7
    path block is reconstructed when present (static via array width)."""
    import jax.numpy as jnp
    n = packed.shape[0]
    w9 = packed[:, 9]
    b = {
        "src": packed[:, 0:4],
        "dst": packed[:, 4:8],
        "sport": (packed[:, 8] >> 16).astype(jnp.int32),
        "dport": (packed[:, 8] & 0xFFFF).astype(jnp.int32),
        "proto": (w9 >> 24).astype(jnp.int32),
        "tcp_flags": ((w9 >> 16) & 0xFF).astype(jnp.int32),
        "http_method": ((w9 >> 8) & 0xFF).astype(jnp.int32),
        "is_v6": ((w9 >> 2) & 1).astype(bool),
        "direction": ((w9 >> 1) & 1).astype(jnp.int32),
        "valid": (w9 & 1).astype(bool),
        "ep_slot": packed[:, 10].astype(jnp.int32),
    }
    if packed.shape[1] > PACK_WORDS:
        words = packed[:, PACK_WORDS:]
        path = jnp.stack([(words >> 24) & 0xFF, (words >> 16) & 0xFF,
                          (words >> 8) & 0xFF, words & 0xFF],
                         axis=-1).reshape(n, -1).astype(jnp.uint8)
        pad = C.L7_PATH_MAXLEN - path.shape[1]
        if pad > 0:        # variable-width wire: restore the full 64B block
            path = jnp.pad(path, ((0, 0), (0, pad)))
        b["http_path"] = path
    else:
        b["http_path"] = jnp.zeros((n, C.L7_PATH_MAXLEN), dtype=jnp.uint8)
    return b
