"""The fused classify step — one jitted program per batch (the analog of one
eBPF datapath run over a batch of packets; SURVEY.md §3.3: "TPU equivalent:
one fused kernel: gather(ipcache-LPM) → conntrack probe → policy
wildcard-ladder as masked [compile-time] resolution → verdict + CT update,
batched over N headers").

Branch-free: every packet takes every path, masks select. XLA fuses the
elementwise pipeline between the gathers; the scatters at the end form the
CT write phase.

Two executors serve the hot interior (LPM walk → CT probe pair → policy
ladder + L7 + verdict composition):

- the **jnp reference** (default): plain XLA ops — portable, and the
  semantics baseline every other path is pinned against;
- the **Pallas megakernel path** (``fused=True``): kernels/fused.py runs
  the same shared core functions inside explicit TPU kernels that keep the
  walk/probe/ladder state in registers/VMEM instead of materializing ~20
  intermediate [N] arrays in HBM between stages. On CPU the fused path runs
  in Pallas interpret mode (``fused_interpret=True``) so CI pins it
  bit-identical to the reference and the oracle without TPU hardware.

The CT insert/apply phase (scatter-heavy, order-defined aggregation) stays
on XLA in both modes — scatters are what XLA already does well, and the
deterministic-winner semantics live in kernels/conntrack.py either way.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

from cilium_tpu.compile.ct_layout import PROBE_DEPTH
from cilium_tpu.kernels import conntrack as ctk
from cilium_tpu.kernels.l7 import l7_match_batch
from cilium_tpu.kernels.lb import lb_step
from cilium_tpu.kernels.lpm import lpm_lookup_prov_batch
from cilium_tpu.kernels.policy import policy_lookup_batch
from cilium_tpu.utils import constants as C

N_REASON_BINS = C.DROP_REASON_BINS   # counter-tensor geometry (one source)


def compose_verdict(decision, enforced, cell_redirect, l7_fail,
                    est, reply, valid):
    """Step 5 of the datapath: (decision, l7_fail) → allow/reason/status/
    redirect, with no intermediate leaving the caller's scope. Shared
    verbatim by the jnp reference and the fused Pallas kernel body
    (kernels/fused.py) — the single source of the composition semantics;
    the L7-gating inputs (``cell_redirect``/``l7_fail``) come from
    :func:`classify_interior_core`, their single source."""
    hit = est | reply
    new_allow = jnp.where(
        decision == C.VERDICT_DENY, False,
        jnp.where(decision == C.VERDICT_MISS, ~enforced,
                  ~l7_fail))  # ALLOW always passes; REDIRECT unless l7_fail
    allow = jnp.where(hit, ~l7_fail, new_allow) & valid
    reason = jnp.where(
        hit,
        jnp.where(l7_fail, int(C.DropReason.POLICY_L7), int(C.DropReason.OK)),
        jnp.where(
            decision == C.VERDICT_DENY, int(C.DropReason.POLICY_DENY),
            jnp.where(decision == C.VERDICT_MISS,
                      jnp.where(enforced, int(C.DropReason.POLICY),
                                int(C.DropReason.OK)),
                      jnp.where(l7_fail, int(C.DropReason.POLICY_L7),
                                int(C.DropReason.OK)))),
    ).astype(jnp.int32)
    status = jnp.where(est, int(C.CTStatus.ESTABLISHED),
                       jnp.where(reply, int(C.CTStatus.REPLY),
                                 int(C.CTStatus.NEW))).astype(jnp.int32)
    redirect = valid & cell_redirect
    return allow, reason, status, redirect


def interior_pre_core(tensors, ep_slot, direction, id_idx, proto,
                      dport, http_method, http_path, rule_axis=None):
    """The CT-independent half of the classify interior: policy ladder +
    L7 token match → (decision, enforced, cell_redirect, l7_fail, mrule).
    Nothing here depends on the CT probe result — only the final
    ``compose_verdict`` does — which is exactly what lets the device-RSS
    exchange path (parallel/exchange.py) run this BEFORE the ring
    ``ppermute`` CT hop and compose after the replies land, while the
    steered/serial paths keep calling it through
    :func:`classify_interior_core` unchanged. One source of the ladder/L7
    semantics either way."""
    decision, l7_cell, enforced, mrule = policy_lookup_batch(
        tensors, ep_slot, direction, id_idx, proto, dport,
        rule_axis=rule_axis)
    # L7-lite: the CURRENT policy cell's rules apply to every packet with
    # tokens — new and established flows alike (the per-request proxy
    # semantics; CT entries carry no L7 state, so policy swaps need no
    # remap)
    has_tokens = (http_method != C.HTTP_METHOD_ANY) \
        | (http_path != 0).any(axis=-1)
    cell_redirect = decision == C.VERDICT_REDIRECT
    set_to_check = jnp.where(cell_redirect, l7_cell, 0)
    l7_ok = l7_match_batch(tensors, set_to_check, http_method, http_path)
    l7_fail = has_tokens & (set_to_check > 0) & ~l7_ok
    return decision, enforced, cell_redirect, l7_fail, mrule


def classify_interior_core(tensors, ep_slot, direction, id_idx, proto,
                           dport, http_method, http_path, est, reply, valid,
                           rule_axis=None):
    """Steps 3-5 of the datapath (policy ladder → L7 token match → verdict
    composition) as one pure function of the snapshot tensor dict + row
    columns. This is the *fusable core*: the jnp reference calls it on XLA
    arrays, the Pallas verdict kernel (kernels/fused.py) calls the exact
    same function on values read from VMEM refs — so
    ``decision → l7_cell → l7_match → allow/reason`` never round-trips
    through HBM on the fused path, and bit-identity between the executors
    holds by construction.

    → (allow [N] bool, reason [N] int32, status [N] int32,
    redirect [N] bool, matched_rule [N] int32); the NO_SERVICE override for
    LB no-backend drops is the caller's job (it precedes this stage's
    inputs either way). ``matched_rule`` is the ladder's provenance column
    (kernels/policy.py): the resolved cell coordinate where a ladder
    actually ran (valid row, enforced direction), -1 otherwise — identical
    across the jnp reference, the fused kernel and the oracle."""
    decision, enforced, cell_redirect, l7_fail, mrule = interior_pre_core(
        tensors, ep_slot, direction, id_idx, proto, dport, http_method,
        http_path, rule_axis=rule_axis)
    allow, reason, status, redirect = compose_verdict(
        decision, enforced, cell_redirect, l7_fail, est, reply, valid)
    matched_rule = jnp.where(valid & enforced, mrule,
                             jnp.int32(-1)).astype(jnp.int32)
    return allow, reason, status, redirect, matched_rule


def classify_pre_ct(tensors, batch, world_index, *, v4_only: bool = False,
                    rule_axis=None, lb_probe_depth: int = 8, plan=None,
                    fused_interpret: bool = False,
                    split_interior: bool = False):
    """Steps 0-1 of the datapath (service LB → ipcache LPM) plus the CT
    key derivation, as one pure function shared by :func:`classify_step`
    and the device-RSS exchange path (parallel/exchange.py) — the single
    source of everything that happens BEFORE the conntrack stage.

    Returns a dict:
      ``batch``  — the post-DNAT column dict (dst/dport rewritten),
      ``valid``  — post-LB validity (``valid & ~no_backend``),
      ``svc`` / ``rev_nat`` / ``no_backend`` — the LB columns,
      ``id_idx`` / ``remote_identity`` / ``lpm_prefix`` — the LPM result
      + provenance (masked by the ORIGINAL valid, like classify_step),
      ``fwd_keys`` / ``rev_keys`` — the post-DNAT CT key pair.

    ``split_interior=True`` additionally runs :func:`interior_pre_core`
    (ladder + L7, no compose) and adds ``decision``/``enforced``/
    ``cell_redirect``/``l7_fail``/``mrule`` — the form the exchange path
    needs, since est/reply only exist after the ppermute hop. ``plan``
    (kernels/fused.fuse_plan) routes the LPM walk through the Pallas
    kernel when eligible, exactly like classify_step."""
    valid0 = batch["valid"]
    direction = batch["direction"]
    # 0. service LB (bpf/lib/lb.h analog): frontend match → Maglev backend
    # → DNAT. Everything downstream (LPM, CT, policy) sees the translated
    # tuple, exactly like the upstream from-container path.
    has_lb = "lb_tab_keys" in tensors
    if has_lb:
        new_dst, new_dport, rev_nat, no_backend = lb_step(
            tensors, batch, probe_depth=lb_probe_depth)
        svc = rev_nat > 0
        batch = dict(batch)
        batch["dst"] = new_dst
        batch["dport"] = new_dport
        valid = valid0 & ~no_backend
    else:
        n = valid0.shape[0]
        rev_nat = jnp.zeros((n,), dtype=jnp.int32)
        svc = jnp.zeros((n,), dtype=bool)
        no_backend = jnp.zeros((n,), dtype=bool)
        valid = valid0

    # 1. ipcache LPM: remote = dst on egress, src on ingress. The walk
    # resolves the identity index AND the winning prefix provenance
    # ((slot << 8) | plen, -1 on miss) in the same register chain
    remote_words = jnp.where((direction == C.DIR_EGRESS)[:, None],
                             batch["dst"], batch["src"])
    if plan is not None and plan.lpm:
        from cilium_tpu.kernels import fused as fk
        id_idx, pfx_meta = fk.lpm_lookup_fused(
            tensors["lpm_v4"], tensors["lpm_v6"], remote_words,
            batch["is_v6"], world_index, v4_only=v4_only,
            interpret=fused_interpret)
    else:
        id_idx, pfx_meta = lpm_lookup_prov_batch(
            tensors["lpm_v4"], tensors["lpm_v6"], remote_words,
            batch["is_v6"], default_index=world_index, v4_only=v4_only)
    remote_identity = tensors["identity_ids"][id_idx].astype(jnp.uint32)
    # provenance masking follows the same truth the columns they explain
    # use: lpm_prefix for every row that was valid at ingest (NO_SERVICE
    # rows keep their VIP-resolved identity AND its prefix), -1 otherwise
    lpm_prefix = jnp.where(valid0, pfx_meta,
                           jnp.int32(-1)).astype(jnp.int32)

    # CT key pair (post-DNAT): the reverse key is a word permutation of
    # the forward key — normalized once, derived twice
    fwd_keys, rev_keys = ctk.ct_key_words_pair(batch)

    pre = {
        "batch": batch, "valid": valid, "svc": svc, "rev_nat": rev_nat,
        "no_backend": no_backend, "id_idx": id_idx,
        "remote_identity": remote_identity, "lpm_prefix": lpm_prefix,
        "fwd_keys": fwd_keys, "rev_keys": rev_keys,
    }
    if split_interior:
        decision, enforced, cell_redirect, l7_fail, mrule = \
            interior_pre_core(
                tensors, batch["ep_slot"], direction, id_idx,
                batch["proto"], batch["dport"], batch["http_method"],
                batch["http_path"], rule_axis=rule_axis)
        pre.update(decision=decision, enforced=enforced,
                   cell_redirect=cell_redirect, l7_fail=l7_fail,
                   mrule=mrule)
    return pre


def ct_update_stage(ct, fwd_keys, proto, tcp_flags, hit, hit_slot, reply,
                    new, allow, rev_nat_vals, now,
                    probe_depth: int = PROBE_DEPTH):
    """Step 6 (+ the 6b batch-start rev-NAT read) of the datapath: the CT
    insert-when-full + aggregate apply, shared verbatim by
    :func:`classify_step` (local rows) and the device-RSS exchange's
    owner-side stage (gathered rows) — the single source of the CT
    mutation semantics, including the tail-evict victim order. Slots this
    batch probe-hit are protected from eviction (snapshot semantics), and
    a flow whose window stays exhausted even after evicting fails CLOSED
    (``ct_full``, the CT_FULL drop the caller composes in).

    → (new_ct, ct_full [N] bool, entry_rnat [N] int32 — the batch-start
    ``rev_nat`` read at each row's hit slot, garbage where ``~hit`` and
    discarded by the caller's reply mask — and n_evicted uint32)."""
    want_insert = new & allow
    cap = ct["expiry"].shape[0]
    protected = jnp.zeros((cap,), dtype=bool).at[
        jnp.where(hit, hit_slot, cap)].set(True, mode="drop")
    new_keys, new_created, zero_mask, slot_new, fail, n_evicted = \
        ctk.ct_insert_new(ct, fwd_keys, want_insert, now, probe_depth,
                          evict=True, protected=protected)
    ct_full = fail                       # fail ⊆ want_insert ⊆ new & allow
    allow = allow & ~ct_full
    slot = jnp.where(hit, hit_slot, slot_new)
    contrib = allow & (jnp.where(hit, True, slot_new >= 0))
    new_ct = ctk.ct_apply(ct, {"proto": proto, "tcp_flags": tcp_flags},
                          slot, reply, contrib, now,
                          new_keys=new_keys, new_created=new_created,
                          zero_mask=zero_mask, rev_nat_vals=rev_nat_vals)
    # 6b read half (lb4_rev_nat analog): the CT entry's stable rev-NAT id
    # as-of the batch start — reads the PRE-apply table, exactly like
    # classify_step always did
    slot_safe = jnp.where(hit_slot >= 0, hit_slot, 0)
    entry_rnat = ct["rev_nat"][slot_safe].astype(jnp.int32)
    return new_ct, ct_full, entry_rnat, n_evicted


def resolve_rev_nat(tensors, entry_rnat, reply, src, sport):
    """Step 6b resolution: a reply on a service flow carries the CT
    entry's stable rev-NAT id → rewrite src back to the VIP. Ids whose
    service is gone resolve to an invalid row → no rewrite (fail closed;
    never another service's VIP). Shared by classify_step and the
    exchange path — the replicated ``lb_rnat_*`` tensors make this a
    purely local lookup once ``entry_rnat`` rode the reply buffer home."""
    if "lb_rnat_valid" not in tensors:
        n = reply.shape[0]
        rnat = jnp.zeros((n,), dtype=bool)
        return rnat, src, sport.astype(jnp.int32)
    n_rnat = tensors["lb_rnat_valid"].shape[0]
    rid = entry_rnat - 1
    known = (rid >= 0) & (rid < n_rnat)
    rid_safe = jnp.where(known, rid, 0)
    rnat = reply & known & tensors["lb_rnat_valid"][rid_safe]
    rnat_src = jnp.where(rnat[:, None], tensors["lb_rnat_addr"][rid_safe],
                         src)
    rnat_sport = jnp.where(rnat, tensors["lb_rnat_port"][rid_safe],
                           sport).astype(jnp.int32)
    return rnat, rnat_src, rnat_sport


def tally_by_reason_dir(reason, direction, counted):
    """Step 7: the per-reason × direction counter tensor (metricsmap
    analog) — one scatter-add, shared by every classify executor."""
    bin_idx = reason * 2 + direction
    scat = jnp.where(counted, bin_idx, N_REASON_BINS * 2)
    return jnp.zeros((N_REASON_BINS * 2,), dtype=jnp.uint32).at[scat].add(
        jnp.uint32(1), mode="drop")


def classify_step(tensors, ct, batch, now, world_index=0, *,
                  probe_depth: int = PROBE_DEPTH, v4_only: bool = False,
                  rule_axis=None, lb_probe_depth: int = 8,
                  fused: bool = False, fused_interpret: bool = False):
    # ``world_index`` is a traced scalar (not static): it changes whenever the
    # identity table grows, and baking it in would force a re-jit per snapshot.
    # ``rule_axis`` names a mesh axis for rule-space (verdict-row) sharding.
    """→ (out, new_ct, counters).

    out: allow [N] bool, reason [N] int32 (DropReason), status [N] int32
    (CTStatus), ct_full [N] bool (new flow denied because its CT probe
    window stayed exhausted after the eviction round), remote_identity [N]
    uint32, redirect [N] bool, the provenance columns matched_rule /
    lpm_prefix / ct_state_pre [N] int32 (see the out dict below), plus the
    NAT rewrite columns the shim applies: svc [N] bool, nat_dst [N,4] uint32, nat_dport [N] int32
    (forward DNAT) and rnat [N] bool, rnat_src [N,4] uint32,
    rnat_sport [N] int32 (reply un-DNAT).
    counters: by_reason_dir [COUNTER_CELLS] uint32 (reasons x directions),
    insert_fail uint32 scalar, ct_evicted uint32 scalar (live entries
    tail-evicted by saturated inserts).

    ``fused=True`` routes the interior through the Pallas kernels of
    kernels/fused.py where each stage's static geometry permits
    (kernels/fused.fuse_plan — VMEM-resident tables, no rule-axis psum);
    ineligible stages fall back to the jnp reference per stage, so the
    choice is a per-shape trace-time constant, never data-dependent.
    ``fused_interpret`` runs those kernels in the Pallas interpreter (the
    CPU-CI bit-identity mode)."""
    if fused:
        from cilium_tpu.kernels import fused as fk
        plan = fk.fuse_plan(tensors, ct, v4_only=v4_only,
                            rule_axis=rule_axis)
    else:
        plan = None

    # 0-1. service LB + ipcache LPM + CT key derivation — the shared
    # pre-CT stage (classify_pre_ct; also the device-RSS exchange's local
    # prologue). The interior splits (ladder/L7 before compose) exactly
    # when the fused policy kernel is NOT taking the whole stage.
    split = plan is None or not plan.policy
    pre = classify_pre_ct(tensors, batch, world_index, v4_only=v4_only,
                          rule_axis=rule_axis, lb_probe_depth=lb_probe_depth,
                          plan=plan, fused_interpret=fused_interpret,
                          split_interior=split)
    batch = pre["batch"]
    valid = pre["valid"]
    direction = batch["direction"]
    no_backend = pre["no_backend"]
    svc = pre["svc"]
    fwd_keys, rev_keys = pre["fwd_keys"], pre["rev_keys"]

    # 2. conntrack probe (batch-start snapshot)
    if plan is not None and plan.ct:
        fwd_slot, rev_slot = fk.ct_probe_pair_fused(
            ct, fwd_keys, rev_keys, now, probe_depth,
            interpret=fused_interpret)
    else:
        fwd_slot = ctk.ct_probe(ct, fwd_keys, now, probe_depth)
        rev_slot = ctk.ct_probe(ct, rev_keys, now, probe_depth)
    est = valid & (fwd_slot >= 0)
    reply = valid & ~est & (rev_slot >= 0)
    new = valid & ~est & ~reply
    hit = est | reply
    hit_slot = jnp.where(est, fwd_slot, jnp.where(reply, rev_slot, 0))

    # 3-5. policy ladder + L7 token match + verdict composition (the fused
    # interior, or the split jnp core composed here — same semantics, see
    # classify_interior_core)
    if plan is not None and plan.policy:
        allow, reason, status, redirect, matched_rule = \
            fk.policy_verdict_fused(
                tensors, batch["ep_slot"], direction, pre["id_idx"],
                batch["proto"], batch["dport"], batch["http_method"],
                batch["http_path"], est, reply, valid,
                interpret=fused_interpret)
    else:
        allow, reason, status, redirect = compose_verdict(
            pre["decision"], pre["enforced"], pre["cell_redirect"],
            pre["l7_fail"], est, reply, valid)
        matched_rule = jnp.where(valid & pre["enforced"], pre["mrule"],
                                 jnp.int32(-1)).astype(jnp.int32)
    reason = jnp.where(no_backend, int(C.DropReason.NO_SERVICE), reason)

    # 6 + 6b-read. CT insert-when-full + aggregate apply + the batch-start
    # rev-NAT read (ct_update_stage — shared with the exchange path's
    # owner-side stage, so the tail-evict order has one source)
    new_ct, ct_full, entry_rnat, n_evicted = ct_update_stage(
        ct, fwd_keys, batch["proto"], batch["tcp_flags"], hit, hit_slot,
        reply, new, allow, pre["rev_nat"], now, probe_depth)
    allow = allow & ~ct_full
    reason = jnp.where(ct_full, int(C.DropReason.CT_FULL), reason)

    # 6b. reply un-DNAT resolution against the replicated lb_rnat_* planes
    rnat, rnat_src, rnat_sport = resolve_rev_nat(
        tensors, entry_rnat, reply, batch["src"], batch["sport"])

    # 7. counters (metricsmap analog: per reason × direction); no_backend
    # drops count under NO_SERVICE even though they are datapath-invalid
    counted = valid | no_backend
    by_reason_dir = tally_by_reason_dir(reason, direction, counted)
    counters = {
        "by_reason_dir": by_reason_dir,
        "insert_fail": ct_full.sum().astype(jnp.uint32),
        "ct_evicted": n_evicted,
    }
    remote_identity = pre["remote_identity"]
    lpm_prefix = pre["lpm_prefix"]

    out = {
        "allow": allow,
        "reason": reason,
        "status": status,
        # the CT-exhaustion signal (same truth class as ``status``: a
        # datapath-internal probe/insert fact as-of classification) — the
        # shadow auditor captures it so oracle.replay can re-derive the
        # CT_FULL deny without modeling the live table's occupancy
        "ct_full": ct_full,
        "remote_identity": remote_identity,
        "redirect": redirect,
        # match provenance (ISSUE 11): the evidence behind the verdict —
        # which policy cell the ladder resolved (matched_rule), which
        # ipcache prefix won the LPM walk (lpm_prefix, (slot<<8)|plen),
        # and the CT probe class as-of classification (ct_state_pre; an
        # explicit alias of ``status``, pinned as its own column so the
        # provenance contract survives any future post-mutation semantics
        # of status). Bit-identical across jnp / fused / oracle.
        "matched_rule": matched_rule,
        "lpm_prefix": lpm_prefix,
        "ct_state_pre": status,
        "svc": svc & valid,
        "nat_dst": batch["dst"],
        "nat_dport": batch["dport"].astype(jnp.int32),
        "rnat": rnat,
        "rnat_src": rnat_src,
        "rnat_sport": rnat_sport,
    }
    return out, new_ct, counters


#: make_classify_fn memo: repeated snapshot placements / engine restarts
#: previously built a FRESH closure (and so a fresh jit cache) per call —
#: every placement re-traced shapes the daemon had already compiled. One
#: jitted fn per static-config key; jax's own cache then dedupes per shape.
#:
#: LRU-bounded: a long-lived daemon cycling many distinct static configs
#: (probe depths, lb depths, fused toggles across restarts/tests) must not
#: grow the memo — and the jit caches it pins — without bound. Cap
#: overridable via CILIUM_TPU_CLASSIFY_FN_CACHE; evictions are counted and
#: exported by Engine.render_metrics (classify_fn_cache_evictions_total).
import collections
import os as _os

FN_CACHE_CAP = max(1, int(_os.environ.get(
    "CILIUM_TPU_CLASSIFY_FN_CACHE", "64")))
_FN_CACHE: "collections.OrderedDict" = collections.OrderedDict()
_FN_LOCK = threading.Lock()
_FN_EVICTIONS = [0]


def fn_cache_stats() -> dict:
    """Memo-cache observability: current size, cap, eviction count."""
    with _FN_LOCK:
        return {"size": len(_FN_CACHE), "cap": FN_CACHE_CAP,
                "evictions": _FN_EVICTIONS[0]}


def make_classify_fn(probe_depth: int = PROBE_DEPTH, v4_only: bool = False,
                     donate_ct: bool = True, packed: bool = False,
                     lb_probe_depth: int = 8, fused: bool = False,
                     fused_interpret: bool = False):
    """jit-compiled classify step. CT buffers are donated (in-place update,
    no double allocation); re-traces only when array shapes change.

    Memoized on the full static-argument key — callers that rebuild their
    datapath (engine restarts, repeated placements, tests) share one jitted
    callable and therefore one trace cache instead of re-tracing identical
    shapes per closure.

    ``packed=True``: the batch argument is the single contiguous uint32 wire
    array (kernels/records.pack_batch) — one host→device transfer instead of
    twelve; unpacking happens on device and fuses into the pipeline. This is
    the transfer-bound production path; the dict path stays for tests. The
    wire width selects the variant at trace time: 4 words = compact v4
    (pack_batch_v4), otherwise the full/L7 layout.

    ``fused``/``fused_interpret``: route the classify interior through the
    Pallas kernels (kernels/fused.py), optionally in interpreter mode (the
    CPU-CI bit-identity configuration) — see classify_step."""
    key = (probe_depth, v4_only, donate_ct, packed, lb_probe_depth,
           fused, fused_interpret)
    with _FN_LOCK:
        fn = _FN_CACHE.get(key)
        if fn is not None:
            _FN_CACHE.move_to_end(key)     # LRU touch
            return fn

    def fn(tensors, ct, batch, now, world_index):
        if packed:
            from cilium_tpu.kernels.records import unpack_wire_jnp
            batch = unpack_wire_jnp(batch)
        return classify_step(tensors, ct, batch, now, world_index,
                             probe_depth=probe_depth, v4_only=v4_only,
                             lb_probe_depth=lb_probe_depth, fused=fused,
                             fused_interpret=fused_interpret)
    fn = jax.jit(fn, donate_argnums=(1,) if donate_ct else ())
    with _FN_LOCK:
        cached = _FN_CACHE.get(key)
        if cached is not None:             # lost the build race: reuse
            _FN_CACHE.move_to_end(key)
            return cached
        _FN_CACHE[key] = fn
        while len(_FN_CACHE) > FN_CACHE_CAP:
            _FN_CACHE.popitem(last=False)  # evict least-recently-used
            _FN_EVICTIONS[0] += 1
        return fn
