"""The fused classify step — one jitted program per batch (the analog of one
eBPF datapath run over a batch of packets; SURVEY.md §3.3: "TPU equivalent:
one fused kernel: gather(ipcache-LPM) → conntrack probe → policy
wildcard-ladder as masked [compile-time] resolution → verdict + CT update,
batched over N headers").

Branch-free: every packet takes every path, masks select. XLA fuses the
elementwise pipeline between the gathers; the scatters at the end form the
CT write phase.
"""

from __future__ import annotations



import jax
import jax.numpy as jnp

from cilium_tpu.compile.ct_layout import PROBE_DEPTH
from cilium_tpu.kernels import conntrack as ctk
from cilium_tpu.kernels.l7 import l7_match_batch
from cilium_tpu.kernels.lb import lb_step
from cilium_tpu.kernels.lpm import lpm_lookup_batch
from cilium_tpu.kernels.policy import policy_lookup_batch
from cilium_tpu.utils import constants as C

N_REASON_BINS = C.DROP_REASON_BINS   # counter-tensor geometry (one source)


def classify_step(tensors, ct, batch, now, world_index=0, *,
                  probe_depth: int = PROBE_DEPTH, v4_only: bool = False,
                  rule_axis=None, lb_probe_depth: int = 8):
    # ``world_index`` is a traced scalar (not static): it changes whenever the
    # identity table grows, and baking it in would force a re-jit per snapshot.
    # ``rule_axis`` names a mesh axis for rule-space (verdict-row) sharding.
    """→ (out, new_ct, counters).

    out: allow [N] bool, reason [N] int32 (DropReason), status [N] int32
    (CTStatus), remote_identity [N] uint32, redirect [N] bool, plus the NAT
    rewrite columns the shim applies: svc [N] bool, nat_dst [N,4] uint32,
    nat_dport [N] int32 (forward DNAT) and rnat [N] bool, rnat_src [N,4]
    uint32, rnat_sport [N] int32 (reply un-DNAT).
    counters: by_reason_dir [COUNTER_CELLS] uint32 (reasons x directions),
    insert_fail uint32 scalar.
    """
    valid = batch["valid"]
    direction = batch["direction"]

    # 0. service LB (bpf/lib/lb.h analog): frontend match → Maglev backend →
    # DNAT. Everything downstream (LPM, CT, policy) sees the translated
    # tuple, exactly like the upstream from-container path (LB before
    # policy). ``no_backend`` drops below.
    has_lb = "lb_tab_keys" in tensors
    if has_lb:
        new_dst, new_dport, rev_nat, no_backend = lb_step(
            tensors, batch, probe_depth=lb_probe_depth)
        svc = rev_nat > 0
        batch = dict(batch)
        batch["dst"] = new_dst
        batch["dport"] = new_dport
        valid = valid & ~no_backend
    else:
        n = valid.shape[0]
        rev_nat = jnp.zeros((n,), dtype=jnp.int32)
        svc = jnp.zeros((n,), dtype=bool)
        no_backend = jnp.zeros((n,), dtype=bool)

    # 1. ipcache LPM: remote = dst on egress, src on ingress
    remote_words = jnp.where((direction == C.DIR_EGRESS)[:, None],
                             batch["dst"], batch["src"])
    id_idx = lpm_lookup_batch(tensors["lpm_v4"], tensors["lpm_v6"],
                              remote_words, batch["is_v6"],
                              default_index=world_index, v4_only=v4_only)
    remote_identity = tensors["identity_ids"][id_idx].astype(jnp.uint32)

    # 2. conntrack probe (batch-start snapshot)
    fwd_keys = ctk.ct_key_words_jnp(batch, reverse=False)
    rev_keys = ctk.ct_key_words_jnp(batch, reverse=True)
    fwd_slot = ctk.ct_probe(ct, fwd_keys, now, probe_depth)
    rev_slot = ctk.ct_probe(ct, rev_keys, now, probe_depth)
    est = valid & (fwd_slot >= 0)
    reply = valid & ~est & (rev_slot >= 0)
    new = valid & ~est & ~reply
    hit = est | reply
    hit_slot = jnp.where(est, fwd_slot, jnp.where(reply, rev_slot, 0))

    # 3. policy (ladder already resolved into the dense image)
    decision, l7_cell, enforced = policy_lookup_batch(
        tensors, batch["ep_slot"], direction, id_idx,
        batch["proto"], batch["dport"], rule_axis=rule_axis)
    cell_redirect = decision == C.VERDICT_REDIRECT

    # 4. L7-lite: the CURRENT policy cell's rules apply to every packet with
    # tokens — new and established flows alike (the per-request proxy
    # semantics; CT entries carry no L7 state, so policy swaps need no remap)
    has_tokens = (batch["http_method"] != C.HTTP_METHOD_ANY) \
        | (batch["http_path"] != 0).any(axis=-1)
    set_to_check = jnp.where(cell_redirect, l7_cell, 0)
    l7_ok = l7_match_batch(tensors, set_to_check, batch["http_method"],
                           batch["http_path"])
    l7_fail = has_tokens & (set_to_check > 0) & ~l7_ok

    # 5. verdict composition (mirrors oracle classify())
    new_allow = jnp.where(
        decision == C.VERDICT_DENY, False,
        jnp.where(decision == C.VERDICT_MISS, ~enforced,
                  ~l7_fail))  # ALLOW always passes; REDIRECT unless l7_fail
    allow = jnp.where(hit, ~l7_fail, new_allow) & valid
    reason = jnp.where(
        hit,
        jnp.where(l7_fail, int(C.DropReason.POLICY_L7), int(C.DropReason.OK)),
        jnp.where(
            decision == C.VERDICT_DENY, int(C.DropReason.POLICY_DENY),
            jnp.where(decision == C.VERDICT_MISS,
                      jnp.where(enforced, int(C.DropReason.POLICY),
                                int(C.DropReason.OK)),
                      jnp.where(l7_fail, int(C.DropReason.POLICY_L7),
                                int(C.DropReason.OK)))),
    ).astype(jnp.int32)
    reason = jnp.where(no_backend, int(C.DropReason.NO_SERVICE), reason)
    status = jnp.where(est, int(C.CTStatus.ESTABLISHED),
                       jnp.where(reply, int(C.CTStatus.REPLY),
                                 int(C.CTStatus.NEW))).astype(jnp.int32)
    redirect = valid & cell_redirect

    # 6. CT insert for allowed new flows, then aggregate effects
    want_insert = new & allow
    new_keys, new_created, zero_mask, slot_new, fail = \
        ctk.ct_insert_new(ct, fwd_keys, want_insert, now, probe_depth)
    slot = jnp.where(hit, hit_slot, slot_new)
    contrib = allow & (jnp.where(hit, True, slot_new >= 0))
    new_ct = ctk.ct_apply(ct, batch, slot, reply, contrib, now,
                          new_keys=new_keys, new_created=new_created,
                          zero_mask=zero_mask, rev_nat_vals=rev_nat)

    # 6b. reply un-DNAT (lb4_rev_nat analog): a reply on a service flow
    # carries the CT entry's stable rev-NAT id → rewrite src back to the
    # VIP. Ids whose service is gone resolve to an invalid row → no rewrite
    # (fail closed; never another service's VIP).
    if has_lb:
        slot_safe = jnp.where(hit_slot >= 0, hit_slot, 0)
        entry_rnat = ct["rev_nat"][slot_safe].astype(jnp.int32)
        n_rnat = tensors["lb_rnat_valid"].shape[0]
        rid = entry_rnat - 1
        known = (rid >= 0) & (rid < n_rnat)
        rid_safe = jnp.where(known, rid, 0)
        rnat = reply & known & tensors["lb_rnat_valid"][rid_safe]
        rnat_src = jnp.where(rnat[:, None], tensors["lb_rnat_addr"][rid_safe],
                             batch["src"])
        rnat_sport = jnp.where(rnat, tensors["lb_rnat_port"][rid_safe],
                               batch["sport"]).astype(jnp.int32)
    else:
        rnat = jnp.zeros_like(svc)
        rnat_src = batch["src"]
        rnat_sport = batch["sport"].astype(jnp.int32)

    # 7. counters (metricsmap analog: per reason × direction); no_backend
    # drops count under NO_SERVICE even though they are datapath-invalid
    counted = valid | no_backend
    bin_idx = reason * 2 + direction
    scat = jnp.where(counted, bin_idx, N_REASON_BINS * 2)
    by_reason_dir = jnp.zeros((N_REASON_BINS * 2,), dtype=jnp.uint32).at[scat].add(
        jnp.uint32(1), mode="drop")
    counters = {
        "by_reason_dir": by_reason_dir,
        "insert_fail": fail.sum().astype(jnp.uint32),
    }

    out = {
        "allow": allow,
        "reason": reason,
        "status": status,
        "remote_identity": remote_identity,
        "redirect": redirect,
        "svc": svc & valid,
        "nat_dst": batch["dst"],
        "nat_dport": batch["dport"].astype(jnp.int32),
        "rnat": rnat,
        "rnat_src": rnat_src,
        "rnat_sport": rnat_sport,
    }
    return out, new_ct, counters


def make_classify_fn(probe_depth: int = PROBE_DEPTH, v4_only: bool = False,
                     donate_ct: bool = True, packed: bool = False):
    """jit-compiled classify step. CT buffers are donated (in-place update,
    no double allocation); re-traces only when array shapes change.

    ``packed=True``: the batch argument is the single contiguous uint32 wire
    array (kernels/records.pack_batch) — one host→device transfer instead of
    twelve; unpacking happens on device and fuses into the pipeline. This is
    the transfer-bound production path; the dict path stays for tests. The
    wire width selects the variant at trace time: 4 words = compact v4
    (pack_batch_v4), otherwise the full/L7 layout."""
    def fn(tensors, ct, batch, now, world_index):
        if packed:
            from cilium_tpu.kernels.records import unpack_wire_jnp
            batch = unpack_wire_jnp(batch)
        return classify_step(tensors, ct, batch, now, world_index,
                             probe_depth=probe_depth, v4_only=v4_only)
    return jax.jit(fn, donate_argnums=(1,) if donate_ct else ())
