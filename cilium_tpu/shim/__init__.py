"""Native ingress shim + golden-vector generator (C++, ctypes-bound).

Build with ``make -C cilium_tpu/shim`` (or ``make shim`` at repo root).
"""
