"""Native ingress shim + golden-vector generator (C++, ctypes-bound) and
the async shim→pipeline feeder (``shim/feeder.py``).

Build with ``make -C cilium_tpu/shim`` (or ``make shim`` at repo root).
"""

__all__ = ["ShimFeeder"]


def __getattr__(name):
    # lazy: importing the package must not require the built .so (feeder
    # pulls in bindings, which raises without libflowshim.so)
    if name == "ShimFeeder":
        from cilium_tpu.shim.feeder import ShimFeeder
        return ShimFeeder
    raise AttributeError(name)
