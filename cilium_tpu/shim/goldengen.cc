// goldengen: independent C++ golden-vector generator (SURVEY.md §2 native
// checklist item 3 — the BPF-unit-test-harness analog: "C++ golden-vector
// generator replaying the same packet constructions").
//
// Reads a binary scenario (ipcache prefixes + MapState entries + L7 sets +
// packet stream) and computes, with its OWN implementation of the verdict
// contract (sequential eBPF semantics: LPM → CT → deny-wins /
// most-specific-allow ladder → L7 → CT update), the expected verdict for
// every packet. The parity suite runs three implementations against each
// other: this generator, the Python oracle, and the TPU kernels.
//
// Scenario format (little-endian, see tests/test_goldengen.py writer):
//   magic   "CTPUGV01"
//   u32 n_ipcache;  n × { u8 addr[16]; u16 plen; u8 is_v6; u8 pad; u32 id }
//   u8 enforced[2]              // egress, ingress
//   u32 n_entries;  n × { u8 dir; u8 deny; u8 proto; u8 pad; u32 identity;
//                         u16 port_lo; u16 port_hi; u16 l7_set; u16 pad }
//   u32 n_l7sets;   per set: u32 n_rules × { u8 method; u8 path_len;
//                                            u8 path[64] }
//   u32 n_packets;  n × { u8 src[16]; u8 dst[16]; u16 sport; u16 dport;
//                         u8 proto; u8 tcp_flags; u8 is_v6; u8 direction;
//                         u8 has_tokens; u8 method; u16 path_len;
//                         u8 path[64]; u32 now }
// Output: n_packets × { u8 allow; u8 reason; u8 status; u8 pad; u32 remote }

#include <stdint.h>
#include <string.h>

#include <algorithm>
#include <array>
#include <cstdio>
#include <cstdlib>
#include <map>
#include <string>
#include <vector>

namespace {

// --- constants mirroring utils/constants.py (independently stated) ---------
constexpr uint32_t kWorld = 2;
constexpr int kDeny = 133, kPolicy = 130, kL7 = 180, kOk = 0;
constexpr int kNew = 0, kEst = 1, kReply = 2;
constexpr int kFin = 1, kSyn = 2, kRst = 4;
constexpr int kSeenNonSyn = 1, kTxClosing = 2, kRxClosing = 4;
constexpr uint32_t kLifeSyn = 60, kLifeTcp = 21600, kLifeNon = 60, kLifeClose = 10;

struct Prefix {
  std::array<uint8_t, 16> addr;
  int plen;
  bool is_v6;
  uint32_t id;
};

struct Entry {
  int dir, deny, proto;
  uint32_t identity;
  int lo, hi, l7;
};

struct L7Rule {
  int method;  // 255 = any
  std::string path;
};

struct Packet {
  std::array<uint8_t, 16> src, dst;
  int sport, dport, proto, flags, is_v6, dir, has_tokens, method;
  std::string path;
  uint32_t now;
};

struct CTEntry {
  uint32_t expiry = 0;
  int flags = 0;
};

bool prefix_covers(const Prefix& p, const std::array<uint8_t, 16>& a) {
  int full = p.plen / 8, rem = p.plen % 8;
  if (memcmp(p.addr.data(), a.data(), full) != 0) return false;
  if (rem == 0) return true;
  uint8_t mask = uint8_t(0xFF << (8 - rem));
  return (p.addr[full] & mask) == (a[full] & mask);
}

uint32_t lpm(const std::vector<Prefix>& table,
             const std::array<uint8_t, 16>& addr, bool is_v6) {
  int best_len = -1;
  uint32_t best = kWorld;
  for (const auto& p : table) {
    if (p.is_v6 != is_v6) continue;
    if (p.plen >= best_len + 1 && prefix_covers(p, addr)) {
      if (p.plen > best_len) {
        best_len = p.plen;
        best = p.id;
      }
    }
  }
  return best;
}

int flag_delta(int proto, int tcp_flags, bool reply) {
  if (proto != 6) return 0;
  int d = 0;
  if (tcp_flags & (kFin | kRst)) {
    d |= reply ? kRxClosing : kTxClosing;
    if (tcp_flags & kRst) d |= kTxClosing | kRxClosing;
  }
  if (!(tcp_flags & kSyn)) d |= kSeenNonSyn;
  return d;
}

uint32_t lifetime(int proto, int flags) {
  if (proto != 6) return kLifeNon;
  if (flags & (kTxClosing | kRxClosing)) return kLifeClose;
  if (flags & kSeenNonSyn) return kLifeTcp;
  return kLifeSyn;
}

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;
  template <typename T>
  T get() {
    T v{};
    if (p + sizeof(T) > end) {
      fail = true;
      return v;
    }
    memcpy(&v, p, sizeof(T));
    p += sizeof(T);
    return v;
  }
  void bytes(void* out, size_t n) {
    if (p + n > end) {
      fail = true;
      return;
    }
    memcpy(out, p, n);
    p += n;
  }
};

}  // namespace

int main(int argc, char** argv) {
  if (argc != 3) {
    fprintf(stderr, "usage: goldengen <scenario.bin> <out.bin>\n");
    return 2;
  }
  FILE* f = fopen(argv[1], "rb");
  if (!f) {
    perror("open scenario");
    return 2;
  }
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> buf(sz);
  if (fread(buf.data(), 1, sz, f) != size_t(sz)) {
    fclose(f);
    return 2;
  }
  fclose(f);

  Reader rd{buf.data(), buf.data() + buf.size()};
  char magic[8];
  rd.bytes(magic, 8);
  if (rd.fail || memcmp(magic, "CTPUGV01", 8) != 0) {
    fprintf(stderr, "bad magic\n");
    return 2;
  }

  std::vector<Prefix> ipcache(rd.get<uint32_t>());
  for (auto& p : ipcache) {
    rd.bytes(p.addr.data(), 16);
    p.plen = rd.get<uint16_t>();
    p.is_v6 = rd.get<uint8_t>();
    rd.get<uint8_t>();
    p.id = rd.get<uint32_t>();
  }
  uint8_t enforced[2];
  rd.bytes(enforced, 2);
  std::vector<Entry> entries(rd.get<uint32_t>());
  for (auto& e : entries) {
    e.dir = rd.get<uint8_t>();
    e.deny = rd.get<uint8_t>();
    e.proto = rd.get<uint8_t>();
    rd.get<uint8_t>();
    e.identity = rd.get<uint32_t>();
    e.lo = rd.get<uint16_t>();
    e.hi = rd.get<uint16_t>();
    e.l7 = rd.get<uint16_t>();
    rd.get<uint16_t>();
  }
  std::vector<std::vector<L7Rule>> l7sets(rd.get<uint32_t>());
  for (auto& set : l7sets) {
    set.resize(rd.get<uint32_t>());
    for (auto& r : set) {
      r.method = rd.get<uint8_t>();
      int n = rd.get<uint8_t>();
      char pathbuf[64];
      rd.bytes(pathbuf, 64);
      r.path.assign(pathbuf, pathbuf + n);
    }
  }
  std::vector<Packet> packets(rd.get<uint32_t>());
  for (auto& pk : packets) {
    rd.bytes(pk.src.data(), 16);
    rd.bytes(pk.dst.data(), 16);
    pk.sport = rd.get<uint16_t>();
    pk.dport = rd.get<uint16_t>();
    pk.proto = rd.get<uint8_t>();
    pk.flags = rd.get<uint8_t>();
    pk.is_v6 = rd.get<uint8_t>();
    pk.dir = rd.get<uint8_t>();
    pk.has_tokens = rd.get<uint8_t>();
    pk.method = rd.get<uint8_t>();
    int n = rd.get<uint16_t>();
    char pathbuf[64];
    rd.bytes(pathbuf, 64);
    pk.path.assign(pathbuf, pathbuf + std::min(n, 64));
    pk.now = rd.get<uint32_t>();
  }
  if (rd.fail) {
    fprintf(stderr, "truncated scenario\n");
    return 2;
  }

  // --- sequential datapath ---------------------------------------------------
  using CTKey = std::array<uint8_t, 38>;  // src16 dst16 sport2 dport2 proto dir
  auto make_key = [](const Packet& pk, bool rev) {
    CTKey k{};
    const auto& s = rev ? pk.dst : pk.src;
    const auto& d = rev ? pk.src : pk.dst;
    int sp = rev ? pk.dport : pk.sport, dp = rev ? pk.sport : pk.dport;
    int dir = rev ? 1 - pk.dir : pk.dir;
    memcpy(k.data(), s.data(), 16);
    memcpy(k.data() + 16, d.data(), 16);
    k[32] = sp >> 8;
    k[33] = sp & 0xFF;
    k[34] = dp >> 8;
    k[35] = dp & 0xFF;
    k[36] = uint8_t(pk.proto);
    k[37] = uint8_t(dir);
    return k;
  };
  std::map<CTKey, CTEntry> ct;

  FILE* out = fopen(argv[2], "wb");
  if (!out) {
    perror("open out");
    return 2;
  }

  for (const auto& pk : packets) {
    uint32_t remote =
        lpm(ipcache, pk.dir == 0 ? pk.dst : pk.src, pk.is_v6 != 0);

    // CT probe
    CTKey fwd = make_key(pk, false), rev = make_key(pk, true);
    int status = kNew;
    CTKey* hit = nullptr;
    auto itf = ct.find(fwd);
    if (itf != ct.end() && itf->second.expiry > pk.now) {
      status = kEst;
      hit = &fwd;
    } else {
      auto itr = ct.find(rev);
      if (itr != ct.end() && itr->second.expiry > pk.now) {
        status = kReply;
        hit = &rev;
      }
    }

    // policy ladder against the sparse entries (deny wins; then most
    // specific: (spec, -width, port_lo) — the documented total order)
    int decision = 0;  // 0 miss 1 allow 2 deny 3 redirect
    int best_l7 = 0;
    bool dir_enforced = enforced[pk.dir] != 0;
    if (dir_enforced) {
      long best_rank = -1;
      bool denied = false;
      for (const auto& e : entries) {
        if (e.dir != pk.dir) continue;
        if (e.identity != 0 && e.identity != remote) continue;
        if (e.proto != 0 && e.proto != pk.proto) continue;
        if (pk.dport < e.lo || pk.dport > e.hi) continue;
        if (e.deny) {
          denied = true;
          continue;
        }
        int spec = (e.identity != 0) * 4 + (e.proto != 0) * 2 +
                   !(e.lo == 0 && e.hi == 65535);
        long rank = (long(spec) << 33) |
                    (long(65535 - (e.hi - e.lo)) << 16) | e.lo;
        if (rank > best_rank) {
          best_rank = rank;
          decision = e.l7 > 0 ? 3 : 1;
          best_l7 = e.l7;
        }
      }
      if (denied) decision = 2;
      else if (best_rank < 0) decision = 0;
    }

    bool l7_fail = false;
    if (decision == 3 && pk.has_tokens) {
      bool ok = false;
      for (const auto& r : l7sets[best_l7 - 1]) {
        bool m_ok = r.method == 255 || r.method == pk.method;
        if (m_ok && pk.path.compare(0, r.path.size(), r.path) == 0) {
          ok = true;
          break;
        }
      }
      l7_fail = !ok;
    }

    uint8_t allow, reason;
    if (status != kNew) {
      allow = l7_fail ? 0 : 1;
      reason = l7_fail ? kL7 : kOk;
      if (allow) {
        CTEntry& e = ct[*hit];
        e.flags |= flag_delta(pk.proto, pk.flags, status == kReply);
        e.expiry = pk.now + lifetime(pk.proto, e.flags);
      }
    } else if (!dir_enforced) {
      allow = 1;
      reason = kOk;
    } else if (decision == 2) {
      allow = 0;
      reason = kDeny;
    } else if (decision == 0) {
      allow = 0;
      reason = kPolicy;
    } else {
      allow = l7_fail ? 0 : 1;
      reason = l7_fail ? kL7 : kOk;
    }
    if (status == kNew && allow) {
      CTEntry e;
      e.flags = flag_delta(pk.proto, pk.flags, false);
      e.expiry = pk.now + lifetime(pk.proto, e.flags);
      ct[fwd] = e;
    }

    uint8_t rec[8] = {allow, reason, uint8_t(status), 0};
    memcpy(rec + 4, &remote, 4);
    fwrite(rec, 1, 8, out);
  }
  fclose(out);
  return 0;
}
