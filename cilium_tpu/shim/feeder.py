"""Async shim→pipeline feeder: the harvest half of zero-copy ingestion.

Before this module the shim path was synchronous per poll: poll a batch,
classify it with a blocking wait, apply verdicts, repeat — the device idles
during every harvest and the host idles during every classify. The feeder
replaces that loop with a harvest thread that

- polls the :class:`~cilium_tpu.shim.bindings.FlowShim` on a budget
  (AF_XDP rings and the heap-mocked rings drain through ``afxdp_poll``;
  the plain mock batcher through ``poll_batch`` alone),
- writes harvested columns straight into a small pool of reusable poll
  buffers (``FlowShim.make_poll_buffer`` — no per-poll column dict),
- maps shim endpoint ids onto the active snapshot's slots (vectorized,
  lookup table cached per snapshot; unknown endpoints fail closed),
- submits each buffer to the engine's ingestion pipeline and
- applies verdicts **FIFO** as tickets resolve — the C++ shim holds one
  FrameRef per emitted record, so verdict order must equal harvest order;
  a rejected/shed/timed-out ticket is applied as all-drop (fail closed)
  rather than skipped, which would desync frames from verdicts.

Buffer lifecycle: a poll buffer stays owned by the pipeline from submit
until its ticket resolves (the scheduler stages from it asynchronously),
so the pool bounds feeder in-flight batches; when every buffer is busy the
feeder blocks on the oldest ticket — natural backpressure from the device
straight back to the rx ring (frames simply wait in the ring).

Fault tolerance: the ``shim.rx_ring`` injection point fires inside both
poll entry points; a trip is one failed poll — frames stay queued and
drain on the next poll. ``stop()`` drains: remaining rx frames are
force-harvested, submitted, and every pending verdict applied in order.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, Optional

import numpy as np

from cilium_tpu.observe.trace import TRACER, Tracer
from cilium_tpu.runtime.faults import FaultInjected
from cilium_tpu.runtime.metrics import Metrics
from cilium_tpu.shim.bindings import MAX_UNVERDICTED_BATCHES, FlowShim
from cilium_tpu.utils import constants as C

log = logging.getLogger("cilium_tpu.feeder")

#: dense-LUT cap: one sparse/huge ep_id must not turn the per-snapshot
#: LUT rebuild into a multi-GB allocation — fall back to dict lookups
DENSE_LUT_MAX = 1 << 20

#: established-flow filter geometry (pow2 slots): a direct-mapped
#: fingerprint table of recently-established flow hashes — the harvest-time
#: priority heuristic, NOT semantics (a collision merely promotes a flood
#: flow's priority class; verdicts are untouched)
EST_FILTER_SLOTS = 1 << 16


def flow_hashes(b: Dict[str, np.ndarray]) -> np.ndarray:
    """Direction-normalized flow hash per row (fwd XOR rev key hash — both
    directions of a flow agree). The UNtranslated tuple, deliberately:
    priority classing is a heuristic and must only be self-consistent
    between its update (verdict apply) and lookup (harvest) sides; the
    steering path keeps its own LB-translated hash."""
    from cilium_tpu.kernels.hashing import hash_words_np
    from cilium_tpu.kernels.records import ct_key_words
    return (hash_words_np(ct_key_words(b))
            ^ hash_words_np(ct_key_words(b, reverse=True)))


class EstablishedFingerprints:
    """Direct-mapped fingerprint table of flows observed allowed-
    ESTABLISHED/REPLY (pow2 slots; slot ``h & mask`` holds ``h | 1`` so an
    empty slot can never read as a hit for hash 0). Two consumers share
    the exact same update/lookup discipline:

    - the feeder's harvest-time priority classing (a hit ranks the row
      PRIO_ESTABLISHED — heuristic only, a collision merely promotes a
      colliding flow's class);
    - the engine's post-remesh CT-salvage grace window (ISSUE 19): a
      denied row whose fingerprint was established before the device loss
      rides through while the survivor mesh's CT cold-learns — there a
      collision admits one flow for a bounded window, which is exactly
      the documented grace contract, never a policy bypass outside it.

    ``note`` never raises (both call sites are verdict hot paths)."""

    def __init__(self, slots: int = EST_FILTER_SLOTS):
        if slots < 1 or slots & (slots - 1):
            raise ValueError("fingerprint slots must be a power of two")
        self._tab = np.zeros((slots,), dtype=np.uint32)
        self._mask = np.uint32(slots - 1)

    def note(self, buf: Dict[str, np.ndarray],
             out: Dict[str, np.ndarray]) -> None:
        """Stamp fingerprints for rows applied allowed-ESTABLISHED/REPLY."""
        try:
            st = np.asarray(out["status"])
            m = (np.asarray(out["allow"])
                 & ((st == int(C.CTStatus.ESTABLISHED))
                    | (st == int(C.CTStatus.REPLY)))
                 & np.asarray(buf["valid"]))
            if not m.any():
                return
            cols = {k: np.asarray(buf[k])[m]
                    for k in ("src", "dst", "sport", "dport", "proto",
                              "direction")}
            h = flow_hashes(cols)
            self._tab[h & self._mask] = h | np.uint32(1)
        except Exception:   # noqa: BLE001 — heuristic, never load-bearing
            log.exception("established-fingerprint update failed")

    def hits(self, buf: Dict[str, np.ndarray]) -> np.ndarray:
        """[N] bool: rows whose direction-normalized fingerprint is
        stamped. Row-aligned with ``buf``; validity is the caller's mask."""
        h = flow_hashes(buf)
        return self._tab[h & self._mask] == (h | np.uint32(1))


def shed_new_rows(b: Dict[str, np.ndarray]) -> int:
    """The SHED-NEW harvest-time shed, shared by the feeder and the cfg6
    bench's synthetic harvest: invalidate every valid row whose ``_prio``
    class is worse than established — those frames get their drop verdict
    at apply time without EVER being submitted (rx-ring backpressure
    relief), while established-class rows ride on. Returns rows shed."""
    from cilium_tpu.pipeline.guard import PRIO_ESTABLISHED
    v = b["valid"]
    m = v & (np.asarray(b["_prio"]) > PRIO_ESTABLISHED)
    n = int(m.sum())
    if n:
        v[m] = False                    # in place: poll buffers are pooled
    return n


def build_slot_lut(slot_of: Dict[int, int],
                   dense_max: int = DENSE_LUT_MAX
                   ) -> Optional[np.ndarray]:
    """ep_id → slot lookup array for one snapshot (None when the id space
    is too sparse to densify — callers fall back to dict lookups)."""
    size = max(slot_of, default=0) + 1
    if size > dense_max:
        return None
    lut = np.full((size,), -1, dtype=np.int32)
    for ep_id, slot in slot_of.items():
        if 0 <= ep_id < size:
            lut[ep_id] = slot
    return lut


def map_raw_slots(raw: np.ndarray, slot_of: Dict[int, int],
                  lut: Optional[np.ndarray]) -> np.ndarray:
    """[N] raw shim ep ids → [N] snapshot slots; -1 for unknown ids AND
    for raw == 0 ("no id"). Vectorized through the dense LUT when one
    exists, per-row dict lookups otherwise. Shared by the feeder's
    harvest-time mapping and the engine's dispatch-time re-mapping so the
    fail-closed semantics cannot diverge."""
    if lut is not None:
        slots = lut[np.clip(raw, 0, lut.size - 1)]
        # out-of-range ids INCLUDING negatives fail closed (a negative
        # would otherwise wrap-index the LUT and steal another
        # endpoint's slot); 0 means "no id"
        return np.where((raw >= lut.size) | (raw <= 0),
                        np.int32(-1), slots)
    return np.fromiter(
        (slot_of.get(int(e), -1) if e else -1 for e in raw),
        dtype=np.int32, count=raw.shape[0])


class ShimFeeder:
    """Harvest thread feeding one ``FlowShim`` into one engine's pipeline.

    ``engine`` needs ``submit(batch, now=...) -> Ticket`` and
    ``active.snapshot`` (slot mapping) — the real Engine, or any
    duck-typed stand-in in tests."""

    def __init__(self, shim: FlowShim, engine, *,
                 pool_batches: int = 4,
                 poll_budget: int = 256,
                 idle_sleep_s: float = 0.0005,
                 n_shards: int = 1,
                 slo_ms: float = 0.0,
                 metrics: Optional[Metrics] = None,
                 tracer: Optional[Tracer] = None,
                 event_sink=None,
                 qos=None,
                 fqdn=None,
                 name: str = "feeder"):
        if not 1 <= pool_batches <= MAX_UNVERDICTED_BATCHES:
            raise ValueError(
                f"pool_batches must be in [1, {MAX_UNVERDICTED_BATCHES}] "
                "(the shim ages out unverdicted batches past that)")
        if poll_budget < 1:
            raise ValueError("poll_budget must be >= 1")
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.shim = shim
        self.engine = engine
        self.metrics = metrics if metrics is not None else Metrics()
        self.tracer = tracer if tracer is not None else TRACER
        self._poll_budget = poll_budget
        self._idle_sleep_s = idle_sleep_s
        self._n_shards = n_shards
        self._name = name
        # guard-event sink (the flight recorder): SHED-NEW harvest drops
        # are narrated as kind="shed" reason="shed-new" events — the
        # relaxed spike class (observe/blackbox.RELAXED_SHED_REASONS).
        # Fired outside any lock, exceptions swallowed.
        self._event_sink = event_sink
        # end-to-end latency SLO: harvest stamp → verdict apply, the TRUE
        # ingest→verdict number (queue wait + staging + dispatch + device +
        # FIFO head-of-line wait). slo_ms > 0 arms the burn counters.
        self._slo_s = slo_ms / 1e3 if slo_ms > 0 else 0.0

        self._free: deque = deque(shim.make_poll_buffer()
                                  for _ in range(pool_batches))
        # overload-ladder level (set by the engine's overload controller);
        # >= SHED_NEW arms the harvest-time priority shed
        self._overload_level = 0
        # priority classing: every poll buffer carries a ``_prio`` column
        # (pipeline/guard.PRIO_*) the admission queue ranks batches by;
        # the established-flow filter below feeds class 0
        from cilium_tpu.pipeline.guard import PRIO_NEW
        for buf in self._free:
            buf["_prio"] = np.full((shim.batch_size,), PRIO_NEW,
                                   dtype=np.int8)
        self._est = EstablishedFingerprints()
        # multi-tenant QoS (cilium_tpu/qos): with a TenantTable armed,
        # every poll buffer carries a ``_tenant`` column stamped at
        # harvest time from the endpoint→tenant LUT (same compiled-LUT
        # discipline as the ep-slot map below) — the admission queue's
        # weighted-fair scheduling and the per-tenant e2e/SLO families
        # key on it. QoS off: no column, zero extra work per poll.
        self._qos = qos
        if qos is not None:
            for buf in self._free:
                buf["_tenant"] = np.zeros((shim.batch_size,),
                                          dtype=np.int32)
        # in-band DNS plane (cilium_tpu/fqdn): with a DNSProxy armed,
        # every poll buffer carries the harvested DNS response payload
        # (``_dns_payload`` [batch, W] uint8, ``_dns_len`` [batch] int32)
        # the verdict-apply tap parses into the FQDN cache. The native
        # C++ shim has no payload channel and never writes either column
        # (the tap sees len==0 everywhere and no-ops); DNS-capable shim
        # stand-ins fill both during poll_batch. Proxy off: no columns,
        # zero extra work per poll.
        self._fqdn = fqdn
        if fqdn is not None:
            w = int(getattr(fqdn, "payload_width", 512))
            for buf in self._free:
                buf["_dns_payload"] = np.zeros((shim.batch_size, w),
                                               dtype=np.uint8)
                buf["_dns_len"] = np.zeros((shim.batch_size,),
                                           dtype=np.int32)
        if n_shards > 1:
            # software RSS (SURVEY §2), HOST steering mode only: harvest
            # pre-bins each record by the direction-normalized flow hash
            # so the pipeline's flush-time scatter is a plain copy, never
            # a re-hash. With ``rss_mode="device"`` the engine passes
            # n_shards=1 (the datapath's ``pipeline_shards``) and this
            # whole block — the harvest-side half of the host RSS tax —
            # disappears: the in-kernel ppermute exchange owns flow→shard
            # resolution. The column carries the SHARD_BIN encoding —
            # shard+1 in the low bits (0 = "not binned", the staging
            # ring's convention for optional ``_*`` columns) and the
            # binning policy revision above, so a bin hashed under a
            # superseded LB table is re-hashed at stage-write instead of
            # stranding a service flow's CT entry on the wrong shard —
            # and rides the same reusable poll buffers.
            for buf in self._free:
                buf["_shard"] = np.zeros((shim.batch_size,), dtype=np.int64)
        self._pending: deque = deque()     # (ticket, buf) in harvest order
        self._zeros = np.zeros((shim.batch_size,), dtype=bool)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._rings: Optional[bool] = None  # afxdp/mock rings attached?
        self._snap = None                   # slot-lookup cache key
        self._slot_lut = np.full((1,), -1, dtype=np.int32)

        # stats (single-writer: the feeder thread; read via stats())
        self.harvested_batches = 0
        self.harvested_records = 0
        self.applied_batches = 0
        self.rejected_batches = 0          # applied fail-closed
        self.harvest_faults = 0
        self.errors = 0                    # unexpected step failures
        self.slo_burns = 0                 # applied batches past the SLO
        self.prio_shed_rows = 0            # SHED-NEW harvest-time drops
        self.prio_shed_batches = 0         # batches never submitted at all
        self._submit_rejects = 0           # log-throttle counter

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ShimFeeder":
        if self._thread is not None:
            return self
        self._stop.clear()      # restart after a clean stop() must harvest
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"{self._name}-harvest")
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        """Stop harvesting and drain: force-poll what the batcher still
        holds, then apply every pending verdict FIFO (fail closed on
        tickets that cannot resolve within ``timeout``)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                # keep the thread ref: nulling it would let a later
                # start() spawn a SECOND harvester interleaving
                # poll/apply on the same shim — frame/verdict desync
                log.warning("feeder thread did not stop within %.1fs; "
                            "keeping it registered", timeout)
                return
            self._thread = None

    def set_overload_state(self, level: int) -> None:
        """Propagate the overload-ladder level (engine's overload
        controller); >= SHED-NEW arms the harvest-time priority shed."""
        self._overload_level = int(level)

    def stats(self) -> Dict:
        t = self._thread
        e2e = self.metrics.histograms.get("ingest_e2e_latency_seconds")
        return {
            "harvested_batches": self.harvested_batches,
            "harvested_records": self.harvested_records,
            "applied_batches": self.applied_batches,
            "rejected_batches": self.rejected_batches,
            "harvest_faults": self.harvest_faults,
            "errors": self.errors,
            "overload_level": self._overload_level,
            "prio_shed_rows": self.prio_shed_rows,
            "prio_shed_batches": self.prio_shed_batches,
            "alive": bool(t is not None and t.is_alive()),
            "pending": len(self._pending),
            "pool_free": len(self._free),
            "slo_ms": round(self._slo_s * 1e3, 3),
            "slo_burns": self.slo_burns,
            "e2e_p50_ms": round(e2e.quantile(0.5) * 1e3, 3) if e2e else 0.0,
            "e2e_p99_ms": round(e2e.quantile(0.99) * 1e3, 3) if e2e else 0.0,
        }

    # -- harvest loop ---------------------------------------------------------
    def _run(self) -> None:
        # supervised degradation: a failing step (e.g. engine.active
        # raising through a regen failure storm) must not kill ingestion
        # for the daemon's lifetime — count, log (throttled), retry
        while not self._stop.is_set():
            try:
                progressed = self._step(force=False)
            except Exception:   # noqa: BLE001 — keep harvesting
                progressed = False
                self._count_error("harvest step failed")
            if not progressed and not self._stop.is_set():
                if self._idle_sleep_s:
                    self._stop.wait(self._idle_sleep_s)
        try:
            self._drain()
        except Exception:   # noqa: BLE001
            log.exception("feeder drain failed")

    def _count_error(self, what: str) -> None:
        self.errors += 1
        self.metrics.inc_counter("feeder_errors_total")
        if self.errors <= 3 or self.errors % 100 == 0:
            log.exception("feeder %s (error %d); retrying", what,
                          self.errors)

    def _step(self, force: bool) -> bool:
        """One harvest iteration. Returns True when any work happened."""
        progressed = self._apply_ready(block=False)
        buf = self._acquire_buffer()
        if buf is None:
            return progressed            # pool exhausted and head not done
        now_us = int(time.monotonic() * 1e6)
        if self._fqdn is not None:
            # reset_batch_rows zeroes only the TAIL of optional columns and
            # the native shim never writes them: a reused buffer would
            # otherwise replay the PREVIOUS poll's DNS payload for head
            # rows. len==0 makes stale payload bytes unreachable.
            buf["_dns_len"][:] = 0
        tid = self.tracer.maybe_sample()
        try:
            with self.tracer.span(tid, "shim.harvest", force=force):
                if self._rings_attached():
                    rc = self.shim.afxdp_poll(self._poll_budget,
                                              now_us=now_us)
                    if rc < 0:
                        log.debug("afxdp_poll -> %d", rc)
                b = self.shim.poll_batch(now_us=now_us, force=force,
                                         out=buf)
        except FaultInjected:
            # one failed poll: frames stay queued in the ring and drain on
            # the next poll — the supervised-degradation contract
            self.harvest_faults += 1
            self.metrics.inc_counter("feeder_harvest_faults_total")
            self._free.append(buf)
            return progressed
        except Exception:   # noqa: BLE001 — buffer must return to the pool
            self._free.append(buf)
            self._count_error("poll failed")
            return progressed
        if b is None:
            self._free.append(buf)
            return progressed
        self.harvested_batches += 1
        self.metrics.inc_counter("feeder_harvest_batches_total")
        ticket = None
        try:
            n_valid = self._map_slots(b)
            self.harvested_records += n_valid
            self.metrics.inc_counter("feeder_harvest_records_total",
                                     n_valid)
            from cilium_tpu.pipeline.guard import OVERLOAD_SHED_NEW
            submit = True
            if self._overload_level >= OVERLOAD_SHED_NEW:
                # the ladder's terminal rung: only established-class rows
                # are submitted; everything else gets its drop verdict at
                # apply time without touching the pipeline — the rx ring's
                # real backpressure relief. A batch shed whole rides the
                # pending queue as the all-drop sentinel (FIFO-safe).
                if self._shed_new(b) and not bool(b["valid"].any()):
                    submit = False
                    self.prio_shed_batches += 1
                    self.metrics.inc_counter(
                        "feeder_prio_shed_batches_total")
            # the harvest stamp rides the ticket (true ingest→verdict
            # latency; monotonic — same clock as now_us above)
            if submit:
                ticket = self.engine.submit(b, ingest_mono=now_us / 1e6)
        except Exception as e:   # noqa: BLE001 — unavailable/closed/
            # regen-storm engine.active/... : the shim already holds this
            # batch's FrameRefs, so a verdict MUST be consumed for it —
            # but strictly AFTER the batches harvested before it
            # (apply_verdicts always consumes the OLDEST batch). The
            # rejection rides the pending queue as a ``None`` sentinel and
            # is applied all-drop in FIFO position, never out of order.
            self._submit_rejects += 1
            if self._submit_rejects <= 3 or self._submit_rejects % 100 == 0:
                # throttled: a breaker-open storm rejects at harvest rate
                log.warning("feeder submit rejected (%d), queueing "
                            "fail-closed drop verdicts: %s",
                            self._submit_rejects, e)
        self._pending.append((ticket, buf, now_us / 1e6))
        self.metrics.set_gauge("feeder_pending", len(self._pending))
        return True

    def _acquire_buffer(self):
        if self._free:
            return self._free.popleft()
        # pool exhausted: backpressure — block on the OLDEST ticket (FIFO),
        # bounded so stop() stays responsive
        if self._apply_ready(block=True, block_timeout=0.05):
            if self._free:
                return self._free.popleft()
        return None

    def _rings_attached(self) -> bool:
        """Whether rx/fill rings exist (AF_XDP bind or mocked); without
        them the plain feed_frame→batcher path needs no ring drain.
        Prefer the shim's own ``rings_ready`` flag: probing the fill
        LEVEL can read zero with every umem descriptor parked in the rx
        ring — exactly the state where the drain is most needed — and
        since only the drain recycles addresses, mistaking that for "no
        rings" wedges ingestion permanently (the producer sees a full rx
        ring, the harvester never looks at it). The level probe remains
        as a fallback for shim stand-ins without the flag; only a
        positive probe is cached, so rings initialized after start still
        attach."""
        if self._rings:
            return True
        ready = getattr(self.shim, "rings_ready", None)
        self._rings = bool(ready) if ready is not None \
            else self.shim.ring_fill_level() > 0
        return bool(self._rings)

    #: class-level alias (tests monkeypatch it to force the sparse path)
    DENSE_LUT_MAX = DENSE_LUT_MAX

    # -- slot mapping ---------------------------------------------------------
    def _map_slots(self, b: Dict[str, np.ndarray]) -> int:
        """Shim-ep-id → snapshot-slot mapping, in place (build_slot_lut /
        map_raw_slots — shared with the engine's dispatch-time re-map).
        Records for endpoints the snapshot doesn't know go invalid (fail
        closed). Returns the surviving valid count."""
        snap = self.engine.active.snapshot
        if snap is not self._snap:
            self._slot_lut = build_slot_lut(snap.ep_slot_of,
                                            self.DENSE_LUT_MAX)
            self._snap = snap
        slots = map_raw_slots(b["_ep_raw"], snap.ep_slot_of,
                              self._slot_lut)
        unknown = slots < 0
        b["ep_slot"][:] = np.where(unknown, 0, slots)
        b["valid"] &= ~unknown
        if "_prio" in b:
            # priority classing while the columns are hot: flows in the
            # established filter outrank new flows outrank
            # unknown-endpoint traffic (pipeline/guard.PRIO_*) — what the
            # admission queue ranks batches by under PRESSURE and the
            # SHED-NEW harvest shed keys on
            from cilium_tpu.pipeline.guard import (PRIO_ESTABLISHED,
                                                   PRIO_NEW, PRIO_UNKNOWN)
            hit = self._est.hits(b)
            pr = np.where(hit, PRIO_ESTABLISHED, PRIO_NEW).astype(np.int8)
            pr[unknown] = PRIO_UNKNOWN
            b["_prio"][:] = pr
        if self._qos is not None and "_tenant" in b:
            # tenant identity while the ep ids are hot: endpoint → tenant
            # via the TenantTable's compiled LUT (cached on its revision
            # counter inside map_tenants — same rebuild-on-change
            # discipline as the ep-slot LUT above). Unknown endpoints
            # land on the default tenant: they must still be served,
            # they just ride the shared budget.
            b["_tenant"][:] = self._qos.map_tenants(b["_ep_raw"])
        if self._n_shards > 1:
            # pre-bin while the columns are already hot in cache: the same
            # direction-normalized hash (post-DNAT tuple) the datapath and
            # the staging ring use, revision-stamped so a regen between
            # harvest and stage-write invalidates the bin rather than
            # mis-steering it
            from cilium_tpu.parallel.mesh import flow_shard_of
            from cilium_tpu.pipeline.scheduler import shard_bin_encode
            lb = snap.lb if snap.lb.n_frontends else None
            b["_shard"][:] = shard_bin_encode(
                flow_shard_of(b, self._n_shards, lb=lb), snap.revision)
        return int(b["valid"].sum())

    def _shed_new(self, b: Dict[str, np.ndarray]) -> int:
        """SHED-NEW shed of one harvested batch (see shed_new_rows), with
        per-class attribution counters — the ``{class=...}`` label family
        operators alert on."""
        from cilium_tpu.pipeline.guard import PRIO_NEW, PRIO_UNKNOWN
        pr = np.asarray(b["_prio"])
        v = np.asarray(b["valid"])
        n_new = int((v & (pr == PRIO_NEW)).sum())
        n_unk = int((v & (pr >= PRIO_UNKNOWN)).sum())
        shed = shed_new_rows(b)
        if shed:
            self.prio_shed_rows += shed
            if n_new:
                self.metrics.inc_counter(
                    'feeder_prio_shed_rows_total{class="new"}', n_new)
            if n_unk:
                self.metrics.inc_counter(
                    'feeder_prio_shed_rows_total{class="unknown"}', n_unk)
            if self._event_sink is not None:
                try:
                    self._event_sink("shed", reason="shed-new", rows=shed)
                except Exception:   # noqa: BLE001 — observability only
                    log.exception("feeder event sink failed")
        return shed

    def _note_established(self, buf, out) -> None:
        """Feed the established-flow filter from applied verdicts: flows
        observed allowed-ESTABLISHED/REPLY stamp their fingerprint, so the
        NEXT harvest ranks them class 0 (EstablishedFingerprints — shared
        with the engine's CT-salvage grace window, which needs the exact
        same update/lookup discipline). Never raises."""
        self._est.note(buf, out)

    # -- verdict application (FIFO) -------------------------------------------
    def _apply_ready(self, block: bool,
                     block_timeout: float = 0.0) -> bool:
        """Apply verdicts for resolved head tickets, strictly FIFO. With
        ``block`` the head ticket is awaited up to ``block_timeout``."""
        did = False
        while self._pending:
            ticket, buf, ingest_mono = self._pending[0]
            if ticket is not None and not ticket.done():
                if not block:
                    break
                try:
                    ticket.result(timeout=block_timeout)
                except TimeoutError:
                    break
                except Exception:   # noqa: BLE001 — applied below
                    pass
                block = False        # at most one blocking wait per call
            self._pending.popleft()
            self._apply_one(ticket, buf, ingest_mono=ingest_mono)
            did = True
        self.metrics.set_gauge("feeder_pending", len(self._pending))
        return did

    def _apply_one(self, ticket, buf, recycle: bool = True,
                   ingest_mono: Optional[float] = None) -> None:
        """Apply one batch's verdicts (``ticket is None``: the rejected-
        at-submit sentinel — all-drop, fail closed). ``recycle=False``
        sheds the buffer instead of pooling it — for tickets that did NOT
        resolve: the pipeline may still stage from the buffer later."""
        rejected = True
        allow = self._zeros
        if ticket is not None:
            try:
                out = ticket.result(timeout=0)
                allow = out["allow"]
                rejected = False
                self._note_established(buf, out)
                if self._fqdn is not None:
                    # in-band DNS learning tap: rows whose verdict carried
                    # the DNS L7 redirect get their response payload parsed
                    # into the FQDN cache. Strictly after the verdict is
                    # computed and strictly before apply_verdicts — the
                    # proxy never raises and never touches ``allow``, so a
                    # broken parser can only lose learning, never the reply
                    # (the fail-open contract; fault point ``fqdn.parse``).
                    self._fqdn.observe_batch(buf, out)
            except Exception:   # noqa: BLE001 — drop/shed/unavailable
                pass
        try:
            self.shim.apply_verdicts(allow)
        except Exception:   # noqa: BLE001
            log.exception("apply_verdicts failed; frame/verdict FIFO may "
                          "be desynced")
        if not rejected and ingest_mono is not None:
            # verdict-apply is the END of the serving path for this batch:
            # harvest stamp → here is the true ingest→verdict latency
            self._observe_e2e(time.monotonic() - ingest_mono, buf)
        if rejected:
            self.rejected_batches += 1
            self.metrics.inc_counter("feeder_rejected_batches_total")
        self.applied_batches += 1
        self.metrics.inc_counter("feeder_applied_batches_total")
        if recycle:
            self._free.append(buf)

    def _observe_e2e(self, lat_s: float, buf: Dict[str, np.ndarray]) -> None:
        """One applied batch's ingest→verdict latency into the e2e SLO
        surface: the ``ingest_e2e_latency_seconds`` histogram (plus a
        per-shard labeled family when the batch pre-binned onto a mesh) and
        the SLO burn counters when a threshold is armed.

        Attribution is BATCH-granular: every row in the batch experienced
        the same harvest→apply latency, so the batch's latency is observed
        once into each shard family that had valid rows (the latency shard
        N's rows truly saw). Under uniformly mixed harvest batches the
        per-shard series therefore move together; they become differential
        exactly when the mesh degrades asymmetrically — only the batches
        carrying the slow shard's rows stall, and that shard's family (and
        burn counter) pulls away from the rest. The unlabeled family/burn
        counts each batch once and stays the aggregate truth. Never raises
        — this rides the verdict-apply hot path."""
        try:
            self.metrics.histogram("ingest_e2e_latency_seconds").observe(
                lat_s)
            shards = ()
            if self._n_shards > 1 and "_shard" in buf:
                from cilium_tpu.pipeline.scheduler import SHARD_BIN_MASK
                # valid-masked: the buffer's padding tail carries the
                # zeroed-row flow hash, which would attribute every batch
                # to one deterministic shard that carried no traffic
                bins = (np.asarray(buf["_shard"]) & SHARD_BIN_MASK) - 1
                bins = bins[np.asarray(buf["valid"])]
                shards = np.unique(bins[(bins >= 0)
                                        & (bins < self._n_shards)])
                for s in shards:
                    self.metrics.histogram(
                        f'ingest_e2e_latency_seconds{{shard="{int(s)}"}}'
                    ).observe(lat_s)
            tnames = ()
            if self._qos is not None and "_tenant" in buf:
                # per-tenant SLO accounting, batch-granular like the
                # shard families: the batch's latency is observed once
                # into each tenant family that had valid rows — the
                # per-tenant p99 the isolation contract is gated on
                tids = np.unique(
                    np.asarray(buf["_tenant"])[np.asarray(buf["valid"])])
                tnames = [self._qos.name_of(int(t)) for t in tids]
                for tn in tnames:
                    self.metrics.histogram(
                        f'ingest_e2e_latency_seconds{{tenant="{tn}"}}'
                    ).observe(lat_s)
            if self._slo_s and lat_s > self._slo_s:
                self.metrics.inc_counter("ingest_e2e_slo_burn_total")
                for s in shards:
                    self.metrics.inc_counter(
                        f'ingest_e2e_slo_burn_total{{shard="{int(s)}"}}')
                for tn in tnames:
                    self.metrics.inc_counter(
                        f'ingest_e2e_slo_burn_total{{tenant="{tn}"}}')
                self.slo_burns += 1
        except Exception:   # noqa: BLE001
            log.exception("e2e latency observation failed")

    def _drain(self) -> None:
        """Stop-path drain: alternate force-harvesting what the batcher
        still holds with resolving + applying pending verdicts FIFO, until
        neither makes progress — a busy device can exhaust the pool
        mid-drain, so harvesting must resume after the pending sweep frees
        buffers (bounded: the producer has stopped injecting)."""
        for _round in range(2 * MAX_UNVERDICTED_BATCHES):
            harvested = False
            for _ in range(MAX_UNVERDICTED_BATCHES):
                if not self._step(force=True):
                    break
                harvested = True
            if not self._pending and not harvested:
                break
            while self._pending:
                ticket, buf, ingest_mono = self._pending.popleft()
                resolved = True
                if ticket is not None:
                    try:
                        ticket.result(timeout=10.0)
                    except TimeoutError:
                        # still owned by the (possibly wedged) pipeline:
                        # apply fail-closed for FIFO, but NEVER pool the
                        # buffer — a later stage could read it rewritten
                        resolved = False
                    except Exception:   # noqa: BLE001 — fail-closed below
                        pass
                self._apply_one(ticket, buf, recycle=resolved,
                                ingest_mono=ingest_mono)
        self.metrics.set_gauge("feeder_pending", len(self._pending))
