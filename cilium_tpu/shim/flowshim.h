// flowshim: the native ingress front end (SURVEY.md §2 native checklist
// item 2 — the userspace replacement for the XDP hook: "C++ AF_XDP shim
// (umem/fill/completion rings, batch header extraction → pinned host
// buffers → TPU transfer). No Python in the packet path.").
//
// Components:
//  - parser: Ethernet/VLAN → IPv4/IPv6 → TCP/UDP/SCTP/ICMP header extraction
//    into the fixed 64-byte record the classifier consumes, plus an HTTP
//    request-line tokenizer filling a parallel 72-byte token record.
//  - batcher: lock-free-ish ring accumulating records until batch_size or an
//    adaptive deadline (p99-latency driven) elapses.
//  - afxdp: AF_XDP socket setup via raw syscalls (UMEM + fill/completion/rx/tx
//    rings). Compiles everywhere; at runtime it requires a privileged netns
//    and an XDP-capable driver, so the library also exposes a mock-driver
//    path (feed frames from memory) used by tests and pcap replay.
//
// C ABI only — consumed from Python via ctypes (no pybind11 in this image).

#ifndef CILIUM_TPU_FLOWSHIM_H_
#define CILIUM_TPU_FLOWSHIM_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

// ---------------------------------------------------------------------------
// Record layouts (must match cilium_tpu/kernels/records.py column order)
// ---------------------------------------------------------------------------
// 64-byte header record. Addresses are 16-byte normalized (IPv4 mapped to
// ::ffff:a.b.c.d) and stored as four BIG-ENDIAN u32 words, matching the
// device batch layout.
typedef struct __attribute__((packed)) ShimRecord {
  uint32_t src[4];     // big-endian words
  uint32_t dst[4];     // big-endian words
  uint16_t sport;      // host order
  uint16_t dport;      // host order; ICMP: type
  uint8_t proto;
  uint8_t tcp_flags;
  uint8_t is_v6;
  uint8_t direction;   // 0 egress / 1 ingress (relative to the endpoint)
  uint32_t ep_id;      // local endpoint id (0 = unclassified)
  uint32_t frame_idx;  // umem frame / mock buffer index for verdict return
  uint32_t orig_len;   // original frame length
  uint8_t pad[12];
} ShimRecord;  // == 64 bytes

// 72-byte L7 token record (parallel array; only meaningful when has_tokens).
typedef struct __attribute__((packed)) ShimTokens {
  uint8_t has_tokens;  // 1 when an HTTP request line was recognized
  uint8_t method;      // HTTP_METHOD id; 255 = none
  uint16_t path_len;
  uint8_t path[64];
  uint8_t pad[4];
} ShimTokens;  // == 72 bytes

typedef struct ShimStats {
  uint64_t frames_seen;
  uint64_t frames_parsed;
  uint64_t parse_errors;
  uint64_t batches_emitted;
  uint64_t records_emitted;
  uint64_t verdict_drops;
  uint64_t verdict_passes;
  // allowed frames lost because the tx ring was full (NIC backpressure) —
  // counted separately from verdict_passes so tx loss is diagnosable
  uint64_t tx_full_drops;
  // records whose batch aged out of the bounded unverdicted queue (a
  // harvest-only consumer — tap mode, pcap replay — never calls
  // shim_apply_verdicts; their umem frames recycle to the fill ring)
  uint64_t verdict_expired;
} ShimStats;

typedef struct Shim Shim;  // opaque

// ---------------------------------------------------------------------------
// Lifecycle
// ---------------------------------------------------------------------------
// Create a shim with a batching target: emit a batch when ``batch_size``
// records accumulated OR ``timeout_us`` elapsed since the first record of the
// batch (adaptive latency bound).
Shim* shim_create(uint32_t batch_size, uint64_t timeout_us);
void shim_destroy(Shim* s);

// Register an endpoint IP → (ep_id). The parser classifies each frame's
// direction by matching src/dst against registered endpoint addresses
// (ip16 = 16-byte normalized address).
int shim_register_endpoint(Shim* s, const uint8_t ip16[16], uint32_t ep_id);

// ---------------------------------------------------------------------------
// Mock-driver ingest (tests / pcap replay). Frames are raw Ethernet.
// Returns 0 on success, -1 on parse error (counted in stats).
// ---------------------------------------------------------------------------
int shim_feed_frame(Shim* s, const uint8_t* frame, uint32_t len,
                    uint64_t now_us);

// Harvest a ready batch (either full or timed out as of ``now_us``).
// Returns the number of records written into out_records/out_tokens
// (caller-allocated, capacity = batch_size), or 0 if no batch is ready.
// ``force`` flushes a partial batch regardless of deadline.
uint32_t shim_poll_batch(Shim* s, uint64_t now_us, int force,
                         ShimRecord* out_records, ShimTokens* out_tokens);

// Return verdicts for a previously harvested batch: allow[i] == 0 → drop.
// In AF_XDP mode this recycles/forwards umem frames; in mock mode it only
// updates stats (and the test inspects them).
void shim_apply_verdicts(Shim* s, const uint8_t* allow, uint32_t n);

void shim_get_stats(const Shim* s, ShimStats* out);

// ---------------------------------------------------------------------------
// AF_XDP mode (privileged; returns -errno on failure, e.g. in containers
// without NET_ADMIN — callers fall back to the mock driver)
// ---------------------------------------------------------------------------
int shim_afxdp_bind(Shim* s, const char* ifname, uint32_t queue_id);
// Drain up to ``budget`` frames from the rx ring into the batcher:
// completion ring → fill ring recycle, then an rx descriptor walk handing
// each umem frame to the parser. Works on the kernel-mapped rings after
// shim_afxdp_bind OR on memory-mocked rings after shim_mock_rings_init.
// Returns descriptors drained (>= 0) or -errno.
int shim_afxdp_poll(Shim* s, uint32_t budget, uint64_t now_us);

// XDP descriptor layout (mirror of the kernel's struct xdp_desc, declared
// here so the ring logic and its memory-mocked tests compile anywhere).
typedef struct ShimXdpDesc {
  uint64_t addr;
  uint32_t len;
  uint32_t options;
} ShimXdpDesc;

// ---------------------------------------------------------------------------
// Memory-mocked rings: the exact producer/consumer ring algebra of AF_XDP
// (fill/completion/rx/tx single-producer single-consumer rings over a umem
// frame pool) backed by heap memory, so the full frame lifecycle —
// fill → (mock driver) rx → parse/batch → verdict → tx or fill-recycle →
// completion → fill — is testable in an unprivileged container. The real
// shim_afxdp_bind wires the same Ring views at kernel-mapped offsets.
// ---------------------------------------------------------------------------
int shim_mock_rings_init(Shim* s, uint32_t ring_size, uint32_t frame_size,
                         uint32_t n_frames);
// Mock NIC RX: take a frame from the fill ring, copy ``frame`` into its umem
// slot, publish an rx descriptor. Returns 0, -ENOSPC (fill empty / rx full)
// or -EMSGSIZE (frame larger than the umem chunk).
int shim_mock_rx_inject(Shim* s, const uint8_t* frame, uint32_t len);
// Mock NIC TX: consume up to ``max`` tx descriptors (the frames the shim
// forwarded), then report them transmitted via the completion ring.
uint32_t shim_mock_tx_drain(Shim* s, uint64_t* addrs, uint32_t* lens,
                            uint32_t max);
// Frames currently available in the fill ring (leak/accounting checks).
uint32_t shim_ring_fill_level(const Shim* s);

// ---------------------------------------------------------------------------
// Service LB steering state (mirror of compile/lb.py's frontend hash table +
// Maglev + backend arrays). Steering must hash the TRANSLATED tuple: CT
// entries live under the DNAT'ed 5-tuple, so a service flow's forward and
// reply packets only land on the same CT shard if the shim applies the same
// deterministic translation the device kernel does.
// All arrays are copied; row-major. Pass cap=0 to clear.
// ---------------------------------------------------------------------------
int shim_set_lb(Shim* s, const uint32_t* tab_keys /*[cap*6]*/,
                const int32_t* tab_val /*[cap]*/, uint32_t cap,
                uint32_t probe_depth, const int32_t* fe_service /*[F]*/,
                uint32_t n_fe, const int32_t* maglev /*[S*M]*/, uint32_t n_svc,
                uint32_t maglev_m, const uint32_t* be_addr /*[B*4]*/,
                const int32_t* be_port /*[B]*/, uint32_t n_be);

// RSS-style flow-shard steering (must match
// cilium_tpu/parallel/mesh.flow_shard_of with the shim's LB state: service
// DNAT first, then XOR of fwd/rev murmur key hashes).
uint32_t shim_flow_shard2(const Shim* s, const ShimRecord* rec,
                          uint32_t n_shards);

// Legacy steering without LB translation (wrong for service traffic on a
// sharded mesh — kept for non-LB deployments).
uint32_t shim_flow_shard(const ShimRecord* rec, uint32_t n_shards);

#ifdef __cplusplus
}
#endif

#endif  // CILIUM_TPU_FLOWSHIM_H_
