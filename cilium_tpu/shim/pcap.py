"""pcap read/write + replay through the native shim (BASELINE config 1:
"IPv4-only 5-tuple pcap replay"; SURVEY.md §7 step 5 "IPv4 5-tuple records
from a pcap-derived source").

Classic libpcap format (magic 0xa1b2c3d4, LINKTYPE_ETHERNET), stdlib-only.
``replay_pcap`` drives frames through the C++ parser/batcher — the same
ingest the AF_XDP path uses — so a pcap-fed benchmark measures the real
frame→record pipeline, not a synthetic numpy generator. ``synthesize_pcap``
writes deterministic 5-tuple traffic for rigs (like this one) with no
capture source; a real capture file drops in unchanged.
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Optional

import numpy as np

PCAP_MAGIC = 0xA1B2C3D4
LINKTYPE_ETHERNET = 1
_GLOBAL_HDR = struct.pack("<IHHiIII", PCAP_MAGIC, 2, 4, 0, 0, 65535,
                          LINKTYPE_ETHERNET)


def write_pcap(path: str, frames) -> int:
    """Write raw Ethernet frames to a classic pcap file. Returns count."""
    n = 0
    with open(path, "wb") as f:
        f.write(_GLOBAL_HDR)
        for frame in frames:
            f.write(struct.pack("<IIII", n, 0, len(frame), len(frame)))
            f.write(frame)
            n += 1
    return n


def read_pcap(path: str) -> Iterator[bytes]:
    """Yield raw frames from a classic pcap file (either byte order)."""
    with open(path, "rb") as f:
        hdr = f.read(24)
        if len(hdr) < 24:
            raise ValueError("truncated pcap global header")
        magic = struct.unpack("<I", hdr[:4])[0]
        if magic == PCAP_MAGIC:
            endian = "<"
        elif magic == struct.unpack(">I", struct.pack("<I", PCAP_MAGIC))[0]:
            endian = ">"
        else:
            raise ValueError(f"not a classic pcap file (magic {magic:#x})")
        while True:
            ph = f.read(16)
            if len(ph) < 16:
                return
            _ts, _us, incl, _orig = struct.unpack(endian + "IIII", ph)
            frame = f.read(incl)
            if len(frame) < incl:
                raise ValueError("truncated pcap record")
            yield frame


# --------------------------------------------------------------------------- #
# deterministic IPv4 5-tuple synthesis (vectorized frame assembly)
# --------------------------------------------------------------------------- #
_V4_TCP_LEN = 14 + 20 + 20


def _v4_tcp_frames(src_ip: np.ndarray, dst_ip: np.ndarray,
                   sport: np.ndarray, dport: np.ndarray,
                   tcp_flags: int = 0x02) -> np.ndarray:
    """[n] uint32 tuple columns → [n, 54] uint8 Ethernet+IPv4+TCP frames."""
    n = dst_ip.shape[0]
    f = np.zeros((n, _V4_TCP_LEN), dtype=np.uint8)
    f[:, 0:6] = (2, 0, 0, 0, 0, 1)          # dst mac
    f[:, 6:12] = (2, 0, 0, 0, 0, 2)         # src mac
    f[:, 12:14] = (0x08, 0x00)              # IPv4
    ip = 14
    f[:, ip + 0] = 0x45
    f[:, ip + 2] = 0
    f[:, ip + 3] = 40                        # total length 40
    f[:, ip + 8] = 64                        # ttl
    f[:, ip + 9] = 6                         # TCP
    for i in range(4):
        f[:, ip + 12 + i] = (src_ip >> (24 - 8 * i)) & 0xFF
        f[:, ip + 16 + i] = (dst_ip >> (24 - 8 * i)) & 0xFF
    tcp = ip + 20
    f[:, tcp + 0] = (sport >> 8) & 0xFF
    f[:, tcp + 1] = sport & 0xFF
    f[:, tcp + 2] = (dport >> 8) & 0xFF
    f[:, tcp + 3] = dport & 0xFF
    f[:, tcp + 12] = 5 << 4                  # data offset
    f[:, tcp + 13] = tcp_flags
    f[:, tcp + 14] = 0xFF                    # window
    f[:, tcp + 15] = 0xFF
    return f


def synthesize_pcap(path: str, n_frames: int, seed: int = 7,
                    src_ip: str = "192.168.0.10") -> int:
    """Write a deterministic IPv4-only 5-tuple capture: one fixed source
    endpoint fanning out to a wide random destination/port space (the cfg1
    traffic shape)."""
    import ipaddress
    rng = np.random.default_rng(seed)
    src = np.full(n_frames,
                  int(ipaddress.IPv4Address(src_ip)), dtype=np.uint64)
    dst = ((rng.integers(1, 220, n_frames).astype(np.uint64) << 24)
           + rng.integers(0, 1 << 24, n_frames).astype(np.uint64))
    sport = rng.integers(20000, 60000, n_frames).astype(np.uint64)
    dport = rng.integers(1, 65535, n_frames).astype(np.uint64)
    frames = _v4_tcp_frames(src, dst, sport, dport)
    # vectorized pcap assembly: per-record header + frame, one tofile
    rec = np.zeros((n_frames, 16 + _V4_TCP_LEN), dtype=np.uint8)
    hdr = rec[:, :16].view("<u4").reshape(n_frames, 4)
    hdr[:, 0] = np.arange(n_frames)          # fake seconds
    hdr[:, 2] = _V4_TCP_LEN
    hdr[:, 3] = _V4_TCP_LEN
    rec[:, 16:] = frames
    with open(path, "wb") as f:
        f.write(_GLOBAL_HDR)
        rec.tofile(f)
    return n_frames


def replay_pcap(shim, path: str, batch_size: int,
                max_batches: Optional[int] = None
                ) -> List[Dict[str, np.ndarray]]:
    """Feed a capture through the C++ parser/batcher; harvest batch dicts in
    the kernels/records layout. The caller maps ``_ep_raw`` endpoint ids to
    snapshot slots (shim endpoint registration carries ep ids)."""
    batches: List[Dict[str, np.ndarray]] = []
    fed = 0
    for frame in read_pcap(path):
        shim.feed_frame(bytes(frame), now_us=fed)
        fed += 1
        if fed % batch_size == 0:
            b = shim.poll_batch(now_us=fed, force=True)
            if b is not None:
                batches.append(b)
            if max_batches is not None and len(batches) >= max_batches:
                return batches
    b = shim.poll_batch(now_us=fed, force=True)
    if b is not None:
        batches.append(b)
    return batches
