// flowshim implementation. See flowshim.h for the component map.

#include "flowshim.h"

#include <errno.h>
#include <string.h>

#include <algorithm>
#include <array>
#include <cstdio>
#include <deque>
#include <vector>

#if defined(__linux__) && __has_include(<linux/if_xdp.h>)
#define FLOWSHIM_HAVE_AFXDP 1
#include <linux/if_xdp.h>
#include <net/if.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <unistd.h>
#else
#define FLOWSHIM_HAVE_AFXDP 0
#endif

namespace {

// ---------------------------------------------------------------------------
// Hash — bit-identical to cilium_tpu/kernels/hashing.py (murmur3-style
// accumulate + fmix32). The steering contract depends on this equality.
// ---------------------------------------------------------------------------
constexpr uint32_t kC1 = 0xCC9E2D51u;
constexpr uint32_t kC2 = 0x1B873593u;
constexpr uint32_t kSeed = 0x9747B28Cu;

inline uint32_t rotl32(uint32_t x, int r) {
  return (x << r) | (x >> (32 - r));
}

uint32_t hash_words(const uint32_t* w, int n) {
  uint32_t h = kSeed;
  for (int i = 0; i < n; i++) {
    uint32_t k = w[i] * kC1;
    k = rotl32(k, 15);
    k = k * kC2;
    h ^= k;
    h = rotl32(h, 13);
    h = h * 5u + 0xE6546B64u;
  }
  h ^= h >> 16;
  h *= 0x85EBCA6Bu;
  h ^= h >> 13;
  h *= 0xC2B2AE35u;
  h ^= h >> 16;
  return h;
}

void ct_key_words(const ShimRecord& r, bool reverse, uint32_t out[10]) {
  uint32_t src[4], dst[4];  // copy out of the packed struct (alignment-safe)
  memcpy(src, reverse ? r.dst : r.src, 16);
  memcpy(dst, reverse ? r.src : r.dst, 16);
  uint32_t sport = reverse ? r.dport : r.sport;
  uint32_t dport = reverse ? r.sport : r.dport;
  uint32_t dir = reverse ? (1u - r.direction) : r.direction;
  for (int i = 0; i < 4; i++) out[i] = src[i];
  for (int i = 0; i < 4; i++) out[4 + i] = dst[i];
  out[8] = (sport << 16) | dport;
  out[9] = (uint32_t(r.proto) << 8) | dir;
}

inline uint32_t be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

// HTTP methods — ids must match utils/constants.py HTTP_METHOD_IDS.
const char* kMethods[] = {"GET",     "POST",  "PUT",   "DELETE", "HEAD",
                          "OPTIONS", "PATCH", "TRACE", "CONNECT"};

struct PendingRecord {
  ShimRecord rec;
  ShimTokens tok;
};

}  // namespace

struct Shim {
  uint32_t batch_size;
  uint64_t timeout_us;
  std::deque<PendingRecord> pending;
  uint64_t first_pending_ts = 0;
  std::vector<std::pair<std::array<uint8_t, 16>, uint32_t>> endpoints;
  ShimStats stats{};
  uint32_t next_frame_idx = 0;
  // service LB steering state (see shim_set_lb)
  std::vector<uint32_t> lb_tab_keys;  // [cap*6]
  std::vector<int32_t> lb_tab_val;    // [cap]
  uint32_t lb_cap = 0;
  uint32_t lb_probe_depth = 0;
  std::vector<int32_t> lb_fe_service;  // [F]
  std::vector<int32_t> lb_maglev;      // [S*M]
  uint32_t lb_maglev_m = 0;
  std::vector<uint32_t> lb_be_addr;    // [B*4]
  std::vector<int32_t> lb_be_port;     // [B]
#if FLOWSHIM_HAVE_AFXDP
  int xsk_fd = -1;
  void* umem_area = nullptr;
  size_t umem_size = 0;
#endif
};

extern "C" {

Shim* shim_create(uint32_t batch_size, uint64_t timeout_us) {
  Shim* s = new Shim();
  s->batch_size = batch_size ? batch_size : 1;
  s->timeout_us = timeout_us;
  return s;
}

void shim_destroy(Shim* s) {
#if FLOWSHIM_HAVE_AFXDP
  if (s->xsk_fd >= 0) close(s->xsk_fd);
  if (s->umem_area) munmap(s->umem_area, s->umem_size);
#endif
  delete s;
}

int shim_register_endpoint(Shim* s, const uint8_t ip16[16], uint32_t ep_id) {
  std::array<uint8_t, 16> a;
  memcpy(a.data(), ip16, 16);
  s->endpoints.emplace_back(a, ep_id);
  return 0;
}

// ---------------------------------------------------------------------------
// Parser
// ---------------------------------------------------------------------------
static const uint8_t kV4Mapped[12] = {0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0xFF, 0xFF};

static bool parse_frame(Shim* s, const uint8_t* f, uint32_t len,
                        PendingRecord* out) {
  if (len < 14) return false;
  uint16_t ethertype = (uint16_t(f[12]) << 8) | f[13];
  uint32_t off = 14;
  if (ethertype == 0x8100 || ethertype == 0x88A8) {  // VLAN
    if (len < 18) return false;
    ethertype = (uint16_t(f[16]) << 8) | f[17];
    off = 18;
  }

  ShimRecord& r = out->rec;
  memset(&r, 0, sizeof(r));
  ShimTokens& t = out->tok;
  memset(&t, 0, sizeof(t));
  t.method = 255;

  uint8_t src16[16], dst16[16];
  uint32_t l4off;
  uint8_t proto;

  if (ethertype == 0x0800) {  // IPv4
    if (len < off + 20) return false;
    const uint8_t* ip = f + off;
    uint32_t ihl = (ip[0] & 0x0F) * 4;
    if ((ip[0] >> 4) != 4 || ihl < 20 || len < off + ihl) return false;
    // fragments with nonzero offset carry no L4 header — refuse (the
    // classifier treats them as untrackable; upstream has a fragmap)
    uint16_t frag = ((uint16_t(ip[6]) << 8) | ip[7]) & 0x1FFF;
    if (frag != 0) return false;
    proto = ip[9];
    memcpy(src16, kV4Mapped, 12);
    memcpy(src16 + 12, ip + 12, 4);
    memcpy(dst16, kV4Mapped, 12);
    memcpy(dst16 + 12, ip + 16, 4);
    l4off = off + ihl;
    r.is_v6 = 0;
  } else if (ethertype == 0x86DD) {  // IPv6 (no extension headers in v1)
    if (len < off + 40) return false;
    const uint8_t* ip = f + off;
    if ((ip[0] >> 4) != 6) return false;
    proto = ip[6];
    memcpy(src16, ip + 8, 16);
    memcpy(dst16, ip + 24, 16);
    l4off = off + 40;
    r.is_v6 = 1;
  } else {
    return false;
  }

  for (int i = 0; i < 4; i++) {
    r.src[i] = be32(src16 + 4 * i);
    r.dst[i] = be32(dst16 + 4 * i);
  }
  r.proto = proto;

  if (proto == 6) {  // TCP
    if (len < l4off + 20) return false;
    const uint8_t* tcp = f + l4off;
    r.sport = (uint16_t(tcp[0]) << 8) | tcp[1];
    r.dport = (uint16_t(tcp[2]) << 8) | tcp[3];
    r.tcp_flags = tcp[13];
    uint32_t doff = (tcp[12] >> 4) * 4;
    uint32_t payload = l4off + doff;
    if (doff >= 20 && len > payload) {
      // HTTP request-line tokenizer
      const uint8_t* p = f + payload;
      uint32_t plen = len - payload;
      for (uint32_t m = 0; m < sizeof(kMethods) / sizeof(kMethods[0]); m++) {
        size_t mlen = strlen(kMethods[m]);
        if (plen > mlen + 1 && memcmp(p, kMethods[m], mlen) == 0 &&
            p[mlen] == ' ') {
          t.has_tokens = 1;
          t.method = uint8_t(m);
          uint32_t start = mlen + 1;
          uint32_t end = start;
          while (end < plen && end - start < 64 && p[end] != ' ' &&
                 p[end] != '\r' && p[end] != '\n')
            end++;
          t.path_len = uint16_t(end - start);
          memcpy(t.path, p + start, t.path_len);
          break;
        }
      }
    }
  } else if (proto == 17 || proto == 132) {  // UDP / SCTP
    if (len < l4off + 8) return false;
    const uint8_t* l4 = f + l4off;
    r.sport = (uint16_t(l4[0]) << 8) | l4[1];
    r.dport = (uint16_t(l4[2]) << 8) | l4[3];
  } else if (proto == 1 || proto == 58) {  // ICMP / ICMPv6: type in dport
    if (len < l4off + 4) return false;
    r.dport = f[l4off];
  }

  // direction + endpoint classification: src match → egress, dst → ingress
  r.ep_id = 0;
  r.direction = 1;
  for (const auto& ep : s->endpoints) {
    if (memcmp(ep.first.data(), src16, 16) == 0) {
      r.ep_id = ep.second;
      r.direction = 0;
      break;
    }
    if (memcmp(ep.first.data(), dst16, 16) == 0) {
      r.ep_id = ep.second;
      r.direction = 1;
      break;
    }
  }
  r.orig_len = len;
  return true;
}

int shim_feed_frame(Shim* s, const uint8_t* frame, uint32_t len,
                    uint64_t now_us) {
  s->stats.frames_seen++;
  PendingRecord pr;
  if (!parse_frame(s, frame, len, &pr)) {
    s->stats.parse_errors++;
    return -1;
  }
  pr.rec.frame_idx = s->next_frame_idx++;
  if (s->pending.empty()) s->first_pending_ts = now_us;
  s->pending.push_back(pr);
  s->stats.frames_parsed++;
  return 0;
}

uint32_t shim_poll_batch(Shim* s, uint64_t now_us, int force,
                         ShimRecord* out_records, ShimTokens* out_tokens) {
  if (s->pending.empty()) return 0;
  bool full = s->pending.size() >= s->batch_size;
  bool timed_out = now_us - s->first_pending_ts >= s->timeout_us;
  if (!full && !timed_out && !force) return 0;
  uint32_t n = std::min<size_t>(s->pending.size(), s->batch_size);
  for (uint32_t i = 0; i < n; i++) {
    out_records[i] = s->pending.front().rec;
    out_tokens[i] = s->pending.front().tok;
    s->pending.pop_front();
  }
  if (!s->pending.empty()) s->first_pending_ts = now_us;
  s->stats.batches_emitted++;
  s->stats.records_emitted += n;
  return n;
}

void shim_apply_verdicts(Shim* s, const uint8_t* allow, uint32_t n) {
  for (uint32_t i = 0; i < n; i++) {
    if (allow[i])
      s->stats.verdict_passes++;
    else
      s->stats.verdict_drops++;
  }
  // AF_XDP mode would recycle dropped frames into the fill ring and submit
  // passed frames to the tx ring here.
}

void shim_get_stats(const Shim* s, ShimStats* out) { *out = s->stats; }

uint32_t shim_flow_shard(const ShimRecord* rec, uint32_t n_shards) {
  uint32_t fwd[10], rev[10];
  ct_key_words(*rec, false, fwd);
  ct_key_words(*rec, true, rev);
  return (hash_words(fwd, 10) ^ hash_words(rev, 10)) % n_shards;
}

int shim_set_lb(Shim* s, const uint32_t* tab_keys, const int32_t* tab_val,
                uint32_t cap, uint32_t probe_depth, const int32_t* fe_service,
                uint32_t n_fe, const int32_t* maglev, uint32_t n_svc,
                uint32_t maglev_m, const uint32_t* be_addr,
                const int32_t* be_port, uint32_t n_be) {
  if (cap == 0) {
    s->lb_cap = 0;
    return 0;
  }
  if (cap & (cap - 1)) return -1;  // capacity must be a power of two
  s->lb_tab_keys.assign(tab_keys, tab_keys + size_t(cap) * 6);
  s->lb_tab_val.assign(tab_val, tab_val + cap);
  s->lb_cap = cap;
  s->lb_probe_depth = probe_depth;
  s->lb_fe_service.assign(fe_service, fe_service + n_fe);
  s->lb_maglev.assign(maglev, maglev + size_t(n_svc) * maglev_m);
  s->lb_maglev_m = maglev_m;
  s->lb_be_addr.assign(be_addr, be_addr + size_t(n_be) * 4);
  s->lb_be_port.assign(be_port, be_port + n_be);
  return 0;
}

// Mirror of compile/lb.lb_translate_np for one record: frontend probe →
// Maglev backend select → DNAT of (dst, dport). no-backend frontends stay
// untranslated (those packets drop; any shard is correct and this matches
// the host mirror).
static bool lb_translate(const Shim* s, const ShimRecord& r,
                         uint32_t new_dst[4], uint16_t* new_dport) {
  if (s->lb_cap == 0) return false;
  uint32_t dst[4];
  memcpy(dst, r.dst, 16);
  uint32_t key[6] = {dst[0], dst[1], dst[2], dst[3], uint32_t(r.dport),
                     uint32_t(r.proto)};
  uint32_t mask = s->lb_cap - 1;
  uint32_t base = hash_words(key, 6) & mask;
  int32_t fe = -1;
  for (uint32_t d = 0; d < s->lb_probe_depth && fe < 0; d++) {
    uint32_t slot = (base + d) & mask;
    if (s->lb_tab_val[slot] < 0) continue;
    if (memcmp(&s->lb_tab_keys[size_t(slot) * 6], key, 24) == 0)
      fe = s->lb_tab_val[slot];
  }
  if (fe < 0) return false;
  uint32_t src[4];
  memcpy(src, r.src, 16);
  uint32_t sel[10] = {src[0], src[1], src[2], src[3],
                      dst[0], dst[1], dst[2], dst[3],
                      (uint32_t(r.sport) << 16) | uint32_t(r.dport),
                      uint32_t(r.proto) << 8};
  uint32_t slot = hash_words(sel, 10) % s->lb_maglev_m;
  int32_t be =
      s->lb_maglev[size_t(s->lb_fe_service[fe]) * s->lb_maglev_m + slot];
  if (be < 0) return false;
  memcpy(new_dst, &s->lb_be_addr[size_t(be) * 4], 16);
  *new_dport = uint16_t(s->lb_be_port[be]);
  return true;
}

uint32_t shim_flow_shard2(const Shim* s, const ShimRecord* rec,
                          uint32_t n_shards) {
  ShimRecord r = *rec;
  uint32_t new_dst[4];
  uint16_t new_dport;
  if (lb_translate(s, r, new_dst, &new_dport)) {
    memcpy(r.dst, new_dst, 16);
    r.dport = new_dport;
  }
  return shim_flow_shard(&r, n_shards);
}

// ---------------------------------------------------------------------------
// AF_XDP (privileged; graceful -errno in unprivileged containers)
// ---------------------------------------------------------------------------
#if FLOWSHIM_HAVE_AFXDP
static constexpr uint32_t kFrameSize = 2048;
static constexpr uint32_t kNumFrames = 4096;

int shim_afxdp_bind(Shim* s, const char* ifname, uint32_t queue_id) {
  unsigned ifindex = if_nametoindex(ifname);
  if (!ifindex) return -ENODEV;
  int fd = socket(AF_XDP, SOCK_RAW, 0);
  if (fd < 0) return -errno;

  s->umem_size = size_t(kFrameSize) * kNumFrames;
  void* area = mmap(nullptr, s->umem_size, PROT_READ | PROT_WRITE,
                    MAP_PRIVATE | MAP_ANONYMOUS | MAP_POPULATE, -1, 0);
  if (area == MAP_FAILED) {
    close(fd);
    return -errno;
  }
  struct xdp_umem_reg umem_reg = {};
  umem_reg.addr = reinterpret_cast<uint64_t>(area);
  umem_reg.len = s->umem_size;
  umem_reg.chunk_size = kFrameSize;
  if (setsockopt(fd, SOL_XDP, XDP_UMEM_REG, &umem_reg, sizeof(umem_reg)) < 0) {
    int err = -errno;
    munmap(area, s->umem_size);
    close(fd);
    return err;
  }
  uint32_t ring_sz = kNumFrames;
  setsockopt(fd, SOL_XDP, XDP_UMEM_FILL_RING, &ring_sz, sizeof(ring_sz));
  setsockopt(fd, SOL_XDP, XDP_UMEM_COMPLETION_RING, &ring_sz, sizeof(ring_sz));
  setsockopt(fd, SOL_XDP, XDP_RX_RING, &ring_sz, sizeof(ring_sz));
  setsockopt(fd, SOL_XDP, XDP_TX_RING, &ring_sz, sizeof(ring_sz));

  struct sockaddr_xdp sxdp = {};
  sxdp.sxdp_family = AF_XDP;
  sxdp.sxdp_ifindex = ifindex;
  sxdp.sxdp_queue_id = queue_id;
  sxdp.sxdp_flags = XDP_COPY;  // portable; zerocopy negotiated by drivers
  if (bind(fd, reinterpret_cast<struct sockaddr*>(&sxdp), sizeof(sxdp)) < 0) {
    int err = -errno;
    munmap(area, s->umem_size);
    close(fd);
    return err;
  }
  s->xsk_fd = fd;
  s->umem_area = area;
  return 0;
}

int shim_afxdp_poll(Shim* s, uint32_t budget, uint64_t now_us) {
  if (s->xsk_fd < 0) return -EBADF;
  // Ring-draining requires mmap'ing the rx ring offsets (XDP_MMAP_OFFSETS)
  // and walking descriptors; each descriptor's frame is handed to
  // shim_feed_frame. Left as the documented next step — this build cannot
  // exercise it without a privileged netns + XDP driver (see shim/README).
  (void)budget;
  (void)now_us;
  return -EOPNOTSUPP;
}
#else   // !FLOWSHIM_HAVE_AFXDP
int shim_afxdp_bind(Shim*, const char*, uint32_t) { return -38; /*ENOSYS*/ }
int shim_afxdp_poll(Shim*, uint32_t, uint64_t) { return -38; }
#endif  // FLOWSHIM_HAVE_AFXDP

}  // extern "C"
