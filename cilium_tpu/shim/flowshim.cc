// flowshim implementation. See flowshim.h for the component map.

#include "flowshim.h"

#include <errno.h>
#include <string.h>

#include <algorithm>
#include <array>
#include <cstdio>
#include <deque>
#include <vector>

#if defined(__linux__) && __has_include(<linux/if_xdp.h>)
#define FLOWSHIM_HAVE_AFXDP 1
#include <linux/if_xdp.h>
#include <net/if.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <unistd.h>
#else
#define FLOWSHIM_HAVE_AFXDP 0
#endif

namespace {

// ---------------------------------------------------------------------------
// Hash — bit-identical to cilium_tpu/kernels/hashing.py (murmur3-style
// accumulate + fmix32). The steering contract depends on this equality.
// ---------------------------------------------------------------------------
constexpr uint32_t kC1 = 0xCC9E2D51u;
constexpr uint32_t kC2 = 0x1B873593u;
constexpr uint32_t kSeed = 0x9747B28Cu;

inline uint32_t rotl32(uint32_t x, int r) {
  return (x << r) | (x >> (32 - r));
}

uint32_t hash_words(const uint32_t* w, int n) {
  uint32_t h = kSeed;
  for (int i = 0; i < n; i++) {
    uint32_t k = w[i] * kC1;
    k = rotl32(k, 15);
    k = k * kC2;
    h ^= k;
    h = rotl32(h, 13);
    h = h * 5u + 0xE6546B64u;
  }
  h ^= h >> 16;
  h *= 0x85EBCA6Bu;
  h ^= h >> 13;
  h *= 0xC2B2AE35u;
  h ^= h >> 16;
  return h;
}

void ct_key_words(const ShimRecord& r, bool reverse, uint32_t out[10]) {
  uint32_t src[4], dst[4];  // copy out of the packed struct (alignment-safe)
  memcpy(src, reverse ? r.dst : r.src, 16);
  memcpy(dst, reverse ? r.src : r.dst, 16);
  uint32_t sport = reverse ? r.dport : r.sport;
  uint32_t dport = reverse ? r.sport : r.dport;
  uint32_t dir = reverse ? (1u - r.direction) : r.direction;
  for (int i = 0; i < 4; i++) out[i] = src[i];
  for (int i = 0; i < 4; i++) out[4 + i] = dst[i];
  out[8] = (sport << 16) | dport;
  out[9] = (uint32_t(r.proto) << 8) | dir;
}

inline uint32_t be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

// HTTP methods — ids must match utils/constants.py HTTP_METHOD_IDS.
const char* kMethods[] = {"GET",     "POST",  "PUT",   "DELETE", "HEAD",
                          "OPTIONS", "PATCH", "TRACE", "CONNECT"};

// Frame provenance of a record: where the bytes live so the verdict can be
// enforced on them (umem frames recycle to fill on drop, forward via tx on
// pass; mock-driver records have no frame to enforce on).
struct FrameRef {
  uint64_t addr = 0;
  uint32_t len = 0;
  bool umem = false;
};

struct PendingRecord {
  ShimRecord rec;
  ShimTokens tok;
  FrameRef frame;
};

// One single-producer/single-consumer AF_XDP ring view. The kernel maps
// producer/consumer indices and the descriptor array at fixed offsets; the
// mock backs them with heap memory. Index arithmetic is free-running uint32
// (entries = prod - cons), acquire/release on the shared indices — the same
// contract the kernel's xsk rings use.
struct Ring {
  volatile uint32_t* producer = nullptr;
  volatile uint32_t* consumer = nullptr;
  void* desc = nullptr;
  uint32_t size = 0;  // entries, power of two
};

static inline uint32_t ring_load_prod(const Ring& r) {
  return __atomic_load_n(r.producer, __ATOMIC_ACQUIRE);
}
static inline uint32_t ring_load_cons(const Ring& r) {
  return __atomic_load_n(r.consumer, __ATOMIC_ACQUIRE);
}
static inline uint32_t ring_entries(const Ring& r) {
  return ring_load_prod(r) - ring_load_cons(r);
}
static inline uint32_t ring_free(const Ring& r) {
  return r.size - ring_entries(r);
}

// fill/completion rings carry bare umem addresses (uint64)
static bool ring_push_addr(Ring& r, uint64_t addr) {
  if (ring_free(r) == 0) return false;
  uint32_t prod = *r.producer;
  static_cast<uint64_t*>(r.desc)[prod & (r.size - 1)] = addr;
  __atomic_store_n(r.producer, prod + 1, __ATOMIC_RELEASE);
  return true;
}
static bool ring_pop_addr(Ring& r, uint64_t* addr) {
  if (ring_entries(r) == 0) return false;
  uint32_t cons = *r.consumer;
  *addr = static_cast<const uint64_t*>(r.desc)[cons & (r.size - 1)];
  __atomic_store_n(r.consumer, cons + 1, __ATOMIC_RELEASE);
  return true;
}

// rx/tx rings carry descriptors
static bool ring_push_desc(Ring& r, const ShimXdpDesc& d) {
  if (ring_free(r) == 0) return false;
  uint32_t prod = *r.producer;
  static_cast<ShimXdpDesc*>(r.desc)[prod & (r.size - 1)] = d;
  __atomic_store_n(r.producer, prod + 1, __ATOMIC_RELEASE);
  return true;
}
static bool ring_pop_desc(Ring& r, ShimXdpDesc* d) {
  if (ring_entries(r) == 0) return false;
  uint32_t cons = *r.consumer;
  *d = static_cast<const ShimXdpDesc*>(r.desc)[cons & (r.size - 1)];
  __atomic_store_n(r.consumer, cons + 1, __ATOMIC_RELEASE);
  return true;
}

}  // namespace

struct Shim {
  uint32_t batch_size;
  uint64_t timeout_us;
  std::deque<PendingRecord> pending;
  uint64_t first_pending_ts = 0;
  std::vector<std::pair<std::array<uint8_t, 16>, uint32_t>> endpoints;
  ShimStats stats{};
  uint32_t next_frame_idx = 0;
  // frames of emitted-but-unverdicted batches, in emission order —
  // shim_apply_verdicts consumes the oldest batch (FIFO matches the
  // poll_batch → classify → verdict pipeline, including when several
  // batches are in flight). Bounded: harvest-only consumers (tap mode,
  // pcap replay) never apply verdicts, so old batches age out (frames
  // recycled, counted in verdict_expired). The Python binding mirrors
  // kMaxUnverdictedBatches for its per-batch count FIFO.
  std::deque<std::vector<FrameRef>> emitted_batches;
  bool enforcing = false;  // a verdict was applied → never age batches out
  // service LB steering state (see shim_set_lb)
  std::vector<uint32_t> lb_tab_keys;  // [cap*6]
  std::vector<int32_t> lb_tab_val;    // [cap]
  uint32_t lb_cap = 0;
  uint32_t lb_probe_depth = 0;
  std::vector<int32_t> lb_fe_service;  // [F]
  std::vector<int32_t> lb_maglev;      // [S*M]
  uint32_t lb_maglev_m = 0;
  std::vector<uint32_t> lb_be_addr;    // [B*4]
  std::vector<int32_t> lb_be_port;     // [B]
  // umem + rings (kernel-mapped after afxdp_bind, heap-backed after
  // mock_rings_init)
  int xsk_fd = -1;
  uint8_t* umem_area = nullptr;
  size_t umem_size = 0;
  uint32_t frame_size = 0;
  bool rings_ready = false;
  bool rings_mock = false;
  Ring fill, comp, rx, tx;
  // mock-mode backing storage
  std::vector<uint64_t> mock_addr_mem;   // fill+comp descriptor arrays
  std::vector<ShimXdpDesc> mock_desc_mem;  // rx+tx descriptor arrays
  std::vector<uint32_t> mock_idx_mem;    // producer/consumer indices
  std::vector<uint8_t> mock_umem;
#if FLOWSHIM_HAVE_AFXDP
  void* ring_maps[4] = {nullptr, nullptr, nullptr, nullptr};
  size_t ring_map_lens[4] = {0, 0, 0, 0};
#endif
};

extern "C" {

Shim* shim_create(uint32_t batch_size, uint64_t timeout_us) {
  Shim* s = new Shim();
  s->batch_size = batch_size ? batch_size : 1;
  s->timeout_us = timeout_us;
  return s;
}

void shim_destroy(Shim* s) {
#if FLOWSHIM_HAVE_AFXDP
  if (s->xsk_fd >= 0) close(s->xsk_fd);
  for (int i = 0; i < 4; i++)
    if (s->ring_maps[i]) munmap(s->ring_maps[i], s->ring_map_lens[i]);
  if (s->umem_area && !s->rings_mock) munmap(s->umem_area, s->umem_size);
#endif
  delete s;
}

int shim_register_endpoint(Shim* s, const uint8_t ip16[16], uint32_t ep_id) {
  std::array<uint8_t, 16> a;
  memcpy(a.data(), ip16, 16);
  s->endpoints.emplace_back(a, ep_id);
  return 0;
}

// ---------------------------------------------------------------------------
// Parser
// ---------------------------------------------------------------------------
static const uint8_t kV4Mapped[12] = {0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0xFF, 0xFF};

static bool parse_frame(Shim* s, const uint8_t* f, uint32_t len,
                        PendingRecord* out) {
  if (len < 14) return false;
  uint16_t ethertype = (uint16_t(f[12]) << 8) | f[13];
  uint32_t off = 14;
  if (ethertype == 0x8100 || ethertype == 0x88A8) {  // VLAN
    if (len < 18) return false;
    ethertype = (uint16_t(f[16]) << 8) | f[17];
    off = 18;
  }

  ShimRecord& r = out->rec;
  memset(&r, 0, sizeof(r));
  ShimTokens& t = out->tok;
  memset(&t, 0, sizeof(t));
  t.method = 255;

  uint8_t src16[16], dst16[16];
  uint32_t l4off;
  uint8_t proto;

  if (ethertype == 0x0800) {  // IPv4
    if (len < off + 20) return false;
    const uint8_t* ip = f + off;
    uint32_t ihl = (ip[0] & 0x0F) * 4;
    if ((ip[0] >> 4) != 4 || ihl < 20 || len < off + ihl) return false;
    // fragments with nonzero offset carry no L4 header — refuse (the
    // classifier treats them as untrackable; upstream has a fragmap)
    uint16_t frag = ((uint16_t(ip[6]) << 8) | ip[7]) & 0x1FFF;
    if (frag != 0) return false;
    proto = ip[9];
    memcpy(src16, kV4Mapped, 12);
    memcpy(src16 + 12, ip + 12, 4);
    memcpy(dst16, kV4Mapped, 12);
    memcpy(dst16 + 12, ip + 16, 4);
    l4off = off + ihl;
    r.is_v6 = 0;
  } else if (ethertype == 0x86DD) {  // IPv6 (no extension headers in v1)
    if (len < off + 40) return false;
    const uint8_t* ip = f + off;
    if ((ip[0] >> 4) != 6) return false;
    proto = ip[6];
    memcpy(src16, ip + 8, 16);
    memcpy(dst16, ip + 24, 16);
    l4off = off + 40;
    r.is_v6 = 1;
  } else {
    return false;
  }

  for (int i = 0; i < 4; i++) {
    r.src[i] = be32(src16 + 4 * i);
    r.dst[i] = be32(dst16 + 4 * i);
  }
  r.proto = proto;

  if (proto == 6) {  // TCP
    if (len < l4off + 20) return false;
    const uint8_t* tcp = f + l4off;
    r.sport = (uint16_t(tcp[0]) << 8) | tcp[1];
    r.dport = (uint16_t(tcp[2]) << 8) | tcp[3];
    r.tcp_flags = tcp[13];
    uint32_t doff = (tcp[12] >> 4) * 4;
    uint32_t payload = l4off + doff;
    if (doff >= 20 && len > payload) {
      // HTTP request-line tokenizer
      const uint8_t* p = f + payload;
      uint32_t plen = len - payload;
      for (uint32_t m = 0; m < sizeof(kMethods) / sizeof(kMethods[0]); m++) {
        size_t mlen = strlen(kMethods[m]);
        if (plen > mlen + 1 && memcmp(p, kMethods[m], mlen) == 0 &&
            p[mlen] == ' ') {
          t.has_tokens = 1;
          t.method = uint8_t(m);
          uint32_t start = mlen + 1;
          uint32_t end = start;
          while (end < plen && end - start < 64 && p[end] != ' ' &&
                 p[end] != '\r' && p[end] != '\n')
            end++;
          t.path_len = uint16_t(end - start);
          memcpy(t.path, p + start, t.path_len);
          break;
        }
      }
    }
  } else if (proto == 17 || proto == 132) {  // UDP / SCTP
    if (len < l4off + 8) return false;
    const uint8_t* l4 = f + l4off;
    r.sport = (uint16_t(l4[0]) << 8) | l4[1];
    r.dport = (uint16_t(l4[2]) << 8) | l4[3];
  } else if (proto == 1 || proto == 58) {  // ICMP / ICMPv6: type in dport
    if (len < l4off + 4) return false;
    r.dport = f[l4off];
  }

  // direction + endpoint classification: src match → egress, dst → ingress
  r.ep_id = 0;
  r.direction = 1;
  for (const auto& ep : s->endpoints) {
    if (memcmp(ep.first.data(), src16, 16) == 0) {
      r.ep_id = ep.second;
      r.direction = 0;
      break;
    }
    if (memcmp(ep.first.data(), dst16, 16) == 0) {
      r.ep_id = ep.second;
      r.direction = 1;
      break;
    }
  }
  r.orig_len = len;
  return true;
}

int shim_feed_frame(Shim* s, const uint8_t* frame, uint32_t len,
                    uint64_t now_us) {
  s->stats.frames_seen++;
  PendingRecord pr;
  if (!parse_frame(s, frame, len, &pr)) {
    s->stats.parse_errors++;
    return -1;
  }
  pr.rec.frame_idx = s->next_frame_idx++;
  if (s->pending.empty()) s->first_pending_ts = now_us;
  s->pending.push_back(pr);
  s->stats.frames_parsed++;
  return 0;
}

static constexpr size_t kMaxUnverdictedBatches = 64;

uint32_t shim_poll_batch(Shim* s, uint64_t now_us, int force,
                         ShimRecord* out_records, ShimTokens* out_tokens) {
  if (s->pending.empty()) return 0;
  bool full = s->pending.size() >= s->batch_size;
  bool timed_out = now_us - s->first_pending_ts >= s->timeout_us;
  if (!full && !timed_out && !force) return 0;
  uint32_t n = std::min<size_t>(s->pending.size(), s->batch_size);
  std::vector<FrameRef> frames;
  frames.reserve(n);
  for (uint32_t i = 0; i < n; i++) {
    out_records[i] = s->pending.front().rec;
    out_tokens[i] = s->pending.front().tok;
    frames.push_back(s->pending.front().frame);
    s->pending.pop_front();
  }
  s->emitted_batches.push_back(std::move(frames));
  // age out ONLY for harvest-only consumers: once a verdict has ever been
  // applied the consumer is enforcing, and evicting would desync every
  // later verdict onto the wrong batch's frames (off-by-one enforcement —
  // worse than unbounded growth, which backpressure bounds in practice)
  while (!s->enforcing &&
         s->emitted_batches.size() > kMaxUnverdictedBatches) {
    for (const FrameRef& fr : s->emitted_batches.front()) {
      s->stats.verdict_expired++;
      if (fr.umem && s->rings_ready) ring_push_addr(s->fill, fr.addr);
    }
    s->emitted_batches.pop_front();
  }
  if (!s->pending.empty()) s->first_pending_ts = now_us;
  s->stats.batches_emitted++;
  s->stats.records_emitted += n;
  return n;
}

static void kick_tx(Shim* s) {
#if FLOWSHIM_HAVE_AFXDP
  if (s->xsk_fd >= 0)
    sendto(s->xsk_fd, nullptr, 0, MSG_DONTWAIT, nullptr, 0);
#else
  (void)s;
#endif
}

void shim_apply_verdicts(Shim* s, const uint8_t* allow, uint32_t n) {
  s->enforcing = true;
  bool sent = false;
  std::vector<FrameRef> frames;
  if (!s->emitted_batches.empty()) {
    frames = std::move(s->emitted_batches.front());
    s->emitted_batches.pop_front();
  }
  for (uint32_t i = 0; i < n; i++) {
    FrameRef fr;
    if (i < frames.size()) fr = frames[i];
    if (allow[i]) {
      if (fr.umem && s->rings_ready) {
        // forward: hand the frame to the tx ring; the frame returns to the
        // fill ring via the completion ring once the NIC is done with it
        ShimXdpDesc d{fr.addr, fr.len, 0};
        if (ring_push_desc(s->tx, d)) {
          sent = true;
          s->stats.verdict_passes++;
        } else {
          // tx ring full → drop rather than leak the frame; counted apart
          // from policy drops so NIC backpressure loss is visible
          s->stats.tx_full_drops++;
          ring_push_addr(s->fill, fr.addr);
        }
      } else {
        s->stats.verdict_passes++;
      }
    } else {
      s->stats.verdict_drops++;
      if (fr.umem && s->rings_ready) ring_push_addr(s->fill, fr.addr);
    }
  }
  // verdicts short of the batch's record count: the rest fail closed
  // (dropped + recycled) rather than leaking frames
  for (size_t i = n; i < frames.size(); i++) {
    s->stats.verdict_drops++;
    if (frames[i].umem && s->rings_ready)
      ring_push_addr(s->fill, frames[i].addr);
  }
  if (sent) kick_tx(s);
}

void shim_get_stats(const Shim* s, ShimStats* out) { *out = s->stats; }

uint32_t shim_flow_shard(const ShimRecord* rec, uint32_t n_shards) {
  uint32_t fwd[10], rev[10];
  ct_key_words(*rec, false, fwd);
  ct_key_words(*rec, true, rev);
  return (hash_words(fwd, 10) ^ hash_words(rev, 10)) % n_shards;
}

int shim_set_lb(Shim* s, const uint32_t* tab_keys, const int32_t* tab_val,
                uint32_t cap, uint32_t probe_depth, const int32_t* fe_service,
                uint32_t n_fe, const int32_t* maglev, uint32_t n_svc,
                uint32_t maglev_m, const uint32_t* be_addr,
                const int32_t* be_port, uint32_t n_be) {
  if (cap == 0) {
    s->lb_cap = 0;
    return 0;
  }
  if (cap & (cap - 1)) return -1;  // capacity must be a power of two
  s->lb_tab_keys.assign(tab_keys, tab_keys + size_t(cap) * 6);
  s->lb_tab_val.assign(tab_val, tab_val + cap);
  s->lb_cap = cap;
  s->lb_probe_depth = probe_depth;
  s->lb_fe_service.assign(fe_service, fe_service + n_fe);
  s->lb_maglev.assign(maglev, maglev + size_t(n_svc) * maglev_m);
  s->lb_maglev_m = maglev_m;
  s->lb_be_addr.assign(be_addr, be_addr + size_t(n_be) * 4);
  s->lb_be_port.assign(be_port, be_port + n_be);
  return 0;
}

// Mirror of compile/lb.lb_translate_np for one record: frontend probe →
// Maglev backend select → DNAT of (dst, dport). no-backend frontends stay
// untranslated (those packets drop; any shard is correct and this matches
// the host mirror).
static bool lb_translate(const Shim* s, const ShimRecord& r,
                         uint32_t new_dst[4], uint16_t* new_dport) {
  if (s->lb_cap == 0) return false;
  uint32_t dst[4];
  memcpy(dst, r.dst, 16);
  uint32_t key[6] = {dst[0], dst[1], dst[2], dst[3], uint32_t(r.dport),
                     uint32_t(r.proto)};
  uint32_t mask = s->lb_cap - 1;
  uint32_t base = hash_words(key, 6) & mask;
  int32_t fe = -1;
  for (uint32_t d = 0; d < s->lb_probe_depth && fe < 0; d++) {
    uint32_t slot = (base + d) & mask;
    if (s->lb_tab_val[slot] < 0) continue;
    if (memcmp(&s->lb_tab_keys[size_t(slot) * 6], key, 24) == 0)
      fe = s->lb_tab_val[slot];
  }
  if (fe < 0) return false;
  uint32_t src[4];
  memcpy(src, r.src, 16);
  uint32_t sel[10] = {src[0], src[1], src[2], src[3],
                      dst[0], dst[1], dst[2], dst[3],
                      (uint32_t(r.sport) << 16) | uint32_t(r.dport),
                      uint32_t(r.proto) << 8};
  uint32_t slot = hash_words(sel, 10) % s->lb_maglev_m;
  int32_t be =
      s->lb_maglev[size_t(s->lb_fe_service[fe]) * s->lb_maglev_m + slot];
  if (be < 0) return false;
  memcpy(new_dst, &s->lb_be_addr[size_t(be) * 4], 16);
  *new_dport = uint16_t(s->lb_be_port[be]);
  return true;
}

uint32_t shim_flow_shard2(const Shim* s, const ShimRecord* rec,
                          uint32_t n_shards) {
  ShimRecord r = *rec;
  uint32_t new_dst[4];
  uint16_t new_dport;
  if (lb_translate(s, r, new_dst, &new_dport)) {
    memcpy(r.dst, new_dst, 16);
    r.dport = new_dport;
  }
  return shim_flow_shard(&r, n_shards);
}

// ---------------------------------------------------------------------------
// The ring-draining packet path (shared by kernel-mapped and mocked rings):
//   1. completion → fill: frames the NIC finished transmitting recycle;
//   2. rx walk: each descriptor's umem frame goes through the parser into
//      the batcher, carrying its FrameRef for verdict enforcement;
//      unparseable frames recycle to the fill ring immediately (they never
//      reach the classifier — the upstream analog is an XDP_DROP before the
//      tc layer).
// ---------------------------------------------------------------------------
int shim_afxdp_poll(Shim* s, uint32_t budget, uint64_t now_us) {
  if (!s->rings_ready) return s->xsk_fd < 0 ? -EBADF : -EINVAL;
  uint64_t addr;
  while (ring_pop_addr(s->comp, &addr)) ring_push_addr(s->fill, addr);

  uint32_t drained = 0;
  ShimXdpDesc d;
  while (drained < budget && ring_entries(s->rx) > 0) {
    if (!ring_pop_desc(s->rx, &d)) break;
    drained++;
    s->stats.frames_seen++;
    const uint8_t* frame = s->umem_area + d.addr;
    PendingRecord pr;
    if (!parse_frame(s, frame, d.len, &pr)) {
      s->stats.parse_errors++;
      ring_push_addr(s->fill, d.addr);
      continue;
    }
    pr.rec.frame_idx = s->next_frame_idx++;
    pr.frame = FrameRef{d.addr, d.len, true};
    if (s->pending.empty()) s->first_pending_ts = now_us;
    s->pending.push_back(pr);
    s->stats.frames_parsed++;
  }
  return int(drained);
}

// ---------------------------------------------------------------------------
// Memory-mocked rings (unprivileged testbench for the path above)
// ---------------------------------------------------------------------------
int shim_mock_rings_init(Shim* s, uint32_t ring_size, uint32_t frame_size,
                         uint32_t n_frames) {
  if (s->rings_ready) return -EBUSY;
  if (!ring_size || (ring_size & (ring_size - 1))) return -EINVAL;
  if (!frame_size || !n_frames) return -EINVAL;
  s->mock_umem.assign(size_t(frame_size) * n_frames, 0);
  s->umem_area = s->mock_umem.data();
  s->umem_size = s->mock_umem.size();
  s->frame_size = frame_size;
  s->mock_addr_mem.assign(size_t(ring_size) * 2, 0);
  s->mock_desc_mem.assign(size_t(ring_size) * 2, ShimXdpDesc{});
  s->mock_idx_mem.assign(8, 0);
  s->fill = Ring{&s->mock_idx_mem[0], &s->mock_idx_mem[1],
                 s->mock_addr_mem.data(), ring_size};
  s->comp = Ring{&s->mock_idx_mem[2], &s->mock_idx_mem[3],
                 s->mock_addr_mem.data() + ring_size, ring_size};
  s->rx = Ring{&s->mock_idx_mem[4], &s->mock_idx_mem[5],
               s->mock_desc_mem.data(), ring_size};
  s->tx = Ring{&s->mock_idx_mem[6], &s->mock_idx_mem[7],
               s->mock_desc_mem.data() + ring_size, ring_size};
  for (uint32_t i = 0; i < n_frames && ring_free(s->fill); i++)
    ring_push_addr(s->fill, uint64_t(i) * frame_size);
  s->rings_ready = true;
  s->rings_mock = true;
  return 0;
}

int shim_mock_rx_inject(Shim* s, const uint8_t* frame, uint32_t len) {
  if (!s->rings_mock) return -EINVAL;
  if (len > s->frame_size) return -EMSGSIZE;
  if (ring_free(s->rx) == 0) return -ENOSPC;
  uint64_t addr;
  if (!ring_pop_addr(s->fill, &addr)) return -ENOSPC;
  memcpy(s->umem_area + addr, frame, len);
  ring_push_desc(s->rx, ShimXdpDesc{addr, len, 0});
  return 0;
}

uint32_t shim_mock_tx_drain(Shim* s, uint64_t* addrs, uint32_t* lens,
                            uint32_t max) {
  if (!s->rings_mock) return 0;
  uint32_t n = 0;
  ShimXdpDesc d;
  while (n < max && ring_pop_desc(s->tx, &d)) {
    if (addrs) addrs[n] = d.addr;
    if (lens) lens[n] = d.len;
    ring_push_addr(s->comp, d.addr);  // "transmitted" → completion
    n++;
  }
  return n;
}

uint32_t shim_ring_fill_level(const Shim* s) {
  return s->rings_ready ? ring_entries(s->fill) : 0;
}

// ---------------------------------------------------------------------------
// AF_XDP socket setup (privileged; graceful -errno in unprivileged
// containers — callers fall back to mock rings or the mock driver)
// ---------------------------------------------------------------------------
#if FLOWSHIM_HAVE_AFXDP
static constexpr uint32_t kFrameSize = 2048;
static constexpr uint32_t kNumFrames = 4096;

int shim_afxdp_bind(Shim* s, const char* ifname, uint32_t queue_id) {
  if (s->rings_ready) return -EBUSY;
  unsigned ifindex = if_nametoindex(ifname);
  if (!ifindex) return -ENODEV;
  int fd = socket(AF_XDP, SOCK_RAW, 0);
  if (fd < 0) return -errno;

  size_t umem_size = size_t(kFrameSize) * kNumFrames;
  void* area = mmap(nullptr, umem_size, PROT_READ | PROT_WRITE,
                    MAP_PRIVATE | MAP_ANONYMOUS | MAP_POPULATE, -1, 0);
  if (area == MAP_FAILED) {
    close(fd);
    return -errno;
  }
  struct xdp_umem_reg umem_reg = {};
  umem_reg.addr = reinterpret_cast<uint64_t>(area);
  umem_reg.len = umem_size;
  umem_reg.chunk_size = kFrameSize;
  if (setsockopt(fd, SOL_XDP, XDP_UMEM_REG, &umem_reg, sizeof(umem_reg)) < 0) {
    int err = -errno;
    munmap(area, umem_size);
    close(fd);
    return err;
  }
  uint32_t ring_sz = kNumFrames;
  setsockopt(fd, SOL_XDP, XDP_UMEM_FILL_RING, &ring_sz, sizeof(ring_sz));
  setsockopt(fd, SOL_XDP, XDP_UMEM_COMPLETION_RING, &ring_sz, sizeof(ring_sz));
  setsockopt(fd, SOL_XDP, XDP_RX_RING, &ring_sz, sizeof(ring_sz));
  setsockopt(fd, SOL_XDP, XDP_TX_RING, &ring_sz, sizeof(ring_sz));

  // map the four rings at the kernel-reported offsets
  struct xdp_mmap_offsets off = {};
  socklen_t optlen = sizeof(off);
  if (getsockopt(fd, SOL_XDP, XDP_MMAP_OFFSETS, &off, &optlen) < 0) {
    int err = -errno;
    munmap(area, umem_size);
    close(fd);
    return err;
  }
  struct MapSpec {
    uint64_t pgoff;
    uint64_t prod_off, cons_off, desc_off;
    uint32_t entries;
    size_t desc_bytes;
    Ring* ring;
  } specs[4] = {
      {XDP_UMEM_PGOFF_FILL_RING, off.fr.producer, off.fr.consumer,
       off.fr.desc, ring_sz, sizeof(uint64_t), &s->fill},
      {XDP_UMEM_PGOFF_COMPLETION_RING, off.cr.producer, off.cr.consumer,
       off.cr.desc, ring_sz, sizeof(uint64_t), &s->comp},
      {XDP_PGOFF_RX_RING, off.rx.producer, off.rx.consumer, off.rx.desc,
       ring_sz, sizeof(struct xdp_desc), &s->rx},
      {XDP_PGOFF_TX_RING, off.tx.producer, off.tx.consumer, off.tx.desc,
       ring_sz, sizeof(struct xdp_desc), &s->tx},
  };
  for (int i = 0; i < 4; i++) {
    size_t len = specs[i].desc_off + size_t(specs[i].entries) *
                                         specs[i].desc_bytes;
    void* m = mmap(nullptr, len, PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_POPULATE, fd, specs[i].pgoff);
    if (m == MAP_FAILED) {
      int err = -errno;
      for (int j = 0; j < i; j++) {
        munmap(s->ring_maps[j], s->ring_map_lens[j]);
        s->ring_maps[j] = nullptr;
        s->ring_map_lens[j] = 0;
      }
      munmap(area, umem_size);
      close(fd);
      return err;
    }
    s->ring_maps[i] = m;
    s->ring_map_lens[i] = len;
    uint8_t* base = static_cast<uint8_t*>(m);
    *specs[i].ring = Ring{
        reinterpret_cast<volatile uint32_t*>(base + specs[i].prod_off),
        reinterpret_cast<volatile uint32_t*>(base + specs[i].cons_off),
        base + specs[i].desc_off, specs[i].entries};
  }

  struct sockaddr_xdp sxdp = {};
  sxdp.sxdp_family = AF_XDP;
  sxdp.sxdp_ifindex = ifindex;
  sxdp.sxdp_queue_id = queue_id;
  sxdp.sxdp_flags = XDP_COPY;  // portable; zerocopy negotiated by drivers
  if (bind(fd, reinterpret_cast<struct sockaddr*>(&sxdp), sizeof(sxdp)) < 0) {
    int err = -errno;
    for (int j = 0; j < 4; j++) {
      munmap(s->ring_maps[j], s->ring_map_lens[j]);
      s->ring_maps[j] = nullptr;
      s->ring_map_lens[j] = 0;
    }
    munmap(area, umem_size);
    close(fd);
    return err;
  }
  s->xsk_fd = fd;
  s->umem_area = static_cast<uint8_t*>(area);
  s->umem_size = umem_size;
  s->frame_size = kFrameSize;
  // prime the fill ring: hand every frame to the NIC for rx
  for (uint32_t i = 0; i < kNumFrames && ring_free(s->fill); i++)
    ring_push_addr(s->fill, uint64_t(i) * kFrameSize);
  s->rings_ready = true;
  return 0;
}
#else   // !FLOWSHIM_HAVE_AFXDP
int shim_afxdp_bind(Shim*, const char*, uint32_t) { return -38; /*ENOSYS*/ }
#endif  // FLOWSHIM_HAVE_AFXDP

}  // extern "C"
