"""ctypes bindings for the C++ shim + goldengen scenario IO.

The shim's batch output converts straight into the ``kernels/records``
dict-of-arrays layout, so tests and pcap replay drive the same path the
AF_XDP front end would: frames → shim parse/batch → device classify →
verdict bitmap → shim_apply_verdicts.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from cilium_tpu.kernels.records import empty_batch, reset_batch_rows
from cilium_tpu.runtime.faults import FAULTS
from cilium_tpu.utils import constants as C

_SHIM_DIR = os.path.dirname(os.path.abspath(__file__))
# CILIUM_TPU_SHIM_LIB overrides the library (e.g. the TSan build from
# `make -C cilium_tpu/shim tsan` — SURVEY §5 race detection)
LIB_PATH = os.environ.get("CILIUM_TPU_SHIM_LIB",
                          os.path.join(_SHIM_DIR, "libflowshim.so"))
GOLDENGEN_PATH = os.path.join(_SHIM_DIR, "goldengen")


class ShimRecord(ctypes.Structure):
    _pack_ = 1
    _fields_ = [
        ("src", ctypes.c_uint32 * 4),
        ("dst", ctypes.c_uint32 * 4),
        ("sport", ctypes.c_uint16),
        ("dport", ctypes.c_uint16),
        ("proto", ctypes.c_uint8),
        ("tcp_flags", ctypes.c_uint8),
        ("is_v6", ctypes.c_uint8),
        ("direction", ctypes.c_uint8),
        ("ep_id", ctypes.c_uint32),
        ("frame_idx", ctypes.c_uint32),
        ("orig_len", ctypes.c_uint32),
        ("pad", ctypes.c_uint8 * 12),
    ]


class ShimTokens(ctypes.Structure):
    _pack_ = 1
    _fields_ = [
        ("has_tokens", ctypes.c_uint8),
        ("method", ctypes.c_uint8),
        ("path_len", ctypes.c_uint16),
        ("path", ctypes.c_uint8 * 64),
        ("pad", ctypes.c_uint8 * 4),
    ]


class ShimStats(ctypes.Structure):
    _fields_ = [(n, ctypes.c_uint64) for n in (
        "frames_seen", "frames_parsed", "parse_errors", "batches_emitted",
        "records_emitted", "verdict_drops", "verdict_passes",
        "tx_full_drops", "verdict_expired")]


# must equal flowshim.cc kMaxUnverdictedBatches: both sides age out the
# oldest unverdicted batch at the same poll, keeping the verdict FIFO and
# the Python count FIFO aligned
MAX_UNVERDICTED_BATCHES = 64


def _load_lib():
    if not os.path.exists(LIB_PATH):
        raise FileNotFoundError(
            f"{LIB_PATH} not built — run `make -C cilium_tpu/shim`")
    lib = ctypes.CDLL(LIB_PATH)
    lib.shim_create.restype = ctypes.c_void_p
    lib.shim_create.argtypes = [ctypes.c_uint32, ctypes.c_uint64]
    lib.shim_destroy.argtypes = [ctypes.c_void_p]
    lib.shim_register_endpoint.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
    lib.shim_feed_frame.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint64]
    lib.shim_poll_batch.restype = ctypes.c_uint32
    lib.shim_poll_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
        ctypes.POINTER(ShimRecord), ctypes.POINTER(ShimTokens)]
    lib.shim_apply_verdicts.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
    lib.shim_get_stats.argtypes = [ctypes.c_void_p, ctypes.POINTER(ShimStats)]
    lib.shim_flow_shard.restype = ctypes.c_uint32
    lib.shim_flow_shard.argtypes = [ctypes.POINTER(ShimRecord), ctypes.c_uint32]
    lib.shim_flow_shard2.restype = ctypes.c_uint32
    lib.shim_flow_shard2.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ShimRecord), ctypes.c_uint32]
    lib.shim_set_lb.restype = ctypes.c_int
    lib.shim_set_lb.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_uint32, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_uint32, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_uint32]
    lib.shim_afxdp_bind.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
    lib.shim_afxdp_poll.restype = ctypes.c_int
    lib.shim_afxdp_poll.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64]
    lib.shim_mock_rings_init.restype = ctypes.c_int
    lib.shim_mock_rings_init.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32]
    lib.shim_mock_rx_inject.restype = ctypes.c_int
    lib.shim_mock_rx_inject.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
    lib.shim_mock_tx_drain.restype = ctypes.c_uint32
    lib.shim_mock_tx_drain.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint32), ctypes.c_uint32]
    lib.shim_ring_fill_level.restype = ctypes.c_uint32
    lib.shim_ring_fill_level.argtypes = [ctypes.c_void_p]
    return lib




class FlowShim:
    """Python handle on the native shim (mock-driver mode unless afxdp_bind
    succeeds)."""

    def __init__(self, batch_size: int = 256, timeout_us: int = 500):
        self._lib = _load_lib()
        self._handle = self._lib.shim_create(batch_size, timeout_us)
        self.batch_size = batch_size
        self._rec_buf = (ShimRecord * batch_size)()
        self._tok_buf = (ShimTokens * batch_size)()
        # structured views over the ctypes buffers, built once — frombuffer
        # per poll would allocate a view object on the harvest hot path
        self._rec_view = np.frombuffer(self._rec_buf, dtype=self._REC_DTYPE,
                                       count=batch_size)
        self._tok_view = np.frombuffer(self._tok_buf, dtype=self._TOK_DTYPE,
                                       count=batch_size)
        self._l7_pos = np.arange(C.L7_PATH_MAXLEN)
        # record counts of harvested-but-unverdicted batches, FIFO — the
        # C++ side holds one FrameRef per emitted record, so apply_verdicts
        # must consume exactly that many per batch (short verdict arrays
        # would desync frames from verdicts; see apply_verdicts)
        self._pending_counts: list = []
        self._enforcing = False        # mirrors flowshim.cc Shim::enforcing
        self._rings_ready = False      # set by afxdp_bind/mock_rings_init

    def close(self):
        if self._handle:
            self._lib.shim_destroy(self._handle)
            self._handle = None

    def register_endpoint(self, ip: str, ep_id: int) -> None:
        from cilium_tpu.utils.ip import parse_addr
        addr16, _ = parse_addr(ip)
        self._lib.shim_register_endpoint(self._handle, addr16, ep_id)

    def feed_frame(self, frame: bytes, now_us: int = 0) -> bool:
        return self._lib.shim_feed_frame(
            self._handle, frame, len(frame), now_us) == 0

    # structured-dtype mirrors of ShimRecord/ShimTokens for vectorized
    # batch conversion (a per-record Python loop caps the harvest path at
    # ~1e5 records/s; frombuffer keeps it out of the packet path)
    _REC_DTYPE = np.dtype([
        ("src", "<u4", (4,)), ("dst", "<u4", (4,)),
        ("sport", "<u2"), ("dport", "<u2"),
        ("proto", "u1"), ("tcp_flags", "u1"), ("is_v6", "u1"),
        ("direction", "u1"), ("ep_id", "<u4"), ("frame_idx", "<u4"),
        ("orig_len", "<u4"), ("pad", "u1", (12,))])
    _TOK_DTYPE = np.dtype([
        ("has_tokens", "u1"), ("method", "u1"), ("path_len", "<u2"),
        ("path", "u1", (C.L7_PATH_MAXLEN,)), ("pad", "u1", (4,))])

    def make_poll_buffer(self) -> Dict[str, np.ndarray]:
        """A reusable ``poll_batch(out=...)`` buffer: the records layout
        plus the shim-side ``_ep_raw``/``_frame_idx`` columns. The feeder
        preallocates a pool of these so the hot harvest loop never builds
        a fresh column dict per poll."""
        b = empty_batch(self.batch_size)
        b["_ep_raw"] = np.zeros((self.batch_size,), dtype=np.int64)
        b["_frame_idx"] = np.zeros((self.batch_size,), dtype=np.int64)
        return b

    def poll_batch(self, now_us: int = 0, force: bool = False,
                   out: Optional[Dict[str, np.ndarray]] = None
                   ) -> Optional[Dict[str, np.ndarray]]:
        """Harvest a batch in the kernels/records layout (None if not ready).
        Records for unknown endpoints (ep_id 0) stay invalid (fail closed).

        ``out=`` reuses a caller-owned buffer from :meth:`make_poll_buffer`
        instead of allocating: rows [:n] are overwritten, rows [n:] are
        reset to the empty-batch defaults (``valid`` False, method ANY,
        zeroed path) so a reused buffer is indistinguishable from a fresh
        one. The caller must not hand the same buffer back before its
        previous batch's consumer is done with it."""
        FAULTS.fire("shim.rx_ring")
        n = self._lib.shim_poll_batch(self._handle, now_us, int(force),
                                      self._rec_buf, self._tok_buf)
        if n == 0:
            return None
        self._pending_counts.append(int(n))
        if not self._enforcing \
                and len(self._pending_counts) > MAX_UNVERDICTED_BATCHES:
            self._pending_counts.pop(0)   # C++ aged out the same batch
        b = out if out is not None else self.make_poll_buffer()
        rec = self._rec_view
        tok = self._tok_view
        b["src"][:n] = rec["src"][:n]
        b["dst"][:n] = rec["dst"][:n]
        b["sport"][:n] = rec["sport"][:n]
        b["dport"][:n] = rec["dport"][:n]
        b["proto"][:n] = rec["proto"][:n]
        b["tcp_flags"][:n] = rec["tcp_flags"][:n]
        b["is_v6"][:n] = rec["is_v6"][:n].astype(bool)
        b["direction"][:n] = rec["direction"][:n]
        b["_ep_raw"][:n] = rec["ep_id"][:n]
        b["_frame_idx"][:n] = rec["frame_idx"][:n]
        b["valid"][:n] = rec["ep_id"][:n] != 0
        has = tok["has_tokens"][:n].astype(bool)
        b["http_method"][:n] = np.where(has, tok["method"][:n],
                                        C.HTTP_METHOD_ANY)
        pos = self._l7_pos
        keep = has[:, None] & (pos[None, :] < tok["path_len"][:n, None])
        b["http_path"][:n] = np.where(keep, tok["path"][:n], 0)
        if out is not None:
            if n < self.batch_size:
                # reused buffer: restore the empty-batch tail so stale
                # rows from the previous poll can never leak into this
                # batch — a reused buffer must be indistinguishable from
                # a fresh one
                reset_batch_rows(b, n, self.batch_size)
            # ep_slot is caller-mapped (poll never writes it): restore the
            # fresh-buffer zeros across ALL rows, not just the tail
            b["ep_slot"][:] = 0
        return b

    def apply_verdicts(self, allow: np.ndarray) -> None:
        """Enforce verdicts for the OLDEST unverdicted batch. ``allow`` may
        cover any prefix of that batch's records (e.g. only the valid rows);
        the remainder is dropped (fail closed) — the C++ side holds one
        frame per emitted record, so the full count must always be consumed
        or later verdicts would enforce on the wrong frames."""
        if not self._pending_counts:
            raise RuntimeError("apply_verdicts without a harvested batch")
        self._enforcing = True
        n = self._pending_counts.pop(0)
        arr = np.zeros((n,), dtype=np.uint8)
        k = min(n, int(np.asarray(allow).shape[0]))
        arr[:k] = np.asarray(allow)[:k].astype(np.uint8)
        self._lib.shim_apply_verdicts(self._handle, arr.tobytes(), n)

    def stats(self) -> Dict[str, int]:
        s = ShimStats()
        self._lib.shim_get_stats(self._handle, ctypes.byref(s))
        return {n: getattr(s, n) for n, _ in ShimStats._fields_}

    def set_lb(self, lb) -> None:
        """Program the steering-side LB state from a compile/lb.LBTables.
        Steering then matches parallel/mesh.flow_shard_of(batch, n, lb=lb):
        service DNAT first, so forward and reply packets of a service flow
        land on the same CT shard."""
        p32 = ctypes.POINTER(ctypes.c_int32)
        pu32 = ctypes.POINTER(ctypes.c_uint32)
        # keep contiguous copies alive across the call (C++ copies them)
        tk = np.ascontiguousarray(lb.tab_keys, dtype=np.uint32)
        tv = np.ascontiguousarray(lb.tab_val, dtype=np.int32)
        fs = np.ascontiguousarray(lb.fe_service, dtype=np.int32)
        mg = np.ascontiguousarray(lb.maglev, dtype=np.int32)
        ba = np.ascontiguousarray(lb.be_addr, dtype=np.uint32)
        bp = np.ascontiguousarray(lb.be_port, dtype=np.int32)
        rc = self._lib.shim_set_lb(
            self._handle,
            tk.ctypes.data_as(pu32), tv.ctypes.data_as(p32),
            tk.shape[0], lb.probe_depth,
            fs.ctypes.data_as(p32), fs.shape[0],
            mg.ctypes.data_as(p32), mg.shape[0], mg.shape[1],
            ba.ctypes.data_as(pu32), bp.ctypes.data_as(p32), bp.shape[0])
        if rc != 0:
            raise ValueError(f"shim_set_lb failed: {rc}")

    def flow_shard(self, rec_index: int, n_shards: int) -> int:
        return self._lib.shim_flow_shard2(
            self._handle, ctypes.byref(self._rec_buf[rec_index]), n_shards)

    def afxdp_bind(self, ifname: str, queue: int = 0) -> int:
        rc = self._lib.shim_afxdp_bind(self._handle, ifname.encode(), queue)
        if rc == 0:
            self._rings_ready = True
        return rc

    @property
    def rings_ready(self) -> bool:
        """Whether rx/fill rings exist (afxdp_bind or mock_rings_init
        succeeded). Python-side truth, deliberately NOT derived from the
        fill LEVEL: with every umem descriptor parked in the rx ring the
        level legitimately reads zero — exactly the state where the ring
        drain is most needed."""
        return self._rings_ready

    # -- ring path (kernel-mapped after afxdp_bind; heap-mocked for tests) --
    def afxdp_poll(self, budget: int = 256, now_us: int = 0) -> int:
        """Drain the rx ring into the batcher (completion→fill recycle
        first). Returns descriptors drained, or -errno.

        The ``shim.rx_ring`` injection point fires here: a fault is one
        failed poll (the caller's harvest loop must tolerate it — frames
        stay queued in the ring and drain on the next poll)."""
        FAULTS.fire("shim.rx_ring")
        return self._lib.shim_afxdp_poll(self._handle, budget, now_us)

    def mock_rings_init(self, ring_size: int = 64, frame_size: int = 2048,
                        n_frames: int = 64) -> None:
        rc = self._lib.shim_mock_rings_init(self._handle, ring_size,
                                            frame_size, n_frames)
        if rc != 0:
            raise OSError(-rc, "shim_mock_rings_init failed")
        self._rings_ready = True

    def mock_rx_inject(self, frame: bytes) -> int:
        """Act as the NIC: fill-ring frame ← frame bytes → rx descriptor."""
        return self._lib.shim_mock_rx_inject(self._handle, frame, len(frame))

    def mock_tx_drain(self, max_n: int = 256):
        """Act as the NIC's tx side: returns [(umem_addr, len)] of frames
        the shim forwarded; marks them transmitted via the completion ring."""
        addrs = (ctypes.c_uint64 * max_n)()
        lens = (ctypes.c_uint32 * max_n)()
        n = self._lib.shim_mock_tx_drain(self._handle, addrs, lens, max_n)
        return [(addrs[i], lens[i]) for i in range(n)]

    def ring_fill_level(self) -> int:
        return self._lib.shim_ring_fill_level(self._handle)


# --------------------------------------------------------------------------- #
# Test-frame builders (Ethernet/IP/TCP/UDP crafting for the mock driver)
# --------------------------------------------------------------------------- #
def build_frame(src_ip: str, dst_ip: str, sport: int, dport: int,
                proto: int = C.PROTO_TCP, tcp_flags: int = C.TCP_SYN,
                payload: bytes = b"", vlan: Optional[int] = None) -> bytes:
    import ipaddress
    src = ipaddress.ip_address(src_ip)
    dst = ipaddress.ip_address(dst_ip)
    eth = b"\x02\x00\x00\x00\x00\x01" + b"\x02\x00\x00\x00\x00\x02"
    if vlan is not None:
        eth += struct.pack(">HH", 0x8100, vlan)
    if proto == C.PROTO_TCP:
        l4 = struct.pack(">HHIIBBHHH", sport, dport, 0, 0, 5 << 4, tcp_flags,
                         65535, 0, 0) + payload
    elif proto in (C.PROTO_UDP, C.PROTO_SCTP):
        l4 = struct.pack(">HHHH", sport, dport, 8 + len(payload), 0) + payload
    else:  # ICMP: dport as the type
        l4 = struct.pack(">BBH", dport, 0, 0) + payload
    if src.version == 4:
        total = 20 + len(l4)
        ip = struct.pack(">BBHHHBBH4s4s", 0x45, 0, total, 0, 0, 64, proto, 0,
                         src.packed, dst.packed)
        return eth + struct.pack(">H", 0x0800) + ip + l4
    ip6 = struct.pack(">IHBB16s16s", 6 << 28, len(l4), proto, 64,
                      src.packed, dst.packed)
    return eth + struct.pack(">H", 0x86DD) + ip6 + l4


def build_http_frame(src_ip: str, dst_ip: str, sport: int, dport: int,
                     method: str, path: str) -> bytes:
    payload = f"{method} {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode()
    return build_frame(src_ip, dst_ip, sport, dport, C.PROTO_TCP,
                       C.TCP_ACK | C.TCP_PSH, payload)


# --------------------------------------------------------------------------- #
# goldengen scenario writer/runner (3-way parity)
# --------------------------------------------------------------------------- #
def write_scenario(path: str, ipcache_entries: Dict[str, int],
                   enforced: Tuple[bool, bool],
                   mapstate_entries: Sequence[Tuple],
                   l7_sets: Sequence[Sequence[Tuple[int, bytes]]],
                   packets: Sequence) -> None:
    """mapstate_entries: (dir, deny, proto, identity, lo, hi, l7_set_1based);
    l7_sets[i]: [(method_id_or_255, path_prefix_bytes)];
    packets: oracle.PacketRecord + .now attribute via tuple (rec, now)."""
    from cilium_tpu.utils.ip import parse_prefix
    out = [b"CTPUGV01"]
    out.append(struct.pack("<I", len(ipcache_entries)))
    for prefix, ident in ipcache_entries.items():
        # goldengen compares in the 128-bit v4-mapped space, same as here
        addr16, plen, is_v6 = parse_prefix(prefix)
        out.append(struct.pack("<16sHBBI", addr16, plen, int(is_v6), 0, ident))
    out.append(struct.pack("<BB", int(enforced[0]), int(enforced[1])))
    out.append(struct.pack("<I", len(mapstate_entries)))
    for (d, deny, proto, ident, lo, hi, l7) in mapstate_entries:
        out.append(struct.pack("<BBBBIHHHH", d, int(deny), proto, 0, ident,
                               lo, hi, l7, 0))
    out.append(struct.pack("<I", len(l7_sets)))
    for rules in l7_sets:
        out.append(struct.pack("<I", len(rules)))
        for method, prefix in rules:
            out.append(struct.pack("<BB64s", method, len(prefix),
                                   prefix.ljust(64, b"\x00")))
    out.append(struct.pack("<I", len(packets)))
    for rec, now in packets:
        out.append(struct.pack(
            "<16s16sHHBBBBBBH64sI", rec.src_addr, rec.dst_addr, rec.src_port,
            rec.dst_port, rec.proto, rec.tcp_flags, int(rec.is_ipv6),
            rec.direction, int(rec.has_l7_tokens),
            rec.http_method, len(rec.http_path),
            rec.http_path.ljust(64, b"\x00"), now))
    with open(path, "wb") as f:
        f.write(b"".join(out))


def run_goldengen(scenario_path: str, out_path: str) -> np.ndarray:
    """Run the C++ generator → structured array of expected verdicts."""
    if not os.path.exists(GOLDENGEN_PATH):
        raise FileNotFoundError(
            f"{GOLDENGEN_PATH} not built — run `make -C cilium_tpu/shim`")
    subprocess.run([GOLDENGEN_PATH, scenario_path, out_path], check=True)
    raw = np.fromfile(out_path, dtype=np.uint8).reshape(-1, 8)
    return np.rec.fromarrays(
        [raw[:, 0], raw[:, 1], raw[:, 2],
         raw[:, 4:8].copy().view("<u4").reshape(-1)],
        names="allow,reason,status,remote")
