"""Per-tenant weighted-fair admission queue (deficit round robin).

Drop-in replacement for the scheduler's single FIFO ``deque``: it keeps
one FIFO deque *per tenant* and serves them in deficit-round-robin order,
with each tenant's per-round credit proportional to its configured
weight. The public surface is deque-compatible (``append`` / ``popleft``
/ ``remove`` / ``clear`` / ``len`` / iteration) so every scheduler sweep
path — wedged-work collection, victim removal, drain — works unchanged;
only the *order* ``popleft`` returns differs, and with a single tenant
even that collapses to FIFO (credit is always sufficient, so pops come
straight off the one deque in arrival order).

All methods are called under the pipeline lock; this class does no
locking of its own (the :class:`~cilium_tpu.qos.tenancy.TenantTable` it
consults is a leaf lock).
"""

from __future__ import annotations

import math
from collections import deque
from typing import Dict, Iterator, Optional, Tuple

from cilium_tpu.qos.tenancy import TENANT_DEFAULT, WEIGHT_FLOOR_ROWS

#: guard against a zero/negative weight making a tenant's queue-share
#: denominator vanish — weights below this are floored for share math.
_MIN_WEIGHT = 1e-6


class TenantQueues:
    """DRR scheduler state: per-tenant FIFOs + an active-tenant ring.

    ``quantum_rows`` is the per-round credit a weight-1.0 tenant earns
    (the pipeline passes its max bucket, so any single batch is
    affordable within one round at weight >= 1). A zero-weight tenant
    still earns :data:`WEIGHT_FLOOR_ROWS` per round — service is slow
    but guaranteed (the starvation floor).
    """

    def __init__(self, table, quantum_rows: int = 512,
                 lane_rows: int = 0):
        self.table = table
        self._qrows = max(1, int(quantum_rows))
        #: latency-lane bypass threshold (the pipeline's lane bucket):
        #: a lane tenant whose HEAD submission is at most this many rows
        #: is served ahead of the DRR ring. 0 disables the bypass.
        self.lane_rows = max(0, int(lane_rows))
        self._queues: Dict[int, deque] = {}
        self._order: deque = deque()          # tenants with queued work
        self._deficit: Dict[int, float] = {}
        # tenants already granted their quantum this visit — DRR grants
        # ONCE per turn, serves while the deficit lasts, then rotates;
        # topping up on every pop would let the head tenant starve the
        # ring (it could always afford its own next batch)
        self._granted: set = set()
        # rows served via the lane bypass but not yet paid for by a ring
        # quantum — the starvation bound: bypass is allowed only while
        # the debt stays under one quantum, and ring grants pay the debt
        # before banking deficit
        self._lane_debt: Dict[int, float] = {}
        self._len = 0
        # lifetime service accounting (rows/batches the DRR actually
        # handed to the dispatcher) — the bench's share-convergence gate
        # reads these, so they must reflect pop order, not arrivals
        self.admitted_rows: Dict[int, int] = {}
        self.admitted_batches: Dict[int, int] = {}

    # -- deque-compatible surface -------------------------------------------
    def __len__(self) -> int:
        return self._len

    def __bool__(self) -> bool:
        return self._len > 0

    def __iter__(self) -> Iterator:
        for tid in list(self._order):
            q = self._queues.get(tid)
            if q:
                yield from q

    def append(self, sub) -> None:
        tid = getattr(sub, "tenant", TENANT_DEFAULT)
        q = self._queues.get(tid)
        if q is None:
            q = deque()
            self._queues[tid] = q
            self._order.append(tid)
            self._deficit[tid] = 0.0
        q.append(sub)
        self._len += 1

    def popleft(self):
        """DRR dequeue with a latency-lane fast path.

        Lane bypass first: a lane tenant whose head submission fits the
        lane bucket is served ahead of the ring — FIFO within the tenant
        holds (it is still that tenant's own head), and the rows are
        charged as lane debt so sustained lane traffic cannot starve the
        ring: once a tenant owes a full quantum it falls back to its
        ring turn, and ring grants pay the debt before banking deficit.

        Otherwise standard DRR: serve the head of the active ring while
        its deficit covers the head batch's row cost; top up one quantum
        and rotate when spent. Guaranteed to terminate in O(tenants) loop
        iterations — a full rotation in which nobody could afford its
        head fast-forwards the number of empty rounds the cheapest
        unblock needs in one step, instead of rotating once per
        :data:`WEIGHT_FLOOR_ROWS` row (a zero-weight tenant's big batch
        would otherwise cost hundreds of ring spins under the pipeline
        lock)."""
        if not self._len:
            raise IndexError("pop from an empty TenantQueues")
        if self.lane_rows:
            for tid in list(self._order):
                q = self._queues.get(tid)
                if (q and self.table.is_lane(tid)
                        and q[0].ticket.n_valid <= self.lane_rows
                        and self._lane_debt.get(tid, 0.0)
                        < self._quantum(tid)):
                    sub = q.popleft()
                    cost = max(1, sub.ticket.n_valid)
                    self._lane_debt[tid] = \
                        self._lane_debt.get(tid, 0.0) + cost
                    return self._served(tid, sub, q, cost)
        spins = 0
        while True:
            tid = self._order[0]
            q = self._queues.get(tid)
            if not q:
                # defensive: an empty per-tenant deque should have been
                # retired at pop/remove time
                self._retire_locked(tid)
                continue
            if tid not in self._granted:
                grant = self._quantum(tid)
                debt = self._lane_debt.get(tid, 0.0)
                pay = min(grant, debt)
                if pay:
                    self._lane_debt[tid] = debt - pay
                self._deficit[tid] += grant - pay
                self._granted.add(tid)
            cost = max(1, q[0].ticket.n_valid)
            if self._deficit[tid] < cost:
                # this tenant's turn is spent: next tenant (it keeps the
                # accrued deficit and earns a fresh quantum next round)
                self._granted.discard(tid)
                self._order.rotate(-1)
                spins += 1
                if spins >= len(self._order):
                    # a whole rotation granted everyone a quantum and
                    # served nobody: replay the empty rounds in bulk
                    self._fast_forward()
                    spins = 0
                continue
            sub = q.popleft()
            self._deficit[tid] -= cost
            return self._served(tid, sub, q, cost)

    def _served(self, tid: int, sub, q: deque, cost: int):
        self._len -= 1
        self.admitted_rows[tid] = self.admitted_rows.get(tid, 0) + cost
        self.admitted_batches[tid] = self.admitted_batches.get(tid, 0) + 1
        if not q:
            # idle tenants bank no credit (standard DRR)
            self._retire_locked(tid)
        return sub

    def remove(self, sub) -> None:
        tid = getattr(sub, "tenant", TENANT_DEFAULT)
        q = self._queues.get(tid)
        if q is None:
            raise ValueError("TenantQueues.remove(x): x not in queue")
        q.remove(sub)                      # ValueError if absent, like deque
        self._len -= 1
        if not q:
            self._retire_locked(tid)

    def clear(self) -> None:
        self._queues.clear()
        self._order.clear()
        self._deficit.clear()
        self._granted.clear()
        self._lane_debt.clear()
        self._len = 0

    # -- internals -----------------------------------------------------------
    def _quantum(self, tid: int) -> float:
        return max(float(WEIGHT_FLOOR_ROWS),
                   self.table.weight_of(tid) * self._qrows)

    def _fast_forward(self) -> None:
        """Credit every queued tenant the smallest whole number of DRR
        rounds after which at least one of them can afford its head
        batch — the deterministic equivalent of that many empty ring
        rotations (each round's grant still pays lane debt before
        banking deficit), collapsed into one O(tenants) pass."""
        rounds: Optional[int] = None
        for tid in self._order:
            q = self._queues.get(tid)
            if not q:
                continue
            need = (max(1, q[0].ticket.n_valid)
                    + self._lane_debt.get(tid, 0.0) - self._deficit[tid])
            k = max(1, int(math.ceil(need / self._quantum(tid))))
            rounds = k if rounds is None else min(rounds, k)
        if not rounds:
            return
        for tid in self._order:
            if not self._queues.get(tid):
                continue
            total = rounds * self._quantum(tid)
            debt = self._lane_debt.get(tid, 0.0)
            pay = min(total, debt)
            if pay:
                self._lane_debt[tid] = debt - pay
            self._deficit[tid] += total - pay

    def _retire_locked(self, tid: int) -> None:
        try:
            self._order.remove(tid)
        except ValueError:
            pass
        self._queues.pop(tid, None)
        self._deficit.pop(tid, None)
        self._granted.discard(tid)
        # lane debt SURVIVES per-tenant retirement, unlike credit: a lane
        # tenant whose queue drains on every pop (arrival rate ~ service
        # rate, one batch queued at a time) retires here after every
        # single popleft, and forgiving the debt with the credit would
        # reset the "bypass only while debt < one quantum" starvation
        # bound each time — the ring would never get a turn. The debt is
        # owed TO the tenants still queued behind the bypass, though, so
        # when the LAST queue drains the creditors no longer exist and
        # all debt is forgiven (otherwise debt banked against an idle
        # ring — e.g. sparse probes on an unloaded system — would deny
        # the bypass at the start of the next busy period and show up as
        # a lane-latency spike that repays nobody). Zeroed entries are
        # dropped so a departed tenant does not leak a dict slot.
        if self._len == 0:
            self._lane_debt.clear()
        elif not self._lane_debt.get(tid):
            self._lane_debt.pop(tid, None)

    # -- admission policy (scheduler hooks) ----------------------------------
    def occupancy(self, tid: int) -> int:
        q = self._queues.get(tid)
        return len(q) if q else 0

    def over_cap(self, tid: int) -> bool:
        """True when the tenant is at its own occupancy cap (batches).
        Cap 0 means uncapped — only the global queue bound applies."""
        cap = self.table.cap_of(tid)
        return cap > 0 and self.occupancy(tid) >= cap

    def over_share(self, tid: int) -> bool:
        """True when the tenant's share of the queued batches meets or
        exceeds its weight share among the tenants currently competing
        (queued tenants plus the incoming one). Used to scope the
        OVERLOAD fail-fast: only over-share tenants are instant-rejected;
        a within-budget tenant still gets to wait/displace. With a single
        tenant this is always True — the old unconditional reject."""
        total = self._len
        if total == 0:
            return False
        tids = set(self._queues)
        tids.add(tid)
        wsum = 0.0
        for t in tids:
            wsum += max(self.table.weight_of(t), _MIN_WEIGHT)
        wshare = max(self.table.weight_of(tid), _MIN_WEIGHT) / wsum
        return (self.occupancy(tid) / total) >= wshare

    def pressure_of(self, tid: int) -> float:
        """Queue pressure normalized by weight — the shed-ordering key
        (worst-pressure tenant sheds first)."""
        occ = self.occupancy(tid)
        if not occ:
            return 0.0
        return occ / max(self.table.weight_of(tid), _MIN_WEIGHT)

    def priority_victim(self, incoming_prio: int, incoming_tid: int):
        """Tenant-scoped displacement under PRESSURE. Scans tenants from
        worst weight-normalized pressure down; within the incoming
        tenant the old contract holds (only a strictly worse class is
        displaced — established CT still outranks new flows *within* a
        tenant); across tenants an equal-or-worse class may be displaced
        but only from a tenant under strictly more pressure than the
        submitter's (the worst-pressure tenant sheds first)."""
        inc_pressure = self.pressure_of(incoming_tid)
        for tid in sorted(self._queues, key=self.pressure_of, reverse=True):
            q = self._queues.get(tid)
            if not q:
                continue
            worst = None
            for sub in q:                      # newest of the worst class
                if worst is None or sub.prio >= worst.prio:
                    worst = sub
            if worst is None:
                continue
            if tid == incoming_tid:
                if worst.prio > incoming_prio:
                    return worst
            elif (worst.prio >= incoming_prio
                    and self.pressure_of(tid) > inc_pressure):
                return worst
        return None

    # -- introspection -------------------------------------------------------
    def occupancy_by_name(self) -> Dict[str, Tuple[int, int]]:
        """``{tenant_name: (cap_batches, queued_batches)}`` for the
        resource ledger (active tenants only — a departed/idle tenant
        stops reporting and the ledger's staleness sweep drops its
        gauges, the PR 13 departed-subject discipline)."""
        names = self.table.tenants()
        out: Dict[str, Tuple[int, int]] = {}
        for tid, q in self._queues.items():
            name = names.get(tid, str(tid))
            cap = self.table.cap_of(tid)
            out[name] = (cap, len(q))
        return out

    def stats(self) -> Dict[str, Dict[str, object]]:
        names = self.table.tenants()
        out: Dict[str, Dict[str, object]] = {}
        tids = set(names) | set(self._queues) | set(self.admitted_batches)
        for tid in sorted(tids):
            name = names.get(tid, str(tid))
            out[name] = {
                "depth": self.occupancy(tid),
                "weight": self.table.weight_of(tid),
                "cap": self.table.cap_of(tid),
                "lane": self.table.is_lane(tid),
                "admitted_rows": self.admitted_rows.get(tid, 0),
                "admitted_batches": self.admitted_batches.get(tid, 0),
            }
        return out
