"""Multi-tenant QoS: tenant identity, weighted-fair admission, and the
latency lane.

- :mod:`cilium_tpu.qos.tenancy` — the tenant registry (names, weights,
  lane flags, occupancy caps) and the compiled endpoint→tenant LUT the
  feeder stamps rows with at harvest time.
- :mod:`cilium_tpu.qos.wfq` — the deficit-round-robin admission queue
  the pipeline swaps in for its FIFO deque when QoS is armed.

Default-off: with ``qos_enabled=False`` (or a single tenant) the
pipeline's behavior is byte-identical to the plain FIFO path.
"""

from cilium_tpu.qos.tenancy import (TENANT_DEFAULT, TENANT_DEFAULT_NAME,
                                    TenantSpecError, TenantTable,
                                    parse_assign_spec, parse_tenant_spec)
from cilium_tpu.qos.wfq import TenantQueues

__all__ = ["TENANT_DEFAULT", "TENANT_DEFAULT_NAME", "TenantQueues",
           "TenantSpecError", "TenantTable", "parse_assign_spec",
           "parse_tenant_spec"]
