"""Tenant model: names, weights, lane flags, occupancy caps, and the
endpoint→tenant assignment LUT.

Upstream Cilium has no first-class tenant object — isolation is spelled
through namespaces and bandwidth-manager annotations. Here a *tenant* is
the serving-path unit of isolation: every harvested row is stamped with a
tenant id at classify time (``shim/feeder.py``, same compiled-LUT
discipline as the ep-slot map), and the admission scheduler
(:mod:`cilium_tpu.qos.wfq`) spends each tenant's budget separately so a
flooding tenant sheds against its *own* queue, not the cluster's.

Tenant 0 (``default``) always exists: unknown endpoints, QoS-off
deployments, and fail-closed paths all land there, which is what makes
the single-tenant case degenerate to today's FIFO bit-for-bit.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, Optional, Tuple

import numpy as np

from cilium_tpu.runtime.faults import register_point

# classify-time shed path: tenant derivation fails → the ticket fails
# CLOSED into the default-tenant FIFO class and the worker survives
register_point("qos.enqueue",
               "tenant classification at admission raises/hangs")

#: the always-present fallback tenant (QoS-off, unknown endpoint,
#: fail-closed classification). Id 0 so a zero-filled ``_tenant`` column
#: means "default", matching the zero-fill of absent ``_``-columns.
TENANT_DEFAULT = 0
TENANT_DEFAULT_NAME = "default"

#: dense endpoint→tenant LUT ceiling — same bound as the feeder's
#: ep-slot LUT (``ShimFeeder.DENSE_LUT_MAX``).
DENSE_LUT_MAX = 1 << 20

#: rows of deficit granted per DRR round even to a zero-weight tenant —
#: the starvation floor. Any queued tenant accumulates at least this much
#: credit per full scheduler round, so every tenant is eventually served
#: no matter how the weights are set.
WEIGHT_FLOOR_ROWS = 1


class TenantSpecError(ValueError):
    """Malformed ``qos_tenants`` / ``qos_assign`` spec string."""


class _Tenant:
    __slots__ = ("tid", "name", "weight", "lane", "cap")

    def __init__(self, tid: int, name: str, weight: float, lane: bool,
                 cap: int):
        self.tid = tid
        self.name = name
        self.weight = weight
        self.lane = lane
        self.cap = cap              # per-tenant queue cap in batches (0=off)


def parse_tenant_spec(spec: str) -> Iterable[Tuple[str, float, bool, int]]:
    """Parse ``"gold=4:lane,silver=2,bulk=1:cap=8"`` into
    ``(name, weight, lane, cap)`` tuples. Raises :class:`TenantSpecError`
    on malformed input (config validation calls this eagerly so a bad
    spec fails at load, not mid-flood)."""
    out = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        fields = part.split(":")
        head = fields[0]
        if "=" not in head:
            raise TenantSpecError(f"tenant entry {head!r}: want name=weight")
        name, _, w = head.partition("=")
        name = name.strip()
        if not name or not name.replace("-", "").replace("_", "").isalnum():
            raise TenantSpecError(f"bad tenant name {name!r}")
        try:
            weight = float(w)
        except ValueError:
            raise TenantSpecError(f"tenant {name!r}: weight {w!r} not a "
                                  "number") from None
        if weight < 0:
            raise TenantSpecError(f"tenant {name!r}: weight must be >= 0")
        lane = False
        cap = 0
        for opt in fields[1:]:
            opt = opt.strip()
            if opt == "lane":
                lane = True
            elif opt.startswith("cap="):
                try:
                    cap = int(opt[4:])
                except ValueError:
                    raise TenantSpecError(
                        f"tenant {name!r}: cap {opt[4:]!r} not an int"
                    ) from None
                if cap < 0:
                    raise TenantSpecError(f"tenant {name!r}: cap must "
                                          "be >= 0")
            else:
                raise TenantSpecError(f"tenant {name!r}: unknown option "
                                      f"{opt!r}")
        out.append((name, weight, lane, cap))
    return out


def parse_assign_spec(spec: str) -> Dict[int, str]:
    """Parse ``"1=gold,2=silver"`` (endpoint id → tenant name)."""
    out: Dict[int, str] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        ep, _, name = part.partition("=")
        try:
            ep_id = int(ep)
        except ValueError:
            raise TenantSpecError(f"assign entry {part!r}: endpoint id "
                                  f"{ep!r} not an int") from None
        if ep_id <= 0:
            raise TenantSpecError(f"assign entry {part!r}: endpoint id "
                                  "must be > 0")
        if not name.strip():
            raise TenantSpecError(f"assign entry {part!r}: missing tenant")
        out[ep_id] = name.strip()
    return out


class TenantTable:
    """Registry of tenants plus the endpoint→tenant assignment, with a
    compiled dense LUT cached on a revision counter (rebuild-on-change,
    same discipline as the feeder's ep-slot LUT)."""

    def __init__(self, default_weight: float = 1.0, default_cap: int = 0):
        self._lock = threading.Lock()
        dflt = _Tenant(TENANT_DEFAULT, TENANT_DEFAULT_NAME,
                       default_weight, False, default_cap)
        self._by_name: Dict[str, _Tenant] = {dflt.name: dflt}
        self._by_id: Dict[int, _Tenant] = {dflt.tid: dflt}
        self._assign: Dict[int, int] = {}          # ep_id -> tid
        self._next_tid = 1
        self._default_cap = default_cap
        self.revision = 0
        self._lut: Optional[np.ndarray] = None
        self._lut_rev = -1

    # -- construction --------------------------------------------------------
    @classmethod
    def from_spec(cls, tenants: str, assign: str = "",
                  default_weight: float = 1.0,
                  default_cap: int = 0) -> "TenantTable":
        tbl = cls(default_weight=default_weight, default_cap=default_cap)
        for name, weight, lane, cap in parse_tenant_spec(tenants):
            tbl.register(name, weight=weight, lane=lane,
                         cap=cap or default_cap)
        for ep_id, name in parse_assign_spec(assign).items():
            tbl.assign(ep_id, name)
        return tbl

    def register(self, name: str, weight: float = 1.0, lane: bool = False,
                 cap: int = 0) -> int:
        """Add (or update) a tenant; returns its id."""
        if weight < 0:
            raise ValueError("tenant weight must be >= 0")
        with self._lock:
            t = self._by_name.get(name)
            if t is None:
                t = _Tenant(self._next_tid, name, weight, lane,
                            cap or self._default_cap)
                self._next_tid += 1
                self._by_name[name] = t
                self._by_id[t.tid] = t
            else:
                t.weight = weight
                t.lane = lane
                t.cap = cap or self._default_cap
            self.revision += 1
            return t.tid

    def remove(self, name: str) -> None:
        """Tenant departs: its endpoints fall back to ``default``. The id
        is retired, never reused — in-flight tickets keep a valid name."""
        if name == TENANT_DEFAULT_NAME:
            raise ValueError("cannot remove the default tenant")
        with self._lock:
            t = self._by_name.pop(name, None)
            if t is None:
                return
            self._by_id.pop(t.tid, None)
            for ep_id in [e for e, tid in self._assign.items()
                          if tid == t.tid]:
                del self._assign[ep_id]
            self.revision += 1

    def assign(self, ep_id: int, name: str) -> None:
        with self._lock:
            t = self._by_name.get(name)
            if t is None:
                raise KeyError(f"unknown tenant {name!r}")
            self._assign[int(ep_id)] = t.tid
            self.revision += 1

    def unassign(self, ep_id: int) -> None:
        with self._lock:
            if self._assign.pop(int(ep_id), None) is not None:
                self.revision += 1

    # -- lookups -------------------------------------------------------------
    def name_of(self, tid: int) -> str:
        with self._lock:
            t = self._by_id.get(tid)
            return t.name if t is not None else TENANT_DEFAULT_NAME

    def weight_of(self, tid: int) -> float:
        with self._lock:
            t = self._by_id.get(tid)
            return t.weight if t is not None else 1.0

    def cap_of(self, tid: int) -> int:
        with self._lock:
            t = self._by_id.get(tid)
            return t.cap if t is not None else self._default_cap

    def is_lane(self, tid: int) -> bool:
        with self._lock:
            t = self._by_id.get(tid)
            return bool(t is not None and t.lane)

    def tenants(self) -> Dict[int, str]:
        with self._lock:
            return {tid: t.name for tid, t in self._by_id.items()}

    def tenant_of_ep(self, ep_id: int) -> int:
        with self._lock:
            return self._assign.get(int(ep_id), TENANT_DEFAULT)

    # -- compiled LUT (feeder hot path) --------------------------------------
    def lut(self) -> Optional[np.ndarray]:
        """Dense ``ep_id -> tid`` int32 LUT, rebuilt only when the table
        revision moved (the feeder calls this per poll batch)."""
        with self._lock:
            if self._lut_rev == self.revision:
                return self._lut
            if self._assign:
                hi = max(self._assign)
                if hi < DENSE_LUT_MAX:
                    lut = np.zeros((hi + 1,), dtype=np.int32)
                    for ep_id, tid in self._assign.items():
                        lut[ep_id] = tid
                    self._lut = lut
                else:
                    self._lut = None     # sparse world: dict fallback
            else:
                self._lut = None
            self._lut_rev = self.revision
            return self._lut

    def map_tenants(self, ep_ids: np.ndarray) -> np.ndarray:
        """Vectorized ``ep_id -> tid`` (unknown/negative → default).
        Fail-open to tenant 0 — an unmapped endpoint must still be
        served, it just rides the default budget."""
        raw = np.asarray(ep_ids)
        out = np.zeros(raw.shape, dtype=np.int32)
        lut = self.lut()
        if lut is not None:
            ok = (raw >= 0) & (raw < lut.shape[0])
            out[ok] = lut[raw[ok].astype(np.int64)]
        else:
            with self._lock:
                assign = dict(self._assign)
            if assign:
                for ep_id, tid in assign.items():
                    out[raw == ep_id] = tid
        return out

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                "revision": self.revision,
                "tenants": {
                    t.name: {"tid": t.tid, "weight": t.weight,
                             "lane": t.lane, "cap": t.cap}
                    for t in self._by_id.values()},
                "assigned_endpoints": len(self._assign),
            }
