"""Multi-chip scale-out (SURVEY.md §2 parallelism inventory, re-expressed
TPU-natively):

- **flow axis** (data parallelism, the per-CPU-map/RSS analog): batches shard
  across chips; each chip owns an independent conntrack shard; the host (or
  shim) steers packets by direction-normalized flow hash so both directions
  of a flow land on the owning shard — exactly RSS steering. Counters are the
  only cross-chip traffic (one psum over ICI).
- **rule axis** ("tensor parallelism over rule space"): when identity-class ×
  port-class verdict tensors outgrow a chip, rows shard across the axis and a
  psum combines each packet's cell.
"""

from cilium_tpu.parallel.mesh import (
    flow_shard_of, make_mesh, make_sharded_classify_fn,
    make_unsteered_classify_fn, pad_snapshot_tensors, shard_ct_arrays,
    steer_batch, unsteer_outputs,
)

__all__ = [
    "flow_shard_of", "make_mesh", "make_sharded_classify_fn",
    "make_unsteered_classify_fn", "pad_snapshot_tensors",
    "shard_ct_arrays", "steer_batch", "unsteer_outputs",
]
